"""HGC: sharded binary graph container — the ADIOS2-equivalent store.

Same schema as the reference's ADIOS design (reference:
hydragnn/utils/adiosdataset.py:79-179): each field of every sample is
concatenated along its ragged axis into ONE global array per field, with
per-sample ``count`` index arrays (offsets = exclusive cumsum) and global
attributes (ndata, minmax tables). On-disk layout under ``<path>/``:

    meta.json            schema: ndata, fields {dtype, row_shape}, attrs
    <field>.bin          the concatenated global array (C-order rows)
    <field>.cnt          int64[ndata] per-sample row counts

Field names: ``x``, ``pos``, ``edge_index`` (stored row-ragged as [e, 2]),
``edge_attr``, ``graph_y``, ``gt_<head>``/``nt_<head>`` target dicts.

Read modes (reference AdiosDataset modes, adiosdataset.py:263-368):
  - ``mmap``    zero-copy memory-mapped reads (out-of-core; page cache
                shares physical pages across processes on a host),
  - ``preload`` load everything into RAM up front,
  - ``shm``     one-copy preload into /dev/shm per node, then mmap from
                there (parallel-filesystem-friendly).

The read hot path (batched ragged row-gather) and the shm copy run in the
native C++ core (hydragnn_tpu/native, libhgc.so) with a numpy fallback.

Multi-process writing mirrors AdiosWriter's MPI pattern: allgather shard
row-counts, then every process writes its own byte range of the
preallocated ``.bin`` files (reference adiosdataset.py:90-130).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample
from hydragnn_tpu.native import MappedFile, copy_to_shm


def _field_arrays(sample: GraphSample) -> Dict[str, np.ndarray]:
    """Decompose a GraphSample into named row-ragged 2-D arrays."""
    out: Dict[str, np.ndarray] = {"x": np.asarray(sample.x, dtype=np.float32)}
    if sample.pos is not None:
        out["pos"] = np.asarray(sample.pos, dtype=np.float32)
    if sample.edge_index is not None:
        out["edge_index"] = np.ascontiguousarray(
            np.asarray(sample.edge_index, dtype=np.int32).T
        )  # [e, 2]: ragged axis first
    if sample.edge_attr is not None:
        out["edge_attr"] = np.asarray(sample.edge_attr, dtype=np.float32)
    if sample.graph_y is not None:
        out["graph_y"] = np.asarray(sample.graph_y, dtype=np.float32).reshape(1, -1)
    for name, v in sample.graph_targets.items():
        out[f"gt_{name}"] = np.asarray(v, dtype=np.float32).reshape(1, -1)
    for name, v in sample.node_targets.items():
        out[f"nt_{name}"] = np.asarray(v, dtype=np.float32)
    # meta (e.g. PBC cell, composition id) rides along as ragged JSON bytes
    # — dropping it would break downstream PBC edge building
    # (hydragnn_tpu/data/ingest.py requires meta['cell']).
    meta_bytes = json.dumps(_jsonable_meta(sample.meta)).encode() if sample.meta else b""
    out["meta"] = np.frombuffer(meta_bytes, dtype=np.uint8).reshape(-1, 1).copy()
    # zero-width fields (e.g. graph_y with no configured graph features)
    # carry no data and would mmap empty .bin files
    return {k: v for k, v in out.items() if int(np.prod(v.shape[1:])) > 0 or v.ndim == 1}


def _jsonable_meta(meta: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in meta.items():
        if isinstance(v, np.ndarray):
            out[k] = v.tolist()
        elif isinstance(v, (np.integer, np.floating)):
            out[k] = v.item()
        else:
            out[k] = v
    return out


class ContainerWriter:
    """Writes a sample list (this process's shard) into an HGC container.

    Single-process: trivial. Multi-process (jax.process_count() > 1):
    every process calls ``save()`` with its own shard; row counts are
    allgathered and each process writes its byte range (the AdiosWriter
    pattern, reference adiosdataset.py:90-130,138-179).
    """

    def __init__(self, path: str):
        self.path = path
        self.samples: List[GraphSample] = []
        self.attrs: Dict[str, Any] = {}

    def add(self, samples: Sequence[GraphSample]) -> None:
        self.samples.extend(samples)

    def add_global(self, name: str, value) -> None:
        self.attrs[name] = np.asarray(value).tolist() if hasattr(value, "tolist") else value

    def save(self) -> None:
        import jax

        nproc, rank = jax.process_count(), jax.process_index()
        os.makedirs(self.path, exist_ok=True)

        per_sample = [_field_arrays(s) for s in self.samples]
        if not per_sample:
            # an empty shard cannot learn the schema, and skipping its
            # collectives would deadlock peers mid-save
            raise ValueError(
                "every process must contribute at least one sample to save()"
            )
        field_names = sorted(per_sample[0].keys())
        for i, fa in enumerate(per_sample):
            if sorted(fa.keys()) != field_names:
                raise ValueError(
                    f"sample {i} has fields {sorted(fa.keys())}, "
                    f"expected {field_names} (schema must be homogeneous)"
                )

        if nproc > 1:
            from jax.experimental import multihost_utils

            import hashlib

            # cross-rank schema agreement: mismatched field sets would
            # desynchronize the per-field collectives below and hang
            fp = np.frombuffer(
                hashlib.sha1(",".join(field_names).encode()).digest(), dtype=np.uint8
            )
            all_fp = np.asarray(multihost_utils.process_allgather(fp))
            if not (all_fp == all_fp[0]).all():
                raise ValueError("field schema differs across processes")
            local_n = np.asarray([len(self.samples)], dtype=np.int64)
            all_n = np.asarray(multihost_utils.process_allgather(local_n)).reshape(-1)
        else:
            all_n = np.asarray([len(self.samples)], dtype=np.int64)

        meta: Dict[str, Any] = {
            "ndata": int(all_n.sum()),
            "keys": field_names,
            "attrs": self.attrs,
            "fields": {},
        }

        for fname in field_names:
            arrays = [fa[fname] for fa in per_sample]
            counts = np.asarray([a.shape[0] for a in arrays], dtype=np.int64)
            row_shape = arrays[0].shape[1:]
            dtype = arrays[0].dtype
            local_concat = (
                np.concatenate(arrays, axis=0)
                if arrays
                else np.zeros((0,) + row_shape, dtype)
            )

            if nproc > 1:
                from jax.experimental import multihost_utils

                local_rows = np.asarray([local_concat.shape[0]], dtype=np.int64)
                all_rows = np.asarray(
                    multihost_utils.process_allgather(local_rows)
                ).reshape(-1)
                # ragged per-shard count vectors: pad-gather-trim
                n_max = int(all_n.max())
                padded = np.zeros(n_max, dtype=np.int64)
                padded[: len(counts)] = counts
                all_counts = np.asarray(multihost_utils.process_allgather(padded))
                global_counts = np.concatenate(
                    [all_counts[p, : all_n[p]] for p in range(nproc)]
                )
            else:
                all_rows = np.asarray([local_concat.shape[0]], dtype=np.int64)
                global_counts = counts

            total_rows = int(all_rows.sum())
            row_start = int(all_rows[:rank].sum())
            row_elems = int(np.prod(row_shape)) if row_shape else 1
            if total_rows * row_elems == 0:
                # nothing to store (e.g. no sample carries meta); an empty
                # .bin cannot be mmapped, so omit the field entirely
                continue

            bin_path = os.path.join(self.path, f"{fname}.bin")
            cnt_path = os.path.join(self.path, f"{fname}.cnt")
            if rank == 0:
                # preallocate, write the full count index
                with open(bin_path, "wb") as f:
                    f.truncate(total_rows * row_elems * dtype.itemsize)
                global_counts.astype(np.int64).tofile(cnt_path)
            if nproc > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(f"hgc_alloc_{fname}")
            if local_concat.shape[0] > 0:
                mm = np.memmap(
                    bin_path,
                    dtype=dtype,
                    mode="r+",
                    shape=(total_rows,) + tuple(row_shape),
                )
                mm[row_start : row_start + local_concat.shape[0]] = local_concat
                mm.flush()
                del mm

            meta["fields"][fname] = {
                "dtype": dtype.name,
                "row_shape": list(row_shape),
                "total_rows": total_rows,
            }

        if rank == 0:
            with open(os.path.join(self.path, "meta.json"), "w") as f:
                json.dump(meta, f)
        if nproc > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("hgc_meta")


class ContainerDataset:
    """Reads an HGC container; ``get(i)`` returns a GraphSample.

    Modes: ``mmap`` (default, out-of-core), ``preload`` (all in RAM),
    ``shm`` (node-local /dev/shm preload + mmap). ``fetch_rows`` exposes
    the threaded native batched gather for bulk loading.
    """

    def __init__(self, path: str, mode: str = "mmap", shm_dir: Optional[str] = None):
        if mode not in ("mmap", "preload", "shm"):
            raise ValueError(f"unknown mode {mode}")
        self.path = path
        self.mode = mode
        with open(os.path.join(path, "meta.json")) as f:
            self.meta = json.load(f)
        self.ndata: int = int(self.meta["ndata"])
        self.attrs: Dict[str, Any] = self.meta.get("attrs", {})
        self.fields: Dict[str, Dict[str, Any]] = self.meta["fields"]

        self._maps: Dict[str, MappedFile] = {}
        self._views: Dict[str, np.ndarray] = {}
        self._counts: Dict[str, np.ndarray] = {}
        self._offsets: Dict[str, np.ndarray] = {}
        # key the default shm dir on the full path, not the basename —
        # distinct containers named alike must not shadow each other
        import hashlib

        path_key = hashlib.sha1(os.path.abspath(path).encode()).hexdigest()[:12]
        shm_target = shm_dir or os.path.join(
            "/dev/shm",
            f"hgc_{os.path.basename(os.path.normpath(path))}_{path_key}",
        )
        for fname, info in self.fields.items():
            bin_path = os.path.join(path, f"{fname}.bin")
            cnt_path = os.path.join(path, f"{fname}.cnt")
            if mode == "shm":
                bin_path = copy_to_shm(bin_path, shm_target)
            cnt = np.fromfile(cnt_path, dtype=np.int64)
            self._counts[fname] = cnt
            self._offsets[fname] = np.concatenate([[0], np.cumsum(cnt)])
            mf = MappedFile(bin_path)
            self._maps[fname] = mf
            view = mf.view(np.dtype(info["dtype"]), tuple(info["row_shape"]))
            if mode == "preload":
                view = np.array(view)  # materialize in RAM
            self._views[fname] = view

    def __len__(self) -> int:
        return self.ndata

    def field_rows(self, fname: str, idx: int) -> np.ndarray:
        off = self._offsets[fname]
        return self._views[fname][off[idx] : off[idx + 1]]

    def _assemble(self, rows) -> GraphSample:
        """Build one GraphSample from a ``rows(fname) -> ndarray``
        accessor (shared by the per-sample and bulk read paths)."""
        sample = GraphSample(x=np.array(rows("x")))
        if "pos" in self._views:
            sample.pos = np.array(rows("pos"))
        if "edge_index" in self._views:
            sample.edge_index = np.ascontiguousarray(rows("edge_index").T)
        if "edge_attr" in self._views:
            sample.edge_attr = np.array(rows("edge_attr"))
        if "graph_y" in self._views:
            sample.graph_y = np.array(rows("graph_y")).reshape(-1)
        for fname in self._views:
            if fname.startswith("gt_"):
                sample.graph_targets[fname[3:]] = np.array(rows(fname)).reshape(-1)
            elif fname.startswith("nt_"):
                sample.node_targets[fname[3:]] = np.array(rows(fname))
        if "meta" in self._views:
            raw = np.array(rows("meta")).reshape(-1).tobytes()
            if raw:
                sample.meta = json.loads(raw.decode())
                # PBC cells round-trip as arrays (ingest requires them)
                if "cell" in sample.meta:
                    sample.meta["cell"] = np.asarray(sample.meta["cell"])
        return sample

    def get(self, idx: int) -> GraphSample:
        if not 0 <= idx < self.ndata:
            raise IndexError(idx)
        return self._assemble(lambda f: self.field_rows(f, idx))

    def __getitem__(self, idx: int) -> GraphSample:
        return self.get(idx)

    def samples(self, indices: Optional[Sequence[int]] = None) -> List[GraphSample]:
        if indices is None:
            indices = range(self.ndata)
        return [self.get(i) for i in indices]

    def fetch_samples(self, indices: Sequence[int]) -> List[GraphSample]:
        """Materialize an index list with ONE bulk read per field — the
        reference AdiosDataset's experimental bulk preflight/populate
        loader (reference: hydragnn/utils/adiosdataset.py:389-437), here
        backed by the native threaded ragged gather (hgc_gather) instead
        of per-sample reads: each field's rows for ALL requested samples
        arrive in a single packed buffer, then slice into GraphSamples."""
        idx = [int(i) for i in indices]
        for i in idx:
            if not 0 <= i < self.ndata:
                raise IndexError(i)
        packed: Dict[str, np.ndarray] = {}
        offs: Dict[str, np.ndarray] = {}
        for fname in self._views:
            rows, cnt = self.fetch_rows(fname, idx)
            packed[fname] = rows
            offs[fname] = np.concatenate([[0], np.cumsum(cnt)])
        out: List[GraphSample] = []
        for k in range(len(idx)):
            out.append(
                self._assemble(
                    lambda f, k=k: packed[f][offs[f][k] : offs[f][k + 1]]
                )
            )
        return out

    def fetch_rows(self, fname: str, indices: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Bulk ragged gather via the native threaded core: returns
        (packed rows [sum(cnt), *row_shape], per-sample counts)."""
        info = self.fields[fname]
        dtype = np.dtype(info["dtype"])
        row_shape = tuple(info["row_shape"])
        row_elems = int(np.prod(row_shape)) if row_shape else 1
        row_bytes = row_elems * dtype.itemsize
        idx = np.asarray(indices, dtype=np.int64)
        cnt = self._counts[fname][idx]
        src_off = self._offsets[fname][idx]
        out_off = np.concatenate([[0], np.cumsum(cnt)[:-1]])
        total = int(cnt.sum())
        if self.mode == "preload":
            packed = np.concatenate(
                [self._views[fname][s : s + c] for s, c in zip(src_off, cnt)], axis=0
            ) if total else np.zeros((0,) + row_shape, dtype)
            return packed, cnt
        out = np.empty(total * row_bytes, dtype=np.uint8)
        self._maps[fname].gather(row_bytes, src_off, cnt, out_off, out)
        return out.view(dtype).reshape((total,) + row_shape), cnt

    def minmax(self) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        g = self.attrs.get("minmax_graph_feature")
        n = self.attrs.get("minmax_node_feature")
        return (
            np.asarray(g) if g is not None else None,
            np.asarray(n) if n is not None else None,
        )

    def close(self) -> None:
        for mf in self._maps.values():
            mf.close()
        self._maps.clear()
        self._views.clear()
