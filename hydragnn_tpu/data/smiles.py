"""SMILES -> graph featurization without RDKit.

The reference turns SMILES strings into PyG graphs with RDKit (reference:
hydragnn/utils/smiles_utils.py:18-119): explicit hydrogens are added, node
features are [one-hot atom type | atomic number | is-aromatic | SP | SP2 |
SP3 | #H-neighbors], and edge features are a 4-class one-hot over
{single, double, triple, aromatic} bonds, duplicated in both directions and
sorted by (sender * N + receiver).

RDKit is not available in this environment, so this module carries its own
small SMILES parser covering the subset those pipelines need (OGB/CSCE-style
organic molecules): organic-subset atoms, bracket atoms with isotope /
charge / explicit H, branches, ring closures (incl. %nn), aromatic
lowercase notation, disconnected components, and directional bonds (read as
single). Implicit hydrogens follow the Daylight valence rules;
hybridization is derived from steric number (sigma neighbors + lone pairs),
with aromatic atoms pinned to SP2 — matching RDKit's assignments on the
molecules these datasets contain.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample

# Daylight organic subset: these may appear bare (outside brackets) and get
# implicit hydrogens. Every other element must be written in brackets.
_ORGANIC = {"B", "C", "N", "O", "P", "S", "F", "Cl", "Br", "I"}
_AROMATIC_ORGANIC = {"b", "c", "n", "o", "p", "s"}

# Default valences used for implicit-H completion (Daylight rules).
_DEFAULT_VALENCE: Dict[str, Tuple[int, ...]] = {
    "B": (3,),
    "C": (4,),
    "N": (3, 5),
    "O": (2,),
    "P": (3, 5),
    "S": (2, 4, 6),
    "F": (1,),
    "Cl": (1,),
    "Br": (1,),
    "I": (1,),
}

# Valence (outer-shell) electron counts, for lone-pair / hybridization math.
_VALENCE_ELECTRONS = {
    "H": 1, "B": 3, "C": 4, "N": 5, "O": 6, "P": 5, "S": 6,
    "F": 7, "Cl": 7, "Br": 7, "I": 7, "Si": 4, "Se": 6, "As": 5,
}

ATOMIC_NUMBERS = {
    "H": 1, "He": 2, "Li": 3, "Be": 4, "B": 5, "C": 6, "N": 7, "O": 8,
    "F": 9, "Ne": 10, "Na": 11, "Mg": 12, "Al": 13, "Si": 14, "P": 15,
    "S": 16, "Cl": 17, "Ar": 18, "K": 19, "Ca": 20, "Sc": 21, "Ti": 22,
    "V": 23, "Cr": 24, "Mn": 25, "Fe": 26, "Co": 27, "Ni": 28, "Cu": 29,
    "Zn": 30, "Ga": 31, "Ge": 32, "As": 33, "Se": 34, "Br": 35, "Kr": 36,
    "Rb": 37, "Sr": 38, "Y": 39, "Zr": 40, "Nb": 41, "Mo": 42, "Tc": 43,
    "Ru": 44, "Rh": 45, "Pd": 46, "Ag": 47, "Cd": 48, "In": 49, "Sn": 50,
    "Sb": 51, "Te": 52, "I": 53, "Xe": 54,
}

_BOND_ORDER = {"-": 1.0, "=": 2.0, "#": 3.0, ":": 1.5, "/": 1.0, "\\": 1.0}
# bond-type -> one-hot class, matching the reference's {SINGLE:0, DOUBLE:1,
# TRIPLE:2, AROMATIC:3} (smiles_utils.py:50)
BOND_CLASSES = {1.0: 0, 2.0: 1, 3.0: 2, 1.5: 3}

_BRACKET_RE = re.compile(
    r"^(?P<isotope>\d+)?"
    r"(?P<symbol>[A-Z][a-z]?|[a-z])"
    r"(?P<chiral>@{1,2}(?:TH\d|AL\d|SP\d|TB\d+|OH\d+)?)?"
    r"(?P<hcount>H\d*)?"
    r"(?P<charge>\+{1,}\d*|-{1,}\d*)?"
    r"(?::(?P<map>\d+))?$"
)


class SmilesParseError(ValueError):
    pass


@dataclasses.dataclass
class Atom:
    symbol: str            # capitalized element symbol
    aromatic: bool
    charge: int = 0
    explicit_h: int = 0    # H count from a bracket spec (bracket atoms only)
    bracket: bool = False
    isotope: int = 0


@dataclasses.dataclass
class Bond:
    a: int
    b: int
    order: float           # 1, 2, 3, or 1.5 (aromatic)


def _parse_bracket(body: str) -> Atom:
    m = _BRACKET_RE.match(body)
    if m is None:
        raise SmilesParseError(f"bad bracket atom [{body}]")
    sym = m.group("symbol")
    aromatic = sym[0].islower()
    sym = sym.capitalize()
    hc = m.group("hcount")
    explicit_h = 0 if hc is None else (1 if hc == "H" else int(hc[1:]))
    ch = m.group("charge")
    charge = 0
    if ch:
        n = ch.lstrip("+-")
        mag = int(n) if n else len(ch)
        charge = mag if ch[0] == "+" else -mag
    iso = int(m.group("isotope")) if m.group("isotope") else 0
    return Atom(sym, aromatic, charge, explicit_h, bracket=True, isotope=iso)


def parse_smiles(s: str) -> Tuple[List[Atom], List[Bond]]:
    """Parse a SMILES string into atom and bond lists (no H completion)."""
    atoms: List[Atom] = []
    bonds: List[Bond] = []
    prev: Optional[int] = None
    pending_bond: Optional[str] = None
    stack: List[Optional[int]] = []
    rings: Dict[str, Tuple[int, Optional[str]]] = {}
    i, n = 0, len(s)

    def attach(idx: int):
        nonlocal prev, pending_bond
        if prev is not None:
            if pending_bond is not None:
                order = _BOND_ORDER[pending_bond]
            elif atoms[prev].aromatic and atoms[idx].aromatic:
                order = 1.5
            else:
                order = 1.0
            bonds.append(Bond(prev, idx, order))
        prev = idx
        pending_bond = None

    def close_ring(label: str):
        nonlocal pending_bond
        if prev is None:
            raise SmilesParseError(f"ring closure {label} before any atom")
        if label in rings:
            j, sym = rings.pop(label)
            bsym = pending_bond or sym
            if bsym is not None:
                order = _BOND_ORDER[bsym]
            elif atoms[j].aromatic and atoms[prev].aromatic:
                order = 1.5
            else:
                order = 1.0
            if j == prev:
                raise SmilesParseError(f"self ring bond {label}")
            bonds.append(Bond(j, prev, order))
        else:
            rings[label] = (prev, pending_bond)
        pending_bond = None

    while i < n:
        c = s[i]
        if c == "[":
            j = s.find("]", i)
            if j < 0:
                raise SmilesParseError("unclosed bracket")
            atoms.append(_parse_bracket(s[i + 1 : j]))
            attach(len(atoms) - 1)
            i = j + 1
        elif c in "-=#:/\\":
            pending_bond = c
            i += 1
        elif c == "(":
            stack.append(prev)
            i += 1
        elif c == ")":
            if not stack:
                raise SmilesParseError("unbalanced parenthesis")
            prev = stack.pop()
            i += 1
        elif c == ".":
            prev = None
            pending_bond = None
            i += 1
        elif c == "%":
            if i + 2 >= n or not s[i + 1 : i + 3].isdigit():
                raise SmilesParseError("bad %nn ring label")
            close_ring(s[i + 1 : i + 3])
            i += 3
        elif c.isdigit():
            close_ring(c)
            i += 1
        elif c.isupper():
            sym = s[i : i + 2] if s[i : i + 2] in ("Cl", "Br") else c
            if sym not in _ORGANIC:
                raise SmilesParseError(
                    f"element {sym!r} must be bracketed (organic subset only)"
                )
            atoms.append(Atom(sym, aromatic=False))
            attach(len(atoms) - 1)
            i += len(sym)
        elif c in _AROMATIC_ORGANIC:
            atoms.append(Atom(c.upper(), aromatic=True))
            attach(len(atoms) - 1)
            i += 1
        elif c == "*":
            raise SmilesParseError("wildcard atoms unsupported")
        else:
            raise SmilesParseError(f"unexpected character {c!r} at {i}")
    if stack:
        raise SmilesParseError("unbalanced parenthesis")
    if rings:
        raise SmilesParseError(f"unclosed ring bonds: {sorted(rings)}")
    return atoms, bonds


def _implicit_h(atom: Atom, bond_sum: float, degree: int) -> int:
    """Daylight implicit-hydrogen count for a bare organic-subset atom."""
    if atom.bracket:
        return atom.explicit_h
    if atom.aromatic:
        # one valence is consumed by the aromatic pi system; sigma bonds
        # count 1 each regardless of the 1.5 bookkeeping order
        need = _DEFAULT_VALENCE[atom.symbol][0] - degree - 1
        return max(0, need)
    total = int(np.ceil(bond_sum))
    for v in _DEFAULT_VALENCE[atom.symbol]:
        if v >= total:
            return v - total
    return 0


def _hybridization(atom: Atom, bond_sum: float, degree: int) -> Tuple[int, int, int]:
    """(sp, sp2, sp3) flags from steric number = sigma neighbors + lone
    pairs; aromatic atoms are SP2 (matches RDKit on these datasets)."""
    if atom.symbol == "H":
        return (0, 0, 0)
    if atom.aromatic:
        return (0, 1, 0)
    ve = _VALENCE_ELECTRONS.get(atom.symbol)
    if ve is None:
        return (0, 0, 1)
    lone_pairs = max(0, (ve - atom.charge - int(round(bond_sum))) // 2)
    steric = degree + lone_pairs
    if steric <= 2:
        return (1, 0, 0)
    if steric == 3:
        return (0, 1, 0)
    return (0, 0, 1)


@dataclasses.dataclass
class Molecule:
    """Hydrogen-complete molecular graph ready for featurization."""

    atoms: List[Atom]
    bonds: List[Bond]

    @property
    def num_atoms(self) -> int:
        return len(self.atoms)


def mol_from_smiles(s: str) -> Molecule:
    """Parse and add explicit hydrogens (reference AddHs,
    smiles_utils.py:52)."""
    atoms, bonds = parse_smiles(s)
    bond_sum = [0.0] * len(atoms)
    degree = [0] * len(atoms)
    for b in bonds:
        bond_sum[b.a] += b.order
        bond_sum[b.b] += b.order
        degree[b.a] += 1
        degree[b.b] += 1
    # cache pre-H sigma counts/bond sums for hybridization
    heavy_stats = [(bond_sum[i], degree[i]) for i in range(len(atoms))]
    for i, atom in enumerate(list(atoms)):
        if atom.symbol == "H":
            continue
        nh = _implicit_h(atom, bond_sum[i], degree[i])
        for _ in range(nh):
            atoms.append(Atom("H", aromatic=False))
            bonds.append(Bond(i, len(atoms) - 1, 1.0))
    mol = Molecule(atoms, bonds)
    mol._heavy_stats = heavy_stats  # type: ignore[attr-defined]
    return mol


def get_node_attribute_name(types: Dict[str, int]):
    """Node feature names/dims, mirroring smiles_utils.py:18-32."""
    names = ["atom" + k for k in types] + [
        "atomicnumber", "IsAromatic", "HSP", "HSP2", "HSP3", "Hprop",
    ]
    return names, [1] * len(names)


def generate_graphdata_from_smilestr(
    smilestr: str,
    ytarget,
    types: Dict[str, int],
    atomic_descriptors: Optional[np.ndarray] = None,
) -> GraphSample:
    """SMILES -> GraphSample with the reference's exact feature layout
    (smiles_utils.py:35-119): x = [one-hot type | Z | aromatic | sp | sp2 |
    sp3 | #H-neighbors], edge_attr = one-hot{single,double,triple,aromatic},
    both edge directions, sorted by sender*N+receiver."""
    mol = mol_from_smiles(smilestr)
    N = mol.num_atoms
    n_types = len(types)

    x = np.zeros((N, n_types + 6), dtype=np.float32)
    # per-atom sigma degree and bond-order sum over the H-complete graph
    bond_sum = [0.0] * N
    degree = [0] * N
    for b in mol.bonds:
        bond_sum[b.a] += b.order
        bond_sum[b.b] += b.order
        degree[b.a] += 1
        degree[b.b] += 1

    for i, atom in enumerate(mol.atoms):
        if atom.symbol not in types:
            raise SmilesParseError(
                f"atom {atom.symbol} not in dataset element types {list(types)}"
            )
        x[i, types[atom.symbol]] = 1.0
        x[i, n_types + 0] = ATOMIC_NUMBERS[atom.symbol]
        x[i, n_types + 1] = 1.0 if atom.aromatic else 0.0
        sp, sp2, sp3 = _hybridization(atom, bond_sum[i], degree[i])
        x[i, n_types + 2] = sp
        x[i, n_types + 3] = sp2
        x[i, n_types + 4] = sp3

    senders: List[int] = []
    receivers: List[int] = []
    bond_cls: List[int] = []
    for b in mol.bonds:
        senders += [b.a, b.b]
        receivers += [b.b, b.a]
        bond_cls += 2 * [BOND_CLASSES[b.order]]
    ei = np.asarray([senders, receivers], dtype=np.int32)
    cls = np.asarray(bond_cls, dtype=np.int64)
    perm = np.argsort(ei[0] * N + ei[1], kind="stable")
    ei = ei[:, perm]
    cls = cls[perm]
    edge_attr = np.eye(len(BOND_CLASSES), dtype=np.float32)[cls]

    # H-neighbor count per atom (reference scatter of hs over col,
    # smiles_utils.py:88-89)
    is_h = np.array([a.symbol == "H" for a in mol.atoms], dtype=np.float32)
    num_hs = np.zeros(N, dtype=np.float32)
    np.add.at(num_hs, ei[1], is_h[ei[0]])
    x[:, n_types + 5] = num_hs

    if atomic_descriptors is not None:
        if atomic_descriptors.shape[0] != N:
            raise ValueError("atomic descriptor rows must equal atom count")
        x = np.concatenate([x, atomic_descriptors.astype(np.float32)], axis=1)

    y = np.atleast_1d(np.asarray(ytarget, dtype=np.float32))
    return GraphSample(x=x, edge_index=ei, edge_attr=edge_attr, graph_y=y)


def molecular_formula(mol: Molecule) -> Dict[str, int]:
    """Element -> count map (test/assertion helper)."""
    out: Dict[str, int] = {}
    for a in mol.atoms:
        out[a.symbol] = out.get(a.symbol, 0) + 1
    return out
