"""Host-side radius-graph construction (cell list, optional PBC).

The reference builds neighbor graphs with torch-cluster's ``RadiusGraph``
(reference: hydragnn/preprocess/utils.py:99-112) and with ase's C neighbor
list for periodic boundary conditions (reference:
hydragnn/preprocess/utils.py:131-171). Both run on host during
preprocessing; here the equivalent is a numpy cell-list builder so the
device never sees a dynamic shape. Edge convention matches PyG: each
directed edge (sender j -> receiver i) with distance(j, i) <= r; no
self-loops unless requested.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def radius_graph(
    pos: np.ndarray,
    r: float,
    max_num_neighbors: Optional[int] = None,
    loop: bool = False,
) -> np.ndarray:
    """Edges within radius ``r``; returns edge_index [2, E] int64
    (row 0 = senders, row 1 = receivers), receiver-major sorted.

    ``max_num_neighbors`` caps incoming edges per receiver, keeping the
    *nearest* ones (torch-cluster semantics keep arbitrary ones; nearest is
    deterministic and at least as informative).
    """
    pos = np.asarray(pos, dtype=np.float64)
    n = pos.shape[0]
    if n == 0:
        return np.zeros((2, 0), dtype=np.int64)

    senders, receivers, dists = _candidate_pairs(pos, pos, r)
    if not loop:
        keep = senders != receivers
        senders, receivers, dists = senders[keep], receivers[keep], dists[keep]
    return _cap_and_sort(senders, receivers, dists, max_num_neighbors)


def radius_graph_pbc(
    pos: np.ndarray,
    r: float,
    cell: np.ndarray,
    pbc: Tuple[bool, bool, bool] = (True, True, True),
    max_num_neighbors: Optional[int] = None,
    loop: bool = False,
) -> np.ndarray:
    """Periodic radius graph via explicit image shifts (supercell method,
    matching ase.neighborlist semantics used by the reference's
    ``RadiusGraphPBC``, hydragnn/preprocess/utils.py:131-171): a pair can
    contribute several edges through different periodic images, and an atom
    can neighbor its own image (i == j with a nonzero shift).
    """
    pos = np.asarray(pos, dtype=np.float64)
    cell = np.asarray(cell, dtype=np.float64).reshape(3, 3)
    n = pos.shape[0]
    if n == 0:
        return np.zeros((2, 0), dtype=np.int64)

    # Number of cell repeats needed in each periodic direction so every
    # image within r is covered (distance between lattice planes).
    recip = np.linalg.inv(cell).T
    heights = 1.0 / np.maximum(np.linalg.norm(recip, axis=1), 1e-30)
    reps = [int(np.ceil(r / heights[k])) if pbc[k] else 0 for k in range(3)]

    shifts = [
        np.array([i, j, k], dtype=np.float64) @ cell
        for i in range(-reps[0], reps[0] + 1)
        for j in range(-reps[1], reps[1] + 1)
        for k in range(-reps[2], reps[2] + 1)
    ]

    all_s, all_r, all_d = [], [], []
    for shift in shifts:
        is_zero_shift = not np.any(shift)
        s, t, d = _candidate_pairs(pos + shift, pos, r)
        if is_zero_shift and not loop:
            keep = s != t
            s, t, d = s[keep], t[keep], d[keep]
        all_s.append(s)
        all_r.append(t)
        all_d.append(d)
    senders = np.concatenate(all_s)
    receivers = np.concatenate(all_r)
    dists = np.concatenate(all_d)
    return _cap_and_sort(senders, receivers, dists, max_num_neighbors)


def edge_lengths(pos: np.ndarray, edge_index: np.ndarray) -> np.ndarray:
    """[E, 1] Euclidean edge lengths (the reference's ``Distance``
    transform with norm=False, hydragnn/preprocess/serialized_dataset_loader.py)."""
    pos = np.asarray(pos, dtype=np.float64)
    d = pos[edge_index[1]] - pos[edge_index[0]]
    return np.linalg.norm(d, axis=1, keepdims=True).astype(np.float32)


def _candidate_pairs(
    src_pos: np.ndarray, dst_pos: np.ndarray, r: float
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """All (src, dst, dist) pairs with dist <= r, via a uniform cell grid.

    Cell size = r, so neighbors of a dst point lie in the 27 surrounding
    cells of its grid cell. O(N * avg_bucket) instead of O(N^2). The hot
    path is the threaded C++ cell-list kernel (native/radius.cpp, the
    torch-cluster/ase-neighborlist stand-in, SURVEY.md §2.9); numpy
    below is the no-compiler fallback.
    """
    n_src, n_dst = src_pos.shape[0], dst_pos.shape[0]
    if n_src * n_dst <= 4096:  # tiny: brute force is faster than bucketing
        diff = src_pos[:, None, :] - dst_pos[None, :, :]
        dist = np.sqrt((diff * diff).sum(-1))
        s, t = np.nonzero(dist <= r)
        return s.astype(np.int64), t.astype(np.int64), dist[s, t]

    from hydragnn_tpu.native import native_radius_pairs

    native = native_radius_pairs(src_pos, dst_pos, r)
    if native is not None:
        return native

    origin = np.minimum(src_pos.min(0), dst_pos.min(0))
    inv = 1.0 / max(r, 1e-12)
    src_cell = np.floor((src_pos - origin) * inv).astype(np.int64)
    dst_cell = np.floor((dst_pos - origin) * inv).astype(np.int64)

    def key(c):
        # Collision-free linear key over the bounded grid.
        extent = max(int(src_cell.max() if n_src else 0), int(dst_cell.max() if n_dst else 0)) + 3
        return (c[:, 0] * extent + c[:, 1]) * extent + c[:, 2], extent

    skey, extent = key(src_cell)
    order = np.argsort(skey, kind="stable")
    skey_sorted = skey[order]

    out_s, out_t, out_d = [], [], []
    offsets = np.array(
        [[i, j, k] for i in (-1, 0, 1) for j in (-1, 0, 1) for k in (-1, 0, 1)],
        dtype=np.int64,
    )
    for off in offsets:
        qkey = ((dst_cell[:, 0] + off[0]) * extent + (dst_cell[:, 1] + off[1])) * extent + (
            dst_cell[:, 2] + off[2]
        )
        lo = np.searchsorted(skey_sorted, qkey, side="left")
        hi = np.searchsorted(skey_sorted, qkey, side="right")
        counts = hi - lo
        if counts.sum() == 0:
            continue
        t_idx = np.repeat(np.arange(n_dst, dtype=np.int64), counts)
        # Gather the source indices bucket-by-bucket.
        s_idx = order[
            np.concatenate([np.arange(l, h, dtype=np.int64) for l, h in zip(lo, hi) if h > l])
        ]
        d = np.linalg.norm(src_pos[s_idx] - dst_pos[t_idx], axis=1)
        keep = d <= r
        out_s.append(s_idx[keep])
        out_t.append(t_idx[keep])
        out_d.append(d[keep])
    if not out_s:
        return (
            np.zeros(0, dtype=np.int64),
            np.zeros(0, dtype=np.int64),
            np.zeros(0, dtype=np.float64),
        )
    return np.concatenate(out_s), np.concatenate(out_t), np.concatenate(out_d)


def _cap_and_sort(
    senders: np.ndarray,
    receivers: np.ndarray,
    dists: np.ndarray,
    max_num_neighbors: Optional[int],
) -> np.ndarray:
    """Sort edges receiver-major (then by distance) and cap per-receiver
    in-degree. Receiver-major ordering makes downstream ``segment_sum``
    over receivers a sorted reduction (better XLA lowering)."""
    order = np.lexsort((dists, receivers))
    senders, receivers, dists = senders[order], receivers[order], dists[order]
    if max_num_neighbors is not None and receivers.size:
        # rank of each edge within its receiver run
        starts = np.searchsorted(receivers, receivers, side="left")
        rank = np.arange(receivers.size) - starts
        keep = rank < max_num_neighbors
        senders, receivers = senders[keep], receivers[keep]
    return np.stack([senders, receivers]).astype(np.int64)
