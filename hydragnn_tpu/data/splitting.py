"""Dataset splitting: proportional slice and compositional stratification.

Mirrors the reference semantics (reference:
hydragnn/preprocess/load_data.py:286-304 for the plain split,
hydragnn/preprocess/compositional_data_splitting.py:117-155 for the
stratified one): the stratification category of a graph is its composition
fingerprint — per-element atom counts positionally encoded by powers of
10^ceil(log10(max_graph_size)) — singleton categories are duplicated so
they can appear on both sides of a split, train is carved out first, then
val/test 50/50. The shuffle-split itself is a numpy per-category
proportional allocation rather than sklearn's StratifiedShuffleSplit; the
statistical contract (every category represented proportionally in every
partition) is the same.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import List, Sequence, Tuple

import numpy as np

from hydragnn_tpu.data.dataset import GraphSample


def composition_categories(samples: Sequence[GraphSample]) -> List[int]:
    max_graph_size = max(s.num_nodes for s in samples)
    power_ten = math.ceil(math.log10(max(max_graph_size, 2)))
    elements: List[float] = sorted({float(v) for s in samples for v in np.unique(s.x[:, 0])})
    index_of = {e: i for i, e in enumerate(elements)}
    cats = []
    for s in samples:
        vals, freqs = np.unique(s.x[:, 0], return_counts=True)
        cat = 0
        for v, f in zip(vals, freqs):
            cat += int(f) * 10 ** (power_ten * index_of[float(v)])
        cats.append(cat)
    return cats


def _duplicate_singletons(samples: list, cats: List[int]) -> Tuple[list, List[int]]:
    counts = Counter(cats)
    extra = [(s, c) for s, c in zip(samples, cats) if counts[c] == 1]
    samples = list(samples) + [s for s, _ in extra]
    cats = list(cats) + [c for _, c in extra]
    return samples, cats


def _stratified_two_way(
    samples: list, cats: List[int], train_size: float, seed: int
) -> Tuple[list, list]:
    """Split so each category contributes ~train_size of its members to the
    first partition (at least one to each side when it has >= 2 members)."""
    rng = np.random.default_rng(seed)
    by_cat = {}
    for i, c in enumerate(cats):
        by_cat.setdefault(c, []).append(i)
    first, second = [], []
    for c in sorted(by_cat):
        idx = np.asarray(by_cat[c])
        rng.shuffle(idx)
        k = int(round(train_size * len(idx)))
        k = min(max(k, 1), len(idx) - 1) if len(idx) >= 2 else k
        first.extend(idx[:k].tolist())
        second.extend(idx[k:].tolist())
    # Shuffle across categories so batches are not composition-ordered.
    first = [first[i] for i in rng.permutation(len(first))]
    second = [second[i] for i in rng.permutation(len(second))]
    return [samples[i] for i in first], [samples[i] for i in second]


def compositional_stratified_splitting(
    samples: Sequence[GraphSample], perc_train: float, seed: int = 0
) -> Tuple[list, list, list]:
    samples = list(samples)
    cats = composition_categories(samples)
    samples, cats = _duplicate_singletons(samples, cats)
    trainset, val_test = _stratified_two_way(samples, cats, perc_train, seed)

    vt_cats = composition_categories(val_test)
    val_test, vt_cats = _duplicate_singletons(val_test, vt_cats)
    valset, testset = _stratified_two_way(val_test, vt_cats, 0.5, seed + 1)
    return trainset, valset, testset


def subsample_categories(samples: Sequence[GraphSample]) -> List[int]:
    """The reference's subsample category: sorted positive type
    frequencies encoded by powers of 100 (``freq * 100**index``,
    hydragnn/utils/abstractrawdataset.py:430-438) — note this merges
    compositions sharing a frequency pattern, unlike
    :func:`composition_categories`."""
    cats: List[int] = []
    for s in samples:
        freqs = sorted(np.unique(s.x[:, 0], return_counts=True)[1].tolist())
        cats.append(sum(int(f) * 100**i for i, f in enumerate(freqs)))
    return cats


def stratified_subsample(
    samples: Sequence[GraphSample], subsample_percentage: float, seed: int = 0
) -> list:
    """Downselect ``samples`` to a fraction with composition-stratified
    sampling (reference: stratified_sampling,
    hydragnn/utils/abstractrawdataset.py:412-452 and the serialized-loader
    subsample path, preprocess/serialized_dataset_loader.py:193-259).

    The reference's per-sample category is the sorted positive type
    frequencies positionally encoded by powers of 100 (``freq *
    100**index``); here the frequencies come from ``np.unique`` of the
    first node-feature column (robust to float/normalized type columns,
    where the reference's ``bincount(x.int())`` degenerates), and the
    per-category proportional draw replaces sklearn's
    StratifiedShuffleSplit with the same contract: every category
    represented ~proportionally in the subsample."""
    if not 0.0 < subsample_percentage <= 1.0:
        raise ValueError(
            f"subsample_percentage must be in (0, 1], got {subsample_percentage}"
        )
    samples = list(samples)
    if subsample_percentage == 1.0:
        return samples
    cats = subsample_categories(samples)

    rng = np.random.default_rng(seed)
    by_cat: dict = {}
    for i, c in enumerate(cats):
        by_cat.setdefault(c, []).append(i)
    # Largest-remainder allocation so the TOTAL hits round(frac * n)
    # exactly (sklearn StratifiedShuffleSplit's _approximate_mode
    # contract): floor per category, then +1 by descending fractional
    # remainder until the target is met.
    target = int(round(subsample_percentage * len(samples)))
    order = sorted(by_cat)
    floors = {c: int(subsample_percentage * len(by_cat[c])) for c in order}
    rem = sorted(
        order,
        key=lambda c: subsample_percentage * len(by_cat[c]) - floors[c],
        reverse=True,
    )
    short = target - sum(floors.values())
    for c in rem[:short]:
        floors[c] += 1
    picked: List[int] = []
    for c in order:
        idx = np.asarray(by_cat[c])
        rng.shuffle(idx)
        picked.extend(idx[: floors[c]].tolist())
    picked = [picked[i] for i in rng.permutation(len(picked))]
    return [samples[i] for i in picked]


def split_dataset(
    samples: Sequence[GraphSample],
    perc_train: float,
    stratify_splitting: bool = False,
    seed: int = 0,
) -> Tuple[list, list, list]:
    if not stratify_splitting:
        perc_val = (1 - perc_train) / 2
        n = len(samples)
        a = int(n * perc_train)
        b = int(n * (perc_train + perc_val))
        return list(samples[:a]), list(samples[a:b]), list(samples[b:])
    return compositional_stratified_splitting(samples, perc_train, seed)
