"""End-to-end dataset preparation: raw samples -> model-ready splits.

The TPU-native equivalent of the reference chain
``transform_raw_data_to_serialized`` -> ``SerializedDataLoader.
load_serialized_data`` -> ``split_dataset`` (reference:
hydragnn/preprocess/load_data.py:207-223,335-393 and
hydragnn/preprocess/serialized_dataset_loader.py:106-259). Steps, in the
reference's order:

  1. read raw files (LSMS text / in-memory samples),
  2. ``*_scaled_num_nodes`` feature scaling,
  3. global min-max normalization,
  4. optional rotation normalization (rotational invariance),
  5. radius-graph edges (plain or PBC) + edge lengths,
  6. global max edge-length normalization,
  7. optional spherical-coordinate edge descriptors,
  8. target packing (dict-of-heads) + input-feature column selection,
  9. train/val/test split (proportional or compositional stratified).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hydragnn_tpu.data.radius_graph import (
    edge_lengths,
    radius_graph,
    radius_graph_pbc,
)
from hydragnn_tpu.data.dataset import (
    GraphSample,
    normalize_dataset,
    scale_features_by_num_nodes,
    select_input_features,
    update_predicted_values,
)
from hydragnn_tpu.data.lsms import read_lsms_dir
from hydragnn_tpu.data.splitting import split_dataset


def normalize_rotation(samples: Sequence[GraphSample]) -> None:
    """Center positions and rotate onto principal axes, in place (the
    reference's PyG ``NormalizeRotation`` transform, used at
    serialized_dataset_loader.py:128-130). Edge lengths are invariant."""
    for s in samples:
        in_dtype = np.asarray(s.pos).dtype
        pos = np.asarray(s.pos, dtype=np.float64)
        pos = pos - pos.mean(axis=0, keepdims=True)
        # right singular vectors = principal axes. Reduced SVD gives the
        # full (3,3) vt for n >= 3; only n < 3 needs full_matrices (and
        # only then — full mode materializes a discarded n x n U, which
        # is O(n^2) memory on big graphs)
        _, _, vt = np.linalg.svd(pos, full_matrices=pos.shape[0] < 3)
        # preserve a floating input dtype (the reference's transform does;
        # a float64 dataset keeps float64 fidelity through normalization);
        # non-float positions (e.g. integer lattice coordinates) must not
        # be truncated back to ints
        out_dtype = in_dtype if np.issubdtype(in_dtype, np.floating) else np.float32
        s.pos = (pos @ vt.T).astype(out_dtype)


def build_edges(
    samples: Sequence[GraphSample],
    radius: float,
    max_neighbours: Optional[int],
    periodic_boundary_conditions: bool = False,
    rotational_invariance: bool = False,
    spherical_coordinates: bool = False,
    point_pair_features: bool = False,
    max_edge_length: Optional[float] = None,
) -> float:
    """Compute radius-graph edges and normalized edge-length attributes for
    every sample, in place. Returns the max edge length used for
    normalization (compute it once on train+val+test together, like the
    reference's global max all-reduce, serialized_dataset_loader.py:155-169)."""
    if rotational_invariance:
        normalize_rotation(samples)

    for s in samples:
        if periodic_boundary_conditions:
            cell = s.meta.get("cell")
            if cell is None:
                raise ValueError("PBC requested but sample has no meta['cell']")
            ei = radius_graph_pbc(
                s.pos, radius, cell, max_num_neighbors=max_neighbours, loop=False
            )
        else:
            ei = radius_graph(s.pos, radius, max_num_neighbors=max_neighbours, loop=False)
        s.edge_index = ei
        s.edge_attr = edge_lengths(s.pos, ei)

    if max_edge_length is None:
        max_edge_length = max(
            (float(s.edge_attr.max()) for s in samples if s.edge_attr.size), default=1.0
        )
    for s in samples:
        s.edge_attr = (s.edge_attr / max_edge_length).astype(np.float32)

    if spherical_coordinates:
        _append_spherical(samples)
    if point_pair_features:
        _append_point_pair(samples, max_edge_length)
    return max_edge_length


def _append_spherical(samples: Sequence[GraphSample]) -> None:
    """Append (theta, phi) spherical angles to the edge attributes (PyG
    ``Spherical`` transform equivalent; rho is the existing length)."""
    for s in samples:
        src = s.pos[s.edge_index[0]]
        dst = s.pos[s.edge_index[1]]
        d = (dst - src).astype(np.float64)
        rho = np.linalg.norm(d, axis=1)
        theta = np.arctan2(d[:, 1], d[:, 0])
        theta = np.where(theta < 0, theta + 2 * np.pi, theta) / (2 * np.pi)
        safe_rho = np.where(rho > 0, rho, 1.0)
        phi = np.arccos(np.clip(d[:, 2] / safe_rho, -1.0, 1.0)) / np.pi
        s.edge_attr = np.concatenate(
            [s.edge_attr, theta[:, None].astype(np.float32), phi[:, None].astype(np.float32)],
            axis=1,
        )


def _append_point_pair(samples: Sequence[GraphSample], max_edge_length: float) -> None:
    """Append PointPairFeatures to the edge attributes (PyG
    ``PointPairFeatures`` transform equivalent; reference usage:
    hydragnn/utils/abstractrawdataset.py:380-383). Per edge (i -> j) with
    per-node normals n: [rho, angle(n_i, d), angle(n_j, d),
    angle(n_i, n_j)], angles in radians via atan2(|cross|, dot). Like the
    spherical descriptor, rho is normalized by the global max edge length
    (the raw-length column PyG would duplicate is already present,
    normalized). Normals come from ``sample.meta['norm']`` ([N, 3]) — the
    same contract as PyG's required ``data.norm``."""

    def angle(v1, v2):
        cross = np.linalg.norm(np.cross(v1, v2), axis=1)
        dot = (v1 * v2).sum(axis=1)
        return np.arctan2(cross, dot)

    for s in samples:
        norm = s.meta.get("norm") if s.meta else None
        if norm is None:
            raise ValueError(
                "PointPairFeatures requires per-node normals in "
                "sample.meta['norm'] (the PyG transform's data.norm contract)"
            )
        norm = np.asarray(norm, dtype=np.float64)
        d = (s.pos[s.edge_index[1]] - s.pos[s.edge_index[0]]).astype(np.float64)
        rho = np.linalg.norm(d, axis=1) / max_edge_length
        ni, nj = norm[s.edge_index[0]], norm[s.edge_index[1]]
        feats = np.stack(
            [rho, angle(ni, d), angle(nj, d), angle(ni, nj)], axis=1
        ).astype(np.float32)
        s.edge_attr = np.concatenate([s.edge_attr, feats], axis=1)


def _prepare_samples(
    samples: List[GraphSample], config: Dict
) -> Tuple[np.ndarray, np.ndarray]:
    """The shared preparation body (steps 2-8 of the module docstring),
    in place over ``samples``; returns (minmax_graph, minmax_node)."""
    ds_cfg = config["Dataset"]
    nn_cfg = config["NeuralNetwork"]
    arch = nn_cfg["Architecture"]
    voi = nn_cfg["Variables_of_interest"]
    nf, gf = ds_cfg["node_features"], ds_cfg["graph_features"]

    scale_features_by_num_nodes(samples, gf["name"], nf["name"], gf["dim"], nf["dim"])
    mm_g, mm_n = normalize_dataset(samples, gf["dim"], nf["dim"])

    desc = ds_cfg.get("Descriptors", {})
    build_edges(
        samples,
        radius=arch["radius"],
        max_neighbours=arch.get("max_neighbours"),
        periodic_boundary_conditions=arch.get("periodic_boundary_conditions", False),
        rotational_invariance=ds_cfg.get("rotational_invariance", False),
        spherical_coordinates=desc.get("SphericalCoordinates", False),
        point_pair_features=desc.get("PointPairFeatures", False),
    )

    update_predicted_values(
        samples,
        voi["type"],
        voi["output_index"],
        voi["output_names"],
        gf["dim"],
        nf["dim"],
    )
    select_input_features(samples, voi["input_node_features"], nf["dim"])
    return mm_g, mm_n


def prepare_dataset(
    samples: List[GraphSample],
    config: Dict,
) -> Tuple[List[GraphSample], List[GraphSample], List[GraphSample], np.ndarray, np.ndarray]:
    """Full preparation pipeline on an in-memory sample list.

    ``config`` is the reference-shaped top-level dict (Dataset /
    NeuralNetwork sections). Returns (train, val, test, minmax_graph,
    minmax_node).
    """
    mm_g, mm_n = _prepare_samples(samples, config)
    samples = _maybe_subsample(samples, config)
    train, val, test = split_dataset(
        samples,
        config["NeuralNetwork"]["Training"]["perc_train"],
        stratify_splitting=config["Dataset"].get(
            "compositional_stratified_splitting", False
        ),
    )
    return train, val, test, mm_g, mm_n


def _maybe_subsample(samples: List[GraphSample], config: Dict) -> List[GraphSample]:
    """Variables_of_interest.subsample_percentage: stratified downselect
    after preparation, before splitting (reference: the __build_edge tail,
    hydragnn/utils/abstractrawdataset.py:396-403).

    Like the reference (which subsamples after __update_atom_features),
    this runs after input-feature selection: the stratification category
    reads x[:, 0] of the SELECTED features, so composition stratification
    requires the composition/type column listed first in
    ``input_node_features`` — otherwise the categories quietly degrade to
    whatever feature 0 is."""
    frac = config["NeuralNetwork"]["Variables_of_interest"].get("subsample_percentage")
    if frac is None:
        return samples
    from hydragnn_tpu.data.splitting import stratified_subsample

    return stratified_subsample(samples, float(frac))


def prepare_presplit_dataset(
    train: List[GraphSample],
    val: List[GraphSample],
    test: List[GraphSample],
    config: Dict,
) -> Tuple[List[GraphSample], List[GraphSample], List[GraphSample], np.ndarray, np.ndarray]:
    """Preparation for pre-defined splits (the reference's per-split
    ``Dataset.path.{train,validate,test}`` layout,
    hydragnn/preprocess/load_data.py:352-393): the same pipeline as
    ``prepare_dataset`` with normalization statistics and edge-length
    normalization computed over ALL splits together (the reference's
    global min-max / max-edge reductions span the full dataset), but the
    split membership preserved."""
    counts = (len(train), len(val), len(test))
    merged = list(train) + list(val) + list(test)
    mm_g, mm_n = _prepare_samples(merged, config)
    a, b = counts[0], counts[0] + counts[1]
    # per-split subsample preserves the predefined membership (the
    # reference's serialized loader subsamples each split it loads)
    return (
        _maybe_subsample(merged[:a], config),
        _maybe_subsample(merged[a:b], config),
        _maybe_subsample(merged[b:], config),
        mm_g,
        mm_n,
    )


def load_raw_samples(config: Dict, path: str) -> List[GraphSample]:
    """Format dispatch for raw on-disk datasets (reference:
    hydragnn/preprocess/load_data.py:335-349; format set matches the
    reference's LSMS/CFG/XYZ readers plus the HGC container)."""
    fmt = config["Dataset"]["format"]
    if fmt in ("LSMS", "unit_test"):
        return read_lsms_dir(path, config["Dataset"])
    if fmt == "XYZ":
        from hydragnn_tpu.data.formats import read_xyz_dir

        return read_xyz_dir(path, config["Dataset"])
    if fmt == "CFG":
        from hydragnn_tpu.data.formats import read_cfg_dir

        return read_cfg_dir(path, config["Dataset"])
    if fmt == "HGC":
        from hydragnn_tpu.data.container import ContainerDataset

        return ContainerDataset(path).samples()
    raise NameError(f"Data format not recognized for raw data loader: {fmt}")
