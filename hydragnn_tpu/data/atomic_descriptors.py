"""Per-element embedding tables (mendeleev-free atomic descriptors).

The reference builds per-element feature embeddings from the ``mendeleev``
package and caches them to JSON (reference:
hydragnn/utils/atomicdescriptors.py:12-243): one-hot element type, group id,
period, covalent radius, electron affinity, block one-hot, atomic volume,
atomic number, atomic weight, Pauling electronegativity, valence-electron
count, and first ionization energy; real-valued properties are min-max
normalized over the selected elements, and an optional ``one_hot`` mode
buckets them into 10 categorical bins.

``mendeleev`` is not available in this environment, so the element data is
embedded below (standard physical-constant values: covalent radii in pm,
electron affinities and first ionization energies in eV, atomic volumes in
cm^3/mol, Pauling electronegativities). Same API, numpy instead of torch.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

_BLOCKS = ["s", "p", "d", "f"]

# symbol: (Z, group, period, cov_radius, electron_affinity, block,
#          atomic_volume, atomic_weight, electronegativity, n_valence,
#          first_ionization_energy)
_ELEMENTS: Dict[str, tuple] = {
    "H":  (1, 1, 1, 31, 0.754, "s", 14.1, 1.008, 2.20, 1, 13.598),
    "He": (2, 18, 1, 28, 0.0, "s", 31.8, 4.003, 0.0, 2, 24.587),
    "Li": (3, 1, 2, 128, 0.618, "s", 13.1, 6.940, 0.98, 1, 5.392),
    "Be": (4, 2, 2, 96, 0.0, "s", 5.0, 9.012, 1.57, 2, 9.323),
    "B":  (5, 13, 2, 84, 0.277, "p", 4.6, 10.810, 2.04, 3, 8.298),
    "C":  (6, 14, 2, 76, 1.263, "p", 5.3, 12.011, 2.55, 4, 11.260),
    "N":  (7, 15, 2, 71, -0.070, "p", 17.3, 14.007, 3.04, 5, 14.534),
    "O":  (8, 16, 2, 66, 1.461, "p", 14.0, 15.999, 3.44, 6, 13.618),
    "F":  (9, 17, 2, 57, 3.401, "p", 17.1, 18.998, 3.98, 7, 17.423),
    "Ne": (10, 18, 2, 58, 0.0, "p", 16.8, 20.180, 0.0, 8, 21.565),
    "Na": (11, 1, 3, 166, 0.548, "s", 23.7, 22.990, 0.93, 1, 5.139),
    "Mg": (12, 2, 3, 141, 0.0, "s", 14.0, 24.305, 1.31, 2, 7.646),
    "Al": (13, 13, 3, 121, 0.441, "p", 10.0, 26.982, 1.61, 3, 5.986),
    "Si": (14, 14, 3, 111, 1.385, "p", 12.1, 28.085, 1.90, 4, 8.152),
    "P":  (15, 15, 3, 107, 0.746, "p", 17.0, 30.974, 2.19, 5, 10.487),
    "S":  (16, 16, 3, 105, 2.077, "p", 15.5, 32.060, 2.58, 6, 10.360),
    "Cl": (17, 17, 3, 102, 3.613, "p", 18.7, 35.450, 3.16, 7, 12.968),
    "Ar": (18, 18, 3, 106, 0.0, "p", 24.2, 39.948, 0.0, 8, 15.760),
    "K":  (19, 1, 4, 203, 0.501, "s", 45.3, 39.098, 0.82, 1, 4.341),
    "Ca": (20, 2, 4, 176, 0.025, "s", 29.9, 40.078, 1.00, 2, 6.113),
    "Sc": (21, 3, 4, 170, 0.188, "d", 15.0, 44.956, 1.36, 3, 6.561),
    "Ti": (22, 4, 4, 160, 0.079, "d", 10.6, 47.867, 1.54, 4, 6.828),
    "V":  (23, 5, 4, 153, 0.525, "d", 8.35, 50.942, 1.63, 5, 6.746),
    "Cr": (24, 6, 4, 139, 0.666, "d", 7.23, 51.996, 1.66, 6, 6.767),
    "Mn": (25, 7, 4, 139, 0.0, "d", 7.39, 54.938, 1.55, 7, 7.434),
    "Fe": (26, 8, 4, 132, 0.151, "d", 7.1, 55.845, 1.83, 8, 7.902),
    "Co": (27, 9, 4, 126, 0.662, "d", 6.7, 58.933, 1.88, 9, 7.881),
    "Ni": (28, 10, 4, 124, 1.156, "d", 6.6, 58.693, 1.91, 10, 7.640),
    "Cu": (29, 11, 4, 132, 1.235, "d", 7.1, 63.546, 1.90, 11, 7.726),
    "Zn": (30, 12, 4, 122, 0.0, "d", 9.2, 65.380, 1.65, 12, 9.394),
    "Ga": (31, 13, 4, 122, 0.430, "p", 11.8, 69.723, 1.81, 3, 5.999),
    "Ge": (32, 14, 4, 120, 1.233, "p", 13.6, 72.630, 2.01, 4, 7.899),
    "As": (33, 15, 4, 119, 0.804, "p", 13.1, 74.922, 2.18, 5, 9.789),
    "Se": (34, 16, 4, 120, 2.021, "p", 16.5, 78.971, 2.55, 6, 9.752),
    "Br": (35, 17, 4, 120, 3.364, "p", 23.5, 79.904, 2.96, 7, 11.814),
    "Kr": (36, 18, 4, 116, 0.0, "p", 32.2, 83.798, 3.00, 8, 14.000),
    "Rb": (37, 1, 5, 220, 0.486, "s", 55.9, 85.468, 0.82, 1, 4.177),
    "Sr": (38, 2, 5, 195, 0.048, "s", 33.7, 87.620, 0.95, 2, 5.695),
    "Zr": (40, 4, 5, 175, 0.426, "d", 14.1, 91.224, 1.33, 4, 6.634),
    "Mo": (42, 6, 5, 154, 0.748, "d", 9.4, 95.950, 2.16, 6, 7.092),
    "Ru": (44, 8, 5, 146, 1.050, "d", 8.3, 101.070, 2.20, 8, 7.360),
    "Rh": (45, 9, 5, 142, 1.137, "d", 8.3, 102.906, 2.28, 9, 7.459),
    "Pd": (46, 10, 5, 139, 0.562, "d", 8.9, 106.420, 2.20, 10, 8.337),
    "Ag": (47, 11, 5, 145, 1.302, "d", 10.3, 107.868, 1.93, 11, 7.576),
    "Cd": (48, 12, 5, 144, 0.0, "d", 13.1, 112.414, 1.69, 12, 8.994),
    "In": (49, 13, 5, 142, 0.404, "p", 15.7, 114.818, 1.78, 3, 5.786),
    "Sn": (50, 14, 5, 139, 1.112, "p", 16.3, 118.710, 1.96, 4, 7.344),
    "Sb": (51, 15, 5, 139, 1.046, "p", 18.4, 121.760, 2.05, 5, 8.608),
    "Te": (52, 16, 5, 138, 1.971, "p", 20.5, 127.600, 2.10, 6, 9.010),
    "I":  (53, 17, 5, 139, 3.059, "p", 25.7, 126.904, 2.66, 7, 10.451),
    "Xe": (54, 18, 5, 140, 0.0, "p", 42.9, 131.293, 2.60, 8, 12.130),
    "Pt": (78, 10, 6, 136, 2.128, "d", 9.1, 195.084, 2.28, 10, 8.959),
    "Au": (79, 11, 6, 136, 2.309, "d", 10.2, 196.967, 2.54, 11, 9.226),
    "Pb": (82, 14, 6, 146, 0.356, "p", 18.3, 207.200, 2.33, 4, 7.417),
}

SYMBOLS = list(_ELEMENTS.keys())
ATOMIC_NUMBER = {sym: v[0] for sym, v in _ELEMENTS.items()}
_BY_Z = {v[0]: sym for sym, v in _ELEMENTS.items()}


def _normalize(vals: List[float], name: str) -> np.ndarray:
    arr = np.asarray(vals, dtype=np.float64)
    lo, hi = arr.min(), arr.max()
    if hi == lo:
        return np.zeros_like(arr)
    return (arr - lo) / (hi - lo)


def _real_to_onehot(vals: np.ndarray, num_classes: int = 10) -> np.ndarray:
    """Bucket a real property into ``num_classes`` bins then one-hot
    (reference __realtocategorical__, atomicdescriptors.py:140-146)."""
    lo, hi = vals.min(), vals.max()
    delta = (hi - lo) / num_classes if hi > lo else 1.0
    cats = np.minimum((vals - lo) / delta, num_classes - 1).astype(np.int64)
    return np.eye(num_classes, dtype=np.float32)[cats]


def _int_to_onehot(vals: np.ndarray) -> np.ndarray:
    cats = vals.astype(np.int64)
    return np.eye(int(cats.max()) + 1, dtype=np.float32)[cats]


class atomicdescriptors:
    """Same contract as the reference class: build (or load) a JSON-cached
    per-element embedding dict keyed by atomic number string, and serve it
    via ``get_atom_features(symbol_or_Z)``."""

    def __init__(
        self,
        embeddingfilename: str,
        overwritten: bool = True,
        element_types: Optional[Sequence[str]] = ("C", "H", "O", "N", "F", "S"),
        one_hot: bool = False,
    ):
        if os.path.exists(embeddingfilename) and not overwritten:
            with open(embeddingfilename, "r") as f:
                self.atom_embeddings = json.load(f)
            return

        if element_types is None:
            self.element_types = list(SYMBOLS)
        else:
            unknown = [e for e in element_types if e not in _ELEMENTS]
            if unknown:
                raise ValueError(f"elements not in the embedded table: {unknown}")
            # keep periodic-table order, like mendeleev.get_all_elements()
            self.element_types = [s for s in SYMBOLS if s in set(element_types)]
        self.one_hot = one_hot
        n = len(self.element_types)
        rows = [_ELEMENTS[s] for s in self.element_types]

        type_id = np.eye(n, dtype=np.float32)
        group_id = np.asarray([r[1] - 1 for r in rows], dtype=np.float64)
        period = np.asarray([r[2] - 1 for r in rows], dtype=np.float64)
        cov_radius = _normalize([r[3] for r in rows], "covalent_radius")
        e_affinity = _normalize([r[4] for r in rows], "electron_affinity")
        block = np.eye(len(_BLOCKS), dtype=np.float32)[
            [_BLOCKS.index(r[5]) for r in rows]
        ]
        volume = _normalize([r[6] for r in rows], "atomic_volume")
        z = np.asarray([float(r[0]) for r in rows], dtype=np.float64)
        weight = _normalize([r[7] for r in rows], "atomic_weight")
        en = _normalize([r[8] for r in rows], "electronegativity")
        nvalence = np.asarray([float(r[9]) for r in rows], dtype=np.float64)
        ion = _normalize([r[10] for r in rows], "ionenergies")

        if one_hot:
            group_id = _int_to_onehot(group_id)
            period = _int_to_onehot(period)
            z_col = _int_to_onehot(z)
            nvalence = _int_to_onehot(nvalence)
            cov_radius = _real_to_onehot(cov_radius)
            e_affinity = _real_to_onehot(e_affinity)
            volume = _real_to_onehot(volume)
            weight = _real_to_onehot(weight)
            en = _real_to_onehot(en)
            ion = _real_to_onehot(ion)
        else:
            group_id = group_id[:, None]
            period = period[:, None]
            z_col = z[:, None]
            nvalence = nvalence[:, None]
            cov_radius = cov_radius[:, None]
            e_affinity = e_affinity[:, None]
            volume = volume[:, None]
            weight = weight[:, None]
            en = en[:, None]
            ion = ion[:, None]

        cols = [type_id, group_id, period, cov_radius, e_affinity, block,
                volume, z_col, weight, en, nvalence, ion]
        table = np.concatenate([np.atleast_2d(c) for c in cols], axis=1)

        self.atom_embeddings = {
            str(ATOMIC_NUMBER[s]): table[i].tolist()
            for i, s in enumerate(self.element_types)
        }
        with open(embeddingfilename, "w") as f:
            json.dump(self.atom_embeddings, f)

    def get_atom_features(self, atomtype) -> np.ndarray:
        if isinstance(atomtype, str):
            atomtype = ATOMIC_NUMBER[atomtype]
        return np.asarray(self.atom_embeddings[str(atomtype)], dtype=np.float32)


if __name__ == "__main__":
    d = atomicdescriptors("./embedding.json", overwritten=True,
                          element_types=["C", "H", "S"])
    print(d.get_atom_features("C"))
    print(len(d.get_atom_features("C")))
    d1 = atomicdescriptors("./embedding_onehot.json", overwritten=True,
                           element_types=["C", "H", "S"], one_hot=True)
    print(d1.get_atom_features("C"))
    print(len(d1.get_atom_features("C")))
