"""Preemption handling and the process exit-code contract.

A preemptible TPU slice gets a SIGTERM and a short grace window before
the evictor sends SIGKILL. :class:`PreemptionHandler` converts that
signal into a graceful-stop flag the train loop checks at batch
granularity; the loop then writes a final checkpoint + meta pair,
records ``preempt`` / ``run_end{status:"preempted"}`` flight events,
and raises :class:`TrainingPreempted`. :func:`run_guard` maps the
typed exceptions onto the exit codes the restart supervisor
(:mod:`hydragnn_tpu.resilience.supervisor`) classifies.

Exit codes follow sysexits where one fits (75 = EX_TEMPFAIL: retry is
reasonable; 78 = EX_CONFIG: retry is pointless):

  ===========================  ====  =========================================
  EXIT_OK                         0  run completed
  EXIT_PREEMPTED                 75  graceful SIGTERM/SIGINT stop, resumable
  EXIT_ROLLBACK_EXHAUSTED        76  non-finite sentry gave up (data/model bug)
  EXIT_CONFIG_ERROR              78  config/shape error — fail fast
  EXIT_HUNG                      79  hang watchdog aborted the process
  anything else / signal exits       crash — retried with backoff
  ===========================  ====  =========================================
"""

from __future__ import annotations

import contextlib
import os
import signal
import sys
import threading
import traceback
from typing import Optional
from hydragnn_tpu.utils import knobs

EXIT_OK = 0
EXIT_PREEMPTED = 75
EXIT_ROLLBACK_EXHAUSTED = 76
EXIT_CONFIG_ERROR = 78
EXIT_HUNG = 79


class TrainingPreempted(Exception):
    """The run was gracefully stopped by SIGTERM/SIGINT after writing a
    resumable checkpoint; re-invoking the same config resumes it."""

    exit_code = EXIT_PREEMPTED

    def __init__(self, signum: int, epoch: int):
        self.signum = int(signum)
        self.epoch = int(epoch)
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        super().__init__(
            f"training preempted by {name} at epoch {epoch}; "
            "checkpoint written, resume with the same config"
        )


class NonFiniteRollbackExhausted(RuntimeError):
    """The non-finite sentry rolled back as many times as allowed (or
    had no checkpoint to roll back to) and the run still produces
    non-finite steps — deterministic data/model problem, not worth a
    restart."""

    exit_code = EXIT_ROLLBACK_EXHAUSTED


class PodHostLost(Exception):
    """A peer host of the pod was declared lost from the heartbeat view
    (resilience/podckpt.py:PodSignaler) — typically mid-commit, where
    waiting longer cannot help: the missing host's manifest will never
    arrive. Exits with the PREEMPTED code: the run is resumable from
    the last committed generation and the pod supervisor should
    restart it promptly, not burn the crash backoff budget."""

    exit_code = EXIT_PREEMPTED

    def __init__(self, lost, epoch: int):
        self.lost = sorted(int(h) for h in lost)
        self.epoch = int(epoch)
        super().__init__(
            f"pod host(s) {self.lost} declared lost at epoch {epoch}; "
            "restart from the last committed generation"
        )


class PreemptionHandler:
    """Installable SIGTERM/SIGINT -> graceful-stop flag.

    The signal handler only sets an event (async-signal-safe) and arms
    a hard-exit timer for ``grace_s`` seconds: if the graceful path
    (finish the batch, write the checkpoint, flush the flight record)
    overruns the window the evictor would enforce anyway, the process
    self-exits with :data:`EXIT_PREEMPTED` rather than dying
    checkpoint-less to the follow-up SIGKILL.

    Installation is best-effort: off the main thread (e.g. a serve
    worker driving training) ``signal.signal`` raises and the handler
    stays inert (``available`` False). ``uninstall`` restores the
    previous handlers and cancels the timer — REQUIRED before the
    process outlives the run (the train loop does this on every exit
    path).
    """

    def __init__(
        self,
        signals=(signal.SIGTERM, signal.SIGINT),
        grace_s: float = 30.0,
        hard_exit: bool = True,
    ):
        self.grace_s = float(grace_s)
        self.hard_exit = bool(hard_exit)
        # graftsync: thread-safe=written only by the signal handler, which CPython runs on the main thread; GIL-atomic int
        self.signum: Optional[int] = None
        # graftsync: thread-safe=written only from the owning thread in install()/uninstall(); the timer thread never touches it
        self.available = False
        self._signals = tuple(signals)
        self._stop = threading.Event()
        # pod coordination (resilience/podckpt.py): when the train loop
        # attaches a PodSignaler + keeps proposed_gen current, the
        # SIGTERM handler announces the preemption to peer hosts so the
        # whole pod cuts the SAME generation inside the grace window
        # graftsync: thread-safe=written by the main thread (loop setup / per-epoch update); read by the main-thread signal handler
        self.signaler = None
        self.proposed_gen = 0
        # graftsync: thread-safe=install()/uninstall() run on the owning (main) thread only
        self._old: dict = {}
        # graftsync: thread-safe=written by the main-thread signal handler and uninstall(); CPython delivers signals on the main thread
        self._timer: Optional[threading.Timer] = None

    def install(self) -> "PreemptionHandler":
        try:
            for sig in self._signals:
                self._old[sig] = signal.signal(sig, self._handle)
            self.available = True
        except ValueError:
            # not the main thread: restore whatever we managed to set
            self.uninstall()
            self.available = False
        return self

    def uninstall(self) -> None:
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except ValueError:
                pass
        self._old.clear()
        self.available = False
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _handle(self, signum, frame) -> None:
        self.signum = signum
        self._stop.set()
        if self.signaler is not None:
            # never raises (PodSignaler.post_preempt is exception-safe)
            self.signaler.post_preempt(self.proposed_gen, signum)
        if self.hard_exit and self._timer is None:
            t = threading.Timer(self.grace_s, self._force_exit)
            t.daemon = True
            t.start()
            self._timer = t

    # graftsync: thread-root
    def _force_exit(self) -> None:
        # runs on the timer thread after the grace window: plain write
        # (no logging machinery) then immediate exit — the evictor's
        # SIGKILL is due any moment
        try:
            os.write(
                2,
                (
                    f"PreemptionHandler: grace window ({self.grace_s}s) "
                    "exceeded; hard-exiting\n"
                ).encode(),
            )
        except OSError:
            pass
        os._exit(EXIT_PREEMPTED)

    def should_stop(self) -> bool:
        return self._stop.is_set()

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


@contextlib.contextmanager
def run_guard():
    """Map the typed training exceptions onto the supervisor's exit-code
    contract — wrap a driver's ``run_training`` call::

        with run_guard():
            run_training(cfg, samples=samples)

    ``ValueError`` / ``KeyError`` / ``TypeError`` / ``FileNotFoundError``
    are classified as config errors (the dominant class for a mis-built
    config or dataset path; deterministic, so the supervisor fail-fasts
    instead of burning its restart budget). Every other exception
    propagates as the generic crash the supervisor retries.
    """
    try:
        yield
    except TrainingPreempted as exc:
        raise SystemExit(exc.exit_code)
    except PodHostLost as exc:
        print(f"run_guard: {exc}", file=sys.stderr)
        raise SystemExit(exc.exit_code)
    except NonFiniteRollbackExhausted as exc:
        print(f"run_guard: {exc}", file=sys.stderr)
        raise SystemExit(exc.exit_code)
    except RuntimeError as exc:
        from hydragnn_tpu.utils.checkpoint import CheckpointFormatError

        if isinstance(exc, CheckpointFormatError):
            # an upgrade refusal is deterministic — retrying cannot help
            traceback.print_exc()
            print(
                "run_guard: checkpoint format refusal (fail-fast)",
                file=sys.stderr,
            )
            raise SystemExit(EXIT_CONFIG_ERROR)
        raise
    except (ValueError, KeyError, TypeError, FileNotFoundError):
        traceback.print_exc()
        print("run_guard: classified as config error (fail-fast)", file=sys.stderr)
        raise SystemExit(EXIT_CONFIG_ERROR)


def auto_resume_config(training: dict, log_name: str, log_dir: str) -> bool:
    """Supervisor resume wiring: when ``HYDRAGNN_AUTO_RESUME=1`` (set by
    the restart supervisor for every restarted child) and the run's
    checkpoint already exists, flip the config to
    ``Training.continue=1`` / ``startfrom=<log_name>`` so the restarted
    process continues instead of starting over. Returns True when the
    config was mutated."""
    if knobs.raw("HYDRAGNN_AUTO_RESUME") != "1":
        return False
    from hydragnn_tpu.utils.checkpoint import checkpoint_exists

    if not checkpoint_exists(log_name, log_dir):
        return False
    training["continue"] = 1
    training.setdefault("startfrom", log_name)
    return True
