"""Pod-scale sharded checkpointing with a generation commit protocol,
plus the filesystem coordination plane the pod runs on (preemption
signals, bounded barriers, liveness heartbeats).

The single-host msgpack path (utils/checkpoint.py) funnels the whole
TrainState through rank 0; on a pod that is both slow (every FSDP/ZeRO
shard gathered over the wire) and fragile (a host dying mid-save tears
the only copy). This module gives every host its own atomic shard file
and makes "which checkpoint is complete?" a one-file question:

  <run_dir>/podckpt/
    ckpt.gen<N>.host<k>.mp              host k's leaf payload (flax msgpack)
    ckpt.gen<N>.host<k>.mp.sha256       integrity sidecar (hex digest)
    ckpt.gen<N>.host<k>.manifest.json   leaf paths, shapes, slices, layout
    gen<N>.COMMIT                       written by rank 0 LAST, only after
                                        every expected manifest validates

A generation without its COMMIT marker is torn by definition and is
never restored; restore walks committed generations newest-first,
validates every shard sidecar, and falls back a generation (loudly)
on any mismatch. Because manifests carry per-leaf slice indices, a
checkpoint cut under one layout restores onto another — fewer hosts,
different mesh — by reassembling full leaves host-side (elastic
re-shard; docs/RESILIENCE.md "Pod recovery").

Coordination files live next door:

  <run_dir>/podsync/
    heartbeat.host<k>.json      periodic liveness beat (t, epoch, step)
    preempt.host<k>.json        "I was SIGTERMed; cut generation G"
    barrier.<name>.host<k>      bounded-wait rendezvous markers

The same exchange directory podview's flight shards use — any shared
filesystem works; on a real pod without one, data/diststore.py's
sharded TCP store is the drop-in transport (same tiny key/value
semantics, documented alternative, not wired here).
"""

from __future__ import annotations

import json
import os
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from hydragnn_tpu.resilience.inject import (
    maybe_pod_barrier_stall,
    maybe_pod_kill_host,
    maybe_pod_lost_heartbeat,
    maybe_pod_torn_shard,
)
from hydragnn_tpu.utils import knobs
from hydragnn_tpu.utils.checkpoint import (
    CHECKPOINT_FORMAT_VERSION,
    CheckpointFormatError,
    _atomic_write,
    _sha256_hex,
)

POD_DIR = "podckpt"
SYNC_DIR = "podsync"


class PodShardError(RuntimeError):
    """A pod checkpoint generation failed validation (missing/torn/
    corrupt shard, incomplete leaf coverage). Restore treats it as
    "fall back one generation", never as fatal on its own."""


# -- paths -----------------------------------------------------------------


def pod_dir(run_dir: str) -> str:
    return os.path.join(run_dir, POD_DIR)


def sync_dir(run_dir: str) -> str:
    return os.path.join(run_dir, SYNC_DIR)


def _shard_path(run_dir: str, gen: int, host: int) -> str:
    return os.path.join(pod_dir(run_dir), f"ckpt.gen{gen}.host{host}.mp")


def _manifest_path(run_dir: str, gen: int, host: int) -> str:
    return os.path.join(pod_dir(run_dir), f"ckpt.gen{gen}.host{host}.manifest.json")


def _commit_path(run_dir: str, gen: int) -> str:
    return os.path.join(pod_dir(run_dir), f"gen{gen}.COMMIT")


# -- leaf flattening -------------------------------------------------------


def flatten_state(state: Any) -> Dict[str, Any]:
    """The TrainState as a flat ``{"a/b/c": leaf}`` dict (flax
    state-dict traversal, '/'-joined keys, sorted order). The flat key
    set is the checkpoint schema both sides of a restore agree on."""
    nested = serialization.to_state_dict(state)
    out: Dict[str, Any] = {}

    def _walk(node, prefix):
        if isinstance(node, dict):
            for key in sorted(node):
                _walk(node[key], f"{prefix}/{key}" if prefix else str(key))
        else:
            out[prefix] = node

    _walk(nested, "")
    return out


def _slices_of(index, shape) -> List[List[int]]:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


# -- save ------------------------------------------------------------------


def save_pod_shard(
    state: Any,
    run_dir: str,
    *,
    gen: int,
    host: int,
    hosts: int,
    step: Optional[int] = None,
    layout: Optional[dict] = None,
) -> dict:
    """Write host ``k``'s shard of generation ``gen``: payload file,
    sha256 sidecar, then the per-host manifest (in that order — a crash
    between them leaves a manifest-less shard the commit wait times out
    on, never a manifest pointing at missing bytes). Returns the
    manifest. Distributed leaves (jax.Array with non-addressable
    shards) contribute this host's replica-0 shards with their slice
    indices; fully-addressable leaves are deal-dealt round-robin over
    sorted leaf paths so every leaf has exactly one owner."""
    flat = flatten_state(state)
    payload: Dict[str, np.ndarray] = {}
    entries: List[dict] = []
    for i, path in enumerate(sorted(flat)):
        leaf = flat[path]
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                key = str(len(payload))
                payload[key] = np.asarray(shard.data)
                entries.append(
                    {
                        "path": path,
                        "key": key,
                        "shape": [int(d) for d in leaf.shape],
                        "dtype": str(np.asarray(shard.data).dtype),
                        "slices": _slices_of(shard.index, leaf.shape),
                    }
                )
        else:
            if i % hosts != host:
                continue
            arr = np.asarray(leaf)
            key = str(len(payload))
            payload[key] = arr
            entries.append(
                {
                    "path": path,
                    "key": key,
                    "shape": [int(d) for d in arr.shape],
                    "dtype": str(arr.dtype),
                    "slices": None,
                }
            )
    os.makedirs(pod_dir(run_dir), exist_ok=True)
    data = serialization.msgpack_serialize(payload)
    sha = _sha256_hex(data)
    if maybe_pod_torn_shard(host, gen):
        # sidecar carries the GOOD digest, the payload gets torn bytes:
        # the sha-mismatch restore must reject (torn-shard injection)
        data = data[: max(len(data) // 2, 1)]
    shard_path = _shard_path(run_dir, gen, host)
    _atomic_write(shard_path, data)
    _atomic_write(shard_path + ".sha256", sha.encode())
    # SIGKILL-mid-checkpoint injection: shard bytes exist, manifest
    # never lands -> the generation can never commit (torn gen)
    maybe_pod_kill_host(host, gen)
    manifest = {
        "format_version": CHECKPOINT_FORMAT_VERSION,
        "gen": int(gen),
        "step": None if step is None else int(step),
        "host": int(host),
        "hosts": int(hosts),
        "layout": layout,
        "shard": os.path.basename(shard_path),
        "sha256": sha,
        "leaves": entries,
        "t": time.time(),
    }
    _atomic_write(
        _manifest_path(run_dir, gen, host),
        json.dumps(manifest, sort_keys=True).encode(),
    )
    return manifest


def _validate_host_shard(run_dir: str, gen: int, host: int) -> Optional[str]:
    """None when host ``k``'s shard of ``gen`` is whole, else a short
    reason naming the bad file."""
    mp = _manifest_path(run_dir, gen, host)
    try:
        with open(mp) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        return f"manifest {os.path.basename(mp)} unreadable ({exc})"
    sp = _shard_path(run_dir, gen, host)
    try:
        with open(sp, "rb") as f:
            data = f.read()
    except OSError:
        return f"shard {os.path.basename(sp)} missing"
    if _sha256_hex(data) != manifest.get("sha256"):
        return f"shard {os.path.basename(sp)} sha256 mismatch (torn write)"
    return None


def commit_generation(
    run_dir: str,
    gen: int,
    hosts: int,
    *,
    timeout_s: Optional[float] = None,
    poll_s: float = 0.05,
    signaler: Optional["PodSignaler"] = None,
    step: Optional[int] = None,
    layout: Optional[dict] = None,
) -> dict:
    """Rank 0's half of the protocol: bounded-wait until every expected
    host manifest exists and validates, then write ``gen<N>.COMMIT``
    atomically (LAST). Never raises and never hangs: on timeout, a bad
    shard, or a peer the heartbeat view declares lost, it returns
    ``committed=False`` with the evidence and the caller decides
    (proceed-and-record). Non-zero hosts never call this — they write
    their shard and move on, so a sequentially-simulated pod (ci.sh
    runs host 1 to completion before host 0 starts) still commits."""
    if timeout_s is None:
        timeout_s = knobs.get_float("HYDRAGNN_POD_COMMIT_TIMEOUT_S", 120.0)
    deadline = time.monotonic() + float(timeout_s)
    t0 = time.monotonic()
    while True:
        missing = [
            k for k in range(hosts) if not os.path.exists(_manifest_path(run_dir, gen, k))
        ]
        if not missing:
            break
        lost = sorted(set(missing) & set(signaler.lost_hosts())) if signaler else []
        if lost:
            return {
                "committed": False,
                "gen": int(gen),
                "missing": missing,
                "lost": lost,
                "bad": [],
                "waited_s": round(time.monotonic() - t0, 3),
            }
        if time.monotonic() > deadline:
            return {
                "committed": False,
                "gen": int(gen),
                "missing": missing,
                "lost": [],
                "bad": [],
                "timeout": True,
                "waited_s": round(time.monotonic() - t0, 3),
            }
        time.sleep(poll_s)
    bad = []
    for k in range(hosts):
        reason = _validate_host_shard(run_dir, gen, k)
        if reason is not None:
            bad.append(reason)
    if step is None or layout is None:
        # the COMMIT record carries the generation's step/layout for
        # readers that never open a manifest; host 0's manifest is the
        # authoritative source when the caller did not pass them
        try:
            with open(_manifest_path(run_dir, gen, 0)) as f:
                m0 = json.load(f)
            step = m0.get("step") if step is None else step
            layout = m0.get("layout") if layout is None else layout
        except (OSError, ValueError):
            pass
    if bad:
        return {
            "committed": False,
            "gen": int(gen),
            "missing": [],
            "lost": [],
            "bad": bad,
            "waited_s": round(time.monotonic() - t0, 3),
        }
    _atomic_write(
        _commit_path(run_dir, gen),
        json.dumps(
            {
                "format_version": CHECKPOINT_FORMAT_VERSION,
                "gen": int(gen),
                "step": None if step is None else int(step),
                "hosts": int(hosts),
                "layout": layout,
                "t": time.time(),
            },
            sort_keys=True,
        ).encode(),
    )
    return {
        "committed": True,
        "gen": int(gen),
        "hosts": int(hosts),
        "waited_s": round(time.monotonic() - t0, 3),
    }


# -- discovery / restore ---------------------------------------------------


def list_committed_generations(run_dir: str) -> List[int]:
    """Generation numbers with a COMMIT marker, ascending. Shard files
    without their marker are torn by definition and never listed."""
    d = pod_dir(run_dir)
    gens = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        if name.startswith("gen") and name.endswith(".COMMIT"):
            try:
                gens.append(int(name[len("gen") : -len(".COMMIT")]))
            except ValueError:
                continue
    return sorted(gens)


def read_commit(run_dir: str, gen: int) -> dict:
    """The COMMIT record for ``gen``. Raises :class:`PodShardError` on
    a missing/unreadable marker and :class:`CheckpointFormatError` on a
    format_version newer than this build understands — a typed refusal,
    not a parse crash (docs/RESILIENCE.md "Checkpoint format")."""
    p = _commit_path(run_dir, gen)
    try:
        with open(p) as f:
            commit = json.load(f)
    except (OSError, ValueError) as exc:
        raise PodShardError(
            f"generation {gen} has no readable COMMIT marker ({exc})"
        ) from exc
    fv = commit.get("format_version")
    if fv is not None and int(fv) > CHECKPOINT_FORMAT_VERSION:
        raise CheckpointFormatError(
            f"pod checkpoint generation {gen} was written by format_version "
            f"{fv}; this build understands <= {CHECKPOINT_FORMAT_VERSION}"
        )
    return commit


def load_generation(run_dir: str, gen: int) -> Tuple[Dict[str, np.ndarray], dict]:
    """Reassemble generation ``gen`` into full host-side leaves from
    every host's shard + manifest — the elastic half of the protocol:
    the reader needs only the manifests, not the writer's host count or
    mesh. Raises :class:`PodShardError` naming the first bad shard."""
    commit = read_commit(run_dir, gen)
    hosts = int(commit["hosts"])
    flat: Dict[str, np.ndarray] = {}
    partial: Dict[str, Tuple[np.ndarray, int]] = {}
    for k in range(hosts):
        reason = _validate_host_shard(run_dir, gen, k)
        if reason is not None:
            raise PodShardError(f"generation {gen}: {reason}")
        with open(_manifest_path(run_dir, gen, k)) as f:
            manifest = json.load(f)
        with open(_shard_path(run_dir, gen, k), "rb") as f:
            try:
                payload = serialization.msgpack_restore(f.read())
            except Exception as exc:
                raise PodShardError(
                    f"generation {gen}: shard ckpt.gen{gen}.host{k}.mp "
                    f"unparseable ({exc})"
                ) from exc
        for entry in manifest.get("leaves", []):
            arr = np.asarray(payload[entry["key"]])
            if entry["slices"] is None:
                flat[entry["path"]] = arr
                continue
            shape = tuple(entry["shape"])
            buf, covered = partial.get(entry["path"], (None, 0))
            if buf is None:
                buf = np.zeros(shape, dtype=arr.dtype)
            idx = tuple(slice(s, e) for s, e in entry["slices"])
            buf[idx] = arr
            partial[entry["path"]] = (buf, covered + int(arr.size))
    for path, (buf, covered) in partial.items():
        if covered < buf.size:
            raise PodShardError(
                f"generation {gen}: leaf {path} has incomplete shard "
                f"coverage ({covered}/{buf.size} elements)"
            )
        flat[path] = buf
    return flat, commit


def _flat_into_state(state: Any, flat: Dict[str, np.ndarray]) -> Any:
    target = flatten_state(state)
    missing = sorted(set(target) - set(flat))
    extra = sorted(set(flat) - set(target))
    if missing or extra:
        raise PodShardError(
            f"leaf schema mismatch: missing={missing[:4]} extra={extra[:4]} "
            f"(checkpoint and target model disagree)"
        )
    # merge the flat leaves into the target's own state-dict template:
    # empty subtrees (an empty opt_state, no batch stats) have no flat
    # leaves, and from_state_dict still requires their keys to exist
    nested = serialization.to_state_dict(state)
    for path, leaf in flat.items():
        node = nested
        keys = path.split("/")
        for key in keys[:-1]:
            node = node[key]
        node[keys[-1]] = leaf
    restored = serialization.from_state_dict(state, nested)

    # preserve the target's placement, exactly like the msgpack restore
    # (utils/checkpoint._restore_bytes_into): reassembled host leaves go
    # back onto whatever sharding the caller's freshly-built state
    # carries — THIS is the elastic re-shard step
    def _place(tgt, val):
        if isinstance(tgt, jax.Array) and hasattr(tgt, "sharding"):
            return jax.device_put(val, tgt.sharding)
        return val

    return jax.tree_util.tree_map(_place, state, restored)


# graftsync: thread-safe=restore lineage handoff written once by the single restoring thread before the train loop starts, consumed once by it
_LAST_RESTORE_INFO: Optional[dict] = None


def consume_last_restore_info() -> Optional[dict]:
    """The lineage of the most recent pod restore in this process
    ({gen, step, hosts, layout, fallbacks}), returned once — the train
    loop stamps it into the run_start manifest as ``pod_resume``."""
    global _LAST_RESTORE_INFO
    info, _LAST_RESTORE_INFO = _LAST_RESTORE_INFO, None
    return info


def restore_pod_checkpoint(state: Any, run_dir: str) -> Tuple[Any, Optional[dict]]:
    """Restore the newest valid committed generation into ``state``,
    falling back generation-by-generation on torn/missing/corrupt
    shards with a loud RuntimeWarning naming the bad shard. Returns
    ``(state, info)``; ``info=None`` means nothing restorable (caller
    falls through to the single-host msgpack chain). A future
    format_version raises :class:`CheckpointFormatError` — upgrade
    refusals must be typed, never silent fallbacks."""
    gens = list_committed_generations(run_dir)
    if not gens:
        return state, None
    fallbacks: List[dict] = []
    for gen in reversed(gens):
        try:
            flat, commit = load_generation(run_dir, gen)
            restored = _flat_into_state(state, flat)
        except PodShardError as exc:
            warnings.warn(
                f"pod checkpoint generation {gen} rejected: {exc}; "
                f"falling back to the previous committed generation",
                RuntimeWarning,
                stacklevel=2,
            )
            fallbacks.append({"gen": int(gen), "error": str(exc)})
            continue
        info = {
            "gen": int(gen),
            "step": commit.get("step"),
            "hosts": commit.get("hosts"),
            "layout": commit.get("layout"),
            "fallbacks": fallbacks,
        }
        global _LAST_RESTORE_INFO
        _LAST_RESTORE_INFO = dict(info)
        return restored, info
    warnings.warn(
        f"all {len(gens)} committed pod generations under {run_dir} failed "
        f"validation; falling through to the single-host checkpoint chain",
        RuntimeWarning,
        stacklevel=2,
    )
    return state, None


def latest_commit_info(run_dir: str) -> Optional[dict]:
    """The newest readable COMMIT record, or None — obs_report's
    ``--validate`` surfaces it next to each run."""
    for gen in reversed(list_committed_generations(run_dir)):
        try:
            return read_commit(run_dir, gen)
        except (PodShardError, CheckpointFormatError):
            continue
    return None


def prune_generations(run_dir: str, keep_last: Optional[int] = None) -> None:
    """Drop committed generations beyond the newest ``keep_last``
    (COMMIT marker first, then shards — a reader racing the prune sees
    a missing marker, i.e. an invalid generation, never a committed one
    with missing bytes). Uncommitted debris newer than the newest
    commit is left alone: it may be a commit in flight."""
    if keep_last is None:
        keep_last = knobs.get_int("HYDRAGNN_POD_KEEP_GENS", 3)
    gens = list_committed_generations(run_dir)
    d = pod_dir(run_dir)
    for gen in gens[: max(0, len(gens) - int(keep_last))]:
        victims = [_commit_path(run_dir, gen)]
        for name in os.listdir(d):
            if name.startswith(f"ckpt.gen{gen}.host"):
                victims.append(os.path.join(d, name))
        for victim in victims:
            try:
                os.remove(victim)
            except OSError:
                pass


# -- coordination plane ----------------------------------------------------


def pod_barrier(
    run_dir: str,
    name: str,
    host: int,
    hosts: int,
    *,
    timeout_s: Optional[float] = None,
    poll_s: float = 0.05,
) -> Tuple[bool, List[int]]:
    """Bounded-wait rendezvous: write this host's marker, poll for the
    peers', and after ``timeout_s`` PROCEED anyway, returning
    ``(False, missing_hosts)`` so the caller can record the partial
    barrier — a pod must degrade to evidence, never to a hang."""
    maybe_pod_barrier_stall(host)
    if timeout_s is None:
        timeout_s = knobs.get_float("HYDRAGNN_POD_BARRIER_TIMEOUT_S", 60.0)
    d = sync_dir(run_dir)
    os.makedirs(d, exist_ok=True)
    _atomic_write(
        os.path.join(d, f"barrier.{name}.host{host}"),
        json.dumps({"t": time.time()}).encode(),
    )
    deadline = time.monotonic() + float(timeout_s)
    while True:
        missing = [
            k
            for k in range(hosts)
            if not os.path.exists(os.path.join(d, f"barrier.{name}.host{k}"))
        ]
        if not missing:
            return True, []
        if time.monotonic() > deadline:
            return False, missing
        time.sleep(poll_s)


class PodSignaler:
    """Filesystem coordination for one host of a pod: liveness
    heartbeats, coordinated-preemption signals, and the lost-host view.

    Loss detection is armed only when ``HYDRAGNN_POD_LOST_AFTER_S > 0``
    (default off): the simulated-host CI mode runs hosts sequentially,
    where stale beats are normal. When armed, a peer whose newest beat
    (or, before its first beat, this signaler's own birth) is older
    than the threshold is lost; ``undeclared_lost()`` hands each lost
    host out exactly once so the ``host_lost`` flight event fires once
    per host no matter how many sites poll.
    """

    # graftsync: thread-safe=mutated only by the owning host's main thread (signal handlers run in the main thread in CPython); peers communicate via atomic file replaces, never shared memory

    def __init__(self, run_dir: str, host: int, hosts: int):
        self.run_dir = run_dir
        self.host = int(host)
        self.hosts = int(hosts)
        self.heartbeat_s = knobs.get_float("HYDRAGNN_POD_HEARTBEAT_S", 1.0)
        self.lost_after_s = knobs.get_float("HYDRAGNN_POD_LOST_AFTER_S", 0.0)
        self._t0 = time.time()
        self._last_beat = 0.0
        self._epoch: Optional[int] = None
        self._declared: set = set()
        d = sync_dir(run_dir)
        try:
            os.makedirs(d, exist_ok=True)
            # a stale preempt signal from a previous attempt would
            # instantly re-preempt the restarted run — clear our own
            os.remove(self._preempt_path(self.host))
        except OSError:
            pass

    def _beat_path(self, host: int) -> str:
        return os.path.join(sync_dir(self.run_dir), f"heartbeat.host{host}.json")

    def _preempt_path(self, host: int) -> str:
        return os.path.join(sync_dir(self.run_dir), f"preempt.host{host}.json")

    # -- liveness ----------------------------------------------------------

    def heartbeat(
        self,
        *,
        epoch: Optional[int] = None,
        step: Optional[int] = None,
        force: bool = False,
    ) -> None:
        """Write this host's beat file (rate-limited to one per
        ``heartbeat_s``). Under the LOST_HEARTBEAT injection the host
        goes silent from the injected epoch on — alive but beatless,
        exactly what a wedged host looks like from outside."""
        if epoch is not None:
            self._epoch = int(epoch)
        if maybe_pod_lost_heartbeat(self.host, self._epoch):
            return
        now = time.time()
        if not force and now - self._last_beat < self.heartbeat_s:
            return
        self._last_beat = now
        try:
            _atomic_write(
                self._beat_path(self.host),
                json.dumps(
                    {
                        "t": now,
                        "host": self.host,
                        "epoch": self._epoch,
                        "step": None if step is None else int(step),
                    }
                ).encode(),
            )
        except OSError:
            pass

    def peer_heartbeats(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for k in range(self.hosts):
            try:
                with open(self._beat_path(k)) as f:
                    out[k] = json.load(f)
            except (OSError, ValueError):
                continue
        return out

    def lost_hosts(self) -> List[int]:
        """Peers whose liveness lapsed past ``lost_after_s`` (empty
        when detection is disarmed). Beats older than this signaler's
        birth count as absent — they are leftovers of a previous
        attempt, and a freshly-restarted pod must give every peer the
        full threshold to produce its first live beat."""
        if self.lost_after_s <= 0:
            return []
        now = time.time()
        beats = self.peer_heartbeats()
        lost = []
        for k in range(self.hosts):
            if k == self.host:
                continue
            beat_t = float(beats.get(k, {}).get("t", 0.0))
            alive_t = beat_t if beat_t >= self._t0 else self._t0
            if now - alive_t > self.lost_after_s:
                lost.append(k)
        return lost

    def undeclared_lost(self) -> List[int]:
        """Lost hosts not yet handed to a caller — the dedupe that
        keeps ``host_lost`` at exactly one flight event per host."""
        return self.mark_declared(self.lost_hosts())

    def mark_declared(self, hosts) -> List[int]:
        """Filter ``hosts`` down to the not-yet-declared ones and mark
        them declared. Lets the commit path (which learns about lost
        peers from ``commit_generation`` rather than its own poll)
        share the same one-event-per-host dedupe."""
        fresh = sorted(int(k) for k in set(hosts) if int(k) not in self._declared)
        self._declared.update(fresh)
        return fresh

    # -- coordinated preemption --------------------------------------------

    def post_preempt(self, gen: int, signum: int = 15) -> None:
        """Announce "this host was preempted; everyone cut generation
        >= gen" to the pod. Called from the SIGTERM handler, so it must
        never raise."""
        try:
            os.makedirs(sync_dir(self.run_dir), exist_ok=True)
            _atomic_write(
                self._preempt_path(self.host),
                json.dumps(
                    {
                        "gen": int(gen),
                        "host": self.host,
                        "signum": int(signum),
                        "t": time.time(),
                    }
                ).encode(),
            )
        except OSError:
            pass

    def preempt_request(self) -> Optional[dict]:
        """The pod-wide preemption request, if any: the posting with
        the HIGHEST requested generation wins, so every host cuts the
        same (maximal) generation inside the grace window."""
        best: Optional[dict] = None
        for k in range(self.hosts):
            try:
                with open(self._preempt_path(k)) as f:
                    req = json.load(f)
            except (OSError, ValueError):
                continue
            if best is None or int(req.get("gen", 0)) > int(best.get("gen", 0)):
                best = req
        return best
