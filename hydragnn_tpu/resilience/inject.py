"""Env-gated deterministic fault injection.

Every resilience path is only trustworthy if it can be driven on
demand; these hooks make each failure mode a reproducible test case
(tests/test_resilience.py, ci.sh fault-injection smoke stage) instead
of a production anecdote. All hooks are no-ops unless their env var is
set, and the restart supervisor strips ``HYDRAGNN_INJECT_*`` from
restarted children by default so an injected fault fires exactly once
per supervised run.

  =================================  ==========================================
  HYDRAGNN_INJECT_NAN_STEP=N[:M]     replace the batch's node features with
                                     NaN for train steps N..N+M-1 (M=1)
  HYDRAGNN_INJECT_SIGTERM_STEP=N     SIGTERM self-signal before train step N
  HYDRAGNN_INJECT_SIGTERM_EPOCH=E    SIGTERM self-signal at the start of
                                     epoch E (the epoch-boundary case)
  HYDRAGNN_INJECT_KILL_CHECKPOINT=K  during the K-th (1-indexed) checkpoint
                                     save of this process: write a TRUNCATED
                                     checkpoint file in place (simulating a
                                     torn write on a filesystem without
                                     atomic replace) and SIGKILL the process
  HYDRAGNN_INJECT_STALL_LOADER=B:S   the loader's producer sleeps S seconds
                                     before building batch B of an epoch
                                     (drives the hang watchdog)
  HYDRAGNN_INJECT_DONATION_CHECK_    force the persistent executable cache's
  FAIL=1                             donation round-trip gate to report
                                     failure (checked directly in
                                     utils/exec_cache.py:donation_roundtrip_ok)
                                     — a donated cached executable is then
                                     EVICTED with a ``donation_check_failed``
                                     miss and the consumer live-compiles
  HYDRAGNN_INJECT_TRIGGER=RULE       force-fire the named SLO trigger rule
                                     once at the next TriggerEngine.evaluate
                                     (obs/triggers.py) — drives the incident
                                     capture path without waiting for a real
                                     anomaly
  =================================  ==========================================

Serving-side faults (docs/RESILIENCE.md "Serving resilience"; request
numbers are the server's admission sequence, 0-based, so an injection
follows its request through batch coalescing AND the retry-as-singles
poison hunt):

  =====================================  ======================================
  HYDRAGNN_INJECT_SERVE_RAISE=N          the forward raises for any batch
                                         containing request N (poison request)
  HYDRAGNN_INJECT_SERVE_NAN=N            the forward's outputs are replaced
                                         with NaN for any batch containing
                                         request N (silent-corruption poison)
  HYDRAGNN_INJECT_SERVE_WEDGE=N:S        the dispatch thread sleeps S seconds
                                         (default 5) inside the forward of the
                                         batch containing request N (wedged
                                         dispatch — drives the serve watchdog)
  HYDRAGNN_INJECT_SERVE_KILL_DISPATCH=K  the K-th (1-indexed) dispatched batch
                                         raises OUTSIDE request isolation,
                                         killing the dispatch thread (drives
                                         the dispatch supervisor restart)
  HYDRAGNN_INJECT_SERVE_TORN_RELOAD=1    ModelServer.reload corrupts the
                                         candidate weights to NaN before the
                                         canary (the canary must fail and the
                                         old weights must keep serving)
  HYDRAGNN_INJECT_DRIFT=SHIFT            add a deterministic covariate shift
                                         of SHIFT (a float) to every incoming
                                         request's node features at admission
                                         (drives the feature_drift trigger +
                                         spool path; obs/drift.py)
  =====================================  ======================================

Retrain-pilot faults (hydragnn_tpu/pilot, docs/RESILIENCE.md "Closed
loop") — one per pilot stage, each proving the loop degrades to "old
weights keep serving" instead of making serving worse:

  =====================================  ======================================
  HYDRAGNN_INJECT_PILOT_TRAIN_CRASH=N    the pilot's first N fine-tune attempts
                                         exit nonzero before training (N=1:
                                         retry-with-backoff then success; N >=
                                         the attempt budget: failed cycle)
  HYDRAGNN_INJECT_PILOT_HUNG_TUNE=S      the fine-tune job wedges S seconds
                                         before any work (the supervisor
                                         wall-clock kill classifies hung/79)
  HYDRAGNN_INJECT_PILOT_CANARY_REGRESS   inflate the candidate's canary scores
  =1                                     so the gate rejects it (cooldown on
                                         the old weights, never a reload)
  HYDRAGNN_INJECT_PILOT_TORN_RELOAD=1    corrupt the candidate's weights
                                         between the pilot canary and the
                                         reload (the server's own reload
                                         canary must reject them)
  =====================================  ======================================

Pod faults (resilience/podckpt.py, docs/RESILIENCE.md "Pod recovery")
— each anchored to a (host, step-like) pair so exactly one simulated
host misbehaves at exactly one point, and provable both in-process
(tests/test_podckpt.py) and end-to-end (ci.sh pod-recovery smoke):

  =====================================  ======================================
  HYDRAGNN_INJECT_POD_KILL_HOST=H:G      host H SIGKILLs itself during the
                                         generation-G pod checkpoint save,
                                         AFTER its shard bytes land but BEFORE
                                         its manifest — generation G can never
                                         commit (the torn-generation case)
  HYDRAGNN_INJECT_POD_TORN_SHARD=H:G     host H's generation-G shard is
                                         written truncated while its sha256
                                         sidecar carries the good digest —
                                         restore must reject the shard by
                                         checksum and fall back a generation
  HYDRAGNN_INJECT_POD_LOST_HEARTBEAT=    host H stops writing heartbeat files
  H:E                                    from epoch E on (alive but silent —
                                         what a wedged host looks like from
                                         outside; drives host_lost detection)
  HYDRAGNN_INJECT_POD_BARRIER_STALL=H:S  host H sleeps S seconds before
                                         entering any pod_barrier (once per
                                         process) — peers must time out,
                                         proceed, and record the stall
  =====================================  ======================================

Step numbers are process-local dispatch counts (0-based, counted by
``TrainHooks``), so injections are deterministic regardless of resume
state.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Optional, Tuple

from hydragnn_tpu.utils import knobs


def _spec(name: str) -> Optional[str]:
    v = knobs.raw(name)
    return v if v else None


def _two_ints(spec: str, default_second: int) -> Tuple[int, int]:
    parts = spec.split(":")
    a = int(parts[0])
    b = int(parts[1]) if len(parts) > 1 and parts[1] else default_second
    return a, b


def maybe_nan_batch(batch, step: int):
    """Return ``batch`` with NaN node features when step is inside the
    injected window, else the batch unchanged."""
    spec = _spec("HYDRAGNN_INJECT_NAN_STEP")
    if spec is None:
        return batch
    start, count = _two_ints(spec, 1)
    if not start <= step < start + count:
        return batch
    import numpy as np

    nodes = np.full_like(np.asarray(batch.nodes), np.nan)
    return batch.replace(nodes=nodes)


def maybe_sigterm(step: Optional[int] = None, epoch: Optional[int] = None) -> None:
    """Self-SIGTERM at the injected step or epoch boundary."""
    if step is not None:
        spec = _spec("HYDRAGNN_INJECT_SIGTERM_STEP")
        if spec is not None and step == int(spec):
            os.kill(os.getpid(), signal.SIGTERM)
    if epoch is not None:
        spec = _spec("HYDRAGNN_INJECT_SIGTERM_EPOCH")
        if spec is not None and epoch == int(spec):
            os.kill(os.getpid(), signal.SIGTERM)


# graftsync: thread-safe=fault-injection counter bumped only by the single checkpoint-writing thread
_CHECKPOINT_SAVES = 0


def maybe_kill_checkpoint(path: str, data: bytes) -> None:
    """During the K-th checkpoint save: leave ``path`` TRUNCATED (half
    the payload, written directly — deliberately bypassing the normal
    tmp-file + atomic-replace discipline, like a filesystem that tears
    writes on power loss) and SIGKILL the process. The restart must
    then reject the truncated file and restore the previous good one —
    the integrity-validation path this exists to prove."""
    spec = _spec("HYDRAGNN_INJECT_KILL_CHECKPOINT")
    if spec is None:
        return
    global _CHECKPOINT_SAVES
    _CHECKPOINT_SAVES += 1
    if _CHECKPOINT_SAVES != int(spec):
        return
    with open(path, "wb") as f:
        f.write(data[: max(len(data) // 2, 1)])
        f.flush()
        os.fsync(f.fileno())
    os.kill(os.getpid(), signal.SIGKILL)


def maybe_stall_loader(batch_index: int) -> None:
    """Sleep in the loader's producer before building the injected
    batch index (per epoch)."""
    spec = _spec("HYDRAGNN_INJECT_STALL_LOADER")
    if spec is None:
        return
    b, seconds = _two_ints(spec, 3600)
    if batch_index == b:
        time.sleep(seconds)


def maybe_serve_raise(seqs) -> None:
    """Raise inside the serving forward when the batch holds the
    injected request number — the poison the retry-as-singles hunt must
    localize (the fault follows request N into its retry single)."""
    spec = _spec("HYDRAGNN_INJECT_SERVE_RAISE")
    if spec is not None and int(spec) in seqs:
        raise RuntimeError(
            f"injected serve fault: raise-in-forward at request {int(spec)}"
        )


def maybe_serve_nan(outputs, seqs):
    """Replace the forward's outputs with NaN when the batch holds the
    injected request number (silent corruption: no exception, just
    non-finite results the finite-output check must catch)."""
    spec = _spec("HYDRAGNN_INJECT_SERVE_NAN")
    if spec is None or int(spec) not in seqs:
        return outputs
    import numpy as np

    return [np.full_like(np.asarray(o), np.nan) for o in outputs]


# graftsync: thread-safe=GIL-atomic one-way False->True latch; only the single dispatch thread writes it
_SERVE_WEDGED = False


def maybe_serve_wedge(seqs) -> None:
    """Sleep inside the serving forward (wedged dispatch) for the batch
    holding the injected request number. Fires once per process."""
    spec = _spec("HYDRAGNN_INJECT_SERVE_WEDGE")
    if spec is None:
        return
    n, seconds = _two_ints(spec, 5)
    global _SERVE_WEDGED
    if n in seqs and not _SERVE_WEDGED:
        _SERVE_WEDGED = True
        time.sleep(seconds)


def maybe_serve_kill_dispatch(batch_count: int) -> None:
    """Raise OUTSIDE the per-request isolation at the K-th (1-indexed)
    dispatched batch — the dispatch thread dies and the in-process
    supervisor must restart it."""
    spec = _spec("HYDRAGNN_INJECT_SERVE_KILL_DISPATCH")
    if spec is not None and batch_count == int(spec):
        raise RuntimeError(
            f"injected serve fault: dispatch thread killed at batch {batch_count}"
        )


# graftsync: thread-safe=GIL-atomic one-way False->True latch; only the single trigger-evaluating thread writes it
_TRIGGER_FIRED = False


def injected_trigger(known_rules=None) -> Optional[str]:
    """The SLO rule name ``HYDRAGNN_INJECT_TRIGGER`` names, returned
    ONCE per process (the engine force-fires that rule at its next
    evaluate). ``known_rules`` filters: an injected name no engine rule
    carries is left un-consumed so the engine that DOES know it (train
    vs serve run in one process) gets the shot."""
    spec = _spec("HYDRAGNN_INJECT_TRIGGER")
    if spec is None:
        return None
    global _TRIGGER_FIRED
    if _TRIGGER_FIRED:
        return None
    if known_rules is not None and spec not in known_rules:
        return None
    _TRIGGER_FIRED = True
    return spec


def maybe_drift_shift(x):
    """Return the request's node features with the injected covariate
    shift applied (``x + SHIFT``), or unchanged when no drift is
    injected. Deterministic: every admitted request shifts identically,
    so the drift sketches see a clean mean/histogram displacement."""
    spec = _spec("HYDRAGNN_INJECT_DRIFT")
    if spec is None:
        return x
    import numpy as np

    return np.asarray(x) + float(spec)


def serve_torn_reload() -> bool:
    """Whether ModelServer.reload should corrupt the candidate weights
    before the canary (torn-reload injection)."""
    return _spec("HYDRAGNN_INJECT_SERVE_TORN_RELOAD") is not None


def pilot_train_crashes() -> int:
    """How many of the pilot's fine-tune attempts must crash before one
    is allowed to run (0 = none injected). Consumed per ATTEMPT by the
    pilot's tune launcher, which counts attempts itself — the child
    process may never even start, so a module latch cannot work here."""
    spec = _spec("HYDRAGNN_INJECT_PILOT_TRAIN_CRASH")
    return int(spec) if spec is not None else 0


def maybe_pilot_hang() -> None:
    """Wedge the fine-tune job for the injected number of seconds
    before it does any work — the supervisor-level wall clock (not the
    in-process watchdog, which never sees a pre-work hang) must kill
    and classify it."""
    spec = _spec("HYDRAGNN_INJECT_PILOT_HUNG_TUNE")
    if spec is not None:
        time.sleep(float(spec))


def pilot_canary_regress() -> bool:
    """Whether the pilot's canary scorer should inflate the CANDIDATE's
    scores so the gate rejects it."""
    return _spec("HYDRAGNN_INJECT_PILOT_CANARY_REGRESS") is not None


def pilot_torn_reload() -> bool:
    """Whether the pilot should corrupt the candidate weights between
    its canary gate and the hot reload (the server's own reload canary
    is then the last line of defense, and must hold)."""
    return _spec("HYDRAGNN_INJECT_PILOT_TORN_RELOAD") is not None


def maybe_pod_kill_host(host: int, gen) -> None:
    """SIGKILL this process when it is the injected host saving the
    injected pod-checkpoint generation. Called between the shard write
    and the manifest write, so the death always leaves a torn
    (uncommittable) generation behind."""
    spec = _spec("HYDRAGNN_INJECT_POD_KILL_HOST")
    if spec is None or gen is None:
        return
    h, g = _two_ints(spec, 1)
    if int(host) == h and int(gen) == g:
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_pod_torn_shard(host: int, gen) -> bool:
    """Whether the injected host must write its injected generation's
    shard TRUNCATED while the sha256 sidecar keeps the good digest —
    the checksum-mismatch case restore's generation fallback exists
    for."""
    spec = _spec("HYDRAGNN_INJECT_POD_TORN_SHARD")
    if spec is None or gen is None:
        return False
    h, g = _two_ints(spec, 1)
    return int(host) == h and int(gen) == g


def maybe_pod_lost_heartbeat(host: int, epoch) -> bool:
    """Whether the injected host must SUPPRESS its heartbeat writes
    (from the injected epoch on). The host keeps training — only its
    liveness signal dies, so peers must declare it lost on evidence,
    not on exit codes."""
    spec = _spec("HYDRAGNN_INJECT_POD_LOST_HEARTBEAT")
    if spec is None or epoch is None:
        return False
    h, e = _two_ints(spec, 0)
    return int(host) == h and int(epoch) >= e


# graftsync: thread-safe=GIL-atomic one-way False->True latch; only the single barrier-entering main thread writes it
_BARRIER_STALLED = False


def maybe_pod_barrier_stall(host: int) -> None:
    """Sleep the injected host before it enters a pod_barrier (once
    per process) — its peers must hit the barrier timeout, proceed,
    and record the missing host rather than hang."""
    spec = _spec("HYDRAGNN_INJECT_POD_BARRIER_STALL")
    if spec is None:
        return
    h, seconds = _two_ints(spec, 5)
    global _BARRIER_STALLED
    if int(host) == h and not _BARRIER_STALLED:
        _BARRIER_STALLED = True
        time.sleep(seconds)


def strip_injection_env(env: dict) -> dict:
    """Copy of ``env`` without any injection knobs — what the restart
    supervisor hands to restarted children so injected faults fire
    exactly once. The removal set is DERIVED from the central knob
    registry's view of the environment (``knobs.active_injections``)
    rather than a hand-maintained list here, so every injection family
    — including ones added after this function — is stripped; the
    prefix filter backstops names a future build sets but this one's
    registry predates."""
    drop = set(knobs.active_injections(env=env))
    return {
        k: v
        for k, v in env.items()
        if k not in drop and not k.startswith(knobs.INJECT_PREFIX)
    }
