"""The per-batch hook bundle the train loop threads through its hot
loop: preemption check, watchdog heartbeat, fault injection, and the
non-finite sentry — one object so ``train_epoch``'s signature stays
flat and the all-disabled path is a couple of attribute checks.
"""

from __future__ import annotations

from typing import Optional

from hydragnn_tpu.resilience import inject


class TrainHooks:
    """Bundles the resilience actors for one training run.

    ``before_step`` runs at batch granularity: beats the watchdog,
    fires step-indexed fault injections, and returns the (possibly
    NaN-injected) batch. ``step_counter`` is the process-local dispatch
    count the injection specs index — deterministic regardless of
    resume state.
    """

    def __init__(
        self,
        preempt=None,
        sentry=None,
        watchdog=None,
    ):
        self.preempt = preempt
        self.sentry = sentry
        self.watchdog = watchdog
        self.step_counter = 0

    @property
    def preempted(self) -> bool:
        return self.preempt is not None and self.preempt.should_stop()

    def beat(self) -> None:
        if self.watchdog is not None:
            self.watchdog.beat()

    def epoch_start(self, epoch: int) -> None:
        self.beat()
        inject.maybe_sigterm(epoch=epoch)
        if self.sentry is not None:
            self.sentry.epoch_start()

    def before_step(self, batch):
        self.beat()
        inject.maybe_sigterm(step=self.step_counter)
        batch = inject.maybe_nan_batch(batch, self.step_counter)
        self.step_counter += 1
        return batch

    def teardown(self) -> None:
        """Idempotent cleanup — every train-loop exit path calls this."""
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.preempt is not None:
            self.preempt.uninstall()
