"""Hang watchdog: detect a wedged training loop and die loudly.

A stuck collective, a hung device dispatch, or a deadlocked data
producer all present the same way: the step loop stops beating while
the process looks perfectly alive to the scheduler. The watchdog is a
daemon thread fed a heartbeat from the hot loop (``TrainHooks.beat``,
once per batch + at epoch boundaries); when no beat arrives for
``stall_s`` seconds it dumps EVERY Python thread's stack into the
flight record (``watchdog`` event + ``run_end{status:"hung"}``) and
aborts the process with :data:`~hydragnn_tpu.resilience.preempt.EXIT_HUNG`
— a structured corpse the restart supervisor classifies and retries,
instead of a silent job that burns its reservation until a human
notices.

Caveat: the first train step legitimately blocks for the compile;
size ``stall_s`` (config ``Training.watchdog_stall_s`` or env
``HYDRAGNN_WATCHDOG_S``) above the worst expected compile time. The
watchdog is OFF unless one of those is set.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from hydragnn_tpu.resilience.preempt import EXIT_HUNG


def dump_thread_stacks() -> Dict[str, str]:
    """Formatted stack of every live Python thread, keyed by thread
    name (the evidence payload for the ``watchdog`` flight event)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, str] = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, f"thread-{ident}")
        out[name] = "".join(traceback.format_stack(frame))
    return out


class HangWatchdog:
    """Heartbeat-fed stall detector.

    ``action`` runs once on the watchdog thread when a stall is
    detected, AFTER the flight events are written; the default
    hard-exits with :data:`EXIT_HUNG` (tests inject a recording action
    instead). ``beat()`` is a single monotonic-clock store — cheap
    enough for the per-batch hot path.

    The serving path (``serve/supervise.py``) embeds the same detector
    against a wedged forward, with three departures from the training
    defaults: ``gate`` (a stall only counts while the gate callable
    returns True — an idle server blocked waiting for traffic is not
    hung), ``rearm=True`` (after firing, the detector keeps polling; a
    recovered heartbeat clears ``fired`` and re-arms it for the next
    stall — serving survives a wedge, training dies from one), and
    ``end_run_on_fire=False`` (record the ``watchdog`` flight event but
    leave the run open: the serving flight record outlives a stall).
    """

    def __init__(
        self,
        stall_s: float,
        flight=None,
        action: Optional[Callable[[], None]] = None,
        poll_s: Optional[float] = None,
        warmup_beats: int = 2,
        gate: Optional[Callable[[], bool]] = None,
        rearm: bool = False,
        end_run_on_fire: bool = True,
    ):
        if stall_s <= 0:
            raise ValueError(f"stall_s must be > 0, got {stall_s}")
        self.stall_s = float(stall_s)
        self.flight = flight
        self.action = action if action is not None else self._default_abort
        self.poll_s = float(poll_s) if poll_s else max(self.stall_s / 4.0, 0.05)
        self.gate = gate
        self.rearm = bool(rearm)
        self.end_run_on_fire = bool(end_run_on_fire)
        # graftsync: thread-safe=only the single watchdog thread increments; readers tolerate staleness
        self.fire_count = 0
        # the watchdog ARMS only after this many beats: setup (imports,
        # model init) and the first train step's compile legitimately
        # block for longer than any reasonable stall threshold — the
        # same skip-the-compile-step discipline as StepSpans.skip_first
        self.warmup_beats = int(warmup_beats)
        # graftsync: thread-safe=GIL-atomic bool; written by the watchdog thread, readers only observe a stale False for one poll interval
        self.fired = False
        # graftsync: thread-safe=GIL-atomic int store from the hot loop; the watchdog thread only compares against warmup_beats
        self._beats = 0
        # graftsync: thread-safe=GIL-atomic float store (the per-batch heartbeat); a torn read is impossible, a stale one just delays firing by one poll
        self._last_beat = time.monotonic()
        self._stop = threading.Event()
        # graftsync: thread-safe=start()/stop() run on the owning thread only
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        self._beats += 1
        self._last_beat = time.monotonic()

    def heartbeat_age(self) -> float:
        """Seconds since the last beat — the serving liveness signal."""
        return time.monotonic() - self._last_beat

    @property
    def armed(self) -> bool:
        return self._beats > self.warmup_beats

    def start(self) -> "HangWatchdog":
        if self._thread is None:
            self.beat()
            self._thread = threading.Thread(
                target=self._run, name="hydragnn-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- internals ---------------------------------------------------------

    # graftsync: thread-root
    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            if not self.armed:
                continue
            stalled = time.monotonic() - self._last_beat
            if self.fired:
                # rearm mode only reaches here: a fresh beat clears the
                # stall and re-arms the detector for the next one
                if stalled < self.stall_s:
                    self.fired = False
                continue
            if stalled >= self.stall_s and (self.gate is None or self.gate()):
                self._fire(stalled)
                if not self.rearm:
                    return

    def _fire(self, stalled: float) -> None:
        self.fired = True
        self.fire_count += 1
        stacks = dump_thread_stacks()
        if self.flight is not None:
            self.flight.record(
                "watchdog", stall_s=round(stalled, 3), stacks=stacks
            )
            if self.end_run_on_fire:
                self.flight.end_run(status="hung", stall_s=round(stalled, 3))
                self.flight.close()
        self.action()

    def _default_abort(self) -> None:
        try:
            os.write(
                2,
                (
                    f"HangWatchdog: no heartbeat for {self.stall_s}s — "
                    "aborting (thread stacks are in the flight record)\n"
                ).encode(),
            )
        except OSError:
            pass
        os._exit(EXIT_HUNG)
