"""Non-finite sentry: host-side policy over the on-device step guard.

The device half lives in the jitted step
(``train/state.py:make_train_step(guard_nonfinite=True)``): a cheap
``isfinite(loss) & isfinite(global_norm(grads))`` check that SKIPS the
offending batch — parameters, optimizer state, BatchNorm statistics
and the step counter all keep their previous values — and threads a
consecutive-bad counter through as a device scalar, so the steady
state pays no extra host sync (the skip accounting materializes once
per epoch with the loss metrics, the same discipline as
``_MetricAccum``).

This class is the host half: it accumulates the per-step bad flags,
finalizes them at epoch end, and decides when skipping is no longer
enough. A run whose epoch ENDS on ``patience`` consecutive bad steps
is not going to self-heal — the sentry then rolls back to the last
good checkpoint with a reduced learning rate (``rollback`` flight
event) instead of continuing from weights that produce non-finite
grads; after ``max_rollbacks`` of those it raises
:class:`~hydragnn_tpu.resilience.preempt.NonFiniteRollbackExhausted`
(a deterministic data/model problem the restart supervisor fail-fasts
on). Isolated bad batches mid-epoch are skipped and counted
(``train.nonfinite_skipped`` in the obs registry) without rollback —
the weights were never touched by them.
"""

from __future__ import annotations

from typing import List, Tuple


class NonFiniteSentry:
    """Per-run skip accounting + rollback policy (one per training run).

    Config (``Training`` section): ``nonfinite_patience`` (consecutive
    bad steps at an epoch's tail that trigger rollback),
    ``nonfinite_max_rollbacks``, ``nonfinite_rollback_lr_factor``.
    """

    def __init__(
        self,
        patience: int = 16,
        max_rollbacks: int = 2,
        lr_factor: float = 0.5,
    ):
        import jax.numpy as jnp

        self.patience = int(patience)
        self.max_rollbacks = int(max_rollbacks)
        self.lr_factor = float(lr_factor)
        self.rollbacks = 0
        self.skipped_total = 0
        # device scalar threaded through the guarded step: number of
        # consecutive bad steps ending at the current step
        self.consec = jnp.zeros((), jnp.int32)
        self._bads: List = []

    def epoch_start(self) -> None:
        self._bads = []

    def observe(self, consec, bad) -> None:
        """Record one guarded step's outputs (device scalars; no sync)."""
        self.consec = consec
        self._bads.append(bad)

    def observe_scan(self, bads, consec) -> None:
        """Record a whole guarded scan-epoch's outputs: the per-step bad
        flags [B] and the carry's final consecutive-bad counter (device
        arrays; no sync — same discipline as :meth:`observe`)."""
        self.consec = consec
        self._bads.append(bads.sum())

    def epoch_finalize(self) -> Tuple[int, int]:
        """One host sync per epoch: returns (skipped_this_epoch,
        consecutive_bad_at_epoch_end)."""
        import jax
        import jax.numpy as jnp

        if self._bads:
            skipped = int(jax.device_get(jnp.stack(self._bads).sum()))
        else:
            skipped = 0
        consec_end = int(jax.device_get(self.consec))
        self.skipped_total += skipped
        self._bads = []
        return skipped, consec_end

    def needs_rollback(self, consec_end: int) -> bool:
        return consec_end >= self.patience

    def on_rollback(self) -> None:
        import jax.numpy as jnp

        self.rollbacks += 1
        self.consec = jnp.zeros((), jnp.int32)

    @property
    def exhausted(self) -> bool:
        return self.rollbacks >= self.max_rollbacks
