"""Fault-tolerant training: the machinery that keeps the north-star
workload alive on preemptible hardware without a human in the loop
(docs/RESILIENCE.md).

The repo already had exact resume and crash-consistent meta repair
(train/loop.py, utils/checkpoint.py); this package DRIVES that
machinery when a run is dying:

  - :mod:`~hydragnn_tpu.resilience.preempt` — SIGTERM/SIGINT ->
    graceful-stop flag checked at batch granularity; final checkpoint
    + ``run_end{status:"preempted"}`` within a grace window; the
    process exit-code contract (``EXIT_*``) and :func:`run_guard`.
  - :mod:`~hydragnn_tpu.resilience.sentry` — host-side policy over the
    on-device non-finite guard folded into the jitted train step
    (``make_train_step(guard_nonfinite=True)``): skipped-batch
    accounting and the roll-back-to-last-good-checkpoint decision.
  - :mod:`~hydragnn_tpu.resilience.watchdog` — heartbeat thread that
    dumps every Python thread's stack into the flight record and
    aborts when the loop stalls (stuck dispatch / collective /
    data-wait).
  - :mod:`~hydragnn_tpu.resilience.supervisor` — bounded restart
    supervisor (``tools/supervise.py``): exponential backoff,
    exit-cause classification, fail-fast on config errors; the
    pod-level variant (``PodSupervisor``, ``tools/supervise.py
    --pod N``) supervises N simulated hosts as one unit with
    ``host_lost`` classed for prompt restart and optional elastic
    N-1 recovery.
  - :mod:`~hydragnn_tpu.resilience.podckpt` — sharded pod checkpoints
    with a generation commit protocol (per-host shard + sha sidecar +
    manifest, rank-0 ``gen<N>.COMMIT`` written LAST), filesystem
    heartbeats/preemption coordination (``PodSignaler``), and elastic
    restore that re-shards a committed generation across a different
    host count.
  - :mod:`~hydragnn_tpu.resilience.inject` — env-gated deterministic
    fault injection (NaN batch, SIGTERM, SIGKILL mid-checkpoint,
    stalled producer) so every path above is testable, not decorative.
  - :mod:`~hydragnn_tpu.resilience.hooks` — the small per-batch hook
    bundle ``train/loop.py`` threads through the hot loop.

Everything flows into the existing flight recorder
(:mod:`hydragnn_tpu.obs.flight`); ``tools/obs_report.py --faults``
narrates a run's fault history.
"""

from hydragnn_tpu.resilience.preempt import (
    EXIT_CONFIG_ERROR,
    EXIT_HUNG,
    EXIT_OK,
    EXIT_PREEMPTED,
    EXIT_ROLLBACK_EXHAUSTED,
    NonFiniteRollbackExhausted,
    PodHostLost,
    PreemptionHandler,
    TrainingPreempted,
    auto_resume_config,
    run_guard,
)
from hydragnn_tpu.resilience.sentry import NonFiniteSentry
from hydragnn_tpu.resilience.watchdog import HangWatchdog, dump_thread_stacks
from hydragnn_tpu.resilience.supervisor import (
    FAIL_FAST_CAUSES,
    PodSupervisor,
    Supervisor,
    SupervisorPolicy,
    classify_exit,
    classify_pod_exit,
)
from hydragnn_tpu.resilience.hooks import TrainHooks

__all__ = [
    "EXIT_OK",
    "EXIT_PREEMPTED",
    "EXIT_ROLLBACK_EXHAUSTED",
    "EXIT_CONFIG_ERROR",
    "EXIT_HUNG",
    "TrainingPreempted",
    "NonFiniteRollbackExhausted",
    "PreemptionHandler",
    "auto_resume_config",
    "run_guard",
    "NonFiniteSentry",
    "HangWatchdog",
    "dump_thread_stacks",
    "Supervisor",
    "SupervisorPolicy",
    "PodSupervisor",
    "PodHostLost",
    "FAIL_FAST_CAUSES",
    "classify_exit",
    "classify_pod_exit",
    "TrainHooks",
]
