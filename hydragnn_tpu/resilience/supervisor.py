"""Bounded restart supervisor: turn "the run crashed" into "the run
resumed" — without looping forever on a run that can never succeed.

The supervisor re-invokes a training command, classifies each exit by
the contract in :mod:`hydragnn_tpu.resilience.preempt`, and decides:

  - ``completed`` (0) — done.
  - ``preempted`` (75) — restart promptly (bounded by
    ``max_preemptions``; eviction is the expected steady state on
    preemptible slices, not a failure).
  - ``config_error`` (78) / ``rollback_exhausted`` (76) — FAIL FAST:
    deterministic, a retry burns the backoff budget to fail
    identically.
  - anything else (``crash``, including signal deaths and ``hung``/79
    from the watchdog) — retry with exponential backoff up to
    ``max_restarts``.

Every restarted child gets ``HYDRAGNN_AUTO_RESUME=1`` (the api layer
flips the config to ``Training.continue`` when the checkpoint exists)
and — by default — the ``HYDRAGNN_INJECT_*`` fault-injection vars
stripped, so an injected fault fires exactly once per supervised run.

``tools/supervise.py`` is the CLI; the ``runner``/``sleep`` seams exist
so the policy is unit-testable without real processes
(tests/test_resilience.py).

:class:`SupervisorPolicy` is also the restart policy of the serving
path's IN-PROCESS supervisor (``serve/supervise.py``): same backoff
arithmetic and give-up bound, scoped to the dispatch thread instead of
a child process, with serving-scale defaults (requests are waiting, so
backoff starts at milliseconds).
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence

from hydragnn_tpu.resilience.inject import strip_injection_env
from hydragnn_tpu.resilience.preempt import (
    EXIT_CONFIG_ERROR,
    EXIT_HUNG,
    EXIT_OK,
    EXIT_PREEMPTED,
    EXIT_ROLLBACK_EXHAUSTED,
)

FAIL_FAST_CAUSES = frozenset({"config_error", "rollback_exhausted"})


def wall_clock_runner(
    max_wall_s: float, *, grace_s: float = 5.0, popen=subprocess.Popen
) -> Callable[[Sequence[str], Dict[str, str]], int]:
    """A ``runner`` that enforces a supervisor-level hard wall clock.

    The in-process watchdog (``resilience/watchdog.py``) only fires when
    the child's Python interpreter is still scheduling threads; a child
    wedged inside a C extension, a stuck collective, or a full device
    queue never reaches it.  This runner is the outer belt: ``Popen`` +
    ``wait(max_wall_s)``, then SIGTERM, ``grace_s`` to die, SIGKILL —
    and the timeout is REPORTED as :data:`EXIT_HUNG` (79) so
    :func:`classify_exit` sees ``hung`` and the policy retries with
    backoff instead of treating the kill signal as a fresh crash class.
    ``popen`` is a seam for tests."""
    if max_wall_s <= 0:
        raise ValueError(f"max_wall_s must be > 0, got {max_wall_s}")

    def _run(argv: Sequence[str], env: Dict[str, str]) -> int:
        proc = popen(list(argv), env=env)
        try:
            return int(proc.wait(timeout=max_wall_s))
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            return EXIT_HUNG

    return _run


def classify_exit(returncode: int) -> str:
    """Exit cause from a child's return code (negative = signal death,
    which subprocess reports for SIGKILL etc.)."""
    if returncode == EXIT_OK:
        return "completed"
    if returncode == EXIT_PREEMPTED:
        return "preempted"
    if returncode == EXIT_ROLLBACK_EXHAUSTED:
        return "rollback_exhausted"
    if returncode == EXIT_CONFIG_ERROR:
        return "config_error"
    if returncode == EXIT_HUNG:
        return "hung"
    return "crash"


@dataclasses.dataclass
class SupervisorPolicy:
    max_restarts: int = 5  # crash/hung-class restarts
    max_preemptions: int = 1000  # preemption resumes (not failures)
    backoff_base_s: float = 1.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    auto_resume: bool = True  # set HYDRAGNN_AUTO_RESUME=1 for restarts
    strip_injection: bool = True  # drop HYDRAGNN_INJECT_* from restarts

    def backoff(self, n_crashes: int) -> float:
        """Delay before the n-th crash-class restart (n >= 1)."""
        return min(
            self.backoff_base_s * self.backoff_factor ** max(n_crashes - 1, 0),
            self.backoff_max_s,
        )


class Supervisor:
    """Run ``argv`` under the restart policy.

    ``runner(argv, env) -> returncode`` defaults to ``subprocess.call``;
    ``flight`` (a :class:`~hydragnn_tpu.obs.flight.FlightRecorder`)
    receives one ``restart`` event per re-invocation and a terminal
    ``run_end``.
    """

    def __init__(
        self,
        argv: Sequence[str],
        policy: Optional[SupervisorPolicy] = None,
        env: Optional[Dict[str, str]] = None,
        flight=None,
        runner: Optional[Callable[[Sequence[str], Dict[str, str]], int]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.argv = list(argv)
        self.policy = policy or SupervisorPolicy()
        self.base_env = dict(env if env is not None else os.environ)
        self.flight = flight
        self.runner = runner or (lambda a, e: subprocess.call(a, env=e))
        self.sleep = sleep
        self.history: List[dict] = []

    def _child_env(self, attempt: int) -> Dict[str, str]:
        env = dict(self.base_env)
        if attempt > 0:
            if self.policy.auto_resume:
                env["HYDRAGNN_AUTO_RESUME"] = "1"
            if self.policy.strip_injection:
                env = strip_injection_env(env)
        return env

    def run(self) -> dict:
        """Supervise to completion or give-up; returns a result dict
        with ``status`` (``completed`` / ``failed_fast`` /
        ``gave_up``), the final ``exit_code``/``cause``, and counts."""
        crashes = 0
        preemptions = 0
        attempt = 0
        while True:
            rc = self.runner(self.argv, self._child_env(attempt))
            cause = classify_exit(rc)
            self.history.append({"attempt": attempt, "exit_code": rc, "cause": cause})
            if cause == "completed":
                return self._finish("completed", rc, cause, crashes, preemptions)
            if cause in FAIL_FAST_CAUSES:
                return self._finish("failed_fast", rc, cause, crashes, preemptions)
            if cause == "preempted":
                preemptions += 1
                if preemptions > self.policy.max_preemptions:
                    return self._finish("gave_up", rc, cause, crashes, preemptions)
                delay = 0.0
            else:  # crash / hung
                crashes += 1
                if crashes > self.policy.max_restarts:
                    return self._finish("gave_up", rc, cause, crashes, preemptions)
                delay = self.policy.backoff(crashes)
            attempt += 1
            if self.flight is not None:
                self.flight.record(
                    "restart",
                    attempt=attempt,
                    cause=cause,
                    exit_code=rc,
                    delay_s=delay,
                )
            if delay > 0:
                self.sleep(delay)

    def _finish(self, status, rc, cause, crashes, preemptions) -> dict:
        result = {
            "status": status,
            "exit_code": rc,
            "cause": cause,
            "attempts": len(self.history),
            "restarts": crashes,
            "preemptions": preemptions,
            "history": list(self.history),
        }
        if self.flight is not None:
            self.flight.end_run(
                status=status,
                exit_code=rc,
                cause=cause,
                attempts=result["attempts"],
                restarts=crashes,
                preemptions=preemptions,
            )
        return result
