"""Bounded restart supervisor: turn "the run crashed" into "the run
resumed" — without looping forever on a run that can never succeed.

The supervisor re-invokes a training command, classifies each exit by
the contract in :mod:`hydragnn_tpu.resilience.preempt`, and decides:

  - ``completed`` (0) — done.
  - ``preempted`` (75) — restart promptly (bounded by
    ``max_preemptions``; eviction is the expected steady state on
    preemptible slices, not a failure).
  - ``config_error`` (78) / ``rollback_exhausted`` (76) — FAIL FAST:
    deterministic, a retry burns the backoff budget to fail
    identically.
  - anything else (``crash``, including signal deaths and ``hung``/79
    from the watchdog) — retry with exponential backoff up to
    ``max_restarts``.

Every restarted child gets ``HYDRAGNN_AUTO_RESUME=1`` (the api layer
flips the config to ``Training.continue`` when the checkpoint exists)
and — by default — the ``HYDRAGNN_INJECT_*`` fault-injection vars
stripped, so an injected fault fires exactly once per supervised run.

``tools/supervise.py`` is the CLI; the ``runner``/``sleep`` seams exist
so the policy is unit-testable without real processes
(tests/test_resilience.py).

:class:`SupervisorPolicy` is also the restart policy of the serving
path's IN-PROCESS supervisor (``serve/supervise.py``): same backoff
arithmetic and give-up bound, scoped to the dispatch thread instead of
a child process, with serving-scale defaults (requests are waiting, so
backoff starts at milliseconds).
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence

from hydragnn_tpu.resilience.inject import strip_injection_env
from hydragnn_tpu.resilience.preempt import (
    EXIT_CONFIG_ERROR,
    EXIT_HUNG,
    EXIT_OK,
    EXIT_PREEMPTED,
    EXIT_ROLLBACK_EXHAUSTED,
)

FAIL_FAST_CAUSES = frozenset({"config_error", "rollback_exhausted"})

# pod-level causes that restart PROMPTLY (no crash backoff): eviction
# and host loss are the expected steady state of preemptible pods, and
# the run resumes from the last committed generation either way
PREEMPT_CLASS_CAUSES = frozenset({"preempted", "host_lost"})


def wall_clock_runner(
    max_wall_s: float, *, grace_s: float = 5.0, popen=subprocess.Popen
) -> Callable[[Sequence[str], Dict[str, str]], int]:
    """A ``runner`` that enforces a supervisor-level hard wall clock.

    The in-process watchdog (``resilience/watchdog.py``) only fires when
    the child's Python interpreter is still scheduling threads; a child
    wedged inside a C extension, a stuck collective, or a full device
    queue never reaches it.  This runner is the outer belt: ``Popen`` +
    ``wait(max_wall_s)``, then SIGTERM, ``grace_s`` to die, SIGKILL —
    and the timeout is REPORTED as :data:`EXIT_HUNG` (79) so
    :func:`classify_exit` sees ``hung`` and the policy retries with
    backoff instead of treating the kill signal as a fresh crash class.
    ``popen`` is a seam for tests."""
    if max_wall_s <= 0:
        raise ValueError(f"max_wall_s must be > 0, got {max_wall_s}")

    def _run(argv: Sequence[str], env: Dict[str, str]) -> int:
        proc = popen(list(argv), env=env)
        try:
            return int(proc.wait(timeout=max_wall_s))
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            return EXIT_HUNG

    return _run


def classify_exit(returncode: int) -> str:
    """Exit cause from a child's return code (negative = signal death,
    which subprocess reports for SIGKILL etc.)."""
    if returncode == EXIT_OK:
        return "completed"
    if returncode == EXIT_PREEMPTED:
        return "preempted"
    if returncode == EXIT_ROLLBACK_EXHAUSTED:
        return "rollback_exhausted"
    if returncode == EXIT_CONFIG_ERROR:
        return "config_error"
    if returncode == EXIT_HUNG:
        return "hung"
    return "crash"


def classify_pod_exit(returncodes: Dict[int, int]) -> str:
    """Collapse one pod attempt's per-host exit codes into a single
    cause, worst-first:

      - any fail-fast code (78 config / 76 rollback) wins — the failure
        is deterministic and restarting N hosts to fail identically is
        N times the waste;
      - else any SIGNAL death (negative returncode — SIGKILL from an
        evictor, the OOM killer, a dead machine) is ``host_lost``:
        preempt-class, restart the pod from the last committed
        generation promptly;
      - else preempted (75) beats hung (79) beats crash;
      - all zero = completed.
    """
    if not returncodes:
        raise ValueError("classify_pod_exit: empty returncode map")
    causes = {classify_exit(rc) for rc in returncodes.values()}
    if "config_error" in causes:
        return "config_error"
    if "rollback_exhausted" in causes:
        return "rollback_exhausted"
    if any(rc < 0 for rc in returncodes.values()):
        return "host_lost"
    if "preempted" in causes:
        return "preempted"
    if "hung" in causes:
        return "hung"
    if "crash" in causes:
        return "crash"
    return "completed"


def _pod_exit_code(returncodes: Dict[int, int], cause: str) -> int:
    """A representative exit code for a classified pod attempt."""
    table = {
        "completed": EXIT_OK,
        "config_error": EXIT_CONFIG_ERROR,
        "rollback_exhausted": EXIT_ROLLBACK_EXHAUSTED,
        "preempted": EXIT_PREEMPTED,
        "hung": EXIT_HUNG,
    }
    if cause in table:
        return table[cause]
    if cause == "host_lost":
        return next(rc for rc in returncodes.values() if rc < 0)
    return next(rc for rc in returncodes.values() if rc != EXIT_OK)


@dataclasses.dataclass
class SupervisorPolicy:
    max_restarts: int = 5  # crash/hung-class restarts
    max_preemptions: int = 1000  # preemption resumes (not failures)
    backoff_base_s: float = 1.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    auto_resume: bool = True  # set HYDRAGNN_AUTO_RESUME=1 for restarts
    strip_injection: bool = True  # drop HYDRAGNN_INJECT_* from restarts

    def backoff(self, n_crashes: int) -> float:
        """Delay before the n-th crash-class restart (n >= 1)."""
        return min(
            self.backoff_base_s * self.backoff_factor ** max(n_crashes - 1, 0),
            self.backoff_max_s,
        )


class Supervisor:
    """Run ``argv`` under the restart policy.

    ``runner(argv, env) -> returncode`` defaults to ``subprocess.call``;
    ``flight`` (a :class:`~hydragnn_tpu.obs.flight.FlightRecorder`)
    receives one ``restart`` event per re-invocation and a terminal
    ``run_end``.
    """

    def __init__(
        self,
        argv: Sequence[str],
        policy: Optional[SupervisorPolicy] = None,
        env: Optional[Dict[str, str]] = None,
        flight=None,
        runner: Optional[Callable[[Sequence[str], Dict[str, str]], int]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.argv = list(argv)
        self.policy = policy or SupervisorPolicy()
        self.base_env = dict(env if env is not None else os.environ)
        self.flight = flight
        self.runner = runner or (lambda a, e: subprocess.call(a, env=e))
        self.sleep = sleep
        self.history: List[dict] = []

    def _child_env(self, attempt: int) -> Dict[str, str]:
        env = dict(self.base_env)
        if attempt > 0:
            if self.policy.auto_resume:
                env["HYDRAGNN_AUTO_RESUME"] = "1"
            if self.policy.strip_injection:
                env = strip_injection_env(env)
        return env

    def run(self) -> dict:
        """Supervise to completion or give-up; returns a result dict
        with ``status`` (``completed`` / ``failed_fast`` /
        ``gave_up``), the final ``exit_code``/``cause``, and counts."""
        crashes = 0
        preemptions = 0
        attempt = 0
        while True:
            rc = self.runner(self.argv, self._child_env(attempt))
            cause = classify_exit(rc)
            self.history.append({"attempt": attempt, "exit_code": rc, "cause": cause})
            if cause == "completed":
                return self._finish("completed", rc, cause, crashes, preemptions)
            if cause in FAIL_FAST_CAUSES:
                return self._finish("failed_fast", rc, cause, crashes, preemptions)
            if cause == "preempted":
                preemptions += 1
                if preemptions > self.policy.max_preemptions:
                    return self._finish("gave_up", rc, cause, crashes, preemptions)
                delay = 0.0
            else:  # crash / hung
                crashes += 1
                if crashes > self.policy.max_restarts:
                    return self._finish("gave_up", rc, cause, crashes, preemptions)
                delay = self.policy.backoff(crashes)
            attempt += 1
            if self.flight is not None:
                self.flight.record(
                    "restart",
                    attempt=attempt,
                    cause=cause,
                    exit_code=rc,
                    delay_s=delay,
                )
            if delay > 0:
                self.sleep(delay)

    def _finish(self, status, rc, cause, crashes, preemptions) -> dict:
        result = {
            "status": status,
            "exit_code": rc,
            "cause": cause,
            "attempts": len(self.history),
            "restarts": crashes,
            "preemptions": preemptions,
            "history": list(self.history),
        }
        if self.flight is not None:
            self.flight.end_run(
                status=status,
                exit_code=rc,
                cause=cause,
                attempts=result["attempts"],
                restarts=crashes,
                preemptions=preemptions,
            )
        return result


class PodSupervisor:
    """Supervise ONE training command as a pod of ``hosts`` concurrent
    simulated-host processes (docs/RESILIENCE.md "Pod recovery").

    Each attempt launches every host with its podview identity
    (``HYDRAGNN_PODVIEW_HOST=k`` / ``HYDRAGNN_PODVIEW_HOSTS=N`` and a
    shared ``HYDRAGNN_PODVIEW_RUN_ID``), then polls. The pod lives and
    dies together: the first host to exit non-zero gets the rest
    SIGTERMed (they cut a final generation inside their grace window),
    then SIGKILLed after ``grace_s``. The attempt's per-host exit codes
    collapse to one cause via :func:`classify_pod_exit`; ``host_lost``
    (a signal-dead host) is preempt-class — restart promptly, resume
    from the last committed generation — not a crash that burns the
    backoff budget.

    ``elastic=True`` drops the pod to N-1 hosts after a ``host_lost``
    attempt instead of insisting on the original width: the restarted
    run re-shards the committed generation across the smaller pod
    (resilience/podckpt.py restore).

    ``popen`` / ``sleep`` are test seams (tests/test_podckpt.py drives
    the policy with fake processes).
    """

    def __init__(
        self,
        argv: Sequence[str],
        hosts: int,
        policy: Optional[SupervisorPolicy] = None,
        env: Optional[Dict[str, str]] = None,
        flight=None,
        run_id: Optional[str] = None,
        popen=subprocess.Popen,
        sleep: Callable[[float], None] = time.sleep,
        grace_s: float = 30.0,
        poll_s: float = 0.05,
        max_wall_s: Optional[float] = None,
        elastic: bool = False,
    ):
        if hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {hosts}")
        self.argv = list(argv)
        self.hosts = int(hosts)
        self.policy = policy or SupervisorPolicy()
        self.base_env = dict(env if env is not None else os.environ)
        self.flight = flight
        self.run_id = run_id
        self.popen = popen
        self.sleep = sleep
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self.max_wall_s = max_wall_s
        self.elastic = bool(elastic)
        self.history: List[dict] = []

    def _host_env(self, host: int, hosts: int, attempt: int) -> Dict[str, str]:
        env = dict(self.base_env)
        if attempt > 0:
            if self.policy.auto_resume:
                env["HYDRAGNN_AUTO_RESUME"] = "1"
            if self.policy.strip_injection:
                env = strip_injection_env(env)
        env["HYDRAGNN_PODVIEW_HOST"] = str(host)
        env["HYDRAGNN_PODVIEW_HOSTS"] = str(hosts)
        if self.run_id:
            env["HYDRAGNN_PODVIEW_RUN_ID"] = self.run_id
        return env

    def _stop_peers(self, procs: dict, rcs: Dict[int, int]) -> None:
        """SIGTERM every still-running host (graceful generation cut),
        give them ``grace_s`` collectively, then SIGKILL stragglers."""
        live = [k for k in procs if k not in rcs]
        for k in live:
            try:
                procs[k].terminate()
            except OSError:
                pass
        deadline = time.monotonic() + self.grace_s
        for k in live:
            if k in rcs:
                continue
            timeout = max(deadline - time.monotonic(), 0.0)
            try:
                rcs[k] = int(procs[k].wait(timeout=timeout))
            except subprocess.TimeoutExpired:
                try:
                    procs[k].kill()
                except OSError:
                    pass
                rcs[k] = int(procs[k].wait())

    def _run_attempt(self, hosts: int, attempt: int) -> Dict[int, int]:
        procs = {
            k: self.popen(self.argv, env=self._host_env(k, hosts, attempt))
            for k in range(hosts)
        }
        rcs: Dict[int, int] = {}
        deadline = (
            time.monotonic() + self.max_wall_s
            if self.max_wall_s is not None
            else None
        )
        while len(rcs) < hosts:
            progressed = False
            failed = False
            for k, p in procs.items():
                if k in rcs:
                    continue
                rc = p.poll()
                if rc is not None:
                    rcs[k] = int(rc)
                    progressed = True
                    if rc != EXIT_OK:
                        failed = True
            if failed:
                self._stop_peers(procs, rcs)
                break
            if deadline is not None and time.monotonic() > deadline:
                # outer-belt wall clock: report the unfinished hosts as
                # hung/79 (same contract as wall_clock_runner), not as
                # the signal death the kill itself produced
                unfinished = [k for k in procs if k not in rcs]
                self._stop_peers(procs, rcs)
                for k in unfinished:
                    rcs[k] = EXIT_HUNG
                break
            if not progressed:
                self.sleep(self.poll_s)
        return rcs

    def run(self) -> dict:
        """Supervise the pod to completion or give-up. Same result
        contract as :meth:`Supervisor.run`, plus per-attempt
        ``exit_codes`` / ``hosts`` in the history and ``host_lost``
        counted with preemptions (both are prompt-restart events)."""
        crashes = 0
        preemptions = 0
        attempt = 0
        hosts = self.hosts
        while True:
            rcs = self._run_attempt(hosts, attempt)
            cause = classify_pod_exit(rcs)
            rc = _pod_exit_code(rcs, cause)
            self.history.append(
                {
                    "attempt": attempt,
                    "hosts": hosts,
                    "exit_codes": {str(k): v for k, v in sorted(rcs.items())},
                    "cause": cause,
                }
            )
            if cause == "completed":
                return self._finish("completed", rc, cause, crashes, preemptions, hosts)
            if cause in FAIL_FAST_CAUSES:
                return self._finish("failed_fast", rc, cause, crashes, preemptions, hosts)
            if cause in PREEMPT_CLASS_CAUSES:
                preemptions += 1
                if preemptions > self.policy.max_preemptions:
                    return self._finish("gave_up", rc, cause, crashes, preemptions, hosts)
                delay = 0.0
            else:  # crash / hung
                crashes += 1
                if crashes > self.policy.max_restarts:
                    return self._finish("gave_up", rc, cause, crashes, preemptions, hosts)
                delay = self.policy.backoff(crashes)
            if cause == "host_lost":
                if self.flight is not None:
                    for k, code in sorted(rcs.items()):
                        if code < 0:
                            self.flight.record(
                                "host_lost", host=k, exit_code=code, attempt=attempt
                            )
                if self.elastic and hosts > 1:
                    hosts -= 1
            attempt += 1
            if self.flight is not None:
                self.flight.record(
                    "restart",
                    attempt=attempt,
                    cause=cause,
                    exit_code=rc,
                    delay_s=delay,
                    hosts=hosts,
                )
            if delay > 0:
                self.sleep(delay)

    def _finish(self, status, rc, cause, crashes, preemptions, hosts) -> dict:
        result = {
            "status": status,
            "exit_code": rc,
            "cause": cause,
            "attempts": len(self.history),
            "restarts": crashes,
            "preemptions": preemptions,
            "hosts": hosts,
            "history": list(self.history),
        }
        if self.flight is not None:
            self.flight.end_run(
                status=status,
                exit_code=rc,
                cause=cause,
                attempts=result["attempts"],
                restarts=crashes,
                preemptions=preemptions,
                hosts=hosts,
            )
        return result
