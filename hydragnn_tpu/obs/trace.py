"""Per-request distributed tracing: one trace ID per serve request,
spans for every hop, one exportable timeline.

The flight recorder answers "what happened to the RUN"; this module
answers "what happened to REQUEST 4817". Every request admitted by
``serve/server.py:ModelServer.submit`` gets a :class:`RequestTrace` (a
trace ID plus an ordered span list); the serve path closes spans at
each hop — bucket route, queue wait (coalescing), device execute,
postprocess — and hands the finished trace back to the
:class:`Tracer`, which keeps a bounded ring of recent traces and
samples every Nth into the serve flight record as a ``trace_capture``
event (``obs/flight.py``). Train-side, ``obs/spans.py:StepSpans``
feeds its sampled synchronous steps through the same Tracer, so train
steps and serve requests land on ONE timeline keyed by
``(run, epoch, step)`` / ``(run, seq)``.

Export is Chrome/Perfetto trace-event JSON (``chrome://tracing``,
https://ui.perfetto.dev): :meth:`Tracer.export_chrome` dumps the live
ring; :func:`flight_to_chrome` rebuilds a timeline offline from any
flight record (``trace_capture`` spans + ``epoch`` events), which is
how a crashed run's trace is recovered from its JSONL alone.

Cost discipline: a disabled tracer (telemetry off, or
``HYDRAGNN_TRACE=0``) returns ``None`` from :meth:`Tracer.begin` and
every downstream call site is null-guarded, so the off path adds one
attribute check per request and nothing else. Timestamps are
``time.time()`` wall seconds — the same clock the flight recorder
stamps ``t`` with, so the two sources merge without skew bookkeeping.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Union

from hydragnn_tpu.utils import knobs, syncdebug


def trace_enabled() -> bool:
    """Process-wide tracing gate: telemetry must be on AND
    ``HYDRAGNN_TRACE`` not disabled (default on)."""
    from hydragnn_tpu.obs.registry import telemetry_enabled

    return telemetry_enabled() and knobs.get_bool("HYDRAGNN_TRACE", True)


def new_trace_id() -> str:
    """64-bit random hex trace ID — collision-safe at serve volumes,
    short enough to grep a flight record for."""
    return os.urandom(8).hex()


class RequestTrace:
    """One request's (or one sampled train step's) span accumulator.

    Spans are closed intervals ``{name, t0, dur_ms, ...attrs}`` with
    ``t0`` in wall seconds. Two recording styles:

      - :meth:`add_span` — explicit interval (batch-level hops shared
        by every request in a coalesced batch);
      - :meth:`mark` — close a span from the previous mark to now (the
        sequential per-request hops: route -> queue wait -> ...).
    """

    __slots__ = ("trace_id", "seq", "t_admit", "spans", "attrs", "_mark")

    def __init__(self, trace_id: str, seq: int = -1, attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.seq = seq
        self.t_admit = time.time()
        self.spans: List[Dict[str, Any]] = []
        self.attrs = dict(attrs or {})
        self._mark = self.t_admit

    def add_span(self, name: str, t0: float, t1: float, **attrs) -> None:
        span: Dict[str, Any] = {
            "name": name,
            "t0": round(t0, 6),
            "dur_ms": round(max(t1 - t0, 0.0) * 1e3, 3),
        }
        if attrs:
            span.update(attrs)
        self.spans.append(span)

    def mark(self, name: str, **attrs) -> float:
        """Close a span covering previous-mark .. now; returns now."""
        now = time.time()
        self.add_span(name, self._mark, now, **attrs)
        self._mark = now
        return now

    def total_ms(self) -> float:
        return round(sum(s["dur_ms"] for s in self.spans), 3)

    def to_dict(self) -> dict:
        # snapshot, not the live lists: the caller (Tracer.finish, chrome
        # export) serializes on another thread than the one still holding
        # this trace — handing out self.spans itself would let a late
        # mark() mutate the list mid-serialization
        d = {
            "trace_id": self.trace_id,
            "seq": self.seq,
            "spans": [dict(s) for s in self.spans],
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class Tracer:
    """Trace factory + sink: mints :class:`RequestTrace` objects at
    admission, keeps a bounded ring of finished traces, and samples
    every ``sample_every``-th finished trace into the flight record as
    a ``trace_capture`` event (the first finished trace is always
    sampled, so even a 3-request smoke run leaves flight evidence).

    ``begin`` returns ``None`` when tracing is off — call sites guard
    with ``if trace is not None`` and pay one attribute check.
    """

    def __init__(
        self,
        flight=None,
        enabled: Optional[bool] = None,
        sample_every: Optional[int] = None,
        keep: int = 256,
    ):
        self.enabled = trace_enabled() if enabled is None else bool(enabled)
        if sample_every is None:
            sample_every = knobs.get_int("HYDRAGNN_TRACE_SAMPLE", 100)
        self.sample_every = max(1, int(sample_every))
        self.flight = flight
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "trace.Tracer._lock"
        )
        # graftsync: guarded-by=trace.Tracer._lock
        self._finished: deque = deque(maxlen=max(1, keep))
        self._count = 0  # graftsync: guarded-by=trace.Tracer._lock

    def begin(self, seq: int = -1, **attrs) -> Optional[RequestTrace]:
        if not self.enabled:
            return None
        return RequestTrace(new_trace_id(), seq, attrs or None)

    def finish(self, trace: Optional[RequestTrace]) -> None:
        if trace is None:
            return
        with self._lock:
            self._finished.append(trace)
            self._count += 1
            n = self._count
        if self.flight is not None and (n - 1) % self.sample_every == 0:
            self.flight.record("trace_capture", **trace.to_dict())

    @property
    def finished_count(self) -> int:
        with self._lock:
            return self._count

    def traces(self) -> List[RequestTrace]:
        """The current ring (a copy), oldest first."""
        with self._lock:
            return list(self._finished)

    # -- export ------------------------------------------------------------

    def to_chrome_trace(self) -> dict:
        events: List[dict] = []
        for i, tr in enumerate(self.traces()):
            d = tr.to_dict()
            tid = tr.seq if tr.seq >= 0 else i
            args = {"trace_id": d["trace_id"]}
            args.update(d.get("attrs", {}))
            events.extend(_chrome_events(d["spans"], pid=1, tid=tid, args=args))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> str:
        """Write the ring as Chrome trace-event JSON; returns ``path``."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        # per-writer tmp name: two threads exporting to the same path
        # must each replace atomically, never interleave into one tmp
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path


def _chrome_events(spans, pid: int, tid, args: Optional[dict] = None) -> List[dict]:
    """Span dicts -> Chrome trace-event 'X' (complete) events.
    ``ts``/``dur`` are microseconds; ``t0`` wall seconds pass through
    unshifted so events from different sources stay on one axis."""
    out = []
    for s in spans:
        ev_args = dict(args or {})
        ev_args.update(
            {k: v for k, v in s.items() if k not in ("name", "t0", "dur_ms")}
        )
        out.append(
            {
                "name": s.get("name", "span"),
                "ph": "X",
                "ts": round(float(s.get("t0", 0.0)) * 1e6, 1),
                "dur": round(float(s.get("dur_ms", 0.0)) * 1e3, 1),
                "pid": pid,
                "tid": tid,
                "args": ev_args,
            }
        )
    return out


def flight_to_chrome(record: Union[str, List[dict]]) -> dict:
    """Rebuild a Chrome/Perfetto timeline from a flight record: every
    ``trace_capture`` event's spans (serve requests, sampled train
    steps) plus one synthetic span per ``epoch`` event, all keyed by
    the run name from the ``run_start`` manifest. This is the offline
    join the tracing design promises: a crashed run's JSONL alone is
    enough to reconstruct the timeline a human can open."""
    from hydragnn_tpu.obs.flight import read_flight_record

    events = read_flight_record(record) if isinstance(record, str) else record
    run = "run"
    for ev in events:
        if ev.get("kind") == "run_start":
            man = ev.get("manifest")
            if isinstance(man, dict):
                run = str(man.get("log_name") or man.get("run") or run)
            break
    out: List[dict] = []
    hosts_seen: set = set()
    for i, ev in enumerate(events):
        kind = ev.get("kind")
        if kind == "trace_capture":
            spans = ev.get("spans")
            if not isinstance(spans, list):
                continue
            seq = ev.get("seq", -1)
            tid = seq if isinstance(seq, int) and seq >= 0 else i
            args = {"run": run, "trace_id": ev.get("trace_id")}
            args.update(
                {
                    k: v
                    for k, v in ev.items()
                    if k not in ("v", "kind", "t", "rank", "spans", "trace_id", "seq")
                }
            )
            out.extend(_chrome_events(spans, pid=1, tid=tid, args=args))
        elif kind == "epoch":
            # the epoch event is stamped at epoch END; reconstruct the
            # interval from the recorded epoch duration when present
            t1 = float(ev.get("t", 0.0))
            dur_s = ev.get("time") or ev.get("epoch_s") or 0.0
            try:
                dur_s = max(float(dur_s), 0.0)
            except (TypeError, ValueError):
                dur_s = 0.0
            args = {"run": run, "epoch": ev.get("epoch")}
            for key in ("train_loss", "val_loss", "steps"):
                if key in ev:
                    args[key] = ev[key]
            tid = int(ev.get("host", ev.get("rank", 0)) or 0)
            hosts_seen.add(tid)
            out.append(
                {
                    "name": f"epoch {ev.get('epoch')}",
                    "ph": "X",
                    "ts": round((t1 - dur_s) * 1e6, 1),
                    "dur": round(dur_s * 1e6, 1),
                    "pid": 0,
                    "tid": tid,
                    "args": args,
                }
            )
        elif kind == "host_epoch":
            # per-host epoch summary (obs/podview.py): one interval per
            # host per epoch — the merged multihost timeline's per-host
            # tracks (tid = host index)
            t1 = float(ev.get("t", 0.0))
            try:
                dur_s = max(float(ev.get("epoch_s") or 0.0), 0.0)
            except (TypeError, ValueError):
                dur_s = 0.0
            host = int(ev.get("host", ev.get("rank", 0)) or 0)
            hosts_seen.add(host)
            args = {"run": run, "epoch": ev.get("epoch"), "host": host}
            for key in ("data_wait_s", "steps", "mfu", "run_id"):
                if ev.get(key) is not None:
                    args[key] = ev[key]
            out.append(
                {
                    "name": f"host{host} epoch {ev.get('epoch')}",
                    "ph": "X",
                    "ts": round((t1 - dur_s) * 1e6, 1),
                    "dur": round(dur_s * 1e6, 1),
                    "pid": 0,
                    "tid": host,
                    "args": args,
                }
            )
    # name the per-host tracks so Perfetto shows "host k" instead of a
    # bare thread id (only worth the metadata rows when >1 host)
    if len(hosts_seen) > 1:
        for h in sorted(hosts_seen):
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 0,
                    "tid": h,
                    "args": {"name": f"host {h}"},
                }
            )
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_flight_chrome(record_path: str, out_path: str) -> str:
    """``flight_to_chrome`` to a file (atomic write); returns out_path.
    ``record_path`` may be a run DIRECTORY holding per-host flight
    shards — they are merged first (obs/podview.py), yielding one
    timeline with one track per host."""
    if os.path.isdir(record_path):
        from hydragnn_tpu.obs.podview import merge_host_flights

        data = flight_to_chrome(merge_host_flights(record_path).events)
    else:
        data = flight_to_chrome(record_path)
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{out_path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    os.replace(tmp, out_path)
    return out_path
