"""Pod-visibility plane: per-host flight shards, cross-host stitching,
and straggler/skew detection for multihost runs.

Every observability surface before this module assumed one process:
``obs/flight.py`` wrote one ``flight.jsonl``, ``obs/spans.py`` timed
one host, ``obs/registry.py`` resolved a single rank. This module makes
an N-host run produce ONE coherent timeline:

  - **Per-host flight sharding.** Rank 0 keeps the canonical
    ``flight.jsonl``; every other host writes its own crash-safe
    ``flight.host<k>.jsonl`` in the same run directory
    (:func:`host_flight_path`). :func:`merge_host_flights` joins the
    shards on ``(run_id, epoch)``, tolerates torn tails and missing
    hosts, and feeds ``tools/obs_report.py --hosts`` and the
    Chrome/Perfetto exporter (one track per host — shard events carry
    the host index in the ``rank`` envelope field).
  - **Skew detection.** Each host appends a lightweight ``host_epoch``
    summary (epoch wall time, data-wait, steps, MFU) to its shard; the
    rank-0 :class:`SkewMonitor` re-reads the peer shards at epoch
    boundaries (filesystem exchange — ``data/diststore.py``'s TCP store
    is the live alternative at pod scale), computes per-epoch duration
    skew and slowest-host attribution, and publishes
    ``podview.skew_frac`` / ``podview.slowest_host`` /
    ``podview.stall_age_s`` and per-host MFU gauges into the registry —
    what the ``step_skew`` / ``host_stall`` trigger rules
    (``obs/triggers.py``) evaluate.
  - **Collective-aware attribution.** :func:`collective_attribution`
    splits modeled step time into compute vs collective wire time using
    the committed ``tools/scaling_estimate.py`` traffic model against
    the run's Partitioner layout, so a skew verdict distinguishes
    "host 3 is slow" from "the interconnect is saturated".

Host identity comes from ``jax.process_index()``/``process_count()``,
overridable with ``HYDRAGNN_PODVIEW_HOST`` / ``HYDRAGNN_PODVIEW_HOSTS``
so single-machine CI can simulate a pod by running the same tiny config
once per host into one run directory (the shards join on the shared
``HYDRAGNN_PODVIEW_RUN_ID``). docs/OBSERVABILITY.md "Pod visibility"
has the full anatomy; everything here is stdlib + knobs only and must
never take a run down — failures degrade to "no podview data".
"""

from __future__ import annotations

import glob
import json
import os
import re
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple, Union

from hydragnn_tpu.utils import knobs

from .flight import read_flight_record

#: filename of the canonical (host 0) shard
CANONICAL_SHARD = "flight.jsonl"
_SHARD_RE = re.compile(r"^flight\.host([0-9]+)\.jsonl$")

PODVIEW_REPORT = "podview_report.json"
PODVIEW_REPORT_SCHEMA = 1

#: step_skew threshold fallback when no committed scaling estimate
#: carries a skew_tolerance block
DEFAULT_SKEW_THRESHOLD = 0.25

#: bound on retained per-epoch skew history (monitor memory + report size)
_HISTORY_MAX = 64


# -- host identity ----------------------------------------------------------


def host_identity() -> Tuple[int, int]:
    """``(host_index, host_count)`` for this process. The
    ``HYDRAGNN_PODVIEW_HOST`` / ``HYDRAGNN_PODVIEW_HOSTS`` overrides win
    (simulated hosts on one machine); otherwise jax's process index and
    count; ``(0, 1)`` when jax is unavailable."""
    host = knobs.get_int("HYDRAGNN_PODVIEW_HOST", -1)
    hosts = knobs.get_int("HYDRAGNN_PODVIEW_HOSTS", 0)
    if host < 0 or hosts <= 0:
        try:
            import jax

            if host < 0:
                host = jax.process_index()
            if hosts <= 0:
                hosts = jax.process_count()
        except Exception:
            pass
    host = max(host, 0)
    return host, max(hosts, host + 1, 1)


def podview_enabled() -> bool:
    """The plane is on when forced (``HYDRAGNN_PODVIEW``) or when the
    run actually spans more than one host (real or simulated)."""
    if knobs.get_bool("HYDRAGNN_PODVIEW", False):
        return True
    return host_identity()[1] > 1


def resolve_run_id(default: Optional[str] = None) -> Optional[str]:
    """The merge join key all of a run's host shards share:
    ``HYDRAGNN_PODVIEW_RUN_ID`` when set (how simulated hosts agree),
    else the caller's default (the run's log name)."""
    return knobs.get_str("HYDRAGNN_PODVIEW_RUN_ID") or default


# -- shard naming -----------------------------------------------------------


def host_flight_path(base_dir: str, host: Optional[int] = None) -> str:
    """Path of host ``host``'s flight shard under ``base_dir``. Host 0
    keeps the legacy canonical name ``flight.jsonl``; host ``k`` writes
    ``flight.host<k>.jsonl``."""
    if host is None:
        host = host_identity()[0]
    name = CANONICAL_SHARD if host == 0 else f"flight.host{host}.jsonl"
    return os.path.join(base_dir, name)


def host_artifact_path(path: str, host: Optional[int] = None) -> str:
    """Suffix a fixed-name artifact path with this process's host index
    so a second host never clobbers the first: ``x/train.prom`` stays
    ``x/train.prom`` on host 0 and becomes ``x/train.host2.prom`` on
    host 2. Applies to Prometheus textfiles and serve probe files."""
    if host is None:
        host = host_identity()[0]
    if host <= 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.host{host}{ext}"


def list_host_shards(base_dir: str) -> Dict[int, str]:
    """``{host_index: shard_path}`` for every flight shard present in
    ``base_dir`` (the canonical ``flight.jsonl`` is host 0)."""
    shards: Dict[int, str] = {}
    try:
        names = os.listdir(base_dir)
    except OSError:
        return shards
    for name in names:
        if name == CANONICAL_SHARD:
            shards[0] = os.path.join(base_dir, name)
            continue
        m = _SHARD_RE.match(name)
        if m:
            shards[int(m.group(1))] = os.path.join(base_dir, name)
    return shards


# -- merge reader -----------------------------------------------------------


class MergedFlights(NamedTuple):
    """Result of :func:`merge_host_flights`: the stitched event list
    (each event stamped with its ``host``), the host indices present,
    and advisory problems (torn tails, missing hosts, duplicates) that
    must NOT fail the merge."""

    events: List[dict]
    hosts: List[int]
    problems: List[str]


def _torn_tail(path: str) -> bool:
    """True when the shard's final non-empty line is not valid JSON —
    the crashed-writer case ``read_flight_record`` silently skips."""
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().split("\n") if ln.strip()]
    except OSError:
        return False
    if not lines:
        return False
    try:
        json.loads(lines[-1])
        return False
    except json.JSONDecodeError:
        return True


def merge_host_flights(
    source: Union[str, List[str]],
    expected_hosts: Optional[int] = None,
) -> MergedFlights:
    """Stitch per-host flight shards into one timeline.

    ``source`` is a run directory (every shard in it), a single shard
    path, or an explicit list of shard paths. Events are stamped with a
    ``host`` field (from the shard filename, falling back to the event's
    ``rank``) and sorted by timestamp; ``host_epoch`` events from
    different hosts join on ``(run_id, epoch)``.

    Degradation is advisory, never fatal: a torn tail, a missing host
    (fewer shards than the manifests/overrides promise), an unparseable
    interior line, or a duplicate ``(run_id, host, epoch)`` summary each
    append to ``problems`` while the merge of everything readable still
    returns."""
    if isinstance(source, str) and os.path.isdir(source):
        shards = list_host_shards(source)
        paths = [shards[h] for h in sorted(shards)]
    elif isinstance(source, str):
        paths = [source]
    else:
        paths = list(source)

    problems: List[str] = []
    events: List[dict] = []
    hosts_seen: List[int] = []
    promised = 0
    seen_summaries: Dict[Tuple[Any, int, int], int] = {}

    for path in paths:
        name = os.path.basename(path)
        m = _SHARD_RE.match(name)
        file_host = int(m.group(1)) if m else (0 if name == CANONICAL_SHARD else None)
        try:
            shard_events = read_flight_record(path)
        except (OSError, FileNotFoundError):
            problems.append(f"{name}: unreadable shard")
            continue
        if _torn_tail(path):
            problems.append(f"{name}: torn tail (final line truncated, skipped)")
        shard_hosts = set()
        for ev in shard_events:
            if ev.get("kind") == "_unparseable":
                problems.append(f"{name}: unparseable interior line")
                continue
            host = file_host if file_host is not None else int(ev.get("rank", 0) or 0)
            ev = dict(ev, host=host)
            shard_hosts.add(host)
            if ev.get("kind") == "host_epoch":
                promised = max(promised, int(ev.get("hosts", 0) or 0))
                key = (ev.get("run_id"), host, int(ev.get("epoch", -1)))
                seen_summaries[key] = seen_summaries.get(key, 0) + 1
            elif ev.get("kind") == "run_start":
                man = ev.get("manifest")
                if isinstance(man, dict):
                    try:
                        promised = max(promised, int(man.get("num_processes", 0) or 0))
                    except (TypeError, ValueError):
                        pass
            events.append(ev)
        for h in sorted(shard_hosts):
            if h not in hosts_seen:
                hosts_seen.append(h)

    for key, count in sorted(seen_summaries.items(), key=lambda kv: str(kv[0])):
        if count > 1:
            run_id, host, epoch = key
            problems.append(
                f"duplicate host_epoch for run_id={run_id!r} host={host} "
                f"epoch={epoch} ({count} copies)"
            )

    if expected_hosts is None:
        expected_hosts = max(knobs.get_int("HYDRAGNN_PODVIEW_HOSTS", 0), promised)
    if expected_hosts:
        missing = sorted(set(range(expected_hosts)) - set(hosts_seen))
        if missing:
            problems.append(
                f"missing host shard(s): {missing} "
                f"(expected {expected_hosts} hosts, saw {sorted(hosts_seen)})"
            )

    events.sort(key=lambda ev: (ev.get("t") or 0.0))
    return MergedFlights(events=events, hosts=sorted(hosts_seen), problems=problems)


def host_epoch_table(
    events: List[dict], run_id: Optional[str] = None
) -> Dict[int, Dict[int, dict]]:
    """The merge join materialized: ``{epoch: {host: host_epoch event}}``
    (optionally filtered to one ``run_id``) — what ``--hosts`` renders
    and the SkewMonitor math runs on."""
    table: Dict[int, Dict[int, dict]] = {}
    for ev in events:
        if ev.get("kind") != "host_epoch":
            continue
        if run_id is not None and ev.get("run_id") not in (None, run_id):
            continue
        epoch = int(ev.get("epoch", -1))
        host = int(ev.get("host", ev.get("rank", 0)) or 0)
        table.setdefault(epoch, {})[host] = ev
    return table


# -- straggler injection ----------------------------------------------------


def straggler_spec() -> Optional[Tuple[int, float]]:
    """Parse ``HYDRAGNN_INJECT_STRAGGLER="HOST:MS"`` into
    ``(host_index, sleep_seconds)``; None when unset or malformed (a
    bad spec must degrade to no injection, not crash)."""
    v = knobs.get_str("HYDRAGNN_INJECT_STRAGGLER")
    if not v:
        return None
    try:
        host, ms = v.split(":", 1)
        return int(host), float(ms) / 1e3
    except (ValueError, TypeError):
        return None


# -- scaling-model coupling -------------------------------------------------


def _scaling_record(path: Optional[str] = None) -> Optional[dict]:
    """The committed scaling estimate (``SCALING_est_*.json`` at the
    repo root, newest by name), or None."""
    if path is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        cands = sorted(glob.glob(os.path.join(root, "SCALING_est_*.json")))
        path = cands[-1] if cands else None
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


def load_skew_tolerance(path: Optional[str] = None) -> float:
    """The model-derived default ``step_skew`` threshold: the committed
    scaling estimate's ``skew_tolerance.default_step_skew_threshold``
    (tools/scaling_estimate.py emits it from each layout's no-overlap
    efficiency), or :data:`DEFAULT_SKEW_THRESHOLD` when absent."""
    rec = _scaling_record(path)
    if rec:
        try:
            thr = rec.get("skew_tolerance", {}).get("default_step_skew_threshold")
            if thr is not None:
                return float(thr)
        except (AttributeError, TypeError, ValueError):
            pass
    return DEFAULT_SKEW_THRESHOLD


def default_skew_threshold() -> float:
    """Effective ``step_skew`` threshold: the ``HYDRAGNN_PODVIEW_SKEW``
    knob when positive, else the scaling-model derivation."""
    knob = knobs.get_float("HYDRAGNN_PODVIEW_SKEW", 0.0)
    return knob if knob > 0 else load_skew_tolerance()


def collective_attribution(
    parallel: Optional[dict], scaling: Optional[dict] = None
) -> dict:
    """Split modeled step time into compute vs collective wire time for
    the run's committed layout, using the same ring all-reduce / FSDP
    traffic formulas as ``tools/scaling_estimate.py``: data-parallel
    gradient all-reduce moves ``2(n-1)/n`` of the gradient bytes, FSDP
    adds an all-gather + reduce-scatter pair at ``(f-1)/f`` each. A high
    observed skew with a low modeled ``wire_frac`` points at a slow
    host; skew within the modeled wire share points at the
    interconnect."""
    out: Dict[str, Any] = {
        "modeled": False,
        "compute_ms": None,
        "wire_ms": None,
        "wire_frac": None,
        "note": "",
    }
    if not isinstance(parallel, dict) or not parallel.get("available", False):
        out["note"] = "no parallel layout committed (single-device run)"
        return out
    if scaling is None:
        scaling = _scaling_record()
    if not scaling:
        out["note"] = "no committed scaling estimate (SCALING_est_*.json)"
        return out
    try:
        step_ms = float(scaling["step_ms_device_single_chip"])
        ici_bps = float(scaling.get("ici_gbps_assumed", 45.0)) * 1e9
        params = parallel.get("params") or {}
        grad_bytes = float(
            params.get("bytes_global")
            or scaling.get("param_bytes_f32")
            or 0.0
        )
        n_data = int(parallel.get("data") or 1)
        n_fsdp = int(parallel.get("fsdp") or 1)
        wire_bytes = 0.0
        if n_data > 1:
            wire_bytes += 2.0 * (n_data - 1) / n_data * grad_bytes
        if n_fsdp > 1:
            wire_bytes += (n_fsdp - 1) / n_fsdp * 2.0 * grad_bytes
        wire_ms = wire_bytes / ici_bps * 1e3
        total = step_ms + wire_ms
        out.update(
            modeled=True,
            compute_ms=round(step_ms, 4),
            wire_ms=round(wire_ms, 4),
            wire_frac=round(wire_ms / total, 6) if total > 0 else 0.0,
            data=n_data,
            fsdp=n_fsdp,
            note=(
                "ring all-reduce + FSDP ag/rs traffic model vs the "
                "committed layout (tools/scaling_estimate.py)"
            ),
        )
    except (KeyError, TypeError, ValueError, ZeroDivisionError) as e:
        out["note"] = f"attribution unavailable: {e}"
    return out


# -- skew monitor -----------------------------------------------------------


class SkewMonitor:
    """Rank-0 cross-host skew detector fed by filesystem shard exchange.

    Single-threaded by design: the train loop calls
    :meth:`observe_epoch` once per epoch boundary (never from the hot
    step path), so no lock is needed. Every public method is wrapped so
    a failure degrades to "no skew data this epoch" — podview must never
    take the run down. The monitor self-times its shard reads;
    :attr:`overhead_s` is what the run_end ``podview.overhead_frac``
    stamp is computed from."""

    def __init__(
        self,
        base_dir: str,
        host: int = 0,
        hosts: int = 1,
        run_id: Optional[str] = None,
        registry=None,
        parallel: Optional[dict] = None,
        threshold: Optional[float] = None,
        scaling: Optional[dict] = None,
    ):
        self.base_dir = base_dir
        self.host = host
        self.hosts = hosts
        self.run_id = run_id
        self.registry = registry
        self.parallel = parallel
        self.threshold = (
            threshold if threshold and threshold > 0 else default_skew_threshold()
        )
        self.history: List[dict] = []
        self.overhead_s = 0.0
        self._scaling = scaling
        # a host that never writes a shard counts as stalled from the
        # monitor's birth, not from the unix epoch
        self._t0 = time.time()

    def set_parallel(self, parallel: Optional[dict]) -> None:
        """Attach the Partitioner manifest once it exists (it is built
        after the monitor, when the train state is sharded)."""
        self.parallel = parallel

    # -- observation -------------------------------------------------------

    def observe_epoch(self, epoch: int, summary: Optional[dict] = None):
        """Read every host's ``host_epoch`` summary for ``epoch`` from
        the shards, compute skew, publish gauges. ``summary`` is this
        host's own record (used directly, saving a re-read race).
        Returns the skew dict (recorded as a ``podview`` flight event)
        or None when fewer than two hosts have reported."""
        t0 = time.perf_counter()
        try:
            return self._observe(int(epoch), summary)
        except Exception:
            return None  # degrade: no skew data this epoch
        finally:
            self.overhead_s += time.perf_counter() - t0

    def _observe(self, epoch: int, summary: Optional[dict]):
        per_host: Dict[int, dict] = {}
        latest_t: Dict[int, float] = {}
        for h, path in list_host_shards(self.base_dir).items():
            try:
                shard_events = read_flight_record(path)
            except OSError:
                continue
            for ev in shard_events:
                t = ev.get("t")
                if isinstance(t, (int, float)):
                    latest_t[h] = max(latest_t.get(h, 0.0), float(t))
                if ev.get("kind") != "host_epoch":
                    continue
                if int(ev.get("epoch", -1)) != epoch:
                    continue
                if self.run_id is not None and ev.get("run_id") not in (
                    None,
                    self.run_id,
                ):
                    continue
                per_host[int(ev.get("host", h) or h)] = ev
        if summary is not None:
            per_host.setdefault(self.host, dict(summary, host=self.host))

        now = time.time()
        stall_age = 0.0
        for h in range(self.hosts):
            if h == self.host:
                continue
            stall_age = max(stall_age, now - latest_t.get(h, self._t0))

        skew = None
        if len(per_host) >= 2:
            durs = {
                h: float(ev.get("epoch_s") or 0.0) for h, ev in per_host.items()
            }
            t_max = max(durs.values())
            slowest = max(sorted(durs), key=lambda h: durs[h])
            skew_frac = (t_max - min(durs.values())) / t_max if t_max > 0 else 0.0
            waits = {
                h: float(ev.get("data_wait_s") or 0.0)
                for h, ev in per_host.items()
            }
            attribution = collective_attribution(self.parallel, self._scaling)
            # name the likely cause: the slowest host starving on data
            # beats everything; skew inside the modeled wire share is
            # the interconnect; otherwise the host itself is slow
            slow_excess = t_max - min(durs.values())
            if waits.get(slowest, 0.0) >= 0.5 * slow_excess > 0:
                cause = "data_wait"
            elif (
                attribution.get("modeled")
                and skew_frac <= (attribution.get("wire_frac") or 0.0)
            ):
                cause = "interconnect"
            else:
                cause = "host_slow"
            skew = {
                "epoch": epoch,
                "skew_frac": round(skew_frac, 6),
                "slowest_host": slowest,
                "cause": cause,
                "threshold": self.threshold,
                "hosts_reporting": sorted(per_host),
                "epoch_s": {str(h): round(durs[h], 4) for h in sorted(durs)},
                "data_wait_s": {
                    str(h): round(waits[h], 4) for h in sorted(waits)
                },
            }
            self.history.append(skew)
            del self.history[:-_HISTORY_MAX]

        if self.registry is not None:
            self.registry.gauge("podview.skew_frac").set(
                skew["skew_frac"] if skew else 0.0
            )
            self.registry.gauge("podview.slowest_host").set(
                float(skew["slowest_host"]) if skew else -1.0
            )
            self.registry.gauge("podview.stall_age_s").set(round(stall_age, 3))
            for h, ev in per_host.items():
                mfu = ev.get("mfu")
                if isinstance(mfu, (int, float)):
                    self.registry.gauge(f"podview.host{h}.mfu").set(float(mfu))
        return skew

    # -- evidence ----------------------------------------------------------

    def report(self) -> dict:
        """The ``podview_report.json`` sidecar body: last verdict, skew
        history, cost attribution, and the monitor's own overhead."""
        last = self.history[-1] if self.history else None
        return {
            "schema": PODVIEW_REPORT_SCHEMA,
            "host": self.host,
            "hosts": self.hosts,
            "run_id": self.run_id,
            "threshold": self.threshold,
            "skew_frac": last["skew_frac"] if last else None,
            "slowest_host": last["slowest_host"] if last else None,
            "cause": last["cause"] if last else None,
            "history": self.history[-32:],
            "attribution": collective_attribution(self.parallel, self._scaling),
            "overhead_s": round(self.overhead_s, 6),
        }

    def shard_tails(self, tail_lines: int = 50) -> Dict[int, List[str]]:
        """The last ``tail_lines`` raw lines of every host shard — the
        per-host evidence an incident bundle captures."""
        tails: Dict[int, List[str]] = {}
        for h, path in list_host_shards(self.base_dir).items():
            try:
                with open(path) as f:
                    tails[h] = f.read().splitlines()[-tail_lines:]
            except OSError:
                continue
        return tails


def validate_podview_report(data) -> List[str]:
    """Schema check for a ``podview_report.json`` body; returns problems
    (empty = valid). Mirrored package-free in ``lint/artifacts.py`` so
    ``graftlint --artifacts`` holds committed sidecars to the same bar."""
    problems: List[str] = []
    if not isinstance(data, dict):
        return ["podview report is not a dict"]
    if not isinstance(data.get("schema"), int):
        problems.append("missing/invalid field 'schema' (int)")
    for field in ("host", "hosts"):
        if not isinstance(data.get(field), int):
            problems.append(f"missing/invalid field {field!r} (int)")
    if not isinstance(data.get("threshold"), (int, float)):
        problems.append("missing/invalid field 'threshold' (number)")
    if not isinstance(data.get("history"), list):
        problems.append("missing/invalid field 'history' (list)")
    if not isinstance(data.get("attribution"), dict):
        problems.append("missing/invalid field 'attribution' (dict)")
    sh = data.get("slowest_host")
    if sh is not None and not isinstance(sh, int):
        problems.append("field 'slowest_host' must be an int or null")
    return problems
