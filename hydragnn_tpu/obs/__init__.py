"""Unified telemetry: metrics registry, flight recorder, span tracing,
compile monitoring — the one observability layer train, serve, the
loader, and the benches all emit into (docs/OBSERVABILITY.md).

Pieces:
  - :mod:`~hydragnn_tpu.obs.registry` — counters / gauges / windowed
    histograms in a rank-aware store; null-object disabled path.
  - :mod:`~hydragnn_tpu.obs.flight` — crash-safe append-only JSONL
    event log per run (manifest, epochs, compiles, errors, summary).
  - :mod:`~hydragnn_tpu.obs.spans` — data-wait / host-dispatch /
    device-execute step-time decomposition with a sampled sync window.
  - :mod:`~hydragnn_tpu.obs.compile_monitor` — ``jax.monitoring``-based
    compile counting ("no recompile after step 1", now assertable).
  - :mod:`~hydragnn_tpu.obs.export` — tensorboard / JSONL / Prometheus
    textfile exporters over the registry.
  - :mod:`~hydragnn_tpu.obs.trace` — per-request / per-step distributed
    traces (trace IDs, spans, Chrome/Perfetto export).
  - :mod:`~hydragnn_tpu.obs.triggers` — declarative SLO rules over the
    live registry; firing captures a bounded profiler trace into a
    self-contained incident bundle.
  - :mod:`~hydragnn_tpu.obs.podview` — pod-visibility plane: per-host
    flight shards, cross-host merge/stitching, and the rank-0
    SkewMonitor behind the ``step_skew`` / ``host_stall`` triggers.

Global gate: ``HYDRAGNN_TELEMETRY=0`` disables the process-global
registry and everything the train loop wires up; each piece is also
individually constructible as enabled/disabled.
"""

from hydragnn_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    reset_registry,
    telemetry_enabled,
)
from hydragnn_tpu.obs.flight import (
    FAULT_KINDS,
    SCHEMA_VERSION,
    SUPPORTED_SCHEMA_VERSIONS,
    FlightRecorder,
    flight_record_warnings,
    read_flight_record,
    validate_flight_record,
)
from hydragnn_tpu.obs.introspect import (
    HardwareLedger,
    HeadDiagnostics,
    collect_head_series,
    cost_analysis,
    device_memory_stats,
    flag_anomalies,
    make_diagnostics_step,
    peak_flops,
    per_head_error_metrics,
)
from hydragnn_tpu.obs.podview import (
    MergedFlights,
    SkewMonitor,
    collective_attribution,
    host_artifact_path,
    host_epoch_table,
    host_flight_path,
    host_identity,
    list_host_shards,
    load_skew_tolerance,
    merge_host_flights,
    podview_enabled,
    resolve_run_id,
    straggler_spec,
    validate_podview_report,
)
from hydragnn_tpu.obs.spans import StepSpans
from hydragnn_tpu.obs.trace import (
    RequestTrace,
    Tracer,
    export_flight_chrome,
    flight_to_chrome,
    new_trace_id,
    trace_enabled,
)
from hydragnn_tpu.obs.triggers import (
    RULE_KINDS,
    IncidentRecorder,
    TriggerEngine,
    TriggerRule,
    TriggerVerdict,
    list_incidents,
    validate_incident_bundle,
    validate_incident_manifest,
)
from hydragnn_tpu.obs.compile_monitor import (
    BACKEND_COMPILE_EVENT,
    CompileMonitor,
)
from hydragnn_tpu.obs.drift import (
    DriftMonitor,
    P2Quantile,
    RunningMoments,
    build_reference,
    load_reference,
    psi,
    validate_drift_report,
)
from hydragnn_tpu.obs.spool import (
    RequestSpool,
    list_shards,
    read_spool,
    validate_spool_manifest,
)
from hydragnn_tpu.obs.export import (
    prometheus_name,
    registry_to_jsonl,
    registry_to_prometheus,
    registry_to_prometheus_text,
    registry_to_tensorboard,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "reset_registry",
    "telemetry_enabled",
    "FAULT_KINDS",
    "SCHEMA_VERSION",
    "SUPPORTED_SCHEMA_VERSIONS",
    "FlightRecorder",
    "flight_record_warnings",
    "read_flight_record",
    "validate_flight_record",
    "HardwareLedger",
    "HeadDiagnostics",
    "collect_head_series",
    "cost_analysis",
    "device_memory_stats",
    "flag_anomalies",
    "make_diagnostics_step",
    "peak_flops",
    "per_head_error_metrics",
    "MergedFlights",
    "SkewMonitor",
    "collective_attribution",
    "host_artifact_path",
    "host_epoch_table",
    "host_flight_path",
    "host_identity",
    "list_host_shards",
    "load_skew_tolerance",
    "merge_host_flights",
    "podview_enabled",
    "resolve_run_id",
    "straggler_spec",
    "validate_podview_report",
    "StepSpans",
    "RequestTrace",
    "Tracer",
    "export_flight_chrome",
    "flight_to_chrome",
    "new_trace_id",
    "trace_enabled",
    "RULE_KINDS",
    "IncidentRecorder",
    "TriggerEngine",
    "TriggerRule",
    "TriggerVerdict",
    "list_incidents",
    "validate_incident_bundle",
    "validate_incident_manifest",
    "BACKEND_COMPILE_EVENT",
    "CompileMonitor",
    "DriftMonitor",
    "P2Quantile",
    "RunningMoments",
    "build_reference",
    "load_reference",
    "psi",
    "validate_drift_report",
    "RequestSpool",
    "list_shards",
    "read_spool",
    "validate_spool_manifest",
    "prometheus_name",
    "registry_to_jsonl",
    "registry_to_prometheus",
    "registry_to_prometheus_text",
    "registry_to_tensorboard",
]
