"""Model-level introspection: per-head gradient diagnostics, task-conflict
tracking, and the per-run hardware-efficiency ledger.

The paper's defining feature is the multi-headed decoder — one shared
encoder trained against N simultaneous tasks — yet a per-task loss number
is all the flight record used to say about the multi-task optimization.
This module makes two more questions answerable from the run's own
artifact (docs/OBSERVABILITY.md "Model-level diagnostics"):

**Is the multi-task optimization healthy?**
  :func:`make_diagnostics_step` builds ONE jitted function computing, per
  sampled step: per-head gradient norms (one forward + one ``jax.vjp``
  linearization shared by H one-hot cotangent pulls — not H separate
  backward passes over a re-traced forward), the pairwise inter-task
  gradient cosine matrix (the conflict matrix: persistently negative
  entries mean two heads fight over the shared encoder), and the global
  update-to-param norm ratio (the effective step size the optimizer is
  actually taking). :class:`HeadDiagnostics` samples it every
  ``Training.diag_every`` steps (default: once per epoch) so the hot
  path gains no per-step host syncs, and the diagnostics executable is a
  SEPARATE jitted fn compiled once — the train step itself is untouched
  (pinned by the zero-unexpected-recompile test).

**How efficiently did the hardware run?**
  :class:`HardwareLedger` records the compiled train step's analytic
  FLOPs/bytes (XLA cost model, obtained from the LOWERED module — no
  second compile) plus the chip's bf16 peak at ``run_start``, and turns
  each epoch's wall time into achieved TFLOP/s + MFU, alongside the
  device-memory watermark (``memory_stats()`` where the backend exposes
  it, ``available: false`` degradation elsewhere — same discipline as the
  compile monitor). ``bench.py`` imports :func:`peak_flops` /
  :func:`cost_analysis` from here (single source for the cost math).

Everything host-side in this module is numpy-only; jax is imported
lazily inside the functions that need it so ``tools/obs_report.py`` can
use the series/anomaly helpers without touching a backend.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# bf16 MXU peak per chip, by device_kind substring (public specs).
# Moved from bench.py so training and bench MFU share one table.
PEAK_BF16_TFLOPS = (
    ("v5 lite", 197.0),
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v6", 918.0),
    ("trillium", 918.0),
)


def peak_flops(device) -> Optional[float]:
    """The device's bf16 peak in FLOP/s, or None when the chip is not in
    the table (CPU, unknown accelerators) — MFU is then unavailable."""
    kind = getattr(device, "device_kind", "").lower()
    for sub, tf in PEAK_BF16_TFLOPS:
        if sub in kind:
            return tf * 1e12
    return None


# HBM bandwidth per chip, by device_kind substring (public specs) — the
# denominator of the per-kernel roofline attribution (bench.py): an op
# running near this number is bandwidth-bound and further kernel fusion
# cannot speed it up; one far below it while off the MXU is
# overhead/serial-bound — the class the fused kernels exist to kill.
PEAK_HBM_GBPS = (
    ("v5 lite", 819.0),
    ("v5e", 819.0),
    ("v5p", 2765.0),
    ("v4", 1228.0),
    ("v6", 1638.0),
    ("trillium", 1638.0),
)


def peak_hbm_bw(device) -> Optional[float]:
    """The device's HBM bandwidth in bytes/s, or None off-table."""
    kind = getattr(device, "device_kind", "").lower()
    for sub, gb in PEAK_HBM_GBPS:
        if sub in kind:
            return gb * 1e9
    return None


def cost_analysis(compiled_or_lowered) -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes) per execution from XLA's cost model, or Nones.

    Accepts either a ``jax.stages.Compiled`` or a ``jax.stages.Lowered``
    — the lowered path analyzes the unoptimized HLO WITHOUT compiling,
    which is what training uses (a second compile of the train step
    would churn the compile monitor's zero-unexpected-recompile
    contract)."""
    try:
        c = compiled_or_lowered.cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0]
        flops = float(c.get("flops", 0.0)) or None
        nbytes = float(c.get("bytes accessed", 0.0)) or None
        return flops, nbytes
    except Exception:
        return None, None


def pad_waste_from_batch(batch) -> Dict[str, Any]:
    """Pad-occupancy accounting for one loader batch: how much of the
    static edge/node pad the batch actually fills. Uses the loader's
    occupancy fields (``GraphBatch.edge_occupancy`` — the fused
    kernel's actual chunk-loop bound, which under run_align includes
    the interleaved masked self-loops below it — and ``n_real_nodes``)
    when present, the masks otherwise. Works on single batches and
    device-stacked ones (means over the leading device axis)."""
    senders = np.asarray(batch.senders)
    edge_pad = int(senders.shape[-1])
    nmask = np.asarray(batch.node_mask)
    node_pad = int(nmask.shape[-1])
    occ = getattr(batch, "edge_occupancy", None)
    if occ is not None:
        real_e = float(np.asarray(occ).mean())
    else:
        real_e = float(np.asarray(batch.edge_mask).sum(axis=-1).mean())
    nrn = getattr(batch, "n_real_nodes", None)
    if nrn is not None:
        real_n = float(np.asarray(nrn).mean())
    else:
        real_n = float(nmask.sum(axis=-1).mean())
    return {
        "edge_pad": edge_pad,
        "node_pad": node_pad,
        "real_edges_mean": round(real_e, 1),
        "real_nodes_mean": round(real_n, 1),
        "edge_waste_frac": round(1.0 - real_e / max(edge_pad, 1), 4),
        "node_waste_frac": round(1.0 - real_n / max(node_pad, 1), 4),
    }


def conv_traffic_model(
    node_pad: int,
    edge_pad: int,
    hidden: int,
    layers: int,
    real_edges: Optional[float] = None,
) -> Dict[str, Any]:
    """Analytic bytes/step of the conv hot path under each kernel mode
    (docs/PERF.md r08) — the useful-vs-padded byte accounting the XLA
    cost model cannot provide (it prices custom-calls from operand
    SHAPES, so occupancy skipping and the bf16 activation path are
    invisible to it).

    Prices, per conv layer, what the fused kernel physically moves:
    edge-id chunk DMAs (3 int32 streams in whole CE-edge chunks),
    sender gather windows (BW rows x padded width, ~one window per
    chunk — the loader's locality contract), the layer's params, and
    the f32 output write. ``fused_skip`` bounds the chunk loop at
    ``real_edges`` (GraphBatch.edge_occupancy); ``fused_skip_bf16``
    additionally moves activations as bf16; ``resident_skip`` loads the
    features once and keeps them in VMEM across layers (intermediate
    out-block flushes counted honestly). ``xla_unfused`` is the
    materialized gather->message->scatter chain for scale."""
    from hydragnn_tpu.ops.segment_pallas import ALIGN, BN, BW, CE

    hp = ((int(hidden) + 127) // 128) * 128
    node_pad = int(node_pad)
    edge_pad = int(edge_pad)
    layers = max(int(layers), 1)
    n_pad_out = ((node_pad + BN - 1) // BN) * BN
    n_res = max(((node_pad + ALIGN - 1) // ALIGN) * ALIGN, BW, n_pad_out)
    e_eff = edge_pad if real_edges is None else min(float(real_edges), edge_pad)

    def chunks(e: float) -> int:
        return -(-int(e) // CE) if e > 0 else 0

    def fused(e: float, act_bytes: int) -> int:
        per_layer = (
            3 * chunks(e) * CE * 4        # send/recv/mask id streams
            + chunks(e) * BW * hp * act_bytes  # sender gather windows
            + (hp * hp + hp) * 4          # layer params (f32 always)
            + n_pad_out * hp * 4          # f32 output write
        )
        return layers * per_layer

    xla = layers * (
        node_pad * hp * 4        # x read
        + 4 * edge_pad * hp * 4  # gather write+read, message write+read
        + 2 * edge_pad * 4       # id reads
        + n_pad_out * hp * 4     # scatter output
    )
    padded = fused(edge_pad, 4)
    skip = fused(e_eff, 4)
    skip_bf16 = fused(e_eff, 2)
    resident_skip = n_res * hp * 4 + layers * (
        3 * chunks(e_eff) * CE * 4 + (hp * hp + hp) * 4 + n_pad_out * hp * 4
    )

    def drop(b: int) -> float:
        return round(1.0 - b / max(padded, 1), 4)

    return {
        "hidden_padded": hp,
        "edge_pad": edge_pad,
        "real_edges": None if real_edges is None else int(real_edges),
        "assumption": "one BW-row gather window per CE-edge chunk (loader locality)",
        "bytes_per_step": {
            "xla_unfused": int(xla),
            "fused_padded": int(padded),
            "fused_skip": int(skip),
            "fused_skip_bf16": int(skip_bf16),
            "resident_skip": int(resident_skip),
        },
        "drop_vs_fused_padded": {
            "fused_skip": drop(skip),
            "fused_skip_bf16": drop(skip_bf16),
            "resident_skip": drop(resident_skip),
        },
    }


def device_memory_stats(device=None) -> Dict[str, Any]:
    """Device-memory watermark with the compile-monitor-style
    ``available`` degradation: CPU (and any backend without
    ``memory_stats``) reports ``{"available": False}`` rather than
    raising or lying."""
    try:
        import jax

        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        stats = None
    if not stats:
        return {"available": False}
    out: Dict[str, Any] = {"available": True}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if key in stats:
            out[key] = int(stats[key])
    return out


# ---------------------------------------------------------------------------
# per-head gradient diagnostics (the on-device half)
# ---------------------------------------------------------------------------


def make_diagnostics_step(
    model,
    tx,
    compute_dtype=None,
    remat: bool = False,
) -> Callable[..., Dict[str, Any]]:
    """Jitted ``(state, batch) -> diagnostics dict`` over the SAME loss
    the train step optimizes (same dropout-rng split, same mixed-precision
    casts), without touching the state: no donation, no mutation — a pure
    observer the loop dispatches on sampled steps only.

    Returned (device) dict:
      - ``grad_norms`` [H]: global norm of each head's UNWEIGHTED loss
        gradient w.r.t. the full parameter tree;
      - ``cosine`` [H, H]: pairwise cosine similarity between per-head
        gradients (1 on the diagonal; negative entries = conflicting
        tasks pulling the shared encoder in opposing directions);
      - ``grad_norm_total``: norm of the task-weighted total gradient
        (what the optimizer actually consumes);
      - ``param_norm`` / ``update_norm`` / ``update_ratio``: global
        parameter norm, optax update norm, and their ratio — the
        effective relative step size.

    Cost: one forward + (H+1) cotangent pulls through one shared
    ``jax.vjp`` linearization (the "per-head loss vjp" trick), plus one
    ``tx.update`` whose new opt_state is discarded.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from hydragnn_tpu.models.base import model_loss
    from hydragnn_tpu.train.state import _cast_floats

    cfg = model.cfg
    num_heads = cfg.num_heads
    weights = jnp.asarray(cfg.normalized_weights, jnp.float32)

    def _tree_dot(a, b) -> jnp.ndarray:
        leaves = jax.tree_util.tree_map(
            lambda x, y: jnp.vdot(
                x.astype(jnp.float32), y.astype(jnp.float32)
            ),
            a,
            b,
        )
        return sum(jax.tree_util.tree_leaves(leaves), jnp.zeros((), jnp.float32))

    def diag(state, batch) -> Dict[str, Any]:
        # identical split to the train step body: the diagnosed gradient
        # is the gradient THIS step's update is built from
        _, dropout_rng = jax.random.split(state.rng)

        def tasks_fn(params):
            if compute_dtype is not None:
                apply_params = _cast_floats(params, compute_dtype)
                apply_batch = _cast_floats(batch, compute_dtype)
            else:
                apply_params, apply_batch = params, batch
            outputs, _ = model.apply(
                {"params": apply_params, "batch_stats": state.batch_stats},
                apply_batch,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
            )
            outputs = [o.astype(jnp.float32) for o in outputs]
            _, tasks = model_loss(cfg, outputs, batch)
            return jnp.stack(tasks)

        fn = jax.checkpoint(tasks_fn) if remat else tasks_fn
        tasks, vjp_fn = jax.vjp(fn, state.params)
        head_grads = []
        for ihead in range(num_heads):
            cot = jnp.zeros((num_heads,), tasks.dtype).at[ihead].set(1.0)
            (g,) = vjp_fn(cot)
            head_grads.append(g)
        # the weighted-total gradient from the same linearization: one
        # more pull with the weight vector as cotangent
        (total_grad,) = vjp_fn(weights.astype(tasks.dtype))

        dots = jnp.stack(
            [
                jnp.stack([_tree_dot(head_grads[i], head_grads[j]) for j in range(num_heads)])
                for i in range(num_heads)
            ]
        )
        norms = jnp.sqrt(jnp.clip(jnp.diagonal(dots), 0.0, None))
        denom = jnp.maximum(norms[:, None] * norms[None, :], 1e-30)
        cosine = dots / denom

        param_norm = optax.global_norm(state.params)
        updates, _ = tx.update(total_grad, state.opt_state, state.params)
        update_norm = optax.global_norm(updates)
        return {
            "tasks_loss": tasks,
            "grad_norms": norms,
            "cosine": cosine,
            "grad_norm_total": optax.global_norm(total_grad),
            "param_norm": param_norm,
            "update_norm": update_norm,
            "update_ratio": update_norm / jnp.maximum(param_norm, 1e-30),
        }

    return jax.jit(diag)


class HeadDiagnostics:
    """Sampling controller around the jitted diagnostics step.

    ``maybe_sample(state, batch)`` is called once per training step
    BEFORE the (buffer-donating) train step consumes the state; on
    non-sampled steps it is a counter increment and nothing else. On
    sampled steps (every ``every`` steps, starting with the very first
    — so the one diagnostics compile lands in epoch 0 alongside the
    train step's) it dispatches the jitted fn and keeps the DEVICE
    results; no host sync happens until :meth:`epoch_snapshot`
    materializes them at the epoch boundary, where the epoch metrics
    sync anyway."""

    def __init__(self, diag_fn, head_names: Sequence[str], every: int):
        self.fn = diag_fn
        self.head_names = list(head_names)
        self.every = max(int(every), 1)
        self._n = 0
        self._pending = None
        self._pending_step = None

    def maybe_sample(self, state, batch) -> None:
        if self._n % self.every == 0:
            self._pending = self.fn(state, batch)
            self._pending_step = self._n
        self._n += 1

    def epoch_snapshot(self) -> Optional[Dict[str, Any]]:
        """Materialize the epoch's sampled diagnostics (one D2H sync),
        keyed by head name — flight-record-ready. None when no step was
        sampled this epoch (``diag_every`` longer than the epoch)."""
        if self._pending is None:
            return None
        import jax

        vals = jax.device_get(self._pending)
        self._pending = None
        names = self.head_names
        grad_norms = np.asarray(vals["grad_norms"], np.float64)
        snap = {
            "available": True,
            "sampled_step": self._pending_step,
            "grad_norm": {n: float(g) for n, g in zip(names, grad_norms)},
            "task_loss": {
                n: float(v) for n, v in zip(names, np.asarray(vals["tasks_loss"]))
            },
            "cosine": np.asarray(vals["cosine"], np.float64).round(6).tolist(),
            "grad_norm_total": float(vals["grad_norm_total"]),
            "param_norm": float(vals["param_norm"]),
            "update_norm": float(vals["update_norm"]),
            "update_ratio": float(vals["update_ratio"]),
        }
        self._pending_step = None
        return snap


# ---------------------------------------------------------------------------
# per-head eval quality metrics
# ---------------------------------------------------------------------------


def per_head_error_metrics(
    trues: Sequence[np.ndarray],
    preds: Sequence[np.ndarray],
    names: Sequence[str],
) -> Dict[str, Dict[str, float]]:
    """MAE/RMSE per head over the gathered (true, predicted) value
    arrays the ``test_epoch`` sample path returns — pure numpy, runs on
    every execution mode (per-step, scan, sharded)."""
    out: Dict[str, Dict[str, float]] = {}
    for name, tv, pv in zip(names, trues, preds):
        tv = np.asarray(tv, np.float64).reshape(-1)
        pv = np.asarray(pv, np.float64).reshape(-1)
        n = min(tv.size, pv.size)
        if n == 0:
            out[name] = {"mae": None, "rmse": None, "count": 0}
            continue
        diff = pv[:n] - tv[:n]
        out[name] = {
            "mae": float(np.abs(diff).mean()),
            "rmse": float(np.sqrt((diff * diff).mean())),
            "count": int(n),
        }
    return out


# ---------------------------------------------------------------------------
# hardware-efficiency ledger
# ---------------------------------------------------------------------------


class HardwareLedger:
    """Per-run hardware-efficiency accounting for the train loop.

    Built once at run start from the train step's LOWERED module (no
    extra compile; ``available: false`` when lowering or the cost model
    is not supported for the step in use — sharded shard_map steps and
    the scan path degrade rather than fail). Per epoch,
    :meth:`epoch_record` turns measured wall seconds into achieved
    TFLOP/s and MFU against the chip's bf16 peak, plus the device
    memory watermark."""

    def __init__(
        self,
        flops_per_step: Optional[float],
        bytes_per_step: Optional[float],
        peak: Optional[float],
        device=None,
        reason: Optional[str] = None,
    ):
        self.flops_per_step = flops_per_step
        self.bytes_per_step = bytes_per_step
        self.peak = peak
        self.device = device
        self.reason = reason
        self.pad_waste: Optional[Dict[str, Any]] = None
        self.conv_traffic: Optional[Dict[str, Any]] = None
        self._mfus: List[float] = []
        self._peak_mem: Optional[int] = None

    @classmethod
    def from_step(cls, step_fn, args: tuple, device=None, reason: Optional[str] = None):
        """Lower ``step_fn`` on example args and read the cost model.
        Any failure (non-jitted callable, shard_map lowering quirks,
        missing cost analysis on this backend) degrades to an
        unavailable ledger carrying the failure class as ``reason``."""
        import jax

        if device is None:
            try:
                device = jax.devices()[0]
            except Exception:
                device = None
        flops = nbytes = None
        if reason is None:
            try:
                lowered = step_fn.lower(*args)
                flops, nbytes = cost_analysis(lowered)
                if flops is None:
                    reason = "cost_analysis_unavailable"
            except Exception as exc:
                reason = f"lowering_failed:{type(exc).__name__}"
        return cls(flops, nbytes, peak_flops(device), device=device, reason=reason)

    @classmethod
    def disabled(cls, reason: str = "disabled"):
        return cls(None, None, None, reason=reason)

    @property
    def available(self) -> bool:
        return self.flops_per_step is not None

    def set_conv_traffic(
        self,
        pad_waste: Optional[Dict[str, Any]],
        conv_traffic: Optional[Dict[str, Any]],
    ) -> None:
        """Attach the batch pad-occupancy accounting and the analytic
        conv-traffic model (useful vs padded bytes) — computed by the
        loop from the example batch; lands in the flight manifest."""
        self.pad_waste = pad_waste
        self.conv_traffic = conv_traffic

    def manifest(self) -> Dict[str, Any]:
        """The ``run_start`` ledger fields: what one step costs and what
        the chip could do."""
        out: Dict[str, Any] = {"available": self.available}
        if not self.available and self.reason:
            out["reason"] = self.reason
        if self.flops_per_step is not None:
            out["flops_per_step"] = self.flops_per_step
        if self.bytes_per_step is not None:
            out["bytes_per_step"] = self.bytes_per_step
        out["peak_bf16_tflops"] = (
            round(self.peak / 1e12, 1) if self.peak else None
        )
        if self.pad_waste is not None:
            out["pad_waste"] = self.pad_waste
        if self.conv_traffic is not None:
            out["conv_traffic"] = self.conv_traffic
        return out

    def epoch_record(self, steps: int, wall_s: float) -> Dict[str, Any]:
        """One epoch's efficiency: achieved TFLOP/s + MFU from the
        epoch's train wall time (an end-to-end number — data waits and
        dispatch gaps count against it, which is the honest production
        MFU), and the memory watermark."""
        out: Dict[str, Any] = {"available": self.available}
        if not self.available and self.reason:
            out["reason"] = self.reason
        out["steps"] = int(steps)
        out["train_wall_s"] = round(float(wall_s), 6)
        if self.available and steps > 0 and wall_s > 0:
            achieved = self.flops_per_step * steps / wall_s
            # 9 decimals: a CPU smoke run's sub-GFLOP/s rate must not
            # round to zero (the TPU range is unaffected)
            out["achieved_tflops"] = round(achieved / 1e12, 9)
            if self.peak:
                mfu = achieved / self.peak
                out["mfu"] = round(mfu, 6)
                self._mfus.append(mfu)
            else:
                out["mfu"] = None
        mem = device_memory_stats(self.device)
        out["memory"] = mem
        if mem.get("peak_bytes_in_use") is not None:
            self._peak_mem = max(self._peak_mem or 0, mem["peak_bytes_in_use"])
        return out

    def run_summary(self) -> Dict[str, Any]:
        """The ``run_end`` rollup: mean/max MFU over epochs and the
        run's high-water memory mark."""
        out: Dict[str, Any] = {"available": self.available}
        if self._mfus:
            out["mfu_mean"] = round(float(np.mean(self._mfus)), 6)
            out["mfu_max"] = round(float(np.max(self._mfus)), 6)
        if self._peak_mem is not None:
            out["peak_bytes_in_use"] = self._peak_mem
        return out


# ---------------------------------------------------------------------------
# flight-record series + anomaly heuristics (numpy-only, used by
# tools/obs_report.py --heads)
# ---------------------------------------------------------------------------


def collect_head_series(events: List[dict]) -> Dict[str, Any]:
    """Extract per-head trajectories from a flight record's epoch
    events: losses (v1 positional lists and v2 name-keyed dicts both
    accepted), sampled grad norms, conflict matrices, eval MAE.

    Returns ``{"names", "epochs", "train_loss", "grad_norm", "mae",
    "rmse", "cosine", "update_ratio"}`` where the per-head entries map
    name -> aligned list (None where an epoch carried no sample)."""
    epochs = [e for e in events if e.get("kind") == "epoch"]
    names: List[str] = []
    for e in epochs:
        heads = e.get("heads") or {}
        if heads.get("names"):
            names = list(heads["names"])
            break
        tt = e.get("train_tasks")
        if isinstance(tt, dict) and not names:
            names = list(tt)
    if not names and epochs:
        tt = epochs[0].get("train_tasks")
        if isinstance(tt, list):
            names = [f"task{i}" for i in range(len(tt))]
    series: Dict[str, Any] = {
        "names": names,
        "epochs": [e.get("epoch") for e in epochs],
        "train_loss": {n: [] for n in names},
        "grad_norm": {n: [] for n in names},
        "mae": {n: [] for n in names},
        "rmse": {n: [] for n in names},
        "cosine": [],
        "update_ratio": [],
    }

    def _per_head(container, key) -> Dict[str, Optional[float]]:
        val = (container or {}).get(key)
        if isinstance(val, dict):
            return {n: val.get(n) for n in names}
        if isinstance(val, list):
            return {n: (val[i] if i < len(val) else None) for i, n in enumerate(names)}
        return {n: None for n in names}

    for e in epochs:
        heads = e.get("heads") or {}
        tl = _per_head(e, "train_tasks")
        gn = _per_head(heads, "grad_norm")
        mae = _per_head(heads, "mae")
        rmse = _per_head(heads, "rmse")
        for n in names:
            series["train_loss"][n].append(tl[n])
            series["grad_norm"][n].append(gn[n])
            series["mae"][n].append(mae[n])
            series["rmse"][n].append(rmse[n])
        series["cosine"].append(heads.get("cosine"))
        series["update_ratio"].append(heads.get("update_ratio"))
    return series


def flag_anomalies(
    series: Dict[str, Any],
    spike_factor: float = 3.0,
    imbalance_factor: float = 10.0,
    negative_persistence: float = 0.5,
) -> List[str]:
    """Heuristic diagnosis over :func:`collect_head_series` output —
    human-readable flags, empty when the multi-task optimization looks
    healthy:

      - **loss spike**: a head's train loss exceeds ``spike_factor`` x
        the rolling median of its previous (up to 5) epochs;
      - **task conflict**: a head pair whose gradient cosine is negative
        in more than ``negative_persistence`` of the sampled epochs AND
        whose mean cosine is below -0.02 (persistently opposed, not a
        near-orthogonal pair flickering around zero);
      - **gradient imbalance**: the mean grad-norm ratio between the
        largest and smallest head exceeds ``imbalance_factor`` — one
        task's gradient drowns the others in the shared encoder.
    """
    flags: List[str] = []
    names = series.get("names") or []
    for n in names:
        losses = series["train_loss"].get(n) or []
        for i in range(1, len(losses)):
            cur = losses[i]
            window = [v for v in losses[max(0, i - 5) : i] if v is not None]
            if cur is None or not window:
                continue
            med = float(np.median(window))
            if med > 0 and cur > spike_factor * med:
                flags.append(
                    f"loss spike: head '{n}' epoch {series['epochs'][i]} "
                    f"train loss {cur:.4g} > {spike_factor:g}x rolling "
                    f"median {med:.4g}"
                )
    mats = [np.asarray(m, np.float64) for m in series.get("cosine") or [] if m is not None]
    if mats:
        h = len(names)
        for i in range(h):
            for j in range(i + 1, h):
                vals = np.asarray([m[i, j] for m in mats if m.shape == (h, h)])
                if (
                    vals.size >= 2
                    and (vals < 0).mean() > negative_persistence
                    and vals.mean() < -0.02
                ):
                    flags.append(
                        f"task conflict: heads '{names[i]}' vs "
                        f"'{names[j]}' gradient cosine negative in "
                        f"{int((vals < 0).sum())}/{vals.size} sampled epochs "
                        f"(mean {vals.mean():+.3f})"
                    )
    means = {}
    for n in names:
        gn = [v for v in (series["grad_norm"].get(n) or []) if v is not None]
        if gn:
            means[n] = float(np.mean(gn))
    if len(means) >= 2:
        hi = max(means, key=means.get)
        lo = min(means, key=means.get)
        if means[lo] > 0 and means[hi] / means[lo] > imbalance_factor:
            flags.append(
                f"gradient imbalance: head '{hi}' mean grad norm "
                f"{means[hi]:.4g} is {means[hi] / means[lo]:.1f}x head "
                f"'{lo}' ({means[lo]:.4g}) — exceeds {imbalance_factor:g}x"
            )
    return flags
