"""SLO trigger engine + auto-captured profiler incident bundles.

The passive observability stack (flight records, metrics registry,
host spans, the epoch-gated profiler) records symptoms; nothing
connects a symptom — p99 over target, MFU dip, queue buildup,
nonfinite burst — to its device-level cause without a human re-running
with profiling on. This module closes that loop the way production
serving stacks do: declarative SLO rules evaluated against the LIVE
metrics registry, and on firing, a bounded ``jax.profiler`` capture
plus a self-contained **incident bundle** written at the moment the
anomaly happens.

Rule kinds (:data:`RULE_KINDS`):

  - ``latency_p99``   — registry histogram p99 over a threshold
  - ``queue_depth``   — registry gauge over a threshold
  - ``queue_age``     — registry gauge (oldest-request age) over threshold
  - ``feature_drift`` — drift gauge (max per-channel PSI / quantile
                        shift published by ``obs/drift.DriftMonitor``)
                        over a threshold
  - ``pred_drift``    — drift gauge (max per-head prediction PSI) over
                        a threshold
  - ``error_drift``   — drift gauge (max per-head MAE over the
                        reference target scale, from labelled spool
                        entries) over a threshold
  - ``mfu_drop``      — observed series falls below ``threshold`` x the
                        rolling median of the previous ``window`` values
  - ``loss_spike``    — observed series exceeds ``threshold`` x the
                        rolling median (the ``introspect.flag_anomalies``
                        heuristic, evaluated online per epoch)
  - ``nonfinite_burst`` — registry counter delta between consecutive
                        evaluations reaches the threshold
                        (``train.nonfinite_skipped``)
  - ``pilot_stuck``   — escalation kind raised directly by the retrain
                        pilot (:mod:`hydragnn_tpu.pilot`) after K
                        consecutive failed recovery cycles; never
                        evaluated by the engine, but its incident
                        manifests must validate like any other
  - ``step_skew``     — podview gauge (``podview.skew_frac``: the
                        cross-host epoch-duration skew the rank-0
                        ``obs/podview.SkewMonitor`` publishes) over a
                        threshold derived from the scaling model's
                        ``skew_tolerance`` block
  - ``host_stall``    — podview gauge (``podview.stall_age_s``: seconds
                        since the least-recently-heard-from host's last
                        flight event) over a threshold

Firing is **rate-limited** (per-engine cooldown + max incident count)
and **overhead-budgeted** (a capture is refused once capture time
exceeds the budgeted fraction of run wall time), so a pathological run
degrades to "first few incidents captured, rest suppressed-and-counted"
rather than profiling itself to death. Deterministic test entry:
``HYDRAGNN_INJECT_TRIGGER=<rule name>`` force-fires that rule once
(``resilience/inject.py``).

An incident bundle under ``<run log dir>/incidents/<id>/`` holds:
``trigger.json`` (verdict), ``metrics.json`` (registry snapshot),
``flight_tail.jsonl`` (last lines of the run's flight record),
``chip_hygiene.json`` (``tools/chip_hygiene.py`` report),
``memory.json`` (device memory stats), ``profile/`` (the bounded
profiler trace) and — written LAST, atomically —
``incident_manifest.json``. A bundle whose manifest is missing is a
run that died mid-capture; every reader here tolerates it.
``tools/incident_report.py`` renders bundles; ``graftlint
--artifacts`` validates manifests (``lint/artifacts.py``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from hydragnn_tpu.utils import knobs, syncdebug

RULE_KINDS = (
    "latency_p99",
    "queue_depth",
    "queue_age",
    "feature_drift",
    "pred_drift",
    "error_drift",
    "mfu_drop",
    "loss_spike",
    "nonfinite_burst",
    "pilot_stuck",
    "step_skew",
    "host_stall",
    "host_lost",
)

#: which rule kinds read a registry metric (vs an observed series)
_REGISTRY_KINDS = (
    "latency_p99",
    "queue_depth",
    "queue_age",
    "feature_drift",
    "pred_drift",
    "error_drift",
    "nonfinite_burst",
    "step_skew",
    "host_stall",
    "host_lost",
)

#: drift kinds read a DriftMonitor-published gauge (obs/drift.py); the
#: monitor keeps its gauges at 0.0 until its warm-up row count is met,
#: so a plain over-threshold compare is safe from cold-start noise
_DRIFT_KINDS = ("feature_drift", "pred_drift", "error_drift")

INCIDENT_MANIFEST = "incident_manifest.json"
INCIDENT_MANIFEST_VERSION = 1


@dataclasses.dataclass(frozen=True)
class TriggerRule:
    """One declarative SLO rule. ``metric`` names a registry metric
    (``latency_p99``/``queue_depth``/``queue_age``/``nonfinite_burst``)
    or an observed series (``mfu_drop``/``loss_spike`` — values fed via
    :meth:`TriggerEngine.observe`). ``threshold`` is in the metric's
    own unit for level rules, and a RATIO of the rolling median for
    ``mfu_drop`` (fire when cur < threshold x median) and
    ``loss_spike`` (fire when cur > threshold x median)."""

    name: str
    kind: str
    metric: str
    threshold: float
    window: int = 5
    min_samples: int = 2

    def __post_init__(self):
        if self.kind not in RULE_KINDS:
            raise ValueError(
                f"unknown trigger rule kind {self.kind!r} (one of {RULE_KINDS})"
            )


@dataclasses.dataclass
class TriggerVerdict:
    """Why a rule fired: the observed value, the threshold it crossed,
    and (for median rules) the baseline — the evidence half of the
    incident bundle's ``trigger.json``."""

    rule: str
    kind: str
    metric: str
    observed: float
    threshold: float
    fired_t: float
    injected: bool = False
    detail: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _median(vals) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return float(s[mid]) if n % 2 else float((s[mid - 1] + s[mid]) / 2.0)


class TriggerEngine:
    """Evaluate a rule set against the live registry + observed series.

    ``evaluate()`` returns the verdicts that PASSED rate limiting (at
    most one per call — one capture at a time is all the profiler can
    do anyway); suppressed firings are counted, never lost silently.
    ``clock`` is injectable for tests (monotonic seconds).
    """

    def __init__(
        self,
        rules,
        registry=None,
        cooldown_s: Optional[float] = None,
        max_incidents: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rules: List[TriggerRule] = list(rules)
        if registry is None:
            from hydragnn_tpu.obs.registry import get_registry

            registry = get_registry()
        self.registry = registry
        if cooldown_s is None:
            cooldown_s = knobs.get_float("HYDRAGNN_INCIDENT_COOLDOWN_S", 300.0)
        if max_incidents is None:
            max_incidents = knobs.get_int("HYDRAGNN_INCIDENT_MAX", 5)
        self.cooldown_s = float(cooldown_s)
        self.max_incidents = int(max_incidents)
        self._clock = clock
        self._series: Dict[str, deque] = {}
        self._counter_last: Dict[str, float] = {}
        self._last_fire_t: Optional[float] = None
        self.fired: List[TriggerVerdict] = []
        self.suppressed = 0
        self._eval_s = 0.0
        self._t0 = clock()

    # -- inputs ------------------------------------------------------------

    def observe(self, name: str, value) -> None:
        """Feed one sample of a named series (per-epoch MFU, loss) for
        the rolling-median rules. ``None`` samples (e.g. MFU off-TPU)
        are dropped so they never poison a median."""
        if value is None:
            return
        dq = self._series.get(name)
        if dq is None:
            dq = self._series[name] = deque(maxlen=64)
        dq.append(float(value))

    # -- evaluation --------------------------------------------------------

    def _eval_rule(self, rule: TriggerRule) -> Optional[TriggerVerdict]:
        now = time.time()
        if rule.kind == "latency_p99":
            h = self.registry.get(rule.metric)
            if h is None or not hasattr(h, "snapshot") or h.count < rule.min_samples:
                return None
            snap = h.snapshot()
            p99 = float(snap.get("p99", 0.0))
            if p99 > rule.threshold:
                return TriggerVerdict(
                    rule.name, rule.kind, rule.metric, round(p99, 6),
                    rule.threshold, now, detail={"count": snap.get("count")},
                )
            return None
        if rule.kind in (
            "queue_depth", "queue_age", "step_skew", "host_stall", "host_lost"
        ):
            g = self.registry.get(rule.metric)
            if g is None or not hasattr(g, "value"):
                return None
            v = float(g.value)
            if v > rule.threshold:
                detail: Dict[str, Any] = {}
                if rule.kind in ("step_skew", "host_stall"):
                    # evidence: which host the podview monitor blamed
                    sg = self.registry.get("podview.slowest_host")
                    if sg is not None and hasattr(sg, "value"):
                        detail["slowest_host"] = int(sg.value)
                if rule.kind == "host_lost":
                    # evidence: which host the liveness view declared lost
                    lg = self.registry.get("podview.lost_host")
                    if lg is not None and hasattr(lg, "value"):
                        detail["lost_host"] = int(lg.value)
                return TriggerVerdict(
                    rule.name, rule.kind, rule.metric, round(v, 6),
                    rule.threshold, now, detail=detail,
                )
            return None
        if rule.kind in _DRIFT_KINDS:
            g = self.registry.get(rule.metric)
            if g is None or not hasattr(g, "value"):
                return None
            v = float(g.value)
            if v > rule.threshold:
                # evidence: how many rows the sketch had folded in when
                # it breached (the DriftMonitor publishes row-count
                # gauges next to each distance gauge)
                rows = {}
                base = rule.metric.rsplit(".", 1)[0]
                for key in ("feature_rows", "pred_rows", "labeled_rows"):
                    rg = self.registry.get(f"{base}.{key}")
                    if rg is not None and hasattr(rg, "value"):
                        rows[key] = float(rg.value)
                return TriggerVerdict(
                    rule.name, rule.kind, rule.metric, round(v, 6),
                    rule.threshold, now, detail=rows,
                )
            return None
        if rule.kind == "nonfinite_burst":
            c = self.registry.get(rule.metric)
            if c is None or not hasattr(c, "value"):
                return None
            cur = float(c.value)
            last = self._counter_last.get(rule.name, 0.0)
            self._counter_last[rule.name] = cur
            delta = cur - last
            if delta >= rule.threshold:
                return TriggerVerdict(
                    rule.name, rule.kind, rule.metric, round(delta, 6),
                    rule.threshold, now, detail={"counter_total": cur},
                )
            return None
        if rule.kind == "pilot_stuck":
            # raised directly by the retrain pilot, never engine-evaluated
            return None
        # rolling-median series rules: mfu_drop / loss_spike
        dq = self._series.get(rule.metric)
        if dq is None or len(dq) < rule.min_samples + 1:
            return None
        cur = dq[-1]
        prev = list(dq)[:-1][-rule.window:]
        med = _median(prev)
        if med <= 0:
            return None
        if rule.kind == "mfu_drop":
            hit = cur < rule.threshold * med
        else:  # loss_spike: the flag_anomalies heuristic, online
            hit = cur > rule.threshold * med
        if hit:
            return TriggerVerdict(
                rule.name, rule.kind, rule.metric, round(cur, 6),
                rule.threshold, now,
                detail={"rolling_median": round(med, 6), "window": len(prev)},
            )
        return None

    def evaluate(self) -> List[TriggerVerdict]:
        """One evaluation pass: every rule is checked, the injected
        rule (``HYDRAGNN_INJECT_TRIGGER``) force-fires, and rate
        limiting admits at most one verdict."""
        t_eval0 = time.perf_counter()
        from hydragnn_tpu.resilience.inject import injected_trigger

        forced = injected_trigger({r.name for r in self.rules})
        verdicts: List[TriggerVerdict] = []
        for rule in self.rules:
            v = self._eval_rule(rule)
            if v is None and forced == rule.name:
                v = TriggerVerdict(
                    rule.name, rule.kind, rule.metric, -1.0,
                    rule.threshold, time.time(), injected=True,
                    detail={"injected": "HYDRAGNN_INJECT_TRIGGER"},
                )
            if v is not None:
                verdicts.append(v)
        admitted: List[TriggerVerdict] = []
        now = self._clock()
        for v in verdicts:
            limited = len(self.fired) >= self.max_incidents or (
                self._last_fire_t is not None
                and now - self._last_fire_t < self.cooldown_s
            )
            if limited or admitted:
                self.suppressed += 1
                continue
            self._last_fire_t = now
            self.fired.append(v)
            admitted.append(v)
        self._eval_s += time.perf_counter() - t_eval0
        return admitted

    # -- accounting --------------------------------------------------------

    def overhead_frac(self, capture_s: float = 0.0) -> float:
        """(evaluation + capture) time as a fraction of wall time since
        the engine was built — the number the <1%-overhead acceptance
        gate asserts on clean runs."""
        wall = max(self._clock() - self._t0, 1e-9)
        return (self._eval_s + capture_s) / wall

    def summary(self, capture_s: float = 0.0) -> dict:
        """Flight-record-ready trigger block for ``run_end``."""
        return {
            "rules": [r.name for r in self.rules],
            "fired": len(self.fired),
            "suppressed": self.suppressed,
            "incidents": [v.rule for v in self.fired],
            "overhead_frac": round(self.overhead_frac(capture_s), 6),
        }


# ---------------------------------------------------------------------------
# incident bundles
# ---------------------------------------------------------------------------


def _atomic_json(path: str, data) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def _chip_hygiene_report() -> dict:
    """``tools/chip_hygiene.py`` report, loaded standalone from the
    repo checkout; degrades to ``{"available": False}`` outside one
    (installed package, stripped tree) rather than failing a capture."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(os.path.dirname(os.path.dirname(here)), "tools", "chip_hygiene.py")
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location("_incident_chip_hygiene", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        report = mod.find_chip_holders()
        report["available"] = True
        return report
    except Exception as exc:
        return {"available": False, "error": str(exc)[:200]}


class Incident:
    """One open incident: sidecars written at open, a bounded profiler
    capture driven by :meth:`tick`, and ``incident_manifest.json``
    written LAST at :meth:`close` — a bundle without a manifest IS the
    crashed-mid-capture signature, and stays readable as such."""

    def __init__(
        self,
        incident_id: str,
        bundle_dir: str,
        verdict: TriggerVerdict,
        profile_steps: int,
        profile_s: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.id = incident_id
        self.dir = bundle_dir
        self.verdict = verdict
        self.profile_dir = os.path.join(bundle_dir, "profile")
        self.profile_steps = max(1, int(profile_steps))
        self.profile_s = float(profile_s)
        self._clock = clock
        self._t_open = clock()
        self._t_capture0: Optional[float] = None
        self._capturing = False
        self._capture_attempted = False
        self.steps = 0
        self.capture_s = 0.0
        self.closed = False
        self.files: Dict[str, str] = {}

    # -- sidecars ----------------------------------------------------------

    def write_sidecars(self, registry=None, flight_path: Optional[str] = None,
                       tail_lines: int = 100, podview=None) -> None:
        _atomic_json(os.path.join(self.dir, "trigger.json"), self.verdict.to_dict())
        self.files["trigger"] = "trigger.json"
        if registry is not None:
            try:
                _atomic_json(
                    os.path.join(self.dir, "metrics.json"), registry.snapshot()
                )
                self.files["metrics"] = "metrics.json"
            except Exception:
                pass
        if flight_path and os.path.exists(flight_path):
            try:
                with open(flight_path) as f:
                    lines = f.read().splitlines()
                tail = "\n".join(lines[-tail_lines:])
                with open(os.path.join(self.dir, "flight_tail.jsonl"), "w") as f:
                    f.write(tail + ("\n" if tail else ""))
                self.files["flight_tail"] = "flight_tail.jsonl"
            except OSError:
                pass
        if podview is not None:
            # pod-visibility evidence (obs/podview.py SkewMonitor): the
            # skew report naming the offending host, plus every OTHER
            # host shard's tail (rank 0's tail is flight_tail.jsonl)
            try:
                _atomic_json(
                    os.path.join(self.dir, "podview_report.json"),
                    podview.report(),
                )
                self.files["podview_report"] = "podview_report.json"
                for h, lines in sorted(podview.shard_tails(tail_lines).items()):
                    if h == 0:
                        continue
                    name = f"flight_tail.host{h}.jsonl"
                    with open(os.path.join(self.dir, name), "w") as f:
                        f.write("\n".join(lines) + ("\n" if lines else ""))
                    self.files[f"flight_tail_host{h}"] = name
            except Exception:
                pass  # evidence capture must never fail the incident
        _atomic_json(
            os.path.join(self.dir, "chip_hygiene.json"), _chip_hygiene_report()
        )
        self.files["chip_hygiene"] = "chip_hygiene.json"
        from hydragnn_tpu.obs.introspect import device_memory_stats

        try:
            mem = device_memory_stats()
        except Exception:
            mem = {"available": False}
        _atomic_json(os.path.join(self.dir, "memory.json"), mem)
        self.files["memory"] = "memory.json"

    # -- bounded profiler capture ------------------------------------------

    def tick(self) -> bool:
        """Drive the capture: the first tick starts a profiler trace
        into the bundle's ``profile/``; the capture stops after
        ``profile_steps`` ticks or ``profile_s`` seconds, whichever
        first. Returns True while the incident wants more ticks."""
        from hydragnn_tpu.utils import profile

        if self.closed:
            return False
        if not self._capture_attempted:
            self._capture_attempted = True
            # refused when another capture (epoch profiler, earlier
            # incident) holds the single process-wide jax trace slot
            self._capturing = profile.try_start_capture(self.profile_dir)
            self._t_capture0 = self._clock()
        self.steps += 1
        elapsed = (
            self._clock() - self._t_capture0 if self._t_capture0 is not None else 0.0
        )
        if self.steps >= self.profile_steps or elapsed >= self.profile_s:
            self._stop_capture()
            return False
        return True

    def _stop_capture(self) -> None:
        from hydragnn_tpu.utils import profile

        if self._capturing:
            try:
                profile.stop_capture()
            except Exception:
                pass
            self._capturing = False
            if self._t_capture0 is not None:
                self.capture_s = self._clock() - self._t_capture0

    def profile_nonempty(self) -> bool:
        for _root, _dirs, files in os.walk(self.profile_dir):
            if files:
                return True
        return False

    # -- close -------------------------------------------------------------

    def close(self, status: str = "ok") -> dict:
        """Finalize: stop any live capture and write the manifest LAST
        (atomic). Idempotent — the first close wins."""
        if self.closed:
            return {}
        self._stop_capture()
        self.closed = True
        manifest = {
            "schema_version": INCIDENT_MANIFEST_VERSION,
            "id": self.id,
            "rule": self.verdict.rule,
            "kind": self.verdict.kind,
            "status": status,
            "trigger": self.verdict.to_dict(),
            "files": dict(self.files),
            "profile": {
                "captured": self._capture_attempted and os.path.isdir(self.profile_dir),
                "steps": self.steps,
                "duration_s": round(self.capture_s, 3),
                "nonempty": self.profile_nonempty(),
            },
        }
        _atomic_json(os.path.join(self.dir, INCIDENT_MANIFEST), manifest)
        return manifest


class IncidentRecorder:
    """Bundle writer for one run: owns the ``incidents/`` directory,
    enforces the capture overhead budget, and keeps at most ONE
    incident open (the profiler has one trace slot; a second verdict
    during a capture is suppressed by the engine's rate limiter)."""

    def __init__(
        self,
        root: str,
        registry=None,
        flight_path: Optional[str] = None,
        profile_steps: Optional[int] = None,
        profile_s: Optional[float] = None,
        overhead_frac: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        on_close: Optional[Callable[[Incident, str], None]] = None,
        podview=None,
    ):
        self.root = root
        self.registry = registry
        self.flight_path = flight_path
        # optional obs/podview.SkewMonitor: every bundle then carries
        # podview_report.json + all host shards' tails as evidence
        self.podview = podview
        # called AFTER each incident closes (outside the lock) with
        # (incident, status) — the server uses it to release spool-shard
        # pins held for the incident's drift evidence
        self.on_close = on_close
        if profile_steps is None:
            profile_steps = knobs.get_int("HYDRAGNN_INCIDENT_PROFILE_STEPS", 3)
        if profile_s is None:
            profile_s = knobs.get_float("HYDRAGNN_INCIDENT_PROFILE_S", 10.0)
        if overhead_frac is None:
            overhead_frac = (
                knobs.get_float("HYDRAGNN_INCIDENT_OVERHEAD_PCT", 5.0) / 100.0
            )
        self.profile_steps = int(profile_steps)
        self.profile_s = float(profile_s)
        self.overhead_frac = float(overhead_frac)
        self._clock = clock
        self._t0 = clock()
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "triggers.IncidentRecorder._lock"
        )
        self._seq = 0  # graftsync: guarded-by=triggers.IncidentRecorder._lock
        # graftsync: guarded-by=triggers.IncidentRecorder._lock
        self._open: Optional[Incident] = None
        self.capture_s = 0.0  # graftsync: guarded-by=triggers.IncidentRecorder._lock
        self.suppressed_budget = 0  # graftsync: guarded-by=triggers.IncidentRecorder._lock
        # graftsync: guarded-by=triggers.IncidentRecorder._lock
        self.closed_ids: List[str] = []

    # graftsync: holds=triggers.IncidentRecorder._lock
    def _budget_exhausted(self) -> bool:
        # charges capture time already SPENT against wall time, so the
        # first capture of a run is always admitted (a short CI run must
        # still capture its one planned incident) and repeat captures
        # are throttled to the budgeted fraction thereafter
        wall = max(self._clock() - self._t0, 1e-9)
        return self.capture_s / wall > self.overhead_frac

    def open_incident(self, verdict: TriggerVerdict, flight=None) -> Optional[Incident]:
        """Open a bundle for a verdict; returns None (and counts a
        budget suppression) when a capture is already open or the
        overhead budget is spent. The ``incident`` flight event is
        recorded at OPEN so even a crash mid-capture leaves the
        pointer in the run's event log."""
        with self._lock:
            if self._open is not None:
                return None
            if self._budget_exhausted():
                self.suppressed_budget += 1
                return None
            self._seq += 1
            iid = f"i{self._seq:03d}-{verdict.rule}"
            bundle = os.path.join(self.root, iid)
            try:
                os.makedirs(bundle, exist_ok=True)
            except OSError:
                return None
            inc = Incident(
                iid, bundle, verdict, self.profile_steps, self.profile_s,
                clock=self._clock,
            )
            self._open = inc
        inc.write_sidecars(
            registry=self.registry,
            flight_path=self.flight_path,
            podview=self.podview,
        )
        if flight is not None:
            flight.record("incident", id=iid, rule=verdict.rule, path=bundle)
        return inc

    @property
    def open(self) -> Optional[Incident]:
        with self._lock:
            return self._open

    def tick(self) -> None:
        """Call once per unit of work (train step, serve batch): drives
        the open incident's capture and closes it when bounded."""
        inc = self.open
        if inc is None:
            return
        if not inc.tick():
            self._close(inc, "ok")

    def _close(self, inc: Incident, status: str) -> None:
        inc.close(status)
        with self._lock:
            self.capture_s += inc.capture_s
            self.closed_ids.append(inc.id)
            if self._open is inc:
                self._open = None
        if self.on_close is not None:
            try:
                self.on_close(inc, status)
            except Exception:
                pass  # a cleanup hook must never fail a close

    def finalize(self) -> None:
        """Run teardown (clean or crashed): close any open incident so
        its capture is stopped and its manifest written."""
        inc = self.open
        if inc is not None:
            self._close(inc, "truncated")


# ---------------------------------------------------------------------------
# bundle validation (runtime + tools; the lint-side schema lives in
# lint/artifacts.py so `graftlint --artifacts` stays jax-free)
# ---------------------------------------------------------------------------


def validate_incident_manifest(data: Any) -> List[str]:
    """Schema-check one parsed manifest; returns problems (empty = ok)."""
    if not isinstance(data, dict):
        return [f"expected a JSON object, got {type(data).__name__}"]
    problems: List[str] = []
    for field, types in (
        ("schema_version", (int,)),
        ("id", (str,)),
        ("rule", (str,)),
        ("kind", (str,)),
        ("status", (str,)),
        ("trigger", (dict,)),
        ("files", (dict,)),
        ("profile", (dict,)),
    ):
        if field not in data:
            problems.append(f"missing required field '{field}'")
        elif not isinstance(data[field], types):
            problems.append(
                f"field '{field}' is {type(data[field]).__name__}, expected "
                + "/".join(t.__name__ for t in types)
            )
    if not problems:
        trig = data["trigger"]
        for field in ("rule", "kind", "observed", "threshold"):
            if field not in trig:
                problems.append(f"trigger missing field '{field}'")
        prof = data["profile"]
        for field in ("captured", "steps", "duration_s", "nonempty"):
            if field not in prof:
                problems.append(f"profile missing field '{field}'")
        if data.get("kind") not in RULE_KINDS:
            problems.append(f"unknown rule kind {data.get('kind')!r}")
    return problems


def validate_incident_bundle(bundle_dir: str) -> List[str]:
    """Validate one on-disk bundle: manifest schema plus existence of
    every file the manifest claims. A missing manifest is reported as
    exactly that (the crashed-mid-write case), not a parse explosion."""
    manifest_path = os.path.join(bundle_dir, INCIDENT_MANIFEST)
    if not os.path.exists(manifest_path):
        return ["manifest missing (run crashed mid-incident-write?)"]
    try:
        with open(manifest_path) as f:
            data = json.load(f)
    except (OSError, ValueError) as exc:
        return [f"unreadable manifest: {exc}"]
    problems = validate_incident_manifest(data)
    for label, rel in (data.get("files") or {}).items():
        if not isinstance(rel, str) or not os.path.exists(
            os.path.join(bundle_dir, rel)
        ):
            problems.append(f"files.{label} -> {rel!r} does not exist in bundle")
    prof = data.get("profile") or {}
    if prof.get("nonempty"):
        pdir = os.path.join(bundle_dir, "profile")
        has_file = any(files for _r, _d, files in os.walk(pdir))
        if not has_file:
            problems.append("manifest claims non-empty profile but profile/ is empty")
    return problems


def list_incidents(incidents_root: str) -> List[str]:
    """Bundle dirs under a run's ``incidents/`` root, sorted by id."""
    if not os.path.isdir(incidents_root):
        return []
    return sorted(
        os.path.join(incidents_root, name)
        for name in os.listdir(incidents_root)
        if os.path.isdir(os.path.join(incidents_root, name))
    )
