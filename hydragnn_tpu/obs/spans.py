"""Host-side span tracing: where does a train step's wall time go?

Decomposes each epoch's steps into the three places wall time hides:

  - **data-wait** — time the consumer blocks on the loader (host
    batching + H2D that the prefetch thread failed to hide);
  - **host-dispatch** — time inside the jitted call before it returns
    (async: tracing/arg handling; on step 0 this includes the compile);
  - **device-execute** — sampled: for a small window of steps per epoch
    the step's outputs are ``block_until_ready``-ed and the extra wait
    beyond dispatch is recorded. Only the window pays the sync; every
    steady-state step stays fully async, so instrumented training keeps
    the device-sync discipline the train loop documents.

This makes the "wall is 6.7x device time" class of gap (VERDICT r05
Weak #4) a measured, per-epoch number: ``epoch_snapshot`` feeds the
flight recorder (``hydragnn_tpu/obs/flight.py``) and tensorboard.
Sampled steps are wrapped in a ``jax.profiler`` trace annotation
("obs.sampled_sync_step") so they are identifiable in XProf timelines
captured by ``utils/profile.py:Profiler``.

Caveat carried over from bench.py: on tunneled dev chips
``block_until_ready`` returns at dispatch-ack, not device completion —
there the device-execute sample is a lower bound (the flight record's
manifest carries the backend so a reader can judge).
"""

from __future__ import annotations

import time
from typing import Any, Iterable, Iterator, Optional


class StepSpans:
    """Per-epoch span accumulator for the per-step training path.

    Usage (the train loop's shape):

        spans.epoch_start(epoch)
        for batch in spans.timed_iter(loader):
            out = spans.step(train_step, state, batch)
        record = spans.epoch_snapshot()

    ``sample_steps`` steps per epoch (after ``skip_first``, which skips
    the compile step) are synchronously fenced to sample device time.
    Use :meth:`disabled` for the inert variant — ``timed_iter`` returns
    its argument unchanged and ``step`` is a direct call, so the off
    path adds no per-step timing syscalls or allocations.
    """

    def __init__(self, sample_steps: int = 3, skip_first: int = 1, tracer=None):
        self.sample_steps = sample_steps
        self.skip_first = skip_first
        self.enabled = True
        self.epoch = -1
        # optional obs/trace.py Tracer: each sampled sync step is also
        # emitted as a one-span trace keyed (epoch, step), joining the
        # train timeline with serve request traces
        self.tracer = tracer
        # deterministic straggler injection (HYDRAGNN_INJECT_STRAGGLER=
        # "HOST:MS"): when this process IS the named podview host, every
        # step sleeps MS — inflating its host_epoch summary so the
        # rank-0 SkewMonitor's step_skew rule has a real signal
        self._straggle_s = 0.0
        from hydragnn_tpu.obs import podview

        spec = podview.straggler_spec()
        if spec is not None and spec[0] == podview.host_identity()[0]:
            self._straggle_s = spec[1]
        # (process_index, process_count) stamped into epoch snapshots;
        # resolved lazily so construction never forces backend init
        self._host_identity: Optional[tuple] = None
        self._reset()

    @staticmethod
    def disabled() -> "_NullSpans":
        return _NULL_SPANS

    def _reset(self) -> None:
        self.steps = 0
        self.data_wait_s = 0.0
        self.dispatch_s = 0.0
        self.first_step_s = 0.0
        self.sampled = 0
        self.device_wait_s = 0.0
        self.sync_step_s = 0.0

    def epoch_start(self, epoch: int) -> None:
        self.epoch = epoch
        self._reset()

    # -- recording ---------------------------------------------------------

    def timed_iter(self, iterable: Iterable) -> Iterator:
        """Yield from ``iterable``, accumulating the time this consumer
        spends blocked waiting for the next batch."""
        it = iter(iterable)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            self.data_wait_s += time.perf_counter() - t0
            yield item

    def step(self, fn, *args) -> Any:
        """Run one train step, recording dispatch time; inside the
        sampling window, fence the outputs and record device wait."""
        t0 = time.perf_counter()
        if self._straggle_s:
            time.sleep(self._straggle_s)
        sampling = (
            self.skip_first <= self.steps < self.skip_first + self.sample_steps
        )
        if sampling:
            from hydragnn_tpu.utils.profile import capture_active

            # a live profiler capture (incident or epoch-gated) must
            # see the step as it actually runs: the sync fence would
            # serialize the very window being profiled, so the sample
            # is skipped outright, not deferred
            sampling = not capture_active()
        if sampling:
            import jax

            from hydragnn_tpu.utils.profile import trace_annotation

            with trace_annotation("obs.sampled_sync_step"):
                out = fn(*args)
                t1 = time.perf_counter()
                jax.block_until_ready(out)
            t2 = time.perf_counter()
            self.dispatch_s += t1 - t0
            self.device_wait_s += t2 - t1
            self.sync_step_s += t2 - t0
            self.sampled += 1
            if self.tracer is not None:
                tr = self.tracer.begin(seq=self.steps, epoch=self.epoch)
                if tr is not None:
                    now = time.time()
                    tr.add_span(
                        "train.sampled_step",
                        now - (t2 - t0),
                        now,
                        epoch=self.epoch,
                        step=self.steps,
                        dispatch_ms=round((t1 - t0) * 1e3, 3),
                        device_wait_ms=round((t2 - t1) * 1e3, 3),
                    )
                    self.tracer.finish(tr)
        else:
            out = fn(*args)
            dt = time.perf_counter() - t0
            self.dispatch_s += dt
            if self.steps == 0:
                self.first_step_s = dt  # includes trace + compile
        self.steps += 1
        return out

    # -- export ------------------------------------------------------------

    def epoch_snapshot(self) -> dict:
        """One epoch's breakdown, flight-record-ready. Millisecond
        per-step means; seconds for the epoch totals."""
        sampled = max(self.sampled, 1) if self.sampled else 0
        if self._host_identity is None:
            from hydragnn_tpu.obs import podview

            self._host_identity = podview.host_identity()
        return {
            "steps": self.steps,
            "process_index": self._host_identity[0],
            "process_count": self._host_identity[1],
            "data_wait_s": round(self.data_wait_s, 6),
            "dispatch_s": round(self.dispatch_s, 6),
            "first_step_s": round(self.first_step_s, 6),
            "sampled_steps": self.sampled,
            "device_wait_ms_mean": (
                round(self.device_wait_s / sampled * 1e3, 3) if sampled else None
            ),
            "sync_step_ms_mean": (
                round(self.sync_step_s / sampled * 1e3, 3) if sampled else None
            ),
        }


class _NullSpans(StepSpans):
    """Telemetry-off spans: structurally a StepSpans (callers need no
    gate) but every hook is free — ``timed_iter`` IS the identity and
    ``step`` a direct call, pinned by tests/test_obs.py."""

    def __init__(self):
        super().__init__(sample_steps=0)
        self.enabled = False

    def epoch_start(self, epoch: int) -> None:
        self.epoch = epoch

    def timed_iter(self, iterable: Iterable) -> Iterable:
        return iterable

    def step(self, fn, *args) -> Any:
        return fn(*args)

    def epoch_snapshot(self) -> Optional[dict]:
        return None


_NULL_SPANS = _NullSpans()
