"""Sampled served-request spool in HGC container format.

Every Nth admitted-and-answered request is captured — featurized
inputs, per-head predictions, trace ID, tenant, model fingerprint, and
timestamp — and appended to rotating HGC container shards
(:mod:`hydragnn_tpu.data.container`).  Because a shard IS a container,
it re-enters ``data/loader.py`` unchanged: predictions are stored as
``gt_<head>`` / ``nt_<head>`` target fields, so a spooled shard loads
as a *labelled* dataset (predictions as pseudo-labels) — exactly the
stream the continual-learning loop (ROADMAP item 4) fine-tunes and the
drift tools replay.  Loader-side ``edge_occupancy`` stamping is
preserved for the skip fast path because the input arrays round-trip
bit-exactly through the same writer direct featurization uses.

Durability story:
  - **atomic finalization** — a shard is written into a dot-prefixed
    temp dir and ``os.replace``'d to its final ``shard-NNNNNN`` name;
    a crash mid-write leaves only a dot-dir that every reader skips
    and the next spool construction sweeps;
  - **bounded disk** — shards rotate at ``shard_mb`` of buffered
    payload and the oldest finalized shards are LRU-evicted once the
    spool exceeds ``max_mb``;
  - **flight evidence** — every rotation emits a ``spool_rotate``
    event (shard name, samples, bytes, evictions) so the flight
    record narrates spool churn.

Thread-safety: offers arrive on the server's dispatch thread(s) and
``finalize()`` on the stopping thread — one lock guards all mutable
state (graftsync-annotated below).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

from hydragnn_tpu.utils import syncdebug

# NOTE: hydragnn_tpu.data is imported lazily inside the functions that
# need it — the obs package must stay importable without pulling the
# (jax-heavy) data/graph stack into every telemetry consumer.

SPOOL_SCHEMA = 1
SHARD_PREFIX = "shard-"
SHARD_MANIFEST = "spool_manifest.json"


def _entry_to_sample(
    g: Mapping[str, Any],
    result: Mapping[str, np.ndarray],
    head_kinds: Mapping[str, str],
    meta: Dict[str, Any],
):
    """Reassemble a request dict + sliced result into a GraphSample the
    container writer serializes exactly like direct featurization (the
    writer owns all dtype normalization, so both paths agree bit-for-
    bit on x/pos/edge_index/edge_attr)."""
    from hydragnn_tpu.data.dataset import GraphSample

    graph_targets: Dict[str, np.ndarray] = {}
    node_targets: Dict[str, np.ndarray] = {}
    for name, arr in result.items():
        a = np.asarray(arr)
        if head_kinds.get(name, "graph") == "graph":
            graph_targets[name] = a.reshape(-1)
        else:
            node_targets[name] = a if a.ndim > 1 else a.reshape(-1, 1)
    return GraphSample(
        x=np.asarray(g["x"]),
        pos=np.asarray(g["pos"]) if g.get("pos") is not None else None,
        edge_index=np.stack(
            [np.asarray(g["senders"]), np.asarray(g["receivers"])]
        ),
        edge_attr=(
            np.asarray(g["edge_attr"]) if g.get("edge_attr") is not None else None
        ),
        graph_targets=graph_targets,
        node_targets=node_targets,
        meta=meta,
    )


def _entry_bytes(sample) -> int:
    total = sample.x.nbytes
    for arr in (sample.pos, sample.edge_index, sample.edge_attr):
        if arr is not None:
            total += np.asarray(arr).nbytes
    for d in (sample.graph_targets, sample.node_targets):
        for v in d.values():
            total += np.asarray(v).nbytes
    total += len(json.dumps(sample.meta)) if sample.meta else 0
    return total


class RequestSpool:
    """Rotating, sampled, size-bounded HGC spool for one server."""

    def __init__(
        self,
        root: str,
        *,
        sample_every: int = 8,
        max_mb: float = 64.0,
        shard_mb: float = 1.0,
        model_fingerprint: str = "",
        head_kinds: Optional[Mapping[str, str]] = None,
        flight=None,
    ):
        if sample_every < 1:
            raise ValueError(f"sample_every must be >= 1, got {sample_every}")
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.sample_every = int(sample_every)
        self.max_bytes = int(max(0.001, float(max_mb)) * 1024 * 1024)
        self.shard_bytes = int(max(0.01, float(shard_mb)) * 1024 * 1024)
        self.model_fingerprint = model_fingerprint
        self.head_kinds = dict(head_kinds or {})
        self.flight = flight
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "spool.RequestSpool._lock"
        )
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._seen = 0
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._pending: List[Any] = []
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._pending_bytes = 0
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._spooled = 0
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._rotations = 0
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._evicted = 0
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._overhead_s = 0.0
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._last_window: Dict[str, Any] = {}
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._pins: Dict[str, int] = {}
        # crash sweep: an interrupted finalization leaves a dot-dir; no
        # reader consumes those, so reclaim the space up front
        for name in os.listdir(self.root):
            if name.startswith("."):
                shutil.rmtree(os.path.join(self.root, name), ignore_errors=True)
        # graftsync: guarded-by=spool.RequestSpool._lock
        self._next_shard = 1 + max(
            [int(n[len(SHARD_PREFIX):]) for n in self._shard_names()] or [0]
        )

    # -- ingest (dispatch thread) -------------------------------------------

    def offer(
        self,
        g: Mapping[str, Any],
        result: Mapping[str, np.ndarray],
        *,
        trace: Optional[str] = None,
        tenant: str = "default",
        seq: int = -1,
    ) -> bool:
        """Consider one answered request; spool it if it is the Nth.
        Returns whether the request was captured."""
        t0 = time.perf_counter()
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self.sample_every != 0:
                return False
            sample = _entry_to_sample(
                g,
                result,
                self.head_kinds,
                {
                    "spool": {
                        "schema": SPOOL_SCHEMA,
                        "trace": trace,
                        "tenant": tenant,
                        "seq": int(seq),
                        "t": time.time(),
                        "model_fingerprint": self.model_fingerprint,
                    }
                },
            )
            self._pending.append(sample)
            self._pending_bytes += _entry_bytes(sample)
            self._spooled += 1
            if self._pending_bytes >= self.shard_bytes:
                self._rotate_locked()
            self._overhead_s += time.perf_counter() - t0
        return True

    # -- rotation / retention ------------------------------------------------

    def _shard_names(self) -> List[str]:
        return sorted(
            n
            for n in os.listdir(self.root)
            if n.startswith(SHARD_PREFIX)
            and os.path.isdir(os.path.join(self.root, n))
        )

    def _shard_size(self, name: str) -> int:
        d = os.path.join(self.root, name)
        return sum(
            os.path.getsize(os.path.join(d, f))
            for f in os.listdir(d)
            if os.path.isfile(os.path.join(d, f))
        )

    # graftsync: holds=spool.RequestSpool._lock
    def _rotate_locked(self) -> Optional[str]:
        """Finalize the pending buffer as one shard, atomically, then
        LRU-evict past the disk bound. Caller holds the lock."""
        if not self._pending:
            return None
        from hydragnn_tpu.data.container import ContainerWriter

        name = f"{SHARD_PREFIX}{self._next_shard:06d}"
        self._next_shard += 1
        tmp = os.path.join(self.root, f".{name}.tmp-{os.getpid()}")
        writer = ContainerWriter(tmp)
        writer.add(self._pending)
        writer.add_global("spool_schema", SPOOL_SCHEMA)
        writer.add_global("model_fingerprint", self.model_fingerprint)
        writer.add_global("sample_every", self.sample_every)
        writer.save()
        entries = self._pending
        manifest = {
            "schema": SPOOL_SCHEMA,
            "shard": name,
            "num_samples": len(entries),
            "model_fingerprint": self.model_fingerprint,
            "sample_every": self.sample_every,
            "tenants": sorted(
                {s.meta["spool"]["tenant"] for s in entries}
            ),
            "seq_range": [
                min(s.meta["spool"]["seq"] for s in entries),
                max(s.meta["spool"]["seq"] for s in entries),
            ],
            "t_range": [
                min(s.meta["spool"]["t"] for s in entries),
                max(s.meta["spool"]["t"] for s in entries),
            ],
            "traces": [s.meta["spool"]["trace"] for s in entries],
        }
        with open(os.path.join(tmp, SHARD_MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1)
        final = os.path.join(self.root, name)
        os.replace(tmp, final)  # atomic: readers only ever see whole shards
        self._pending = []
        self._pending_bytes = 0
        self._rotations += 1

        shards = self._shard_names()
        sizes = {n: self._shard_size(n) for n in shards}
        evicted = []
        # Eviction candidates: everything but the newest shard, minus
        # pinned shards (an open drift incident or a running retrain
        # holds a reference — evicting under it would dangle the
        # bundle's spool pointer / the fine-tune's input set).
        evictable = [
            n for n in shards[:-1] if self._pins.get(n, 0) == 0
        ]
        while evictable and sum(sizes.values()) > self.max_bytes:
            oldest = evictable.pop(0)  # LRU == lowest shard number
            shards.remove(oldest)
            shutil.rmtree(os.path.join(self.root, oldest), ignore_errors=True)
            sizes.pop(oldest)
            evicted.append(oldest)
            self._evicted += 1
        self._last_window = {
            "dir": self.root,
            "shards": shards[-4:],
            "last_shard": name if name in shards else shards[-1] if shards else None,
            "seq_range": manifest["seq_range"],
            "tenants": manifest["tenants"],
        }
        if self.flight is not None:
            self.flight.record(
                "spool_rotate",
                shard=name,
                samples=len(entries),
                bytes=sizes.get(name, 0),
                total_bytes=sum(sizes.values()),
                shards=len(shards),
                evicted=evicted,
            )
        return name

    # -- lifecycle -----------------------------------------------------------

    def flush_pending(self) -> Optional[str]:
        """Finalize whatever is buffered as a (possibly small) shard.
        (Not named ``flush``: file-object ``.flush()`` calls under other
        locks would alias it in graftsync's name-based order graph.)"""
        with self._lock:
            return self._rotate_locked()

    def finalize(self) -> Dict[str, Any]:
        """Flush and return the summary block stamped into run_end."""
        with self._lock:
            self._rotate_locked()
            shards = self._shard_names()
            total = sum(self._shard_size(n) for n in shards)
            return {
                "dir": self.root,
                "seen": self._seen,
                "spooled": self._spooled,
                "sample_every": self.sample_every,
                "shards": len(shards),
                "rotations": self._rotations,
                "evicted": self._evicted,
                "bytes": total,
                "pinned": len(self._pins),
                "overhead_s": round(self._overhead_s, 6),
            }

    # -- pinning -------------------------------------------------------------

    def pin(self, shards: Sequence[str]) -> List[str]:
        """Ref-count-pin shards against LRU eviction.  Accepts shard
        basenames or paths; returns the basenames actually pinned
        (shards that no longer exist are skipped, not errors — the
        caller learns what survives).  Each ``pin`` must be balanced by
        one ``unpin`` of the returned names."""
        with self._lock:
            existing = set(self._shard_names())
            pinned = []
            for s in shards:
                name = os.path.basename(os.path.normpath(str(s)))
                if name in existing:
                    self._pins[name] = self._pins.get(name, 0) + 1
                    pinned.append(name)
            return pinned

    def unpin(self, shards: Sequence[str]) -> None:
        """Release one pin reference per shard; eviction resumes once a
        shard's count reaches zero.  Over-unpinning is a no-op."""
        with self._lock:
            for s in shards:
                name = os.path.basename(os.path.normpath(str(s)))
                n = self._pins.get(name, 0)
                if n <= 1:
                    self._pins.pop(name, None)
                else:
                    self._pins[name] = n - 1

    def pinned(self) -> Dict[str, int]:
        """Current pin counts by shard basename (copy)."""
        with self._lock:
            return dict(self._pins)

    # -- introspection -------------------------------------------------------

    @property
    def overhead_s(self) -> float:
        with self._lock:
            return self._overhead_s

    def window(self) -> Dict[str, Any]:
        """Pointer to the most recent spool window — attached to drift
        incidents so the bundle says WHERE the offending traffic is."""
        with self._lock:
            if self._last_window:
                return dict(self._last_window)
            return {
                "dir": self.root,
                "shards": self._shard_names()[-4:],
                "pending": len(self._pending),
            }


# -- readers -----------------------------------------------------------------


def list_shards(root: str) -> List[str]:
    """Finalized shard directories under a spool root, oldest first
    (dot-prefixed in-progress/crashed temp dirs are invisible)."""
    if not os.path.isdir(root):
        return []
    return [
        os.path.join(root, n)
        for n in sorted(os.listdir(root))
        if n.startswith(SHARD_PREFIX) and os.path.isdir(os.path.join(root, n))
    ]


def read_spool(root: str) -> List[Any]:
    """Load every spooled sample (oldest shard first) back through the
    standard container reader — the loader round-trip in one call."""
    from hydragnn_tpu.data.container import ContainerDataset

    out: List[Any] = []
    for shard in list_shards(root):
        out.extend(ContainerDataset(shard).samples())
    return out


def read_shard_manifest(shard_dir: str) -> Dict[str, Any]:
    with open(os.path.join(shard_dir, SHARD_MANIFEST)) as f:
        return json.load(f)


def validate_spool_manifest(manifest: Mapping[str, Any]) -> List[str]:
    """Schema check for a shard's ``spool_manifest.json`` (lint gate +
    ``tools/drift_report.py --validate``); returns problems."""
    problems: List[str] = []
    if int(manifest.get("schema", -1)) != SPOOL_SCHEMA:
        problems.append(
            f"spool manifest schema {manifest.get('schema')!r} != {SPOOL_SCHEMA}"
        )
    for key in ("shard", "num_samples", "model_fingerprint", "sample_every",
                "tenants", "seq_range", "t_range"):
        if key not in manifest:
            problems.append(f"spool manifest missing key {key!r}")
    if "num_samples" in manifest and int(manifest["num_samples"]) < 1:
        problems.append("spool manifest num_samples < 1")
    seq_range = manifest.get("seq_range")
    if isinstance(seq_range, (list, tuple)) and len(seq_range) != 2:
        problems.append("spool manifest seq_range is not a [lo, hi] pair")
    return problems
