"""Compile-event monitor: count XLA compiles as they happen.

Serving already proves "zero unexpected recompiles" because its bucket
cache counts compiles explicitly; training had no equivalent — a
silently recompiling train step (shape drift, weak-type flip, donation
mismatch) just reads as a mysteriously slow epoch. This hooks
``jax.monitoring``'s duration-event stream, on which jax records every
backend compile (``/jax/core/compile/backend_compile_duration``), so
the train loop can record per-epoch compile counts in the flight
record and assert "no recompile after step 1" the way serving does.

jax has no listener-unregister API in all supported versions, so ONE
process-wide dispatcher is registered lazily and forwards to whatever
monitors are currently active — starting/stopping a monitor never
mutates jax's listener list. On jax builds without ``jax.monitoring``
(or without the duration-listener hook) the monitor degrades to
``available=False``: counts stay 0 and callers treat the assertion as
unavailable rather than vacuously true; the fallback state is recorded
into the metrics registry so a flight record never silently claims
"0 compiles" from a monitor that could not listen.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from hydragnn_tpu.utils import syncdebug

# the event jax's dispatch layer records around every backend compile
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# graftsync: guarded-by=compile_monitor._active_lock
_active: List["CompileMonitor"] = []
_active_lock = syncdebug.maybe_wrap(
    threading.Lock(), "compile_monitor._active_lock"
)
_dispatcher_registered = False  # graftsync: guarded-by=compile_monitor._active_lock


def _dispatch(event: str, duration_secs: float, **kwargs) -> None:
    with _active_lock:
        monitors = list(_active)
    for m in monitors:
        m._on_event(event, duration_secs)


def _monitoring_available() -> bool:
    try:
        import jax.monitoring as mon

        return hasattr(mon, "register_event_duration_secs_listener")
    except Exception:
        return False


def _ensure_dispatcher() -> bool:
    global _dispatcher_registered
    # check-and-register under the lock: two monitors starting
    # concurrently must not both register the dispatcher, or every
    # compile would be counted twice forever (jax has no unregister)
    if not _monitoring_available():
        return False
    with _active_lock:
        if _dispatcher_registered:
            return True
        import jax.monitoring as mon

        mon.register_event_duration_secs_listener(_dispatch)
        _dispatcher_registered = True
        return True


class CompileMonitor:
    """Counts matching duration events while active.

    ``marks`` give windowed assertions: ``mark("warm")`` after the
    first step, then ``count_since("warm") == 0`` is the steady-state
    no-recompile contract. Use as a context manager or via
    start()/stop().
    """

    def __init__(
        self,
        events: Tuple[str, ...] = (BACKEND_COMPILE_EVENT,),
        registry=None,
    ):
        self._events = frozenset(events)
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "compile_monitor.CompileMonitor._lock"
        )
        self.count = 0  # graftsync: guarded-by=compile_monitor.CompileMonitor._lock
        self.total_duration_s = 0.0  # graftsync: guarded-by=compile_monitor.CompileMonitor._lock
        # graftsync: guarded-by=compile_monitor.CompileMonitor._lock
        self.records: List[Tuple[float, str, float]] = []  # (t, event, dur)
        # graftsync: guarded-by=compile_monitor.CompileMonitor._lock
        self._marks: Dict[str, int] = {}
        # graftsync: thread-safe=written only from the lifecycle-owning thread in start(); the dispatch thread only reads
        self.available = False
        # graftsync: thread-safe=written only from the lifecycle-owning thread in start()/stop()
        self._started = False
        if registry is not None:
            registry.gauge("obs.compile_monitor_available")
        self._registry = registry

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "CompileMonitor":
        if self._started:
            return self
        self.available = _ensure_dispatcher()
        if self.available:
            with _active_lock:
                _active.append(self)
        if self._registry is not None:
            self._registry.gauge("obs.compile_monitor_available").set(
                1 if self.available else 0
            )
        self._started = True
        return self

    def stop(self) -> None:
        if not self._started:
            return
        with _active_lock:
            if self in _active:
                _active.remove(self)
        self._started = False

    def __enter__(self) -> "CompileMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- event sink --------------------------------------------------------

    def _on_event(self, event: str, duration_secs: float) -> None:
        if event not in self._events:
            return
        with self._lock:
            self.count += 1
            self.total_duration_s += duration_secs
            self.records.append((time.time(), event, duration_secs))

    # -- windowed queries --------------------------------------------------

    def mark(self, name: str) -> int:
        """Snapshot the current count under ``name``; returns it."""
        with self._lock:
            self._marks[name] = self.count
            return self.count

    def count_since(self, name: str) -> int:
        with self._lock:
            return self.count - self._marks.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "available": self.available,
                "count": self.count,
                "total_duration_s": round(self.total_duration_s, 6),
            }
