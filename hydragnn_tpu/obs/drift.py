"""Online drift sketches over served traffic (stdlib + numpy only).

The serving stack traces latency and quarantines NaNs, but a model that
is confidently wrong on shifted inputs looks perfectly healthy.  This
module closes that blind spot with streaming sketches maintained from
the **already host-side** postprocess outputs — the dispatch thread
hands :class:`DriftMonitor` the same numpy arrays it slices per-request
results from, so the hot path performs ZERO extra device->host syncs
(HG001 clean by construction).

Sketches
--------
  - :class:`RunningMoments` — vectorised Welford/Chan batch merge:
    exact count/mean/variance per node-feature channel over every row
    observed, O(channels) state.
  - :class:`P2Quantile` — the classic Jain & Chlamtac P² streaming
    quantile estimator (5 markers, parabolic interpolation), O(1) per
    observation.  Applied to a bounded per-batch row subsample so a
    10k-node graph does not pay 10k sequential marker updates.
  - bucketed histograms with explicit under/overflow bins, so mass
    that leaves the reference support is *counted*, not silently
    dropped (``np.histogram`` alone would hide exactly the shift we
    are hunting).

Distances
---------
  - :func:`psi` — Population Stability Index between reference and
    current bin fractions (eps-clipped, renormalised).
  - quantile shift — max over probe quantiles of
    ``|cur_q - ref_q| / ref_std``.

The reference window is captured from the *training* run: the train
loop stamps :func:`build_reference` output into its flight manifest
(``run_start.manifest["stats"]``) and the server loads it back with
:func:`load_reference` (``HYDRAGNN_DRIFT_REF`` points at either the
training ``flight.jsonl`` or a bare stats JSON).

Where ground truth arrives after serving (labelled spool entries), the
error-drift track compares live MAE against the reference target scale
via :meth:`DriftMonitor.observe_labeled`.

Published gauges (``<prefix>.drift.*``) are read by the three drift
trigger kinds in :mod:`~hydragnn_tpu.obs.triggers`
(``feature_drift`` / ``pred_drift`` / ``error_drift``); gauges stay at
0.0 until ``min_count`` rows have been observed so a cold server never
fires on sketch noise.
"""

from __future__ import annotations

import bisect
import json
import math
import os
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

REFERENCE_SCHEMA = 1
DRIFT_REPORT_SCHEMA = 1

# Probe quantiles tracked by both the reference window and the live P²
# sketches; the quantile-shift distance compares them pairwise.
QUANTILE_PROBES = (0.05, 0.5, 0.95)

_EPS = 1e-4


class RunningMoments:
    """Exact streaming mean/variance per channel (Chan's parallel
    batch-merge of Welford), vectorised over a fixed channel count."""

    def __init__(self, num_channels: int):
        self.count = 0
        self.mean = np.zeros(num_channels, dtype=np.float64)
        self._m2 = np.zeros(num_channels, dtype=np.float64)

    def update(self, rows: np.ndarray) -> None:
        """Merge a batch of shape ``[n, channels]`` (or ``[n]`` for a
        single channel) into the running moments."""
        arr = np.asarray(rows, dtype=np.float64)
        if arr.ndim == 1:
            arr = arr[:, None]
        n = arr.shape[0]
        if n == 0:
            return
        mean_b = arr.mean(axis=0)
        m2_b = ((arr - mean_b) ** 2).sum(axis=0)
        if self.count == 0:
            self.count, self.mean, self._m2 = n, mean_b, m2_b
            return
        delta = mean_b - self.mean
        total = self.count + n
        self._m2 = self._m2 + m2_b + delta**2 * (self.count * n / total)
        self.mean = self.mean + delta * (n / total)
        self.count = total

    @property
    def variance(self) -> np.ndarray:
        if self.count < 2:
            return np.zeros_like(self.mean)
        return self._m2 / self.count

    @property
    def std(self) -> np.ndarray:
        return np.sqrt(self.variance)


class P2Quantile:
    """Jain & Chlamtac's P² estimator for one quantile of one stream.

    Five markers track (min, p/2, p, (1+p)/2, max); marker heights move
    by piecewise-parabolic interpolation as observations arrive.  Exact
    until 5 observations (sorted buffer), approximate after.
    """

    def __init__(self, p: float):
        if not 0.0 < p < 1.0:
            raise ValueError(f"quantile p must be in (0, 1), got {p}")
        self.p = float(p)
        self.count = 0
        self._q: List[float] = []  # marker heights
        self._n: List[float] = []  # marker positions (1-based)
        self._np: List[float] = []  # desired positions
        self._dn: List[float] = []  # desired-position increments

    def add(self, x: float) -> None:
        x = float(x)
        self.count += 1
        if self.count <= 5:
            bisect.insort(self._q, x)
            if self.count == 5:
                p = self.p
                self._n = [1.0, 2.0, 3.0, 4.0, 5.0]
                self._np = [1.0, 1 + 2 * p, 1 + 4 * p, 3 + 2 * p, 5.0]
                self._dn = [0.0, p / 2, p, (1 + p) / 2, 1.0]
            return
        q, n = self._q, self._n
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = 3
            for i in range(1, 5):
                if x < q[i]:
                    k = i - 1
                    break
        for i in range(k + 1, 5):
            n[i] += 1.0
        for i in range(5):
            self._np[i] += self._dn[i]
        for i in range(1, 4):
            d = self._np[i] - n[i]
            if (d >= 1.0 and n[i + 1] - n[i] > 1.0) or (
                d <= -1.0 and n[i - 1] - n[i] < -1.0
            ):
                sign = 1.0 if d > 0 else -1.0
                cand = self._parabolic(i, sign)
                if not q[i - 1] < cand < q[i + 1]:
                    cand = self._linear(i, sign)
                q[i] = cand
                n[i] += sign

    def _parabolic(self, i: int, d: float) -> float:
        q, n = self._q, self._n
        return q[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (q[i + 1] - q[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (q[i] - q[i - 1]) / (n[i] - n[i - 1])
        )

    def _linear(self, i: int, d: float) -> float:
        q, n = self._q, self._n
        j = i + int(d)
        return q[i] + d * (q[j] - q[i]) / (n[j] - n[i])

    @property
    def value(self) -> float:
        if self.count == 0:
            return float("nan")
        if self.count <= 5:
            s = self._q
            idx = self.p * (len(s) - 1)
            lo = int(math.floor(idx))
            hi = min(lo + 1, len(s) - 1)
            return s[lo] + (s[hi] - s[lo]) * (idx - lo)
        return self._q[2]


def hist_counts(values: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Histogram ``values`` against ``edges`` with explicit underflow /
    overflow bins: returns ``len(edges) + 1`` counts where ``[0]`` is
    mass below ``edges[0]`` and ``[-1]`` is mass strictly above
    ``edges[-1]``.  Shifted traffic that leaves the reference support
    lands in the outer bins instead of vanishing.  Values exactly at
    the top edge stay in the last inner bin (np.histogram's closed
    right edge) — the reference fracs were built with that convention,
    and discrete features routinely put real mass exactly at the
    reference max, so the two sides MUST agree bin-for-bin."""
    v = np.asarray(values, dtype=np.float64).ravel()
    e = np.asarray(edges, dtype=np.float64)
    inner, _ = np.histogram(v, bins=e)
    under = int((v < e[0]).sum())
    over = int((v > e[-1]).sum())
    return np.concatenate([[under], inner.astype(np.int64), [over]])


def psi(ref_fracs: Sequence[float], cur_fracs: Sequence[float], eps: float = _EPS) -> float:
    """Population Stability Index between two bin-fraction vectors of
    equal length.  Both sides are eps-clipped and renormalised, so
    empty bins contribute boundedly instead of producing infinities."""
    r = np.clip(np.asarray(ref_fracs, dtype=np.float64), eps, None)
    c = np.clip(np.asarray(cur_fracs, dtype=np.float64), eps, None)
    r = r / r.sum()
    c = c / c.sum()
    return float(np.sum((c - r) * np.log(c / r)))


def _padded_ref_fracs(fracs: Sequence[float]) -> np.ndarray:
    """Reference fractions extended with empty under/overflow bins to
    match :func:`hist_counts` layout."""
    f = np.asarray(fracs, dtype=np.float64)
    return np.concatenate([[0.0], f, [0.0]])


def _value_stats(
    values: np.ndarray, *, bins: int, quantiles: Sequence[float]
) -> Dict[str, Any]:
    v = np.asarray(values, dtype=np.float64).ravel()
    lo = float(v.min())
    hi = float(v.max())
    if not hi > lo:
        hi = lo + 1.0
    edges = np.linspace(lo, hi, bins + 1)
    counts, _ = np.histogram(v, bins=edges)
    total = max(1, int(counts.sum()))
    return {
        "mean": float(v.mean()),
        "std": float(v.std()),
        "quantiles": {str(q): float(np.quantile(v, q)) for q in quantiles},
        "edges": [float(x) for x in edges],
        "fracs": [float(c) / total for c in counts],
    }


def build_reference(
    samples: Sequence[Any],
    *,
    head_names: Sequence[str] = (),
    bins: int = 16,
    max_samples: int = 512,
    quantiles: Sequence[float] = QUANTILE_PROBES,
) -> Dict[str, Any]:
    """Build the drift reference window from training samples.

    Per node-feature channel: mean/std, probe quantiles, and a
    ``bins``-bucket histogram (edges + fractions).  Per head: the same
    stats over the *training targets* — the best available stand-in
    for healthy prediction mass (a well-fit model's predictions track
    its targets), and the scale the error-drift track normalises by.
    Bounded to ``max_samples`` samples so manifest stamping stays
    cheap on large runs.
    """
    sub = list(samples)[: max(1, int(max_samples))]
    if not sub:
        raise ValueError("build_reference needs at least one sample")
    x = np.concatenate([np.asarray(s.x, dtype=np.float64) for s in sub], axis=0)
    if x.ndim == 1:
        x = x[:, None]
    channels = [
        _value_stats(x[:, c], bins=bins, quantiles=quantiles)
        for c in range(x.shape[1])
    ]

    heads: Dict[str, Any] = {}
    names = list(head_names)
    if not names:
        names = sorted(
            set(sub[0].graph_targets.keys()) | set(sub[0].node_targets.keys())
        )
    for name in names:
        vals = []
        for s in sub:
            t = s.graph_targets.get(name)
            if t is None:
                t = s.node_targets.get(name)
            if t is not None:
                vals.append(np.asarray(t, dtype=np.float64).ravel())
        if not vals:
            continue
        stats = _value_stats(np.concatenate(vals), bins=bins, quantiles=quantiles)
        stats["scale"] = max(stats["std"], _EPS)
        heads[name] = stats

    return {
        "schema": REFERENCE_SCHEMA,
        "num_samples": len(sub),
        "num_rows": int(x.shape[0]),
        "quantile_probes": [float(q) for q in quantiles],
        "feature": {"channels": channels},
        "heads": heads,
    }


def load_reference(path: str) -> Dict[str, Any]:
    """Load a drift reference window from ``path``: either a training
    ``flight.jsonl`` (the ``run_start.manifest["stats"]`` block) or a
    bare stats JSON file (e.g. one written by ``tools/drift_report.py
    --export-ref``)."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"drift reference not found: {path}")
    if path.endswith(".jsonl"):
        from hydragnn_tpu.obs.flight import read_flight_record

        for event in read_flight_record(path):
            if event.get("kind") != "run_start":
                continue
            stats = (event.get("manifest") or {}).get("stats")
            if stats:
                return _check_reference(stats, path)
        raise ValueError(
            f"no run_start.manifest.stats block in flight record {path} "
            "(was the training run recorded before drift support?)"
        )
    with open(path) as f:
        return _check_reference(json.load(f), path)


def _check_reference(stats: Mapping[str, Any], origin: str) -> Dict[str, Any]:
    if int(stats.get("schema", -1)) != REFERENCE_SCHEMA:
        raise ValueError(
            f"drift reference {origin} has schema {stats.get('schema')!r}, "
            f"expected {REFERENCE_SCHEMA}"
        )
    channels = (stats.get("feature") or {}).get("channels") or []
    if not channels:
        raise ValueError(f"drift reference {origin} has no feature channels")
    return dict(stats)


class _HeadSketch:
    """Live sketch for one output head's prediction stream.

    Prediction drift is SELF-BASELINED: the first ``baseline_rows``
    live prediction values form a frozen baseline window (its own bin
    edges + fractions), and later traffic is PSI-compared against it.
    The training reference is deliberately NOT the pred baseline — the
    reference head stats describe the *label* distribution, and an
    imperfectly fit model would read as permanent "drift" on perfectly
    clean traffic.  Self-baselining makes ``pred_psi`` mean "the
    prediction distribution CHANGED during this serve session" (a bad
    weight reload, an upstream shift arriving mid-run) and stays quiet
    on a stable, merely-imperfect model.  Feature drift and the
    error-score scale still compare against the training reference.
    """

    def __init__(
        self,
        *,
        bins: int = 8,
        baseline_rows: int = 64,
        baseline_requests: int = 8,
    ):
        # Coarse bins on purpose: the PSI sampling noise between two
        # clean windows scales ~bins/rows, and a wholesale distribution
        # shift saturates even 8 bins.  The baseline must ALSO span
        # several requests — node-head slices deliver a whole graph's
        # rows at once, and one graph is not a traffic distribution.
        self.bins = int(bins)
        self.baseline_rows = max(2, int(baseline_rows))
        self.baseline_requests = max(1, int(baseline_requests))
        self._buffer: List[float] = []
        self._updates = 0
        self._live_updates = 0
        self.base_requests = 0
        self.edges: Optional[np.ndarray] = None
        self.base_fracs: Optional[np.ndarray] = None
        self.counts: Optional[np.ndarray] = None
        self.moments = RunningMoments(1)

    def update(self, values: np.ndarray) -> None:
        v = np.asarray(values, dtype=np.float64).ravel()
        if v.size == 0:
            return
        self.moments.update(v)
        if self.base_fracs is None:
            self._updates += 1
            self._buffer.extend(float(x) for x in v)
            if (
                len(self._buffer) >= self.baseline_rows
                and self._updates >= self.baseline_requests
            ):
                self._freeze_baseline()
            return
        self._live_updates += 1
        self.counts += hist_counts(v, self.edges)

    def _freeze_baseline(self) -> None:
        arr = np.asarray(self._buffer, dtype=np.float64)
        lo, hi = float(arr.min()), float(arr.max())
        if hi - lo < _EPS:
            # Degenerate (near-constant) baseline: widen so the inner
            # bins exist and any later movement lands in the outer bins.
            pad = max(abs(lo), 1.0) * 1e-3
            lo, hi = lo - pad, hi + pad
        self.edges = np.linspace(lo, hi, self.bins + 1)
        base = hist_counts(arr, self.edges).astype(np.float64)
        self.base_requests = self._updates
        self.base_fracs = base / base.sum()
        self.counts = np.zeros(len(self.edges) + 1, dtype=np.int64)
        self._buffer = []

    @property
    def count(self) -> int:
        """Total prediction rows observed (baseline + live)."""
        return self.moments.count

    @property
    def live_rows(self) -> int:
        """Rows observed AFTER the baseline window froze."""
        return 0 if self.counts is None else int(self.counts.sum())

    def psi(self) -> float:
        if self.base_fracs is None or self.live_rows == 0:
            return 0.0
        raw = psi(self.base_fracs, self.counts / self.live_rows)
        # Two finite windows of the SAME distribution still measure
        # E[PSI] ≈ (K-1)(1/n_base + 1/n_live) (first-order chi-square
        # bias) — subtract it so clean windows read ~0 while a real
        # shift (PSI in whole units) barely notices.  The effective
        # sample size is the REQUEST count, not the row count: a node
        # head's rows arrive one whole graph at a time and are strongly
        # correlated within it, so counting rows would understate the
        # noise floor ~nodes-per-graph-fold.
        k = len(self.base_fracs)
        noise = (k - 1) * (
            1.0 / max(self.base_requests, 1)
            + 1.0 / max(self._live_updates, 1)
        )
        return max(0.0, raw - noise)


class DriftMonitor:
    """Streaming drift state for one server, fed from host-side arrays.

    Not thread-safe by itself: the server calls :meth:`observe` from
    its single dispatch thread and reads the resulting gauges from the
    trigger engine via the (thread-safe) metrics registry.
    """

    def __init__(
        self,
        reference: Mapping[str, Any],
        registry: Any,
        *,
        prefix: str = "serve",
        min_count: int = 64,
        min_labeled: int = 8,
        quantile_rows: int = 8,
    ):
        self.reference = _check_reference(reference, "<inline>")
        self.prefix = prefix
        self.min_count = int(min_count)
        self.min_labeled = int(min_labeled)
        self.quantile_rows = max(1, int(quantile_rows))

        ref_channels = self.reference["feature"]["channels"]
        self.num_channels = len(ref_channels)
        self._ref_channels = ref_channels
        self._edges = [
            np.asarray(ch["edges"], dtype=np.float64) for ch in ref_channels
        ]
        self._ref_fracs = [
            _padded_ref_fracs(ch["fracs"]) for ch in ref_channels
        ]
        self._counts = [
            np.zeros(len(e) + 1, dtype=np.int64) for e in self._edges
        ]
        self.moments = RunningMoments(self.num_channels)
        probes = [float(q) for q in self.reference.get("quantile_probes", QUANTILE_PROBES)]
        self._probes = probes
        self._p2 = [
            {q: P2Quantile(q) for q in probes} for _ in range(self.num_channels)
        ]
        # Head sketches are created lazily per predicted head name (so
        # pred drift works even when the reference carries no head
        # stats); each one self-baselines on its first min_count rows.
        self._heads: Dict[str, _HeadSketch] = {}
        self._abs_err: Dict[str, RunningMoments] = {}

        g = registry.gauge
        self._g_feature_psi = g(f"{prefix}.drift.feature_psi")
        self._g_feature_qshift = g(f"{prefix}.drift.feature_qshift")
        self._g_pred_psi = g(f"{prefix}.drift.pred_psi")
        self._g_error_score = g(f"{prefix}.drift.error_score")
        self._g_feature_rows = g(f"{prefix}.drift.feature_rows")
        self._g_pred_rows = g(f"{prefix}.drift.pred_rows")
        self._g_labeled_rows = g(f"{prefix}.drift.labeled_rows")

    def reset(self) -> None:
        """Drop every live sketch and republish zeroed gauges, keeping
        the reference window.  Called by the retrain pilot after a
        successful canary + reload: the sketches accumulated the DRIFTED
        traffic, and without a reset the same rows would re-breach the
        threshold forever against the freshly recovered model.  Runs on
        the pilot's thread while the dispatch thread may be observing —
        callers quiesce the server (or accept one request's worth of
        interleaved updates, which the warm-up gate absorbs)."""
        self._counts = [
            np.zeros(len(e) + 1, dtype=np.int64) for e in self._edges
        ]
        self.moments = RunningMoments(self.num_channels)
        self._p2 = [
            {q: P2Quantile(q) for q in self._probes}
            for _ in range(self.num_channels)
        ]
        self._heads = {}
        self._abs_err = {}
        self._g_feature_psi.set(0.0)
        self._g_feature_qshift.set(0.0)
        self._g_pred_psi.set(0.0)
        self._g_error_score.set(0.0)
        self._publish()

    # -- ingest (dispatch thread; host-side numpy only) ---------------------

    def observe(
        self, x: np.ndarray, predictions: Mapping[str, np.ndarray]
    ) -> None:
        """Fold one request's featurized inputs ``x`` (``[n, channels]``)
        and its per-head prediction slices into the sketches, then
        republish the drift gauges."""
        rows = np.asarray(x, dtype=np.float64)
        if rows.ndim == 1:
            rows = rows[:, None]
        if rows.shape[1] != self.num_channels:
            raise ValueError(
                f"drift monitor built for {self.num_channels} feature "
                f"channels, got x with {rows.shape[1]}"
            )
        self.moments.update(rows)
        for c in range(self.num_channels):
            self._counts[c] += hist_counts(rows[:, c], self._edges[c])
        # P² marker updates are sequential per value: bound the cost per
        # request to quantile_rows rows, evenly strided over the graph.
        stride = max(1, rows.shape[0] // self.quantile_rows)
        for row in rows[::stride][: self.quantile_rows]:
            for c in range(self.num_channels):
                for est in self._p2[c].values():
                    est.add(row[c])
        for name, arr in predictions.items():
            sketch = self._heads.get(name)
            if sketch is None:
                sketch = self._heads[name] = _HeadSketch(
                    baseline_rows=self.min_count
                )
            sketch.update(np.asarray(arr))
        self._publish()

    def observe_labeled(
        self, head: str, prediction: np.ndarray, truth: np.ndarray
    ) -> None:
        """Error-drift track: fold one labelled (prediction, truth)
        pair — e.g. a spool entry whose ground truth arrived later —
        into the per-head absolute-error moments."""
        err = np.abs(
            np.asarray(prediction, dtype=np.float64).ravel()
            - np.asarray(truth, dtype=np.float64).ravel()
        )
        mom = self._abs_err.get(head)
        if mom is None:
            mom = self._abs_err[head] = RunningMoments(1)
        mom.update(err)
        self._publish()

    # -- distances -----------------------------------------------------------

    def feature_psi(self) -> List[float]:
        out = []
        for c in range(self.num_channels):
            total = int(self._counts[c].sum())
            if total == 0:
                out.append(0.0)
            else:
                out.append(psi(self._ref_fracs[c], self._counts[c] / total))
        return out

    def feature_qshift(self) -> List[float]:
        """Per channel: max over probe quantiles of
        ``|live_q - ref_q| / ref_std``."""
        out = []
        for c in range(self.num_channels):
            ref = self._ref_channels[c]
            scale = max(float(ref["std"]), _EPS)
            worst = 0.0
            for q in self._probes:
                est = self._p2[c][q]
                if est.count == 0:
                    continue
                ref_q = float(ref["quantiles"][str(q)])
                worst = max(worst, abs(est.value - ref_q) / scale)
            out.append(worst)
        return out

    def head_psi(self) -> Dict[str, float]:
        return {name: s.psi() for name, s in self._heads.items()}

    def error_scores(self) -> Dict[str, float]:
        """Per head with labelled data: live MAE over the reference
        target scale — ~O(noise/scale) when healthy, >> 1 when the
        model has gone wrong on shifted inputs."""
        out = {}
        for name, mom in self._abs_err.items():
            ref = (self.reference.get("heads") or {}).get(name) or {}
            scale = max(float(ref.get("scale", ref.get("std", 1.0)) or 1.0), _EPS)
            out[name] = float(mom.mean[0]) / scale
        return out

    # -- gauge publication ---------------------------------------------------

    @property
    def feature_rows(self) -> int:
        return self.moments.count

    @property
    def pred_rows(self) -> int:
        return sum(s.count for s in self._heads.values())

    @property
    def pred_live_rows(self) -> int:
        """Prediction rows observed after every head froze a baseline —
        the mass the pred PSI is actually computed over."""
        return sum(s.live_rows for s in self._heads.values())

    @property
    def labeled_rows(self) -> int:
        return sum(m.count for m in self._abs_err.values())

    def _publish(self) -> None:
        self._g_feature_rows.set(float(self.feature_rows))
        self._g_pred_rows.set(float(self.pred_rows))
        self._g_labeled_rows.set(float(self.labeled_rows))
        # Warm-up guard: stay at 0.0 below min_count rows so a freshly
        # started server cannot fire a drift trigger on sketch noise.
        if self.feature_rows >= self.min_count:
            self._g_feature_psi.set(max(self.feature_psi(), default=0.0))
            self._g_feature_qshift.set(max(self.feature_qshift(), default=0.0))
        # Per-head gate: a head contributes its PSI only once it has
        # min_count LIVE rows past its frozen baseline — a 3-row live
        # window against a 64-row baseline is pure sampling noise.
        stable = [
            s.psi()
            for s in self._heads.values()
            if s.live_rows >= self.min_count
        ]
        if stable:
            self._g_pred_psi.set(max(stable))
        if self.labeled_rows >= self.min_labeled:
            self._g_error_score.set(
                max(self.error_scores().values(), default=0.0)
            )

    # -- reporting -----------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """Full drift report: the incident-bundle sidecar and the
        ``tools/drift_report.py`` payload."""
        per_channel = []
        psis = self.feature_psi()
        qshifts = self.feature_qshift()
        for c in range(self.num_channels):
            ref = self._ref_channels[c]
            per_channel.append(
                {
                    "channel": c,
                    "psi": psis[c],
                    "qshift": qshifts[c],
                    "mean": float(self.moments.mean[c]),
                    "std": float(self.moments.std[c]),
                    "ref_mean": float(ref["mean"]),
                    "ref_std": float(ref["std"]),
                    "quantiles": {
                        str(q): self._p2[c][q].value
                        for q in self._probes
                        if self._p2[c][q].count
                    },
                    "counts": [int(n) for n in self._counts[c]],
                }
            )
        heads = {}
        head_psis = self.head_psi()
        for name, sketch in self._heads.items():
            heads[name] = {
                "psi": head_psis[name],
                "mean": float(sketch.moments.mean[0]),
                "std": float(sketch.moments.std[0]),
                "rows": sketch.count,
                "live_rows": sketch.live_rows,
            }
        return {
            "schema": DRIFT_REPORT_SCHEMA,
            "min_count": self.min_count,
            "counts": {
                "feature_rows": self.feature_rows,
                "pred_rows": self.pred_rows,
                "labeled_rows": self.labeled_rows,
            },
            "feature": {
                "psi_max": max(psis, default=0.0),
                "qshift_max": max(qshifts, default=0.0),
                "channels": per_channel,
            },
            "heads": heads,
            "error": {"scores": self.error_scores()},
        }

    def summary(self) -> Dict[str, Any]:
        """Compact block for run_end / flight manifests."""
        return {
            "feature_rows": self.feature_rows,
            "pred_rows": self.pred_rows,
            "labeled_rows": self.labeled_rows,
            "feature_psi_max": max(self.feature_psi(), default=0.0),
            "pred_psi_max": max(self.head_psi().values(), default=0.0),
            "error_score_max": max(self.error_scores().values(), default=0.0),
        }


def validate_drift_report(report: Mapping[str, Any]) -> List[str]:
    """Schema check for a ``drift_report.json`` sidecar; returns a list
    of problems (empty == valid).  Used by ``lint/artifacts.py`` and
    ``tools/drift_report.py --validate``."""
    problems: List[str] = []
    if int(report.get("schema", -1)) != DRIFT_REPORT_SCHEMA:
        problems.append(
            f"drift report schema {report.get('schema')!r} != {DRIFT_REPORT_SCHEMA}"
        )
    for key in ("counts", "feature", "heads", "error"):
        if key not in report:
            problems.append(f"drift report missing key {key!r}")
    feature = report.get("feature") or {}
    if "feature" in report:
        for key in ("psi_max", "qshift_max", "channels"):
            if key not in feature:
                problems.append(f"drift report feature block missing {key!r}")
    for i, ch in enumerate(feature.get("channels") or []):
        for key in ("channel", "psi", "mean", "ref_mean"):
            if key not in ch:
                problems.append(f"drift report channel[{i}] missing {key!r}")
    counts = report.get("counts") or {}
    if "counts" in report:
        for key in ("feature_rows", "pred_rows", "labeled_rows"):
            if key not in counts:
                problems.append(f"drift report counts block missing {key!r}")
    return problems
