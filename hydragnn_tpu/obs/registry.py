"""Shared metrics registry: counters, gauges, windowed histograms.

One rank-aware in-process store that train, serve, the loader, and the
benches all record into — the generalization of the serving-only
counters that ``serve/metrics.py:ServeMetrics`` grew first (that class
is now a facade over this registry; its ``snapshot()`` keys are
unchanged). Three metric kinds cover everything the subsystems emit:

  - :class:`Counter` — monotone accumulator (requests, compile events,
    seconds spent waiting on the prefetch queue);
  - :class:`Gauge` — last-write-wins level with a tracked peak (queue
    depth);
  - :class:`Histogram` — bounded rolling window with nearest-rank
    p50/p95/p99 (request latency; a serving process lives for days, so
    warmup samples must age out of the tail stats).

Cost discipline: a DISABLED registry hands out process-wide null
singletons whose record methods are empty-body no-ops — no lock, no
allocation, no time syscall — so instrumented hot paths stay honest
when telemetry is off (tests/test_obs.py pins this). Export goes
through :mod:`hydragnn_tpu.obs.export` (tensorboard / JSONL /
Prometheus textfile).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Dict, Optional
from hydragnn_tpu.utils import knobs, syncdebug


def _percentile_nearest_rank(sorted_vals, q: float) -> float:
    """Nearest-rank percentile on an already-sorted sample — exact for
    the small windows kept here, no interpolation surprises at the
    tail (same protocol as serve latency stats)."""
    n = len(sorted_vals)
    if not n:
        return 0.0
    i = min(n - 1, max(0, int(round(q * (n - 1)))))
    return float(sorted_vals[i])


class Counter:
    """Monotone float/int accumulator."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "registry.Counter._lock"
        )
        self._value = 0.0  # graftsync: guarded-by=registry.Counter._lock

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        v = self.value
        return int(v) if float(v).is_integer() else v


class Gauge:
    """Last-write-wins level; ``peak`` tracks the max ever set."""

    __slots__ = ("name", "_lock", "_value", "_peak")

    def __init__(self, name: str):
        self.name = name
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "registry.Gauge._lock"
        )
        self._value = 0.0  # graftsync: guarded-by=registry.Gauge._lock
        self._peak = 0.0  # graftsync: guarded-by=registry.Gauge._lock

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            if v > self._peak:
                self._peak = v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def peak(self) -> float:
        with self._lock:
            return self._peak

    def snapshot(self):
        v = self.value
        return int(v) if float(v).is_integer() else v


class Histogram:
    """Bounded rolling window of observations with nearest-rank
    percentiles. ``window`` bounds memory AND makes the percentiles a
    recent-traffic statistic rather than an all-time one."""

    __slots__ = ("name", "_lock", "_window", "_count", "_sum")

    def __init__(self, name: str, window: int = 2048):
        self.name = name
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "registry.Histogram._lock"
        )
        # graftsync: guarded-by=registry.Histogram._lock
        self._window: deque = deque(maxlen=window)
        self._count = 0  # graftsync: guarded-by=registry.Histogram._lock
        self._sum = 0.0  # graftsync: guarded-by=registry.Histogram._lock

    def observe(self, v: float) -> None:
        with self._lock:
            self._window.append(v)
            self._count += 1
            self._sum += v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def values(self):
        """The current window (a copy), oldest first."""
        with self._lock:
            return list(self._window)

    def snapshot(self) -> dict:
        with self._lock:
            vals = sorted(self._window)
            count, total = self._count, self._sum
        return {
            "count": count,
            "sum": total,
            "mean": (sum(vals) / len(vals)) if vals else 0.0,
            "p50": _percentile_nearest_rank(vals, 0.50),
            "p95": _percentile_nearest_rank(vals, 0.95),
            "p99": _percentile_nearest_rank(vals, 0.99),
        }


class _NullCounter(Counter):
    __slots__ = ()

    def inc(self, n: float = 1) -> None:
        pass


class _NullGauge(Gauge):
    __slots__ = ()

    def set(self, v: float) -> None:
        pass


class _NullHistogram(Histogram):
    __slots__ = ()

    def observe(self, v: float) -> None:
        pass


# process-wide singletons: every disabled-registry lookup returns these,
# so the disabled path allocates nothing per call site
NULL_COUNTER = _NullCounter("null")
NULL_GAUGE = _NullGauge("null")
NULL_HISTOGRAM = _NullHistogram("null", window=1)


class MetricsRegistry:
    """Named metric store. Metric names are dotted paths
    (``serve.requests_total``, ``loader.prefetch_wait_s``); ``snapshot``
    nests them back into a dict tree so the tensorboard exporter
    (``utils/tensorboard.py:write_scalar_dict``) and the flight
    recorder consume it directly.

    ``enabled=False`` turns every factory into a null-singleton lookup
    (see module docstring); ``snapshot`` is then empty.
    """

    def __init__(self, enabled: bool = True, rank: Optional[int] = None):
        self.enabled = enabled
        # graftsync: thread-safe=write-once None->int latch (set under _lock in rank); unlocked reads see None or the final value
        self._rank = rank
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "registry.MetricsRegistry._lock"
        )
        # graftsync: guarded-by=registry.MetricsRegistry._lock
        self._metrics: Dict[str, object] = {}

    # -- factories ---------------------------------------------------------

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        if not self.enabled:
            return NULL_COUNTER
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        if not self.enabled:
            return NULL_GAUGE
        return self._get(name, Gauge)

    def histogram(self, name: str, window: int = 2048) -> Histogram:
        if not self.enabled:
            return NULL_HISTOGRAM
        return self._get(name, Histogram, window)

    # -- introspection -----------------------------------------------------

    @property
    def rank(self) -> int:
        """This process's rank; resolved lazily so building a registry
        never forces jax backend initialization."""
        if self._rank is None:
            # resolve OUTSIDE the lock — process_index() can block on
            # backend init for seconds; racing resolvers compute the
            # same value and the first write under the lock wins. The
            # podview simulated-host override wins over jax so per-host
            # Prometheus exports stay distinguishable on one machine.
            r = knobs.get_int("HYDRAGNN_PODVIEW_HOST", -1)
            if r < 0:
                try:
                    import jax

                    r = jax.process_index()
                except Exception:
                    r = 0
            with self._lock:
                if self._rank is None:
                    self._rank = r
        return self._rank

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> dict:
        """Nested dict of every metric's current value, keyed by the
        dotted-path segments (counters/gauges -> numbers, histograms ->
        {count, sum, mean, p50, p95, p99})."""
        with self._lock:
            items = list(self._metrics.items())
        out: dict = {}
        for name, metric in items:
            node = out
            parts = name.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = metric.snapshot()
        return out


_GLOBAL: Optional[MetricsRegistry] = None  # graftsync: guarded-by=registry._GLOBAL_LOCK
_GLOBAL_LOCK = syncdebug.maybe_wrap(threading.Lock(), "registry._GLOBAL_LOCK")


def telemetry_enabled() -> bool:
    """Process-wide telemetry gate: ``HYDRAGNN_TELEMETRY`` accepts
    0/false/off (any case) to disable; default on."""
    return knobs.get_bool("HYDRAGNN_TELEMETRY", True)


def get_registry() -> MetricsRegistry:
    """The process-global registry (created on first use, honoring
    ``HYDRAGNN_TELEMETRY`` at creation time). Subsystems that need
    isolation (one ``ServeMetrics`` per server) construct their own
    :class:`MetricsRegistry` instead."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = MetricsRegistry(enabled=telemetry_enabled())
        return _GLOBAL


def reset_registry() -> None:
    """Drop the process-global registry (tests; a fresh one re-reads
    ``HYDRAGNN_TELEMETRY``)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = None
