"""Exporters for the metrics registry: tensorboard, JSONL, Prometheus.

Three sinks, one source (:class:`~hydragnn_tpu.obs.registry.
MetricsRegistry`):

  - **tensorboard** rides the existing rank-0 writer plumbing
    (``utils/tensorboard.py:write_scalar_dict``) — dashboards for a
    long-lived server or training run;
  - **JSONL** appends one snapshot line per call — the same parseable
    shape the flight recorder uses, for ad-hoc scraping;
  - **Prometheus textfile** writes the node-exporter textfile-collector
    format (atomic tmp+rename, as that collector requires), with the
    process rank as a label — the hook a fleet scraper needs without
    this package growing an HTTP server.

All exporters read a snapshot under the registry's locks and then work
on plain dicts — an export never blocks a recording hot path for
longer than the snapshot copy.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

from hydragnn_tpu.obs.registry import MetricsRegistry

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def registry_to_tensorboard(
    writer, registry: MetricsRegistry, step: int, prefix: str = "obs"
) -> int:
    """Flush a registry snapshot as scalar tags; returns scalars
    written."""
    from hydragnn_tpu.utils.tensorboard import write_scalar_dict

    return write_scalar_dict(writer, registry.snapshot(), step, prefix=prefix)


def registry_to_jsonl(
    path: str, registry: MetricsRegistry, extra: Optional[dict] = None
) -> None:
    """Append one snapshot line ``{"t": ..., "rank": ..., "metrics":
    {...}}`` (plus ``extra``'s keys) to ``path``."""
    line = {
        "t": round(time.time(), 3),
        "rank": registry.rank,
        "metrics": registry.snapshot(),
    }
    if extra:
        line.update(extra)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(line) + "\n")


def prometheus_name(name: str, prefix: str = "hydragnn") -> str:
    """Dotted metric path -> a legal Prometheus metric name."""
    return _PROM_BAD.sub("_", f"{prefix}_{name.replace('.', '_')}")


def registry_to_prometheus_text(
    registry: MetricsRegistry, prefix: str = "hydragnn"
) -> str:
    """Render the registry in Prometheus exposition format. Counters
    and gauges become single samples; histograms expose _count/_sum
    plus quantile-labeled samples (the summary convention)."""
    from hydragnn_tpu.obs.registry import Counter, Gauge, Histogram

    rank = registry.rank
    lines = []
    for name in registry.names():
        metric = registry.get(name)
        if metric is None:
            continue
        pname = prometheus_name(name, prefix)
        label = f'{{rank="{rank}"}}'
        if isinstance(metric, Histogram):
            snap = metric.snapshot()
            lines.append(f"# TYPE {pname} summary")
            for q in ("p50", "p95", "p99"):
                lines.append(
                    f'{pname}{{rank="{rank}",quantile="0.{q[1:]}"}} {snap[q]}'
                )
            lines.append(f"{pname}_count{label} {snap['count']}")
            lines.append(f"{pname}_sum{label} {snap['sum']}")
        elif isinstance(metric, Gauge):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname}{label} {metric.value}")
            lines.append(f"# TYPE {pname}_peak gauge")
            lines.append(f"{pname}_peak{label} {metric.peak}")
        elif isinstance(metric, Counter):
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname}{label} {metric.value}")
    return "\n".join(lines) + "\n"


def registry_to_prometheus(
    registry: MetricsRegistry, path: str, prefix: str = "hydragnn"
) -> None:
    """Write the textfile-collector snapshot atomically (write to a
    sibling tmp file, rename over — the collector may read at any
    moment and must never see a partial file)."""
    text = registry_to_prometheus_text(registry, prefix)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
