"""Run flight recorder: a crash-safe, append-only JSONL event log.

One file per run tells the whole story in machine-readable form: a
``run_start`` manifest (resolved config, jax/backend versions, mesh
shape, pad plans), one ``epoch`` record per epoch (losses, the
data-wait / dispatch / device step-time decomposition, compile counts),
``compile`` / ``retry`` / ``error`` events as they happen, and a
``run_end`` summary. Training writes it alongside checkpoints
(``<log_dir>/<log_name>/flight.jsonl``); ``bench.py`` / ``bench_serve.py``
write one next to their JSON records — the self-contained evidence
artifact a round verdict can parse instead of a builder anecdote (a
run that died mid-way still has every event up to the crash: each line
is written and flushed atomically-enough that the tail is at worst one
truncated line, which the reader skips).

Every ``run_start`` manifest additionally carries a ``graftcheck``
block — the compiled-IR contract audit (docs/LINT.md CC rules) stamped
by the emitter at run start: ``{"schema": .., "contracts": {CC001:
pass|fail|not_checked + why, ...}, "violations": [..]}``. Emitters that
never lower an executable (the restart supervisor) stamp an honest
all-``not_checked`` block so the key is universal.

Schema (``SCHEMA_VERSION``): every event is one JSON object per line
with ``v`` (schema version), ``kind``, ``t`` (unix seconds), ``rank``;
kind-specific required fields are in ``_REQUIRED``. Validate with
:func:`validate_flight_record` (ci.sh runs it on a tiny training run;
``tools/obs_report.py`` pretty-prints and diffs records).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Union

# v2 (model-introspection PR): epoch events gain name-keyed per-task
# losses (``train_tasks``/``val_tasks`` as dicts), a ``heads`` block
# (per-head grad norms, conflict matrix, MAE/RMSE) and a ``hw`` block
# (achieved TFLOP/s, MFU, memory watermark); run_start manifests gain
# ``hw_cost`` (compiled-step FLOPs/bytes + chip peak) and
# ``diagnostics``. All new fields are OPTIONAL: the validator accepts
# every version in SUPPORTED_SCHEMA_VERSIONS, so v1 records (and v1
# writers) keep validating unchanged.
SCHEMA_VERSION = 2
SUPPORTED_SCHEMA_VERSIONS = (1, 2)

# kind -> fields every event of that kind must carry (beyond the
# envelope v/kind/t/rank). Unknown kinds are allowed (forward compat);
# unknown extra fields always are.
_REQUIRED: Dict[str, tuple] = {
    "run_start": ("manifest",),
    "epoch": ("epoch", "train_loss", "val_loss"),
    "compile": ("count",),
    "retry": ("attempt", "error"),
    "error": ("error", "error_type"),
    "profile_trace": ("path",),
    "run_end": ("status",),
    # fault-tolerance events (hydragnn_tpu/resilience, docs/RESILIENCE.md)
    "preempt": ("signal", "epoch"),
    "resumed": ("epoch",),
    "rollback": ("epoch", "consec"),
    "watchdog": ("stall_s", "stacks"),
    "restart": ("attempt", "cause"),
    # serving-resilience events (hydragnn_tpu/serve, docs/RESILIENCE.md
    # "Serving resilience"): a quarantined poison request, an in-process
    # dispatch-thread restart, and hot-reload outcomes
    "quarantine": ("seq", "reason"),
    "dispatch_restart": ("attempt", "cause"),
    "reload": ("source",),
    "reload_failed": ("source", "error"),
    # persistent AOT executable cache (hydragnn_tpu/utils/exec_cache.py):
    # one event per cache interaction — hit / miss (with reason) /
    # store / evict / store_failed
    "exec_cache": ("event",),
    # incident-grade tracing (hydragnn_tpu/obs/trace.py, obs/triggers.py):
    # a sampled request/step trace (span list) and an SLO-trigger
    # incident bundle opened under logs/<run>/incidents/<id>/
    "trace_capture": ("trace_id", "spans"),
    "incident": ("id", "rule", "path"),
    # runtime lock-order witness (hydragnn_tpu/utils/syncdebug.py,
    # HYDRAGNN_LOCK_DEBUG=1): an observed acquisition order that
    # contradicts the static graftsync lock-order graph, with every
    # thread's stack at the moment of the inversion
    "lock_order": ("locks", "stacks"),
    # bench evidence events: one per measured config (bench.py) and one
    # per gate verdict (bench_serve.py warm-start check) — required here
    # so graftlint --artifacts can hold the committed BENCH_*.jsonl
    # records to the same schema bar as training flight logs
    "bench_config": ("name", "result"),
    "bench_result": ("record", "passed"),
    # serving-fleet events (hydragnn_tpu/fleet, docs/FLEET.md): every
    # autoscaler decision (up / down / replace / hold / up_failed, with
    # the trigger rule or quiet-timer reason and the resulting replica
    # count) and every per-replica step of a fleet-wide rolling reload
    "fleet_scale": ("action", "reason", "replicas"),
    "fleet_reload": ("model", "replica", "ok"),
    # served-traffic spool shard finalization (obs/spool.py): every
    # rotation names the shard, its sample/byte footprint, and any
    # LRU-evicted shards — the spool's disk-bound audit trail
    "spool_rotate": ("shard", "samples", "total_bytes"),
    # a drift trigger breached (obs/drift.py + the feature_drift /
    # pred_drift / error_drift rule kinds): which rule, what the sketch
    # observed vs the threshold, and where the offending spool window is
    "drift": ("rule", "observed", "threshold"),
    # retrain-pilot transitions (hydragnn_tpu/pilot, docs/RESILIENCE.md
    # "Closed loop"): every state-machine edge of the continual-learning
    # loop — which state the pilot entered, in which recovery cycle, and
    # why — so one flight timeline narrates incident -> fine-tune ->
    # canary -> reload end to end
    "pilot": ("state", "cycle"),
    # pod-visibility plane (obs/podview.py, docs/OBSERVABILITY.md "Pod
    # visibility"): a per-host epoch summary written into that host's
    # flight shard (the join unit merge_host_flights stitches on
    # ``(run_id, epoch)``), and the rank-0 SkewMonitor's per-epoch skew
    # verdict over all hosts' summaries
    "host_epoch": ("epoch", "host", "run_id", "epoch_s"),
    "podview": ("epoch", "skew_frac", "slowest_host"),
    # pod fault tolerance (resilience/podckpt.py, docs/RESILIENCE.md
    # "Pod recovery"): a peer host declared lost from the heartbeat
    # view (exactly one event per lost host per run), and the lineage
    # stamp of a run restored from a committed pod generation
    "host_lost": ("host",),
    "pod_resume": ("gen",),
}

# the fault-history subset tools/obs_report.py --faults narrates
FAULT_KINDS = (
    "preempt",
    "resumed",
    "rollback",
    "watchdog",
    "restart",
    "retry",
    "error",
    "quarantine",
    "dispatch_restart",
    "reload",
    "reload_failed",
    "incident",
    "lock_order",
    "drift",
    "fleet_scale",
    "fleet_reload",
    "pilot",
    "host_lost",
    "pod_resume",
)

_MANIFEST_REQUIRED = ("jax_version", "backend", "num_processes")


def _jsonable(obj: Any, depth: int = 0) -> Any:
    """Best-effort conversion to JSON-serializable structures: numpy
    scalars/arrays to python, unknown leaves to repr — a flight record
    write must never take the run down."""
    if depth > 8:
        return repr(obj)
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, depth + 1) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v, depth + 1) for v in obj]
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()  # numpy scalar
    if hasattr(obj, "tolist"):
        try:
            return obj.tolist()
        except Exception:
            return repr(obj)
    return repr(obj)


class FlightRecorder:
    """Append-only JSONL writer for one run.

    Each :meth:`record` opens nothing (the fd stays open), writes one
    line, and flushes — crash-safe in the sense that every completed
    event survives the process dying right after it. Disabled
    recorders (``enabled=False``) are inert: no file is created, every
    method is a no-op, so call sites never need their own gate.
    """

    def __init__(
        self,
        path: Optional[str],
        enabled: bool = True,
        host: Optional[int] = None,
    ):
        import threading

        from hydragnn_tpu.utils import syncdebug

        self.path = path
        # pod-visibility host identity: when set, every event's ``rank``
        # envelope field is stamped with this value instead of
        # jax.process_index() — how simulated hosts (HYDRAGNN_PODVIEW_HOST)
        # and real multihost shards both get distinguishable tracks in
        # the merged timeline (obs/podview.py)
        self.host = host
        # graftsync: thread-safe=GIL-atomic bool gate; a record() racing close() re-checks _f under the lock, worst case one event is dropped
        self.enabled = bool(enabled and path)
        self._f = None  # graftsync: guarded-by=flight.FlightRecorder._lock
        # the watchdog and preemption grace timer record from their own
        # threads; one lock keeps lines whole
        self._lock = syncdebug.maybe_wrap(
            threading.Lock(), "flight.FlightRecorder._lock"
        )
        if self.enabled:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._f = open(path, "a", buffering=1)
            syncdebug.register_flight(self)

    # -- core --------------------------------------------------------------

    def record(self, kind: str, **payload) -> None:
        if not self.enabled:
            return
        event = {
            "v": SCHEMA_VERSION,
            "kind": kind,
            "t": round(time.time(), 3),
            "rank": self.host if self.host is not None else _rank(),
        }
        event.update({k: _jsonable(v) for k, v in payload.items()})
        try:
            with self._lock:
                if self._f is None:
                    return  # closed concurrently after the enabled gate
                self._f.write(json.dumps(event) + "\n")
                self._f.flush()
        except (OSError, ValueError):
            # a full disk or closed fd must not take the run down;
            # stop recording rather than raise per-event
            self.enabled = False

    # -- typed convenience wrappers ---------------------------------------

    def start_run(self, manifest: Dict[str, Any]) -> None:
        """The run's identity card. Callers pass what they know
        (resolved config, pad plans, mesh); the environment fields the
        schema requires are filled in here."""
        manifest = dict(manifest)
        manifest.setdefault("jax_version", _jax_version())
        manifest.setdefault("backend", _backend_name())
        manifest.setdefault("num_processes", _num_processes())
        self.record("run_start", manifest=manifest)

    def epoch(self, epoch: int, **payload) -> None:
        self.record("epoch", epoch=epoch, **payload)

    def compile_event(self, count: int, **payload) -> None:
        self.record("compile", count=count, **payload)

    def retry(self, attempt: int, error: str, **payload) -> None:
        self.record("retry", attempt=attempt, error=str(error)[-400:], **payload)

    def error(self, error: BaseException | str, **payload) -> None:
        self.record(
            "error",
            error=str(error)[-400:],
            error_type=type(error).__name__
            if isinstance(error, BaseException)
            else "str",
            **payload,
        )

    def end_run(self, status: str, **payload) -> None:
        self.record("run_end", status=status, **payload)

    def close(self) -> None:
        # detach under the lock so a concurrent record() either wins the
        # race (its line lands before the close) or sees _f gone — never
        # a write to a closed fd; the actual close happens outside
        with self._lock:
            f = self._f
            self._f = None
            self.enabled = False
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    def __enter__(self) -> "FlightRecorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def _jax_version() -> str:
    try:
        import jax

        return jax.__version__
    except Exception:
        return "unavailable"


def _backend_name() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unavailable"


def _num_processes() -> int:
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def read_flight_record(path: str) -> List[dict]:
    """Parse a flight record, tolerating a truncated final line (the
    crash case the recorder exists for). Raises FileNotFoundError when
    the file is absent; malformed INTERIOR lines are kept as
    ``{"kind": "_unparseable", "line": ...}`` so validation can flag
    them without losing the rest."""
    events: List[dict] = []
    with open(path) as f:
        lines = f.read().split("\n")
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            events.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1 or (i == len(lines) - 2 and not lines[-1]):
                continue  # truncated tail: expected for a crashed run
            events.append({"kind": "_unparseable", "line": line[:200]})
    return events


def validate_flight_record(
    record: Union[str, List[dict]], require_complete: bool = False
) -> List[str]:
    """Schema check; returns a list of problems (empty = valid).

    ``require_complete=True`` additionally demands the happy-path
    shape: exactly one ``run_start`` first, at least one ``epoch``,
    and a terminal ``run_end`` — what ci.sh asserts of a tiny run.
    Without it, a crashed run (no run_end) still validates as long as
    every event it DID write is well-formed.
    """
    events = read_flight_record(record) if isinstance(record, str) else record
    problems: List[str] = []
    if not events:
        return ["empty flight record"]
    for i, ev in enumerate(events):
        where = f"event[{i}]"
        if ev.get("kind") == "_unparseable":
            problems.append(f"{where}: unparseable line {ev.get('line')!r}")
            continue
        for field in ("v", "kind", "t", "rank"):
            if field not in ev:
                problems.append(f"{where}: missing envelope field {field!r}")
        v = ev.get("v")
        if v is not None and v not in SUPPORTED_SCHEMA_VERSIONS:
            if isinstance(v, int) and v > SCHEMA_VERSION:
                pass  # newer writer: forward-compat, surfaced as a warning
            else:
                problems.append(
                    f"{where}: schema version {v!r} not in "
                    f"{SUPPORTED_SCHEMA_VERSIONS}"
                )
        kind = ev.get("kind")
        for field in _REQUIRED.get(kind, ()):
            if field not in ev:
                problems.append(f"{where} ({kind}): missing field {field!r}")
        if kind == "run_start":
            man = ev.get("manifest")
            if not isinstance(man, dict):
                problems.append(f"{where}: manifest is not a dict")
            else:
                for field in _MANIFEST_REQUIRED:
                    if field not in man:
                        problems.append(
                            f"{where}: manifest missing field {field!r}"
                        )
    kinds = [e.get("kind") for e in events]
    if require_complete:
        if kinds.count("run_start") != 1:
            problems.append(
                f"expected exactly one run_start, got {kinds.count('run_start')}"
            )
        elif kinds[0] != "run_start":
            problems.append(f"first event is {kinds[0]!r}, expected run_start")
        if "epoch" not in kinds:
            problems.append("no epoch events")
        if kinds[-1] != "run_end":
            problems.append(f"last event is {kinds[-1]!r}, expected run_end")
    return problems


def flight_record_warnings(record: Union[str, List[dict]]) -> List[str]:
    """Forward-compat advisories that must NOT fail validation: event
    kinds this reader does not know (a newer writer's events — still
    structurally fine) and events stamped with a schema version newer
    than this reader supports. ``tools/obs_report.py --validate/--diff``
    print these as warnings and exit 0."""
    events = read_flight_record(record) if isinstance(record, str) else record
    warnings: List[str] = []
    for i, ev in enumerate(events):
        kind = ev.get("kind")
        if kind is not None and kind != "_unparseable" and kind not in _REQUIRED:
            warnings.append(f"event[{i}]: unknown event kind {kind!r}")
        v = ev.get("v")
        if isinstance(v, int) and v > SCHEMA_VERSION:
            warnings.append(
                f"event[{i}]: schema version {v} is newer than this "
                f"reader (supports {SUPPORTED_SCHEMA_VERSIONS}) — fields "
                "may be missing from views"
            )
    return warnings
