"""Jittable in-forward radius graph for SchNet.

The reference SchNet stack rebuilds its interaction graph INSIDE the
forward pass from node positions (reference: hydragnn/models/SCFStack.py:
63-76, ``RadiusInteractionGraph(radius, max_neighbours)``). Dynamic
neighbor search with data-dependent edge counts does not jit; this is the
static-shape equivalent: every node gets exactly ``max_neighbours`` edge
slots, filled with its nearest same-graph neighbors within the cutoff and
masked beyond, so the edge buffer is [N*K] with a boolean mask instead of
a ragged [E].

Semantics match the host-side cell-list builder
(hydragnn_tpu/data/radius_graph.py): per-receiver nearest-K cap, no
self-loops, receiver-major ordering (receivers ascending — segment ops
downstream see sorted ids).

Cost is the dense [N, N] distance matrix + top_k — O(N^2) in the padded
node count, the right trade for molecule-scale graphs (the reference only
uses in-forward graphs for SchNet on molecular data); large-graph runs
should precompute edges host-side (the default path).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def radius_graph_in_forward(
    pos: jnp.ndarray,
    node_graph: jnp.ndarray,
    node_mask: jnp.ndarray,
    radius: float,
    max_neighbours: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fixed-shape radius graph from positions.

    Args:
      pos: [N, 3] node positions (padded slots arbitrary).
      node_graph: [N] graph id per node.
      node_mask: [N] bool, True on real nodes.
      radius: cutoff distance.
      max_neighbours: K edge slots per receiver.

    Returns ``(senders, receivers, dist, edge_mask)``, each [N*K];
    ``receivers`` is ascending (receiver-major). Masked slots carry
    ``dist = 2 * radius`` so downstream smearing/cutoff math stays finite.
    """
    n = pos.shape[0]
    k = int(min(max_neighbours, max(n - 1, 1)))
    pos = pos.astype(jnp.float32)
    diff = pos[:, None, :] - pos[None, :, :]
    d2 = jnp.sum(diff * diff, axis=-1)  # [N, N] receiver-major rows
    ok = (
        (node_graph[:, None] == node_graph[None, :])
        & (node_mask[:, None] & node_mask[None, :])
        & ~jnp.eye(n, dtype=bool)
        & (d2 <= jnp.asarray(radius, jnp.float32) ** 2)
    )
    masked = jnp.where(ok, d2, jnp.inf)
    neg_d2, idx = jax.lax.top_k(-masked, k)  # nearest k per receiver row
    edge_mask = jnp.isfinite(neg_d2)
    receivers = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    senders = idx.astype(jnp.int32).reshape(-1)
    dist = jnp.sqrt(jnp.maximum(-neg_d2, 0.0)).reshape(-1)
    dist = jnp.where(edge_mask.reshape(-1), dist, 2.0 * radius)
    # masked slots: point the gather at node 0 (contribution zeroed by mask)
    senders = jnp.where(edge_mask.reshape(-1), senders, 0)
    return senders, receivers, dist, edge_mask.reshape(-1)
