"""Fully fused message-passing conv layer: gather -> edge MLP -> scatter
in ONE Pallas kernel.

Every conv flavor in ``models/convs.py`` bottoms out in the same
three-op chain over the edge set:

    v_e  = x[send_e]                      (CSR row gather, [E, Hin])
    m_e  = f(v_e)                         (edge network: matmul+bias+act,
                                           gating product, or identity)
    out  = segment_sum(mask_e * m_e)      (scatter into receivers)

Unfused, that chain materializes v and m in HBM and reads them back —
2-4 full [E, H] HBM round trips per conv layer plus XLA's serial
per-row scatter (docs/PERF.md r03-r05 traces put these at the top of
every step profile). This kernel runs the whole chain inside VMEM:

  - grid over receiver node blocks with scalar-prefetched CSR block
    pointers (receivers arrive sorted — the loader contract every conv
    already relies on);
  - per edge chunk, the sender rows are fetched with the windowed
    gather (senders are unsorted-but-local for batched graphs: a
    scalar-prefetched per-chunk window plan bounds each chunk's row
    span, the same plan machinery as ``segment_pallas``'s bcast
    kernel) and reduced to output rows by one-hot MXU matmuls;
  - the edge network runs on the gathered chunk in registers/VMEM:
    up to two linear branches ``act_k(v @ W_k + b_k + rtab_k[recv_e]
    + eterm_k)`` combined by elementwise product (the CGCNN
    sigmoid*softplus gate), an optional per-edge ``scale`` factor
    (the SchNet filter), or plain identity (GIN/SAGE/MFC
    aggregation). Receiver-side terms are gathered from the
    node-blocked ``rtab`` operand with the transpose of the scatter
    one-hot — they never touch edge-space HBM;
  - DOUBLE-BUFFERED HBM->VMEM DMA at two levels: edge-chunk operands
    (ids, mask, eterm, scale) prefetch chunk k+1 while chunk k
    computes, and the sender-window DMA for chunk k+1 is issued
    BEFORE chunk k's MLP/scatter matmuls so the gather of the next
    chunk overlaps the compute of the current one.

Training rides a hand-written VJP built from the existing fast
machinery (``segment_pallas``): the cotangent gather is the sorted
CSR-broadcast kernel, grad_x scatters through the local-window segment
sum (no edge permute), rtab grads are a sorted segment sum, and W/b
grads are plain MXU contractions. The forward's XLA fallback
(`use_kernel=False`) computes the identical composition with plain
jnp ops — the numerical contract the kernel is tested against in
interpret mode — and both paths share the same custom VJP, so
gradient semantics cannot diverge between them.

SPMD: the kernel call is wrapped in ``custom_partitioning`` with an
edge-axis rule — GSPMD sharding the edge-space operands on their
leading axis runs the kernel per shard (contiguous receiver-sorted
slices keep the CSR contract) and one ``psum`` combines the node-space
partials. Inside ``shard_map`` the operands are already local and the
wrapper lowers to the plain kernel. ``vmap`` contexts force the XLA
path via the shared ``HYDRAGNN_PALLAS`` knob machinery
(``xla_segment_ops``), exactly like the segment kernels.

Knob contract: ``HYDRAGNN_PALLAS`` as in ``segment_pallas`` (auto =
kernel on TPU, ``interpret`` forces interpret mode on any backend for
CPU tests, ``0`` forces XLA). The BN/CE block/chunk sizes are imported
from ``segment_pallas``, whose import-time defaults come from the
committed sweep table ``TUNE_TILES.json`` (``tools/tune_tiles.py
--save``; explicit HYDRAGNN_BN/CE env knobs always win). Widths are
lane-padded to 128 in and sliced back out. Output is float32 (the
segment-sum accumulation contract); callers cast.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from hydragnn_tpu.utils import knobs

from hydragnn_tpu.ops.segment_pallas import (
    ALIGN,
    BN,
    BW,
    CE,
    _def_partition_compat,
    _interpret_mode,
    _kernel_eligible,
    _match_vma,
    _sds,
    _vma_of,
    _window_plan_local,
    gather_rows_local_fast,
    gather_rows_sorted_fast,
    pallas_available,
    segment_sum_fast,
    segment_sum_local_fast,
)

# Edge-network activations: (f, df) where df takes (pre, f(pre)) so the
# derivative can reuse the forward value (sigmoid, tanh). All run in f32
# inside the kernel; the XLA fallback applies them in the compute dtype.
_ACTS = {
    "none": (lambda x: x, lambda x, a: jnp.ones_like(x)),
    "relu": (
        lambda x: jnp.maximum(x, jnp.zeros_like(x)),
        lambda x, a: (x > 0).astype(x.dtype),
    ),
    "sigmoid": (jax.nn.sigmoid, lambda x, a: a * (1.0 - a)),
    "softplus": (jax.nn.softplus, lambda x, a: jax.nn.sigmoid(x)),
    "tanh": (jnp.tanh, lambda x, a: 1.0 - a * a),
    "silu": (
        jax.nn.silu,
        lambda x, a: jax.nn.sigmoid(x) * (1.0 + x * (1.0 - jax.nn.sigmoid(x))),
    ),
}


def fused_conv_active() -> bool:
    """Would :func:`fused_conv` lower to the Pallas kernel here? Shares
    the segment kernels' knob/backend contract (sorted receivers are
    the caller contract, so only the knob/backend part is checked)."""
    return pallas_available() and _kernel_eligible(indices_are_sorted=True)


def _pad128(h: int) -> int:
    return ((h + 127) // 128) * 128


def _pad_cols(a: Optional[jnp.ndarray], w: int) -> Optional[jnp.ndarray]:
    if a is None or a.shape[-1] == w:
        return a
    return jnp.concatenate(
        [a, jnp.zeros(a.shape[:-1] + (w - a.shape[-1],), a.dtype)], axis=-1
    )


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _make_fused_kernel(k_br, acts, has_rtab, has_eterm, has_scale, hp, hop,
                       x_bf16):
    """Build the kernel closure for one static layout. Ref layout (after
    the two scalar-prefetch refs):

      inputs : x, send, recv, mask, [w, b], [rtab], [eterm], [scale]
      outputs: out
      scratch: win(2,BW,hp), send(2,1,CE), recv(2,1,CE), mask(2,1,CE),
               [eterm(2,CE,k*hop)], [scale(2,CE,hop)], gacc(CE,hp) f32,
               sem_ids(2,S), sem_win(2,)
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_id_streams = 3 + (1 if has_eterm else 0) + (1 if has_scale else 0)

    def kernel(ptr_ref, plan_ref, *refs):
        it = iter(refs)
        x_hbm = next(it)
        send_hbm = next(it)
        recv_hbm = next(it)
        mask_hbm = next(it)
        w_ref = next(it) if k_br else None
        b_ref = next(it) if k_br else None
        rtab_ref = next(it) if has_rtab else None
        eterm_hbm = next(it) if has_eterm else None
        scale_hbm = next(it) if has_scale else None
        out_ref = next(it)
        win_vmem = next(it)
        send_vmem = next(it)
        recv_vmem = next(it)
        mask_vmem = next(it)
        eterm_vmem = next(it) if has_eterm else None
        scale_vmem = next(it) if has_scale else None
        gacc_ref = next(it)
        sem_ids = next(it)
        sem_win = next(it)

        i = pl.program_id(0)
        # Occupancy clamp (ISSUE 10): plan row 3 carries the index after
        # the last slot that can hold a REAL edge. Everything past it is
        # padding whose messages the mask would zero anyway — bounding
        # [lo, hi) at the occupancy makes fully-padded tail chunks cost
        # zero DMAs and zero MXU work while leaving every contributing
        # term bit-identical (skipped chunks contributed exact +0: the
        # mask factor zeroes their messages before the scatter, and the
        # bf16 split of 0 is 0).
        occ = plan_ref[3, 0]
        lo = jnp.minimum(ptr_ref[i], occ)
        hi = jnp.minimum(ptr_ref[i + 1], occ)
        n_clamp = plan_ref[2, 0]
        out_ref[:] = jnp.zeros_like(out_ref)
        k0 = lo // CE
        k1 = (hi + CE - 1) // CE

        def id_dmas(slot, k):
            start = pl.multiple_of(k * CE, CE)
            cps = [
                pltpu.make_async_copy(
                    send_hbm.at[:, pl.ds(start, CE)], send_vmem.at[slot],
                    sem_ids.at[slot, 0],
                ),
                pltpu.make_async_copy(
                    recv_hbm.at[:, pl.ds(start, CE)], recv_vmem.at[slot],
                    sem_ids.at[slot, 1],
                ),
                pltpu.make_async_copy(
                    mask_hbm.at[:, pl.ds(start, CE)], mask_vmem.at[slot],
                    sem_ids.at[slot, 2],
                ),
            ]
            s = 3
            if has_eterm:
                cps.append(
                    pltpu.make_async_copy(
                        eterm_hbm.at[pl.ds(start, CE), :], eterm_vmem.at[slot],
                        sem_ids.at[slot, s],
                    )
                )
                s += 1
            if has_scale:
                cps.append(
                    pltpu.make_async_copy(
                        scale_hbm.at[pl.ds(start, CE), :], scale_vmem.at[slot],
                        sem_ids.at[slot, s],
                    )
                )
            return cps

        def win_dma(slot, wstart):
            return pltpu.make_async_copy(
                x_hbm.at[
                    pl.ds(
                        pl.multiple_of(jnp.minimum(wstart, n_clamp), ALIGN), BW
                    ),
                    :,
                ],
                win_vmem.at[slot],
                sem_win.at[slot],
            )

        @pl.when(k0 < k1)
        def _warmup():
            for cp in id_dmas(k0 % 2, k0):
                cp.start()
            win_dma(k0 % 2, plan_ref[0, k0]).start()

        def chunk_body(k, _):
            slot = k % 2

            @pl.when(k + 1 < k1)
            def _prefetch_ids():
                for cp in id_dmas((k + 1) % 2, k + 1):
                    cp.start()

            for cp in id_dmas(slot, k):
                cp.wait()
            send = send_vmem[slot][0, :]  # [CE]
            astart = plan_ref[0, k]
            wcnt = plan_ref[1, k]
            gacc_ref[:] = jnp.zeros_like(gacc_ref)

            # -- windowed sender gather (exact one-hot row copies) --
            def window_body(w, _):
                wslot = (k + w) % 2
                wstart = astart + w * BW

                @pl.when(w + 1 < wcnt)
                def _prefetch_win():
                    win_dma((k + w + 1) % 2, wstart + BW).start()

                win_dma(wslot, wstart).wait()
                cstart = jnp.minimum(wstart, n_clamp)
                local = send - cstart
                in_range = (send >= wstart) & (send < wstart + BW)
                local = jnp.where(in_range, local, -1)
                onehot = (
                    local[:, None]
                    == jax.lax.broadcasted_iota(jnp.int32, (CE, BW), 1)
                )
                win = win_vmem[wslot]
                if win.dtype == jnp.float32:
                    gacc_ref[:] += jax.lax.dot_general(
                        onehot.astype(jnp.float32), win,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST,
                    )
                else:
                    gacc_ref[:] += jax.lax.dot_general(
                        onehot.astype(win.dtype), win,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                return 0

            jax.lax.fori_loop(0, wcnt, window_body, 0)

            # Issue chunk k+1's gather DMA BEFORE the MLP/scatter
            # matmuls below: the next chunk's HBM read overlaps this
            # chunk's compute (the tentpole's cross-block double
            # buffering; its target buffer's previous DMA was waited
            # inside the window loop above).
            @pl.when(k + 1 < k1)
            def _prefetch_next_win():
                win_dma((k + 1) % 2, plan_ref[0, k + 1]).start()

            v = gacc_ref[:]  # [CE, hp] f32, exact copies of x rows
            rows = jax.lax.broadcasted_iota(jnp.int32, (BN, CE), 0) + i * BN
            onehot_r = recv_vmem[slot] == rows  # [BN, CE]
            mf = mask_vmem[slot][0, :].astype(jnp.float32)[:, None]  # [CE,1]

            if k_br:
                # edge MLP in VMEM: f32 accumulation throughout; bf16
                # models round only the operands/messages (matching the
                # XLA fallback's compute dtype within tolerance)
                if x_bf16:
                    pre = jax.lax.dot_general(
                        v.astype(jnp.bfloat16), w_ref[:],
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                else:
                    pre = jax.lax.dot_general(
                        v, w_ref[:], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST,
                    )
                pre = pre + b_ref[:]  # [1, k*hop] broadcasts
                if has_rtab:
                    # receiver-side term: transpose of the scatter
                    # one-hot against the node-blocked table — exact
                    # row copies for in-block receivers; stray edges
                    # (chunk overhang) get garbage rows but never
                    # scatter into this block
                    rt = rtab_ref[:]
                    if rt.dtype == jnp.float32:
                        pre = pre + jax.lax.dot_general(
                            onehot_r.astype(jnp.float32), rt,
                            (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST,
                        )
                    else:
                        pre = pre + jax.lax.dot_general(
                            onehot_r.astype(rt.dtype), rt,
                            (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )
                if has_eterm:
                    pre = pre + eterm_vmem[slot].astype(jnp.float32)
                msg = None
                for kk in range(k_br):
                    p = pre[:, kk * hop : (kk + 1) * hop]
                    a = _ACTS[acts[kk]][0](p)
                    msg = a if msg is None else msg * a
            else:
                msg = v
            if has_scale:
                msg = msg * scale_vmem[slot].astype(jnp.float32)
            msg = msg * mf

            # -- masked one-hot scatter into the out block (f32 acc) --
            onehot_t = onehot_r.astype(jnp.bfloat16)
            if x_bf16:
                # bf16 models: the XLA fallback's message is bf16 too,
                # so rounding here matches; products are then native-MXU
                out_ref[:] += jax.lax.dot_general(
                    onehot_t, msg.astype(jnp.bfloat16),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            else:
                # f32 messages: 3-term bf16 split (hi+mid+lo carries the
                # full f32 significand) x exact 0/1 one-hot — the same
                # scheme as segment_pallas._csr_chunk_loop's f32 path
                r = msg
                hi_t = r.astype(jnp.bfloat16)
                r1 = r - hi_t.astype(jnp.float32)
                mid_t = r1.astype(jnp.bfloat16)
                lo_t = (r1 - mid_t.astype(jnp.float32)).astype(jnp.bfloat16)
                for term in (hi_t, mid_t, lo_t):
                    out_ref[:] += jax.lax.dot_general(
                        onehot_t, term, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
            return 0

        jax.lax.fori_loop(k0, k1, chunk_body, 0)

    return kernel, n_id_streams


def _fused_kernel_call(x, senders, receivers, mask, w_cat, b_cat, rtab,
                       eterm, scale, real_edges, num_segments, spec,
                       interpret):
    """Shard-local fused kernel invocation. Operands are pre-padded to
    128-lane widths by the dispatcher; receivers sorted ascending.
    ``real_edges`` ([1] int32 or None) bounds the chunk loop — None
    processes the full edge pad (always correct; `ptr <= e` already)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k_br, acts = spec
    e = senders.shape[0]
    n, hp = x.shape
    hop = (w_cat.shape[1] // k_br) if k_br else hp
    xd = x.dtype

    n_pad_out = ((num_segments + BN - 1) // BN) * BN
    # sender gather table padding: window DMAs need BW rows headroom
    n_pad_t = max(((n + ALIGN - 1) // ALIGN) * ALIGN, BW)
    if n_pad_t != n:
        x = jnp.concatenate([x, jnp.zeros((n_pad_t - n, hp), xd)], axis=0)
    e_pad = ((e + CE - 1) // CE) * CE
    send = jnp.concatenate(
        [senders.astype(jnp.int32), jnp.full((e_pad - e,), n_pad_t, jnp.int32)]
    )
    recv = jnp.concatenate(
        [receivers.astype(jnp.int32), jnp.full((e_pad - e,), n_pad_out, jnp.int32)]
    )
    mask_i = jnp.concatenate(
        [mask.astype(jnp.int32), jnp.zeros((e_pad - e,), jnp.int32)]
    )
    n_blocks = n_pad_out // BN
    boundaries = jnp.arange(n_blocks + 1, dtype=jnp.int32) * BN
    block_ptr = jnp.searchsorted(recv[:e], boundaries, side="left").astype(jnp.int32)
    n_chunks = e_pad // CE
    plan = _window_plan_local(send, n_pad_t, n_chunks, ce=CE)
    # plan row 3: the occupancy bound for the kernel's chunk-loop clamp.
    # Defaults to e (a no-op: block_ptr <= e by construction); clamped
    # to e so a stale/overshooting caller value cannot read past the pad.
    occ = (
        jnp.full((1,), e, jnp.int32)
        if real_edges is None
        else jnp.minimum(real_edges.reshape(1).astype(jnp.int32), e)
    )
    plan = jnp.concatenate(
        [plan, jnp.broadcast_to(occ, (1, n_chunks))], axis=0
    )

    operands = [x, send[None, :], recv[None, :], mask_i[None, :]]
    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),  # x (manual windowed DMA)
        pl.BlockSpec(memory_space=pl.ANY),  # send
        pl.BlockSpec(memory_space=pl.ANY),  # recv
        pl.BlockSpec(memory_space=pl.ANY),  # mask
    ]
    if k_br:
        operands += [w_cat, b_cat.astype(jnp.float32)]
        in_specs += [
            pl.BlockSpec((hp, k_br * hop), lambda i, p, q: (0, 0)),
            pl.BlockSpec((1, k_br * hop), lambda i, p, q: (0, 0)),
        ]
    has_rtab = rtab is not None
    if has_rtab:
        rt = jnp.concatenate(
            [rtab, jnp.zeros((n_pad_out - rtab.shape[0], rtab.shape[1]), rtab.dtype)],
            axis=0,
        )
        operands.append(rt)
        in_specs.append(
            pl.BlockSpec((BN, k_br * hop), lambda i, p, q: (i, 0))
        )
    has_eterm = eterm is not None
    if has_eterm:
        et = jnp.concatenate(
            [eterm, jnp.zeros((e_pad - e, eterm.shape[1]), eterm.dtype)], axis=0
        )
        operands.append(et)
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
    has_scale = scale is not None
    if has_scale:
        sc = jnp.concatenate(
            [scale, jnp.zeros((e_pad - e, scale.shape[1]), scale.dtype)], axis=0
        )
        operands.append(sc)
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))

    kernel, n_id_streams = _make_fused_kernel(
        k_br, acts, has_rtab, has_eterm, has_scale, hp, hop,
        x_bf16=(xd == jnp.bfloat16),
    )
    scratch = [
        pltpu.VMEM((2, BW, hp), xd),
        pltpu.VMEM((2, 1, CE), jnp.int32),
        pltpu.VMEM((2, 1, CE), jnp.int32),
        pltpu.VMEM((2, 1, CE), jnp.int32),
    ]
    if has_eterm:
        scratch.append(pltpu.VMEM((2, CE, k_br * hop), et.dtype))
    if has_scale:
        scratch.append(pltpu.VMEM((2, CE, hop), sc.dtype))
    scratch += [
        pltpu.VMEM((CE, hp), jnp.float32),
        pltpu.SemaphoreType.DMA((2, n_id_streams)),
        pltpu.SemaphoreType.DMA((2,)),
    ]

    vma = _vma_of(*operands)
    operands = [_match_vma(o, vma) for o in operands]
    block_ptr = _match_vma(block_ptr, vma)
    plan = _match_vma(plan, vma)
    out_sds = _sds((n_pad_out, hop), jnp.float32, vma=vma)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((BN, hop), lambda i, p, q: (i, 0)),
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=out_sds,
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_ptr, plan, *operands)
    return out[:num_segments]


# ---------------------------------------------------------------------------
# custom_partitioning wrapper (edge-axis rule, like the segment kernels)
# ---------------------------------------------------------------------------

_FUSED_OPS: dict = {}


def _get_partitioned_fused(layout: Tuple[str, ...]):
    """One custom_partitioning op per operand layout. ``layout`` tags
    each tensor operand's leading-axis kind: "n" node-space (replicated
    under edge sharding), "e"/"t"/"s" edge-space (ids/mask, eterm,
    scale — all sharded on the edge mesh axis), "p" parameter
    (replicated). Statics (spec, num_segments, interpret) ride as
    trailing static args."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    if layout in _FUSED_OPS:
        return _FUSED_OPS[layout]

    n_ops = len(layout)

    def base(*args):
        operands = args[:n_ops]
        spec, num_segments, interpret = args[n_ops], args[n_ops + 1], args[n_ops + 2]
        return _fused_kernel_call(
            *_unflatten_operands(layout, operands), num_segments, spec, interpret
        )

    op = custom_partitioning(base, static_argnums=(n_ops, n_ops + 1, n_ops + 2))

    def infer(spec, num_segments, interpret, mesh, arg_shapes, result_shape):
        return NamedSharding(mesh, P())

    def partition(spec, num_segments, interpret, mesh, arg_shapes, result_shape):
        senders_spec = arg_shapes[1].sharding.spec
        edge_axis = senders_spec[0] if len(senders_spec) >= 1 else None

        def lower_fn(*operands):
            out = _fused_kernel_call(
                *_unflatten_operands(layout, operands), num_segments, spec,
                interpret,
            )
            if edge_axis is not None:
                out = jax.lax.psum(out, edge_axis)
            return out

        arg_sh = []
        for kind, shp in zip(layout, arg_shapes):
            nd = len(shp.shape)
            if kind in ("e", "t", "s"):
                arg_sh.append(
                    NamedSharding(mesh, P(*((edge_axis,) + (None,) * (nd - 1))))
                )
            else:
                arg_sh.append(NamedSharding(mesh, P(*((None,) * nd))))
        return mesh, lower_fn, NamedSharding(mesh, P()), tuple(arg_sh)

    # shardy rule (newer jax): edge-dim operands share factor "e",
    # node-space the output's "n"; distinct width factors per operand.
    # The occupancy scalar ("o", [1]) is replicated — its one dim gets
    # its own private factor.
    parts = []
    for idx, kind in enumerate(layout):
        if kind in ("e", "t", "s"):
            parts.append("e" if idx in (1, 2, 3) else f"e w{idx}")
        elif kind == "n":
            parts.append(f"n w{idx}")
        elif kind == "o":
            parts.append(f"o{idx}")
        else:
            parts.append(f"p{idx} w{idx}")
    _def_partition_compat(
        op,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule=", ".join(parts) + " -> n h",
    )
    _FUSED_OPS[layout] = op
    return op


def _flatten_operands(x, senders, receivers, mask, w_cat, b_cat, rtab, eterm,
                      scale, real_edges):
    """(layout, operands) with absent optionals dropped — the layout is
    the partitioned-op cache key and the unflatten schema. The occupancy
    scalar travels last as kind "o" ([1] int32, replicated: a shard's
    local real-edge positions are <= their global positions, so the
    global bound never clips a shard-local real edge)."""
    layout = ["n", "e", "e", "e"]
    operands = [x, senders, receivers, mask]
    for a, kind in ((w_cat, "p"), (b_cat, "p"), (rtab, "n"), (eterm, "t"),
                    (scale, "s"), (real_edges, "o")):
        if a is not None:
            layout.append(kind)
            operands.append(a)
    return tuple(layout), operands


def _unflatten_operands(layout, operands):
    """Inverse of :func:`_flatten_operands` for the op body: positions
    4+ are (w, b, rtab, eterm, scale, real_edges) in order, present or
    None."""
    it = list(operands[4:])
    x, senders, receivers, mask = operands[:4]
    kinds = list(layout[4:])
    # w/b always travel together (both "p", w first)
    w_cat = it.pop(0) if "p" in kinds else None
    b_cat = it.pop(0) if "p" in kinds else None
    rtab = it.pop(0) if "n" in kinds else None
    eterm = it.pop(0) if "t" in kinds else None
    scale = it.pop(0) if "s" in kinds else None
    real_edges = it.pop(0) if "o" in kinds else None
    return (x, senders, receivers, mask, w_cat, b_cat, rtab, eterm, scale,
            real_edges)


# ---------------------------------------------------------------------------
# forward impl + hand-written VJP
# ---------------------------------------------------------------------------


def _branch_pres(v, branches, recv_gather):
    """Per-branch pre-activations of the edge network, compute dtype."""
    pres = []
    for (W, b, rtab, eterm) in branches:
        pre = v @ W.astype(v.dtype)
        if b is not None:
            pre = pre + b.astype(pre.dtype)
        if rtab is not None:
            pre = pre + recv_gather(rtab.astype(pre.dtype))
        if eterm is not None:
            pre = pre + eterm.astype(pre.dtype)
        pres.append(pre)
    return pres


def _fused_ref(spec, num_segments, x, senders, receivers, mask, branches,
               scale):
    """The bit-compatible XLA fallback: the identical composition in
    plain jnp — also the contract the kernel is tested against."""
    k_br, acts = spec
    v = x[senders]
    if k_br:
        pres = _branch_pres(v, branches, lambda t: t[receivers])
        msg = None
        for kk in range(k_br):
            a = _ACTS[acts[kk]][0](pres[kk])
            msg = a if msg is None else msg * a
    else:
        msg = v
    if scale is not None:
        msg = msg * scale.astype(msg.dtype)
    msg = jnp.where(mask[:, None], msg, 0).astype(jnp.float32)
    return jax.ops.segment_sum(
        msg, receivers, num_segments, indices_are_sorted=True
    )


def _cat_branches(branches):
    """Stack the K branches' params on the output axis for the kernel:
    W_cat [Hin, K*Hout], b_cat [1, K*Hout] (zeros where absent),
    rtab_cat [N, K*Hout] / eterm_cat [E, K*Hout] (zeros for branches
    without one; None when NO branch has one)."""
    if not branches:
        return None, None, None, None
    ws = [W for (W, _, _, _) in branches]
    hout = ws[0].shape[1]
    w_cat = jnp.concatenate(ws, axis=1)
    b_cat = jnp.concatenate(
        [
            (b if b is not None else jnp.zeros((hout,), w_cat.dtype)).reshape(1, -1)
            for (_, b, _, _) in branches
        ],
        axis=1,
    )
    rtab_cat = eterm_cat = None
    if any(r is not None for (_, _, r, _) in branches):
        n = next(r for (_, _, r, _) in branches if r is not None).shape[0]
        rtab_cat = jnp.concatenate(
            [
                r if r is not None else jnp.zeros((n, hout), w_cat.dtype)
                for (_, _, r, _) in branches
            ],
            axis=1,
        )
    if any(e is not None for (_, _, _, e) in branches):
        ne = next(e for (_, _, _, e) in branches if e is not None).shape[0]
        eterm_cat = jnp.concatenate(
            [
                e if e is not None else jnp.zeros((ne, hout), w_cat.dtype)
                for (_, _, _, e) in branches
            ],
            axis=1,
        )
    return w_cat, b_cat, rtab_cat, eterm_cat


def _fused_impl(spec, num_segments, use_kernel, interpret, x, senders,
                receivers, mask, win, real_edges, branches, scale):
    if not use_kernel or senders.shape[0] == 0:
        # the reference path ignores the occupancy bound: skipped chunks
        # only ever held masked edges, whose messages the jnp.where
        # zeroes — the two paths are definitionally identical
        return _fused_ref(
            spec, num_segments, x, senders, receivers, mask, branches, scale
        )
    w_cat, b_cat, rtab_cat, eterm_cat = _cat_branches(branches)
    layout, operands = _flatten_operands(
        x, senders.astype(jnp.int32), receivers.astype(jnp.int32),
        jax.lax.stop_gradient(mask), w_cat, b_cat, rtab_cat, eterm_cat, scale,
        None if real_edges is None
        else jax.lax.stop_gradient(real_edges).reshape(1).astype(jnp.int32),
    )
    op = _get_partitioned_fused(layout)
    return op(*operands, spec, num_segments, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _fused_conv(spec, num_segments, use_kernel, interpret, x, senders,
                receivers, mask, win, real_edges, branches, scale):
    return _fused_impl(spec, num_segments, use_kernel, interpret, x, senders,
                       receivers, mask, win, real_edges, branches, scale)


def _fused_conv_fwd(spec, num_segments, use_kernel, interpret, x, senders,
                    receivers, mask, win, real_edges, branches, scale):
    out = _fused_impl(spec, num_segments, use_kernel, interpret, x, senders,
                      receivers, mask, win, real_edges, branches, scale)
    return out, (x, senders, receivers, mask, win, real_edges, branches, scale)


def _fused_conv_bwd(spec, num_segments, use_kernel, interpret, res, g):
    """Hand-written backward from the closed-form chain, built on the
    fast machinery: sorted CSR-broadcast for the node->edge cotangent
    gathers, local-window segment sum for the sender scatter (no edge
    permute), sorted CSR sum for rtab grads, MXU contractions for W/b.
    Recomputes v (one gather) and the branch pre-activations instead of
    saving [E, *] residuals — the same recompute-over-HBM trade as the
    PNA presum backward."""
    k_br, acts = spec
    x, senders, receivers, mask, win, real_edges, branches, scale = res
    dt = x.dtype
    n = x.shape[0]
    f0 = jax.dtypes.float0

    def egather(t):
        if use_kernel and t.ndim == 2:
            return gather_rows_sorted_fast(t, receivers)
        return t[receivers]

    def sgather(t):
        if use_kernel and win is not None and t.ndim == 2:
            return gather_rows_local_fast(t, senders)
        return t[senders]

    def sender_scatter(grad_v):
        if use_kernel and win is not None:
            return segment_sum_local_fast(grad_v, senders, win, n)
        return jax.ops.segment_sum(grad_v.astype(jnp.float32), senders, n)

    ge = egather(g.astype(dt))  # [E, Hout]
    mfac = mask[:, None].astype(dt)
    g_msg = ge * mfac
    g_scale = None

    if k_br:
        v = sgather(x)
        pres = _branch_pres(v, branches, egather)
        a = [_ACTS[acts[kk]][0](pres[kk]) for kk in range(k_br)]
        if scale is not None:
            prod_all = a[0]
            for kk in range(1, k_br):
                prod_all = prod_all * a[kk]
            g_scale = (g_msg * prod_all).astype(scale.dtype)
            g_msg = g_msg * scale.astype(g_msg.dtype)
        g_branches = []
        grad_v = None
        for kk in range(k_br):
            others = None
            for jj in range(k_br):
                if jj == kk:
                    continue
                others = a[jj] if others is None else others * a[jj]
            g_pre = g_msg if others is None else g_msg * others
            g_pre = g_pre * _ACTS[acts[kk]][1](pres[kk], a[kk])
            W, b, rtab, eterm = branches[kk]
            term = g_pre @ W.astype(g_pre.dtype).T
            grad_v = term if grad_v is None else grad_v + term
            gW = (
                v.astype(jnp.float32).T @ g_pre.astype(jnp.float32)
            ).astype(W.dtype)
            gb = (
                g_pre.astype(jnp.float32).sum(axis=0).astype(b.dtype)
                if b is not None
                else None
            )
            grtab = (
                segment_sum_fast(
                    g_pre, receivers, n, indices_are_sorted=True
                ).astype(rtab.dtype)
                if rtab is not None
                else None
            )
            geterm = g_pre.astype(eterm.dtype) if eterm is not None else None
            g_branches.append((gW, gb, grtab, geterm))
        g_branches = tuple(g_branches)
    else:
        if scale is not None:
            v = sgather(x)
            g_scale = (g_msg * v).astype(scale.dtype)
            grad_v = g_msg * scale.astype(g_msg.dtype)
        else:
            grad_v = g_msg
        g_branches = branches  # () — empty structure

    grad_x = sender_scatter(grad_v).astype(dt)
    return (
        grad_x,
        jnp.zeros(senders.shape, dtype=f0),
        jnp.zeros(receivers.shape, dtype=f0),
        jnp.zeros(mask.shape, dtype=f0),
        None if win is None else jnp.zeros(win.shape, dtype=f0),
        None if real_edges is None else jnp.zeros(real_edges.shape, dtype=f0),
        g_branches,
        g_scale,
    )


_fused_conv.defvjp(_fused_conv_fwd, _fused_conv_bwd)


# ---------------------------------------------------------------------------
# public dispatcher
# ---------------------------------------------------------------------------


def fused_conv(
    x: jnp.ndarray,
    senders: jnp.ndarray,
    receivers: jnp.ndarray,
    edge_mask: jnp.ndarray,
    num_segments: int,
    branches: Sequence[Tuple] = (),
    acts: Sequence[str] = (),
    scale: Optional[jnp.ndarray] = None,
    win: Optional[jnp.ndarray] = None,
    real_edges: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Fused gather -> edge network -> masked scatter (module docstring).

    ``branches``: up to two ``(W [Hin, Hout], b [Hout]|None,
    rtab [N, Hout]|None, eterm [E, Hout]|None)`` tuples whose activated
    outputs multiply elementwise (one branch = a plain edge MLP, two =
    the CGCNN gate); empty = identity messages (Hout = Hin).
    ``acts``: one activation name per branch (see ``_ACTS``).
    ``scale``: optional [E, Hout] per-edge factor (SchNet filter).
    ``win``: loader-emitted sender block windows ([2, n_blocks] int32)
    — routes the backward's sender scatter through the local-window
    kernel; without it the backward falls back to XLA's scatter-add.
    ``real_edges``: optional scalar int32 occupancy bound
    (GraphBatch.edge_occupancy) — every edge slot at position >=
    real_edges must be MASKED; the kernel then skips fully-padded tail
    chunks entirely (zero DMAs, zero MXU work) with bit-identical
    output. None processes the full pad.

    CONTRACT: ``receivers`` sorted ascending (the loader contract all
    convs rely on — same as ``segment_sum_family``). Returns float32
    [num_segments, Hout]; callers cast. The mask is non-differentiable.
    """
    branches = tuple(tuple(br) for br in branches)
    acts = tuple(acts)
    if len(acts) != len(branches):
        raise ValueError(
            f"fused_conv: {len(branches)} branches but {len(acts)} activations"
        )
    if len(branches) > 2:
        raise ValueError("fused_conv supports at most 2 edge-network branches")
    for name in acts:
        if name not in _ACTS:
            raise ValueError(f"unknown fused_conv activation {name!r}")
    hout = branches[0][0].shape[1] if branches else x.shape[1]
    spec = (len(branches), acts)
    use_kernel = fused_conv_active() and senders.shape[0] > 0
    interpret = _interpret_mode()
    mask = jax.lax.stop_gradient(edge_mask)

    if not use_kernel:
        return _fused_conv(spec, num_segments, False, False, x, senders,
                           receivers, mask, win, real_edges, branches, scale)

    # lane-pad every width to the 128-lane kernel tile; padding lives
    # OUTSIDE the custom-vjp op, so AD slices the cotangents back
    hp = _pad128(x.shape[1])
    hop = _pad128(hout)
    xk = _pad_cols(x, hp)
    brk = tuple(
        (
            _pad_cols(
                jnp.concatenate(
                    [W, jnp.zeros((hp - W.shape[0], W.shape[1]), W.dtype)], axis=0
                )
                if W.shape[0] != hp
                else W,
                hop,
            ),
            _pad_cols(b, hop),
            _pad_cols(r, hop),
            _pad_cols(e_, hop),
        )
        for (W, b, r, e_) in branches
    )
    sck = _pad_cols(scale, hop)
    out = _fused_conv(spec, num_segments, True, interpret, xk, senders,
                      receivers, mask, win, real_edges, brk, sck)
    return out[:, :hout]


# ---------------------------------------------------------------------------
# cross-layer VMEM residency: the fused conv STACK
# ---------------------------------------------------------------------------
#
# A width-preserving stack of L fused conv layers executed as ONE kernel
# with the node features RESIDENT in VMEM between layers:
#
#     h_0     = x
#     out_l   = segment_sum(mask * act_e(h_l[send] @ W_l + b_l))
#     h_{l+1} = act_i(out_l)
#
# returning out_{L-1} (no inter-layer activation on the last layer).
# The single-layer kernel reads the gather table from HBM once per
# sender window per chunk and writes the layer output back to HBM — for
# an L-layer stack that is L full round trips of the node features.
# Here the features live in a ping-pong VMEM scratch pair: layer l
# gathers its windows from slot l%2 with plain VMEM dynamic slices
# (zero HBM gather traffic after the one-time load) and writes its
# activated out blocks into slot (l+1)%2. Per-layer weights arrive as a
# blocked [L, hp, hp] operand whose index map advances with the layer
# grid dim, so Pallas's input pipeline double-buffers layer l+1's
# weight DMA behind layer l's compute. The TPU grid (L, n_blocks)
# executes sequentially in lexicographic order — every block of layer l
# completes before layer l+1 starts, which is what makes the ping-pong
# safe.
#
# Restrictions (enforced by the dispatcher, which falls back to the
# per-layer loop): square weights (width-preserving), f32 activations,
# num_segments == x.shape[0] (outputs feed back as inputs), one edge
# MLP per layer (no rtab/eterm/scale — those are per-layer functions of
# h_l and would have to be recomputed in-kernel), and the VMEM
# footprint estimate under HYDRAGNN_RESIDENCY_VMEM_MB. Intermediate
# layers' out-block flushes do write garbage to the output's HBM
# buffer, but the final layer's flush overwrites every block (last
# writer wins on the sequential grid) — the waste is L-1 node-space
# writes, far smaller than the L-1 edge-space gather round trips
# deleted.


def _make_stack_kernel(act_e, act_i, hp, n_layers):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(ptr_ref, plan_ref, *refs):
        (x_hbm, send_hbm, recv_hbm, mask_hbm, w_ref, b_ref, out_ref,
         xbuf, send_vmem, recv_vmem, mask_vmem, gacc_ref,
         sem_ids, sem_x) = refs

        l = pl.program_id(0)
        i = pl.program_id(1)
        # same occupancy clamp as the single-layer kernel: the edge set
        # is identical for every layer, so skipped tail chunks are
        # skipped L times over
        occ = plan_ref[3, 0]
        lo = jnp.minimum(ptr_ref[i], occ)
        hi = jnp.minimum(ptr_ref[i + 1], occ)
        n_clamp = plan_ref[2, 0]
        out_ref[:] = jnp.zeros_like(out_ref)
        k0 = lo // CE
        k1 = (hi + CE - 1) // CE
        sslot = l % 2  # layer l reads slot l%2, writes slot (l+1)%2

        # one-time residency load at grid step (0, 0): x -> slot 0, and
        # zero slot 1 so rows outside the written blocks ([n_pad_out,
        # n_res), never stored to) read as exact zeros in every layer
        @pl.when((l == 0) & (i == 0))
        def _load_resident():
            cp = pltpu.make_async_copy(x_hbm, xbuf.at[0], sem_x.at[0])
            cp.start()
            cp.wait()
            xbuf[1] = jnp.zeros(xbuf.shape[1:], xbuf.dtype)

        def id_dmas(slot, k):
            start = pl.multiple_of(k * CE, CE)
            return [
                pltpu.make_async_copy(
                    send_hbm.at[:, pl.ds(start, CE)], send_vmem.at[slot],
                    sem_ids.at[slot, 0],
                ),
                pltpu.make_async_copy(
                    recv_hbm.at[:, pl.ds(start, CE)], recv_vmem.at[slot],
                    sem_ids.at[slot, 1],
                ),
                pltpu.make_async_copy(
                    mask_hbm.at[:, pl.ds(start, CE)], mask_vmem.at[slot],
                    sem_ids.at[slot, 2],
                ),
            ]

        @pl.when(k0 < k1)
        def _warmup():
            for cp in id_dmas(k0 % 2, k0):
                cp.start()

        def chunk_body(k, _):
            slot = k % 2

            @pl.when(k + 1 < k1)
            def _prefetch_ids():
                for cp in id_dmas((k + 1) % 2, k + 1):
                    cp.start()

            for cp in id_dmas(slot, k):
                cp.wait()
            send = send_vmem[slot][0, :]  # [CE]
            astart = plan_ref[0, k]
            wcnt = plan_ref[1, k]
            gacc_ref[:] = jnp.zeros_like(gacc_ref)

            # windowed sender gather — same one-hot math as the single
            # kernel, but the window is a VMEM slice of the resident
            # buffer instead of an HBM DMA (the traffic this mode
            # deletes). The source slot alternates per layer; the two
            # pl.when branches keep the slot index static for the load.
            def window_body(w, _):
                wstart = astart + w * BW
                cstart = pl.multiple_of(
                    jnp.minimum(wstart, n_clamp), ALIGN
                )
                local = send - cstart
                in_range = (send >= wstart) & (send < wstart + BW)
                local = jnp.where(in_range, local, -1)
                onehot = (
                    local[:, None]
                    == jax.lax.broadcasted_iota(jnp.int32, (CE, BW), 1)
                ).astype(jnp.float32)

                def accumulate(win):
                    gacc_ref[:] += jax.lax.dot_general(
                        onehot, win, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST,
                    )

                @pl.when(sslot == 0)
                def _from_slot0():
                    accumulate(xbuf[0, pl.ds(cstart, BW), :])

                @pl.when(sslot == 1)
                def _from_slot1():
                    accumulate(xbuf[1, pl.ds(cstart, BW), :])

                return 0

            jax.lax.fori_loop(0, wcnt, window_body, 0)

            v = gacc_ref[:]  # [CE, hp] f32, exact copies of h_l rows
            rows = jax.lax.broadcasted_iota(jnp.int32, (BN, CE), 0) + i * BN
            onehot_r = recv_vmem[slot] == rows  # [BN, CE]
            mf = mask_vmem[slot][0, :].astype(jnp.float32)[:, None]

            # this layer's edge MLP (w_ref block = [1, hp, hp] at layer l)
            pre = jax.lax.dot_general(
                v, w_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            pre = pre + b_ref[0]  # [1, hp] broadcasts
            msg = _ACTS[act_e][0](pre) * mf

            # masked one-hot scatter, 3-term bf16 split (exact f32)
            onehot_t = onehot_r.astype(jnp.bfloat16)
            hi_t = msg.astype(jnp.bfloat16)
            r1 = msg - hi_t.astype(jnp.float32)
            mid_t = r1.astype(jnp.bfloat16)
            lo_t = (r1 - mid_t.astype(jnp.float32)).astype(jnp.bfloat16)
            for term in (hi_t, mid_t, lo_t):
                out_ref[:] += jax.lax.dot_general(
                    onehot_t, term, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            return 0

        jax.lax.fori_loop(k0, k1, chunk_body, 0)

        # hand the activated block to the next layer: store into the
        # TARGET slot (static index under pl.when, dynamic row offset).
        # Rows [num_segments, n_pad_out) get act_i(0) here where the
        # per-layer loop re-pads zeros — but no sender ever points at
        # them (senders < num_segments), so they are only ever read with
        # zero one-hot coefficients: exact +0 either way.
        @pl.when(l + 1 < n_layers)
        def _store_next():
            y = _ACTS[act_i][0](out_ref[:])
            row0 = pl.multiple_of(i * BN, BN)

            @pl.when(sslot == 0)
            def _to_slot1():
                xbuf[1, pl.ds(row0, BN), :] = y

            @pl.when(sslot == 1)
            def _to_slot0():
                xbuf[0, pl.ds(row0, BN), :] = y

    return kernel


def _stack_kernel_call(x, senders, receivers, mask, w_stack, b_stack,
                       real_edges, num_segments, spec, interpret):
    """Resident-stack kernel invocation. ``x`` pre-padded to 128 lanes,
    ``w_stack`` [L, hp, hp] f32, ``b_stack`` [L, 1, hp] f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    act_e, act_i, n_layers = spec
    e = senders.shape[0]
    n, hp = x.shape

    n_pad_out = ((num_segments + BN - 1) // BN) * BN
    # the resident buffer doubles as gather table AND inter-layer output
    # target: rows must cover both the window headroom and every written
    # out block (n_pad_out can exceed the single kernel's gather pad)
    n_res = max(((n + ALIGN - 1) // ALIGN) * ALIGN, BW, n_pad_out)
    if n_res != n:
        x = jnp.concatenate(
            [x, jnp.zeros((n_res - n, hp), x.dtype)], axis=0
        )
    e_pad = ((e + CE - 1) // CE) * CE
    send = jnp.concatenate(
        [senders.astype(jnp.int32), jnp.full((e_pad - e,), n_res, jnp.int32)]
    )
    recv = jnp.concatenate(
        [receivers.astype(jnp.int32), jnp.full((e_pad - e,), n_pad_out, jnp.int32)]
    )
    mask_i = jnp.concatenate(
        [mask.astype(jnp.int32), jnp.zeros((e_pad - e,), jnp.int32)]
    )
    n_blocks = n_pad_out // BN
    boundaries = jnp.arange(n_blocks + 1, dtype=jnp.int32) * BN
    block_ptr = jnp.searchsorted(recv[:e], boundaries, side="left").astype(jnp.int32)
    n_chunks = e_pad // CE
    plan = _window_plan_local(send, n_res, n_chunks, ce=CE)
    occ = (
        jnp.full((1,), e, jnp.int32)
        if real_edges is None
        else jnp.minimum(real_edges.reshape(1).astype(jnp.int32), e)
    )
    plan = jnp.concatenate([plan, jnp.broadcast_to(occ, (1, n_chunks))], axis=0)

    operands = [
        x, send[None, :], recv[None, :], mask_i[None, :],
        w_stack.astype(jnp.float32), b_stack.astype(jnp.float32),
    ]
    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),  # x (one-time residency DMA)
        pl.BlockSpec(memory_space=pl.ANY),  # send
        pl.BlockSpec(memory_space=pl.ANY),  # recv
        pl.BlockSpec(memory_space=pl.ANY),  # mask
        # per-layer params: block index follows the layer grid dim, so
        # the pipeline prefetches layer l+1's weights during layer l
        pl.BlockSpec((1, hp, hp), lambda l, i, p, q: (l, 0, 0)),
        pl.BlockSpec((1, 1, hp), lambda l, i, p, q: (l, 0, 0)),
    ]
    kernel = _make_stack_kernel(act_e, act_i, hp, n_layers)
    scratch = [
        pltpu.VMEM((2, n_res, hp), jnp.float32),  # resident ping-pong pair
        pltpu.VMEM((2, 1, CE), jnp.int32),
        pltpu.VMEM((2, 1, CE), jnp.int32),
        pltpu.VMEM((2, 1, CE), jnp.int32),
        pltpu.VMEM((CE, hp), jnp.float32),
        pltpu.SemaphoreType.DMA((2, 3)),
        pltpu.SemaphoreType.DMA((1,)),
    ]
    vma = _vma_of(*operands)
    operands = [_match_vma(o, vma) for o in operands]
    block_ptr = _match_vma(block_ptr, vma)
    plan = _match_vma(plan, vma)
    out_sds = _sds((n_pad_out, hp), jnp.float32, vma=vma)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_layers, n_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((BN, hp), lambda l, i, p, q: (i, 0)),
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=out_sds,
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_ptr, plan, *operands)
    return out[:num_segments]


def _stack_ref_loop(spec, num_segments, use_kernel, interpret, x, senders,
                    receivers, mask, win, real_edges, w_stack, b_stack):
    """Per-layer composition of ``_fused_conv`` — three jobs at once:
    the numerical contract the resident kernel is tested against
    (bit-exact in f32), the VMEM-budget fallback path (still per-layer
    fused kernels when available), and the backward's recompute target.
    Intermediate activations are cast back to the input dtype so bf16
    stacks stay bf16 layer to layer."""
    act_e, act_i, n_layers = spec
    h = x
    out = None
    for l in range(n_layers):
        branches = ((w_stack[l], b_stack[l].reshape(-1), None, None),)
        out = _fused_conv((1, (act_e,)), num_segments, use_kernel, interpret,
                          h, senders, receivers, mask, win, real_edges,
                          branches, None)
        if l + 1 < n_layers:
            h = _ACTS[act_i][0](out).astype(x.dtype)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _fused_stack(spec, num_segments, use_kernel, interpret, x, senders,
                 receivers, mask, win, real_edges, w_stack, b_stack):
    if use_kernel:
        return _stack_kernel_call(x, senders, receivers, mask, w_stack,
                                  b_stack, real_edges, num_segments, spec,
                                  interpret)
    return _stack_ref_loop(spec, num_segments, False, interpret, x, senders,
                           receivers, mask, win, real_edges, w_stack, b_stack)


def _fused_stack_fwd(spec, num_segments, use_kernel, interpret, x, senders,
                     receivers, mask, win, real_edges, w_stack, b_stack):
    out = _fused_stack(spec, num_segments, use_kernel, interpret, x, senders,
                       receivers, mask, win, real_edges, w_stack, b_stack)
    return out, (x, senders, receivers, mask, win, real_edges, w_stack, b_stack)


def _fused_stack_bwd(spec, num_segments, use_kernel, interpret, res, g):
    """Recompute-based backward: differentiate the per-layer composition
    (which runs the fast single-layer VJPs — local-window scatters, MXU
    contractions). The resident forward is bit-identical to that
    composition, so gradients are consistent by construction."""
    x, senders, receivers, mask, win, real_edges, w_stack, b_stack = res
    f0 = jax.dtypes.float0

    def f(x_, w_, b_):
        return _stack_ref_loop(spec, num_segments, use_kernel, interpret, x_,
                               senders, receivers, mask, win, real_edges,
                               w_, b_)

    _, vjp = jax.vjp(f, x, w_stack, b_stack)
    gx, gw, gb = vjp(g)
    return (
        gx,
        jnp.zeros(senders.shape, dtype=f0),
        jnp.zeros(receivers.shape, dtype=f0),
        jnp.zeros(mask.shape, dtype=f0),
        None if win is None else jnp.zeros(win.shape, dtype=f0),
        None if real_edges is None else jnp.zeros(real_edges.shape, dtype=f0),
        gw,
        gb,
    )


_fused_stack.defvjp(_fused_stack_fwd, _fused_stack_bwd)


def residency_vmem_budget_bytes() -> int:
    """VMEM the resident stack kernel may claim, from
    ``HYDRAGNN_RESIDENCY_VMEM_MB`` (default 12 — a TPU core has ~16MB
    and the compiler needs headroom for the pipeline's own buffers)."""
    return int(knobs.get_float("HYDRAGNN_RESIDENCY_VMEM_MB", 12.0) * (1 << 20))


def residency_vmem_bytes(num_nodes: int, width: int) -> int:
    """Estimated VMEM footprint of the resident stack kernel for a
    given gather-table size — the decision rule documented in
    docs/PERF.md r08. Dominated by the ping-pong feature pair.

    graftcheck contract CC006 (docs/LINT.md) re-derives this estimate
    from the entry point's shapes and fails CI when it exceeds the
    ``HYDRAGNN_RESIDENCY_VMEM_MB`` budget — or when the budget itself
    over-promises physical VMEM — so keep this arithmetic and
    ``hydragnn_tpu/lint/ir.py::check_vmem_budget`` telling one story."""
    hp = _pad128(width)
    n_pad_out = ((num_nodes + BN - 1) // BN) * BN
    n_res = max(((num_nodes + ALIGN - 1) // ALIGN) * ALIGN, BW, n_pad_out)
    return (
        2 * n_res * hp * 4        # resident ping-pong feature pair
        + 2 * (hp * hp + hp) * 4  # double-buffered layer params
        + 3 * 2 * CE * 4          # id chunk buffers
        + CE * hp * 4             # gather accumulator
        + 2 * BN * hp * 4         # out block double buffer
    )


def fused_conv_stack(
    x: jnp.ndarray,
    senders: jnp.ndarray,
    receivers: jnp.ndarray,
    edge_mask: jnp.ndarray,
    num_segments: int,
    weights: jnp.ndarray,
    biases: Optional[jnp.ndarray] = None,
    edge_act: str = "none",
    inter_act: str = "relu",
    win: Optional[jnp.ndarray] = None,
    real_edges: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """L fused conv layers with cross-layer VMEM residency (see the
    section comment above). Computes, for l in [0, L):

        h_0 = x;  out_l = segment_sum(mask * edge_act(h_l[send] @ W_l + b_l))
        h_{l+1} = inter_act(out_l)

    and returns out_{L-1} as float32 [num_segments, H] (no inter_act on
    the last layer; callers apply their own epilogue and cast).

    ``weights``: [L, H, H] (or a sequence of [H, H]) — width-preserving
    by construction. ``biases``: [L, H] or None. ``num_segments`` must
    equal ``x.shape[0]`` (outputs feed back as inputs). ``win`` /
    ``real_edges``: as in :func:`fused_conv`; the occupancy bound
    applies to every layer. Falls back to a per-layer loop of
    :func:`fused_conv` (same numerics) when the Pallas kernel is off,
    activations are not f32, or the estimated VMEM footprint exceeds
    :func:`residency_vmem_budget_bytes`."""
    if not isinstance(weights, jnp.ndarray):
        weights = jnp.stack([jnp.asarray(w) for w in weights], axis=0)
    if weights.ndim != 3 or weights.shape[1] != weights.shape[2]:
        raise ValueError(
            f"fused_conv_stack needs square [L, H, H] weights, got {weights.shape}"
        )
    n, h = x.shape
    n_layers = int(weights.shape[0])
    if weights.shape[1] != h:
        raise ValueError(
            f"weights width {weights.shape[1]} != feature width {h}"
        )
    if num_segments != n:
        raise ValueError(
            "fused_conv_stack feeds layer outputs back as inputs; "
            f"num_segments ({num_segments}) must equal x.shape[0] ({n})"
        )
    for name in (edge_act, inter_act):
        if name not in _ACTS:
            raise ValueError(f"unknown fused_conv_stack activation {name!r}")
    if biases is not None and not isinstance(biases, jnp.ndarray):
        biases = jnp.stack([jnp.asarray(b) for b in biases], axis=0)

    spec = (edge_act, inter_act, n_layers)
    mask = jax.lax.stop_gradient(edge_mask)
    use_kernel = fused_conv_active() and senders.shape[0] > 0
    interpret = _interpret_mode()

    hp = _pad128(h)
    xk = _pad_cols(x, hp)
    wk = weights
    if hp != h:
        wk = jnp.concatenate(
            [wk, jnp.zeros((n_layers, hp - h, h), wk.dtype)], axis=1
        )
        wk = _pad_cols(wk, hp)
    bk = (
        jnp.zeros((n_layers, 1, hp), wk.dtype)
        if biases is None
        else _pad_cols(biases, hp).reshape(n_layers, 1, hp)
    )
    re_ = (
        None
        if real_edges is None
        else jax.lax.stop_gradient(real_edges).reshape(1).astype(jnp.int32)
    )

    resident = (
        use_kernel
        and xk.dtype == jnp.float32
        and wk.dtype == jnp.float32
        and residency_vmem_bytes(n, h) <= residency_vmem_budget_bytes()
    )
    if resident:
        out = _fused_stack(spec, num_segments, True, interpret, xk, senders,
                           receivers, mask, win, re_, wk, bk)
    else:
        # per-layer dispatch: still the fused single-layer kernel when
        # available (each call carries its own VJP), plain XLA otherwise
        out = _stack_ref_loop(spec, num_segments, use_kernel, interpret, xk,
                              senders, receivers, mask, win, re_, wk, bk)
    return out[:, :h]
