"""Fused one-pass "sum-family" segment aggregation (sum, sum-of-squares,
count) — the PNA hot path.

PNA needs mean/std per receiver (reference: hydragnn/models/PNAStack.py:27
via PyG aggregators), which decomposes into three sum-reductions over the
edge messages. Done naively that is 3+ scatter passes, each re-reading
the [E, H] message array from HBM. Two fused implementations:

  - ``segment_sum_family_xla``: one concatenated segment_sum — XLA reads
    the messages once and scatters [E, 2H+1] rows. The default; on
    TPU v5e XLA's sorted scatter runs at HBM bandwidth (measured: a
    single 64k x 128 f32 segment-sum ~ 0.02-0.08 ms), so this is already
    near-optimal.
  - ``segment_sum_family_pallas``: a Pallas TPU kernel — grid over
    output node blocks with scalar-prefetched CSR row pointers, manual
    HBM->VMEM DMA of edge chunks, and one-hot MXU matmul accumulation in
    VMEM. One read of the messages, no scatter at all. Useful headroom
    on hardware/shapes where XLA's scatter is not bandwidth-bound; kept
    behind ``HYDRAGNN_PALLAS`` (1=pallas, 0=xla, default xla).

The Pallas kernel requires ``segment_ids`` sorted ascending (it builds
CSR block pointers by binary search); the XLA pass accepts any order.
Both need a static ``num_segments``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

BN = 128  # output rows (nodes) per grid step
CE = 512  # edges DMA'd per inner chunk


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    except ImportError:  # pragma: no cover
        return False
    return True


def segment_sum_family_xla(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(sum, sumsq, count) in ONE segment_sum over [E, 2H+1].

    No sortedness hint: SMILES-featurized graphs order edges
    sender-major (reference parity, smiles_utils.py sort), so receivers
    are not guaranteed sorted here — a false ``indices_are_sorted`` is
    undefined behavior. Measured cost of the unsorted scatter on v5e is
    within noise of the sorted one."""
    # accumulate in f32 even under bf16 mixed precision: sum/sumsq feed a
    # variance cancellation (mean(x^2) - mean(x)^2) that bf16 cannot carry
    data = data.astype(jnp.float32)
    ones = jnp.ones((data.shape[0], 1), dtype=jnp.float32)
    if mask is not None:
        m = mask[:, None].astype(jnp.float32)
        data = data * m
        ones = ones * m
    packed = jnp.concatenate([data, data * data, ones], axis=-1)
    out = jax.ops.segment_sum(packed, segment_ids, num_segments)
    h = data.shape[1]
    return out[:, :h], out[:, h : 2 * h], out[:, 2 * h]


def _family_kernel(block_ptr_ref, msg_hbm, recv_hbm,
                   sum_ref, sumsq_ref,
                   msg_vmem, recv_vmem, sems):
    """One grid step aggregates every edge of node block i
    (rows [i*BN, (i+1)*BN)). Edges arrive receiver-sorted, so the block's
    edges live in [block_ptr[i], block_ptr[i+1]); DMA windows are CE-
    aligned (Mosaic tiling) and stray edges from neighbouring blocks are
    excluded by the one-hot receiver match itself."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    lo = block_ptr_ref[i]
    hi = block_ptr_ref[i + 1]

    sum_ref[:] = jnp.zeros_like(sum_ref)
    sumsq_ref[:] = jnp.zeros_like(sumsq_ref)

    k0 = lo // CE
    k1 = (hi + CE - 1) // CE

    def chunk_body(k, _):
        start = pl.multiple_of(k * CE, CE)
        cp_msg = pltpu.make_async_copy(
            msg_hbm.at[pl.ds(start, CE), :], msg_vmem, sems.at[0]
        )
        cp_recv = pltpu.make_async_copy(
            recv_hbm.at[:, pl.ds(start, CE)], recv_vmem, sems.at[1]
        )
        cp_msg.start(); cp_recv.start()
        cp_msg.wait(); cp_recv.wait()

        msg = msg_vmem[:]
        # one-hot transpose [BN, CE]: row b hits edges whose receiver is
        # node i*BN + b (receivers outside this block match no row)
        rows = jax.lax.broadcasted_iota(jnp.int32, (BN, CE), 0) + i * BN
        onehot_t = (recv_vmem[:] == rows).astype(jnp.float32)

        sum_ref[:] += jax.lax.dot_general(
            onehot_t, msg, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        sumsq_ref[:] += jax.lax.dot_general(
            onehot_t, msg * msg, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return 0

    jax.lax.fori_loop(k0, k1, chunk_body, 0)


@functools.partial(
    jax.jit, static_argnames=("num_segments", "interpret", "indices_are_sorted")
)
def segment_sum_family_pallas(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    interpret: bool = False,
    indices_are_sorted: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not indices_are_sorted:
        # the kernel's CSR block pointers require sorted receivers;
        # SMILES-featurized graphs order edges sender-major, so sort
        # unless the caller guarantees otherwise
        order = jnp.argsort(segment_ids)
        segment_ids = segment_ids[order]
        data = data[order]
        if mask is not None:
            mask = mask[order]

    e, h = data.shape
    n_pad = ((num_segments + BN - 1) // BN) * BN
    n_blocks = n_pad // BN

    data = data.astype(jnp.float32)
    ones = jnp.ones((e, 1), jnp.float32)
    if mask is not None:
        m = mask[:, None].astype(jnp.float32)
        # zero masked messages; the one-hot matmuls then ignore them
        data = data * m
        ones = ones * m
    # the count is an [E, 1] reduction — bandwidth-trivial next to the
    # [E, H] passes, so XLA keeps it while Pallas does the heavy lifting
    cnt = jax.ops.segment_sum(
        ones[:, 0], segment_ids, num_segments, indices_are_sorted=True
    )

    # tail padding to a CE multiple: every DMA reads a fixed, aligned CE
    # window; sentinel receivers (n_pad) match no block row
    e_pad = ((e + CE - 1) // CE) * CE
    data = jnp.concatenate([data, jnp.zeros((e_pad - e, h), jnp.float32)], axis=0)
    recv = jnp.concatenate(
        [segment_ids.astype(jnp.int32), jnp.full((e_pad - e,), n_pad, jnp.int32)]
    )
    # CSR row pointers at node-block boundaries (cheap log-search)
    boundaries = jnp.arange(n_blocks + 1, dtype=jnp.int32) * BN
    block_ptr = jnp.searchsorted(
        recv[:e], boundaries, side="left"
    ).astype(jnp.int32)
    recv_row = recv[None, :]  # [1, E]: receivers along lanes

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((BN, h), lambda i, ptr: (i, 0)),
            pl.BlockSpec((BN, h), lambda i, ptr: (i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((CE, h), jnp.float32),
            pltpu.VMEM((1, CE), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    s, sq = pl.pallas_call(
        _family_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, h), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, h), jnp.float32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_ptr, data, recv_row)
    return s[:num_segments], sq[:num_segments], cnt


def segment_sum_family(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dispatch: HYDRAGNN_PALLAS=1 selects the Pallas kernel (TPU only,
    feature width must be a lane-tile multiple of 128 — Mosaic DMA
    constraint); default is the XLA fused pass (measured ~10% faster on
    v5e at bench shapes, 135k edges x 128 features)."""
    if (
        os.environ.get("HYDRAGNN_PALLAS", "0") == "1"
        and pallas_available()
        and data.shape[1] % 128 == 0
    ):
        return segment_sum_family_pallas(data, segment_ids, num_segments, mask)
    return segment_sum_family_xla(data, segment_ids, num_segments, mask)
