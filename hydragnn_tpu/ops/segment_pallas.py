"""Fused one-pass "sum-family" segment aggregation (sum, sum-of-squares,
count) — the PNA hot path.

PNA needs mean/std per receiver (reference: hydragnn/models/PNAStack.py:27
via PyG aggregators), which decomposes into three sum-reductions over the
edge messages. Done naively that is 3+ scatter passes, each re-reading
the [E, H] message array from HBM. Two fused implementations:

  - ``segment_sum_family_xla``: one concatenated segment_sum — XLA
    reads the messages once and scatters [E, 2H+1] rows (measured
    1.1-2.0 ms at E=120k, H=128 on v5e — ~7x off the HBM roofline).
  - ``segment_sum_family_pallas``: a Pallas TPU kernel — grid over
    output node blocks with scalar-prefetched CSR row pointers,
    DOUBLE-BUFFERED HBM->VMEM DMA of edge chunks, and one-hot MXU
    matmul accumulation in VMEM (precision=HIGHEST: the MXU's default
    path rounds f32 inputs to bf16). One read of the messages, no
    scatter: measured 0.36 ms at the same shape — 5.5x over XLA
    (docs/PERF.md). The TPU DEFAULT via ``HYDRAGNN_PALLAS=auto``
    when receivers are sorted (batch_graphs canonicalizes
    receiver-major order) and H % 128 == 0; ``0`` forces XLA,
    ``1`` forces the kernel (sorting on the fly).

Training goes through a hand-written gather VJP (``_family``): the
kernel has no native autodiff, and the closed-form backward
(g_sum[ids] + 2*data*g_sumsq[ids], masked) is cheaper than XLA's
packed-scatter VJP anyway.

The Pallas kernel requires ``segment_ids`` sorted ascending (it builds
CSR block pointers by binary search); the XLA pass accepts any order.
Both need a static ``num_segments``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

BN = 128  # output rows (nodes) per grid step
CE = 512  # edges DMA'd per inner chunk


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    except ImportError:  # pragma: no cover
        return False
    return True


def segment_sum_family_xla(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(sum, sumsq, count) in ONE segment_sum over [E, 2H+1]."""
    # accumulate in f32 even under bf16 mixed precision: sum/sumsq feed a
    # variance cancellation (mean(x^2) - mean(x)^2) that bf16 cannot carry
    data = data.astype(jnp.float32)
    ones = jnp.ones((data.shape[0], 1), dtype=jnp.float32)
    if mask is not None:
        m = mask[:, None].astype(jnp.float32)
        data = data * m
        ones = ones * m
    packed = jnp.concatenate([data, data * data, ones], axis=-1)
    out = jax.ops.segment_sum(
        packed, segment_ids, num_segments, indices_are_sorted=indices_are_sorted
    )
    h = data.shape[1]
    return out[:, :h], out[:, h : 2 * h], out[:, 2 * h]


def _family_kernel(block_ptr_ref, msg_hbm, recv_hbm,
                   sum_ref, sumsq_ref,
                   msg_vmem, recv_vmem, sems):
    """One grid step aggregates every edge of node block i
    (rows [i*BN, (i+1)*BN)). Edges arrive receiver-sorted, so the block's
    edges live in [block_ptr[i], block_ptr[i+1]); DMA windows are CE-
    aligned (Mosaic tiling) and stray edges from neighbouring blocks are
    excluded by the one-hot receiver match itself. Chunks are
    DOUBLE-BUFFERED (see :func:`_csr_chunk_loop`)."""
    _csr_chunk_loop(block_ptr_ref, msg_hbm, recv_hbm,
                    msg_vmem, recv_vmem, sems, sum_ref, sumsq_ref)


@functools.partial(
    jax.jit, static_argnames=("num_segments", "interpret", "indices_are_sorted")
)
def segment_sum_family_pallas(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    interpret: bool = False,
    indices_are_sorted: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # shared host-side prep (sort if needed, dtype/mask normalization,
    # CE tail padding with sentinel receivers, CSR block pointers)
    data, sorted_ids, sorted_mask, recv, block_ptr, n_pad, n_blocks, h = _csr_prep(
        data, segment_ids, mask, num_segments, indices_are_sorted
    )
    # the count is an [E, 1] reduction — bandwidth-trivial next to the
    # [E, H] passes, so XLA keeps it while Pallas does the heavy lifting
    ones = jnp.ones((sorted_ids.shape[0],), jnp.float32)
    if sorted_mask is not None:
        ones = ones * sorted_mask.astype(jnp.float32)
    cnt = jax.ops.segment_sum(
        ones, sorted_ids, num_segments, indices_are_sorted=True
    )
    recv_row = recv[None, :]  # [1, E]: receivers along lanes

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((BN, h), lambda i, ptr: (i, 0)),
            pl.BlockSpec((BN, h), lambda i, ptr: (i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, CE, h), data.dtype),
            pltpu.VMEM((2, 1, CE), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    s, sq = pl.pallas_call(
        _family_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, h), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, h), jnp.float32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_ptr, data, recv_row)
    return s[:num_segments], sq[:num_segments], cnt


def _sum_kernel(block_ptr_ref, msg_hbm, recv_hbm, sum_ref,
                msg_vmem, recv_vmem, sems):
    """Sum-only sibling of :func:`_family_kernel` (one matmul per chunk)
    — serves the VJP hot paths (gather backwards, extremum tie counts)
    where only a plain segment sum is needed. Shares the DMA/one-hot
    structure via :func:`_csr_chunk_loop`."""
    _csr_chunk_loop(block_ptr_ref, msg_hbm, recv_hbm,
                    msg_vmem, recv_vmem, sems, sum_ref, None)


def _csr_chunk_loop(block_ptr_ref, msg_hbm, recv_hbm,
                    msg_vmem, recv_vmem, sems, sum_ref, sumsq_ref):
    """Shared double-buffered CSR chunk loop: accumulate the one-hot
    matmul into ``sum_ref`` (and ``sumsq_ref`` when not None)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    lo = block_ptr_ref[i]
    hi = block_ptr_ref[i + 1]
    sum_ref[:] = jnp.zeros_like(sum_ref)
    if sumsq_ref is not None:
        sumsq_ref[:] = jnp.zeros_like(sumsq_ref)
    k0 = lo // CE
    k1 = (hi + CE - 1) // CE

    def dmas(slot, k):
        start = pl.multiple_of(k * CE, CE)
        return (
            pltpu.make_async_copy(
                msg_hbm.at[pl.ds(start, CE), :], msg_vmem.at[slot], sems.at[slot, 0]
            ),
            pltpu.make_async_copy(
                recv_hbm.at[:, pl.ds(start, CE)], recv_vmem.at[slot], sems.at[slot, 1]
            ),
        )

    @pl.when(k0 < k1)
    def _warmup():
        for cp in dmas(k0 % 2, k0):
            cp.start()

    def chunk_body(k, _):
        slot = k % 2

        @pl.when(k + 1 < k1)
        def _prefetch():
            for cp in dmas((k + 1) % 2, k + 1):
                cp.start()

        for cp in dmas(slot, k):
            cp.wait()
        # upcast bf16 DMA payloads in registers; matmuls accumulate f32
        msg = msg_vmem[slot].astype(jnp.float32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (BN, CE), 0) + i * BN
        onehot_t = (recv_vmem[slot] == rows).astype(jnp.float32)
        # precision=HIGHEST: the MXU default rounds f32 inputs to bf16
        sum_ref[:] += jax.lax.dot_general(
            onehot_t, msg, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if sumsq_ref is not None:
            sumsq_ref[:] += jax.lax.dot_general(
                onehot_t, msg * msg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
        return 0

    jax.lax.fori_loop(k0, k1, chunk_body, 0)


def _csr_prep(data, segment_ids, mask, num_segments, indices_are_sorted):
    """Shared host-side prep: optional sort, dtype normalization (bf16
    stays bf16 for half-width DMA, everything else goes f32), mask
    premultiply (always in f32 so non-boolean weight masks keep full
    precision), CE tail padding with sentinel receivers, CSR block
    pointers."""
    if not indices_are_sorted:
        order = jnp.argsort(segment_ids)
        segment_ids = segment_ids[order]
        data = data[order]
        if mask is not None:
            mask = mask[order]
    e, h = data.shape
    n_pad = ((num_segments + BN - 1) // BN) * BN
    # bf16 stays bf16: the kernel DMAs half the bytes and upcasts in
    # registers before the f32-accumulating matmuls (under mixed
    # precision the model already rounded the messages to bf16, so no
    # information is lost); every other dtype goes f32
    if data.dtype != jnp.bfloat16:
        data = data.astype(jnp.float32)
    if mask is not None:
        # multiply in f32 then round once: a non-boolean weight mask must
        # not be pre-rounded to bf16 (double-rounding precision cliff)
        data = (
            data.astype(jnp.float32) * mask[:, None].astype(jnp.float32)
        ).astype(data.dtype)
    e_pad = ((e + CE - 1) // CE) * CE
    data = jnp.concatenate([data, jnp.zeros((e_pad - e, h), data.dtype)], axis=0)
    recv = jnp.concatenate(
        [segment_ids.astype(jnp.int32), jnp.full((e_pad - e,), n_pad, jnp.int32)]
    )
    n_blocks = n_pad // BN
    boundaries = jnp.arange(n_blocks + 1, dtype=jnp.int32) * BN
    block_ptr = jnp.searchsorted(recv[:e], boundaries, side="left").astype(jnp.int32)
    return data, segment_ids, mask, recv, block_ptr, n_pad, n_blocks, h


@functools.partial(
    jax.jit, static_argnames=("num_segments", "interpret", "indices_are_sorted")
)
def segment_sum_pallas(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    interpret: bool = False,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    """Plain segment sum through the double-buffered CSR kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    data, _, _, recv, block_ptr, n_pad, n_blocks, h = _csr_prep(
        data, segment_ids, mask, num_segments, indices_are_sorted
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec((BN, h), lambda i, ptr: (i, 0))],
        scratch_shapes=[
            pltpu.VMEM((2, CE, h), data.dtype),
            pltpu.VMEM((2, 1, CE), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    (s,) = pl.pallas_call(
        _sum_kernel,
        out_shape=[jax.ShapeDtypeStruct((n_pad, h), jnp.float32)],
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_ptr, data, recv[None, :])
    return s[:num_segments]


def _use_pallas(data: jnp.ndarray, indices_are_sorted: bool) -> bool:
    """Shared HYDRAGNN_PALLAS knob contract: "1" forces the kernel
    (sorting on the fly), "0" forces XLA, default auto = Pallas on TPU
    for sorted, 2-D, 128-lane-multiple data."""
    tiles = data.ndim == 2 and data.shape[1] % 128 == 0
    knob = os.environ.get("HYDRAGNN_PALLAS", "auto")
    if knob == "1":
        return pallas_available() and tiles
    if knob == "0":
        return False
    return (
        pallas_available()
        and tiles
        and indices_are_sorted
        and jax.default_backend() == "tpu"
    )


def segment_sum_fast(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    """Segment sum for VJP hot paths: the Pallas CSR kernel on TPU when
    receivers are sorted and the width tiles (same knob contract as
    :func:`segment_sum_family`: "1" forces the kernel, sorting on the
    fly; "0" forces XLA; default auto), XLA otherwise. Not
    differentiated itself — callers are custom backward functions."""
    if _use_pallas(data, indices_are_sorted):
        return segment_sum_pallas(
            data, segment_ids, num_segments, mask,
            indices_are_sorted=indices_are_sorted,
        )
    if mask is not None:
        data = data * mask[:, None].astype(data.dtype)
    return jax.ops.segment_sum(
        data, segment_ids, num_segments, indices_are_sorted=indices_are_sorted
    )


def _family_impl(data, segment_ids, num_segments, mask, indices_are_sorted, use_pallas):
    if use_pallas:
        return segment_sum_family_pallas(
            data, segment_ids, num_segments, mask,
            indices_are_sorted=indices_are_sorted,
        )
    return segment_sum_family_xla(
        data, segment_ids, num_segments, mask,
        indices_are_sorted=indices_are_sorted,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 4, 5))
def _family(data, segment_ids, num_segments, mask, indices_are_sorted, use_pallas):
    """Family with a hand-written gather backward: makes the Pallas
    kernel trainable (pallas_call has no native VJP) and replaces XLA's
    packed-scatter VJP with the closed form
    d/d(data) = mask * (g_sum[ids] + 2 * data * g_sumsq[ids])."""
    return _family_impl(data, segment_ids, num_segments, mask,
                        indices_are_sorted, use_pallas)


def _family_fwd(data, segment_ids, num_segments, mask, indices_are_sorted, use_pallas):
    out = _family_impl(data, segment_ids, num_segments, mask,
                       indices_are_sorted, use_pallas)
    return out, (data, segment_ids, mask)


def _family_bwd(num_segments, indices_are_sorted, use_pallas, res, g):
    data, segment_ids, mask = res
    g_sum, g_sumsq, _ = g  # count is data-independent
    grad = g_sum[segment_ids] + 2.0 * data.astype(g_sum.dtype) * g_sumsq[segment_ids]
    if mask is not None:
        grad = jnp.where(mask[:, None], grad, 0)
    ids_zero = jnp.zeros(segment_ids.shape, dtype=jax.dtypes.float0)
    mask_zero = (
        None if mask is None else jnp.zeros(mask.shape, dtype=jax.dtypes.float0)
    )
    return grad.astype(data.dtype), ids_zero, mask_zero


_family.defvjp(_family_fwd, _family_bwd)


def segment_sum_family(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dispatch. Default ("auto"): the double-buffered Pallas kernel on
    TPU when the caller guarantees sorted receivers and the feature
    width is a 128-lane multiple (measured 5.5x faster than the XLA
    scatter at E=120k, H=128 on v5e — docs/PERF.md); the fused XLA pass
    otherwise. HYDRAGNN_PALLAS=1 forces the kernel (sorting on the fly
    if needed), HYDRAGNN_PALLAS=0 forces XLA — the escape hatch for
    paths where a pallas_call cannot partition (e.g. PNA over
    GSPMD-edge-sharded giant graphs)."""
    use_pallas = _use_pallas(data, indices_are_sorted)
    return _family(data, segment_ids, num_segments, mask,
                   indices_are_sorted, use_pallas)
