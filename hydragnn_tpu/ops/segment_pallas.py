"""Fused one-pass "sum-family" segment aggregation (sum, sum-of-squares,
count) — the PNA hot path.

PNA needs mean/std per receiver (reference: hydragnn/models/PNAStack.py:27
via PyG aggregators), which decomposes into three sum-reductions over the
edge messages. Done naively that is 3+ scatter passes, each re-reading
the [E, H] message array from HBM. Two fused implementations:

  - ``segment_sum_family_xla``: one concatenated segment_sum — XLA
    reads the messages once and scatters [E, 2H+1] rows (measured
    1.1-2.0 ms at E=120k, H=128 on v5e — ~7x off the HBM roofline).
  - ``segment_sum_family_pallas``: a Pallas TPU kernel — grid over
    output node blocks with scalar-prefetched CSR row pointers,
    DOUBLE-BUFFERED HBM->VMEM DMA of edge chunks, and one-hot MXU
    matmul accumulation in VMEM (precision=HIGHEST: the MXU's default
    path rounds f32 inputs to bf16). One read of the messages, no
    scatter: measured 0.36 ms at the same shape — 5.5x over XLA
    (docs/PERF.md). The TPU DEFAULT via ``HYDRAGNN_PALLAS=auto``
    when receivers are sorted (batch_graphs canonicalizes
    receiver-major order) and H % 128 == 0.

SPMD composition: the kernel calls are wrapped in
``jax.experimental.custom_partitioning`` with an edge-axis rule — when
GSPMD shards the operands on their leading (edge) axis (the giant-graph
path, ``parallel/edge_sharded.py:place_giant_batch``), each device runs
the CSR kernel on its LOCAL edge slice (a contiguous receiver-sorted
range, so the CSR contract holds per shard) and one ``psum`` over the
sharded axis combines the per-node partials. No escape hatch needed:
the fast kernel and the giant-graph sharding path compose. Inside
``shard_map`` (the DP train step) the operands are already local and
the wrapper lowers to the plain kernel. The one context that cannot
partition the op is ``vmap`` (custom_partitioning has no batching
rule) — ``make_dp_edge_train_step`` traces its model vmap under
:func:`xla_segment_ops`, which forces the XLA path programmatically.

Training goes through a hand-written gather VJP (``_family``): the
kernel has no native autodiff, and the closed-form backward
(m*g_sum[ids] + 2*m^2*data*g_sumsq[ids]) is cheaper than XLA's
packed-scatter VJP anyway. The mask is non-differentiable by contract
(stop_gradient applied on entry): it is an edge-validity weighting,
not a learnable quantity.

The Pallas kernel requires ``segment_ids`` sorted ascending (it builds
CSR block pointers by binary search); the XLA pass accepts any order.
Both need a static ``num_segments``.

``HYDRAGNN_PALLAS`` knob contract:
  - ``auto`` (default): Pallas on TPU for sorted, 2-D, 128-lane data;
  - ``1``: force the kernel when the backend is TPU (sorting on the
    fly if needed); falls back to XLA elsewhere rather than crashing
    at Mosaic lowering on CPU/GPU;
  - ``interpret``: force the kernel in interpret mode on ANY backend
    (CPU-mesh tests of the sharded kernel path);
  - ``0``: force XLA.

The FULLY FUSED conv-layer kernel (gather -> edge MLP -> scatter in
one Pallas call, r07) builds on this file's machinery — window plans,
vma matching, partitioning compat, the fast gather/sum dispatchers —
and lives in :mod:`hydragnn_tpu.ops.fused_conv`; it shares the knob
contract above.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P

from hydragnn_tpu.utils import knobs

# Grid tile sizes, env-overridable for on-chip tuning (tools/tune_tiles.py):
# larger tiles amortize per-grid-step overhead (the r04 flagship trace
# shows ~1 ms kernel calls moving only ~0.2 GB — overhead-bound), at the
# cost of VMEM and wasted work on boundary blocks.


def _tile_defaults() -> dict:
    """Block/chunk defaults from the committed sweep table
    (``TUNE_TILES.json`` at the repo root, written by
    ``tools/tune_tiles.py --save``): ``{shape_tag: {device_kind:
    {"BN", "CE", "BCAST_CE"}}}``. Selection keys come from env —
    ``HYDRAGNN_TILE_SHAPE`` then ``HYDRAGNN_DEVICE_KIND``, each falling
    back to the table's ``"default"`` row — NOT from ``jax.devices()``:
    importing this module must never trigger backend init ahead of the
    platform pinning entry scripts rely on. The explicit
    ``HYDRAGNN_BN`` / ``HYDRAGNN_CE`` / ``HYDRAGNN_BCAST_CE`` env knobs
    always win over the table; any read/parse failure falls back to the
    baked r05-measured defaults, so a missing or mangled table can
    never change kernel behavior."""
    out = {"BN": 128, "CE": 512, "BCAST_CE": 1024}
    try:
        import json

        path = os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
            "TUNE_TILES.json",
        )
        with open(path) as f:
            table = json.load(f)
        shape = knobs.get_str("HYDRAGNN_TILE_SHAPE", "default")
        kind = knobs.get_str("HYDRAGNN_DEVICE_KIND", "default")
        by_shape = table.get(shape) or table.get("default") or {}
        entry = by_shape.get(kind) or by_shape.get("default") or {}
        for k in out:
            if k in entry:
                out[k] = int(entry[k])
    except Exception:
        pass
    return out


_TILE_DEFAULTS = _tile_defaults()
BN = knobs.get_int("HYDRAGNN_BN", _TILE_DEFAULTS["BN"])  # output rows (nodes) per grid step
CE = knobs.get_int("HYDRAGNN_CE", _TILE_DEFAULTS["CE"])  # edges DMA'd per inner chunk
# Gather-kernel chunk: the bcast kernel has no cross-chunk accumulator,
# so it tolerates bigger chunks than the family/sum kernels' CE —
# measured on v5e (r05 flagship trace): 512 -> 77.8 ms/step, 1024 ->
# 75.9, 2048 -> 79.7 (wider chunks span more BW-windows and the stray
# re-reads win back the overhead). Default 1024.
_BCAST_CE = knobs.get_int("HYDRAGNN_BCAST_CE", _TILE_DEFAULTS["BCAST_CE"])
if BN % 16 or CE % 16 or BN <= 0 or CE <= 0 or _BCAST_CE % 16 or _BCAST_CE <= 0:
    raise ValueError(
        f"HYDRAGNN_BN={BN} / HYDRAGNN_CE={CE} / HYDRAGNN_BCAST_CE={_BCAST_CE} "
        "must be positive multiples of 16 (Mosaic tiling: HBM slice starts "
        "and output blocks must stay tile-aligned — a misaligned value "
        "fails deep in kernel lowering)"
    )

_FORCE_XLA = contextvars.ContextVar("hydragnn_force_xla_segment_ops", default=False)


@contextlib.contextmanager
def xla_segment_ops():
    """Force the XLA segment path for every op traced inside this
    context. Needed where the partitioned kernel op cannot appear:
    under ``vmap`` (custom_partitioning has no batching rule —
    ``parallel/edge_sharded.py:make_dp_edge_train_step`` vmaps the
    model over the data axis). Trace-time scoped: wrap the code that
    BUILDS/TRACES the jitted function, not the execution."""
    tok = _FORCE_XLA.set(True)
    try:
        yield
    finally:
        _FORCE_XLA.reset(tok)


def _vma_of(*arrays) -> frozenset:
    """Union of the manual-mesh axes the given arrays vary over (empty
    outside shard_map, and on jax versions without vma tracking)."""
    from hydragnn_tpu.utils.jax_compat import typeof_vma

    out: frozenset = frozenset()
    for a in arrays:
        out = out | typeof_vma(a)
    return out


def _match_vma(x, vma: frozenset):
    """Promote ``x`` to vary over ``vma`` (jax.lax.pvary) — constructed
    operands (zero padding, window plans) otherwise arrive non-varying
    inside shard_map with check_vma=True and fail the interpreter's
    per-operand vma match. No-op on pre-vma jax."""
    from hydragnn_tpu.utils.jax_compat import pvary, typeof_vma

    return pvary(x, tuple(vma - typeof_vma(x)))


def _sds(shape, dtype, vma: frozenset = frozenset()):
    """ShapeDtypeStruct carrying vma where the jax version supports it
    (utils/jax_compat.shape_dtype_struct)."""
    from hydragnn_tpu.utils.jax_compat import shape_dtype_struct

    return shape_dtype_struct(shape, dtype, vma)


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    except ImportError:  # pragma: no cover
        return False
    return True


def segment_sum_family_xla(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(sum, sumsq, count) in ONE segment_sum over [E, 2H+1]."""
    # accumulate in f32 even under bf16 mixed precision: sum/sumsq feed a
    # variance cancellation (mean(x^2) - mean(x)^2) that bf16 cannot carry
    data = data.astype(jnp.float32)
    ones = jnp.ones((data.shape[0], 1), dtype=jnp.float32)
    if mask is not None:
        m = mask[:, None].astype(jnp.float32)
        data = data * m
        ones = ones * m
    packed = jnp.concatenate([data, data * data, ones], axis=-1)
    out = jax.ops.segment_sum(
        packed, segment_ids, num_segments, indices_are_sorted=indices_are_sorted
    )
    h = data.shape[1]
    return out[:, :h], out[:, h : 2 * h], out[:, 2 * h]


def _family_kernel(block_ptr_ref, msg_hbm, recv_hbm,
                   sum_ref, sumsq_ref,
                   msg_vmem, recv_vmem, sems):
    """One grid step aggregates every edge of node block i
    (rows [i*BN, (i+1)*BN)). Edges arrive receiver-sorted, so the block's
    edges live in [block_ptr[i], block_ptr[i+1]); DMA windows are CE-
    aligned (Mosaic tiling) and stray edges from neighbouring blocks are
    excluded by the one-hot receiver match itself. Chunks are
    DOUBLE-BUFFERED (see :func:`_csr_chunk_loop`)."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    _csr_chunk_loop(block_ptr_ref[i], block_ptr_ref[i + 1], msg_hbm, recv_hbm,
                    msg_vmem, recv_vmem, sems, sum_ref, sumsq_ref)


def _sum_kernel(block_ptr_ref, msg_hbm, recv_hbm, sum_ref,
                msg_vmem, recv_vmem, sems):
    """Sum-only sibling of :func:`_family_kernel` (one matmul per chunk)
    — serves the VJP hot paths (gather backwards, extremum tie counts)
    where only a plain segment sum is needed. Shares the DMA/one-hot
    structure via :func:`_csr_chunk_loop`."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    _csr_chunk_loop(block_ptr_ref[i], block_ptr_ref[i + 1], msg_hbm, recv_hbm,
                    msg_vmem, recv_vmem, sems, sum_ref, None)


def _sum_local_kernel(win_ref, msg_hbm, recv_hbm, sum_ref,
                      msg_vmem, recv_vmem, sems):
    """Segment sum for UNSORTED-BUT-LOCAL ids: block i's edges are not
    contiguous, but the caller guarantees every edge whose id falls in
    rows [i*B, (i+1)*B) — B = the out-ref block size, derived from the
    window shape by :func:`local_block_rows` — lies inside the
    edge-position window [win[0, i], win[1, i]) (host-precomputed —
    ``graph/batch.py`` emits it from the batch's block structure). The
    window may contain stray edges of neighbouring blocks; the one-hot
    id match excludes them, exactly like the CE-aligned DMA overhang
    in the sorted kernel."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    _csr_chunk_loop(win_ref[0, i], win_ref[1, i], msg_hbm, recv_hbm,
                    msg_vmem, recv_vmem, sems, sum_ref, None)


def _csr_chunk_loop(lo, hi, msg_hbm, recv_hbm,
                    msg_vmem, recv_vmem, sems, sum_ref, sumsq_ref):
    """Shared double-buffered CSR chunk loop: accumulate the one-hot
    matmul over edge positions [lo, hi) into ``sum_ref`` (and
    ``sumsq_ref`` when not None)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    sum_ref[:] = jnp.zeros_like(sum_ref)
    if sumsq_ref is not None:
        sumsq_ref[:] = jnp.zeros_like(sumsq_ref)
    k0 = lo // CE
    k1 = (hi + CE - 1) // CE

    def dmas(slot, k):
        start = pl.multiple_of(k * CE, CE)
        return (
            pltpu.make_async_copy(
                msg_hbm.at[pl.ds(start, CE), :], msg_vmem.at[slot], sems.at[slot, 0]
            ),
            pltpu.make_async_copy(
                recv_hbm.at[:, pl.ds(start, CE)], recv_vmem.at[slot], sems.at[slot, 1]
            ),
        )

    @pl.when(k0 < k1)
    def _warmup():
        for cp in dmas(k0 % 2, k0):
            cp.start()

    def chunk_body(k, _):
        slot = k % 2

        @pl.when(k + 1 < k1)
        def _prefetch():
            for cp in dmas((k + 1) % 2, k + 1):
                cp.start()

        for cp in dmas(slot, k):
            cp.wait()
        raw = msg_vmem[slot]
        # block size from the output ref itself: BN for the sorted
        # kernels, the window plan's derived size for the local kernel
        bn = sum_ref.shape[0]
        rows = jax.lax.broadcasted_iota(jnp.int32, (bn, CE), 0) + i * bn
        onehot = recv_vmem[slot] == rows
        if raw.dtype == jnp.bfloat16:
            # native-MXU bf16 path: onehot x value products are exact
            # (0/1 times an already-bf16 value) and accumulation is f32
            # — no need for the 6x-cost HIGHEST f32 emulation. The
            # squares are NOT bf16-exact, so sumsq splits the exact f32
            # square into hi + lo bf16 terms (two native matmuls):
            # products then roundtrip within ~2^-16 relative of f32,
            # matching the XLA reference's upcast-then-square.
            onehot_t = onehot.astype(jnp.bfloat16)
            sum_ref[:] += jax.lax.dot_general(
                onehot_t, raw, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if sumsq_ref is not None:
                sq = raw.astype(jnp.float32)
                sq = sq * sq
                hi = sq.astype(jnp.bfloat16)
                lo = (sq - hi.astype(jnp.float32)).astype(jnp.bfloat16)
                sumsq_ref[:] += jax.lax.dot_general(
                    onehot_t, hi, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) + jax.lax.dot_general(
                    onehot_t, lo, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
        else:
            # f32 values: 3-term bf16 split -> 3 native MXU matmuls per
            # sum instead of the 6-pass HIGHEST f32 emulation (2x
            # faster). The one-hot side is exact 0/1, and hi+mid+lo
            # carries 24 mantissa bits — the full f32 significand — so
            # each product reconstructs the f32 value exactly and the
            # only deviation from HIGHEST is f32 accumulation order
            # (well inside the segment-sum contract; a 2-term split was
            # tried and fails the 1e-5 interpret-vs-XLA gate under
            # cancellation). Bit-exactness contracts live in the GATHER
            # kernel (_window_gather_acc), which keeps HIGHEST.
            msg = raw.astype(jnp.float32)
            onehot_t = onehot.astype(jnp.bfloat16)

            def split_dot(x):
                hi = x.astype(jnp.bfloat16)
                r1 = x - hi.astype(jnp.float32)
                mid = r1.astype(jnp.bfloat16)
                lo = (r1 - mid.astype(jnp.float32)).astype(jnp.bfloat16)
                out = None
                for term in (hi, mid, lo):
                    d = jax.lax.dot_general(
                        onehot_t, term, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    out = d if out is None else out + d
                return out

            sum_ref[:] += split_dot(msg)
            if sumsq_ref is not None:
                sumsq_ref[:] += split_dot(msg * msg)
        return 0

    jax.lax.fori_loop(k0, k1, chunk_body, 0)


def _csr_prep(data, segment_ids, mask, num_segments):
    """Shard-local prep for the CSR kernels (``segment_ids`` must be
    sorted ascending — sorting, if any, happens before the partitioned
    op so each shard's slice stays contiguous): dtype normalization
    (bf16 stays bf16 for half-width DMA unless a float weight mask
    forces f32; everything else goes f32), mask premultiply, CE tail
    padding with sentinel receivers, CSR block pointers."""
    e, h = data.shape
    n_pad = ((num_segments + BN - 1) // BN) * BN
    # bf16 stays bf16: the kernel DMAs half the bytes and upcasts in
    # registers before the f32-accumulating matmuls (under mixed
    # precision the model already rounded the messages to bf16, so no
    # information is lost); every other dtype goes f32
    float_mask = mask is not None and jnp.issubdtype(mask.dtype, jnp.floating)
    if data.dtype != jnp.bfloat16 or float_mask:
        # bf16 stays bf16 EXCEPT under a float weight mask: the weighted
        # products are not bf16-representable, and rounding them before
        # accumulation measurably diverges from the f32 XLA path at
        # realistic degrees (caught by the on-chip selfcheck at E=120k,
        # ~23 edges/node — boolean masks are exact in any dtype)
        data = data.astype(jnp.float32)
    if mask is not None:
        data = data * mask[:, None].astype(data.dtype)
    e_pad = ((e + CE - 1) // CE) * CE
    data = jnp.concatenate([data, jnp.zeros((e_pad - e, h), data.dtype)], axis=0)
    recv = jnp.concatenate(
        [segment_ids.astype(jnp.int32), jnp.full((e_pad - e,), n_pad, jnp.int32)]
    )
    n_blocks = n_pad // BN
    boundaries = jnp.arange(n_blocks + 1, dtype=jnp.int32) * BN
    block_ptr = jnp.searchsorted(recv[:e], boundaries, side="left").astype(jnp.int32)
    return data, recv, block_ptr, n_pad, n_blocks, h


def _csr_kernel_call(data, segment_ids, mask, num_segments, interpret, family):
    """Shard-local CSR kernel invocation (sorted contract). Returns
    (sum, sumsq, cnt) when ``family`` else the plain sum."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    data, recv, block_ptr, n_pad, n_blocks, h = _csr_prep(
        data, segment_ids, mask, num_segments
    )
    n_out = 2 if family else 1
    # under shard_map with check_vma=True the out_shape must declare which
    # manual mesh axes the result varies over, and every operand
    # (including constructed padding/pointer arrays) must carry them
    vma = _vma_of(data, recv)
    data = _match_vma(data, vma)
    recv = _match_vma(recv, vma)
    block_ptr = _match_vma(block_ptr, vma)
    out_sds = _sds((n_pad, h), jnp.float32, vma=vma)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec((BN, h), lambda i, ptr: (i, 0))] * n_out,
        scratch_shapes=[
            pltpu.VMEM((2, CE, h), data.dtype),
            pltpu.VMEM((2, 1, CE), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    outs = pl.pallas_call(
        _family_kernel if family else _sum_kernel,
        out_shape=[out_sds] * n_out,
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_ptr, data, recv[None, :])
    if not family:
        return outs[0][:num_segments]
    # the count is an [E, 1] reduction — bandwidth-trivial next to the
    # [E, H] passes, so XLA keeps it while Pallas does the heavy lifting
    ones = jnp.ones((segment_ids.shape[0],), jnp.float32)
    if mask is not None:
        ones = ones * mask.astype(jnp.float32)
    cnt = jax.ops.segment_sum(
        ones, segment_ids, num_segments, indices_are_sorted=True
    )
    return outs[0][:num_segments], outs[1][:num_segments], cnt


def _def_partition_compat(op, *, partition, infer_sharding_from_operands, sharding_rule):
    """``def_partition`` across jax versions (utils/jax_compat): the
    shardy ``sharding_rule`` spec only exists on newer jax; 0.4.x takes
    the same partition/infer pair and uses classic GSPMD propagation."""
    from hydragnn_tpu.utils.jax_compat import def_partition

    def_partition(
        op,
        partition=partition,
        infer_sharding_from_operands=infer_sharding_from_operands,
        sharding_rule=sharding_rule,
    )


def _make_partitioned_op(family: bool, has_mask: bool):
    """Build a custom_partitioning wrapper around the CSR kernel.

    Partitioning rule: when GSPMD shards the operands on the edge axis
    (leading dim of ``data``/``ids``/``mask`` — the giant-graph path),
    each device runs the kernel on its local, contiguous,
    receiver-sorted edge slice against the full segment range, and one
    ``psum`` over the sharded mesh axis combines the per-node partials.
    Any other operand sharding is canonicalized to replicated. Outputs
    are replicated (they are [num_segments, ...] node-space arrays)."""
    n_args = 3 if has_mask else 2

    def base(*args):
        data, ids = args[0], args[1]
        mask = args[2] if has_mask else None
        num_segments, interpret = args[n_args], args[n_args + 1]
        return _csr_kernel_call(data, ids, mask, num_segments, interpret, family)

    op = custom_partitioning(base, static_argnums=(n_args, n_args + 1))

    def _out_shardings(mesh):
        rep = NamedSharding(mesh, P())
        return (rep, rep, rep) if family else rep

    def infer(num_segments, interpret, mesh, arg_shapes, result_shape):
        return _out_shardings(mesh)

    def partition(num_segments, interpret, mesh, arg_shapes, result_shape):
        spec = arg_shapes[0].sharding.spec
        edge_axis = spec[0] if len(spec) >= 1 else None

        def lower_fn(*arrs):
            data, ids = arrs[0], arrs[1]
            mask = arrs[2] if has_mask else None
            out = _csr_kernel_call(
                data, ids, mask, num_segments, interpret, family
            )
            if edge_axis is not None:
                out = jax.lax.psum(out, edge_axis)
            return out

        arg_sh = [
            NamedSharding(mesh, P(edge_axis, None)),
            NamedSharding(mesh, P(edge_axis)),
        ]
        if has_mask:
            arg_sh.append(NamedSharding(mesh, P(edge_axis)))
        return mesh, lower_fn, _out_shardings(mesh), tuple(arg_sh)

    ins = "e h, e" + (", e" if has_mask else "")
    outs = "n h, n h, n" if family else "n h"
    _def_partition_compat(
        op,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule=f"{ins} -> {outs}",
    )
    return op


_FAMILY_OP = _make_partitioned_op(family=True, has_mask=False)
_FAMILY_OP_MASKED = _make_partitioned_op(family=True, has_mask=True)
_SUM_OP = _make_partitioned_op(family=False, has_mask=False)
_SUM_OP_MASKED = _make_partitioned_op(family=False, has_mask=True)


def _sort_for_csr(data, segment_ids, mask, indices_are_sorted):
    """Global pre-sort for the forced-kernel path. Happens OUTSIDE the
    partitioned op so the sorted contract holds per shard."""
    if indices_are_sorted:
        return data, segment_ids, mask
    order = jnp.argsort(segment_ids)
    return (
        data[order],
        segment_ids[order],
        None if mask is None else mask[order],
    )


@functools.partial(
    jax.jit, static_argnames=("num_segments", "interpret", "indices_are_sorted")
)
def segment_sum_family_pallas(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    interpret: bool = False,
    indices_are_sorted: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    data, segment_ids, mask = _sort_for_csr(
        data, segment_ids, mask, indices_are_sorted
    )
    if mask is not None:
        return _FAMILY_OP_MASKED(data, segment_ids, mask, num_segments, interpret)
    return _FAMILY_OP(data, segment_ids, num_segments, interpret)


@functools.partial(
    jax.jit, static_argnames=("num_segments", "interpret", "indices_are_sorted")
)
def segment_sum_pallas(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    interpret: bool = False,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    """Plain segment sum through the double-buffered CSR kernel."""
    data, segment_ids, mask = _sort_for_csr(
        data, segment_ids, mask, indices_are_sorted
    )
    if mask is not None:
        return _SUM_OP_MASKED(data, segment_ids, mask, num_segments, interpret)
    return _SUM_OP(data, segment_ids, num_segments, interpret)


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def segment_sum_local_pallas(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    win: jnp.ndarray,
    num_segments: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Segment sum for UNSORTED ids with host-provided per-node-block
    edge windows — the scatter-add of a batched-graph sender axis
    without the [E, H] permute a sorted reduction needs (the permute
    row-gather is serial on TPU: ~7.4 ms at E=699k, r03 trace).

    ``win`` is int32 [2, n_blocks]: every edge e with
    ``segment_ids[e] // B == i`` must satisfy
    ``win[0, i] <= e < win[1, i]``, where the block size B =
    :func:`local_block_rows`(num_segments, n_blocks) — derived
    identically by the window EMITTER (``graph/batch.py:
    _block_windows``) and this kernel, so B rides the win SHAPE and
    needs no extra plumbing. Blocks sized to the batch's typical graph
    keep large graphs from re-scanning their edge window once per
    128-row block (docs/PERF.md r04). Windows of different blocks may
    overlap (stray ids are excluded by the kernel's one-hot match);
    empty blocks use lo == hi. Locality is guaranteed for batched
    graphs because each graph's nodes and edges are contiguous."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e, h = data.shape
    n_blocks = int(win.shape[1])
    BNL = local_block_rows(num_segments, n_blocks)
    n_pad = n_blocks * BNL
    # a window plan emitted for a DIFFERENT num_segments derives a
    # different block size here and would silently drop edges whose
    # id // BNL disagrees with the emitter's id // B; the minimality
    # check catches that mismatch class (the emitter always produces
    # the minimal block count for its derived size)
    if n_blocks > 1 and (n_blocks - 1) * BNL >= num_segments:
        raise ValueError(
            f"win has {n_blocks} blocks but num_segments={num_segments} "
            f"needs at most {(num_segments + BNL - 1) // BNL} at the "
            f"derived block size {BNL} — the plan was emitted for a "
            "different num_segments (graph/batch.py:_block_windows)"
        )
    if data.dtype != jnp.bfloat16:
        data = data.astype(jnp.float32)
    e_pad = ((e + CE - 1) // CE) * CE
    data = jnp.concatenate([data, jnp.zeros((e_pad - e, h), data.dtype)], axis=0)
    ids = jnp.concatenate(
        [segment_ids.astype(jnp.int32), jnp.full((e_pad - e,), n_pad, jnp.int32)]
    )
    vma = _vma_of(data, ids)
    data = _match_vma(data, vma)
    ids = _match_vma(ids, vma)
    win = _match_vma(win.astype(jnp.int32), vma)
    out_sds = _sds((n_pad, h), jnp.float32, vma=vma)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec((BNL, h), lambda i, ptr: (i, 0))],
        scratch_shapes=[
            pltpu.VMEM((2, CE, h), data.dtype),
            pltpu.VMEM((2, 1, CE), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    (out,) = pl.pallas_call(
        _sum_local_kernel,
        out_shape=[out_sds],
        grid_spec=grid_spec,
        interpret=interpret,
    )(win, data, ids[None, :])
    return out[:num_segments]


def local_block_rows(num_segments: int, n_blocks: int) -> int:
    """The local-window kernels' block size, derived from the window
    plan's SHAPE: the B (multiple of 16 — the same bf16 sublane-tiling
    envelope the HYDRAGNN_BN guard enforces) with n_blocks * B >=
    num_segments that both the emitter and the kernel compute from
    (num_segments, n_blocks) — the contract that lets the host pick
    graph-sized blocks without extra static plumbing."""
    b = (num_segments + n_blocks - 1) // n_blocks
    return ((b + 15) // 16) * 16


def segment_sum_local_fast(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    win: Optional[jnp.ndarray],
    num_segments: int,
) -> jnp.ndarray:
    """Dispatcher for the local-window segment sum: Pallas kernel when
    the window plan is present and the knob/backend allow it (window
    locality substitutes for the sorted contract), XLA's unsorted
    scatter-add otherwise. Accumulates f32; returns f32 like
    :func:`segment_sum_fast`."""
    if win is not None and data.ndim == 2:
        h = _narrow_kernel_width(data, indices_are_sorted=True)
        if h is not None:
            return segment_sum_local_pallas(
                _lane_pad(data), segment_ids, win, num_segments,
                interpret=_interpret_mode(),
            )[:, :h]
        if _use_pallas(data, indices_are_sorted=True):
            return segment_sum_local_pallas(
                data, segment_ids, win, num_segments,
                interpret=_interpret_mode(),
            )
    return jax.ops.segment_sum(
        data.astype(jnp.float32), segment_ids, num_segments
    )


# ---------------------------------------------------------------------------
# CSR broadcast (sorted-ids row gather): out[e] = table[ids[e]]
# ---------------------------------------------------------------------------
#
# XLA lowers a [N, H] -> [E, H] row gather on TPU to a serial per-row
# loop — measured 6-9 ms at E=699k, H=128 on v5e (~19 GB/s effective),
# and the PNA backward pays ~36 of them per step (g_sum[recv],
# g_sumsq[recv], extremum out[recv]/share[recv] per layer): 280 of the
# 471 ms step (r03 trace, docs/PERF.md). For SORTED ids the gather is a
# CSR broadcast with perfect locality: an edge chunk of C ids
# (C = _BCAST_CE for the gather kernel, CE for the fused backward)
# reads only the <= C distinct table rows it references, so a one-hot
# MXU matmul (out_chunk = onehot[C, W] @ window[W, H]) streams the
# output at bandwidth instead of looping rows; chunks spanning more
# than one BW-row window loop over as many windows as needed. Exactness: each output row is
# 1.0 * table_row summed once — exact for bf16 inputs with f32
# accumulation; f32 inputs use HIGHEST (the f32-as-3xbf16 split times
# exact 1.0 reconstructs exactly) — for |x| >= ~1e-30. Below that the
# split's residual terms progressively fall under bf16's NORMAL floor
# and flush (measured v5e decay: ~2^-16 rel by 1e-33, ~2^-8 rel by
# 3e-36); below bf16's min normal (1.18e-38) the hi term itself
# flushes and the value reads back exactly 0 (gated by
# tools/tpu_selfcheck.py:bcast_tiny_magnitude_f32). Consequence for
# the extremum backward's tie detection (data == gather(out)):
# segments whose extremum magnitude is below ~1e-30 can drop their
# extremum gradient — numerically-negligible in any real training.

ALIGN = 16  # window starts/sizes are 16-row aligned: Mosaic must prove
# HBM slice starts divisible by the tiling — 8 rows for f32, 16 for
# packed bf16 (8-sublane tile x 2-row packing)
BW = CE + ALIGN  # table-window rows per DMA: CE sorted edges span
# <= CE distinct rows; +ALIGN covers the aligned window start. Chunks
# wider than BW (the gather kernel's _BCAST_CE=1024 default) loop over
# ceil(span / BW) windows inside _window_gather_acc.


def _window_gather_acc(scal_ref, table_hbm, recv_ref, win_vmem, acc_ref, sems):
    """Shared windowed-gather loop: accumulate ``table[recv]`` for the
    current grid step's edge chunk into ``acc_ref`` (f32).

    A chunk's CE sorted ids hold <= CE distinct VALUES but may SPAN an
    arbitrary row range (ids can skip nodes), so the chunk loops over
    as many BW-wide windows as its span needs — ``scal_ref[1, k]``
    (prefetched) holds the count, 1 in the dense-receiver common case.
    Window DMA starts are clamped to stay in bounds; a logical range
    check keeps overlapping clamped windows from double-selecting.
    Exactness: each output row accumulates exactly one 1.0 x value
    product in f32 — native bf16 matmul for bf16 tables, HIGHEST for
    f32 (the f32-as-3xbf16 split times exact 1.0 reconstructs
    exactly). This is the subtlest logic in the file; it is shared by
    the bcast gather and the fused PNA backward's K2 so the two cannot
    diverge."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k = pl.program_id(0)
    astart = scal_ref[0, k]
    wcnt = scal_ref[1, k]
    n_clamp = scal_ref[2, 0]  # n_pad - BW: max legal DMA start
    recv = recv_ref[0, :]
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def dma(slot, wstart):
        return pltpu.make_async_copy(
            table_hbm.at[
                pl.ds(pl.multiple_of(jnp.minimum(wstart, n_clamp), ALIGN), BW), :
            ],
            win_vmem.at[slot],
            sems.at[slot],
        )

    dma(0, astart).start()

    def window_body(w, _):
        slot = w % 2
        wstart = astart + w * BW

        @pl.when(w + 1 < wcnt)
        def _prefetch():
            dma((w + 1) % 2, wstart + BW).start()

        dma(slot, wstart).wait()
        cstart = jnp.minimum(wstart, n_clamp)
        local = recv - cstart  # [CE]
        # fold the logical-range check into the index vector (Mosaic
        # cannot broadcast a 1-bit vector into a minor dim): ids outside
        # [wstart, wstart + BW) get a poison index no iota lane matches
        in_range = (recv >= wstart) & (recv < wstart + BW)
        local = jnp.where(in_range, local, -1)
        onehot = (
            local[:, None]
            == jax.lax.broadcasted_iota(jnp.int32, (recv.shape[0], BW), 1)
        )
        win = win_vmem[slot]
        if win.dtype == jnp.float32:
            acc_ref[:] += jax.lax.dot_general(
                onehot.astype(jnp.float32), win, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
        else:
            acc_ref[:] += jax.lax.dot_general(
                onehot.astype(win.dtype), win, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        return 0

    jax.lax.fori_loop(0, wcnt, window_body, 0)


def _window_plan(recv, e, n_pad_t, n_chunks, ce=None):
    """Per-chunk window plan (scalar-prefetch operand for
    :func:`_window_gather_acc`): [astart; wcnt; n_clamp] as int32
    [3, n_chunks]. ``recv`` is the chunk-padded sorted id vector whose
    sentinels are >= ``n_pad_t`` (outside every logical window)."""
    ce = CE if ce is None else ce
    first = recv[::ce][:n_chunks]
    astart = first & ~jnp.int32(ALIGN - 1)
    last_real = jnp.minimum(recv[ce - 1 :: ce][:n_chunks], recv[e - 1])
    wcnt = jnp.maximum(1, (last_real + 1 - astart + BW - 1) // BW)
    return jnp.stack(
        [astart, wcnt, jnp.full((n_chunks,), n_pad_t - BW, jnp.int32)]
    ).astype(jnp.int32)


def _window_plan_local(recv, n_pad_t, n_chunks, ce=None):
    """Window plan for UNSORTED ids: per-chunk min/max via a fused
    [n_chunks, CE] reshape reduction (the sorted plan's strided-slice
    shortcut assumes monotonicity). Correct for arbitrary ids; FAST
    only when each chunk's ids span a narrow row range — true for
    batched graphs, whose senders are confined to their graph's
    contiguous node block. Sentinel ids (>= n_pad_t) never match a
    window row (windows are clamped to n_pad_t - BW), so only the min
    needs guarding against them."""
    ce = CE if ce is None else ce
    chunks = recv[: n_chunks * ce].reshape(n_chunks, ce)
    lo = jnp.min(chunks, axis=1)
    hi = jnp.minimum(jnp.max(chunks, axis=1), n_pad_t - 1)
    astart = lo & ~jnp.int32(ALIGN - 1)
    wcnt = jnp.maximum(1, (hi + 1 - astart + BW - 1) // BW)
    return jnp.stack(
        [astart, wcnt, jnp.full((n_chunks,), n_pad_t - BW, jnp.int32)]
    ).astype(jnp.int32)


def _bcast_kernel(scal_ref, table_hbm, recv_ref, out_ref,
                  win_vmem, acc_ref, sems):
    """Grid step k: out rows [k*C, (k+1)*C) = table[recv rows], C =
    the call's chunk size (_BCAST_CE; chunks wider than BW loop over
    multiple table windows — the dense common case at the 1024 default).
    recv chunk and out chunk are Pallas-pipelined BlockSpec windows; the
    data-dependent table windows are manual DMAs (BlockSpec index maps
    cannot express data-dependent starts) — see
    :func:`_window_gather_acc`."""
    _window_gather_acc(scal_ref, table_hbm, recv_ref, win_vmem, acc_ref, sems)
    out_ref[:] = acc_ref[:].astype(out_ref.dtype)


def _bcast_kernel_call(table, ids, interpret, sorted_ids=True):
    """Shard-local windowed-row-gather kernel invocation. ``sorted_ids``
    picks the window-plan flavour: strided-slice shortcut for sorted
    ids, chunk min/max (:func:`_window_plan_local`) for unsorted-but-
    local ids — the kernel itself is id-order agnostic."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e = ids.shape[0]
    n, h = table.shape
    if e == 0:
        return table[:0]
    # The gather kernel has no cross-chunk accumulator, so its chunk
    # size can exceed the family/sum kernels' CE without VMEM pressure;
    # HYDRAGNN_BCAST_CE overrides (per-call measurement knob).
    bce = _BCAST_CE
    n_pad = max(((n + ALIGN - 1) // ALIGN) * ALIGN, BW)
    if n_pad != n:
        table = jnp.concatenate(
            [table, jnp.zeros((n_pad - n, h), table.dtype)], axis=0
        )
    e_pad = ((e + bce - 1) // bce) * bce
    # sentinel rows land outside every logical window -> zero rows
    recv = jnp.concatenate(
        [ids.astype(jnp.int32), jnp.full((e_pad - e,), n_pad, jnp.int32)]
    )
    n_chunks = e_pad // bce
    if sorted_ids:
        scal = _window_plan(recv, e, n_pad, n_chunks, ce=bce)
    else:
        scal = _window_plan_local(recv, n_pad, n_chunks, ce=bce)
    vma = _vma_of(recv, table)
    table = _match_vma(table, vma)
    recv = _match_vma(recv, vma)
    scal = _match_vma(scal, vma)
    out_sds = _sds((e_pad, h), table.dtype, vma=vma)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, bce), lambda k, ptr: (0, k)),
        ],
        out_specs=pl.BlockSpec((bce, h), lambda k, ptr: (k, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, BW, h), table.dtype),
            pltpu.VMEM((bce, h), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        _bcast_kernel,
        out_shape=out_sds,
        grid_spec=grid_spec,
        interpret=interpret,
    )(scal, table, recv[None, :])
    return out[:e]


# ---------------------------------------------------------------------------
# Fused gather + K-group pre-reduction (r05): the PNA aligned path's four
# statistics without materializing v = table[senders] in HBM
# ---------------------------------------------------------------------------
#
# The run-aligned PNA branch (models/convs.py) computed v via the bcast
# gather ([E, H] HBM write), then read it back 4-6x in separate fused
# passes (sum8, sumsq8, vmax8, vneg8 — the r05 trace's "fwd reduce_sum
# n=4" block at ~3.5 ms/layer). This kernel keeps the gathered chunk in
# VMEM and emits the K-group statistics directly:
#
#   stats [E/K, 2H] f32   = [group-sum(masked v) | group-sum(masked v^2)]
#   both  [E/K, 2H] dtype = [group-max(masked v) | group-max(masked -v)]
#
# exactly the layouts the downstream E/K segment ops consume. The
# backward (jax.custom_vjp in :func:`gather_presum_stats`) REGATHERS v
# once and differentiates the identical jnp composition, so gradient
# semantics (incl. reshape-max tie handling) match the unfused path by
# construction; grad_table is the windowed local scatter.


def _gather_stats_kernel(scal_ref, table_hbm, recv_ref, mask_ref,
                         stats_ref, both_ref, win_vmem, acc_ref, sems):
    """Grid step k: gather chunk k's rows into VMEM (shared windowed
    loop), then reduce the K-groups in registers. K is static:
    chunk_rows // stats_rows."""
    _window_gather_acc(scal_ref, table_hbm, recv_ref, win_vmem, acc_ref, sems)
    acc = acc_ref[:]  # [bce, h] f32 (exact for bf16 tables)
    bce, h = acc.shape
    k_stat = bce // stats_ref.shape[0]
    # arithmetic masking: Mosaic cannot broadcast a 1-bit vector into a
    # minor dim (same constraint as _window_gather_acc's range check),
    # so the mask rides as f32 0/1 — exact, and select-free
    mf = mask_ref[0, :].astype(jnp.float32)[:, None]
    vf = acc * mf
    stats_ref[:, :h] = vf.reshape(-1, k_stat, h).sum(axis=1)
    stats_ref[:, h:] = (vf * vf).reshape(-1, k_stat, h).sum(axis=1)
    # fill with the OUTPUT dtype's min so all-masked groups read back
    # exactly like the unfused where(m, v, finfo(dtype).min) path
    neg = jnp.float32(jnp.finfo(both_ref.dtype).min)
    fill = (1.0 - mf) * neg
    vx = (acc * mf + fill).reshape(-1, k_stat, h).max(axis=1)
    vn = (-acc * mf + fill).reshape(-1, k_stat, h).max(axis=1)
    both_ref[:, :h] = vx.astype(both_ref.dtype)
    both_ref[:, h:] = vn.astype(both_ref.dtype)


def _gather_stats_call(table, ids, mask, k_group, interpret):
    """Invoke the fused gather+stats kernel. ``ids`` are unsorted-but-
    local (batched-graph senders); requires k_group | len(ids) and the
    chunk size divisible by k_group (loader-aligned batches guarantee
    both). Returns (stats [E/k, 2H] f32, both [E/k, 2H] table.dtype)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e = ids.shape[0]
    n, h = table.shape
    bce = _BCAST_CE
    # explicit raise, not assert: a direct (non-gated) caller under
    # ``python -O`` must still get the invariant message rather than an
    # opaque Pallas BlockSpec/shape error downstream
    if e % bce != 0 or bce % k_group != 0:
        raise ValueError(
            "gather_presum_stats divisibility contract violated: needs "
            f"len(ids) % _BCAST_CE == 0 and _BCAST_CE % k_group == 0, got "
            f"len(ids)={e}, _BCAST_CE={bce}, k_group={k_group} — gate calls "
            "with gather_presum_eligible()"
        )
    n_pad = max(((n + ALIGN - 1) // ALIGN) * ALIGN, BW)
    if n_pad != n:
        table = jnp.concatenate(
            [table, jnp.zeros((n_pad - n, h), table.dtype)], axis=0
        )
    recv = ids.astype(jnp.int32)
    n_chunks = e // bce
    scal = _window_plan_local(recv, n_pad, n_chunks, ce=bce)
    mask_i = mask.astype(jnp.int32)
    vma = _vma_of(recv, table, mask_i)
    table = _match_vma(table, vma)
    recv = _match_vma(recv, vma)
    mask_i = _match_vma(mask_i, vma)
    scal = _match_vma(scal, vma)
    rows = e // k_group
    stats_sds = _sds((rows, 2 * h), jnp.float32, vma=vma)
    both_sds = _sds((rows, 2 * h), table.dtype, vma=vma)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, bce), lambda k, ptr: (0, k)),
            pl.BlockSpec((1, bce), lambda k, ptr: (0, k)),
        ],
        out_specs=[
            pl.BlockSpec((bce // k_group, 2 * h), lambda k, ptr: (k, 0)),
            pl.BlockSpec((bce // k_group, 2 * h), lambda k, ptr: (k, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, BW, h), table.dtype),
            pltpu.VMEM((bce, h), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    stats, both = pl.pallas_call(
        _gather_stats_kernel,
        out_shape=[stats_sds, both_sds],
        grid_spec=grid_spec,
        interpret=interpret,
    )(scal, table, recv[None, :], mask_i[None, :])
    return stats, both


def _presum_stats_ref(v, mask, k_group):
    """The unfused composition the kernel replaces — also the VJP's
    recompute target, so gradient semantics (reshape-sum broadcast,
    reshape-max even tie split) match the pre-r05 path exactly."""
    m = mask[:, None]
    h = v.shape[1]
    vf = jnp.where(m, v, 0).astype(jnp.float32)
    stats = jnp.concatenate(
        [
            vf.reshape(-1, k_group, h).sum(axis=1),
            (vf * vf).reshape(-1, k_group, h).sum(axis=1),
        ],
        axis=-1,
    )
    neg = jnp.finfo(v.dtype).min
    both = jnp.concatenate(
        [
            jnp.where(m, v, neg).reshape(-1, k_group, h).max(axis=1),
            jnp.where(m, -v, neg).reshape(-1, k_group, h).max(axis=1),
        ],
        axis=-1,
    )
    return stats, both


def local_min_rows() -> int:
    """Shared row threshold for the local-window kernel family: the
    fixed per-call cost (window plan + grid setup) only pays off on
    large operands (qm9's 61k-row config measured 7.5 vs 6.3 ms device
    on the local pair — docs/PERF.md r04)."""
    return knobs.get_int("HYDRAGNN_LOCAL_MIN_ROWS", 200_000)


def gather_presum_eligible(table, ids, win, k_group) -> bool:
    """Kernel-path gate for :func:`gather_presum_stats`: TPU with the
    local kernels active, host-emitted scatter windows present, lane-
    aligned width, and chunk divisibility at BOTH granularities (the
    call hard-asserts them; an ineligible shape must fall back, not
    crash — e.g. run_align=3 with an accidentally 1024-divisible
    E_pad, or a hand-tuned HYDRAGNN_BCAST_CE K doesn't divide)."""
    return (
        win is not None
        and table.ndim == 2
        and table.shape[1] % 128 == 0
        and ids.shape[0] % _BCAST_CE == 0
        and _BCAST_CE % k_group == 0
        and ids.shape[0] >= local_min_rows()
        and local_kernel_active()
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def gather_presum_stats(table, ids, mask, win, num_rows, k_group):
    """Fused ``v = table[ids]`` + masked K-group (sum, sumsq, max, -min)
    — the PNA aligned pre-reduction without materializing v in HBM.
    Callers must pass :func:`gather_presum_eligible` first; the fallback
    composition lives in the caller (models/convs.py), not here."""
    stats, both = _gather_stats_call(
        table, ids, mask, k_group, interpret=_interpret_mode()
    )
    return stats, both


def _gather_presum_fwd(table, ids, mask, win, num_rows, k_group):
    stats, both = gather_presum_stats(table, ids, mask, win, num_rows, k_group)
    return (stats, both), (table, ids, mask, win, both)


def _gather_presum_bwd(num_rows, k_group, res, cots):
    """Analytic backward: regather v once and assemble grad_v in closed
    form from the SAVED forward outputs — an earlier jax.vjp-based
    variant re-ran the whole forward composition inside the pullback
    (the primal is evaluated by jax.vjp), costing ~2.3 ms/layer of
    redundant E-level passes on the flagship trace.

    Semantics match plain AD of :func:`_presum_stats_ref`: the sum
    terms are linear (+ 2 v g for the square), the max terms follow
    jax's reduce-max convention — even split among tied group slots,
    tie counts taken on the FILLED values (masked slots tie only in
    all-masked groups, where the mask factor zeroes them anyway).
    Share math runs f32 (the extremum-VJP contract, segment.py)."""
    table, ids, mask, win, both_fwd = res
    g_stats, g_both = cots
    h = table.shape[1]
    m = mask[:, None]
    v = gather_rows_local_fast(table, ids)

    def rep(a):
        return jnp.broadcast_to(
            a[:, None, :], (a.shape[0], k_group, a.shape[1])
        ).reshape(a.shape[0] * k_group, a.shape[1])

    # tie masks stay in the COMPUTE dtype (0/1 exact in bf16; group
    # counts <= k_group are exact too) — an f32 formulation materialized
    # ~2 GB/layer of converts on the flagship trace. Shares divide in
    # f32 at the E/K level (bandwidth-trivial), then broadcast.
    neg = jnp.finfo(v.dtype).min
    tie_x = (jnp.where(m, v, neg) == rep(both_fwd[:, :h])).astype(v.dtype)
    tie_n = (jnp.where(m, -v, neg) == rep(both_fwd[:, h:])).astype(v.dtype)
    cnt_x = tie_x.reshape(-1, k_group, h).sum(axis=1).astype(jnp.float32)
    cnt_n = tie_n.reshape(-1, k_group, h).sum(axis=1).astype(jnp.float32)
    share_x = (
        g_both[:, :h].astype(jnp.float32) / jnp.maximum(cnt_x, 1.0)
    ).astype(v.dtype)
    share_n = (
        g_both[:, h:].astype(jnp.float32) / jnp.maximum(cnt_n, 1.0)
    ).astype(v.dtype)
    vf = jnp.where(m, v, 0).astype(jnp.float32)
    grad = (
        rep(g_stats[:, :h])
        + 2.0 * vf * rep(g_stats[:, h:])
        + (tie_x * rep(share_x)).astype(jnp.float32)
        - (tie_n * rep(share_n)).astype(jnp.float32)
    )
    grad_v = jnp.where(m, grad, 0.0).astype(table.dtype)
    grad_table = segment_sum_local_fast(
        grad_v, ids, win, num_rows
    ).astype(table.dtype)
    f0 = jax.dtypes.float0
    return (
        grad_table,
        jnp.zeros(ids.shape, dtype=f0),
        jnp.zeros(mask.shape, dtype=f0),
        jnp.zeros(win.shape, dtype=f0),
    )


gather_presum_stats.defvjp(_gather_presum_fwd, _gather_presum_bwd)


def _make_partitioned_bcast():
    """custom_partitioning wrapper: ids may be GSPMD-sharded on the edge
    axis (each shard's slice is contiguous and sorted — the giant-graph
    path); the table is replicated and each device gathers its local
    rows. Output follows the ids' edge sharding; no collective."""

    def base(table, ids, interpret, sorted_ids=True):
        return _bcast_kernel_call(table, ids, interpret, sorted_ids)

    op = custom_partitioning(base, static_argnums=(2, 3))

    def infer(interpret, sorted_ids, mesh, arg_shapes, result_shape):
        ids_spec = arg_shapes[1].sharding.spec
        edge_axis = ids_spec[0] if len(ids_spec) >= 1 else None
        return NamedSharding(mesh, P(edge_axis, None))

    def partition(interpret, sorted_ids, mesh, arg_shapes, result_shape):
        ids_spec = arg_shapes[1].sharding.spec
        edge_axis = ids_spec[0] if len(ids_spec) >= 1 else None

        def lower_fn(table, ids):
            return _bcast_kernel_call(table, ids, interpret, sorted_ids)

        arg_sh = (
            NamedSharding(mesh, P(None, None)),
            NamedSharding(mesh, P(edge_axis)),
        )
        return mesh, lower_fn, NamedSharding(mesh, P(edge_axis, None)), arg_sh

    _def_partition_compat(
        op,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule="n h, e -> e h",
    )
    return op


_BCAST_OP = _make_partitioned_bcast()


def gather_rows_sorted_fast(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """``table[ids]`` for SORTED ids: the CSR-broadcast Pallas kernel on
    TPU (one-hot MXU matmul per edge chunk — streams at bandwidth where
    XLA's row gather loops serially), plain indexing otherwise. NOT
    differentiated — callers are custom backward functions (the gather's
    own VJP would be a sorted segment sum). Same knob contract as
    :func:`segment_sum_family`; requires 2-D [N, H] table with
    H % 128 == 0 for the kernel path (narrower tables are lane-padded
    in and sliced back — :func:`_lane_pad`)."""
    if ids.shape[0] == 0 or table.ndim != 2:
        return table[ids]
    h = _narrow_kernel_width(table, indices_are_sorted=True)
    if h is not None:
        return _BCAST_OP(_lane_pad(table), ids, _interpret_mode(), True)[:, :h]
    if _use_pallas(table, indices_are_sorted=True):
        return _BCAST_OP(table, ids, _interpret_mode(), True)
    return table[ids]


def gather_rows_local_fast(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """``table[ids]`` for UNSORTED-BUT-LOCAL ids (each id chunk
    spans a narrow row range — batched-graph senders): the windowed
    bcast kernel with the chunk-min/max plan. Plain indexing off-TPU.
    NOT differentiated, like :func:`gather_rows_sorted_fast` — callers
    pair it with the local-window segment sum backward."""
    if ids.shape[0] == 0 or table.ndim != 2:
        return table[ids]
    h = _narrow_kernel_width(table, indices_are_sorted=True)
    if h is not None:
        return _BCAST_OP(_lane_pad(table), ids, _interpret_mode(), False)[:, :h]
    if _use_pallas(table, indices_are_sorted=True):
        return _BCAST_OP(table, ids, _interpret_mode(), False)
    return table[ids]


def _kernel_eligible(indices_are_sorted: bool) -> bool:
    """Knob/backend part of the dispatch decision (no shape check)."""
    if _FORCE_XLA.get():
        return False
    knob = knobs.get_str("HYDRAGNN_PALLAS", "auto")
    if knob == "0":
        return False
    if not pallas_available():
        return False
    if knob == "interpret":
        return True
    if knob == "1":
        return jax.default_backend() == "tpu"
    return indices_are_sorted and jax.default_backend() == "tpu"


def local_kernel_active() -> bool:
    """Trace-time: would the local-window kernel pair actually lower to
    Pallas here? Callers holding BOTH a window plan and a sorted perm
    (the model chassis) use this to pick the local path only when it
    wins — on forced-XLA paths (vmap'd dp_edge step, non-TPU backends)
    the sorted-permute fallback beats the unsorted scatter-add the
    local fallback would pay."""
    return _kernel_eligible(indices_are_sorted=True)


def _use_pallas(data: jnp.ndarray, indices_are_sorted: bool) -> bool:
    """Shared HYDRAGNN_PALLAS knob contract (module docstring): "1"
    forces the kernel on TPU, "interpret" forces it in interpret mode
    on any backend, "0" forces XLA, default auto = Pallas on TPU for
    sorted, 2-D, 128-lane-multiple data. :func:`xla_segment_ops`
    overrides everything (vmap has no custom_partitioning rule)."""
    tiles = data.ndim == 2 and data.shape[1] % 128 == 0
    return tiles and _kernel_eligible(indices_are_sorted)


def _narrow_kernel_width(data: jnp.ndarray, indices_are_sorted: bool):
    """The shared narrow-data dispatch test: returns the original width
    ``h`` when ``data`` is 2-D, NOT 128-lane aligned, and the knob /
    backend allow the kernel — i.e. the caller should ``_lane_pad`` the
    data in and slice ``[:, :h]`` back out. None otherwise. One
    definition so the eligibility contract cannot diverge between the
    gather / sum / family dispatchers."""
    if data.ndim != 2:
        return None
    h = data.shape[1]
    if h % 128 != 0 and _kernel_eligible(indices_are_sorted):
        return h
    return None


def _lane_pad(data: jnp.ndarray) -> jnp.ndarray:
    """Zero-pad the feature axis up to the next 128-lane multiple.

    XLA's scatter/gather segment lowerings loop PER ROW on TPU, so a
    narrow op (e.g. the first conv layer, whose width is the raw
    feature count) costs the same 5-9 ms as a 128-wide one while the
    Pallas kernels stream rows in bulk — padding lanes to reach the
    kernel is a large net win (r03 trace: conv_0's XLA-fallback ops
    were ~40 ms of the step). Callers slice the result back; under AD
    the pad's transpose slices cotangents automatically."""
    h = data.shape[1]
    hp = ((h + 127) // 128) * 128
    return jnp.concatenate(
        [data, jnp.zeros((data.shape[0], hp - h), data.dtype)], axis=1
    )


def _interpret_mode() -> bool:
    return knobs.get_str("HYDRAGNN_PALLAS", "auto") == "interpret"


def segment_sum_fast(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    """Segment sum for VJP hot paths: the Pallas CSR kernel on TPU when
    receivers are sorted and the width tiles (same knob contract as
    :func:`segment_sum_family`), XLA otherwise. Not differentiated
    itself — callers are custom backward functions.

    ACCUMULATION CONTRACT: sums always accumulate in >= f32 regardless
    of input dtype — the kernel accumulates f32 natively (bf16 inputs
    DMA half the bytes, exact for 0/1-valued data like tie masks), and
    the XLA fallback upcasts sub-f32 inputs first. Callers may
    therefore pass bf16 cotangents/masks purely for bandwidth.

    f32 inputs ride a 3-term bf16 split (3 native MXU matmuls); the
    reconstruction is bit-exact only while all three split terms stay
    bf16-normal — |x| >= ~1e-30. Below that the lo/mid terms flush
    (bf16 subnormals) and accuracy decays to the hi term's 8 bits; the
    on-chip selfcheck gates the measured decay bands for BOTH the
    gather (``bcast_tiny_magnitude_f32``) and this sum path
    (``sum_tiny_magnitude_f32``). Training impact: segments whose
    values sit below ~1e-30 are numerically zero anyway.

    Narrow data is lane-padded into the kernel (see :func:`_lane_pad`)."""
    h = _narrow_kernel_width(data, indices_are_sorted)
    if h is not None:
        out = segment_sum_pallas(
            _lane_pad(data), segment_ids, num_segments, mask,
            interpret=_interpret_mode(),
            indices_are_sorted=indices_are_sorted,
        )
        return out[:, :h]
    if _use_pallas(data, indices_are_sorted):
        return segment_sum_pallas(
            data, segment_ids, num_segments, mask,
            interpret=_interpret_mode(),
            indices_are_sorted=indices_are_sorted,
        )
    if data.dtype in (jnp.bfloat16, jnp.float16):
        data = data.astype(jnp.float32)
    if mask is not None:
        data = data * mask[:, None].astype(data.dtype)
    return jax.ops.segment_sum(
        data, segment_ids, num_segments, indices_are_sorted=indices_are_sorted
    )


def _family_impl(data, segment_ids, num_segments, mask, indices_are_sorted, use_pallas):
    if use_pallas:
        return segment_sum_family_pallas(
            data, segment_ids, num_segments, mask,
            interpret=_interpret_mode(),
            indices_are_sorted=indices_are_sorted,
        )
    return segment_sum_family_xla(
        data, segment_ids, num_segments, mask,
        indices_are_sorted=indices_are_sorted,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 4, 5))
def _family(data, segment_ids, num_segments, mask, indices_are_sorted, use_pallas):
    """Family with a hand-written gather backward: makes the Pallas
    kernel trainable (pallas_call has no native VJP) and replaces XLA's
    packed-scatter VJP with the closed form
    d/d(data) = m * g_sum[ids] + 2 * m^2 * data * g_sumsq[ids]
    (m = mask weights; for a boolean mask m^2 = m and this reduces to
    the gated form)."""
    return _family_impl(data, segment_ids, num_segments, mask,
                        indices_are_sorted, use_pallas)


def _family_fwd(data, segment_ids, num_segments, mask, indices_are_sorted, use_pallas):
    out = _family_impl(data, segment_ids, num_segments, mask,
                       indices_are_sorted, use_pallas)
    return out, (data, segment_ids, mask)


def _family_bwd(num_segments, indices_are_sorted, use_pallas, res, g):
    data, segment_ids, mask = res
    g_sum, g_sumsq, _ = g  # count is data-independent
    # cast the [N, H] cotangents to the data dtype BEFORE the
    # [E, H]-widening gathers: under bf16 mixed precision this halves
    # the two gather writes (the backward's dominant HBM traffic), and
    # the final cotangent is data.dtype regardless
    g_sum = g_sum.astype(data.dtype)
    g_sumsq = g_sumsq.astype(data.dtype)
    if indices_are_sorted:
        # ONE stacked CSR-broadcast instead of two serial XLA row
        # gathers (the r03 trace's dominant backward cost: 6-9 ms each
        # at E=699k vs ~0.5 ms through the kernel)
        both = gather_rows_sorted_fast(
            jnp.concatenate([g_sum, g_sumsq], axis=-1), segment_ids
        )
        h = data.shape[1]
        g_sum_e, g_sumsq_e = both[:, :h], both[:, h:]
    else:
        g_sum_e, g_sumsq_e = g_sum[segment_ids], g_sumsq[segment_ids]
    sumsq_term = 2.0 * data * g_sumsq_e
    if mask is None:
        grad = g_sum_e + sumsq_term
        mask_zero = None
    else:
        # weighted closed form: out_sum = sum(m*d), out_sumsq = sum(m^2*d^2)
        # => d/dd = m*g_sum[ids] + 2*m^2*d*g_sumsq[ids]
        m = mask.astype(g_sum.dtype)[:, None]
        grad = m * (g_sum_e + m * sumsq_term)
        # the mask is non-differentiable by contract (stop_gradient on
        # entry in segment_sum_family): bool/int masks take a float0
        # cotangent, float weight masks a true-zero one
        if jnp.issubdtype(mask.dtype, jnp.floating):
            mask_zero = jnp.zeros(mask.shape, dtype=mask.dtype)
        else:
            mask_zero = jnp.zeros(mask.shape, dtype=jax.dtypes.float0)
    ids_zero = jnp.zeros(segment_ids.shape, dtype=jax.dtypes.float0)
    return grad.astype(data.dtype), ids_zero, mask_zero


_family.defvjp(_family_fwd, _family_bwd)


# ---------------------------------------------------------------------------
# Fused PNA aggregation: (sum, sumsq, [max(v), max(-v)]) with a two-kernel
# backward
# ---------------------------------------------------------------------------
#
# The r03 retrace showed the PNA backward still paying ~128 ms/step in
# edge-space fragments: per layer, two widening gathers for the family
# cotangents, tie-mask construction + a count kernel + a share gather
# per extremum, then three [E, H] cotangent branches concatenated and
# added. Fusing the WHOLE aggregation backward into two CSR kernels
# collapses all of it to three [E, *] passes per layer:
#
#   K1 (node-block grid): one pass over v computing the min/max tie
#      counts [N, 2H] — the per-edge extremum values arrive via a
#      one-hot MXU matmul against the node-blocked `both` array, so the
#      tie masks never touch HBM.
#   K2 (edge-chunk grid): one pass over v emitting the COMPLETE grad_v
#      — all six node-level tables (g_sum, g_sumsq, both, shares) are
#      stacked into one [N, 6H] table and gathered per chunk with a
#      single windowed one-hot matmul (the bcast kernel's window plan),
#      then combined in VMEM:
#        grad = m * (g_sum_e + 2 v g_sumsq_e
#                    + (v == max_e) shmax_e - ((-v) == negmin_e) shmin_e)
#
# Exactness of the tie compares: one-hot x bf16 products are exact and
# each output row accumulates exactly one nonzero product in f32, so the
# gathered extremum is a bit-exact row copy and `v == max_e` matches the
# unfused semantics. Masked edges carry vv = -inf in K1 (never tie in a
# real segment) and are zeroed by the final m factor in K2; `both` is
# empty-cleaned to 0 before the backward, so empty segments tie nothing.
#
# The float-weight-mask case (m^2 factor on the sumsq term) and
# non-kernel contexts fall back to an unfused composition of the same
# formulas.


def _pna_bwd_count_kernel(block_ptr_ref, v_hbm, recv_hbm, mask_hbm, both_ref,
                          cnt_ref, v_vmem, recv_vmem, mask_vmem, sems):
    """K1: per node block, count min/max ties over the block's edges."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    lo = block_ptr_ref[i]
    hi = block_ptr_ref[i + 1]
    cnt_ref[:] = jnp.zeros_like(cnt_ref)
    k0 = lo // CE
    k1 = (hi + CE - 1) // CE
    has_mask = mask_hbm is not None

    def dmas(slot, k):
        start = pl.multiple_of(k * CE, CE)
        cps = [
            pltpu.make_async_copy(
                v_hbm.at[pl.ds(start, CE), :], v_vmem.at[slot], sems.at[slot, 0]
            ),
            pltpu.make_async_copy(
                recv_hbm.at[:, pl.ds(start, CE)], recv_vmem.at[slot], sems.at[slot, 1]
            ),
        ]
        if has_mask:
            cps.append(
                pltpu.make_async_copy(
                    mask_hbm.at[:, pl.ds(start, CE)], mask_vmem.at[slot],
                    sems.at[slot, 2],
                )
            )
        return cps

    @pl.when(k0 < k1)
    def _warmup():
        for cp in dmas(k0 % 2, k0):
            cp.start()

    def chunk_body(k, _):
        slot = k % 2

        @pl.when(k + 1 < k1)
        def _prefetch():
            for cp in dmas((k + 1) % 2, k + 1):
                cp.start()

        for cp in dmas(slot, k):
            cp.wait()
        v = v_vmem[slot]
        # tie detection runs in f32 regardless of data dtype (the v5e
        # VPU has no bf16 compare): bf16 -> f32 is exact, and the
        # gathered extremum rows are f32 accumulations of exact values
        neg = float(jnp.finfo(v.dtype).min)
        vv = jnp.concatenate([v, -v], axis=-1).astype(jnp.float32)  # [CE, 2H]
        if has_mask:
            # arithmetic masking (avoids broadcasting a 1-bit vector):
            # unmasked rows keep their value, masked rows become the
            # forward's where(mask, vv, finfo.min) sentinel
            m = (mask_vmem[slot][0, :][:, None] > 0).astype(jnp.float32)
            vv = jnp.maximum(vv * m + (1.0 - m) * neg, neg)
        rows = jax.lax.broadcasted_iota(jnp.int32, (BN, CE), 0) + i * BN
        onehot = recv_vmem[slot] == rows  # [BN, CE]
        # per-edge extremum rows via one-hot matmul against the node
        # block: exact row copies — native bf16 for bf16 data (0/1
        # products exact, single nonzero per row, f32 accumulation),
        # HIGHEST for f32 (the 3x-bf16 split times exact 1.0
        # reconstructs exactly)
        if v.dtype == jnp.bfloat16:
            oh = onehot.astype(jnp.bfloat16)
            both_e = jax.lax.dot_general(
                oh, both_ref[:], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            sel = (vv == both_e).astype(jnp.bfloat16)
            cnt_ref[:] += jax.lax.dot_general(
                oh, sel, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            oh = onehot.astype(jnp.float32)
            both_e = jax.lax.dot_general(
                oh, both_ref[:].astype(jnp.float32), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            sel = (vv == both_e).astype(jnp.float32)
            cnt_ref[:] += jax.lax.dot_general(
                oh, sel, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
        return 0

    jax.lax.fori_loop(k0, k1, chunk_body, 0)


def _pna_bwd_grad_kernel(scal_ref, table_hbm, recv_ref, v_ref, mask_ref,
                         grad_ref, win_vmem, acc_ref, sems):
    """K2: per edge chunk, gather the stacked [N, 6H] cotangent table
    (shared window plan/loop — :func:`_window_gather_acc`) and emit the
    complete grad_v chunk."""
    _window_gather_acc(scal_ref, table_hbm, recv_ref, win_vmem, acc_ref, sems)

    v = v_ref[:]
    h = v.shape[1]
    # combine in f32: the acc rows are exact copies of the (possibly
    # bf16) table values, v upcasts exactly, and the v5e VPU has no
    # bf16 compare anyway — only the final grad casts back
    vf = v.astype(jnp.float32)
    g = acc_ref[:]  # [CE, 6H] f32
    gs, gss = g[:, :h], g[:, h : 2 * h]
    mx, mnn = g[:, 2 * h : 3 * h], g[:, 3 * h : 4 * h]
    shx, shn = g[:, 4 * h : 5 * h], g[:, 5 * h :]
    grad = gs + 2.0 * vf * gss
    grad = grad + jnp.where(vf == mx, shx, 0.0)
    grad = grad - jnp.where(-vf == mnn, shn, 0.0)
    if mask_ref is not None:
        m = (mask_ref[0, :] > 0).astype(jnp.float32)
        # bool-mask semantics: m == m^2, one factor gates everything
        grad = grad * m[:, None]
    grad_ref[:] = grad.astype(grad_ref.dtype)


def _pna_bwd_kernels(v, receivers, mask, both, g_sum, g_sumsq, g_both,
                     num_segments, interpret):
    """Shard-local fused backward: K1 tie counts, node-level shares,
    K2 full grad. Requires sorted receivers, H % 128 == 0, bool mask."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e, h = v.shape
    vd = v.dtype
    n_pad_out = ((num_segments + BN - 1) // BN) * BN
    e_pad = ((e + CE - 1) // CE) * CE
    recv = jnp.concatenate(
        [receivers.astype(jnp.int32), jnp.full((e_pad - e,), n_pad_out, jnp.int32)]
    )
    v_p = jnp.concatenate([v, jnp.zeros((e_pad - e, h), vd)], axis=0)
    if mask is not None:
        mask_i = jnp.concatenate(
            [mask.astype(jnp.int32), jnp.zeros((e_pad - e,), jnp.int32)]
        )
    else:
        mask_i = None

    # ---- K1: tie counts [n_pad_out, 2H] ----
    both_p = jnp.concatenate(
        [both, jnp.zeros((n_pad_out - num_segments, 2 * h), both.dtype)], axis=0
    )
    n_blocks = n_pad_out // BN
    boundaries = jnp.arange(n_blocks + 1, dtype=jnp.int32) * BN
    block_ptr = jnp.searchsorted(recv[:e], boundaries, side="left").astype(jnp.int32)
    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),  # v
        pl.BlockSpec(memory_space=pl.ANY),  # recv
    ]
    operands = [v_p, recv[None, :]]
    if mask_i is not None:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        operands.append(mask_i[None, :])
    in_specs.append(pl.BlockSpec((BN, 2 * h), lambda i, ptr: (i, 0)))  # both
    operands.append(both_p)

    def k1_kernel(*args):
        if mask_i is not None:
            ptr, vh, rh, mh, bh, cnt, vv, rv, mv, sems = args
            _pna_bwd_count_kernel(ptr, vh, rh, mh, bh, cnt, vv, rv, mv, sems)
        else:
            ptr, vh, rh, bh, cnt, vv, rv, sems = args
            _pna_bwd_count_kernel(ptr, vh, rh, None, bh, cnt, vv, rv, None, sems)

    scratch = [
        pltpu.VMEM((2, CE, h), vd),
        pltpu.VMEM((2, 1, CE), jnp.int32),
    ]
    if mask_i is not None:
        scratch.append(pltpu.VMEM((2, 1, CE), jnp.int32))
    scratch.append(pltpu.SemaphoreType.DMA((2, 3)))
    # under shard_map with check_vma=True the out_shape must declare
    # which manual mesh axes the result varies over, and every operand
    # must carry them (same as the family/bcast kernels)
    vma = _vma_of(v_p, recv, both_p)
    operands = [_match_vma(o, vma) for o in operands]
    block_ptr = _match_vma(block_ptr, vma)
    cnt_both = pl.pallas_call(
        k1_kernel,
        out_shape=_sds((n_pad_out, 2 * h), jnp.float32, vma=vma),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_blocks,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((BN, 2 * h), lambda i, ptr: (i, 0)),
            scratch_shapes=scratch,
        ),
        interpret=interpret,
    )(block_ptr, *operands)[:num_segments]

    # ---- node-level shares, stacked table ----
    share = (g_both.astype(jnp.float32) / jnp.maximum(cnt_both, 1.0)).astype(vd)
    table = jnp.concatenate(
        [g_sum.astype(vd), g_sumsq.astype(vd), both.astype(vd), share], axis=-1
    )  # [num_segments, 6H]

    # ---- K2: full grad via the bcast window plan over the 6H table ----
    n = table.shape[0]
    n_pad_t = max(((n + ALIGN - 1) // ALIGN) * ALIGN, BW)
    table_p = jnp.concatenate(
        [table, jnp.zeros((n_pad_t - n, 6 * h), vd)], axis=0
    )
    recv_t = jnp.where(recv >= n, n_pad_t, recv)  # sentinels beyond windows
    n_chunks = e_pad // CE
    scal = _window_plan(recv_t, e, n_pad_t, n_chunks)

    in_specs2 = [
        pl.BlockSpec(memory_space=pl.ANY),  # table
        pl.BlockSpec((1, CE), lambda k, ptr: (0, k)),  # recv
        pl.BlockSpec((CE, h), lambda k, ptr: (k, 0)),  # v
    ]
    operands2 = [table_p, recv_t[None, :], v_p]
    if mask_i is not None:
        in_specs2.append(pl.BlockSpec((1, CE), lambda k, ptr: (0, k)))
        operands2.append(mask_i[None, :])

    def k2_kernel(*args):
        if mask_i is not None:
            scal_r, th, rr, vr, mr, gr, wv, ac, sems = args
            _pna_bwd_grad_kernel(scal_r, th, rr, vr, mr, gr, wv, ac, sems)
        else:
            scal_r, th, rr, vr, gr, wv, ac, sems = args
            _pna_bwd_grad_kernel(scal_r, th, rr, vr, None, gr, wv, ac, sems)

    vma2 = vma | _vma_of(table_p)
    operands2 = [_match_vma(o, vma2) for o in operands2]
    scal = _match_vma(scal, vma2)
    grad = pl.pallas_call(
        k2_kernel,
        out_shape=_sds((e_pad, h), vd, vma=vma2),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_chunks,),
            in_specs=in_specs2,
            out_specs=pl.BlockSpec((CE, h), lambda k, ptr: (k, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, BW, 6 * h), vd),
                pltpu.VMEM((CE, 6 * h), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        interpret=interpret,
    )(scal, *operands2)
    return grad[:e]


def _pna_bwd_unfused(v, receivers, mask, both, g_sum, g_sumsq, g_both,
                     num_segments, indices_are_sorted):
    """Reference composition of the same backward (CPU / vmap / float
    masks): identical math, built from the dispatching building blocks."""
    vd = v.dtype
    h = v.shape[1]
    neg = jnp.finfo(vd).min
    vv = jnp.concatenate([v, -v], axis=-1)
    if mask is not None:
        vv = jnp.where(mask[:, None], vv, neg)
    from hydragnn_tpu.graph.segment import _gather_fwd_impl

    both_e = _gather_fwd_impl(both.astype(vd), receivers, indices_are_sorted)
    sel = vv == both_e
    cnt_both = segment_sum_fast(
        sel.astype(vd), receivers, num_segments,
        indices_are_sorted=indices_are_sorted,
    ).astype(jnp.float32)
    share = (g_both.astype(jnp.float32) / jnp.maximum(cnt_both, 1.0)).astype(vd)
    gpack = _gather_fwd_impl(
        jnp.concatenate([g_sum.astype(vd), g_sumsq.astype(vd), share], axis=-1),
        receivers, indices_are_sorted,
    )
    gs, gss, sh = gpack[:, :h], gpack[:, h : 2 * h], gpack[:, 2 * h :]
    ties = jnp.where(sel, sh, vd.type(0))
    tie_term = ties[:, :h] - ties[:, h:]
    if mask is None:
        grad = gs + 2.0 * v * gss + tie_term
    elif jnp.issubdtype(mask.dtype, jnp.floating):
        # float masks WEIGHT the sums (m on sum, m^2 on sumsq — the
        # family's weighted closed form) but only GATE the extremum
        # (the forward's where(mask, vv, -inf) is a boolean gate)
        m = mask.astype(vd)[:, None]
        mb = (mask != 0).astype(vd)[:, None]
        grad = m * gs + m * m * 2.0 * v * gss + mb * tie_term
    else:
        m = mask.astype(vd)[:, None]
        grad = m * (gs + 2.0 * v * gss + tie_term)
    return grad.astype(vd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 4))
def _pna_aggregate(v, receivers, num_segments, mask, indices_are_sorted):
    s, sq, cnt = _family_impl(
        v, receivers, num_segments, mask, indices_are_sorted,
        _use_pallas(v, indices_are_sorted),
    )
    vd = v.dtype
    neg = jnp.finfo(vd).min
    vv = jnp.concatenate([v, -v], axis=-1)
    if mask is not None:
        vv = jnp.where(mask[:, None], vv, neg)
    raw = jax.ops.segment_max(
        vv, receivers, num_segments, indices_are_sorted=indices_are_sorted
    )
    both = jnp.where(raw <= neg, vd.type(0), raw)  # empty-cleaned
    return s, sq, cnt, both


def _pna_aggregate_fwd(v, receivers, num_segments, mask, indices_are_sorted):
    out = _pna_aggregate(v, receivers, num_segments, mask, indices_are_sorted)
    return out, (v, receivers, mask, out[3])


def _pna_aggregate_bwd(num_segments, indices_are_sorted, res, g):
    v, receivers, mask, both = res
    g_sum, g_sumsq, _, g_both = g  # count is data-independent
    float_mask = mask is not None and jnp.issubdtype(mask.dtype, jnp.floating)
    if (
        indices_are_sorted
        and v.ndim == 2
        and v.shape[1] % 128 == 0
        and not float_mask
        and _kernel_eligible(indices_are_sorted)
    ):
        grad = _pna_bwd_kernels(
            v, receivers, mask, both.astype(v.dtype), g_sum, g_sumsq, g_both,
            num_segments, _interpret_mode(),
        )
    else:
        grad = _pna_bwd_unfused(
            v, receivers, mask, both.astype(v.dtype), g_sum, g_sumsq, g_both,
            num_segments, indices_are_sorted,
        )
    ids_zero = jnp.zeros(receivers.shape, dtype=jax.dtypes.float0)
    if mask is None:
        mask_zero = None
    elif jnp.issubdtype(mask.dtype, jnp.floating):
        mask_zero = jnp.zeros(mask.shape, dtype=mask.dtype)
    else:
        mask_zero = jnp.zeros(mask.shape, dtype=jax.dtypes.float0)
    return grad, ids_zero, mask_zero


_pna_aggregate.defvjp(_pna_aggregate_fwd, _pna_aggregate_bwd)


def pna_aggregate(
    v: jnp.ndarray,
    receivers: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused PNA aggregation statistics of ``v`` grouped by receiver.

    Returns ``(vsum f32, vsumsq f32, cnt f32, both)`` where
    ``both[:, :H] = segment_max(v)`` and ``both[:, H:] =
    segment_max(-v)`` (= -min), masked, with EMPTY segments already
    cleaned to 0; ``cnt`` is the mask-aware per-segment edge count the
    family pass computes anyway (data-independent cotangent — callers
    with a precomputed degree can ignore it and XLA dead-code
    eliminates it). The backward is the two-kernel fused pass
    documented above (falls back to an unfused composition off-TPU /
    under vmap / for float masks). The mask is non-differentiable by
    contract. Narrow data is lane-padded into the kernels
    (:func:`_lane_pad`) and the outputs sliced back."""
    if mask is not None:
        mask = jax.lax.stop_gradient(mask)
    h = _narrow_kernel_width(v, indices_are_sorted)
    if h is not None:
        s, sq, cnt, both = _pna_aggregate(
            _lane_pad(v), receivers, num_segments, mask, indices_are_sorted
        )
        hp = (h + 127) // 128 * 128
        both = jnp.concatenate([both[:, :h], both[:, hp : hp + h]], axis=-1)
        return s[:, :h], sq[:, :h], cnt, both
    return _pna_aggregate(v, receivers, num_segments, mask, indices_are_sorted)


def segment_sum_family(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dispatch. Default ("auto"): the double-buffered Pallas kernel on
    TPU when the caller guarantees sorted receivers and the feature
    width is a 128-lane multiple (measured 5.5x faster than the XLA
    scatter at E=120k, H=128 on v5e — docs/PERF.md); the fused XLA pass
    otherwise. The kernel op carries a custom_partitioning rule, so it
    composes with GSPMD edge sharding (module docstring); only vmap
    contexts need :func:`xla_segment_ops`. The mask (edge validity or
    float weights) is non-differentiable by contract. Narrow data is
    lane-padded into the kernel (:func:`_lane_pad`; the pad's AD
    transpose slices the cotangent back automatically)."""
    if mask is not None:
        mask = jax.lax.stop_gradient(mask)
    h = _narrow_kernel_width(data, indices_are_sorted)
    if h is not None:
        s, sq, cnt = _family(
            _lane_pad(data), segment_ids, num_segments, mask,
            indices_are_sorted, True,
        )
        return s[:, :h], sq[:, :h], cnt
    use_pallas = _use_pallas(data, indices_are_sorted)
    return _family(data, segment_ids, num_segments, mask,
                   indices_are_sorted, use_pallas)
