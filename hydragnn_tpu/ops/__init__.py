from hydragnn_tpu.ops.fused_conv import (
    fused_conv,
    fused_conv_active,
    fused_conv_stack,
    residency_vmem_budget_bytes,
    residency_vmem_bytes,
)
from hydragnn_tpu.ops.segment_pallas import (
    pallas_available,
    pna_aggregate,
    segment_sum_family,
    segment_sum_family_pallas,
    segment_sum_family_xla,
)
