from hydragnn_tpu.ops.segment_pallas import (
    pallas_available,
    segment_sum_family,
    segment_sum_family_pallas,
    segment_sum_family_xla,
)
