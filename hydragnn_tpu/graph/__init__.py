from hydragnn_tpu.graph.batch import GraphBatch, batch_graphs, pad_batch
from hydragnn_tpu.graph.segment import (
    segment_sum,
    segment_mean,
    segment_max,
    segment_min,
    segment_std,
    segment_softmax,
    node_degree,
)

__all__ = [
    "GraphBatch",
    "batch_graphs",
    "pad_batch",
    "segment_sum",
    "segment_mean",
    "segment_max",
    "segment_min",
    "segment_std",
    "segment_softmax",
    "node_degree",
]
