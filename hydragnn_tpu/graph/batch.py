"""Statically-padded graph batches — the TPU-native PyG ``Batch`` equivalent.

The reference feeds ragged PyG ``Data`` objects through a collate that
concatenates nodes/edges and keeps a ``batch`` vector (torch_geometric
collate, consumed at reference hydragnn/models/Base.py:244-275). Ragged
shapes recompile under ``jit``, so here a batch is padded to static
``(num_nodes, num_edges, num_graphs)`` with explicit masks:

  - one *padding graph* slot absorbs all padding nodes/edges (jraph-style),
  - padding edges point at a padding node, so segment reductions stay clean,
  - targets are a dict-of-heads ``{head_name: values}`` replacing the
    reference's ragged ``data.y`` + ``y_loc`` offset table
    (reference: hydragnn/preprocess/serialized_dataset_loader.py:262-303,
    hydragnn/train/train_validate_test.py:218-281) — per-head values carry
    their own masks, which eliminates the index gymnastics while keeping
    loss parity.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
import jax.numpy as jnp


def _round_up(x: int, multiple: int) -> int:
    return ((x + multiple - 1) // multiple) * multiple


def _block_windows(
    ids: np.ndarray,
    perm: np.ndarray,
    num_rows: int,
    target_rows: Optional[int] = None,
) -> np.ndarray:
    """Host-side per-node-block position windows [2, n_blocks] for the
    local-window kernels: every position p with ``ids[p] // B == i``
    satisfies ``win[0, i] <= p < win[1, i]``, where B is derived from
    (num_rows, n_blocks) by the SAME formula the kernel uses
    (ops/segment_pallas.py:local_block_rows) — the block size rides
    the window shape. ``perm`` must be a stable argsort of ``ids``.
    ``target_rows`` sizes blocks to the batch's typical graph so large
    graphs don't re-scan their edge window once per 128-row block
    (docs/PERF.md r04).

    Windows are ALWAYS emitted (a data-dependent None would make the
    pytree structure vary per batch — breaking device_stack stacking
    and flapping the jit cache). Tightness, not validity, depends on
    locality: batches from :func:`batch_graphs` are graph-contiguous,
    bounding the kernel's scan at a small multiple of a sorted
    layout's; a pathologically shuffled node order degrades to
    wide windows — slower, never wrong (the one-hot match filters
    strays). The giant-graph path strips windows before GSPMD sharding
    (parallel/edge_sharded.py:place_giant_batch)."""
    from hydragnn_tpu.ops.segment_pallas import BN, local_block_rows

    t = target_rows or BN
    n_blocks = max(1, (max(num_rows, 1) + t - 1) // t)
    b_eff = local_block_rows(num_rows, n_blocks)
    lo = np.zeros(n_blocks, dtype=np.int64)
    hi = np.zeros(n_blocks, dtype=np.int64)
    if ids.size:
        sblk = ids[perm] // b_eff  # sorted ids -> sorted block ids
        starts = np.searchsorted(sblk, np.arange(n_blocks), side="left")
        ends = np.searchsorted(sblk, np.arange(n_blocks), side="right")
        ne = ends > starts
        if ne.any():
            # nonempty block segments tile the sorted array contiguously,
            # so reduceat over their starts reduces exactly [start, end)
            lo[ne] = np.minimum.reduceat(perm, starts[ne])
            hi[ne] = np.maximum.reduceat(perm, starts[ne]) + 1
    return np.stack([lo, hi]).astype(np.int32)


class BatchInvariantError(AssertionError):
    """A loader-layout contract was violated (GraphBatch.check_invariants).

    Subclasses AssertionError for caller compatibility, but is raised
    explicitly so the checks survive ``python -O`` (graftlint HG007)."""


def _invariant(cond, message: str) -> None:
    if not cond:
        raise BatchInvariantError(message)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GraphBatch:
    """A fixed-shape batch of graphs.

    Attributes:
      nodes: [N, F] node features.
      senders / receivers: [E] int32 edge endpoints (message flows
        sender -> receiver, matching PyG's edge_index[0] -> edge_index[1]).
      edge_attr: [E, De] edge features, or None.
      pos: [N, 3] node positions, or None.
      node_graph: [N] int32 graph id of each node (PyG ``batch`` vector).
      n_node / n_edge: [G] int32 per-graph counts (padding slots are 0).
      node_mask: [N] bool, True for real nodes.
      edge_mask: [E] bool, True for real edges.
      graph_mask: [G] bool, True for real graphs.
      graph_targets: {name: [G, d]} graph-level targets.
      node_targets: {name: [N, d]} node-level targets.
    """

    nodes: jnp.ndarray
    senders: jnp.ndarray
    receivers: jnp.ndarray
    node_graph: jnp.ndarray
    n_node: jnp.ndarray
    n_edge: jnp.ndarray
    node_mask: jnp.ndarray
    edge_mask: jnp.ndarray
    graph_mask: jnp.ndarray
    edge_attr: Optional[jnp.ndarray] = None
    pos: Optional[jnp.ndarray] = None
    graph_targets: Dict[str, jnp.ndarray] = dataclasses.field(default_factory=dict)
    node_targets: Dict[str, jnp.ndarray] = dataclasses.field(default_factory=dict)
    # Dense per-node edge-slot map (host-emitted, free: receivers are
    # already receiver-major sorted so node n's edges are contiguous).
    # Lets aggregations run as DENSE [N, D, H] reshape reductions — one
    # fused XLA pass forward, pure broadcasts backward — instead of
    # scatter/segment ops (XLA's TPU scatter-extremum is row-bound:
    # ~7-9 ms per pass at E=699k, docs/PERF.md r03). D is the dataset
    # max in-degree (static across batches); padding slots carry
    # mask=False and point at a padding edge/node.
    dense_senders: Optional[jnp.ndarray] = None  # [N, D] int32
    dense_mask: Optional[jnp.ndarray] = None  # [N, D] bool
    dense_edge_attr: Optional[jnp.ndarray] = None  # [N, D, De]
    # Host-precomputed edge-structure derivatives, pure functions of
    # senders/receivers. The model chassis (models/base.py:_conv_args)
    # consumes these instead of recomputing argsort/searchsorted inside
    # the jitted step every iteration — at flagship scale (E=699k) the
    # in-step sorts are serial row-bound ops worth ~ms/step (r03 trace,
    # docs/PERF.md). Batches built outside batch_graphs/pad_batch may
    # leave them None; the chassis falls back to in-jit computation.
    sender_perm: Optional[jnp.ndarray] = None  # [E] int32, stable argsort(senders)
    in_degree: Optional[jnp.ndarray] = None  # [N] f32, edge count per receiver
    dense_sender_perm: Optional[jnp.ndarray] = None  # [N*D] int32
    # Per-node-block edge-position windows for the local-window Pallas
    # kernels (ops/segment_pallas.py:segment_sum_local_pallas): every
    # edge e with senders[e] // B == i lies in [win[0,i], win[1,i]),
    # where B = local_block_rows(num_nodes, win.shape[1]) — the block
    # size is DERIVED from the window shape, identically by the emitter
    # (_block_windows) and the kernel; external producers must use the
    # same derivation. Tight for batched graphs (graph g's senders
    # live in g's contiguous node block); lets the sender-gather
    # backward scatter WITHOUT the [E, H] cotangent permute.
    # batch_graphs ALWAYS emits them (pathological id layouts just get
    # wide, slow-but-correct windows); None only for externally-built
    # batches and the GSPMD-sharded giant-graph path, which strips
    # them.
    sender_win: Optional[jnp.ndarray] = None  # [2, n_blocks] int32
    dense_sender_win: Optional[jnp.ndarray] = None  # [2, n_blocks] int32
    # Edge OCCUPANCY: scalar int32 — the index AFTER the last edge slot
    # that can carry a real (unmasked) edge. Everything at position >=
    # edge_occupancy is pure padding (the batch_graphs sentinel tail;
    # run_align keeps its masked self-loops interleaved BELOW this
    # bound, so the bound is int(adeg.sum()) there, tot_edges otherwise;
    # _mask_out filler batches advertise 0). The fused conv kernel
    # clamps its chunk loop at ceil(edge_occupancy / CE), so tail
    # padding costs zero DMAs and zero MXU work — device cost scales
    # with real edges, not the pad plan (ISSUE 10). Carried as a scalar
    # ARRAY (not static) so bucket-ladder batches with different
    # occupancies share one jit cache entry and device_stack stacking
    # works. None on externally-built batches — consumers then process
    # the full pad (slower, never wrong).
    edge_occupancy: Optional[jnp.ndarray] = None  # [] int32
    # Real (unmasked) node count, for pad-waste accounting in the
    # bench/ledger layers (obs/introspect.py, bench.py). None on
    # externally-built batches.
    n_real_nodes: Optional[jnp.ndarray] = None  # [] int32
    # STATIC (pytree meta): run-aligned edge layout factor. When K > 0,
    # every node's receiver-run is padded to a multiple of K with MASKED
    # self-loop edges (sender = receiver = the node), so every K-group
    # of edge slots lies within one node's run (or the batch tail) and
    # segment reductions can PRE-REDUCE each group with one fused
    # elementwise pass — shrinking the serial scatter/segment work K-fold
    # (XLA's TPU scatter loops per ROW; docs/PERF.md r03/r04). Downstream
    # contracts that change under K > 0: masked edges may target REAL
    # nodes (always as self-loops), so consumers MUST apply edge_mask —
    # all in-tree convs do; in_degree counts REAL edges only (either way).
    run_align: int = dataclasses.field(default=0, metadata=dict(static=True))

    @property
    def num_nodes(self) -> int:
        return self.nodes.shape[0]

    @property
    def num_edges(self) -> int:
        return self.senders.shape[0]

    @property
    def num_graphs(self) -> int:
        return self.n_node.shape[0]

    def replace(self, **kwargs) -> "GraphBatch":
        return dataclasses.replace(self, **kwargs)

    def check_invariants(self) -> None:
        """Validate the loader contracts the model chassis SILENTLY
        relies on (r03 advisor): raises :class:`BatchInvariantError`
        (an AssertionError subclass) with a named violation. Host-side debug helper — call it on batches built
        outside :func:`batch_graphs`/:func:`pad_batch` (which maintain
        these by construction); never inside jit.

          1. receivers sorted ascending (segment reductions pass
             indices_are_sorted=True — a violated hint silently corrupts
             sums on TPU);
          2. every masked edge targets a padding node (the degree
             shortcut counts edges without consulting the mask);
          3. sender_perm is a stable argsort of senders, in_degree
             matches the receiver bincount, and the block windows cover
             every edge position of their id block.
        """
        import numpy as np_

        recv = np_.asarray(self.receivers)
        send = np_.asarray(self.senders)
        emask = np_.asarray(self.edge_mask)
        nmask = np_.asarray(self.node_mask)
        _invariant(
            np_.all(recv[:-1] <= recv[1:]), "receivers not sorted ascending"
        )
        masked_idx = np_.flatnonzero(~emask)
        if masked_idx.size:
            to_real = nmask[recv[masked_idx]]
            if self.run_align:
                # run-aligned layout: masked edges at real nodes must be
                # SELF-LOOPS (they then cannot corrupt any masked
                # aggregation, and sender locality is preserved)
                bad = to_real & (send[masked_idx] != recv[masked_idx])
                _invariant(
                    not bad.any(),
                    "masked edge targets a real node without being a "
                    "self-loop (run_align contract)",
                )
            else:
                _invariant(
                    not to_real.any(),
                    "masked edge targets a REAL node (degree shortcut + "
                    "dense map assume padding edges only ever point at "
                    "padding nodes)",
                )
        if self.edge_occupancy is not None:
            occ = int(np_.asarray(self.edge_occupancy))
            real_pos = np_.flatnonzero(emask)
            _invariant(
                not real_pos.size or int(real_pos.max()) < occ,
                "unmasked edge at position >= edge_occupancy (the fused "
                "kernel skips all chunks past the occupancy bound)",
            )
            _invariant(
                int(np_.asarray(self.n_real_nodes)) == int(nmask.sum()),
                "n_real_nodes != node_mask.sum()",
            )
        if self.sender_perm is not None:
            sp = np_.asarray(self.sender_perm)
            _invariant(
                np_.all(send[sp][:-1] <= send[sp][1:]),
                "sender_perm does not sort senders",
            )
        if self.in_degree is not None:
            deg = np_.asarray(self.in_degree)
            real = recv[emask]
            ref = np_.bincount(real, minlength=real.max() + 1 if real.size else 0)
            _invariant(
                np_.array_equal(deg[: ref.shape[0]], ref)
                and not deg[ref.shape[0]:].any(),
                "in_degree != bincount(real receivers)",
            )
        for ids, win, label in (
            (send, self.sender_win, "sender_win"),
            (
                None
                if self.dense_senders is None
                else np_.asarray(self.dense_senders).reshape(-1),
                self.dense_sender_win,
                "dense_sender_win",
            ),
        ):
            if win is None or ids is None:
                continue
            from hydragnn_tpu.ops.segment_pallas import local_block_rows

            w = np_.asarray(win)
            b_eff = local_block_rows(self.num_nodes, w.shape[1])
            blk = ids // b_eff
            pos = np_.arange(ids.shape[0])
            lo, hi = w[0][blk], w[1][blk]
            _invariant(
                np_.all((pos >= lo) & (pos < hi)),
                f"{label} does not cover every position of its id block",
            )


def batch_graphs(
    graphs: Sequence[Dict[str, Any]],
    n_node_pad: Optional[int] = None,
    n_edge_pad: Optional[int] = None,
    n_graph_pad: Optional[int] = None,
    node_multiple: int = 16,
    edge_multiple: int = 8,
    dense_slots: Optional[int] = None,
    run_align: int = 0,
    win_block_rows: Optional[int] = None,
) -> GraphBatch:
    """Concatenate a list of single graphs and pad to static shapes.

    Each graph is a dict with keys ``x`` [n, F], ``senders``/``receivers``
    [e] (or ``edge_index`` [2, e]), optional ``edge_attr``, ``pos``,
    ``graph_targets`` {name: [d]}, ``node_targets`` {name: [n, d]}.
    All numpy; this runs on host in the input pipeline.

    ``run_align=K`` (K > 1) emits the run-aligned edge layout: each
    node's receiver-run padded to a multiple of K with masked self-loop
    edges (see GraphBatch.run_align). Mutually exclusive with
    ``dense_slots`` — they are alternative answers to the same
    scatter-cost problem, dense for tight degree distributions,
    run-align for wide ones.
    """
    if not graphs:
        raise ValueError("graphs must be non-empty")
    n_graphs = len(graphs)
    tot_nodes = sum(int(np.asarray(g["x"]).shape[0]) for g in graphs)
    tot_edges = sum(_num_edges(g) for g in graphs)

    # Field presence must be homogeneous — a silently dropped optional field
    # is worse than an error here.
    for key in ("edge_attr", "pos"):
        present = [g.get(key) is not None for g in graphs]
        if any(present) and not all(present):
            raise ValueError(f"field '{key}' present on some graphs but not others")
    gt_names = sorted(graphs[0].get("graph_targets", {}).keys())
    nt_names = sorted(graphs[0].get("node_targets", {}).keys())
    for g in graphs:
        if sorted(g.get("graph_targets", {}).keys()) != gt_names:
            raise ValueError("graph_targets keys differ across graphs")
        if sorted(g.get("node_targets", {}).keys()) != nt_names:
            raise ValueError("node_targets keys differ across graphs")

    # One extra padding graph absorbs padding nodes/edges; at least one
    # padding node/edge must exist for them to point at. node_multiple
    # defaults to 16 = ops.segment_pallas.ALIGN so the CSR-broadcast
    # kernel never re-pads (copies) the node table per call.
    if n_graph_pad is None:
        n_graph_pad = n_graphs + 1
    if n_node_pad is None:
        n_node_pad = _round_up(tot_nodes + 1, node_multiple)
    if n_edge_pad is None:
        n_edge_pad = max(_round_up(tot_edges + 1, edge_multiple), 1)
    if n_graph_pad <= n_graphs:
        raise ValueError(
            f"n_graph_pad={n_graph_pad} must exceed num real graphs {n_graphs} "
            "(one slot is reserved for the padding graph)"
        )
    # Padding edges only need a padding *node* to point at, so an exact-fit
    # edge capacity is fine; the node side must strictly exceed.
    if n_node_pad <= tot_nodes or n_edge_pad < tot_edges:
        raise ValueError(
            f"padded sizes (nodes {n_node_pad}, edges {n_edge_pad}) too small "
            f"for real totals (nodes {tot_nodes}, edges {tot_edges})"
        )

    feat_dim = _as_2d(graphs[0]["x"]).shape[1]
    nodes = np.zeros((n_node_pad, feat_dim), dtype=np.float32)
    senders = np.full((n_edge_pad,), tot_nodes, dtype=np.int32)
    receivers = np.full((n_edge_pad,), tot_nodes, dtype=np.int32)
    node_graph = np.full((n_node_pad,), n_graphs, dtype=np.int32)
    n_node = np.zeros((n_graph_pad,), dtype=np.int32)
    n_edge = np.zeros((n_graph_pad,), dtype=np.int32)
    node_mask = np.zeros((n_node_pad,), dtype=bool)
    edge_mask = np.zeros((n_edge_pad,), dtype=bool)
    graph_mask = np.zeros((n_graph_pad,), dtype=bool)

    has_edge_attr = graphs[0].get("edge_attr") is not None
    has_pos = graphs[0].get("pos") is not None
    edge_attr = None
    pos = None
    if has_edge_attr:
        de = _as_2d(graphs[0]["edge_attr"]).shape[1]
        edge_attr = np.zeros((n_edge_pad, de), dtype=np.float32)
    if has_pos:
        pos = np.zeros((n_node_pad, np.asarray(graphs[0]["pos"]).shape[-1]), dtype=np.float32)

    g_targets: Dict[str, list] = {}
    n_targets: Dict[str, Any] = {}
    for name in nt_names:
        d = _as_2d(graphs[0]["node_targets"][name]).shape[1]
        n_targets[name] = np.zeros((n_node_pad, d), dtype=np.float32)

    node_off, edge_off = 0, 0
    for gi, g in enumerate(graphs):
        x = _as_2d(g["x"])
        n, e = x.shape[0], _num_edges(g)
        s, r = _edge_endpoints(g)
        nodes[node_off : node_off + n] = x
        senders[edge_off : edge_off + e] = s + node_off
        receivers[edge_off : edge_off + e] = r + node_off
        node_graph[node_off : node_off + n] = gi
        n_node[gi], n_edge[gi] = n, e
        node_mask[node_off : node_off + n] = True
        edge_mask[edge_off : edge_off + e] = True
        graph_mask[gi] = True
        if has_edge_attr:
            edge_attr[edge_off : edge_off + e] = _as_2d(g["edge_attr"])
        if has_pos:
            pos[node_off : node_off + n] = np.asarray(g["pos"], dtype=np.float32)
        for name in gt_names:
            g_targets.setdefault(name, []).append(
                np.asarray(g["graph_targets"][name], dtype=np.float32).reshape(-1)
            )
        for name in nt_names:
            n_targets[name][node_off : node_off + n] = _as_2d(g["node_targets"][name])
        node_off += n
        edge_off += e

    graph_targets = {}
    for name, rows in g_targets.items():
        d = rows[0].shape[0]
        arr = np.zeros((n_graph_pad, d), dtype=np.float32)
        arr[:n_graphs] = np.stack(rows)
        graph_targets[name] = arr

    # Canonical RECEIVER-MAJOR edge order: segment reductions may then
    # assume indices_are_sorted (better XLA lowering; enables the Pallas
    # CSR family kernel on TPU) regardless of the featurizer's emission
    # order (the radius pipeline is already receiver-sorted; SMILES is
    # sender-major). Stable sort; padding receivers (= tot_nodes
    # sentinel) stay at the tail. Aggregation is order-invariant, so
    # results are unchanged.
    if not np.all(receivers[:-1] <= receivers[1:]):
        perm = np.argsort(receivers, kind="stable")
        senders = senders[perm]
        receivers = receivers[perm]
        edge_mask = edge_mask[perm]
        if has_edge_attr:
            edge_attr = edge_attr[perm]

    # Index after the last slot that can hold a real edge (see
    # GraphBatch.edge_occupancy). Receiver-major sort puts the sentinel
    # tail last, so this is tot_edges here; the run_align relayout
    # interleaves its masked self-loops below int(adeg.sum()) and
    # overwrites it below.
    edge_occ = tot_edges

    if run_align and run_align > 1:
        if dense_slots:
            raise ValueError("run_align and dense_slots are mutually exclusive")
        K = int(run_align)
        if n_edge_pad % K:
            raise ValueError(f"n_edge_pad={n_edge_pad} not a multiple of run_align={K}")
        # Real edges occupy [0, tot_edges): real receivers < tot_nodes
        # strictly, padding receivers == tot_nodes, and the sort is
        # receiver-major. Re-lay runs on K-aligned starts; pad slots are
        # masked SELF-LOOPS at their node (receivers stay sorted, sender
        # locality preserved, and a self-loop cannot corrupt any masked
        # aggregation). The tail keeps the padding-node sentinel.
        deg = np.bincount(receivers[:tot_edges], minlength=n_node_pad)
        adeg = ((deg + K - 1) // K) * K * (deg > 0)
        total = int(adeg.sum())
        if total > n_edge_pad:
            raise ValueError(
                f"run_align={K} needs {total} edge slots > n_edge_pad={n_edge_pad}; "
                "size the pad from the ALIGNED per-sample counts "
                "(data/loader.py:_aligned_edge_counts — GraphLoader does "
                "this automatically)"
            )
        rs = np.zeros(n_node_pad + 1, dtype=np.int64)
        rs[1:] = np.cumsum(adeg)
        row_ptr = np.zeros(n_node_pad + 1, dtype=np.int64)
        row_ptr[1:] = np.cumsum(deg)
        r = receivers[:tot_edges]
        new_pos = rs[r] + (np.arange(tot_edges) - row_ptr[r])
        fill = np.repeat(np.arange(n_node_pad, dtype=np.int32), adeg)
        new_recv = np.full(n_edge_pad, tot_nodes, dtype=np.int32)
        new_recv[:total] = fill
        new_send = new_recv.copy()
        new_mask = np.zeros(n_edge_pad, dtype=bool)
        new_send[new_pos] = senders[:tot_edges]
        new_mask[new_pos] = True
        if has_edge_attr:
            new_ea = np.zeros_like(edge_attr)
            new_ea[new_pos] = edge_attr[:tot_edges]
            edge_attr = new_ea
        senders, receivers, edge_mask = new_send, new_recv, new_mask
        edge_occ = total

    dense_senders = dense_mask = dense_edge_attr = dense_sender_perm = None
    if dense_slots is not None and dense_slots > 0:
        # receiver-major sorted + only padding edges masked (targeting a
        # padding node), so node n's real edges occupy the contiguous
        # range [row_ptr[n], row_ptr[n] + deg[n])
        deg = np.bincount(receivers[edge_mask], minlength=n_node_pad)
        dmax = int(deg.max(initial=0))
        if dmax > dense_slots:
            raise ValueError(
                f"dense_slots={dense_slots} < batch max in-degree {dmax}"
            )
        row_ptr = np.zeros(n_node_pad, dtype=np.int64)
        row_ptr[1:] = np.cumsum(deg)[:-1]
        slot = np.arange(dense_slots, dtype=np.int64)[None, :]
        dense_mask = slot < deg[:, None]
        # host-side slot->edge positions (a local temporary: consumers
        # only ever need the gathered senders / edge features)
        dense_edge_pos = np.where(
            dense_mask, row_ptr[:, None] + slot, n_edge_pad - 1
        ).astype(np.int32)
        dense_senders = senders[dense_edge_pos]
        if has_edge_attr:
            dense_edge_attr = edge_attr[dense_edge_pos]
        dense_sender_perm = np.argsort(
            dense_senders.reshape(-1), kind="stable"
        ).astype(np.int32)

    # Stable argsort matches jnp.argsort's tie-breaking, so the sorted
    # segment-sum reduction order (hence bf16 numerics) is identical to
    # the previous in-jit computation.
    sender_perm = np.argsort(senders, kind="stable").astype(np.int32)
    # Counts REAL edges per receiver. Real-node values match
    # models/convs.py:sorted_in_degree (masked edges never target a real
    # node except as run_align self-loop padding, excluded here by the
    # mask); padding-node rows are 0 rather than the masked-tail count —
    # strictly cleaner for every consumer (PNA has-gate, MFC dispatch).
    in_degree = np.bincount(
        receivers[edge_mask], minlength=n_node_pad
    ).astype(np.float32)
    # ``win_block_rows`` must be BATCH-INDEPENDENT for a fixed pad plan
    # (the loader derives it once from dataset-wide stats): window
    # shapes are part of the pytree structure, and a per-batch
    # data-dependent target would break device_stack stacking and flap
    # the jit cache. Default BN keeps standalone callers stable.
    sender_win = _block_windows(senders, sender_perm, n_node_pad, win_block_rows)
    dense_sender_win = (
        _block_windows(
            dense_senders.reshape(-1), dense_sender_perm, n_node_pad, win_block_rows
        )
        if dense_sender_perm is not None
        else None
    )

    return GraphBatch(
        nodes=jnp.asarray(nodes),
        senders=jnp.asarray(senders),
        receivers=jnp.asarray(receivers),
        node_graph=jnp.asarray(node_graph),
        n_node=jnp.asarray(n_node),
        n_edge=jnp.asarray(n_edge),
        node_mask=jnp.asarray(node_mask),
        edge_mask=jnp.asarray(edge_mask),
        graph_mask=jnp.asarray(graph_mask),
        edge_attr=jnp.asarray(edge_attr) if edge_attr is not None else None,
        pos=jnp.asarray(pos) if pos is not None else None,
        graph_targets={k: jnp.asarray(v) for k, v in graph_targets.items()},
        node_targets={k: jnp.asarray(v) for k, v in n_targets.items()},
        dense_senders=jnp.asarray(dense_senders) if dense_senders is not None else None,
        dense_mask=jnp.asarray(dense_mask) if dense_mask is not None else None,
        dense_edge_attr=jnp.asarray(dense_edge_attr) if dense_edge_attr is not None else None,
        sender_perm=jnp.asarray(sender_perm),
        in_degree=jnp.asarray(in_degree),
        dense_sender_perm=(
            jnp.asarray(dense_sender_perm) if dense_sender_perm is not None else None
        ),
        sender_win=jnp.asarray(sender_win) if sender_win is not None else None,
        dense_sender_win=(
            jnp.asarray(dense_sender_win) if dense_sender_win is not None else None
        ),
        edge_occupancy=jnp.asarray(np.int32(edge_occ)),
        n_real_nodes=jnp.asarray(np.int32(tot_nodes)),
        run_align=int(run_align) if run_align and run_align > 1 else 0,
    )


def pad_batch(batch: GraphBatch, n_node: int, n_edge: int, n_graph: int) -> GraphBatch:
    """Pad an existing GraphBatch up to larger static shapes."""
    dn = n_node - batch.num_nodes
    de = n_edge - batch.num_edges
    dg = n_graph - batch.num_graphs
    if dn < 0 or de < 0 or dg < 0:
        raise ValueError("target shape smaller than current batch")
    if batch.run_align and n_edge % batch.run_align:
        raise ValueError(
            f"n_edge={n_edge} must stay a multiple of run_align="
            f"{batch.run_align} (the model reshapes edges into K-groups)"
        )
    if dn == de == dg == 0:
        return batch

    def pad0(a, amount, value=0):
        if a is None:
            return None
        widths = [(0, amount)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=value)

    # New padding nodes/edges must point at a *padding* slot. If this
    # dimension grows, the first new slot is one; otherwise reuse the
    # existing padding slot at the end (batch_graphs always reserves one).
    if dg > 0:
        pad_graph_id = batch.num_graphs
    else:
        if bool(batch.graph_mask[-1]):
            raise ValueError("cannot pad nodes: batch has no padding graph slot")
        pad_graph_id = batch.num_graphs - 1
    if dn > 0:
        pad_node_id = batch.num_nodes
    else:
        if bool(batch.node_mask[-1]):
            raise ValueError("cannot pad edges: batch has no padding node slot")
        pad_node_id = batch.num_nodes - 1
    # Precomputed edge-structure derivatives extend without a re-sort:
    # appended padding edges sit at the tail with sender/receiver value
    # pad_node_id >= every existing value (real ids < tot_nodes <=
    # pad_node_id), and stable argsort tie-breaks old-index-first — so
    # the stable argsort of the padded array is exactly
    # concat(old_perm, arange(old_E, new_E)). in_degree only gains the
    # de new edges, all targeting pad_node_id (a padding slot).
    sender_perm = batch.sender_perm
    if sender_perm is not None:
        sender_perm = jnp.concatenate(
            [sender_perm, jnp.arange(batch.num_edges, n_edge, dtype=sender_perm.dtype)]
        )
    # in_degree counts REAL edges only; appended padding edges are
    # masked, so only zero-extension is needed
    in_degree = batch.in_degree
    if in_degree is not None:
        in_degree = pad0(in_degree, dn)
    dense_sender_perm = batch.dense_sender_perm
    if dense_sender_perm is not None and batch.dense_senders is not None:
        old_flat = batch.dense_senders.size
        new_flat = old_flat + dn * batch.dense_senders.shape[1]
        dense_sender_perm = jnp.concatenate(
            [
                dense_sender_perm,
                jnp.arange(old_flat, new_flat, dtype=dense_sender_perm.dtype),
            ]
        )

    def _extend_win(win, n_appended, old_len, new_len):
        """dn == 0: block boundaries are unchanged (the kernel derives
        the block size from (num_segments, n_blocks), both fixed), so
        only the pad-node block's window widens to cover the appended
        tail positions. dn > 0 changes the derived block size —
        callers rebuild windows on host instead (below)."""
        if win is None:
            return None
        from hydragnn_tpu.ops.segment_pallas import local_block_rows

        if n_appended <= 0:
            return win
        b_eff = local_block_rows(batch.num_nodes, win.shape[1])
        b = pad_node_id // b_eff
        empty = win[0, b] == win[1, b]
        lo = jnp.where(empty, old_len, jnp.minimum(win[0, b], old_len))
        win = win.at[0, b].set(lo.astype(win.dtype))
        return win.at[1, b].set(new_len)

    if dn > 0 and (batch.sender_win is not None or batch.dense_sender_win is not None):
        # growing the node axis changes the derived block size; rebuild
        # the plans on host, PRESERVING the original block granularity
        # (derived back from the old window shape). pad_batch with
        # node growth therefore requires concrete (host) arrays —
        # strip the windows first to pad under a trace (the GSPMD
        # giant path already does).
        import numpy as _np

        from hydragnn_tpu.ops.segment_pallas import local_block_rows

        if isinstance(batch.senders, jax.core.Tracer):
            raise ValueError(
                "pad_batch cannot grow the node axis of a TRACED batch "
                "carrying window plans (the block size must be re-derived "
                "on host); replace(sender_win=None, dense_sender_win=None) "
                "before padding under jit/vmap"
            )
        if batch.sender_win is not None and sender_perm is not None:
            target = local_block_rows(batch.num_nodes, batch.sender_win.shape[1])
            sender_win = jnp.asarray(
                _block_windows(
                    _np.asarray(pad0(batch.senders, de, pad_node_id)),
                    _np.asarray(sender_perm),
                    n_node,
                    target,
                )
            )
        else:
            # a window without its perm (exotic external batch): the
            # consumers' fallback chain handles a None window correctly
            sender_win = None
        if (
            batch.dense_sender_win is not None
            and batch.dense_senders is not None
            and dense_sender_perm is not None
        ):
            target = local_block_rows(
                batch.num_nodes, batch.dense_sender_win.shape[1]
            )
            new_dense = _np.asarray(
                pad0(batch.dense_senders, dn, pad_node_id)
            ).reshape(-1)
            dense_sender_win = jnp.asarray(
                _block_windows(new_dense, _np.asarray(dense_sender_perm), n_node, target)
            )
        else:
            dense_sender_win = None
    else:
        sender_win = _extend_win(batch.sender_win, de, batch.num_edges, n_edge)
        dense_sender_win = batch.dense_sender_win
        if dense_sender_win is not None and batch.dense_senders is not None:
            dense_sender_win = _extend_win(
                dense_sender_win,
                dn * batch.dense_senders.shape[1],
                batch.dense_senders.size,
                batch.dense_senders.size + dn * batch.dense_senders.shape[1],
            )
    return batch.replace(
        nodes=pad0(batch.nodes, dn),
        senders=pad0(batch.senders, de, pad_node_id),
        receivers=pad0(batch.receivers, de, pad_node_id),
        node_graph=pad0(batch.node_graph, dn, pad_graph_id),
        n_node=pad0(batch.n_node, dg),
        n_edge=pad0(batch.n_edge, dg),
        node_mask=pad0(batch.node_mask, dn, False),
        edge_mask=pad0(batch.edge_mask, de, False),
        graph_mask=pad0(batch.graph_mask, dg, False),
        edge_attr=pad0(batch.edge_attr, de),
        pos=pad0(batch.pos, dn),
        graph_targets={k: pad0(v, dg) for k, v in batch.graph_targets.items()},
        node_targets={k: pad0(v, dn) for k, v in batch.node_targets.items()},
        # new dense rows are all-padding slots: mask False, senders at a
        # padding node, positions at the (old) last edge slot
        dense_senders=pad0(batch.dense_senders, dn, pad_node_id),
        dense_mask=pad0(batch.dense_mask, dn, False),
        dense_edge_attr=pad0(batch.dense_edge_attr, dn),
        sender_perm=sender_perm,
        in_degree=in_degree,
        dense_sender_perm=dense_sender_perm,
        sender_win=sender_win,
        dense_sender_win=dense_sender_win,
    )


def _as_2d(a) -> np.ndarray:
    a = np.asarray(a, dtype=np.float32)
    return a[:, None] if a.ndim == 1 else a


def _num_edges(g: Dict[str, Any]) -> int:
    if "senders" in g:
        return int(np.asarray(g["senders"]).shape[0])
    return int(np.asarray(g["edge_index"]).shape[1])


def _edge_endpoints(g: Dict[str, Any]):
    if "senders" in g:
        return np.asarray(g["senders"]), np.asarray(g["receivers"])
    ei = np.asarray(g["edge_index"])
    return ei[0], ei[1]
