"""Masked segment ops — the TPU-native replacement for torch-scatter.

Every message-passing layer in the reference aggregates edge messages with
torch-scatter kernels (reference: requirements-torchdep.txt:2-4, used inside
every torch_geometric conv). On TPU the idiomatic equivalent is XLA's
``segment_*`` family: a sorted/unsorted segment reduction that XLA lowers to
one-hot matmuls or sorted scans on the MXU/VPU. All ops here are:

  - static-shape friendly (``num_segments`` is a Python int, jit-safe),
  - mask-aware: padding edges (mask=False) contribute the reduction identity,
  - safe on empty segments (mean returns 0, max/min return 0 rather than
    +/-inf so padded graph slots never poison downstream arithmetic).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _expand_mask(mask: Optional[jnp.ndarray], data: jnp.ndarray) -> Optional[jnp.ndarray]:
    if mask is None:
        return None
    while mask.ndim < data.ndim:
        mask = mask[..., None]
    return mask


def segment_sum(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    m = _expand_mask(mask, data)
    if m is not None:
        data = jnp.where(m, data, 0)
    return jax.ops.segment_sum(
        data, segment_ids, num_segments, indices_are_sorted=indices_are_sorted
    )


def segment_count(
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    ones = jnp.ones(segment_ids.shape[0], dtype=jnp.float32)
    if mask is not None:
        ones = jnp.where(mask, ones, 0.0)
    return jax.ops.segment_sum(
        ones, segment_ids, num_segments, indices_are_sorted=indices_are_sorted
    )


def segment_mean(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    total = segment_sum(data, segment_ids, num_segments, mask, indices_are_sorted)
    count = segment_count(segment_ids, num_segments, mask, indices_are_sorted)
    count = _expand_mask(count, total)
    return total / jnp.maximum(count, 1.0)


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _segment_extremum(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    indices_are_sorted: bool,
    is_max: bool,
) -> jnp.ndarray:
    """Segment max/min with a FAST custom gradient.

    XLA's native VJP for segment max/min lowers to a slow scatter on TPU
    (measured ~3.1 ms backward for E=120k, H=128 on v5e — ~5x the
    forward); since PNA takes min AND max per conv layer, that VJP
    dominated the whole train step. The custom backward reroutes the
    cotangent through gathers: grad flows to the tied extrema of each
    segment, split evenly (jax's own segment_max convention), costing one
    segment_sum + two gathers instead of the scatter.
    """
    raw_op = jax.ops.segment_max if is_max else jax.ops.segment_min
    return raw_op(
        data, segment_ids, num_segments, indices_are_sorted=indices_are_sorted
    )


def _segment_extremum_fwd(data, segment_ids, num_segments, indices_are_sorted, is_max):
    out = _segment_extremum(
        data, segment_ids, num_segments, indices_are_sorted, is_max
    )
    return out, (data, segment_ids, out)


def _segment_extremum_bwd(num_segments, indices_are_sorted, is_max, res, g):
    from hydragnn_tpu.ops.segment_pallas import segment_sum_fast

    data, segment_ids, out = res
    # CSR-broadcast kernel for sorted ids: XLA's [N,H]->[E,H] row gather
    # is the r03 trace's dominant backward cost (docs/PERF.md)
    sel = data == _gather_fwd_impl(out, segment_ids, indices_are_sorted)
    # tie count: a full-width segment sum — the Pallas CSR kernel when
    # ids are sorted on TPU (this is a backward hot path: PNA pays it
    # every layer). The 0/1 tie mask travels in the DATA dtype (half
    # HBM bytes under bf16 — 0/1 are exact in bf16), while the
    # ACCUMULATION is >= f32 by segment_sum_fast's contract, so counts
    # above 256 stay exact; count and share math stays f32 (bf16 cannot
    # represent large counts, mis-splitting heavily-tied segments).
    cnt = segment_sum_fast(
        sel.astype(data.dtype),
        segment_ids,
        num_segments,
        indices_are_sorted=indices_are_sorted,
    ).astype(jnp.float32)
    share = g.astype(jnp.float32) / jnp.maximum(cnt, 1.0)
    # cast BEFORE the [E, H]-widening gather: halves the gather's HBM
    # write under bf16; the final cotangent is data.dtype anyway
    share = share.astype(data.dtype)
    grad = jnp.where(sel, _gather_fwd_impl(share, segment_ids, indices_are_sorted), 0)
    ids_zero = jnp.zeros(segment_ids.shape, dtype=jax.dtypes.float0)
    return grad, ids_zero


_segment_extremum.defvjp(_segment_extremum_fwd, _segment_extremum_bwd)


def segment_max(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
    empty_value: float = 0.0,
) -> jnp.ndarray:
    m = _expand_mask(mask, data)
    neg = jnp.finfo(data.dtype).min
    if m is not None:
        data = jnp.where(m, data, neg)
    out = _segment_extremum(
        data, segment_ids, num_segments, indices_are_sorted, is_max=True
    )
    return jnp.where(out <= neg, empty_value, out)


def segment_min(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
    empty_value: float = 0.0,
) -> jnp.ndarray:
    m = _expand_mask(mask, data)
    pos = jnp.finfo(data.dtype).max
    if m is not None:
        data = jnp.where(m, data, pos)
    out = _segment_extremum(
        data, segment_ids, num_segments, indices_are_sorted, is_max=False
    )
    return jnp.where(out >= pos, empty_value, out)


def segment_std(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
    eps: float = 1e-5,
) -> jnp.ndarray:
    """Per-segment standard deviation (biased, matching PyG's PNA ``std``).

    PyG computes std = sqrt(relu(mean(x^2) - mean(x)^2) + eps) — we mirror
    that so PNA parity holds (reference: torch_geometric aggr 'std' used by
    hydragnn/models/PNAStack.py:27).
    """
    mean = segment_mean(data, segment_ids, num_segments, mask, indices_are_sorted)
    mean_sq = segment_mean(data * data, segment_ids, num_segments, mask, indices_are_sorted)
    var = jax.nn.relu(mean_sq - mean * mean)
    return jnp.sqrt(var + eps)


def segment_softmax(
    logits: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    """Numerically-stable softmax within each segment (GAT attention).

    Padding entries (mask=False) get probability 0.
    """
    m = _expand_mask(mask, logits)
    neg = jnp.finfo(logits.dtype).min
    masked_logits = logits if m is None else jnp.where(m, logits, neg)
    # max-shift under stop_gradient: its softmax gradient contribution
    # cancels mathematically, and XLA's segment_max VJP is a slow TPU
    # scatter (see _segment_extremum) — standard logsumexp practice.
    seg_max = jax.ops.segment_max(
        jax.lax.stop_gradient(masked_logits),
        segment_ids,
        num_segments,
        indices_are_sorted=indices_are_sorted,
    )
    seg_max = jnp.where(seg_max <= neg, 0.0, seg_max)
    shifted = masked_logits - seg_max[segment_ids]
    exp = jnp.exp(shifted)
    if m is not None:
        exp = jnp.where(m, exp, 0.0)
    denom = jax.ops.segment_sum(
        exp, segment_ids, num_segments, indices_are_sorted=indices_are_sorted
    )
    return exp / jnp.maximum(denom[segment_ids], 1e-16)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def segment_sum_sorted(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    grad_dtype=None,
) -> jnp.ndarray:
    """Differentiable segment sum for SORTED ids on the fast kernel
    path: forward = the Pallas CSR sum kernel (XLA fallback off-TPU),
    backward = the CSR-broadcast row gather ``g[ids]``. The f32
    accumulation contract of :func:`segment_sum_fast` applies. Built
    for the run-aligned pre-reduced aggregations
    (models/convs.py:_run_presum), whose forward use needs AD — the
    raw kernel dispatchers are VJP-internal and not differentiated.

    ``grad_dtype``: dtype the backward's widening gather travels in
    (same bandwidth contract as the unaligned family VJP, whose
    cotangent gathers ride the compute dtype — docs/PERF.md r03). The
    run-aligned callers pre-reduce in f32 for exact accumulation but
    consume the gradient in the compute dtype anyway; without this the
    cotangent gather runs the f32 3-term-split kernel at 6x the cost
    (r05 trace: 1.50 vs 0.26 ms per layer at E/8 x 2H). None keeps the
    cotangent dtype."""
    from hydragnn_tpu.ops.segment_pallas import segment_sum_fast

    return segment_sum_fast(
        data, segment_ids, num_segments, indices_are_sorted=True
    ).astype(data.dtype)


def _segment_sum_sorted_fwd(data, segment_ids, num_segments, grad_dtype):
    return (
        segment_sum_sorted(data, segment_ids, num_segments, grad_dtype),
        segment_ids,
    )


def _segment_sum_sorted_bwd(num_segments, grad_dtype, ids, g):
    gd = g if grad_dtype is None else g.astype(grad_dtype)
    grad = _gather_fwd_impl(gd, ids, indices_are_sorted=True).astype(g.dtype)
    return grad, jnp.zeros(ids.shape, dtype=jax.dtypes.float0)


segment_sum_sorted.defvjp(_segment_sum_sorted_fwd, _segment_sum_sorted_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def gather_rows(
    x: jnp.ndarray,
    ids: jnp.ndarray,
    num_rows: int,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    """``x[ids]`` with a segment-sum backward that can exploit
    sortedness: the VJP of a plain gather is a scatter-add XLA performs
    without an ordering hint; routing it through
    :func:`hydragnn_tpu.ops.segment_pallas.segment_sum_fast` uses the
    Pallas CSR kernel on TPU for sorted ids (the per-layer
    receiver-gather backward in every conv). The forward itself also
    takes the CSR-broadcast kernel for sorted ids (XLA's row gather
    loops serially on TPU — docs/PERF.md r03 trace)."""
    return _gather_fwd_impl(x, ids, indices_are_sorted)


def _gather_fwd_impl(x, ids, indices_are_sorted):
    if indices_are_sorted and x.ndim == 2:
        from hydragnn_tpu.ops.segment_pallas import gather_rows_sorted_fast

        return gather_rows_sorted_fast(x, ids)
    return x[ids]


def _gather_rows_fwd(x, ids, num_rows, indices_are_sorted):
    return _gather_fwd_impl(x, ids, indices_are_sorted), ids


def _gather_rows_bwd(num_rows, indices_are_sorted, ids, g):
    from hydragnn_tpu.ops.segment_pallas import segment_sum_fast

    grad = segment_sum_fast(
        g, ids, num_rows, indices_are_sorted=indices_are_sorted
    ).astype(g.dtype)
    return grad, jnp.zeros(ids.shape, dtype=jax.dtypes.float0)


gather_rows.defvjp(_gather_rows_fwd, _gather_rows_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def gather_rows_permuted(
    x: jnp.ndarray,
    ids: jnp.ndarray,
    perm: jnp.ndarray,
    num_rows: int,
) -> jnp.ndarray:
    """``x[ids]`` for UNSORTED ids with a sorted-segment-sum backward:
    ``perm`` must sort ``ids`` ascending (``perm = argsort(ids)``,
    computed once per step by the chassis and reused by every layer).
    The VJP permutes the cotangent into sorted order and reduces with
    the sorted/Pallas segment sum — XLA's unsorted scatter-add costs
    ~1.1 ms at [E=120k, H=128] on v5e vs ~0.5 ms this way."""
    return x[ids]


def _gather_rows_permuted_fwd(x, ids, perm, num_rows):
    return x[ids], (ids, perm)


def _gather_rows_permuted_bwd(num_rows, res, g):
    from hydragnn_tpu.ops.segment_pallas import segment_sum_fast

    ids, perm = res
    # ids[perm] == sort(ids) by the perm contract — jnp.sort costs
    # ~0.9 ms at E=699k where the int row gather costs ~5 ms (r03 trace)
    grad = segment_sum_fast(
        g[perm], jnp.sort(ids), num_rows, indices_are_sorted=True
    ).astype(g.dtype)
    f0 = jax.dtypes.float0
    return grad, jnp.zeros(ids.shape, dtype=f0), jnp.zeros(perm.shape, dtype=f0)


gather_rows_permuted.defvjp(_gather_rows_permuted_fwd, _gather_rows_permuted_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def gather_rows_local(
    x: jnp.ndarray,
    ids: jnp.ndarray,
    win: jnp.ndarray,
    num_rows: int,
) -> jnp.ndarray:
    """``x[ids]`` for UNSORTED-BUT-LOCAL ids — batched graphs, where
    each graph's senders are confined to its contiguous node block —
    with both directions on the windowed Pallas kernels:

      forward:  bcast kernel, chunk-min/max window plan (in-jit);
      backward: local-window segment sum over ``win`` (int32
                [2, n_blocks], host-emitted ``graph/batch.py`` block
                windows) — no edge permute, no sort, no scatter.

    vs :func:`gather_rows_permuted`, this removes the backward's
    [E, H] cotangent permute (a serial row gather, ~7.4 ms at E=699k
    on v5e) and the argsort it rides on. Off-TPU both directions fall
    back to plain indexing / XLA scatter-add."""
    from hydragnn_tpu.ops.segment_pallas import gather_rows_local_fast

    return gather_rows_local_fast(x, ids)


def _gather_rows_local_fwd(x, ids, win, num_rows):
    return gather_rows_local(x, ids, win, num_rows), (ids, win)


def _gather_rows_local_bwd(num_rows, res, g):
    from hydragnn_tpu.ops.segment_pallas import segment_sum_local_fast

    ids, win = res
    grad = segment_sum_local_fast(g, ids, win, num_rows).astype(g.dtype)
    f0 = jax.dtypes.float0
    return grad, jnp.zeros(ids.shape, dtype=f0), jnp.zeros(win.shape, dtype=f0)


gather_rows_local.defvjp(_gather_rows_local_fwd, _gather_rows_local_bwd)


def node_degree(
    receivers: jnp.ndarray,
    num_nodes: int,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """In-degree of each node (count of incoming edges), float32."""
    return segment_count(receivers, num_nodes, mask)
