"""Flagship model/problem builder shared by bench.py and __graft_entry__.py.

The flagship configuration is the reference's strongest model family — a
multi-head PNA stack (graph energy head + 3 nodal heads) on the
deterministic BCC dataset (reference model zoo: hydragnn/models/PNAStack.py;
dataset: tests/deterministic_graph_data.py) — scaled so the conv stack's
matmuls land on the MXU with meaningful tiles.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.utils.config import update_config


def flagship_config(
    hidden_dim: int = 128,
    num_conv_layers: int = 6,
    batch_size: int = 128,
    num_epoch: int = 1,
) -> Dict[str, Any]:
    return {
        "Verbosity": {"level": 0},
        "Dataset": {
            "name": "flagship_bench",
            "format": "unit_test",
            "compositional_stratified_splitting": False,
            "rotational_invariance": False,
            "node_features": {
                "name": ["x", "x2", "x3"],
                "dim": [1, 1, 1],
                "column_index": [0, 6, 7],
            },
            "graph_features": {
                "name": ["sum_x_x2_x3"],
                "dim": [1],
                "column_index": [0],
            },
        },
        "NeuralNetwork": {
            "Architecture": {
                "model_type": "PNA",
                "radius": 2.0,
                "max_neighbours": 100,
                "periodic_boundary_conditions": False,
                "hidden_dim": hidden_dim,
                "num_conv_layers": num_conv_layers,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 2,
                        "dim_sharedlayers": hidden_dim,
                        "num_headlayers": 2,
                        "dim_headlayers": [hidden_dim, hidden_dim // 2],
                    },
                    "node": {
                        "num_headlayers": 2,
                        "dim_headlayers": [hidden_dim, hidden_dim // 2],
                        "type": "mlp",
                    },
                },
                "task_weights": [4.0, 2.0, 2.0, 2.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["sum_x_x2_x3", "x", "x2", "x3"],
                "output_index": [0, 0, 1, 2],
                "type": ["graph", "node", "node", "node"],
            },
            "Training": {
                "num_epoch": num_epoch,
                "perc_train": 0.8,
                "loss_function_type": "mse",
                "batch_size": batch_size,
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            },
        },
    }


def build_flagship(
    n_samples: int = 512,
    hidden_dim: int = 128,
    num_conv_layers: int = 6,
    batch_size: int = 128,
    device_stack: int = 1,
    unit_cells: Tuple[int, int] = (2, 4),
    seed: int = 0,
    cache_device_batches: bool = False,
    edge_multiple: int = 8,
    edge_lengths: bool = False,
    bn_axis_name: Optional[str] = None,
):
    """Returns (config, model, variables, train_loader). ``edge_lengths``
    adds the reference's length edge feature (Architecture.edge_features,
    QM9-style edge_dim=1 attributes through every conv). ``bn_axis_name``
    enables SyncBN over that mesh axis — required for a sharded step to
    be numerically equivalent to the single-device step (each shard
    otherwise normalizes with its local batch statistics)."""
    config = flagship_config(hidden_dim, num_conv_layers, batch_size)
    if edge_lengths:
        config["NeuralNetwork"]["Architecture"]["edge_features"] = ["lengths"]
    samples = deterministic_graph_data(
        number_configurations=n_samples,
        unit_cell_x_range=unit_cells,
        unit_cell_y_range=unit_cells,
        unit_cell_z_range=unit_cells,
        seed=seed,
    )
    train, val, test, _, _ = prepare_dataset(samples, config)
    config = update_config(config, train, val, test)
    loader = GraphLoader(
        train,
        batch_size,
        shuffle=True,
        device_stack=device_stack,
        drop_last=True,
        cache_device_batches=cache_device_batches,
        edge_multiple=edge_multiple,
    )
    import jax

    example = next(iter(loader))
    if device_stack > 1:
        example = jax.tree_util.tree_map(lambda x: x[0], example)
    model, variables = create_model_config(
        config["NeuralNetwork"], example, bn_axis_name=bn_axis_name
    )
    return config, model, variables, loader
