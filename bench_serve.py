"""Serving benchmark: synthetic online traffic through the ModelServer.

Prints ONE JSON line. Headline: steady-state serving throughput
(graphs/sec) through the bucketed micro-batching path, plus the serving
metrics the subsystem exists to bound — request latency percentiles,
per-bucket occupancy, and ``compile_misses_after_warmup`` (MUST be 0:
every steady-state request routes to an AOT-compiled bucket; a nonzero
value means the ladder no longer covers the traffic and requests are
paying XLA compiles on the serving path).

Two phases after startup AOT warmup:
  1. a short warmup burst (stabilizes jit/allocator state; its requests
     are excluded from the timed window);
  2. the timed load phase — ``SERVE_THREADS`` concurrent closed-loop
     clients submitting ``SERVE_REQUESTS`` graphs sampled from the
     dataset size distribution.

CPU mode (``JAX_PLATFORMS=cpu python bench_serve.py``) runs a smoke-
sized model; the same knobs scale it to a real chip. Knobs:
SERVE_REQUESTS, SERVE_THREADS, SERVE_MAX_BATCH, SERVE_DELAY_MS,
SERVE_BUCKETS, SERVE_SAMPLES, SERVE_HIDDEN, SERVE_LAYERS.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def main() -> None:
    from bench import init_device_with_flight, open_bench_flight

    metric = "serve_bucketed_throughput"
    # backend init with bounded transient-failure retry + a fresh flight
    # record: the serving bench leaves the same self-contained JSONL
    # evidence artifact training and bench.py do (BENCH_FLIGHT overrides
    # the path for both benches; default name differs so one round can
    # keep both artifacts)
    flight = open_bench_flight("BENCH_SERVE_FLIGHT.jsonl")
    device, init_retries = init_device_with_flight(metric, flight)

    import numpy as np

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.serve import ModelRegistry, ModelServer, ServeConfig

    n_requests = int(os.environ.get("SERVE_REQUESTS", 96))
    n_threads = int(os.environ.get("SERVE_THREADS", 2))
    max_batch = int(os.environ.get("SERVE_MAX_BATCH", 8))
    delay_ms = float(os.environ.get("SERVE_DELAY_MS", 5.0))
    num_buckets = int(os.environ.get("SERVE_BUCKETS", 3))
    n_samples = int(os.environ.get("SERVE_SAMPLES", 64))
    hidden = int(os.environ.get("SERVE_HIDDEN", 16))
    layers = int(os.environ.get("SERVE_LAYERS", 2))

    # Random-init flagship (PNA multi-head): serving cost does not depend
    # on the weights, and skipping the train/checkpoint round-trip keeps
    # the bench self-contained. The checkpoint path is covered by
    # tests/test_serve.py's run_prediction-equivalence test.
    _, model, variables, loader = build_flagship(
        n_samples=n_samples,
        hidden_dim=hidden,
        num_conv_layers=layers,
        batch_size=max(max_batch, 2),
        unit_cells=(2, 4),
    )
    registry = ModelRegistry()
    served = registry.register("bench_serve", model, variables)

    requests = list(loader.all_samples)
    server = ModelServer(
        served,
        requests,
        ServeConfig(
            max_batch=max_batch,
            num_buckets=num_buckets,
            max_delay_ms=delay_ms,
            max_pending=max(4 * max_batch * n_threads, 64),
        ),
        flight=flight,
    )
    t0 = time.perf_counter()
    server.start()  # AOT-compiles the whole bucket ladder
    warmup_s = time.perf_counter() - t0

    # phase 1: warmup burst (excluded from the timed window)
    for s in requests[: min(2 * max_batch, len(requests))]:
        server.predict(s, timeout=60)
    snap_warm = server.metrics_snapshot()
    misses_at_warmup = snap_warm["compile_misses"]

    # phase 2: timed closed-loop clients over the dataset distribution
    rng = np.random.default_rng(0)
    order = rng.integers(0, len(requests), size=n_requests)
    per_thread = np.array_split(order, n_threads)
    errors: list = []

    def client(idx_list) -> None:
        try:
            for i in idx_list:
                server.predict(requests[int(i)], timeout=120)
        except BaseException as exc:  # pragma: no cover - surfaced in record
            errors.append(repr(exc))

    threads = [threading.Thread(target=client, args=(ix,)) for ix in per_thread]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    snap = server.metrics_snapshot()
    server.stop()
    misses_after_warmup = snap["compile_misses"] - misses_at_warmup
    occ = {
        name: round(b["occupancy_mean"], 2)
        for name, b in snap["buckets"].items()
        if b["batches"]
    }
    record = {
        "metric": metric,
        "value": round(n_requests / wall, 2),
        "unit": "graphs/sec",
        "init_retries": init_retries,
        "requests": n_requests,
        "threads": n_threads,
        "max_batch": max_batch,
        "max_delay_ms": delay_ms,
        "buckets": len(server.buckets),
        "bucket_plans": [
            [b.cap_nodes, b.cap_edges, b.node_pad, b.edge_pad] for b in server.buckets
        ],
        "warmup_compile_s": round(warmup_s, 2),
        "compile_warmup": snap["compile_warmup"],
        "compile_misses_after_warmup": misses_after_warmup,
        "latency": {k: round(v, 2) for k, v in snap["latency"].items()},
        "occupancy_mean": occ,
        "queue_depth_peak": snap["queue_depth_peak"],
        "rejected_overload": snap["rejected_overload"],
        "errors": errors[:3],
    }
    # server.stop() already logged its run_end (metrics snapshot); the
    # bench's own verdict rides a final event, then the file closes
    flight.record(
        "bench_result",
        record=record,
        passed=bool(not errors and misses_after_warmup == 0),
    )
    flight.close()
    print(json.dumps(record))
    if errors:
        raise SystemExit(1)
    if misses_after_warmup != 0:
        print(
            f"FAIL: {misses_after_warmup} compile-cache misses after warmup — "
            "steady-state traffic recompiled",
            file=sys.stderr,
        )
        raise SystemExit(1)


if __name__ == "__main__":
    main()
