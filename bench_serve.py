"""Serving benchmark: synthetic online traffic through the ModelServer.

Prints ONE JSON line. Headline: steady-state serving throughput
(graphs/sec) through the bucketed micro-batching path, plus the serving
metrics the subsystem exists to bound — request latency percentiles,
per-bucket occupancy, and ``compile_misses_after_warmup`` (MUST be 0:
every steady-state request routes to an AOT-compiled bucket; a nonzero
value means the ladder no longer covers the traffic and requests are
paying XLA compiles on the serving path).

Two phases after startup AOT warmup:
  1. a short warmup burst (stabilizes jit/allocator state; its requests
     are excluded from the timed window);
  2. the timed load phase — ``SERVE_THREADS`` concurrent closed-loop
     clients submitting ``SERVE_REQUESTS`` graphs sampled from the
     dataset size distribution.

CPU mode (``JAX_PLATFORMS=cpu python bench_serve.py``) runs a smoke-
sized model; the same knobs scale it to a real chip. Knobs:
SERVE_REQUESTS, SERVE_THREADS, SERVE_MAX_BATCH, SERVE_DELAY_MS,
SERVE_BUCKETS, SERVE_SAMPLES, SERVE_HIDDEN, SERVE_LAYERS.

Cold-vs-warm mode (``python bench_serve.py --cold-warm``, or
SERVE_COLD_WARM=1): the r09 cold-start headline. Starts TWO sequential
servers against the same persistent executable cache directory
(utils/exec_cache.py; SERVE_EXEC_CACHE overrides the default fresh temp
dir): the first (cold) pays the AOT bucket-ladder compiles and stores
every executable, the second (warm) must deserialize the whole ladder
from disk — ``compile_warmup == 0`` is asserted, the record reports
``startup_cold_s`` / ``startup_warm_s`` plus compile and exec-cache
counts, and both servers prove the ladder actually serves traffic.

Chaos mode (``python bench_serve.py --chaos``, or SERVE_CHAOS=1): the
committed self-healing acceptance run (docs/RESILIENCE.md "Serving
resilience"). Against live traffic it injects a raise-in-forward poison
request, a wedged dispatch (forward sleeps past the watchdog
threshold), a dispatch-thread death, and performs one hot reload —
then asserts the server ends the run READY, every submitted request
resolved (result or typed RequestFailed: ZERO lost/hanging futures),
the quarantine/restart/reload counts match the injection plan in both
the metrics and the flight record, and post-recovery traffic paid 0
new compile misses. The headline value is the worst not-ready gap
(recovery time); exit 1 on any violated invariant. The mid-traffic
hot reload here is the same canary + atomic-swap path the retrain
pilot (``hydragnn_tpu/pilot``, docs/RESILIENCE.md "Closed loop")
drives as the final stage of every retrain cycle, so this number is
also the serving-impact bound for a pilot-initiated reload.

Fleet mode (``python bench_serve.py --fleet``, or SERVE_FLEET=1): the
fleet chaos acceptance run (docs/FLEET.md). Measures sustained QPS at
fixed p99 through an N=2 replica fleet (vs an N=1 baseline — the
scale-out efficiency headline), then runs the three fleet chaos
scenarios against live traffic: replica-kill mid-traffic (controller
reaps + replaces, router death-retry absorbs in-flights), scale-up
under sustained queue breach (trigger verdict spawns a replica), and a
fleet-wide rolling reload. Every scenario asserts p99 under
FLEET_SLO_P99_MS and zero lost futures; every post-first replica must
warm-start from the shared exec cache with 0 AOT compiles. Writes the
committed, schema-validated BENCH_FLEET.json.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def main() -> None:
    from bench import init_device_with_flight, open_bench_flight

    metric = "serve_bucketed_throughput"
    # backend init with bounded transient-failure retry + a fresh flight
    # record: the serving bench leaves the same self-contained JSONL
    # evidence artifact training and bench.py do (BENCH_FLIGHT overrides
    # the path for both benches; default name differs so one round can
    # keep both artifacts)
    flight = open_bench_flight("BENCH_SERVE_FLIGHT.jsonl")
    device, init_retries = init_device_with_flight(metric, flight)

    import numpy as np

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.serve import ModelRegistry, ModelServer, ServeConfig

    n_requests = int(os.environ.get("SERVE_REQUESTS", 96))
    n_threads = int(os.environ.get("SERVE_THREADS", 2))
    max_batch = int(os.environ.get("SERVE_MAX_BATCH", 8))
    delay_ms = float(os.environ.get("SERVE_DELAY_MS", 5.0))
    num_buckets = int(os.environ.get("SERVE_BUCKETS", 3))
    n_samples = int(os.environ.get("SERVE_SAMPLES", 64))
    hidden = int(os.environ.get("SERVE_HIDDEN", 16))
    layers = int(os.environ.get("SERVE_LAYERS", 2))

    # Random-init flagship (PNA multi-head): serving cost does not depend
    # on the weights, and skipping the train/checkpoint round-trip keeps
    # the bench self-contained. The checkpoint path is covered by
    # tests/test_serve.py's run_prediction-equivalence test.
    _, model, variables, loader = build_flagship(
        n_samples=n_samples,
        hidden_dim=hidden,
        num_conv_layers=layers,
        batch_size=max(max_batch, 2),
        unit_cells=(2, 4),
    )
    registry = ModelRegistry()
    served = registry.register("bench_serve", model, variables)

    requests = list(loader.all_samples)
    server = ModelServer(
        served,
        requests,
        ServeConfig(
            max_batch=max_batch,
            num_buckets=num_buckets,
            max_delay_ms=delay_ms,
            max_pending=max(4 * max_batch * n_threads, 64),
        ),
        flight=flight,
    )
    t0 = time.perf_counter()
    server.start()  # AOT-compiles the whole bucket ladder
    warmup_s = time.perf_counter() - t0

    # phase 1: warmup burst (excluded from the timed window)
    for s in requests[: min(2 * max_batch, len(requests))]:
        server.predict(s, timeout=60)
    snap_warm = server.metrics_snapshot()
    misses_at_warmup = snap_warm["compile_misses"]

    # phase 2: timed closed-loop clients over the dataset distribution
    rng = np.random.default_rng(0)
    order = rng.integers(0, len(requests), size=n_requests)
    per_thread = np.array_split(order, n_threads)
    errors: list = []

    # graftsync: thread-root
    def client(idx_list) -> None:
        try:
            for i in idx_list:
                server.predict(requests[int(i)], timeout=120)
        except BaseException as exc:  # pragma: no cover - surfaced in record
            errors.append(repr(exc))

    # graftsync: disable=HS004 -- every element is joined in the loop below
    threads = [threading.Thread(target=client, args=(ix,)) for ix in per_thread]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    snap = server.metrics_snapshot()
    server.stop()
    misses_after_warmup = snap["compile_misses"] - misses_at_warmup
    occ = {
        name: round(b["occupancy_mean"], 2)
        for name, b in snap["buckets"].items()
        if b["batches"]
    }
    record = {
        "metric": metric,
        "value": round(n_requests / wall, 2),
        "unit": "graphs/sec",
        "init_retries": init_retries,
        "requests": n_requests,
        "threads": n_threads,
        "max_batch": max_batch,
        "max_delay_ms": delay_ms,
        "buckets": len(server.buckets),
        "bucket_plans": [
            [b.cap_nodes, b.cap_edges, b.node_pad, b.edge_pad] for b in server.buckets
        ],
        "warmup_compile_s": round(warmup_s, 2),
        "compile_warmup": snap["compile_warmup"],
        "compile_misses_after_warmup": misses_after_warmup,
        "latency": {k: round(v, 2) for k, v in snap["latency"].items()},
        "occupancy_mean": occ,
        "queue_depth_peak": snap["queue_depth_peak"],
        "rejected_overload": snap["rejected_overload"],
        # whether drift monitoring / the request spool was armed for
        # this bench (obs_report --validate surfaces the same from the
        # flight manifest)
        "observability": server.obs_arming,
        "errors": errors[:3],
    }
    # server.stop() already logged its run_end (metrics snapshot); the
    # bench's own verdict rides a final event, then the file closes
    flight.record(
        "bench_result",
        record=record,
        passed=bool(not errors and misses_after_warmup == 0),
    )
    flight.close()
    print(json.dumps(record))
    if errors:
        raise SystemExit(1)
    if misses_after_warmup != 0:
        print(
            f"FAIL: {misses_after_warmup} compile-cache misses after warmup — "
            "steady-state traffic recompiled",
            file=sys.stderr,
        )
        raise SystemExit(1)


def cold_warm() -> None:
    """Cold vs warm serve startup against one persistent executable
    cache dir (see module docstring). Exit 1 if the warm start paid ANY
    live warmup compile — the zero-compile second replica is the
    acceptance bar, not an aspiration."""
    from bench import init_device_with_flight, open_bench_flight

    metric = "serve_cold_vs_warm_startup"
    flight = open_bench_flight("BENCH_SERVE_WARM_FLIGHT.jsonl")
    device, init_retries = init_device_with_flight(metric, flight)

    import tempfile

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.serve import ModelRegistry, ModelServer, ServeConfig

    max_batch = int(os.environ.get("SERVE_MAX_BATCH", 8))
    num_buckets = int(os.environ.get("SERVE_BUCKETS", 3))
    n_samples = int(os.environ.get("SERVE_SAMPLES", 64))
    hidden = int(os.environ.get("SERVE_HIDDEN", 16))
    layers = int(os.environ.get("SERVE_LAYERS", 2))
    cache_dir = os.environ.get("SERVE_EXEC_CACHE") or tempfile.mkdtemp(
        prefix="serve_exec_cache_"
    )

    _, model, variables, loader = build_flagship(
        n_samples=n_samples,
        hidden_dim=hidden,
        num_conv_layers=layers,
        batch_size=max(max_batch, 2),
        unit_cells=(2, 4),
    )
    requests = list(loader.all_samples)
    registry = ModelRegistry()

    def one_start(tag: str) -> dict:
        # a fresh registration per start = a fresh jitted forward, so
        # the warm server cannot lean on the cold server's in-process
        # jit cache — its zero-compile startup is the DISK cache's work
        served = registry.register(f"bench_serve_{tag}", model, variables)
        server = ModelServer(
            served,
            requests,
            ServeConfig(
                max_batch=max_batch,
                num_buckets=num_buckets,
                exec_cache_dir=cache_dir,
            ),
            flight=flight,
        )
        t0 = time.perf_counter()
        server.start()
        startup_s = time.perf_counter() - t0
        # the deserialized ladder must actually serve, not just load
        for s in requests[: min(max_batch, len(requests))]:
            server.predict(s, timeout=60)
        snap = server.metrics_snapshot()
        ladder = len(server.buckets)
        server.stop()
        return {
            "startup_s": round(startup_s, 3),
            "buckets": ladder,
            "compile_warmup": snap["compile_warmup"],
            "compile_misses": snap["compile_misses"],
            "exec_cache_hits": snap["exec_cache_hits"],
            "exec_cache_misses": snap["exec_cache_misses"],
            "exec_cache_miss_reasons": snap["exec_cache_miss_reasons"],
            "observability": server.obs_arming,
        }

    cold = one_start("cold")
    warm = one_start("warm")

    failures = []
    if warm["compile_warmup"] != 0:
        failures.append(
            f"warm start paid {warm['compile_warmup']} live warmup "
            "compiles — the persistent cache did not cover the ladder"
        )
    if warm["exec_cache_hits"] < warm["buckets"]:
        failures.append(
            f"warm exec_cache_hits={warm['exec_cache_hits']} below the "
            f"ladder size {warm['buckets']} — some bucket recompiled"
        )
    record = {
        "metric": metric,
        "value": warm["startup_s"],
        "unit": "s_warm_startup",
        "init_retries": init_retries,
        "startup_cold_s": cold["startup_s"],
        "startup_warm_s": warm["startup_s"],
        "warm_over_cold": round(
            warm["startup_s"] / max(cold["startup_s"], 1e-9), 3
        ),
        "cache_dir": cache_dir,
        "cold": cold,
        "warm": warm,
        "failures": failures,
    }
    flight.record("bench_result", record=record, passed=not failures)
    flight.close()
    print(json.dumps(record))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        raise SystemExit(1)


def chaos() -> None:
    """The serving-resilience acceptance run (see module docstring)."""
    from bench import init_device_with_flight, open_bench_flight

    metric = "serve_chaos_recovery"
    flight = open_bench_flight("BENCH_SERVE_CHAOS_FLIGHT.jsonl")
    device, init_retries = init_device_with_flight(metric, flight)

    import numpy as np

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.serve import (
        ModelRegistry,
        ModelServer,
        RequestFailed,
        ServeConfig,
    )

    n_requests = int(os.environ.get("SERVE_REQUESTS", 96))
    max_batch = int(os.environ.get("SERVE_MAX_BATCH", 8))
    n_samples = int(os.environ.get("SERVE_SAMPLES", 64))
    hidden = int(os.environ.get("SERVE_HIDDEN", 16))
    layers = int(os.environ.get("SERVE_LAYERS", 2))

    # the injection plan: one poison raise, one wedged forward past the
    # watchdog threshold, one dispatch-thread death, one hot reload
    seq_raise = n_requests // 4
    seq_wedge = (2 * n_requests) // 3
    kill_batch = 3
    wedge_s = 1
    os.environ["HYDRAGNN_INJECT_SERVE_RAISE"] = str(seq_raise)
    os.environ["HYDRAGNN_INJECT_SERVE_WEDGE"] = f"{seq_wedge}:{wedge_s}"
    os.environ["HYDRAGNN_INJECT_SERVE_KILL_DISPATCH"] = str(kill_batch)

    _, model, variables, loader = build_flagship(
        n_samples=n_samples,
        hidden_dim=hidden,
        num_conv_layers=layers,
        batch_size=max(max_batch, 2),
        unit_cells=(2, 4),
    )
    registry = ModelRegistry()
    served = registry.register("bench_serve_chaos", model, variables)
    requests = list(loader.all_samples)
    server = ModelServer(
        served,
        requests,
        ServeConfig(
            max_batch=max_batch,
            max_delay_ms=3.0,
            max_pending=max(8 * n_requests, 256),
            dispatch_stall_s=0.25,
            dispatch_backoff_base_s=0.2,
        ),
        flight=flight,
    )
    server.start()

    # readiness sampler: the recovery-time measurement
    ready_samples: list = []
    sampling = threading.Event()

    # graftsync: thread-root
    def sampler() -> None:
        while not sampling.wait(0.01):
            ready_samples.append((time.perf_counter(), server.health()["ready"]))

    sampler_t = threading.Thread(target=sampler, daemon=True)
    sampler_t.start()

    rng = np.random.default_rng(0)
    order = rng.integers(0, len(requests), size=n_requests)
    futures = []
    t0 = time.perf_counter()
    reload_info = None
    for i, idx in enumerate(order):
        futures.append(server.submit(requests[int(idx)]))
        time.sleep(0.002)  # paced open-loop: faults land mid-traffic
        if i == n_requests // 2:
            # hot reload mid-traffic (fresh copy of the same weights:
            # the canary + atomic-swap path, architecture unchanged)
            reload_info = server.reload(variables=dict(variables))
    results, typed_failures, lost = 0, 0, 0
    for f in futures:
        try:
            f.result(timeout=120)
            results += 1
        except RequestFailed:
            typed_failures += 1
        except BaseException:
            lost += 1  # an UNtyped failure is a lost contract
    wall = time.perf_counter() - t0

    # settle, then measure the not-ready gaps out of the sampler trace
    deadline = time.perf_counter() + 10.0
    while not server.health()["ready"] and time.perf_counter() < deadline:
        time.sleep(0.01)
    sampling.set()
    sampler_t.join(timeout=2.0)
    gaps, gap_start = [], None
    for t, ready in ready_samples:
        if not ready and gap_start is None:
            gap_start = t
        elif ready and gap_start is not None:
            gaps.append(t - gap_start)
            gap_start = None
    if gap_start is not None:
        gaps.append(ready_samples[-1][0] - gap_start)

    health = server.health()
    snap = server.metrics_snapshot()
    server.stop()
    for k in list(os.environ):
        if k.startswith("HYDRAGNN_INJECT_SERVE_"):
            del os.environ[k]

    from hydragnn_tpu.obs.flight import read_flight_record

    events = read_flight_record(flight.path)
    fcounts = {
        kind: sum(1 for e in events if e.get("kind") == kind)
        for kind in ("quarantine", "dispatch_restart", "watchdog", "reload", "reload_failed")
    }

    plan = {"quarantined": 1, "dispatch_restarts": 1, "reloads": 1}
    failures = []
    if lost:
        failures.append(f"{lost} futures failed UNtyped (lost contract)")
    if results + typed_failures != n_requests:
        failures.append(
            f"resolved {results}+{typed_failures} != submitted {n_requests}"
        )
    if not health["ready"]:
        failures.append(f"server not ready at end: {health['reasons']}")
    for key, want in plan.items():
        if snap[key] != want:
            failures.append(f"metrics {key}={snap[key]} != plan {want}")
    if fcounts["quarantine"] != plan["quarantined"]:
        failures.append(f"flight quarantine={fcounts['quarantine']} != 1")
    if fcounts["dispatch_restart"] != plan["dispatch_restarts"]:
        failures.append(f"flight dispatch_restart={fcounts['dispatch_restart']} != 1")
    if fcounts["reload"] != plan["reloads"] or fcounts["reload_failed"]:
        failures.append(
            f"flight reload={fcounts['reload']}/failed={fcounts['reload_failed']}"
        )
    if fcounts["watchdog"] < 1:
        failures.append("wedged dispatch never tripped the watchdog")
    if snap["compile_misses"] != 0:
        failures.append(
            f"{snap['compile_misses']} compile misses — recovery recompiled"
        )

    record = {
        "metric": metric,
        "value": round(max(gaps), 3) if gaps else 0.0,
        "unit": "s_worst_not_ready_gap",
        "init_retries": init_retries,
        "requests": n_requests,
        "wall_s": round(wall, 2),
        "results": results,
        "typed_failures": typed_failures,
        "lost_futures": lost,
        "injection_plan": {
            "raise_at_seq": seq_raise,
            "wedge_at_seq": [seq_wedge, wedge_s],
            "kill_dispatch_at_batch": kill_batch,
            "reload_at_request": n_requests // 2,
        },
        "not_ready_gaps_s": [round(g, 3) for g in gaps],
        "reload": reload_info,
        "metrics": {k: snap[k] for k in (
            "quarantined", "poison_retries", "dispatch_restarts", "reloads",
            "reload_failed", "errors", "compile_misses",
        )},
        "flight_counts": fcounts,
        "observability": server.obs_arming,
        "failures": failures,
    }
    flight.record("bench_result", record=record, passed=not failures)
    flight.close()
    print(json.dumps(record))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        raise SystemExit(1)


def fleet_chaos() -> None:
    """Fleet acceptance run (``--fleet``, docs/FLEET.md): sustained QPS
    at fixed p99 through an N>=2 replica fleet on one host, then the
    three fleet chaos scenarios against live traffic — replica-kill
    mid-traffic (controller restores capacity), scale-up-under-load
    (trigger verdict spawns a replica), and a fleet-wide rolling reload
    — each asserting p99 under the SLO throughout and ZERO lost
    futures (result or typed error; the router's death-retry absorbs
    the kill). Every post-first replica must warm-start from the shared
    exec cache with 0 AOT compiles; scale-out efficiency (QPS at N=2 vs
    N=1) lands in the committed, schema-validated BENCH_FLEET.json.
    Per-replica SLO trigger rules stay armed, so any breach
    auto-captures an incident bundle (counted in the record)."""
    from bench import init_device_with_flight, open_bench_flight

    metric = "fleet_sustained_qps"
    flight = open_bench_flight("BENCH_FLEET_FLIGHT.jsonl")
    device, init_retries = init_device_with_flight(metric, flight)

    import tempfile

    import numpy as np

    from hydragnn_tpu.fleet import ControllerConfig, Fleet, FleetController
    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.serve import ModelRegistry, ServeConfig

    n_requests = int(os.environ.get("SERVE_REQUESTS", 96))
    n_threads = int(os.environ.get("SERVE_THREADS", 4))
    max_batch = int(os.environ.get("SERVE_MAX_BATCH", 8))
    n_samples = int(os.environ.get("SERVE_SAMPLES", 64))
    hidden = int(os.environ.get("SERVE_HIDDEN", 16))
    layers = int(os.environ.get("SERVE_LAYERS", 2))
    slo_p99_ms = float(os.environ.get("FLEET_SLO_P99_MS", 3000.0))
    out_path = os.environ.get("FLEET_BENCH_OUT", "BENCH_FLEET.json")

    cache_dir = os.environ.get("SERVE_EXEC_CACHE") or tempfile.mkdtemp(
        prefix="fleet_exec_cache_"
    )
    incident_dir = tempfile.mkdtemp(prefix="fleet_incidents_")

    _, model, variables, loader = build_flagship(
        n_samples=n_samples,
        hidden_dim=hidden,
        num_conv_layers=layers,
        batch_size=max(max_batch, 2),
        unit_cells=(2, 4),
    )
    registry = ModelRegistry()
    requests = list(loader.all_samples)
    serve_cfg = ServeConfig(
        max_batch=max_batch,
        max_delay_ms=3.0,
        max_pending=max(8 * n_requests, 256),
        dispatch_backoff_base_s=0.2,
        slo_p99_ms=slo_p99_ms,
        incident_dir=incident_dir,
    )
    rng = np.random.default_rng(0)
    failures: list = []
    lost_total = 0

    def run_traffic(fleet, n: int, tag: str) -> dict:
        """Closed-loop clients through the ROUTER; returns QPS + p99 +
        the resolve ledger (every submitted future accounted for)."""
        nonlocal lost_total
        order = rng.integers(0, len(requests), size=n)
        per_thread = np.array_split(order, n_threads)
        latencies: list = []
        ledger = {"results": 0, "typed": 0, "lost": 0}
        ledger_lock = threading.Lock()

        # graftsync: thread-root
        def client(idx_list) -> None:
            from hydragnn_tpu.serve import Overloaded, RequestFailed
            from hydragnn_tpu.serve.batcher import ServerClosed

            for i in idx_list:
                t0 = time.perf_counter()
                try:
                    fleet.predict(requests[int(i)], timeout=120)
                    with ledger_lock:
                        latencies.append(time.perf_counter() - t0)
                        ledger["results"] += 1
                except (RequestFailed, Overloaded, ServerClosed):
                    with ledger_lock:
                        ledger["typed"] += 1
                except BaseException:
                    with ledger_lock:
                        ledger["lost"] += 1

        # graftsync: disable=HS004 -- every element is joined in the loop below
        threads = [threading.Thread(target=client, args=(ix,)) for ix in per_thread]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        lat_sorted = sorted(latencies)
        p99 = (
            lat_sorted[min(len(lat_sorted) - 1, int(round(0.99 * (len(lat_sorted) - 1))))]
            * 1e3
            if lat_sorted
            else 0.0
        )
        lost_total += ledger["lost"]
        if ledger["lost"]:
            failures.append(f"{tag}: {ledger['lost']} futures failed UNtyped")
        if p99 > slo_p99_ms:
            failures.append(f"{tag}: p99 {p99:.0f}ms over SLO {slo_p99_ms:g}ms")
        return {
            "qps": round(n / wall, 2),
            "p99_ms": round(p99, 1),
            "wall_s": round(wall, 2),
            **ledger,
        }

    scenarios = {}

    # -- phase A: N=1 baseline QPS (pays the one-time AOT compiles) --------
    fleet1 = Fleet(exec_cache_dir=cache_dir, flight=flight)
    fleet1.add_model("flagship", registry.register("fleet_n1", model, variables),
                     requests, serve_cfg, replicas=1)
    scenarios["baseline_n1"] = run_traffic(fleet1, n_requests, "baseline_n1")
    fleet1.stop()
    qps_n1 = scenarios["baseline_n1"]["qps"]

    # -- phase B: N=2 fleet from the same cache (both replicas warm) -------
    fleet = Fleet(exec_cache_dir=cache_dir, flight=flight)
    reps = fleet.add_model(
        "flagship", registry.register("fleet_n2", model, variables),
        requests, serve_cfg, replicas=2,
    )
    warm_aot = sum(r.server.metrics_snapshot()["compile_warmup"] for r in reps)
    if warm_aot:
        failures.append(
            f"{warm_aot} AOT compiles in the N=2 fleet — the shared exec "
            "cache did not cover the ladder"
        )
    ctl = FleetController(
        fleet,
        registry=fleet.registry,
        config=ControllerConfig(
            min_replicas=1, max_replicas=3, cooldown_s=0.0, quiet_for_s=3600.0,
            slo_queue_depth=4.0, breach_evals=2,
        ),
        flight=flight,
    )

    scenarios["sustained_n2"] = run_traffic(fleet, n_requests, "sustained_n2")
    qps_n2 = scenarios["sustained_n2"]["qps"]

    # -- scenario: replica-kill mid-traffic --------------------------------
    victim = fleet.replicas()[0]
    killer = threading.Timer(0.05, victim.kill)
    killer.start()
    kill_stats = run_traffic(fleet, n_requests, "replica_kill")
    killer.join()
    ctl.step()  # reap + replace, outside any cooldown
    replacement = [
        r for r in fleet.replicas() if r.name not in (victim.name,)
    ]
    kill_stats["replaced"] = fleet.replica_count() == 2
    kill_stats["replacement_aot_compiles"] = sum(
        r.server.metrics_snapshot()["compile_warmup"]
        for r in replacement
    )
    if not kill_stats["replaced"]:
        failures.append("replica_kill: controller did not restore capacity")
    if kill_stats["replacement_aot_compiles"]:
        failures.append("replica_kill: replacement replica paid AOT compiles")
    if not all(r.ready for r in fleet.replicas()):
        failures.append("replica_kill: fleet not READY after replacement")
    scenarios["replica_kill"] = kill_stats

    # -- scenario: scale-up under load -------------------------------------
    burst = [fleet.submit(requests[int(i)]) for i in
             rng.integers(0, len(requests), size=6 * max_batch)]
    decisions = []
    deadline = time.perf_counter() + 30.0
    while time.perf_counter() < deadline:
        if fleet.total_load() <= 4:
            # keep the queue over the trigger threshold until the
            # controller has seen a SUSTAINED breach (breach_evals=2)
            burst += [
                fleet.submit(requests[int(i)])
                for i in rng.integers(0, len(requests), size=2 * max_batch)
            ]
        decisions += ctl.step()
        if any(d["action"] == "up" for d in decisions):
            break
    burst_lost = 0
    for f in burst:
        try:
            f.result(timeout=120)
        except BaseException as exc:
            from hydragnn_tpu.serve import Overloaded, RequestFailed

            if not isinstance(exc, (RequestFailed, Overloaded)):
                burst_lost += 1
    lost_total += burst_lost
    scaled = any(d["action"] == "up" for d in decisions)
    new_replicas = [r for r in fleet.replicas()]
    scenarios["scale_up_under_load"] = {
        "scaled": scaled,
        "replicas_after": fleet.replica_count(),
        "burst": len(burst),
        "lost": burst_lost,
        "new_replica_aot_compiles": sum(
            r.server.metrics_snapshot()["compile_warmup"] for r in new_replicas
        ),
        "decisions": [d["action"] for d in decisions],
    }
    if not scaled:
        failures.append("scale_up: no up decision under sustained queue breach")
    if burst_lost:
        failures.append(f"scale_up: {burst_lost} burst futures failed UNtyped")
    if scenarios["scale_up_under_load"]["new_replica_aot_compiles"]:
        failures.append("scale_up: scaled-up replica paid AOT compiles")
    if not all(r.ready for r in fleet.replicas()):
        failures.append("scale_up: fleet not READY after scale-up")

    # -- scenario: fleet-wide rolling reload mid-traffic -------------------
    roller_result: list = []

    # graftsync: thread-root
    def roller() -> None:
        try:
            roller_result.append(
                fleet.rolling_reload("flagship", variables=dict(variables))
            )
        except BaseException as exc:  # pragma: no cover - surfaced below
            roller_result.append(exc)

    roll_t = threading.Thread(target=roller)
    roll_t.start()
    reload_stats = run_traffic(fleet, n_requests, "rolling_reload")
    roll_t.join(timeout=120)
    ok = (
        roller_result
        and isinstance(roller_result[0], list)
        and all(o["ok"] for o in roller_result[0])
        and len(roller_result[0]) == fleet.replica_count()
    )
    reload_stats["reloaded_replicas"] = (
        len(roller_result[0]) if ok else 0
    )
    if not ok:
        failures.append(f"rolling_reload failed: {roller_result[:1]!r}")
    if not all(r.ready for r in fleet.replicas()):
        failures.append("rolling_reload: fleet not READY at end")
    scenarios["rolling_reload"] = reload_stats

    # every replica shares one ServeConfig, so one replica's arming
    # blocks describe the whole fleet's drift-observability posture
    reps = fleet.replicas()
    obs_arming = reps[0].server.obs_arming if reps else None

    health = fleet.health()
    fleet.stop()

    incidents = sum(
        1 for root, dirs, files in os.walk(incident_dir)
        if "trigger.json" in files
    )
    record = {
        "metric": metric,
        "value": qps_n2,
        "unit": "graphs/sec",
        "init_retries": init_retries,
        "replicas": 2,
        "requests_per_phase": n_requests,
        "threads": n_threads,
        "slo_p99_ms": slo_p99_ms,
        "qps_n1": qps_n1,
        "qps_n2": qps_n2,
        "scaleout_efficiency": round(qps_n2 / max(2 * qps_n1, 1e-9), 3),
        "warm_replica_aot_compiles": warm_aot,
        "lost_futures": lost_total,
        "incidents_captured": incidents,
        "final_health": {
            k: health[k] for k in ("replica_count", "ready_count", "live_count")
        },
        "scenarios": scenarios,
        "observability": obs_arming,
        "failures": failures,
    }
    flight.record("bench_result", record=record, passed=not failures)
    flight.close()
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(record))
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


if __name__ == "__main__":
    if "--fleet" in sys.argv or os.environ.get("SERVE_FLEET") == "1":
        fleet_chaos()
    elif "--chaos" in sys.argv or os.environ.get("SERVE_CHAOS") == "1":
        chaos()
    elif "--cold-warm" in sys.argv or os.environ.get("SERVE_COLD_WARM") == "1":
        cold_warm()
    else:
        main()
