#!/usr/bin/env python
"""CI bench gate: a tiny FIXED-config training bench compared against a
committed baseline — the stage that keeps future PRs from silently
regressing the hot path (ISSUE 6 satellite; wired as a ci.sh stage).

Protocol:
  - build the fixed tiny flagship config (PNA multi-head — the same
    model family as the headline bench, shrunk to CI scale), compile
    one train step, and measure graphs/sec as the MEDIAN of several
    D2H-fenced segments (the bench.py timing discipline: a real
    readback fences each segment);
  - compare against the committed baseline (``BENCH_CI_BASELINE.json``)
    keyed by ``backend:device_kind`` so a CPU CI box and a TPU runner
    each gate against their own machine's number;
  - FAIL (exit 2) when throughput drops more than ``--tolerance``
    (default 15%) below baseline; on TPU, MFU (from the XLA cost model
    + the chip peak table) gates with the same tolerance;
  - the step's COST-MODEL bytes ("bytes accessed" of the compiled
    step) gate alongside: more than ``--tolerance`` ABOVE baseline
    fails — a PR that silently re-materializes an [E, H] intermediate
    regresses traffic long before a tiny CI box can measure it as time
    (ISSUE 10 satellite). Bytes are deterministic per build, so this
    arm is noise-free;
  - a machine with no recorded baseline WRITES one and passes (prints
    a notice) — the committed file carries this container's key; other
    machines self-baseline on first run. ``--update-baseline`` forces a
    rewrite (use after an intentional perf change, and commit it).

Warm-start arm (``--warm-start-arm``, run as its own invocation): the
persistent-executable-cache gate (utils/exec_cache.py). Builds the same
fixed tiny step twice against a fresh cache dir — the first build pays
lower+compile+store (cold), the second must come back as a disk
deserialize (warm) — and FAILS unless the warm build is a hit, paid
zero XLA compiles, and took under 50% of the cold build. Self-contained
ratio: no committed baseline, so it gates identically on any machine.
Refuses to run with any ``HYDRAGNN_INJECT_*`` set (an injected
donation-gate failure would turn the expected hit into a miss).

Self-test hooks: ``--inject-slowdown-ms F`` sleeps F ms inside the
timed loop after every step — a genuine measured slowdown, not a
doctored number — so ci.sh can assert the gate demonstrably fails on a
slow build. ``--inject-traffic-mb M`` adds the cost-model bytes of a
REAL compiled executable over an M-MiB array to the measured step
bytes (genuine extra cost-model traffic, not an arithmetic fudge) so
the traffic arm's failure path is demonstrable the same way.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

# invoked as `python tools/bench_gate.py` from the repo root: sys.path[0]
# is tools/, so the package root must be added explicitly
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _measure(inject_ms: float, steps: int, inject_traffic_mb: float = 0.0) -> dict:
    import jax
    import numpy as np

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.obs.introspect import cost_analysis, peak_flops
    from hydragnn_tpu.train import (
        create_train_state,
        make_train_step,
        select_optimizer,
    )

    # FIXED config — change it only together with --update-baseline:
    # the committed baseline prices exactly this shape.
    batch_size = 16
    config, model, variables, loader = build_flagship(
        n_samples=80,
        hidden_dim=16,
        num_conv_layers=2,
        batch_size=batch_size,
        unit_cells=(2, 3),
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(variables, tx)
    on_tpu = jax.default_backend() == "tpu"
    step = make_train_step(
        model, tx, compute_dtype=jax.numpy.bfloat16 if on_tpu else None
    )
    batches = list(loader)
    compiled = step.lower(state, batches[0]).compile()
    flops, nbytes = cost_analysis(compiled)

    # traffic-arm self-test: the extra bytes come from the XLA cost
    # model of a REAL compiled executable over an M-MiB array — the
    # same pricing path as the gated number, not an arithmetic fudge
    if inject_traffic_mb > 0 and nbytes:
        import jax.numpy as jnp

        n = max(1, int(inject_traffic_mb * (1 << 20)) // 4)
        ballast = (
            jax.jit(lambda x: x + 1.0)
            .lower(jnp.zeros((n,), jnp.float32))
            .compile()
        )
        _, extra = cost_analysis(ballast)
        nbytes += extra or inject_traffic_mb * (1 << 20) * 2

    state, loss, _ = compiled(state, batches[0])  # warmup execution
    np.asarray(loss)
    n_seg = 5
    per_seg = max(1, steps // n_seg)
    seg_ms = []
    done = 0
    for _ in range(n_seg):
        t0 = time.perf_counter()
        for _ in range(per_seg):
            state, loss, _ = compiled(state, batches[done % len(batches)])
            done += 1
            if inject_ms > 0:
                time.sleep(inject_ms / 1e3)
        np.asarray(loss)  # real D2H fence
        seg_ms.append((time.perf_counter() - t0) / per_seg * 1e3)
    step_ms = statistics.median(seg_ms)
    dev = jax.devices()[0]
    peak = peak_flops(dev)
    out = {
        "graphs_per_sec": round(batch_size / step_ms * 1e3, 2),
        "step_ms_median": round(step_ms, 3),
        "step_ms_segments": [round(t, 2) for t in seg_ms],
        "steps": done,
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "mfu": (
            round(flops / (step_ms / 1e3) / peak, 5)
            if flops and peak and on_tpu
            else None
        ),
        "bytes_per_step_costmodel": round(nbytes) if nbytes else None,
    }
    # the analytic conv-traffic modes for THIS fixed config (informational
    # in the baseline: a change here is a deliberate kernel-mode change,
    # reviewed via the committed diff rather than a numeric tolerance)
    try:
        from hydragnn_tpu.obs.introspect import (
            conv_traffic_model,
            pad_waste_from_batch,
        )

        waste = pad_waste_from_batch(batches[0])
        out["conv_traffic_model"] = conv_traffic_model(
            waste["node_pad"], waste["edge_pad"], 16, 2,
            real_edges=waste["real_edges_mean"],
        )["bytes_per_step"]
        out["pad_waste"] = waste
    except Exception:
        pass
    return out


def _warm_start_arm() -> int:
    """Cold vs warm executable build through the persistent exec cache
    (module docstring). Returns the process exit code."""
    import tempfile

    import jax
    import numpy as np

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.obs import CompileMonitor
    from hydragnn_tpu.train import (
        create_train_state,
        make_train_step,
        select_optimizer,
    )
    from hydragnn_tpu.utils.exec_cache import (
        ExecCache,
        abstract_fingerprint,
        compat_manifest,
        fingerprint,
    )
    from hydragnn_tpu.utils import knobs

    injected = knobs.active_injections()
    if injected:
        print(
            f"bench gate warm-start arm: refusing to gate with {injected} "
            "set (injected faults would fail the cache on purpose)"
        )
        return 1

    config, model, variables, loader = build_flagship(
        n_samples=80,
        hidden_dim=16,
        num_conv_layers=2,
        batch_size=16,
        unit_cells=(2, 3),
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(variables, tx)
    # the donation-free twin, matching what train/loop.py caches — a
    # deserialized DONATED executable is unsound (utils/exec_cache.py)
    step = make_train_step(model, tx)
    body = getattr(step, "__wrapped__", None)
    if body is not None:
        step = jax.jit(body)
    batch = next(iter(loader))

    cache = ExecCache(
        tempfile.mkdtemp(prefix="bench_gate_exec_cache_"),
        consumer="bench_gate",
    )
    key = fingerprint(
        "bench_gate_step", abstract_fingerprint((state, batch))
    )
    compat = compat_manifest()
    cmon = CompileMonitor().start()
    exe, hit_cold, cold_s = cache.get_or_compile(
        key, step, (state, batch), compat, donated=body is None, label="gate_cold"
    )
    cmon.mark("warm")
    exe2, hit_warm, warm_s = cache.get_or_compile(
        key, step, (state, batch), compat, donated=body is None, label="gate_warm"
    )
    warm_compiles = cmon.count_since("warm")
    cmon.stop()
    # both executables must actually run (the warm one on a copy: the
    # step donates its state argument)
    st = jax.tree_util.tree_map(lambda x: x.copy(), state)
    _, loss, _ = exe2(st, batch)
    np.asarray(loss)

    ratio = warm_s / max(cold_s, 1e-9)
    print(
        f"bench gate warm-start arm: cold build {cold_s:.3f}s -> warm "
        f"build {warm_s:.3f}s (ratio {ratio:.3f}, warm compiles "
        f"{warm_compiles}, hit {hit_warm})"
    )
    failures = []
    if hit_cold:
        failures.append("cold build unexpectedly HIT a fresh cache dir")
    if not hit_warm:
        reasons = cache.stats["miss_reasons"]
        failures.append(f"warm build MISSED the cache ({reasons})")
    if warm_compiles:
        failures.append(f"warm build paid {warm_compiles} XLA compiles")
    if ratio >= 0.5:
        failures.append(
            f"warm build took {ratio:.0%} of cold — the cache saved "
            "nothing (gate: < 50%)"
        )
    for msg in failures:
        print(f"bench gate warm-start FAIL: {msg}")
    return 2 if failures else 0


def main() -> int:
    from hydragnn_tpu.utils import knobs

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--baseline", default=os.path.join(here, "BENCH_CI_BASELINE.json")
    )
    ap.add_argument(
        "--tolerance",
        type=float,
        default=knobs.get_float("HYDRAGNN_BENCH_GATE_TOL", 0.15),
        help="max fractional regression before failing (default 0.15)",
    )
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument(
        "--inject-slowdown-ms",
        type=float,
        default=0.0,
        help="self-test: sleep this many ms per step inside the timed loop",
    )
    ap.add_argument(
        "--inject-traffic-mb",
        type=float,
        default=0.0,
        help="self-test: add a real compiled executable's cost-model "
        "bytes over an array of this many MiB to the step's bytes",
    )
    ap.add_argument(
        "--warm-start-arm",
        action="store_true",
        help="run ONLY the persistent-exec-cache cold/warm gate "
        "(self-contained ratio; no committed baseline)",
    )
    args = ap.parse_args()

    if args.warm_start_arm:
        return _warm_start_arm()

    cur = _measure(args.inject_slowdown_ms, args.steps, args.inject_traffic_mb)
    key = f"{cur['backend']}:{cur['device_kind']}"
    print(
        f"bench gate [{key}]: {cur['graphs_per_sec']} graphs/sec "
        f"(step {cur['step_ms_median']} ms, segments "
        f"{cur['step_ms_segments']}, mfu {cur['mfu']}, "
        f"bytes/step {cur['bytes_per_step_costmodel']})"
    )

    baselines = {}
    if os.path.exists(args.baseline):
        with open(args.baseline) as f:
            baselines = json.load(f)
    base = baselines.get(key)

    if base is None or args.update_baseline:
        if args.inject_slowdown_ms > 0 or args.inject_traffic_mb > 0:
            print("bench gate: refusing to record a baseline with an "
                  "injected slowdown/traffic")
            return 1
        baselines[key] = {
            "graphs_per_sec": cur["graphs_per_sec"],
            "step_ms_median": cur["step_ms_median"],
            "mfu": cur["mfu"],
            "steps": cur["steps"],
            "bytes_per_step_costmodel": cur["bytes_per_step_costmodel"],
        }
        if cur.get("conv_traffic_model"):
            baselines[key]["conv_traffic_model"] = cur["conv_traffic_model"]
        with open(args.baseline, "w") as f:
            json.dump(baselines, f, indent=1, sort_keys=True)
            f.write("\n")
        print(
            f"bench gate: {'updated' if base else 'recorded new'} baseline "
            f"for {key} -> {args.baseline} (commit it)"
        )
        return 0

    floor = base["graphs_per_sec"] * (1.0 - args.tolerance)
    failures = []
    if cur["graphs_per_sec"] < floor:
        failures.append(
            f"graphs/sec {cur['graphs_per_sec']} < {floor:.2f} "
            f"(baseline {base['graphs_per_sec']} - {args.tolerance:.0%})"
        )
    if cur["mfu"] is not None and base.get("mfu"):
        mfu_floor = base["mfu"] * (1.0 - args.tolerance)
        if cur["mfu"] < mfu_floor:
            failures.append(
                f"MFU {cur['mfu']} < {mfu_floor:.5f} "
                f"(baseline {base['mfu']} - {args.tolerance:.0%})"
            )
    # traffic arm: cost-model bytes/step are deterministic per build —
    # MORE than tolerance above baseline is a regression (a build that
    # re-materializes a fused intermediate shows up here even when a
    # tiny CI box can't resolve it as wall time)
    if cur.get("bytes_per_step_costmodel") and base.get("bytes_per_step_costmodel"):
        ceil_b = base["bytes_per_step_costmodel"] * (1.0 + args.tolerance)
        if cur["bytes_per_step_costmodel"] > ceil_b:
            failures.append(
                f"cost-model bytes/step {cur['bytes_per_step_costmodel']} > "
                f"{ceil_b:.0f} (baseline {base['bytes_per_step_costmodel']} "
                f"+ {args.tolerance:.0%})"
            )
    if failures:
        for msg in failures:
            print(f"bench gate FAIL: {msg}")
        return 2
    print(
        f"bench gate OK: within {args.tolerance:.0%} of baseline "
        f"{base['graphs_per_sec']} graphs/sec"
        + (
            f" (and MFU baseline {base['mfu']})"
            if cur["mfu"] is not None and base.get("mfu")
            else ""
        )
    )
    if cur["graphs_per_sec"] > base["graphs_per_sec"] * (1.0 + args.tolerance):
        print(
            "bench gate: current throughput exceeds baseline by more than "
            "the tolerance — consider --update-baseline (and commit it) so "
            "the gate guards the new level"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
