#!/usr/bin/env python3
"""graftsync — run the repo's thread-safety/lock-discipline analyzer
(docs/LINT.md, HS rules).

Usage:
    python tools/graftsync.py                       # full tree, all rules
    python tools/graftsync.py --changed             # fast pre-commit loop
    python tools/graftsync.py --rule HS003 --strict hydragnn_tpu/serve
    python tools/graftsync.py --order-graph -       # static lock-order graph
    python tools/graftsync.py --list-rules

Exit codes: 0 clean, 1 findings, 2 usage/internal error.

Like tools/graftlint.py, the lint package is loaded standalone
(importlib, not ``import hydragnn_tpu``): the package root pulls in
jax-adjacent subpackages, and the analyzer must run in milliseconds on
any container with a bare CPython.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint_pkg():
    """Load ``hydragnn_tpu.lint`` as a standalone package named
    ``_graftsync`` so relative imports inside it resolve without ever
    executing ``hydragnn_tpu/__init__.py``."""
    pkg_dir = os.path.join(REPO_ROOT, "hydragnn_tpu", "lint")
    spec = importlib.util.spec_from_file_location(
        "_graftsync",
        os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    pkg = importlib.util.module_from_spec(spec)
    sys.modules["_graftsync"] = pkg
    spec.loader.exec_module(pkg)
    core = importlib.import_module("_graftsync.core")
    concurrency = importlib.import_module("_graftsync.concurrency")
    return core, concurrency


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftsync", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to analyze (default: the whole tree)",
    )
    parser.add_argument(
        "--rule",
        action="append",
        default=None,
        metavar="HSNNN",
        help="run only this rule id (repeatable)",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="exit nonzero on any finding regardless of severity",
    )
    parser.add_argument(
        "--json",
        metavar="PATH",
        default=None,
        help="also write findings as JSON ('-' for stdout)",
    )
    parser.add_argument(
        "--baseline",
        metavar="PATH",
        default=os.path.join("tools", "graftsync_baseline.json"),
        help="baseline file of grandfathered findings "
        "(default: tools/graftsync_baseline.json)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline file",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--changed",
        action="store_true",
        help="analyze only files git reports as changed vs HEAD",
    )
    parser.add_argument(
        "--order-graph",
        metavar="PATH",
        default=None,
        help="dump the static lock-order graph as JSON ('-' for stdout) "
        "and exit (the runtime witness asserts against this graph)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    core, concurrency = _load_lint_pkg()
    all_rules = concurrency.concurrency_rules(REPO_ROOT)

    if args.list_rules:
        for rule in all_rules:
            print(f"{rule.id}  {rule.name:40s} [{rule.severity}] "
                  f"{rule.description}")
        return 0

    if args.order_graph is not None:
        graph = concurrency.build_lock_order(REPO_ROOT, args.paths or None)
        payload = json.dumps(graph, indent=2)
        if args.order_graph == "-":
            print(payload)
        else:
            with open(args.order_graph, "w") as f:
                f.write(payload + "\n")
            print(
                f"graftsync: wrote lock-order graph "
                f"({len(graph['locks'])} locks, {len(graph['edges'])} "
                f"edges) to {args.order_graph}"
            )
        return 0

    rules = all_rules
    if args.rule:
        wanted = {r.upper() for r in args.rule}
        rules = [r for r in all_rules if r.id in wanted]
        unknown = wanted - {r.id for r in all_rules}
        if unknown:
            print(f"graftsync: unknown rule id(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    paths = args.paths or None
    if args.changed:
        paths = core.changed_paths(REPO_ROOT)
        if not paths:
            print("graftsync: no changed python files")
            return 0

    baseline = None if (args.no_baseline or args.write_baseline) else (
        args.baseline
        if os.path.isabs(args.baseline)
        else os.path.join(REPO_ROOT, args.baseline)
    )
    # full_tree=True even for path-restricted scans: HS006's cycle
    # detection is an aggregate that must run on whatever was scanned
    findings = core.run_lint(
        REPO_ROOT, rules, paths=paths, baseline=baseline, full_tree=True
    )

    if args.write_baseline:
        out = (
            args.baseline
            if os.path.isabs(args.baseline)
            else os.path.join(REPO_ROOT, args.baseline)
        )
        core.write_baseline(out, findings, tool="graftsync")
        print(f"graftsync: wrote {len(findings)} finding(s) to {out}")
        return 0

    for f in findings:
        print(f.render())
    _emit_json(args.json, findings)
    errors = [f for f in findings if f.severity == "error"]
    if (args.strict and findings) or errors:
        print(
            f"graftsync: {len(findings)} finding(s) "
            f"({len(errors)} error(s))"
        )
        return 1
    if findings:
        print(f"graftsync: {len(findings)} warning(s) (non-strict: ok)")
    else:
        print("graftsync: clean")
    return 0


def _emit_json(dest, findings) -> None:
    if not dest:
        return
    payload = json.dumps(
        {"version": 1, "count": len(findings),
         "findings": [f.to_json() for f in findings]},
        indent=2,
    )
    if dest == "-":
        print(payload)
    else:
        with open(dest, "w") as f:
            f.write(payload + "\n")


if __name__ == "__main__":
    sys.exit(main())
