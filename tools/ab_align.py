"""A/B the flagship PNA step: current CSR layout vs the run-aligned
layout (graph/batch.py run_align), interleaved in one process.

Usage: python tools/ab_align.py [steps_per_arm] [K]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

t0 = time.time()


def log(msg):
    print(f"[{time.time()-t0:7.1f}s] {msg}", flush=True)


from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.utils.config import update_config
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 8
K = int(sys.argv[2]) if len(sys.argv) > 2 else 8
BATCH = 1024

config = flagship_config(128, 6, BATCH)
samples = deterministic_graph_data(
    number_configurations=1280,
    unit_cell_x_range=(2, 4),
    unit_cell_y_range=(2, 4),
    unit_cell_z_range=(2, 4),
    seed=0,
)
train, val, test, _, _ = prepare_dataset(samples, config)
config = update_config(config, train, val, test)
log(f"dataset ready: {len(train)} train samples")

arms = {}
for name, ra in (("plain", False), (f"align{K}", K)):
    loader = GraphLoader(
        train, BATCH, shuffle=True, drop_last=True, dense_slots=None, run_align=ra
    )
    batches = list(loader)
    arms[name] = batches
    b = batches[0]
    log(f"{name}: edge_pad={b.senders.shape[0]} run_align={b.run_align}")

tx = select_optimizer(config["NeuralNetwork"]["Training"])
model, variables = create_model_config(config["NeuralNetwork"], arms["plain"][0])
state0 = create_train_state(variables, tx)
step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)

compiled = {}
for name, batches in arms.items():
    compiled[name] = step.lower(state0, batches[0]).compile()
    log(f"{name}: compiled")

states = {name: jax.tree_util.tree_map(jnp.copy, state0) for name in arms}
losses = {}
for name, batches in arms.items():
    states[name], loss, _ = compiled[name](states[name], batches[0])
    losses[name] = float(np.asarray(loss))
log(f"warmup losses: {losses}")

KSEG = 4
results = {name: [] for name in arms}
seg = 0
while seg * KSEG < STEPS:
    for name, batches in arms.items():
        t1 = time.perf_counter()
        for i in range(KSEG):
            states[name], loss, _ = compiled[name](
                states[name], batches[(seg * KSEG + i) % len(batches)]
            )
        np.asarray(loss)
        results[name].append((time.perf_counter() - t1) / KSEG * 1e3)
    seg += 1

for name, ts in results.items():
    med = sorted(ts)[len(ts) // 2]
    print(
        f"{name}: step_ms segments={['%.1f' % t for t in ts]} median={med:.1f} "
        f"graphs/sec={BATCH / med * 1e3:.0f}"
    )
