"""Aggregate /tmp/hlo_stats.csv (tools/parse_trace.py output) into a
per-component time/bytes table.

Prints (a) top-K ops by total self time, (b) category rollup, and
(c) trace-measured HBM bytes per step — self_time x measured BW summed
over ops — the measurement that replaces cost-model bytes in bench.py
(VERDICT r03 Weak #2).

Usage: python tools/analyze_hlo_stats.py [/tmp/hlo_stats.csv] [n_steps] [n_top]
"""

import csv
import json
import re
import sys
from collections import defaultdict

_ITEMSIZE = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
    "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
}

_SHAPE_RE = re.compile(
    r"\b(f64|f32|bf16|f16|s64|u64|s32|u32|s16|u16|s8|u8|pred)\[([\d,]*)\]"
)


def _customcall_bytes(expr: str) -> float:
    """Operand+result sizes of a custom-call (Pallas kernel). xprof
    reports no memory BW for custom-calls, so their DMA traffic is
    invisible to the measured total; the CSR kernels stream each
    operand exactly once by construction, so the static shape sum is
    a sound per-op estimate (window-looping chunks can re-read table
    rows, making this a slight UNDER-estimate on jumpy ids)."""
    head = expr.split("custom_call_target", 1)[0]
    total = 0.0
    for dt, dims in _SHAPE_RE.findall(head):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _ITEMSIZE[dt]
    return total


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/hlo_stats.csv"
    n_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    n_top = int(sys.argv[3]) if len(sys.argv) > 3 else 30

    raw = open(path).read()
    rows = []
    if raw.lstrip().startswith("{"):  # gviz JSON despite the .csv name
        tab = json.loads(raw)
        cols = [c["id"] for c in tab["cols"]]
        dicts = [
            {cols[i]: (cell or {}).get("v") for i, cell in enumerate(row["c"])}
            for row in tab["rows"]
        ]
    else:
        dicts = list(csv.DictReader(raw.splitlines()))
    for r in dicts:
        try:
            t_us = float(r.get("total_self_time", 0) or 0)
        except ValueError:
            continue
        if t_us <= 0:
            continue
        bw = float(r.get("measured_memory_bw", 0) or 0)  # GiB/s
        full_expr = str(r.get("hlo_op_expression", "") or "")
        cat = str(r.get("category", ""))
        rows.append(
            {
                "op": str(r.get("hlo_op_name", "")),
                "cat": cat,
                "tf": str(r.get("tf_op_name", "")),
                "n": int(float(r.get("occurrences", 1) or 1)),
                "us": t_us,
                "bytes": bw * (2**30) * (t_us / 1e6),
                "kbytes": _customcall_bytes(full_expr)
                * int(float(r.get("occurrences", 1) or 1))
                if cat == "custom-call"
                else 0.0,
                "bound": str(r.get("bound_by", "")),
                "expr": full_expr[:160],
            }
        )

    if not rows:
        raise SystemExit(f"no rows with positive self time parsed from {path}")
    tot_ms = sum(r["us"] for r in rows) / 1e3
    tot_bytes = sum(r["bytes"] for r in rows)
    kernel_bytes = sum(r["kbytes"] for r in rows)
    print(f"total device self time: {tot_ms:.1f} ms over {n_steps} steps "
          f"-> {tot_ms / n_steps:.1f} ms/step")
    print(f"trace-measured HBM traffic: {tot_bytes / 1e9:.2f} GB "
          f"-> {tot_bytes / n_steps / 1e9:.2f} GB/step "
          f"-> {tot_bytes / (tot_ms / 1e3) / 1e9:.1f} GB/s average")
    if kernel_bytes:
        comb = tot_bytes + kernel_bytes
        print(
            f"custom-call (Pallas) traffic, est. from operand+result "
            f"shapes (invisible to xprof BW counters): "
            f"{kernel_bytes / n_steps / 1e9:.2f} GB/step -> combined "
            f"{comb / n_steps / 1e9:.2f} GB/step = "
            f"{comb / (tot_ms / 1e3) / 1e9:.1f} GB/s average"
        )
    print()

    print(f"== top {n_top} ops by self time (ms/step) ==")
    for r in sorted(rows, key=lambda r: -r["us"])[:n_top]:
        print(
            f"{r['us']/1e3/n_steps:8.2f} ms {r['bytes']/n_steps/1e9:7.2f} GB "
            f"{r['cat'][:18]:18s} {r['bound'][:10]:10s} {r['op'][:28]:28s} "
            f"{r['tf'][:70]}"
        )

    print()
    print("== category rollup (ms/step) ==")
    cats = defaultdict(lambda: [0.0, 0.0, 0])
    for r in rows:
        c = cats[r["cat"]]
        c[0] += r["us"]
        c[1] += r["bytes"]
        c[2] += r["n"]
    for name, (us, b, n) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        print(f"{us/1e3/n_steps:8.2f} ms {b/n_steps/1e9:7.2f} GB  n={n:5d}  {name}")

    out = {
        "ms_per_step": tot_ms / n_steps,
        "measured_bytes_per_step": tot_bytes / n_steps,
        "measured_hbm_gbps": tot_bytes / (tot_ms / 1e3) / 1e9,
        "kernel_bytes_est_per_step": kernel_bytes / n_steps,
        "combined_hbm_gbps_est": (tot_bytes + kernel_bytes)
        / (tot_ms / 1e3)
        / 1e9,
        "n_steps": n_steps,
    }
    with open("/tmp/hlo_summary.json", "w") as f:
        json.dump(out, f)
    print("\nwrote /tmp/hlo_summary.json:", json.dumps(out))


if __name__ == "__main__":
    main()
