"""Aggregate /tmp/hlo_stats.csv (tools/parse_trace.py output) into a
per-component time/bytes table.

Prints (a) top-K ops by total self time, (b) category rollup, and
(c) trace-measured HBM bytes per step — self_time x measured BW summed
over ops — the measurement that replaces cost-model bytes in bench.py
(VERDICT r03 Weak #2).

Usage: python tools/analyze_hlo_stats.py [/tmp/hlo_stats.csv] [n_steps] [n_top]
"""

import csv
import json
import sys
from collections import defaultdict


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/hlo_stats.csv"
    n_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    n_top = int(sys.argv[3]) if len(sys.argv) > 3 else 30

    raw = open(path).read()
    rows = []
    if raw.lstrip().startswith("{"):  # gviz JSON despite the .csv name
        tab = json.loads(raw)
        cols = [c["id"] for c in tab["cols"]]
        dicts = [
            {cols[i]: (cell or {}).get("v") for i, cell in enumerate(row["c"])}
            for row in tab["rows"]
        ]
    else:
        dicts = list(csv.DictReader(raw.splitlines()))
    for r in dicts:
        try:
            t_us = float(r.get("total_self_time", 0) or 0)
        except ValueError:
            continue
        if t_us <= 0:
            continue
        bw = float(r.get("measured_memory_bw", 0) or 0)  # GiB/s
        rows.append(
            {
                "op": str(r.get("hlo_op_name", "")),
                "cat": str(r.get("category", "")),
                "tf": str(r.get("tf_op_name", "")),
                "n": int(float(r.get("occurrences", 1) or 1)),
                "us": t_us,
                "bytes": bw * (2**30) * (t_us / 1e6),
                "bound": str(r.get("bound_by", "")),
                "expr": str(r.get("hlo_op_expression", "") or "")[:160],
            }
        )

    if not rows:
        raise SystemExit(f"no rows with positive self time parsed from {path}")
    tot_ms = sum(r["us"] for r in rows) / 1e3
    tot_bytes = sum(r["bytes"] for r in rows)
    print(f"total device self time: {tot_ms:.1f} ms over {n_steps} steps "
          f"-> {tot_ms / n_steps:.1f} ms/step")
    print(f"trace-measured HBM traffic: {tot_bytes / 1e9:.2f} GB "
          f"-> {tot_bytes / n_steps / 1e9:.2f} GB/step "
          f"-> {tot_bytes / (tot_ms / 1e3) / 1e9:.1f} GB/s average")
    print()

    print(f"== top {n_top} ops by self time (ms/step) ==")
    for r in sorted(rows, key=lambda r: -r["us"])[:n_top]:
        print(
            f"{r['us']/1e3/n_steps:8.2f} ms {r['bytes']/n_steps/1e9:7.2f} GB "
            f"{r['cat'][:18]:18s} {r['bound'][:10]:10s} {r['op'][:28]:28s} "
            f"{r['tf'][:70]}"
        )

    print()
    print("== category rollup (ms/step) ==")
    cats = defaultdict(lambda: [0.0, 0.0, 0])
    for r in rows:
        c = cats[r["cat"]]
        c[0] += r["us"]
        c[1] += r["bytes"]
        c[2] += r["n"]
    for name, (us, b, n) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        print(f"{us/1e3/n_steps:8.2f} ms {b/n_steps/1e9:7.2f} GB  n={n:5d}  {name}")

    out = {
        "ms_per_step": tot_ms / n_steps,
        "measured_bytes_per_step": tot_bytes / n_steps,
        "measured_hbm_gbps": tot_bytes / (tot_ms / 1e3) / 1e9,
        "n_steps": n_steps,
    }
    with open("/tmp/hlo_summary.json", "w") as f:
        json.dump(out, f)
    print("\nwrote /tmp/hlo_summary.json:", json.dumps(out))


if __name__ == "__main__":
    main()
