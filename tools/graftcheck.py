#!/usr/bin/env python3
"""graftcheck — the compiled-IR contract checker (docs/LINT.md CC rules).

Lowers the repo's hot entry points (train step, scan epoch, eval/stats
steps, serve bucket ladder, bf16 conv forward) under the named
Partitioner layouts and audits the StableHLO / post-SPMD HLO for the
six CC contracts (hydragnn_tpu/lint/ir.py). Where graftlint proves the
SOURCE, graftcheck proves the EXECUTABLE — on any container, for any
backend target, without running a single step.

Usage:
    python tools/graftcheck.py                         # dp + fsdp2, all contracts
    python tools/graftcheck.py --layout dp             # one layout
    python tools/graftcheck.py --contract CC001 --contract CC005
    python tools/graftcheck.py --json /tmp/graftcheck.json
    python tools/graftcheck.py --list-contracts

Exit codes: 0 clean, 1 findings, 2 usage/internal error.

Self-test: HYDRAGNN_INJECT_GRAFTCHECK=cc003 plants a layout-mismatched
collective (and cc001/cc002/cc004/cc005/cc006 their own violations);
ci.sh asserts each contract individually rejects its injection.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The audit reasons about an 8-device mesh the way CI does (ci.sh
# partitioner smoke): pin the forced host platform BEFORE jax loads.
# A real accelerator run would hide host-platform forcing behind the
# backend, so only force when nothing else chose a platform.
if "JAX_PLATFORMS" not in os.environ:
    os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, REPO_ROOT)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftcheck", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "--layout",
        action="append",
        default=None,
        metavar="NAME",
        help="named Partitioner layout to audit (dp | fsdp2; repeatable; "
        "default: HYDRAGNN_GRAFTCHECK_LAYOUTS)",
    )
    parser.add_argument(
        "--contract",
        action="append",
        default=None,
        metavar="CCNNN",
        help="run only this contract (repeatable; default: all six)",
    )
    parser.add_argument(
        "--json",
        metavar="PATH",
        default=None,
        help="also write findings as JSON ('-' for stdout)",
    )
    parser.add_argument(
        "--baseline",
        metavar="PATH",
        default=os.path.join("tools", "graftcheck_baseline.json"),
        help="baseline file of grandfathered findings "
        "(default: tools/graftcheck_baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true", help="ignore the baseline file"
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--list-contracts", action="store_true", help="print the CC catalog"
    )
    args = parser.parse_args(argv)

    from hydragnn_tpu.lint import ir
    from hydragnn_tpu.lint.core import load_baseline, write_baseline
    from hydragnn_tpu.utils import knobs

    if args.list_contracts:
        for cid, (name, desc) in ir.CONTRACTS.items():
            print(f"{cid}  {name:24s} {desc}")
        return 0

    layouts = args.layout or [
        t.strip()
        for t in knobs.get_str("HYDRAGNN_GRAFTCHECK_LAYOUTS", "dp,fsdp2").split(",")
        if t.strip()
    ]
    contracts = None
    if args.contract:
        contracts = {c.upper() for c in args.contract}
        unknown = contracts - set(ir.CONTRACTS)
        if unknown:
            print(
                f"graftcheck: unknown contract id(s): {sorted(unknown)}",
                file=sys.stderr,
            )
            return 2

    try:
        findings = ir.run_graftcheck(layouts=layouts, contracts=contracts)
    except ValueError as exc:
        print(f"graftcheck: {exc}", file=sys.stderr)
        return 2

    baseline_path = (
        args.baseline
        if os.path.isabs(args.baseline)
        else os.path.join(REPO_ROOT, args.baseline)
    )
    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"graftcheck: wrote {len(findings)} finding(s) to {baseline_path}")
        return 0
    if not args.no_baseline:
        grandfathered = load_baseline(baseline_path)
        if grandfathered:
            findings = [
                f for f in findings if f.fingerprint() not in grandfathered
            ]

    for f in findings:
        print(f.render())
    if args.json:
        payload = json.dumps(
            {
                "version": ir.SCHEMA_VERSION,
                "layouts": layouts,
                "count": len(findings),
                "findings": [f.to_json() for f in findings],
            },
            indent=2,
        )
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as fh:
                fh.write(payload + "\n")
    if findings:
        print(f"graftcheck: {len(findings)} contract violation(s)")
        return 1
    scope = ",".join(sorted(contracts)) if contracts else "CC001-CC006"
    print(f"graftcheck: clean ({scope} over {'+'.join(layouts)} + global)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
