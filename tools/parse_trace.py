"""Parse an xplane trace dir into a per-HLO-op time table (via the
xprof converter's hlo_stats tool; the tensorboard-plugin-profile
converter in this image has a protobuf mismatch, xprof's works).

Usage: python tools/parse_trace.py /tmp/tb_flagship [n_top]
"""

import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tb_flagship"
    n_top = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    planes = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    if not planes:
        raise SystemExit(f"no xplane files under {trace_dir}")

    try:
        from xprof.convert import raw_to_tool_data as rd
    except ImportError:  # the tb-plugin converter has a protobuf mismatch here
        from tensorboard_plugin_profile.convert import raw_to_tool_data as rd

    params = {"tqx": "out:csv;"}
    for tool in ("hlo_stats", "framework_op_stats"):
        try:
            data, _ = rd.xspace_to_tool_data(planes, tool, params)
        except Exception as e:
            print(f"{tool}: FAILED {e!r}")
            continue
        if isinstance(data, bytes):
            data = data.decode("utf-8", "replace")
        out = f"/tmp/{tool}.csv"
        with open(out, "w") as f:
            f.write(data)
        print(f"{tool}: wrote {out} ({len(data)} bytes)")
        lines = data.splitlines()
        print(lines[0] if lines else "(empty)")
        break
    else:
        # fallback: raw xplane decode via xprof protos
        try:
            from xprof.protobuf import xplane_pb2  # type: ignore
        except ImportError:
            from tensorboard_plugin_profile.protobuf import xplane_pb2  # type: ignore

        import collections

        tot = collections.Counter()
        for p in planes:
            xs = xplane_pb2.XSpace()
            xs.ParseFromString(open(p, "rb").read())
            for plane in xs.planes:
                if "TPU" not in plane.name and "Device" not in plane.name:
                    continue
                ev_names = {k: v for k, v in plane.event_metadata.items()}
                for line in plane.lines:
                    for ev in line.events:
                        md = ev_names.get(ev.metadata_id)
                        name = md.name if md else str(ev.metadata_id)
                        tot[name] += ev.duration_ps
        for name, ps in tot.most_common(n_top):
            print(f"{ps/1e9:10.3f} ms  {name}")


if __name__ == "__main__":
    main()
