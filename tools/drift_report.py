"""Drift reporter: render and validate the served-traffic drift trail.

The serving drift plane (hydragnn_tpu/obs/drift.py + obs/spool.py)
leaves three kinds of evidence behind: the ``drift`` / ``spool_rotate``
events in a serve flight record, the rotating HGC request-spool shards
on disk, and the ``drift_report.json`` sidecar a drift incident bundle
carries. This tool is the human view over all three — the first page
of a "did my traffic move?" post-mortem:

    python tools/drift_report.py logs/serve/flight.jsonl      # flight
    python tools/drift_report.py logs/serve/spool             # spool
    python tools/drift_report.py .../i001-x/drift_report.json # sidecar
    python tools/drift_report.py --validate <any of the above>
    python tools/drift_report.py --export-ref logs/train/flight.jsonl \
        --out ref.json   # extract the training reference window

Arguments dispatch by shape: a ``*.jsonl`` path is a flight record, a
``*.json`` path is a drift-report sidecar, a directory is a spool
root. ``--validate`` exits 1 when any spool-shard manifest fails its
schema, a drift report fails its schema, or a flight record lacks the
``run_start.manifest.stats`` reference block a drift-armed server
would need.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as `python tools/drift_report.py`
    sys.path.insert(0, _REPO)

from hydragnn_tpu.obs.drift import (  # noqa: E402
    load_reference,
    validate_drift_report,
)
from hydragnn_tpu.obs.flight import read_flight_record  # noqa: E402
from hydragnn_tpu.obs.spool import (  # noqa: E402
    list_shards,
    read_shard_manifest,
    validate_spool_manifest,
)

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float]) -> str:
    """Eight-level unicode trend strip; constant series render flat."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi - lo <= 0:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / (hi - lo) * len(_SPARK)))]
        for v in vals
    )


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


# -- flight-record view -------------------------------------------------------


def render_flight(path: str) -> str:
    """The drift story one serve flight record tells: armed config,
    breach events, rotation cadence, end-of-run sketch summary."""
    events = read_flight_record(path)
    lines = [f"== drift trail: {path} =="]
    start = next((e for e in events if e.get("kind") == "run_start"), {})
    man = start.get("manifest") or {}
    spool, drift = man.get("spool") or {}, man.get("drift") or {}
    lines.append(
        f"  spool: {'on' if spool.get('enabled') else 'off'}"
        + (
            f" dir={spool.get('dir')} 1/{spool.get('sample_every')}"
            f" max={spool.get('max_mb')}MB"
            if spool.get("enabled")
            else ""
        )
    )
    if drift.get("armed"):
        th = drift.get("thresholds") or {}
        lines.append(
            f"  drift: armed ref={drift.get('ref')}"
            f" channels={drift.get('channels')}"
            f" thresholds={{{', '.join(f'{k}={v}' for k, v in sorted(th.items()))}}}"
        )
    else:
        lines.append("  drift: not armed")
    rotations = [e for e in events if e.get("kind") == "spool_rotate"]
    if rotations:
        lines.append(
            f"  rotations: {len(rotations)}  samples/shard "
            + sparkline([e.get("samples", 0) for e in rotations])
            + f"  last={rotations[-1].get('shard')}"
        )
    breaches = [e for e in events if e.get("kind") == "drift"]
    lines.append(f"  breaches: {len(breaches)}")
    for e in breaches:
        window = e.get("spool_window") or {}
        lines.append(
            f"    [{e.get('rule_kind')}] {e.get('rule')}:"
            f" observed {_fmt(e.get('observed'))}"
            f" vs threshold {_fmt(e.get('threshold'))}"
            f" spool={window.get('dir') or '<off>'}"
        )
    end = next(
        (e for e in reversed(events) if e.get("kind") == "run_end"), {}
    )
    for block in ("spool", "drift"):
        data = end.get(block)
        if isinstance(data, dict):
            lines.append(
                f"  run_end {block}: "
                + " ".join(f"{k}={_fmt(v)}" for k, v in sorted(data.items()))
            )
    return "\n".join(lines)


# -- spool view ---------------------------------------------------------------


def _shard_feature_means(shard: str) -> Optional[float]:
    """Mean of channel 0 of x over one shard — the per-shard trend
    point (import deferred: the container reader pulls in jax)."""
    from hydragnn_tpu.data.container import ContainerDataset

    try:
        import numpy as np

        ds = ContainerDataset(shard)
        vals = [float(np.asarray(s.x).mean()) for s in ds.samples()]
        return sum(vals) / len(vals) if vals else None
    except Exception:
        return None


def render_spool(root: str, *, trend: bool = True) -> str:
    """Shard table (chronological) + tenant breakdown + per-shard
    feature-mean sparkline — how the spooled traffic moved over time."""
    shards = list_shards(root)
    lines = [f"== request spool: {root} ({len(shards)} shard(s)) =="]
    if not shards:
        return "\n".join(lines + ["  (empty)"])
    tenants: Dict[str, int] = {}
    fps = set()
    for shard in shards:
        man = read_shard_manifest(shard) or {}
        for t in man.get("tenants") or []:
            tenants[t] = tenants.get(t, 0) + man.get("num_samples", 0)
        fps.add(man.get("model_fingerprint", "?"))
        seq = man.get("seq_range") or ["?", "?"]
        lines.append(
            f"  {os.path.basename(shard)}: {man.get('num_samples', '?')} samples"
            f"  seq [{seq[0]}..{seq[-1]}]"
            f"  tenants={','.join(man.get('tenants') or ['?'])}"
        )
    lines.append(
        "  tenants: "
        + " ".join(f"{t}={n}" for t, n in sorted(tenants.items()))
    )
    lines.append(f"  model fingerprints: {len(fps)}")
    if trend:
        means = [_shard_feature_means(s) for s in shards]
        known = [m for m in means if m is not None]
        if known:
            lines.append(
                "  feature mean/shard: "
                + sparkline(known)
                + f"  [{_fmt(min(known))} .. {_fmt(max(known))}]"
            )
    return "\n".join(lines)


# -- drift-report sidecar view ------------------------------------------------


def render_report(path: str) -> str:
    """Per-channel / per-head tables for one ``drift_report.json``."""
    with open(path) as f:
        report = json.load(f)
    lines = [f"== drift report: {path} =="]
    trig = report.get("trigger") or {}
    if trig:
        lines.append(
            f"  trigger: {trig.get('rule')} ({trig.get('kind')})"
            f" observed {_fmt(trig.get('observed'))}"
            f" vs threshold {_fmt(trig.get('threshold'))}"
        )
    counts = report.get("counts") or {}
    lines.append(
        "  rows: " + " ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    )
    feature = report.get("feature") or {}
    lines.append(
        f"  feature psi_max={_fmt(feature.get('psi_max'))}"
        f" qshift_max={_fmt(feature.get('qshift_max'))}"
    )
    for ch in feature.get("channels") or []:
        lines.append(
            f"    ch{ch.get('channel')}: psi={_fmt(ch.get('psi'))}"
            f" qshift={_fmt(ch.get('qshift'))}"
            f" mean {_fmt(ch.get('mean'))} (ref {_fmt(ch.get('ref_mean'))})"
            f" std {_fmt(ch.get('std'))} (ref {_fmt(ch.get('ref_std'))})"
        )
        cnt = ch.get("counts") or []
        if cnt:
            lines.append("      live bins: " + sparkline(cnt))
    heads = report.get("heads") or {}
    for name, h in sorted(heads.items()):
        lines.append(
            f"    head {name}: psi={_fmt(h.get('psi'))}"
            f" mean={_fmt(h.get('mean'))} rows={h.get('rows')}"
        )
    scores = (report.get("error") or {}).get("scores") or {}
    if scores:
        lines.append(
            "  error scores: "
            + " ".join(f"{k}={_fmt(v)}" for k, v in sorted(scores.items()))
        )
    window = report.get("spool_window") or {}
    if window:
        lines.append(
            f"  spool window: dir={window.get('dir')}"
            f" shards={len(window.get('shards') or [])}"
            f" last={window.get('last_shard')}"
        )
    return "\n".join(lines)


# -- validation ---------------------------------------------------------------


def validate_path(path: str) -> List[str]:
    """Problems for one argument (empty == valid), dispatched by shape
    exactly like rendering."""
    problems: List[str] = []
    if os.path.isdir(path):
        shards = list_shards(path)
        if not shards:
            problems.append(f"{path}: no spool shards")
        for shard in shards:
            man = read_shard_manifest(shard)
            if man is None:
                problems.append(f"{shard}: missing/unreadable spool manifest")
                continue
            problems.extend(f"{shard}: {p}" for p in validate_spool_manifest(man))
    elif path.endswith(".jsonl"):
        # A flight record passes if it is usable by the drift plane:
        # either a TRAINING flight carrying the reference stats block,
        # or a serve flight whose manifest shows the plane was armed.
        events = read_flight_record(path)
        start = next((e for e in events if e.get("kind") == "run_start"), {})
        man = start.get("manifest") or {}
        armed = bool(
            (man.get("drift") or {}).get("armed")
            or (man.get("spool") or {}).get("enabled")
        )
        if not armed:
            try:
                load_reference(path)
            except (OSError, ValueError) as exc:
                problems.append(f"{path}: {exc}")
    else:
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError) as exc:
            problems.append(f"{path}: unreadable ({exc})")
        else:
            problems.extend(f"{path}: {p}" for p in validate_drift_report(report))
    return problems


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument(
        "paths", nargs="+",
        help="serve flight.jsonl, spool dir, or drift_report.json",
    )
    p.add_argument(
        "--validate", action="store_true",
        help="schema-check instead of rendering; exit 1 on problems",
    )
    p.add_argument(
        "--export-ref", action="store_true",
        help="extract the training reference window from a flight "
        "record and write it as bare stats JSON (see --out)",
    )
    p.add_argument("--out", help="output path for --export-ref")
    p.add_argument(
        "--no-trend", action="store_true",
        help="skip the per-shard feature trend (avoids loading shards)",
    )
    args = p.parse_args(argv)

    if args.export_ref:
        if not args.out:
            p.error("--export-ref requires --out")
        ref = load_reference(args.paths[0])
        with open(args.out, "w") as f:
            json.dump(ref, f, indent=1, sort_keys=True)
        print(f"wrote reference ({ref.get('num_rows')} rows) to {args.out}")
        return 0

    rc = 0
    for path in args.paths:
        if args.validate:
            problems = validate_path(path)
            if problems:
                rc = 1
                print(f"{path}: INVALID ({len(problems)} problem(s))")
                for prob in problems:
                    print(f"  - {prob}")
            else:
                print(f"{path}: OK")
        elif os.path.isdir(path):
            print(render_spool(path, trend=not args.no_trend))
        elif path.endswith(".jsonl"):
            print(render_flight(path))
        else:
            print(render_report(path))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
