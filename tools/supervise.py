"""Bounded restart supervisor CLI — keep a training command alive
through preemptions and crashes, without looping on a run that can
never succeed (hydragnn_tpu/resilience/supervisor.py,
docs/RESILIENCE.md):

    python tools/supervise.py [options] -- python my_train_driver.py ...

The child should wrap its ``run_training`` call in
``hydragnn_tpu.resilience.run_guard()`` so its exits follow the code
contract the supervisor classifies:

    0   completed            done
    75  preempted            restart promptly (HYDRAGNN_AUTO_RESUME=1)
    76  rollback exhausted   FAIL FAST (deterministic non-finite run)
    78  config error         FAIL FAST
    79  hung (watchdog)      retry with backoff
    *   crash / signal       retry with exponential backoff

Restarted children get ``HYDRAGNN_AUTO_RESUME=1`` and (by default) the
``HYDRAGNN_INJECT_*`` fault-injection vars stripped. ``--flight`` writes
the supervisor's own flight record (one ``restart`` event per
re-invocation + a terminal ``run_end``) next to the run's.

``--pod N`` supervises the command as a pod of N concurrent simulated
hosts instead of one process: each child gets its podview identity
(``HYDRAGNN_PODVIEW_HOST=k`` / ``_HOSTS=N``), the pod lives and dies as
one unit, and a SIGNAL-dead host (SIGKILL, OOM, dead machine) is
classified ``host_lost`` — preempt-class, restarted promptly from the
last committed pod-checkpoint generation. ``--pod-elastic`` restarts
with N-1 hosts after a loss (the restore re-shards the committed
generation across the smaller pod).

The supervisor's own exit code is the FINAL child exit code (0 when the
run completed), so wrapping scripts compose.
"""

from __future__ import annotations

import json
import os
import sys

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as `python tools/supervise.py`
    sys.path.insert(0, _REPO)

from hydragnn_tpu.obs.flight import FlightRecorder  # noqa: E402
from hydragnn_tpu.resilience.supervisor import (  # noqa: E402
    PodSupervisor,
    Supervisor,
    SupervisorPolicy,
    wall_clock_runner,
)


def main(argv=None) -> int:
    import argparse

    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" not in argv:
        print("usage: supervise.py [options] -- <command> [args...]", file=sys.stderr)
        return 2
    split = argv.index("--")
    opts, child = argv[:split], argv[split + 1 :]
    if not child:
        print("supervise.py: empty child command", file=sys.stderr)
        return 2

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--max-restarts", type=int, default=5)
    p.add_argument("--max-preemptions", type=int, default=1000)
    p.add_argument("--backoff-base", type=float, default=1.0)
    p.add_argument("--backoff-factor", type=float, default=2.0)
    p.add_argument("--backoff-max", type=float, default=60.0)
    p.add_argument(
        "--no-auto-resume",
        action="store_true",
        help="do not set HYDRAGNN_AUTO_RESUME=1 for restarted children",
    )
    p.add_argument(
        "--keep-injection",
        action="store_true",
        help="keep HYDRAGNN_INJECT_* env vars across restarts (default: "
        "stripped so an injected fault fires exactly once)",
    )
    p.add_argument(
        "--max-wall-s",
        type=float,
        default=None,
        help="supervisor-level hard wall clock per attempt: kill the "
        "child (SIGTERM, then SIGKILL) after this many seconds and "
        "classify the attempt as hung/79 — the outer belt for children "
        "wedged where the in-process watchdog cannot fire",
    )
    p.add_argument(
        "--flight",
        default=None,
        help="write the supervisor's flight record (restart events + "
        "final summary) to this JSONL path",
    )
    p.add_argument(
        "--pod",
        type=int,
        default=None,
        metavar="N",
        help="supervise the command as a pod of N concurrent simulated "
        "hosts (HYDRAGNN_PODVIEW_HOST=k/_HOSTS=N per child); the pod "
        "lives and dies as one unit, a signal-dead host classifies as "
        "host_lost and restarts promptly from the last committed "
        "generation (docs/RESILIENCE.md 'Pod recovery')",
    )
    p.add_argument(
        "--pod-elastic",
        action="store_true",
        help="after a host_lost attempt, restart the pod with N-1 hosts "
        "instead of the original width (the restore re-shards the "
        "committed generation)",
    )
    p.add_argument(
        "--pod-grace",
        type=float,
        default=30.0,
        help="seconds surviving hosts get after SIGTERM to cut their "
        "final generation before SIGKILL (pod mode only)",
    )
    p.add_argument(
        "--run-id",
        default=None,
        help="shared HYDRAGNN_PODVIEW_RUN_ID for all pod hosts (pod "
        "mode only; defaults to the children deriving it from the run)",
    )
    args = p.parse_args(opts)

    policy = SupervisorPolicy(
        max_restarts=args.max_restarts,
        max_preemptions=args.max_preemptions,
        backoff_base_s=args.backoff_base,
        backoff_factor=args.backoff_factor,
        backoff_max_s=args.backoff_max,
        auto_resume=not args.no_auto_resume,
        strip_injection=not args.keep_injection,
    )
    flight = FlightRecorder(args.flight, enabled=args.flight is not None)
    # the supervisor never lowers an executable itself — the child's
    # run_start carries the real contract audit; this one records an
    # honest all-not_checked block so every run_start has the key
    from hydragnn_tpu.lint.ir import contract_block

    flight.start_run(
        {
            "supervisor": True,
            "argv": child,
            "policy": vars(args),
            "graftcheck": contract_block(None),
        }
    )
    if args.pod is not None:
        sup = PodSupervisor(
            child,
            hosts=args.pod,
            policy=policy,
            env=dict(os.environ),
            flight=flight,
            run_id=args.run_id,
            grace_s=args.pod_grace,
            max_wall_s=args.max_wall_s,
            elastic=args.pod_elastic,
        )
    else:
        runner = (
            wall_clock_runner(args.max_wall_s)
            if args.max_wall_s is not None
            else None
        )
        sup = Supervisor(
            child, policy=policy, env=dict(os.environ), flight=flight,
            runner=runner,
        )
    result = sup.run()
    flight.close()
    print(
        "supervise.py: "
        + json.dumps({k: v for k, v in result.items() if k != "history"}),
        file=sys.stderr,
    )
    return int(result["exit_code"]) if result["status"] != "completed" else 0


if __name__ == "__main__":
    raise SystemExit(main())
