#!/usr/bin/env python
"""Export a reference HydraGNN ADIOS2 dataset to the sharded-pickle
layout (the format hydragnn_tpu.data.import_reference consumes).

STANDALONE by design: depends only on ``adios2`` + ``numpy`` + stdlib,
so it runs unmodified inside a reference HydraGNN environment (where
adios2 lives) with no hydragnn_tpu checkout needed. Two-step migration:

    # in the reference environment
    python export_adios_to_pickle.py gfm_data.bp trainset /tmp/export
    # in the hydragnn_tpu environment
    python -m hydragnn_tpu.data.import_reference /tmp/export trainset out.hgc

Schema read (reference hydragnn/utils/adiosdataset.py AdiosWriter.save
:79-179): per split ``label``, attribute ``{label}/ndata`` + string
attribute ``{label}/keys``; per key ``k`` a global array ``{label}/{k}``
concatenated along attribute ``{label}/{k}/variable_dim`` with ragged
per-sample ``variable_count`` / ``variable_offset`` index arrays.

Layout written (reference hydragnn/utils/pickledataset.py
SimplePickleWriter :74-146): ``<out>/<label>-meta.pkl`` holding 5
sequential pickles (minmax_node_feature, minmax_graph_feature, ntotal,
use_subdir, nmax_persubdir) and one ``<out>/<label>-<k>.pkl`` per
sample. Samples are written as plain ``{field: ndarray}`` dicts — the
tolerant importer walks dict state exactly as it walks pickled PyG Data
state, and plain numpy pickles need no torch at load time.
"""

import argparse
import os
import pickle
import sys

import numpy as np


def _open_adios(filename):
    try:
        import adios2
    except ImportError:
        raise SystemExit(
            "this script needs the adios2 python library — run it inside "
            "the reference HydraGNN environment"
        )
    if hasattr(adios2, "FileReader"):  # adios2 >= 2.9
        return adios2.FileReader(filename)
    return adios2.open(filename, "r")


def export(filename: str, label: str, out_dir: str) -> int:
    os.makedirs(out_dir, exist_ok=True)
    f = _open_adios(filename)
    try:
        attrs = set(f.available_attributes())
        if f"{label}/ndata" not in attrs:
            labels = sorted(
                a[: -len("/ndata")]
                for a in attrs
                if a.endswith("/ndata") and a != "total_ndata"
            )
            raise SystemExit(
                f"label {label!r} not in {filename!r}; available: {labels}"
            )
        ndata = int(np.asarray(f.read_attribute(f"{label}/ndata")).reshape(-1)[0])
        keys = f.read_attribute_string(f"{label}/keys")
        if isinstance(keys, str):
            keys = [keys]

        data, count, offset, vdim = {}, {}, {}, {}
        for k in keys:
            data[k] = np.asarray(f.read(f"{label}/{k}"))
            count[k] = (
                np.asarray(f.read(f"{label}/{k}/variable_count"))
                .reshape(-1)
                .astype(np.int64)
            )
            offset[k] = (
                np.asarray(f.read(f"{label}/{k}/variable_offset"))
                .reshape(-1)
                .astype(np.int64)
            )
            vdim[k] = int(
                np.asarray(f.read_attribute(f"{label}/{k}/variable_dim")).reshape(-1)[0]
            )

        minmax_node = (
            np.asarray(f.read_attribute("minmax_node_feature")).reshape(2, -1)
            if "minmax_node_feature" in attrs
            else None
        )
        minmax_graph = (
            np.asarray(f.read_attribute("minmax_graph_feature")).reshape(2, -1)
            if "minmax_graph_feature" in attrs
            else None
        )
    finally:
        f.close()

    for idx in range(ndata):
        sample = {}
        for k in keys:
            arr = data[k]
            sl = [slice(None)] * arr.ndim
            sl[vdim[k]] = slice(
                int(offset[k][idx]), int(offset[k][idx] + count[k][idx])
            )
            sample[k] = np.ascontiguousarray(arr[tuple(sl)])
        with open(os.path.join(out_dir, f"{label}-{idx}.pkl"), "wb") as fh:
            pickle.dump(sample, fh)

    with open(os.path.join(out_dir, f"{label}-meta.pkl"), "wb") as fh:
        pickle.dump(minmax_node, fh)
        pickle.dump(minmax_graph, fh)
        pickle.dump(ndata, fh)
        pickle.dump(False, fh)  # use_subdir
        pickle.dump(ndata + 1, fh)  # nmax_persubdir (unused when flat)
    return ndata


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("bpfile", help="ADIOS2 .bp file/dir written by AdiosWriter")
    p.add_argument("label", help="split label (trainset / valset / testset)")
    p.add_argument("out", help="output directory for the pickle layout")
    args = p.parse_args(argv)
    n = export(args.bpfile, args.label, args.out)
    print(f"exported {n} samples -> {args.out}/{args.label}-*.pkl")


if __name__ == "__main__":
    main()
