"""Chip hygiene: detect lingering accelerator-holding processes.

The r05 bench died with a bare traceback whose proximate cause class —
a previous run's process still holding the TPU when the next one tried
to initialize — is invisible after the fact. This tool makes it a
reported condition BEFORE it costs a round: it scans ``/proc`` for
processes holding accelerator device nodes (``/dev/accel*``,
``/dev/vfio/*``) or the libtpu lockfile, and prints ONE JSON line a
driver or operator can parse. ``ci.sh`` runs it as an informational
step; ``bench.py``'s retry-with-backoff
(``utils/platform.py:init_backend_with_retry``) handles the transient
window this tool diagnoses.

Report only — nothing is killed. ``--fail-on-holders`` turns holders
(other than this process tree) into exit code 1 for gating scripts.
"""

from __future__ import annotations

import glob
import json
import os
import sys
from typing import Dict, List

# device nodes + lockfiles whose open fds mark a process as chip-holding
_TARGET_GLOBS = (
    "/dev/accel*",
    "/dev/apex_*",
    "/dev/vfio/*",
    "/tmp/libtpu_lockfile*",
)


def _target_paths() -> List[str]:
    out: List[str] = []
    for pat in _TARGET_GLOBS:
        out.extend(glob.glob(pat))
    return sorted(set(out))


def _cmdline(pid: int) -> str:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return f.read().replace(b"\0", b" ").decode("utf-8", "replace").strip()
    except OSError:
        return ""


def _age_s(pid: int) -> float | None:
    try:
        import time

        return round(time.time() - os.stat(f"/proc/{pid}").st_mtime, 1)
    except OSError:
        return None


def _ancestors(pid: int) -> List[int]:
    """pid + its ancestor chain — a report must not flag the reporting
    shell/CI pipeline itself as a lingering holder."""
    chain = [pid]
    for _ in range(64):
        try:
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().split(")")[-1].split()[1])
        except (OSError, ValueError, IndexError):
            break
        if ppid <= 1:
            break
        chain.append(ppid)
        pid = ppid
    return chain


def find_chip_holders() -> Dict:
    """Scan /proc/*/fd for open handles on accelerator devices and
    lockfiles. Unreadable processes (other users, no root) are counted,
    not silently dropped — an empty holder list with a large
    ``unreadable_proc_count`` is 'unknown', not 'clean'."""
    targets = _target_paths()
    target_set = set(targets)
    self_and_ancestors = set(_ancestors(os.getpid()))
    holders: List[Dict] = []
    unreadable = 0
    for pid_dir in glob.glob("/proc/[0-9]*"):
        try:
            pid = int(os.path.basename(pid_dir))
        except ValueError:
            continue
        fd_dir = os.path.join(pid_dir, "fd")
        try:
            fds = os.listdir(fd_dir)
        except OSError:
            unreadable += 1
            continue
        held: List[str] = []
        for fd in fds:
            try:
                dest = os.readlink(os.path.join(fd_dir, fd))
            except OSError:
                continue
            if dest in target_set:
                held.append(dest)
        if held:
            holders.append(
                {
                    "pid": pid,
                    "cmdline": _cmdline(pid)[:200],
                    "age_s": _age_s(pid),
                    "targets": sorted(set(held)),
                    "is_self_tree": pid in self_and_ancestors,
                }
            )
    return {
        "targets_present": targets,
        "holders": sorted(holders, key=lambda h: h["pid"]),
        "foreign_holder_count": sum(
            1 for h in holders if not h["is_self_tree"]
        ),
        "unreadable_proc_count": unreadable,
        "self_pid": os.getpid(),
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="Report processes holding accelerator devices/lockfiles "
        "as one JSON line."
    )
    p.add_argument(
        "--fail-on-holders",
        action="store_true",
        help="exit 1 when a process OUTSIDE this process tree holds a chip",
    )
    args = p.parse_args(argv)
    report = find_chip_holders()
    print(json.dumps(report))
    if args.fail_on_holders and report["foreign_holder_count"]:
        print(
            f"chip hygiene: {report['foreign_holder_count']} foreign "
            "process(es) holding accelerator handles",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
