"""Trace-based qm9-scale device time for both dense-gather paths —
scan-slope through the tunnel is unreliable at this config's scale
(adjacent identical runs measured 1.4 vs 9.3 ms), the summed HLO self
time is not. Usage: python tools/trace_qm9.py [min_rows_values...]"""

import glob
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.flagship import build_flagship
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

t0 = time.time()
config, model, variables, loader = build_flagship(
    n_samples=384, batch_size=256, hidden_dim=64, num_conv_layers=6,
    unit_cells=(2, 3), edge_lengths=True,
)
tx = select_optimizer(config["NeuralNetwork"]["Training"])
state0 = create_train_state(variables, tx)
batch = next(iter(loader))
step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)

arms = {
    "win-kernel": batch,
    "permuted": batch.replace(dense_sender_win=None, sender_win=None),
}
os.environ["HYDRAGNN_LOCAL_MIN_ROWS"] = "0"  # let the batch decide

for name, b in arms.items():
    compiled = step.lower(state0, b).compile()
    st = jax.tree_util.tree_map(jnp.copy, state0)
    st, loss, _ = compiled(st, b)
    np.asarray(loss)
    tdir = f"/tmp/tq_{name}"
    shutil.rmtree(tdir, ignore_errors=True)
    with jax.profiler.trace(tdir):
        for _ in range(3):
            st, loss, _ = compiled(st, b)
        np.asarray(loss)
    planes = glob.glob(f"{tdir}/**/*.xplane.pb", recursive=True)
    from xprof.convert import raw_to_tool_data as rd
    import json as _json

    data, _ = rd.xspace_to_tool_data(planes, "hlo_stats", {"tqx": "out:csv;"})
    tab = _json.loads(data.decode() if isinstance(data, bytes) else data)
    cols = [c["id"] for c in tab["cols"]]
    i_t = cols.index("total_self_time")
    tot = sum(float((r["c"][i_t] or {}).get("v") or 0) for r in tab["rows"])
    print(f"{name}: device {tot/3e3:.3f} ms/step", flush=True)
