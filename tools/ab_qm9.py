"""A/B the qm9-scale config: dense gathers via local-window kernel vs
the permuted path (strip dense_sender_win), scan-slope timing.
Usage: python tools/ab_qm9.py"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["HYDRAGNN_LOCAL_MIN_ROWS"] = "0"  # the A/B decides by batch, not gate

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.flagship import build_flagship
from hydragnn_tpu.train import create_train_state, select_optimizer
from hydragnn_tpu.train.state import _train_step_body
from hydragnn_tpu.utils.profile import scan_slope_ms

t0 = time.time()
config, model, variables, loader = build_flagship(
    n_samples=384, batch_size=256, hidden_dim=64, num_conv_layers=6,
    unit_cells=(2, 3), edge_lengths=True,
)
tx = select_optimizer(config["NeuralNetwork"]["Training"])
state = create_train_state(variables, tx)
body = _train_step_body(model, tx, compute_dtype=jnp.bfloat16)
batch = next(iter(loader))
print(f"[{time.time()-t0:.0f}s] dense={batch.dense_senders is not None} "
      f"win={batch.dense_sender_win is not None}", flush=True)

arms = {
    "win-kernel": batch,
    "permuted": batch.replace(dense_sender_win=None, sender_win=None),
}

def make_chain(b):
    def mk(k):
        def f(st, _):
            st, loss, _ = body(st, b)
            return st, loss
        fn = jax.jit(lambda st: jax.lax.scan(f, st, None, length=k))
        def run():
            _, losses = fn(state)
            np.asarray(losses[-1])
        return run
    return mk

for name, b in arms.items():
    ms = scan_slope_ms(make_chain(b), 4, 12)
    print(f"{name}: scan-slope step {ms:.3f} ms", flush=True)
