"""Export an HGC container to the reference sharded-pickle layout —
the INVERSE of ``hydragnn_tpu/data/import_reference.py``, completing
docs/MIGRATION.md's two-way story (native runs can hand datasets back
to reference deployments, and round-trip conversions are testable).

    python tools/export_to_reference_pickle.py data.hgc outdir [label]

Layout written (reference: hydragnn/utils/pickledataset.py
SimplePickleWriter):

    <outdir>/<label>-meta.pkl   5 sequential pickles: minmax_node_feature,
                                minmax_graph_feature, ntotal, use_subdir,
                                nmax_persubdir
    <outdir>/<label>-<k>.pkl    one pickle per sample (under
                                ``<k // nmax_persubdir>/`` subdirs when
                                --subdir-max is set)

Each sample pickle is a plain ``{field: numpy array}`` dict carrying
the reference field names (``x``, ``pos``, ``edge_index`` [2, e],
``edge_attr``, and the packed ``y`` + ``y_loc`` head layout written by
the reference's update_predicted_values — graph heads flat, node heads
num_nodes x dim row-major). The importer's tolerant unpickler consumes
dicts and torch ``Data`` objects identically (``_tensor_mapping``), so
``import_reference`` round-trips this layout without torch installed;
a reference-side consumer reads it with ``pickle.load`` + attribute
assembly (no foreign classes are pickled, by design — nothing to
import at load time).

Head packing order is deterministic — graph targets sorted by name,
then node targets sorted by name — and the CLI prints the
``--head-type``/``--head-name`` flags that re-import the container
unambiguously (``y``/``y_loc`` alone cannot distinguish a node head
from a graph head whose dim divides num_nodes).
"""

from __future__ import annotations

import os
import pickle
import sys
from typing import List, Optional, Sequence, Tuple

import numpy as np

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as `python tools/export_to_reference_pickle.py`
    sys.path.insert(0, _REPO)

from hydragnn_tpu.data.dataset import GraphSample  # noqa: E402


def head_order(sample: GraphSample) -> Tuple[List[str], List[str]]:
    """Deterministic packed-head order for one sample: (names, types),
    graph targets first then node targets, each sorted by name."""
    names: List[str] = []
    types: List[str] = []
    for name in sorted(sample.graph_targets):
        names.append(name)
        types.append("graph")
    for name in sorted(sample.node_targets):
        names.append(name)
        types.append("node")
    return names, types


def sample_to_reference_dict(sample: GraphSample) -> dict:
    """GraphSample -> reference-layout field dict (the inverse of
    ``import_reference.data_object_to_sample``)."""
    out = {"x": np.asarray(sample.x, dtype=np.float32)}
    if sample.pos is not None:
        out["pos"] = np.asarray(sample.pos, dtype=np.float32)
    if sample.edge_index is not None:
        out["edge_index"] = np.asarray(sample.edge_index, dtype=np.int64)
    if sample.edge_attr is not None:
        out["edge_attr"] = np.asarray(sample.edge_attr, dtype=np.float32)
    names, types = head_order(sample)
    if names:
        segs = []
        for name, htype in zip(names, types):
            v = (
                sample.graph_targets[name]
                if htype == "graph"
                else sample.node_targets[name]
            )
            # node heads: [num_nodes, dim] row-major flatten — the
            # update_predicted_values packing the importer unpacks
            segs.append(np.asarray(v, dtype=np.float32).reshape(-1))
        out["y"] = np.concatenate(segs) if segs else np.zeros(0, np.float32)
        out["y_loc"] = np.concatenate(
            [[0], np.cumsum([s.shape[0] for s in segs])]
        ).astype(np.int64)
    elif sample.graph_y is not None:
        out["y"] = np.asarray(sample.graph_y, dtype=np.float32).reshape(-1)
    return out


def export_samples_to_pickles(
    samples: Sequence[GraphSample],
    outdir: str,
    label: str = "total",
    minmax_node_feature=None,
    minmax_graph_feature=None,
    nmax_persubdir: int = 0,
) -> Tuple[int, List[str], List[str]]:
    """Write the sharded-pickle layout; returns
    (n_samples, head_names, head_types) — the import flags that make
    the round trip unambiguous. Heads must be homogeneous across
    samples (they are, for any prepared dataset)."""
    os.makedirs(outdir, exist_ok=True)
    use_subdir = bool(nmax_persubdir and nmax_persubdir > 0)
    names, types = head_order(samples[0]) if len(samples) else ([], [])
    for s in samples:
        if head_order(s) != (names, types):
            raise ValueError(
                "samples carry heterogeneous target heads; the packed "
                "y/y_loc layout requires one schema for the whole set"
            )
    meta_path = os.path.join(outdir, f"{label}-meta.pkl")
    with open(meta_path, "wb") as f:
        for obj in (
            None if minmax_node_feature is None else np.asarray(minmax_node_feature),
            None if minmax_graph_feature is None else np.asarray(minmax_graph_feature),
            int(len(samples)),
            use_subdir,
            int(nmax_persubdir) if use_subdir else 0,
        ):
            pickle.dump(obj, f)
    for k, s in enumerate(samples):
        d = outdir
        if use_subdir:
            d = os.path.join(outdir, str(k // nmax_persubdir))
            os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, f"{label}-{k}.pkl"), "wb") as f:
            pickle.dump(sample_to_reference_dict(s), f)
    return len(samples), names, types


def export_container(
    container_path: str,
    outdir: str,
    label: str = "total",
    nmax_persubdir: int = 0,
) -> Tuple[int, List[str], List[str]]:
    """HGC container -> sharded-pickle layout (minmax globals ride
    along into the meta pickle, as the importer expects)."""
    from hydragnn_tpu.data.container import ContainerDataset

    ds = ContainerDataset(container_path)
    try:
        mm_graph, mm_node = ds.minmax()
        return export_samples_to_pickles(
            ds.samples(),
            outdir,
            label,
            minmax_node_feature=mm_node,
            minmax_graph_feature=mm_graph,
            nmax_persubdir=nmax_persubdir,
        )
    finally:
        ds.close()


def main(argv: Optional[Sequence[str]] = None) -> None:
    import argparse

    p = argparse.ArgumentParser(
        description="Export an HGC container to the reference "
        "sharded-pickle layout (inverse of data/import_reference.py)."
    )
    p.add_argument("container", help="input .hgc container path")
    p.add_argument("outdir", help="output directory for the pickle set")
    p.add_argument("label", nargs="?", default="total", help="dataset label")
    p.add_argument(
        "--subdir-max",
        type=int,
        default=0,
        help="write at most N sample pickles per numbered subdirectory "
        "(the reference's use_subdir mode; 0 = flat layout)",
    )
    args = p.parse_args(argv)
    n, names, types = export_container(
        args.container, args.outdir, args.label, args.subdir_max
    )
    flags = " ".join(
        f"--head-type {t} --head-name {nm}" for nm, t in zip(names, types)
    )
    print(f"exported {n} samples -> {args.outdir} (label {args.label!r})")
    if flags:
        print(
            "re-import unambiguously with:\n"
            f"  python -m hydragnn_tpu.data.import_reference {args.outdir} "
            f"{args.label} out.hgc {flags}"
        )


if __name__ == "__main__":
    main()
