"""Flight-record reporter: pretty-print, validate, and diff runs.

The flight record (hydragnn_tpu/obs/flight.py) is the machine-readable
artifact; this is the human view over it:

    python tools/obs_report.py run/flight.jsonl             # summary
    python tools/obs_report.py --validate run/flight.jsonl  # schema gate
    python tools/obs_report.py --diff a/flight.jsonl b/flight.jsonl

``--validate`` exits 1 on schema problems (``--require-complete`` also
demands run_start/epoch/run_end — what ci.sh asserts of its smoke run);
``--diff`` is the round-over-round tool: manifest drift (config,
backend, pad plans) and per-epoch loss/step-time deltas between two
runs — e.g. two rounds' BENCH flight records.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as `python tools/obs_report.py`
    sys.path.insert(0, _REPO)

from hydragnn_tpu.obs.flight import (  # noqa: E402
    FAULT_KINDS,
    flight_record_warnings,
    read_flight_record,
    validate_flight_record,
)
from hydragnn_tpu.obs.introspect import (  # noqa: E402
    collect_head_series,
    flag_anomalies,
)
from hydragnn_tpu.obs.podview import (  # noqa: E402
    host_epoch_table,
    merge_host_flights,
)


def _fmt(v, nd: int = 6) -> str:
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def _flatten(d: dict, prefix: str = "") -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in sorted(d.items()):
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _first(events: List[dict], kind: str) -> Optional[dict]:
    for e in events:
        if e.get("kind") == kind:
            return e
    return None


def _exec_cache_summary(events: List[dict]) -> Optional[str]:
    """One line over the run's ``exec_cache`` events (persistent AOT
    executable cache, hydragnn_tpu/utils/exec_cache.py): hit / miss /
    store / evict counts with the miss-reason breakdown. None when the
    record has no cache traffic (cache disabled or pre-r09 record)."""
    counts: Dict[str, int] = {}
    reasons: Dict[str, int] = {}
    for e in events:
        if e.get("kind") != "exec_cache":
            continue
        ev = str(e.get("event"))
        counts[ev] = counts.get(ev, 0) + 1
        if ev in ("miss", "evict"):
            r = str(e.get("reason") or "absent")
            reasons[r] = reasons.get(r, 0) + 1
    if not counts:
        return None
    parts = [f"{counts.get(k, 0)} {k}" for k in ("hit", "miss", "store", "evict")]
    line = " / ".join(parts)
    if reasons:
        line += " (" + ", ".join(
            f"{k}={v}" for k, v in sorted(reasons.items())
        ) + ")"
    ready = [
        e
        for e in events
        if e.get("kind") == "exec_cache" and e.get("event") == "train_ready"
    ]
    if ready:
        r = ready[-1]
        line += (
            f"; train_ready hit={r.get('hit')} compiles={r.get('compiles')} "
            f"build_s={r.get('build_s')} mode={r.get('mode')}"
        )
    return line


def render_report(events: List[dict]) -> str:
    """One run's story as text: manifest, epoch table, incidents,
    summary."""
    lines: List[str] = []
    start = _first(events, "run_start")
    if start:
        man = start.get("manifest", {})
        lines.append("== manifest ==")
        for key in (
            "run",
            "mode",
            "metric",
            "jax_version",
            "backend",
            "device_kind",
            "num_processes",
            "mesh",
            "num_epoch",
            "start_epoch",
            "scan_epoch",
            "mixed_precision",
            "init_retries",
        ):
            if key in man:
                lines.append(f"  {key}: {_fmt(man[key])}")
        par = man.get("parallel")
        if isinstance(par, dict) and par.get("available"):
            from hydragnn_tpu.parallel.partitioner import parallel_manifest_summary

            lines.append(f"  parallel: {parallel_manifest_summary(par)}")
        for split, plan in (man.get("pad_plans") or {}).items():
            lines.append(f"  pad[{split}]: {plan}")
    epochs = [e for e in events if e.get("kind") == "epoch"]
    if epochs:
        lines.append("== epochs ==")
        lines.append(
            "  ep    train_loss      val_loss        lr      steps  "
            "data_wait_s  dispatch_s  device_ms  compiles"
        )
        for e in epochs:
            st = e.get("step_time") or {}
            comp = e.get("compiles") or {}
            flag = " RECOMPILE!" if comp.get("unexpected") else ""
            lines.append(
                f"  {e.get('epoch', '?'):>4} "
                f"{_fmt(e.get('train_loss'), 6):>13} "
                f"{_fmt(e.get('val_loss'), 6):>13} "
                f"{_fmt(e.get('lr'), 4):>9} "
                f"{st.get('steps', '-'):>6} "
                f"{_fmt(st.get('data_wait_s', '-'), 4):>12} "
                f"{_fmt(st.get('dispatch_s', '-'), 4):>11} "
                f"{_fmt(st.get('device_wait_ms_mean', '-'), 4):>10} "
                f"{comp.get('count', '-'):>8}{flag}"
            )
    ecache = _exec_cache_summary(events)
    if ecache:
        lines.append("== exec cache ==")
        lines.append(f"  {ecache}")
    incidents = [
        e for e in events if e.get("kind") in ("retry", "error", "_unparseable")
    ]
    if incidents:
        lines.append("== incidents ==")
        for e in incidents:
            lines.append(
                f"  [{e.get('kind')}] {e.get('error') or e.get('line') or ''}"
            )
    for kind in ("bench_config", "bench_result", "profile_trace"):
        for e in events:
            if e.get("kind") == kind:
                name = e.get("name") or e.get("path") or ""
                lines.append(f"== {kind} {name} ==")
                payload = {
                    k: v
                    for k, v in e.items()
                    if k not in ("v", "kind", "t", "rank", "name")
                }
                lines.append("  " + json.dumps(payload)[:400])
    end = _first(events, "run_end")
    if end is None:
        lines.append("== run_end: MISSING (crashed or still running) ==")
    else:
        lines.append("== run_end ==")
        for k, v in end.items():
            if k in ("v", "kind", "t", "rank", "metrics", "timers"):
                continue
            lines.append(f"  {k}: {_fmt(v)}")
        for k, t in (end.get("timers") or {}).items():
            lines.append(f"  timer {k}: {t}")
    return "\n".join(lines)


def render_heads(events: List[dict]) -> str:
    """The multi-task health view (``--heads``): per-head loss /
    grad-norm / MAE trajectories, the mean task-conflict matrix, the
    hardware-efficiency ledger, and the anomaly flags
    (``hydragnn_tpu/obs/introspect.py:flag_anomalies``) — the diagnosis
    a human or CI reads, not just the data."""
    series = collect_head_series(events)
    names = series["names"]
    lines: List[str] = []
    if not names:
        return "== heads: no per-head data in this record =="
    lines.append(f"== heads ({len(names)}): {', '.join(names)} ==")

    lines.append("== per-head trajectories ==")
    for n in names:
        lines.append(f"  head {n!r}:")
        lines.append(
            "      ep   train_loss    grad_norm          mae         rmse"
        )
        for i, ep in enumerate(series["epochs"]):
            row = [
                _fmt(series[key][n][i] if series[key][n][i] is not None else "-", 5)
                for key in ("train_loss", "grad_norm", "mae", "rmse")
            ]
            lines.append(
                f"    {ep!s:>4} {row[0]:>12} {row[1]:>12} {row[2]:>12} {row[3]:>12}"
            )

    mats = [m for m in series["cosine"] if m is not None]
    if mats:
        import numpy as np

        h = len(names)
        good = [np.asarray(m, float) for m in mats]
        good = [m for m in good if m.shape == (h, h)]
        if good:
            mean = np.mean(good, axis=0)
            lines.append(
                f"== task-conflict matrix (mean gradient cosine over "
                f"{len(good)} sampled epoch(s)) =="
            )
            short = [n[:12] for n in names]
            lines.append("  " + " " * 14 + " ".join(f"{s:>12}" for s in short))
            for i, s in enumerate(short):
                lines.append(
                    f"  {s:>14}"
                    + " ".join(f"{mean[i, j]:>+12.3f}" for j in range(h))
                )
    ratios = [r for r in series["update_ratio"] if r is not None]
    if ratios:
        lines.append(
            "== update/param norm ratio (sampled): "
            + ", ".join(f"{r:.3g}" for r in ratios)
            + " =="
        )

    hw_rows = [
        (e.get("epoch"), e.get("hw"))
        for e in events
        if e.get("kind") == "epoch" and isinstance(e.get("hw"), dict)
    ]
    if hw_rows:
        lines.append("== hardware-efficiency ledger ==")
        lines.append("      ep        mfu   achieved_tflops   mem_peak_bytes")
        for ep, hw in hw_rows:
            mem = (hw.get("memory") or {}).get("peak_bytes_in_use", "-")
            mfu = hw.get("mfu")
            tfl = hw.get("achieved_tflops")
            lines.append(
                f"    {ep!s:>4} {_fmt(mfu if mfu is not None else '-', 4):>10} "
                f"{_fmt(tfl if tfl is not None else '-', 6):>17} {mem!s:>16}"
            )

    flags = flag_anomalies(series)
    lines.append(f"== anomalies ({len(flags)}) ==")
    if flags:
        lines.extend(f"  ! {f}" for f in flags)
    else:
        lines.append("  (none — multi-task optimization looks healthy)")
    return "\n".join(lines)


def render_faults(events: List[dict]) -> str:
    """A run's fault history: chronological preemption / rollback /
    watchdog / restart / retry / error timeline — plus the serving-side
    kinds (quarantine, dispatch_restart, reload/reload_failed) — and
    non-completed run_end statuses: the view a post-mortem starts from.
    Handles MERGED records (several run_start..run_end segments in one
    file, the append-mode artifact of a supervised run)."""
    t0 = events[0].get("t") if events and isinstance(events[0].get("t"), (int, float)) else None

    def _rel(e) -> str:
        t = e.get("t")
        if t0 is None or not isinstance(t, (int, float)):
            return "     ?"
        return f"{t - t0:+9.2f}s"

    interesting = [
        e
        for e in events
        if e.get("kind") in FAULT_KINDS
        or e.get("kind") == "_unparseable"
        or (e.get("kind") == "run_end" and e.get("status") != "completed")
    ]
    counts = {
        "runs": sum(1 for e in events if e.get("kind") == "run_start"),
        "completed": sum(
            1
            for e in events
            if e.get("kind") == "run_end" and e.get("status") == "completed"
        ),
        "preempted": sum(
            1
            for e in events
            if e.get("kind") == "run_end" and e.get("status") == "preempted"
        ),
        "resumed": sum(1 for e in events if e.get("kind") == "resumed"),
        "rollbacks": sum(1 for e in events if e.get("kind") == "rollback"),
        "watchdog": sum(1 for e in events if e.get("kind") == "watchdog"),
        "restarts": sum(1 for e in events if e.get("kind") == "restart"),
        "host_lost": sum(1 for e in events if e.get("kind") == "host_lost"),
        "pod_resumes": sum(1 for e in events if e.get("kind") == "pod_resume"),
        "errors": sum(1 for e in events if e.get("kind") == "error"),
        "quarantined": sum(1 for e in events if e.get("kind") == "quarantine"),
        "dispatch_restarts": sum(
            1 for e in events if e.get("kind") == "dispatch_restart"
        ),
        "reloads": sum(1 for e in events if e.get("kind") == "reload"),
        "reload_failed": sum(
            1 for e in events if e.get("kind") == "reload_failed"
        ),
        "incidents": sum(1 for e in events if e.get("kind") == "incident"),
        "drift": sum(1 for e in events if e.get("kind") == "drift"),
        "pilot_cycles": sum(
            1
            for e in events
            if e.get("kind") == "pilot" and e.get("state") == "drift_confirmed"
        ),
        "pilot_stuck": sum(
            1
            for e in events
            if e.get("kind") == "pilot" and e.get("state") == "stuck"
        ),
        "spool_rotations": sum(
            1 for e in events if e.get("kind") == "spool_rotate"
        ),
        "nonfinite_skipped": sum(
            (e.get("nonfinite") or {}).get("skipped", 0)
            for e in events
            if e.get("kind") == "epoch"
        ),
    }
    lines = ["== fault summary =="]
    lines.append("  " + " ".join(f"{k}={v}" for k, v in counts.items()))
    if not interesting:
        lines.append("  (no fault events — a clean run)")
        return "\n".join(lines)
    lines.append("== fault timeline (t relative to first event) ==")
    for e in interesting:
        kind = e.get("kind")
        if kind == "preempt":
            detail = f"signal={e.get('signal')} epoch={e.get('epoch')} step={e.get('step')}"
        elif kind == "resumed":
            detail = f"epoch={e.get('epoch')}"
        elif kind == "rollback":
            detail = (
                f"epoch={e.get('epoch')} consec={e.get('consec')} "
                f"rollbacks={e.get('rollbacks')} lr={_fmt(e.get('lr'))}"
            )
        elif kind == "watchdog":
            stacks = e.get("stacks") or {}
            detail = f"stall_s={e.get('stall_s')} threads={sorted(stacks)}"
        elif kind == "restart":
            detail = (
                f"attempt={e.get('attempt')} cause={e.get('cause')} "
                f"exit_code={e.get('exit_code')} delay_s={e.get('delay_s')}"
            )
        elif kind == "host_lost":
            # a pod peer's heartbeats lapsed (or the supervisor saw its
            # signal death): the run restarts from the last committed
            # generation (docs/RESILIENCE.md 'Pod recovery')
            extras = [
                f"{k}={e[k]}"
                for k in ("epoch", "lost_after_s", "exit_code", "attempt")
                if e.get(k) is not None
            ]
            detail = f"host {e.get('host')} declared lost" + (
                " (" + " ".join(extras) + ")" if extras else ""
            )
        elif kind == "pod_resume":
            # the restarted run says which committed generation it rose
            # from and the pod layout that generation was cut under
            detail = (
                f"resumed from committed gen {e.get('gen')} "
                f"(prior_hosts={e.get('prior_hosts')}"
                + (
                    f", fallbacks={e.get('fallbacks')}"
                    if e.get("fallbacks")
                    else ""
                )
                + ")"
            )
        elif kind == "quarantine":
            detail = (
                f"seq={e.get('seq')} reason={e.get('reason')} "
                f"bucket={e.get('bucket')} error={str(e.get('error') or '')[:80]}"
            )
        elif kind == "dispatch_restart":
            detail = (
                f"attempt={e.get('attempt')} cause={e.get('cause')} "
                f"delay_s={e.get('delay_s')}"
            )
        elif kind == "reload":
            detail = f"source={e.get('source')} swap_s={e.get('swap_s')}"
        elif kind == "reload_failed":
            detail = (
                f"source={e.get('source')} rolled_back={e.get('rolled_back')} "
                f"error={str(e.get('error') or '')[:80]}"
            )
        elif kind == "incident":
            # SLO trigger fired; the bundle at `path` holds the evidence
            # (render it with tools/incident_report.py)
            detail = f"id={e.get('id')} rule={e.get('rule')} path={e.get('path')}"
        elif kind == "drift":
            # served traffic left the training reference; the incident
            # bundle's drift_report.json + the spool window hold the
            # evidence (render with tools/drift_report.py)
            window = e.get("spool_window") or {}
            detail = (
                f"rule={e.get('rule')} observed={_fmt(e.get('observed'))} "
                f"threshold={_fmt(e.get('threshold'))} "
                f"spool={window.get('dir') or '<off>'}"
            )
        elif kind == "pilot":
            # the retrain pilot's state machine (hydragnn_tpu/pilot):
            # drift_confirmed -> fine_tuning -> canary -> reloading ->
            # cooldown, or stuck when the recovery budget is spent
            extras = [
                f"{k}={e[k]}"
                for k in ("reason", "candidate", "rule")
                if e.get(k) is not None
            ]
            detail = (
                f"state={e.get('state')} cycle={e.get('cycle')} "
                f"failed_cycles={e.get('failed_cycles')}"
                + ("".join(" " + x for x in extras))
            )
        elif kind == "run_end":
            detail = f"status={e.get('status')}"
        else:
            detail = str(e.get("error") or e.get("line") or "")[:160]
        lines.append(f"  {_rel(e)} [{kind}] {detail}")
    return "\n".join(lines)


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values: List[object], lo: float = 0.0, hi: Optional[float] = None) -> str:
    """Unicode block sparkline; non-numeric entries render as spaces."""
    nums = [v for v in values if isinstance(v, (int, float))]
    if not nums:
        return ""
    if hi is None:
        hi = max(nums)
    span = max(hi - lo, 1e-9)
    out = []
    for v in values:
        if not isinstance(v, (int, float)):
            out.append(" ")
            continue
        idx = int((v - lo) / span * (len(_SPARK) - 1) + 0.5)
        out.append(_SPARK[min(len(_SPARK) - 1, max(0, idx))])
    return "".join(out)


def render_hosts(merged) -> str:
    """The pod view (``--hosts``, over a run directory of per-host
    flight shards): a per-epoch table with one row per host (epoch wall
    time, data-wait, nonfinite skips, MFU), the merge reader's advisory
    problems, and the rank-0 SkewMonitor's verdicts as a skew-fraction
    sparkline across epochs (docs/OBSERVABILITY.md 'Pod visibility')."""
    lines: List[str] = []
    lines.append(
        f"== hosts ({len(merged.hosts)}): "
        f"{', '.join(str(h) for h in merged.hosts) or '(none)'} =="
    )
    for prob in merged.problems:
        lines.append(f"  note: {prob}")
    table = host_epoch_table(merged.events)
    if not table:
        lines.append(
            "  (no host_epoch events — single-host record or podview off)"
        )
    else:
        lines.append(
            "    ep  host     epoch_s  data_wait_s  nonfinite          mfu"
        )
        for ep in sorted(table):
            rows = sorted(table[ep].items())
            slowest = (
                max(rows, key=lambda kv: kv[1].get("epoch_s") or 0.0)[0]
                if len(rows) > 1
                else None
            )
            for h, ev in rows:
                mark = "  <- slowest" if h == slowest else ""
                lines.append(
                    f"  {ep!s:>4} {h!s:>5} "
                    f"{_fmt(ev.get('epoch_s', '-'), 5):>11} "
                    f"{_fmt(ev.get('data_wait_s', '-'), 4):>12} "
                    f"{ev.get('nonfinite_skipped', 0)!s:>10} "
                    f"{_fmt(ev.get('mfu', '-'), 4):>12}{mark}"
                )
    verdicts = [e for e in merged.events if e.get("kind") == "podview"]
    if verdicts:
        vals = [e.get("skew_frac") for e in verdicts]
        nums = [v for v in vals if isinstance(v, (int, float))]
        last = verdicts[-1]
        thr = last.get("threshold")
        lines.append("== skew (rank-0 SkewMonitor) ==")
        lines.append(
            f"  skew_frac {_sparkline(vals, 0.0, max(nums + [thr or 0.0, 1e-9]))} "
            f"(epochs {verdicts[0].get('epoch')}..{last.get('epoch')}, "
            f"threshold {_fmt(thr, 4)})"
        )
        lines.append(
            f"  last: skew_frac={_fmt(last.get('skew_frac'), 4)} "
            f"slowest_host={last.get('slowest_host')} "
            f"cause={last.get('cause')}"
        )
    return "\n".join(lines)


def fault_schema_problems(events: List[dict]) -> List[str]:
    """Schema problems affecting the fault-history subset (what
    ``--faults`` gates on: a fault event that cannot be parsed is
    evidence lost exactly when it matters)."""
    watched = set(FAULT_KINDS) | {"run_end"}
    out = []
    for p in validate_flight_record(events):
        if "unparseable" in p or any(f"({k})" in p for k in watched):
            out.append(p)
    return out


def render_diff(a_events: List[dict], b_events: List[dict]) -> str:
    """What changed between two runs: manifest drift + per-epoch and
    summary deltas."""
    lines: List[str] = []
    a_start, b_start = _first(a_events, "run_start"), _first(b_events, "run_start")
    a_man = _flatten((a_start or {}).get("manifest") or {})
    b_man = _flatten((b_start or {}).get("manifest") or {})
    drift = []
    for key in sorted(set(a_man) | set(b_man)):
        va, vb = a_man.get(key, "<absent>"), b_man.get(key, "<absent>")
        if va != vb:
            drift.append(f"  {key}: {_fmt(va)} -> {_fmt(vb)}")
    lines.append(f"== manifest drift ({len(drift)} keys) ==")
    lines.extend(drift or ["  (identical)"])

    a_ep = {e.get("epoch"): e for e in a_events if e.get("kind") == "epoch"}
    b_ep = {e.get("epoch"): e for e in b_events if e.get("kind") == "epoch"}
    common = sorted(set(a_ep) & set(b_ep))
    if common:
        lines.append("== per-epoch deltas (B - A) ==")
        for ep in common:
            ea, eb = a_ep[ep], b_ep[ep]
            parts = [f"  ep {ep}:"]
            for field in ("train_loss", "val_loss"):
                va, vb = ea.get(field), eb.get(field)
                if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
                    parts.append(f"{field} {vb - va:+.6g}")
            sa = (ea.get("step_time") or {}).get("data_wait_s")
            sb = (eb.get("step_time") or {}).get("data_wait_s")
            if isinstance(sa, (int, float)) and isinstance(sb, (int, float)):
                parts.append(f"data_wait_s {sb - sa:+.4g}")
            lines.append(" ".join(parts))
    only_a, only_b = sorted(set(a_ep) - set(b_ep)), sorted(set(b_ep) - set(a_ep))
    if only_a:
        lines.append(f"  epochs only in A: {only_a}")
    if only_b:
        lines.append(f"  epochs only in B: {only_b}")

    a_end, b_end = _first(a_events, "run_end"), _first(b_events, "run_end")
    lines.append("== run_end ==")
    for name, end in (("A", a_end), ("B", b_end)):
        if end is None:
            lines.append(f"  {name}: MISSING")
        else:
            brief = {
                k: v
                for k, v in end.items()
                if k in ("status", "epochs", "best_val_loss", "value", "metric")
            }
            lines.append(f"  {name}: {json.dumps(brief)}")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument(
        "records",
        nargs="+",
        help="flight-record .jsonl path(s), or run directories of "
        "per-host shards (flight.jsonl + flight.host<k>.jsonl)",
    )
    p.add_argument(
        "--validate",
        action="store_true",
        help="schema-check instead of printing; exit 1 on problems",
    )
    p.add_argument(
        "--require-complete",
        action="store_true",
        help="with --validate: also require run_start + epoch(s) + run_end",
    )
    p.add_argument(
        "--diff",
        action="store_true",
        help="diff exactly two records (A B)",
    )
    p.add_argument(
        "--faults",
        action="store_true",
        help="fault-history view: preemption / rollback / watchdog / "
        "restart timeline (handles merged multi-run records); exits 1 "
        "when any fault event fails its schema",
    )
    p.add_argument(
        "--hosts",
        action="store_true",
        help="pod view over merged per-host flight shards: per-host "
        "epoch table (wall, data-wait, nonfinite skips, MFU) and the "
        "SkewMonitor skew sparkline; accepts a run directory",
    )
    p.add_argument(
        "--heads",
        action="store_true",
        help="multi-task health view: per-head loss/grad-norm/MAE "
        "trajectories, the gradient-cosine conflict matrix, the "
        "hardware-efficiency ledger, and anomaly flags "
        "(docs/OBSERVABILITY.md 'Model-level diagnostics')",
    )
    args = p.parse_args(argv)

    def _print_warnings(events) -> None:
        # forward-compat advisories (unknown kinds, newer schema
        # versions): surfaced, never fatal
        for w in flight_record_warnings(events):
            print(f"  WARNING: {w}")

    if args.hosts:
        for path in args.records:
            merged = merge_host_flights(path)
            if len(args.records) > 1:
                print(f"===== {path} =====")
            print(render_hosts(merged))
            _print_warnings(merged.events)
        return 0

    if args.heads:
        for path in args.records:
            events = read_flight_record(path)
            if len(args.records) > 1:
                print(f"===== {path} =====")
            print(render_heads(events))
            _print_warnings(events)
        return 0

    if args.faults:
        rc = 0
        for path in args.records:
            events = read_flight_record(path)
            if len(args.records) > 1:
                print(f"===== {path} =====")
            print(render_faults(events))
            problems = fault_schema_problems(events)
            for prob in problems:
                rc = 1
                print(f"  SCHEMA: {prob}")
        return rc

    if args.diff:
        if len(args.records) != 2:
            p.error("--diff needs exactly two records")
        a, b = (read_flight_record(r) for r in args.records)
        print(render_diff(a, b))
        _print_warnings(a)
        _print_warnings(b)
        return 0

    import os

    rc = 0
    for path in args.records:
        if args.validate and os.path.isdir(path):
            # a run directory of per-host shards: the merged timeline
            # must be schema-valid, but shard-level trouble (torn
            # tails, missing hosts) is advisory — the surviving hosts'
            # evidence still merges and must not fail the gate
            merged = merge_host_flights(path)
            problems = list(validate_flight_record(merged.events))
            if args.require_complete:
                # completeness is per shard: the merged timeline
                # legitimately interleaves one run_start per host
                from hydragnn_tpu.obs.podview import list_host_shards

                for h, shard in sorted(list_host_shards(path).items()):
                    for prob in validate_flight_record(
                        shard, require_complete=True
                    ):
                        problems.append(f"host{h}: {prob}")
            if problems:
                rc = 1
                print(f"{path}: INVALID ({len(problems)} problem(s))")
                for prob in problems:
                    print(f"  - {prob}")
            else:
                print(
                    f"{path}: OK ({len(merged.events)} merged events from "
                    f"{len(merged.hosts)} host shard(s))"
                )
                # pod-checkpoint posture: the newest committed
                # generation a restart would rise from, and — when a
                # run in this record DID rise from one — its lineage
                from hydragnn_tpu.resilience.podckpt import latest_commit_info

                commit = latest_commit_info(path)
                if commit is not None:
                    print(
                        f"  podckpt: last committed gen {commit.get('gen')}"
                        f" (step={commit.get('step')}"
                        f" hosts={commit.get('hosts')})"
                    )
                for e in merged.events:
                    if e.get("kind") != "run_start":
                        continue
                    lineage = (e.get("manifest") or {}).get("pod_resume")
                    if lineage:
                        print(
                            "  pod_resume: from gen "
                            f"{lineage.get('resumed_from_gen')} "
                            f"(prior_hosts={lineage.get('prior_hosts')}, "
                            f"prior_layout={lineage.get('prior_layout')})"
                        )
            for prob in merged.problems:
                print(f"  WARNING: {prob}")
            _print_warnings(merged.events)
            continue
        events = read_flight_record(path)
        if args.validate:
            problems = validate_flight_record(
                events, require_complete=args.require_complete
            )
            if problems:
                rc = 1
                print(f"{path}: INVALID ({len(problems)} problem(s))")
                for prob in problems:
                    print(f"  - {prob}")
            else:
                print(f"{path}: OK ({len(events)} events)")
                # surface the parallelism story alongside the verdict:
                # which mesh ran this record and how its state sharded
                start = _first(events, "run_start")
                par = ((start or {}).get("manifest") or {}).get("parallel")
                if isinstance(par, dict) and par.get("available"):
                    from hydragnn_tpu.parallel.partitioner import (
                        parallel_manifest_summary,
                    )

                    print(f"  parallel: {parallel_manifest_summary(par)}")
                ecache = _exec_cache_summary(events)
                if ecache:
                    print(f"  exec_cache: {ecache}")
                lineage = ((start or {}).get("manifest") or {}).get("pod_resume")
                if lineage:
                    print(
                        "  pod_resume: from gen "
                        f"{lineage.get('resumed_from_gen')} "
                        f"(prior_hosts={lineage.get('prior_hosts')}, "
                        f"prior_layout={lineage.get('prior_layout')})"
                    )
                # drift-observability posture: was the spool/drift plane
                # armed for the serve run(s) this record holds? (a serve
                # bench artifact with drift off is a monitoring gap, not
                # a schema error — surfaced, never fatal)
                serves = [
                    (e.get("manifest") or {})
                    for e in events
                    if e.get("kind") == "run_start"
                    and (e.get("manifest") or {}).get("mode") == "serve"
                ]
                if serves:
                    armed = sum(
                        1 for m in serves if (m.get("drift") or {}).get("armed")
                    )
                    spooled = sum(
                        1 for m in serves if (m.get("spool") or {}).get("enabled")
                    )
                    print(
                        f"  drift: armed on {armed}/{len(serves)} serve run(s),"
                        f" spool on {spooled}/{len(serves)}"
                    )
            _print_warnings(events)
        else:
            if len(args.records) > 1:
                print(f"===== {path} =====")
            print(render_report(events))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
