"""A/B the flagship PNA step: CSR (+local-window sender kernels) vs the
dense ELL slot map, interleaved in one process (tunnel throttle makes
cross-process absolute times incomparable — verify skill notes).

Usage: python tools/ab_dense.py [steps_per_arm]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

t0 = time.time()


def log(msg):
    print(f"[{time.time()-t0:7.1f}s] {msg}", flush=True)


from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader, max_in_degree
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.utils.config import update_config
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 8
BATCH = 1024

config = flagship_config(128, 6, BATCH)
samples = deterministic_graph_data(
    number_configurations=1280,
    unit_cell_x_range=(2, 4),
    unit_cell_y_range=(2, 4),
    unit_cell_z_range=(2, 4),
    seed=0,
)
train, val, test, _, _ = prepare_dataset(samples, config)
config = update_config(config, train, val, test)
log(f"dataset ready: {len(train)} train samples, dmax={max_in_degree(train)}")

arms = {}
for name, dense in (("csr", False), ("dense", max_in_degree(train))):
    # run_align=False: keep this a pure dense-vs-CSR comparison (the
    # loader default would silently run-align the CSR arm)
    loader = GraphLoader(
        train, BATCH, shuffle=True, drop_last=True, dense_slots=dense,
        run_align=False,
    )
    batches = list(loader)
    arms[name] = batches
    b = batches[0]
    log(
        f"{name}: node_pad={b.nodes.shape[0]} edge_pad={b.senders.shape[0]} "
        f"dense={None if b.dense_senders is None else b.dense_senders.shape} "
        f"sender_win={'y' if b.sender_win is not None else 'n'} "
        f"dense_win={'y' if b.dense_sender_win is not None else 'n'}"
    )

tx = select_optimizer(config["NeuralNetwork"]["Training"])
model, variables = create_model_config(config["NeuralNetwork"], arms["csr"][0])
state0 = create_train_state(variables, tx)
step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)

compiled = {}
for name, batches in arms.items():
    compiled[name] = step.lower(state0, batches[0]).compile()
    log(f"{name}: compiled")

# the jitted step DONATES the state: give each arm its own copy
states = {
    name: jax.tree_util.tree_map(jnp.copy, state0) for name in arms
}

# warmup + loss parity check
losses = {}
for name, batches in arms.items():
    states[name], loss, _ = compiled[name](states[name], batches[0])
    losses[name] = float(np.asarray(loss))
log(f"warmup losses: {losses}")

# interleaved timing, D2H fence per arm segment
K = 4  # steps per segment
results = {name: [] for name in arms}
seg = 0
while seg * K < STEPS:
    for name, batches in arms.items():
        t1 = time.perf_counter()
        for i in range(K):
            states[name], loss, _ = compiled[name](
                states[name], batches[(seg * K + i) % len(batches)]
            )
        np.asarray(loss)
        results[name].append((time.perf_counter() - t1) / K * 1e3)
    seg += 1

for name, ts in results.items():
    med = sorted(ts)[len(ts) // 2]
    print(
        f"{name}: step_ms segments={['%.1f' % t for t in ts]} median={med:.1f} "
        f"graphs/sec={BATCH / med * 1e3:.0f}"
    )
