"""Trace the large_graph bench config's train step (per-op device
table). Usage: python tools/trace_large.py"""

import glob
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.flagship import build_flagship
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

t0 = time.time()
config, model, variables, loader = build_flagship(
    n_samples=48, batch_size=32, hidden_dim=128, num_conv_layers=6,
    unit_cells=(6, 8),
)
tx = select_optimizer(config["NeuralNetwork"]["Training"])
state = create_train_state(variables, tx)
step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)
batch = next(iter(loader))
print(f"[{time.time()-t0:.0f}s] node_pad={batch.nodes.shape[0]} "
      f"edge_pad={batch.senders.shape[0]} run_align={batch.run_align}", flush=True)
compiled = step.lower(state, batch).compile()
state, loss, _ = compiled(state, batch)
np.asarray(loss)
print(f"[{time.time()-t0:.0f}s] warmup loss={float(loss):.4f}", flush=True)
tdir = os.environ.get("TRACE_DIR", "/tmp/tb_large")
shutil.rmtree(tdir, ignore_errors=True)
with jax.profiler.trace(tdir):
    for _ in range(3):
        state, loss, _ = compiled(state, batch)
    np.asarray(loss)
print("traced; parse with: python tools/parse_trace.py", tdir, flush=True)
