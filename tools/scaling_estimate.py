"""HLO-derived data-parallel scaling estimate (VERDICT r03 item 4).

Real multi-chip hardware is unavailable here, so instead of ASSUMING a
DP efficiency factor (BASELINE.md previously used 0.9 with no support),
this derives one from first principles + the compiled program:

  1. jit the FULL flagship train step over an 8-device mesh (virtual
     CPU devices — the SPMD partitioner emits the same collective
     structure it would on a TPU pod slice);
  2. read the per-step all-reduce bytes straight from the compiled
     HLO (the gradient all-reduce over the data axis; ring all-reduce
     moves 2(n-1)/n x bytes over ICI per chip);
  3. convert to expected ICI time on the v5e's public link budget and
     compare against the measured single-chip step time.

Writes SCALING_est_r04.json and prints a summary.

ICI budget: the v5e exposes 4 ICI links per chip in a 2D torus
(public spec: 1,600 Gbps aggregate per chip = 200 GB/s). A ring
all-reduce uses one axis, and achievable efficiency on real pods is
~80-90% of nominal; ICI_GBPS (default 45 = one link direction x 90%)
keeps the estimate conservative and overridable.
"""

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

N_DEV = 8
ICI_GBPS = float(os.environ.get("ICI_GBPS", 45.0))
# measured single-chip flagship step (r04 trace: device self time; the
# wall step adds tunnel RTT a pod would not pay)
STEP_MS_DEVICE = float(os.environ.get("STEP_MS_DEVICE", 98.7))


def _dtype_bytes(tag: str) -> int:
    return {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
            "pred": 1, "s8": 1, "u8": 1}.get(tag, 4)


def collective_bytes(hlo: str) -> dict:
    """Sum result bytes of every collective in the HLO text, by kind.
    Handles tuple-typed results (one all-reduce over many gradient
    leaves) and async start/done pairs (counting the start only)."""
    shape_pat = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
    # "%name = TYPE kind(...)": TYPE may be a tuple of many gradient
    # leaves; async pairs count the -start only (the -done repeats it)
    line_pat = re.compile(
        r"=\s*(.*?)\s*"
        r"(all-reduce|reduce-scatter|all-gather|all-to-all|collective-permute)"
        r"(?:-start)?\("
    )
    out = {}
    for line in hlo.splitlines():
        m = line_pat.search(line)
        if not m or f"{m.group(2)}-done" in line:
            continue
        total = 0
        for dtype, shape in shape_pat.findall(m.group(1)):
            elems = 1
            for d in shape.split(","):
                if d.strip():
                    elems *= int(d)
            total += elems * _dtype_bytes(dtype)
        out[m.group(2)] = out.get(m.group(2), 0) + total
    return out


def main():
    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.parallel import make_mesh, make_sharded_train_step, place_state
    from hydragnn_tpu.train import create_train_state, select_optimizer

    config, model, variables, loader = build_flagship(
        n_samples=4 * N_DEV * 4, batch_size=4 * N_DEV, device_stack=N_DEV,
        hidden_dim=128, num_conv_layers=6,
    )
    mesh = make_mesh(N_DEV)
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = place_state(mesh, create_train_state(variables, tx))
    step = make_sharded_train_step(model, tx, mesh, compute_dtype=jnp.bfloat16)
    batch = next(iter(loader))
    lowered = step.lower(state, batch)
    compiled = lowered.compile()
    hlo = compiled.as_text()

    byts = collective_bytes(hlo)
    param_bytes = sum(
        np.prod(p.shape) * 4 for p in jax.tree_util.tree_leaves(variables["params"])
    )
    ar = byts.get("all-reduce", 0)
    # ring all-reduce: each chip moves 2(n-1)/n x payload over ICI
    wire = 2 * (N_DEV - 1) / N_DEV * ar
    t_ici_ms = wire / (ICI_GBPS * 1e9) * 1e3
    eff_no_overlap = STEP_MS_DEVICE / (STEP_MS_DEVICE + t_ici_ms)
    # XLA overlaps the gradient all-reduce with the tail of the backward
    # pass; treating HALF the wire time as exposed is the usual planning
    # number when no measured overlap exists
    eff_half_overlap = STEP_MS_DEVICE / (STEP_MS_DEVICE + 0.5 * t_ici_ms)

    rec = {
        "n_devices": N_DEV,
        "mesh": "1-D data-parallel (DP) over ICI",
        "collective_bytes_per_step": byts,
        "param_bytes_f32": int(param_bytes),
        "allreduce_bytes_per_step": int(ar),
        "allreduce_vs_2x_params": round(ar / max(2 * param_bytes, 1), 3),
        "ici_gbps_assumed": ICI_GBPS,
        "wire_bytes_per_chip_ring": int(wire),
        "t_ici_ms": round(t_ici_ms, 3),
        "step_ms_device_single_chip": STEP_MS_DEVICE,
        "dp_efficiency_no_overlap": round(eff_no_overlap, 4),
        "dp_efficiency_half_overlap": round(eff_half_overlap, 4),
        "note": (
            "Collective bytes read from the compiled 8-way SPMD HLO "
            "(virtual CPU mesh; same partitioner as TPU). Efficiency = "
            "compute / (compute + exposed ICI time); no-overlap is the "
            "floor, half-overlap the planning number. SCALING_cpu8.json "
            "remains correctness-only evidence (shared-core timings are "
            "not a scaling measurement)."
        ),
    }
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "SCALING_est_r04.json")
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec, indent=1))


if __name__ == "__main__":
    main()
