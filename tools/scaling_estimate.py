"""HLO-derived distributed scaling estimate (VERDICT r03 item 4 /
r04 item 6).

Real multi-chip hardware is unavailable here, so instead of ASSUMING a
DP efficiency factor (BASELINE.md previously used 0.9 with no support,
then a single 8-way-derived 0.997), this derives the scaling model from
the compiled programs themselves:

  1. jit the FULL flagship train step over 8-, 16-, and 32-way data
     meshes (virtual CPU devices — the SPMD partitioner emits the same
     collective structure it would on a TPU pod slice);
  2. read the per-step collective bytes straight from each compiled
     HLO (the gradient all-reduce over the data axis; ring all-reduce
     moves 2(n-1)/n x bytes over ICI per chip);
  3. add the OFF-STEP collectives a training run actually pays — the
     eval path's padded variable-length all-gather
     (train/loop.py:_allgather_varlen) and the checkpoint write
     (utils/checkpoint.py; ZeRO-1 shards write 1/n each) — amortized
     per step at a stated cadence;
  4. convert to expected wire time on the v5e/v4 public link budgets,
     with an optional DCN hop term for data axes spanning multiple ICI
     slices, and derive per-width DP efficiency;
  5. project the v4-32 (16-chip) north-star aggregate from the
     MEASURED single-chip traced step time, bandwidth-scaled to v4's
     HBM, times the DERIVED 16-way efficiency — replacing BASELINE.md's
     hand arithmetic.

Writes SCALING_est_r06.json (override with SCALING_OUT) and prints it.
FSDP variants (``FSDP_WIDTHS``, default "2,4") additionally compile the
largest width with parameters+optimizer sharded over an fsdp axis and
model the all-gather/reduce-scatter wire traffic the HLO then carries.

Link budgets: v5e exposes 4 ICI links/chip in a 2D torus (1,600 Gbps
aggregate = 200 GB/s); a ring all-reduce uses one axis, and achievable
efficiency on real pods is ~80-90% of nominal. ICI_GBPS (default 45 =
one link direction x 90%) keeps the estimate conservative. v4's ICI is
faster per link; reusing the v5e number is again conservative. DCN
(multi-slice) planning number: DCN_GBPS per host, default 12.5
(100 Gbps NICs x ~=1 direction), 4 chips/host on v4.
"""

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MESH_SIZES = [int(s) for s in os.environ.get("MESH_SIZES", "8,16,32").split(",")]

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={max(MESH_SIZES)}"
)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
ICI_GBPS = float(os.environ.get("ICI_GBPS", 45.0))
DCN_GBPS = float(os.environ.get("DCN_GBPS", 12.5))
# measured single-chip flagship step (r05 trace: device self time; the
# wall step adds tunnel RTT a pod would not pay)
STEP_MS_DEVICE = float(os.environ.get("STEP_MS_DEVICE", 77.8))
# v4 vs v5e HBM bandwidth ratio: the workload is bandwidth-bound
# (docs/PERF.md "Honest throughput"), so per-chip step time scales with
# HBM bandwidth to first order
V4_BW_SCALE = 1228.0 / 820.0
V4_32_CHIPS = 16  # a v4-32 slice = 16 chips (32 TensorCores)
BATCH_PER_CHIP = 1024
# off-step cadences for the amortized terms
STEPS_PER_EPOCH = int(os.environ.get("STEPS_PER_EPOCH", 50))
EPOCHS_PER_CHECKPOINT = int(os.environ.get("EPOCHS_PER_CHECKPOINT", 1))


def _dtype_bytes(tag: str) -> int:
    return {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
            "pred": 1, "s8": 1, "u8": 1}.get(tag, 4)


def collective_bytes(hlo: str) -> dict:
    """Sum result bytes of every collective in the HLO text, by kind.
    Handles tuple-typed results (one all-reduce over many gradient
    leaves) and async start/done pairs (counting the start only)."""
    shape_pat = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
    line_pat = re.compile(
        r"=\s*(.*?)\s*"
        r"(all-reduce|reduce-scatter|all-gather|all-to-all|collective-permute)"
        r"(?:-start)?\("
    )
    out = {}
    for line in hlo.splitlines():
        m = line_pat.search(line)
        if not m or f"{m.group(2)}-done" in line:
            continue
        total = 0
        for dtype, shape in shape_pat.findall(m.group(1)):
            elems = 1
            for d in shape.split(","):
                if d.strip():
                    elems *= int(d)
            total += elems * _dtype_bytes(dtype)
        out[m.group(2)] = out.get(m.group(2), 0) + total
    return out


def compile_width(n_dev: int, fsdp: int = 1) -> dict:
    """Compile the partitioned flagship step over an n_dev mesh
    (``data = n_dev/fsdp × fsdp``) and return its collective-bytes table
    + parameter size + the partitioner's per-device state bytes. The
    SAME Partitioner train/serve/bench use builds the step, so the HLO
    read here is the HLO a real run compiles."""
    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.parallel import Partitioner
    from hydragnn_tpu.train import create_train_state, select_optimizer

    config, model, variables, loader = build_flagship(
        n_samples=4 * n_dev * 2, batch_size=4 * n_dev, device_stack=n_dev,
        hidden_dim=128, num_conv_layers=6,
    )
    part = Partitioner(data=n_dev // fsdp, fsdp=fsdp)
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = part.shard_init(create_train_state(variables, tx))
    step = part.shard_train_step(model, tx, compute_dtype=jnp.bfloat16)
    batch = next(iter(loader))
    hlo = step.lower(state, batch).compile().as_text()
    param_bytes = sum(
        int(np.prod(p.shape)) * 4
        for p in jax.tree_util.tree_leaves(variables["params"])
    )
    man = part.manifest(state=state)
    return {
        "collectives": collective_bytes(hlo),
        "param_bytes": param_bytes,
        "fsdp": fsdp,
        "state_bytes_per_device": (
            man["params"]["bytes_per_device"] + man["opt"]["bytes_per_device"]
        ),
        "state_bytes_global": (
            man["params"]["bytes_global"] + man["opt"]["bytes_global"]
        ),
    }


def width_record(n_dev: int, comp: dict, dcn_slices: int = 1) -> dict:
    """Efficiency model for one mesh width.

    In-step: ring all-reduce wire bytes over ICI; with an fsdp axis the
    compiled program additionally carries the FSDP parameter all-gather
    and gradient/state reduce-scatter — read from the SAME HLO and
    modeled as rings over the fsdp axis width (each chip wires
    (f-1)/f of the payload per collective). When the data axis spans
    ``dcn_slices`` ICI slices, the inter-slice fraction of the ring
    rides DCN instead (2(s-1)/s of the payload crosses a slice boundary
    once per direction, shared by the slice's hosts)."""
    ar = comp["collectives"].get("all-reduce", 0)
    n = n_dev
    wire = 2 * (n - 1) / n * ar
    t_ici_ms = wire / (ICI_GBPS * 1e9) * 1e3
    # FSDP wire traffic (zero on pure-DP meshes, whose HLO carries no
    # all-gather/reduce-scatter): parameters all-gather into the step,
    # gradients/optimizer state reduce-scatter out of it, both ringing
    # over the fsdp axis
    f = int(comp.get("fsdp", 1) or 1)
    ag = comp["collectives"].get("all-gather", 0)
    rs = comp["collectives"].get("reduce-scatter", 0)
    fsdp_wire = ((f - 1) / f) * (ag + rs) if f > 1 else 0.0
    t_fsdp_ms = fsdp_wire / (ICI_GBPS * 1e9) * 1e3
    t_dcn_ms = 0.0
    if dcn_slices > 1:
        # ring over slices: each slice boundary carries the full reduced
        # payload once per direction; per-host DCN bandwidth shared by
        # the 4 chips of a v4 host
        dcn_wire = 2 * (dcn_slices - 1) / dcn_slices * ar
        t_dcn_ms = dcn_wire / (DCN_GBPS * 1e9) * 1e3
    # off-step terms, amortized per step:
    #  - eval all-gather: every process contributes its padded
    #    predictions once per epoch (head dims ~4 f32 per graph at
    #    flagship scale; n_max rows ~ batch_per_chip * steps_per_epoch)
    eval_rows = BATCH_PER_CHIP * STEPS_PER_EPOCH
    eval_bytes = eval_rows * 4 * 4 * n  # rows x heads x f32 x processes
    t_eval_ms = eval_bytes / (DCN_GBPS * 1e9) * 1e3 / STEPS_PER_EPOCH
    #  - checkpoint: ZeRO-1 shards write param+opt (3x params f32) / n
    #    per chip to storage once per EPOCHS_PER_CHECKPOINT epochs
    ckpt_bytes = 3 * comp["param_bytes"] / n
    t_ckpt_ms = (
        ckpt_bytes / (DCN_GBPS * 1e9) * 1e3
        / (STEPS_PER_EPOCH * EPOCHS_PER_CHECKPOINT)
    )
    exposed = t_ici_ms + t_fsdp_ms + t_dcn_ms + t_eval_ms + t_ckpt_ms
    eff_no_overlap = STEP_MS_DEVICE / (STEP_MS_DEVICE + exposed)
    eff_half_overlap = STEP_MS_DEVICE / (STEP_MS_DEVICE + 0.5 * exposed)
    rec = {
        "n_devices": n,
        "fsdp": f,
        "dcn_slices": dcn_slices,
        "collective_bytes_per_step": comp["collectives"],
        "allreduce_bytes_per_step": int(ar),
        "wire_bytes_per_chip_ring": int(wire),
        "t_ici_ms": round(t_ici_ms, 3),
        "t_dcn_ms": round(t_dcn_ms, 3),
        "t_eval_allgather_ms_amortized": round(t_eval_ms, 4),
        "t_checkpoint_ms_amortized": round(t_ckpt_ms, 4),
        "dp_efficiency_no_overlap": round(eff_no_overlap, 4),
        "dp_efficiency_half_overlap": round(eff_half_overlap, 4),
    }
    if f > 1:
        rec.update(
            {
                "allgather_bytes_per_step": int(ag),
                "reduce_scatter_bytes_per_step": int(rs),
                "fsdp_wire_bytes_per_chip_ring": int(fsdp_wire),
                "t_fsdp_ms": round(t_fsdp_ms, 3),
                "state_bytes_per_device": comp.get("state_bytes_per_device"),
                "state_bytes_global": comp.get("state_bytes_global"),
            }
        )
    return rec


def skew_tolerance_block(widths: dict) -> dict:
    """Model-derived ``step_skew`` trigger defaults (consumed by
    ``obs/podview.py`` as the default threshold on the cross-host
    epoch-duration skew gauge). A layout's no-overlap efficiency already
    concedes ``1 - eff`` of step time to exposed wire; observed skew
    beyond ~4x that concession cannot be the modeled collectives and
    indicates a genuine straggler. The threshold is floored at 0.2
    (host-level noise on shared machines) and capped at 0.5."""
    per_width = {}
    worst = 0.0
    for name, w in sorted(widths.items()):
        eff = w.get("dp_efficiency_no_overlap")
        if eff is None:
            continue
        thr = round(min(0.5, max(0.2, 4.0 * (1.0 - float(eff)))), 4)
        per_width[name] = {
            "dp_efficiency_no_overlap": eff,
            "skew_frac_threshold": thr,
        }
        worst = max(worst, thr)
    return {
        "derivation": (
            "threshold = clamp(4 x (1 - dp_efficiency_no_overlap), 0.2, 0.5)"
        ),
        "per_width": per_width,
        "default_step_skew_threshold": round(worst, 4) if per_width else 0.25,
    }


def main():
    widths = {}
    comp_by_n = {}
    for n in MESH_SIZES:
        print(f"compiling {n}-way sharded step ...", file=sys.stderr)
        comp_by_n[n] = compile_width(n)
        widths[str(n)] = width_record(n, comp_by_n[n])
    # FSDP variants at the largest width: the (data = n/f, fsdp = f)
    # layouts of the SAME computation — all-gather/reduce-scatter wire
    # traffic read from their compiled HLO, state bytes per device from
    # the partitioner's committed shardings
    n_max = max(MESH_SIZES)
    fsdp_widths = [
        int(s)
        for s in os.environ.get("FSDP_WIDTHS", "2,4").split(",")
        if s.strip()
    ]
    for f in fsdp_widths:
        if f <= 1 or n_max % f:
            continue
        print(f"compiling {n_max}-way fsdp={f} step ...", file=sys.stderr)
        widths[f"{n_max}_fsdp{f}"] = width_record(
            n_max, compile_width(n_max, fsdp=f)
        )
    # multi-slice variants at 32-way: the data axis spanning 2 and 4
    # ICI slices (DCN between slices)
    if 32 in comp_by_n:
        for s in (2, 4):
            widths[f"32_dcn{s}slices"] = width_record(32, comp_by_n[32], dcn_slices=s)

    # v4-32 north-star projection from measured device time + derived
    # 16-way efficiency (replaces BASELINE.md's hand arithmetic)
    eff16 = widths.get("16", {}).get("dp_efficiency_no_overlap", None)
    step_ms_v4 = STEP_MS_DEVICE / V4_BW_SCALE
    gps_chip_v4 = BATCH_PER_CHIP / step_ms_v4 * 1e3
    projection = {
        "platform": "v4-32 (16 chips, one ICI slice)",
        "assumption": (
            "bandwidth-bound workload: per-chip step time scales with "
            "HBM bandwidth (v4 1228 / v5e 820); efficiency from the "
            "16-way compiled-HLO model (no-overlap floor)"
        ),
        "step_ms_device_v4_chip": round(step_ms_v4, 2),
        "graphs_per_sec_per_chip_v4": round(gps_chip_v4, 1),
        "dp_efficiency_16way": eff16,
        "aggregate_graphs_per_sec": (
            round(V4_32_CHIPS * gps_chip_v4 * eff16, 1) if eff16 else None
        ),
    }

    rec = {
        "mesh": (
            "Partitioner (data[, fsdp]) over ICI (+DCN variants); "
            "fsdp variants shard params+optimizer over the fsdp axis"
        ),
        "step_ms_device_single_chip": STEP_MS_DEVICE,
        "batch_per_chip": BATCH_PER_CHIP,
        "ici_gbps_assumed": ICI_GBPS,
        "dcn_gbps_assumed": DCN_GBPS,
        "steps_per_epoch_assumed": STEPS_PER_EPOCH,
        "param_bytes_f32": comp_by_n[MESH_SIZES[0]]["param_bytes"],
        "widths": widths,
        "skew_tolerance": skew_tolerance_block(widths),
        "v4_32_projection": projection,
        "note": (
            "Collective bytes read from compiled SPMD HLO at each width "
            "(virtual CPU mesh; same partitioner as TPU). Efficiency = "
            "compute / (compute + exposed wire time); no-overlap is the "
            "floor, half-overlap the planning number. Off-step terms "
            "(eval padded all-gather, ZeRO-1 sharded checkpoint write) "
            "amortized at the stated cadence. SCALING_cpu8.json remains "
            "correctness-only evidence (shared-core timings are not a "
            "scaling measurement)."
        ),
    }
    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        os.environ.get("SCALING_OUT", "SCALING_est_r06.json"),
    )
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec, indent=1))


if __name__ == "__main__":
    main()
