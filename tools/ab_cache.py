"""Measure the flagship aligned step with DEVICE-RESIDENT batches vs
host batches (H2D per step): quantifies the transfer share of the wall
step. Usage: python tools/ab_cache.py [steps]"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.flagship import build_flagship
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 8
t0 = time.time()
config, model, variables, loader = build_flagship(
    n_samples=1280, hidden_dim=128, num_conv_layers=6, batch_size=1024,
    unit_cells=(2, 4),
)
tx = select_optimizer(config["NeuralNetwork"]["Training"])
state0 = create_train_state(variables, tx)
step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)
host_batches = list(loader)
b0 = host_batches[0]
print(f"[{time.time()-t0:.0f}s] edge_pad={b0.senders.shape[0]} run_align={b0.run_align}", flush=True)
dev_batches = [jax.device_put(b) for b in host_batches]
compiled = step.lower(state0, host_batches[0]).compile()
print(f"[{time.time()-t0:.0f}s] compiled", flush=True)

states = {k: jax.tree_util.tree_map(jnp.copy, state0) for k in ("host", "device")}
for k, batches in (("host", host_batches), ("device", dev_batches)):
    states[k], loss, _ = compiled(states[k], batches[0])
    np.asarray(loss)

K = 4
res = {"host": [], "device": []}
for seg in range(STEPS // K):
    for k, batches in (("host", host_batches), ("device", dev_batches)):
        t1 = time.perf_counter()
        for i in range(K):
            states[k], loss, _ = compiled(states[k], batches[(seg * K + i) % len(batches)])
        np.asarray(loss)
        res[k].append((time.perf_counter() - t1) / K * 1e3)

for k, ts in res.items():
    med = sorted(ts)[len(ts) // 2]
    print(f"{k}: segments={['%.1f' % t for t in ts]} median={med:.1f} g/s={1024/med*1e3:.0f}")
