"""Capture a device trace of the flagship train step and write an xplane
profile under TRACE_DIR (default /tmp/tb_flagship). Dev tooling: pair
with tools/parse_trace.py to get the per-HLO-op time table that drove
the r03 backward-gather finding (docs/PERF.md)."""

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

t0 = time.time()


def log(msg):
    print(f"[{time.time()-t0:7.1f}s] {msg}", flush=True)


from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.flagship import build_flagship
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

config, model, variables, loader = build_flagship(
    n_samples=1280, hidden_dim=128, num_conv_layers=6, batch_size=1024,
    unit_cells=(2, 4),
)
log("flagship built")
tx = select_optimizer(config["NeuralNetwork"]["Training"])
state = create_train_state(variables, tx)
step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)
batches = list(loader)
compiled = step.lower(state, batches[0]).compile()
log("compiled")

state, loss, _ = compiled(state, batches[0])
np.asarray(loss)
log(f"warmup done loss={float(loss):.4f}")

trace_dir = os.environ.get("TRACE_DIR", "/tmp/tb_flagship")
import shutil
shutil.rmtree(trace_dir, ignore_errors=True)
with jax.profiler.trace(trace_dir):
    for i in range(3):
        state, loss, _ = compiled(state, batches[(i + 1) % len(batches)])
    np.asarray(loss)
log("traced 3 steps")

planes = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
log(f"xplane files: {planes}")
