"""Exit-code health probe for a running ModelServer — the command an
orchestrator's liveness/readiness check runs.

The server exports its health gauges into a Prometheus textfile
(``ServeConfig.prometheus_path`` makes the dispatch supervisor's
monitor thread rewrite it every ``prometheus_every_s``; or call
``ModelServer.export_prometheus`` yourself). This CLI turns that file
into the contract probes speak:

    python tools/serve_probe.py --prom /run/serve.prom            # readiness
    python tools/serve_probe.py --prom /run/serve.prom --live     # liveness
    python tools/serve_probe.py --prom /run/serve.prom --max-age 30

Exit codes:
    0  the probed gauge (``hydragnn_serve_ready`` / ``_live``) is 1 and
       the file is fresh
    1  the gauge is 0 — the server says it is not ready/live
    2  no evidence: file missing, unparseable, gauge absent, or STALE
       (mtime older than ``--max-age``; a server that stopped exporting
       is indistinguishable from a dead one, so staleness fails the
       probe rather than trusting an old "ready")

``--verbose`` prints what was decided and why (probes are run by
machines, so the default is silent).

Pilot mode (``hydragnn_tpu/pilot``, docs/RESILIENCE.md "Closed
loop"): ``--pilot`` probes the retrain pilot's gauges in the same
textfile (``hydragnn_serve_pilot_state`` — the integer state code —
and ``hydragnn_serve_pilot_last_cycle_ok``):

    python tools/serve_probe.py --prom /run/serve.prom --pilot

    0  pilot attached and not stuck, last cycle (if any) succeeded
    1  pilot STUCK (terminal; human intervention) or last cycle failed
    2  no pilot gauges in the textfile (none attached, or stale)

Fleet mode (``hydragnn_tpu/fleet``, docs/FLEET.md): ``--fleet DIR``
probes every replica textfile plus the router's in the directory
``Fleet.export_probes`` writes (``r*.prom`` + ``router.prom``), prints
a one-line-per-replica table, and aggregates:

    python tools/serve_probe.py --fleet /run/fleet/

    0  router serving and EVERY replica healthy
    1  degraded-but-serving: the router still routes (>=1 ready
       replica) but some replica is down, not ready, or stale
    2  fleet down: the router reports not-ready, its file is
       missing/stale, or there are no replica files at all
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import time


def parse_prometheus_gauge(text: str, name: str):
    """First sample value of ``name`` (any label set) in an exposition-
    format body, or None when absent."""
    pat = re.compile(rf"^{re.escape(name)}(?:\{{[^}}]*\}})?\s+([^\s]+)\s*$", re.M)
    m = pat.search(text)
    if m is None:
        return None
    try:
        return float(m.group(1))
    except ValueError:
        return None


def probe(path: str, mode: str = "ready", max_age_s: float = 60.0):
    """Returns (exit_code, message)."""
    gauge = f"hydragnn_serve_{mode}"
    try:
        age = time.time() - os.stat(path).st_mtime
    except OSError as exc:
        return 2, f"no textfile at {path!r} ({exc.__class__.__name__})"
    if max_age_s > 0 and age > max_age_s:
        return 2, f"textfile is stale ({age:.1f}s old > --max-age {max_age_s:g}s)"
    try:
        with open(path) as f:
            text = f.read()
    except OSError as exc:
        return 2, f"unreadable textfile {path!r} ({exc.__class__.__name__})"
    value = parse_prometheus_gauge(text, gauge)
    if value is None:
        return 2, f"gauge {gauge} not found in {path!r}"
    if value >= 1.0:
        return 0, f"{gauge}=1 (age {age:.1f}s)"
    return 1, f"{gauge}={value:g} — server reports not {mode}"


#: pilot/pilot.py STATE_CODES, inverted for narration (the gauge is the
#: integer code so probes stay numeric)
_PILOT_STATES = (
    "idle",
    "drift_confirmed",
    "fine_tuning",
    "canary",
    "reloading",
    "cooldown",
    "stuck",
)
_PILOT_STUCK = _PILOT_STATES.index("stuck")


def probe_pilot(path: str, max_age_s: float = 60.0):
    """Probe the retrain pilot's gauges in the same textfile: exit 0
    while the pilot is in any non-terminal state, 1 when it is STUCK
    (or its last cycle failed — a human should look), 2 when no pilot
    gauges are exported (no pilot attached, stale or missing file)."""
    try:
        age = time.time() - os.stat(path).st_mtime
    except OSError as exc:
        return 2, f"no textfile at {path!r} ({exc.__class__.__name__})"
    if max_age_s > 0 and age > max_age_s:
        return 2, f"textfile is stale ({age:.1f}s old > --max-age {max_age_s:g}s)"
    try:
        with open(path) as f:
            text = f.read()
    except OSError as exc:
        return 2, f"unreadable textfile {path!r} ({exc.__class__.__name__})"
    state = parse_prometheus_gauge(text, "hydragnn_serve_pilot_state")
    if state is None:
        return 2, f"gauge hydragnn_serve_pilot_state not found in {path!r}"
    code = int(state)
    name = (
        _PILOT_STATES[code] if 0 <= code < len(_PILOT_STATES) else f"?{code}"
    )
    last_ok = parse_prometheus_gauge(text, "hydragnn_serve_pilot_last_cycle_ok")
    outcome = {1.0: "ok", 0.0: "failed", -1.0: "none"}.get(last_ok, "absent")
    msg = f"pilot state={name} last_cycle={outcome} (age {age:.1f}s)"
    if code == _PILOT_STUCK:
        return 1, msg + " — pilot is STUCK, human intervention required"
    if last_ok == 0.0:
        return 1, msg + " — last retrain cycle failed"
    return 0, msg


ROUTER_FILE = "router.prom"


def probe_fleet(directory: str, mode: str = "ready", max_age_s: float = 60.0):
    """Probe every ``*.prom`` in ``directory`` (``router.prom`` is the
    router, the rest are replicas). Returns ``(exit_code, rows)`` with
    one ``(name, rc, msg)`` row per file probed, router first."""
    try:
        names = sorted(
            f for f in os.listdir(directory) if f.endswith(".prom")
        )
    except OSError as exc:
        return 2, [("router", 2, f"no fleet probe dir {directory!r} "
                    f"({exc.__class__.__name__})")]
    rows = []
    router_rc = 2
    if ROUTER_FILE in names:
        names.remove(ROUTER_FILE)
        router_rc, msg = probe(
            os.path.join(directory, ROUTER_FILE), mode=mode, max_age_s=max_age_s
        )
        rows.append(("router", router_rc, msg))
    else:
        rows.append(("router", 2, f"no {ROUTER_FILE} in {directory!r}"))
    replica_rcs = []
    for name in names:
        rc, msg = probe(
            os.path.join(directory, name), mode=mode, max_age_s=max_age_s
        )
        rows.append((name[: -len(".prom")], rc, msg))
        replica_rcs.append(rc)
    if router_rc != 0 or not replica_rcs:
        return 2, rows
    if all(rc == 0 for rc in replica_rcs):
        return 0, rows
    return 1, rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--prom",
        help="Prometheus textfile the server exports "
        "(ServeConfig.prometheus_path / ModelServer.export_prometheus)",
    )
    src.add_argument(
        "--fleet",
        metavar="DIR",
        help="probe a whole fleet: the directory Fleet.export_probes "
        "writes (r*.prom per replica + router.prom); aggregate exit "
        "0 all healthy / 1 degraded-but-serving / 2 fleet down",
    )
    g = p.add_mutually_exclusive_group()
    g.add_argument(
        "--ready",
        action="store_true",
        help="probe readiness (warm buckets + queue below high-water; default)",
    )
    g.add_argument(
        "--live",
        action="store_true",
        help="probe liveness only (dispatch thread beating)",
    )
    g.add_argument(
        "--pilot",
        action="store_true",
        help="probe the retrain pilot: exit 0 healthy, 1 stuck or "
        "last cycle failed, 2 no pilot gauges exported "
        "(--prom mode only)",
    )
    p.add_argument(
        "--max-age",
        type=float,
        default=60.0,
        help="fail (exit 2) when the textfile is older than this many "
        "seconds (0 disables; default 60)",
    )
    p.add_argument("--verbose", action="store_true", help="print the verdict")
    args = p.parse_args(argv)
    mode = "live" if args.live else "ready"
    if args.pilot:
        if not args.prom:
            print("serve_probe: --pilot needs --prom", file=sys.stderr)
            return 2
        rc, msg = probe_pilot(args.prom, max_age_s=args.max_age)
        if args.verbose or rc != 0:
            print(f"serve_probe[pilot]: {msg}", file=sys.stderr)
        return rc
    if args.fleet:
        rc, rows = probe_fleet(args.fleet, mode=mode, max_age_s=args.max_age)
        width = max(len(name) for name, _, _ in rows)
        for name, row_rc, msg in rows:
            verdict = {0: "ok", 1: "not-" + mode}.get(row_rc, "no-evidence")
            print(f"{name:<{width}}  {verdict:<11}  {msg}")
        label = {0: "healthy", 1: "degraded-but-serving", 2: "fleet down"}[rc]
        if args.verbose or rc != 0:
            print(f"serve_probe[fleet/{mode}]: {label}", file=sys.stderr)
        return rc
    rc, msg = probe(args.prom, mode=mode, max_age_s=args.max_age)
    if args.verbose or rc != 0:
        print(f"serve_probe[{mode}]: {msg}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
