"""Sweep kernel tile sizes (HYDRAGNN_BN x HYDRAGNN_CE x
HYDRAGNN_BCAST_CE — the gather kernel's chunk reads only the latter)
on the flagship step, traced device time per setting (subprocess per
setting — the constants bake at import).

Usage: python tools/tune_tiles.py [BNxCE[xBCE] ...]
(BCE defaults to the package default when omitted)"""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import glob, os, shutil, sys, time
sys.path.insert(0, %(here)r)
from hydragnn_tpu.utils.platform import pin_platform_from_env
pin_platform_from_env()
import jax, jax.numpy as jnp, numpy as np
from hydragnn_tpu.flagship import build_flagship
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

import os as _os
if _os.environ.get("TUNE_CONFIG") == "large":
    config, model, variables, loader = build_flagship(
        n_samples=48, hidden_dim=128, num_conv_layers=6, batch_size=32,
        unit_cells=(6, 8),
    )
else:
    config, model, variables, loader = build_flagship(
        n_samples=1280, hidden_dim=128, num_conv_layers=6, batch_size=1024,
        unit_cells=(2, 4),
    )
tx = select_optimizer(config["NeuralNetwork"]["Training"])
state = create_train_state(variables, tx)
step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)
batch = next(iter(loader))
compiled = step.lower(state, batch).compile()
state, loss, _ = compiled(state, batch)
np.asarray(loss)
tdir = "/tmp/tune_trace"
shutil.rmtree(tdir, ignore_errors=True)
with jax.profiler.trace(tdir):
    for _ in range(3):
        state, loss, _ = compiled(state, batch)
    np.asarray(loss)
planes = glob.glob(f"{tdir}/**/*.xplane.pb", recursive=True)
from xprof.convert import raw_to_tool_data as rd
import json as _json
data, _ = rd.xspace_to_tool_data(planes, "hlo_stats", {"tqx": "out:csv;"})
tab = _json.loads(data.decode() if isinstance(data, bytes) else data)
cols = [c["id"] for c in tab["cols"]]
i_t = cols.index("total_self_time")
i_c = cols.index("category")
tot = pall = 0.0
for r in tab["rows"]:
    t = float((r["c"][i_t] or {}).get("v") or 0)
    tot += t
    if (r["c"][i_c] or {}).get("v") == "custom-call":
        pall += t
print(f"RESULT device={tot/3e3:.2f} pallas={pall/3e3:.2f} loss={float(loss):.5f}")
"""


def run(bn, ce, bce=None):
    env = dict(os.environ, HYDRAGNN_BN=str(bn), HYDRAGNN_CE=str(ce))
    if bce is not None:
        env["HYDRAGNN_BCAST_CE"] = str(bce)
    tag = f"BN={bn} CE={ce}" + (f" BCE={bce}" if bce is not None else "")
    out = subprocess.run(
        [sys.executable, "-c", CHILD % {"here": HERE}],
        env=env, capture_output=True, text=True, timeout=560,
    )
    for line in out.stdout.splitlines():
        if line.startswith("RESULT"):
            print(f"{tag}: {line[7:]}", flush=True)
            return
    print(f"{tag}: FAILED\n{out.stderr[-500:]}", flush=True)


if __name__ == "__main__":
    # r05-measured gather-chunk sweep included: 512/1024/2048 traced
    # 77.8 / 75.9 / 79.7 ms on the flagship (docs/PERF.md)
    settings = [
        (128, 512, None),
        (256, 512, None),
        (128, 512, 512),
        (128, 512, 2048),
        (128, 1024, None),
    ]
    if len(sys.argv) > 1:
        settings = []
        for s in sys.argv[1:]:
            parts = list(map(int, s.split("x")))
            settings.append(tuple(parts) if len(parts) == 3 else (*parts, None))
    for bn, ce, bce in settings:
        run(bn, ce, bce)
