"""Sweep kernel tile sizes (HYDRAGNN_BN x HYDRAGNN_CE x
HYDRAGNN_BCAST_CE — the gather kernel's chunk reads only the latter)
on the flagship step, traced device time per setting (subprocess per
setting — the constants bake at import).

Usage: python tools/tune_tiles.py [--save] [BNxCE[xBCE] ...]
(BCE defaults to the package default when omitted)

``--save`` persists the sweep's best setting (minimum traced device
ms) into the committed ``TUNE_TILES.json`` at the repo root, keyed
``(shape_tag, device_kind)`` — shape_tag is ``TUNE_CONFIG`` (default
"flagship"), device_kind is what the child measured on.
``hydragnn_tpu/ops/segment_pallas.py`` (and through it
``ops/fused_conv.py``, which imports BN/CE from there) reads its
import-time tile defaults from that table via ``HYDRAGNN_TILE_SHAPE``
/ ``HYDRAGNN_DEVICE_KIND``; the explicit HYDRAGNN_BN/CE/BCAST_CE env
knobs always win. Commit the updated JSON."""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import glob, os, shutil, sys, time
sys.path.insert(0, %(here)r)
from hydragnn_tpu.utils.platform import pin_platform_from_env
pin_platform_from_env()
import jax, jax.numpy as jnp, numpy as np
from hydragnn_tpu.flagship import build_flagship
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

import os as _os
if _os.environ.get("TUNE_CONFIG") == "large":
    config, model, variables, loader = build_flagship(
        n_samples=48, hidden_dim=128, num_conv_layers=6, batch_size=32,
        unit_cells=(6, 8),
    )
else:
    config, model, variables, loader = build_flagship(
        n_samples=1280, hidden_dim=128, num_conv_layers=6, batch_size=1024,
        unit_cells=(2, 4),
    )
tx = select_optimizer(config["NeuralNetwork"]["Training"])
state = create_train_state(variables, tx)
step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)
batch = next(iter(loader))
compiled = step.lower(state, batch).compile()
state, loss, _ = compiled(state, batch)
np.asarray(loss)
tdir = "/tmp/tune_trace"
shutil.rmtree(tdir, ignore_errors=True)
with jax.profiler.trace(tdir):
    for _ in range(3):
        state, loss, _ = compiled(state, batch)
    np.asarray(loss)
planes = glob.glob(f"{tdir}/**/*.xplane.pb", recursive=True)
from xprof.convert import raw_to_tool_data as rd
import json as _json
data, _ = rd.xspace_to_tool_data(planes, "hlo_stats", {"tqx": "out:csv;"})
tab = _json.loads(data.decode() if isinstance(data, bytes) else data)
cols = [c["id"] for c in tab["cols"]]
i_t = cols.index("total_self_time")
i_c = cols.index("category")
tot = pall = 0.0
for r in tab["rows"]:
    t = float((r["c"][i_t] or {}).get("v") or 0)
    tot += t
    if (r["c"][i_c] or {}).get("v") == "custom-call":
        pall += t
kind = getattr(jax.devices()[0], "device_kind", "unknown").replace(" ", "_")
print(f"RESULT device={tot/3e3:.2f} pallas={pall/3e3:.2f} loss={float(loss):.5f} kind={kind}")
"""


def run(bn, ce, bce=None):
    env = dict(os.environ, HYDRAGNN_BN=str(bn), HYDRAGNN_CE=str(ce))
    if bce is not None:
        env["HYDRAGNN_BCAST_CE"] = str(bce)
    tag = f"BN={bn} CE={ce}" + (f" BCE={bce}" if bce is not None else "")
    out = subprocess.run(
        [sys.executable, "-c", CHILD % {"here": HERE}],
        env=env, capture_output=True, text=True, timeout=560,
    )
    for line in out.stdout.splitlines():
        if line.startswith("RESULT"):
            print(f"{tag}: {line[7:]}", flush=True)
            try:
                fields = dict(p.split("=", 1) for p in line[7:].split())
                return {
                    "BN": bn,
                    "CE": ce,
                    "BCAST_CE": bce,
                    "device_ms": float(fields["device"]),
                    "kind": fields.get("kind", "unknown"),
                }
            except (KeyError, ValueError):
                return None
    print(f"{tag}: FAILED\n{out.stderr[-500:]}", flush=True)
    return None


def save_best(results) -> None:
    """Merge the sweep's best (min traced device ms) setting into the
    committed TUNE_TILES.json under (shape_tag, device_kind)."""
    best = min(results, key=lambda r: r["device_ms"])
    shape_tag = os.environ.get("TUNE_CONFIG") or "flagship"
    path = os.path.join(HERE, "TUNE_TILES.json")
    table = {}
    if os.path.exists(path):
        with open(path) as f:
            table = json.load(f)
    entry = {
        "BN": best["BN"],
        "CE": best["CE"],
        "device_ms": best["device_ms"],
    }
    if best["BCAST_CE"] is not None:
        entry["BCAST_CE"] = best["BCAST_CE"]
    table.setdefault(shape_tag, {})[best["kind"]] = entry
    with open(path, "w") as f:
        json.dump(table, f, indent=1, sort_keys=True)
        f.write("\n")
    print(
        f"saved best setting BN={best['BN']} CE={best['CE']} "
        f"BCE={best['BCAST_CE']} ({best['device_ms']} ms) -> {path} "
        f"[{shape_tag}:{best['kind']}] — commit it; consumers select it "
        f"via HYDRAGNN_TILE_SHAPE={shape_tag} "
        f"HYDRAGNN_DEVICE_KIND={best['kind']}"
    )


if __name__ == "__main__":
    argv = [a for a in sys.argv[1:] if a != "--save"]
    save = len(argv) != len(sys.argv) - 1
    # r05-measured gather-chunk sweep included: 512/1024/2048 traced
    # 77.8 / 75.9 / 79.7 ms on the flagship (docs/PERF.md)
    settings = [
        (128, 512, None),
        (256, 512, None),
        (128, 512, 512),
        (128, 512, 2048),
        (128, 1024, None),
    ]
    if argv:
        settings = []
        for s in argv:
            parts = list(map(int, s.split("x")))
            settings.append(tuple(parts) if len(parts) == 3 else (*parts, None))
    results = [r for r in (run(bn, ce, bce) for bn, ce, bce in settings) if r]
    if save:
        if not results:
            print("no successful settings — nothing to save")
            sys.exit(1)
        save_best(results)
