"""Decomposition profile of the flagship train step on the real chip.

Times each segment op at the flagship shape (E=699368 pad, H=128,
N=32752 pad) plus the whole step under auto-Pallas vs forced-XLA, via
the scan-slope protocol (2 dispatches per measurement, RTT cancels).
Scratch tooling — not part of the package.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.utils.profile import scan_slope_ms

WHICH = os.environ.get("PROF_WHICH", "ops,step").split(",")
results = {}


def chain_op(fn, *args, k1=2, k2=8):
    """Scan-slope time fn(*args) with a data dependency threaded through
    the carry so the chain cannot be parallelized or DCE'd."""

    def make_chain(k):
        def body(carry, _):
            out = fn(*args, carry)
            return out, ()

        chained = jax.jit(lambda c: jax.lax.scan(body, c, None, length=k)[0])

        def run():
            out = chained(jnp.zeros((), jnp.float32))
            np.asarray(out)

        return run

    return scan_slope_ms(make_chain, k1, k2)


def main():
    E, N, H = 699368, 32752, 128
    key = jax.random.PRNGKey(0)
    # receiver-sorted edges with realistic degree (~21 edges/node)
    recv = jnp.sort(jax.random.randint(key, (E,), 0, N, jnp.int32))
    send = jax.random.randint(jax.random.PRNGKey(1), (E,), 0, N, jnp.int32)
    perm = jnp.argsort(send)
    mask = jnp.ones((E,), bool)
    v = jax.random.normal(jax.random.PRNGKey(2), (E, H), jnp.bfloat16)
    xnode = jax.random.normal(jax.random.PRNGKey(3), (N, H), jnp.bfloat16)
    g_node = jax.random.normal(jax.random.PRNGKey(4), (N, H), jnp.bfloat16)

    from hydragnn_tpu.graph import segment as S
    from hydragnn_tpu.ops import segment_sum_family

    if "ops" in WHICH:
        # --- forward ops (carry c threads the dependency) ---
        def f_family(c):
            s, sq, cnt = segment_sum_family(
                v + c, recv, N, mask=mask, indices_are_sorted=True
            )
            return s.sum().astype(jnp.float32)

        def f_max(c):
            return S.segment_max(
                v + c, recv, N, mask=mask, indices_are_sorted=True
            ).sum().astype(jnp.float32)

        def f_minmax_fused(c):
            both = jnp.concatenate([v + c, -(v + c)], axis=-1)
            out = S.segment_max(both, recv, N, mask=mask, indices_are_sorted=True)
            return out.sum().astype(jnp.float32)

        def f_gather(c):
            return S.gather_rows_permuted(xnode + c, send, perm, N).sum().astype(
                jnp.float32
            )

        # --- fwd+bwd versions ---
        def g_of(f):
            grad = jax.grad(lambda c: f(c))
            return grad

        for name, f in [
            ("family_fwd", f_family),
            ("max_fwd", f_max),
            ("minmax_fused2H_fwd", f_minmax_fused),
            ("gather_fwd", f_gather),
        ]:
            ms = chain_op(lambda c, _f=f: _f(c))
            results[name] = round(ms, 3)
            print(name, results[name], flush=True)

        for name, f in [
            ("family_fwdbwd", f_family),
            ("max_fwdbwd", f_max),
            ("minmax_fused2H_fwdbwd", f_minmax_fused),
            ("gather_fwdbwd", f_gather),
        ]:
            gf = g_of(f)
            ms = chain_op(lambda c, _g=gf: _g(c))
            results[name] = round(ms, 3)
            print(name, results[name], flush=True)

    if "step" in WHICH:
        from hydragnn_tpu.flagship import build_flagship
        from hydragnn_tpu.train import (
            create_train_state,
            make_train_step,
            select_optimizer,
        )
        from hydragnn_tpu.train.state import _train_step_body

        config, model, variables, loader = build_flagship(
            n_samples=1280,
            hidden_dim=128,
            num_conv_layers=6,
            batch_size=1024,
            unit_cells=(2, 4),
        )
        tx = select_optimizer(config["NeuralNetwork"]["Training"])
        state = create_train_state(variables, tx)
        body = _train_step_body(model, tx, compute_dtype=jnp.bfloat16)
        batch0 = next(iter(loader))

        def make_chain(k):
            def f(st, _):
                st, loss, _ = body(st, batch0)
                return st, loss

            fn = jax.jit(lambda st: jax.lax.scan(f, st, None, length=k))

            def run():
                _, losses = fn(state)
                np.asarray(losses[-1])

            return run

        results["step_auto"] = round(scan_slope_ms(make_chain, 4, 12), 3)
        print("step_auto", results["step_auto"], flush=True)

        # forced XLA step
        os.environ["HYDRAGNN_PALLAS"] = "0"
        body_xla = _train_step_body(model, tx, compute_dtype=jnp.bfloat16)

        def make_chain_xla(k):
            def f(st, _):
                st, loss, _ = body_xla(st, batch0)
                return st, loss

            fn = jax.jit(lambda st: jax.lax.scan(f, st, None, length=k))

            def run():
                _, losses = fn(state)
                np.asarray(losses[-1])

            return run

        results["step_xla"] = round(scan_slope_ms(make_chain_xla, 4, 12), 3)
        print("step_xla", results["step_xla"], flush=True)
        os.environ["HYDRAGNN_PALLAS"] = "auto"

    print(json.dumps(results))


if __name__ == "__main__":
    main()
