"""Incident-bundle reporter: render and validate auto-captured bundles.

An SLO trigger firing (hydragnn_tpu/obs/triggers.py) writes a
self-contained bundle under ``<run log dir>/incidents/<id>/``; this is
the human view over it — the first page of a post-mortem:

    python tools/incident_report.py logs/run/incidents            # all
    python tools/incident_report.py logs/run/incidents/i001-...   # one
    python tools/incident_report.py --validate logs/run/incidents

A directory argument that itself contains ``incident_manifest.json``
is treated as one bundle; any other directory is scanned as an
``incidents/`` root. ``--validate`` exits 1 when any bundle fails the
manifest schema or claims files that do not exist; a bundle with NO
manifest renders (and validates) as the crashed-mid-capture case it is.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as `python tools/incident_report.py`
    sys.path.insert(0, _REPO)

from hydragnn_tpu.obs.triggers import (  # noqa: E402
    INCIDENT_MANIFEST,
    list_incidents,
    validate_incident_bundle,
)


def _load_manifest(bundle_dir: str) -> Optional[dict]:
    path = os.path.join(bundle_dir, INCIDENT_MANIFEST)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fmt_t(t) -> str:
    if not isinstance(t, (int, float)):
        return "?"
    import datetime

    return datetime.datetime.fromtimestamp(t).strftime("%Y-%m-%d %H:%M:%S")


def render_bundle(bundle_dir: str) -> str:
    """One bundle's story as text: verdict, capture, evidence files."""
    lines: List[str] = [f"== incident {os.path.basename(bundle_dir)} =="]
    man = _load_manifest(bundle_dir)
    if man is None:
        lines.append(
            "  NO MANIFEST — the run died mid-capture; whatever sidecars"
        )
        lines.append("  landed before the crash are below:")
        for name in sorted(os.listdir(bundle_dir)):
            lines.append(f"    {name}")
        return "\n".join(lines)
    trig = man.get("trigger") or {}
    lines.append(
        f"  rule: {man.get('rule')} ({man.get('kind')})"
        f"  status: {man.get('status')}"
    )
    lines.append(f"  fired: {_fmt_t(trig.get('fired_t'))}")
    obs, thr = trig.get("observed"), trig.get("threshold")
    metric = trig.get("metric")
    if trig.get("injected"):
        lines.append(f"  verdict: INJECTED ({metric}, threshold {thr})")
    else:
        lines.append(f"  verdict: {metric} observed {obs} vs threshold {thr}")
    for k, v in sorted((trig.get("detail") or {}).items()):
        lines.append(f"    {k}: {v}")
    prof = man.get("profile") or {}
    lines.append(
        f"  profile: captured={prof.get('captured')} "
        f"steps={prof.get('steps')} duration_s={prof.get('duration_s')} "
        f"nonempty={prof.get('nonempty')}"
    )
    lines.append("  files:")
    for label, rel in sorted((man.get("files") or {}).items()):
        path = os.path.join(bundle_dir, str(rel))
        try:
            size = os.path.getsize(path)
        except OSError:
            size = "MISSING"
        lines.append(f"    {label}: {rel} ({size} bytes)")
    hyg = _read_json(os.path.join(bundle_dir, "chip_hygiene.json"))
    if hyg is not None and hyg.get("available"):
        lines.append(
            f"  chip hygiene: targets_present={hyg.get('targets_present')} "
            f"foreign_holders={hyg.get('foreign_holder_count')}"
        )
    mem = _read_json(os.path.join(bundle_dir, "memory.json"))
    if mem is not None and mem.get("available"):
        lines.append(
            f"  device memory: in_use={mem.get('bytes_in_use')} "
            f"peak={mem.get('peak_bytes_in_use')} limit={mem.get('bytes_limit')}"
        )
    return "\n".join(lines)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            data = json.load(f)
        return data if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None


def _resolve_bundles(arg: str) -> List[str]:
    """A bundle dir is its own result; any other dir is an incidents
    root (possibly empty)."""
    if os.path.exists(os.path.join(arg, INCIDENT_MANIFEST)):
        return [arg]
    return list_incidents(arg)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument(
        "paths", nargs="+",
        help="incident bundle dir(s) or incidents/ root dir(s)",
    )
    p.add_argument(
        "--validate",
        action="store_true",
        help="schema-check bundles instead of rendering; exit 1 on problems",
    )
    args = p.parse_args(argv)

    bundles: List[str] = []
    for arg in args.paths:
        found = _resolve_bundles(arg)
        if not found:
            print(f"{arg}: no incident bundles")
        bundles.extend(found)

    rc = 0
    for bundle in bundles:
        if args.validate:
            problems = validate_incident_bundle(bundle)
            if problems:
                rc = 1
                print(f"{bundle}: INVALID ({len(problems)} problem(s))")
                for prob in problems:
                    print(f"  - {prob}")
            else:
                print(f"{bundle}: OK")
        else:
            print(render_bundle(bundle))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
