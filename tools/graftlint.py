#!/usr/bin/env python3
"""graftlint — run the repo's AST invariant linter (docs/LINT.md).

Usage:
    python tools/graftlint.py                       # full tree, all rules
    python tools/graftlint.py --changed             # fast pre-commit loop
    python tools/graftlint.py --rule HG002 --strict hydragnn_tpu bench.py
    python tools/graftlint.py --json /tmp/findings.json
    python tools/graftlint.py --artifacts           # validate committed artifacts
    python tools/graftlint.py --list-rules

Exit codes: 0 clean, 1 findings, 2 usage/internal error.

The lint package is loaded standalone (importlib, not ``import
hydragnn_tpu``): the package root pulls in jax-adjacent subpackages,
and the linter must run in milliseconds on any container with a bare
CPython — CI calls it before anything heavyweight is proven healthy.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint_pkg():
    """Load ``hydragnn_tpu.lint`` as a standalone package named
    ``_graftlint`` so relative imports inside it resolve without ever
    executing ``hydragnn_tpu/__init__.py``."""
    pkg_dir = os.path.join(REPO_ROOT, "hydragnn_tpu", "lint")
    spec = importlib.util.spec_from_file_location(
        "_graftlint",
        os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    pkg = importlib.util.module_from_spec(spec)
    sys.modules["_graftlint"] = pkg
    spec.loader.exec_module(pkg)
    core = importlib.import_module("_graftlint.core")
    rules = importlib.import_module("_graftlint.rules")
    artifacts = importlib.import_module("_graftlint.artifacts")
    return core, rules, artifacts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the whole tree)",
    )
    parser.add_argument(
        "--rule",
        action="append",
        default=None,
        metavar="HGNNN",
        help="run only this rule id (repeatable)",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="exit nonzero on any finding regardless of severity",
    )
    parser.add_argument(
        "--json",
        metavar="PATH",
        default=None,
        help="also write findings as JSON ('-' for stdout)",
    )
    parser.add_argument(
        "--baseline",
        metavar="PATH",
        default=os.path.join("tools", "graftlint_baseline.json"),
        help="baseline file of grandfathered findings "
        "(default: tools/graftlint_baseline.json)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline file",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--changed",
        action="store_true",
        help="lint only files git reports as changed vs HEAD",
    )
    parser.add_argument(
        "--artifacts",
        action="store_true",
        help="validate committed machine artifacts (flight JSONLs + "
        "BENCH_r*/SCALING_*/MULTICHIP_*/TUNE_TILES/BENCH_CI_BASELINE "
        "JSON schemas) instead of linting source",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    core, rules_mod, artifacts_mod = _load_lint_pkg()
    all_rules = rules_mod.all_rules(REPO_ROOT)

    if args.list_rules:
        for rule in all_rules:
            print(f"{rule.id}  {rule.name:28s} [{rule.severity}] "
                  f"{rule.description}")
        return 0

    if args.artifacts:
        findings = artifacts_mod.validate_artifacts(
            REPO_ROOT, args.paths or None
        )
        for f in findings:
            print(f.render())
        _emit_json(args.json, findings)
        if findings:
            print(f"graftlint --artifacts: {len(findings)} problem(s)")
            return 1
        print("graftlint --artifacts: all committed artifacts valid")
        return 0

    rules = all_rules
    if args.rule:
        wanted = {r.upper() for r in args.rule}
        rules = [r for r in all_rules if r.id in wanted]
        unknown = wanted - {r.id for r in all_rules}
        if unknown:
            print(f"graftlint: unknown rule id(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    paths = args.paths or None
    if args.changed:
        paths = core.changed_paths(REPO_ROOT)
        if not paths:
            print("graftlint: no changed python files")
            return 0

    baseline = None if (args.no_baseline or args.write_baseline) else (
        args.baseline
        if os.path.isabs(args.baseline)
        else os.path.join(REPO_ROOT, args.baseline)
    )
    findings = core.run_lint(
        REPO_ROOT, rules, paths=paths, baseline=baseline
    )

    if args.write_baseline:
        out = (
            args.baseline
            if os.path.isabs(args.baseline)
            else os.path.join(REPO_ROOT, args.baseline)
        )
        core.write_baseline(out, findings)
        print(f"graftlint: wrote {len(findings)} finding(s) to {out}")
        return 0

    for f in findings:
        print(f.render())
    _emit_json(args.json, findings)
    errors = [f for f in findings if f.severity == "error"]
    if (args.strict and findings) or errors:
        print(
            f"graftlint: {len(findings)} finding(s) "
            f"({len(errors)} error(s))"
        )
        return 1
    if findings:
        print(f"graftlint: {len(findings)} warning(s) (non-strict: ok)")
    else:
        print("graftlint: clean")
    return 0


def _emit_json(dest, findings) -> None:
    if not dest:
        return
    payload = json.dumps(
        {"version": 1, "count": len(findings),
         "findings": [f.to_json() for f in findings]},
        indent=2,
    )
    if dest == "-":
        print(payload)
    else:
        with open(dest, "w") as f:
            f.write(payload + "\n")


if __name__ == "__main__":
    sys.exit(main())
