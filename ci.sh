#!/usr/bin/env bash
# CI protocol runner — the committed encoding of the test discipline
# (VERDICT r02 item 7), mirroring the reference's CI pipeline
# (/root/reference/.github/workflows/CI.yml: black format gate, serial
# pytest, the same suite again under mpirun -n 2).
#
# Stages:
#   1. format gate      — `black --check .` when black is installed; the
#                         baked TPU image ships no formatter, so the gate
#                         degrades to a full-tree syntax check (compileall)
#                         and prints which gate ran.
#   2. graftlint +      — tools/graftlint.py (docs/LINT.md): the
#      graftsync          `--changed` pre-commit fast path first, then
#                         the AST invariant linter over the whole tree
#                         (HG001 host-sync-in-hot-path ... HG008
#                         tracer-leak) with an empty committed baseline,
#                         JSON findings artifact, committed-artifact
#                         schema validation (--artifacts: flight JSONLs
#                         + the BENCH_r*/SCALING_*/MULTICHIP_*/
#                         TUNE_TILES/BENCH_CI_BASELINE machine JSON
#                         schemas), and a self-test that injects one
#                         violation per guarded rule (HG001/HG002/
#                         HG005/HG006 — including the aliased `from
#                         jax.sharding import Mesh as M` case the old
#                         grep missed) and requires the linter to fail
#                         on each. Then tools/graftsync.py (docs/LINT.md
#                         HS rules): the thread-safety/lock-discipline
#                         analyzer — same --changed fast path, full-tree
#                         scan with an EMPTY committed baseline, and a
#                         self-test injecting one violation per HS rule
#                         (HS001 unguarded shared state ... HS006
#                         lock-order cycle), each of which must
#                         individually fail the gate.
#   3. graftcheck       — tools/graftcheck.py (docs/LINT.md, CC rules):
#                         the compiled-IR contract checker — lowers the
#                         hot entry points under the pure-DP and fsdp=2
#                         layouts on the forced 8-device host mesh and
#                         proves CC001 host-transfer freedom, CC002
#                         bf16 dtype discipline, CC003 collective
#                         layout, CC004 bucket-stable compiles, CC005
#                         donation landing, and CC006 static VMEM
#                         budgeting from the StableHLO / post-SPMD HLO
#                         (JSON findings artifact next to graftlint's);
#                         then a self-test injects one REAL violation
#                         per contract (HYDRAGNN_INJECT_GRAFTCHECK) and
#                         requires each contract to reject its own.
#   4. chip hygiene     — tools/chip_hygiene.py reports processes holding
#                         accelerator devices/lockfiles (informational:
#                         a lingering holder from a dead run is the
#                         transient-init failure class bench.py retries
#                         through; VERDICT r05 next-round #1).
#   5. serial suite     — python -m pytest tests/ -q on the virtual
#                         8-device CPU mesh (conftest pins it). This
#                         INCLUDES the 2-OS-process distributed pass: the
#                         reference re-runs its whole suite under
#                         `mpirun -n 2`; here the multi-process rendezvous
#                         is exercised by tests/test_multiprocess.py, which
#                         spawns 2 python processes with a shared
#                         coordinator itself (TPU-native launch shape —
#                         jax.distributed, not MPI).
#   6. partitioner      — unified-Partitioner gate (docs/PARALLELISM.md):
#      smoke               (a) graftlint rule HG002 — no module outside
#                         hydragnn_tpu/parallel/ may construct a
#                         jax.sharding.Mesh directly (train/serve/bench
#                         obtain meshes exclusively through Partitioner);
#                         (b) forced 8-device CPU host mesh, one tiny
#                         train run with Parallel.fsdp=2 — the flight
#                         manifest must carry the parallel block with
#                         sharded param/opt leaves and a per-device byte
#                         drop, and the loss history must equal the
#                         fsdp=1 data-parallel run's.
#   7. telemetry smoke  — one tiny training through api.run_training,
#                         then the emitted flight record is schema-
#                         validated (tools/obs_report.py --validate
#                         --require-complete) and pretty-printed: the
#                         committed proof that a default run leaves a
#                         parseable evidence artifact
#                         (docs/OBSERVABILITY.md).
#   8. fault-injection  — a tiny run is SIGTERM-killed mid-epoch via
#      smoke               HYDRAGNN_INJECT_SIGTERM_STEP, the restart
#                         supervisor (tools/supervise.py) resumes it to
#                         completion, and the merged flight record must
#                         validate with exactly one preempted run_end +
#                         one resumed event (docs/RESILIENCE.md). The run
#                         shares a persistent executable cache
#                         (HYDRAGNN_EXEC_CACHE survives the restart), so
#                         the resumed segment must reach first-step-ready
#                         as a cache HIT with 0 new compiles.
#   9. serve-chaos      — a tiny trained run is served; a poison request
#      smoke               is injected (raise-in-forward), then the
#                         checkpoint is HOT-reloaded into the running
#                         server; the server must answer identically
#                         afterwards, the serve flight record must
#                         validate (quarantine/reload event kinds), and
#                         tools/serve_probe.py must exit 0 on the
#                         exported Prometheus textfile
#                         (docs/RESILIENCE.md "Serving resilience").
#                         Then the lock-order witness smoke: the same
#                         serve is re-run with HYDRAGNN_LOCK_DEBUG=1
#                         and an injected lock-order inversion
#                         (HYDRAGNN_INJECT_LOCK_ORDER) — the witness
#                         must convert it into a schema-valid
#                         `lock_order` flight event (thread stacks
#                         attached) while the server keeps answering
#                         and the probe still exits 0: the witness is
#                         observability, never an availability risk.
#  10. exec-cache smoke — persistent AOT executable cache (docs/PERF.md
#                         "r09 cold start"): train a tiny model once,
#                         start TWO servers (separate processes) against
#                         one cache dir — the second must perform 0 AOT
#                         compiles (every bucket a disk hit) — then
#                         corrupt one entry and require a LOUD
#                         single-entry eviction + recompile, not a crash.
#  11. perf gate        — tools/bench_gate.py: a tiny fixed-config bench
#                         measured with D2H-fenced segments and compared
#                         against the committed BENCH_CI_BASELINE.json
#                         (>15% graphs/sec regression fails; MFU too on
#                         TPU; >15% cost-model bytes/step INCREASE
#                         fails), then self-tests proving the gate fails
#                         on an injected slowdown and on injected
#                         cost-model traffic; plus the warm-start arm —
#                         a warm executable-cache start must cost <50%
#                         of the cold start and 0 compiles.
#  12. full matrix      — opt-in (CI_FULL=1): all 7 models x head configs
#                         trained to the reference accuracy thresholds
#                         (HYDRAGNN_FULL_MATRIX=1, ~15 min).
#  13. TPU kernel suite — opt-in (CI_TPU=1, needs a real TPU):
#                         HYDRAGNN_TPU_TESTS=1 on-chip kernel-vs-XLA
#                         checks, budgeted under the tunnel's dispatch
#                         throttle (tests/test_tpu_chip.py).
#
# Usage: ./ci.sh            # stages 1-11 (the default CI gate)
#        CI_FULL=1 ./ci.sh  # + acceptance matrix
#        CI_TPU=1  ./ci.sh  # + real-chip kernel suite
set -euo pipefail
cd "$(dirname "$0")"

echo "== format gate =="
if python -m black --version >/dev/null 2>&1; then
    python -m black --check .
elif command -v black >/dev/null 2>&1; then
    black --check .
else
    echo "black not installed in this image; running syntax gate (compileall)"
    python -m compileall -q hydragnn_tpu tests examples tools bench.py bench_scaling.py bench_serve.py __graft_entry__.py
fi

echo "== graftlint (AST invariant linter, docs/LINT.md) =="
# The --changed fast path first: this is the exact pre-commit loop a
# developer runs locally (working tree + index vs HEAD), so CI proves
# the fast path itself stays healthy. The full-tree scan below remains
# the authoritative gate — --changed narrows WHICH files, never WHICH
# rules.
python tools/graftlint.py --changed || {
    echo "FAIL: graftlint --changed (pre-commit fast path) found violations"
    exit 1
}
# Full tree, all rules, empty committed baseline. On failure the JSON
# findings artifact is left at /tmp/graftlint_findings.json for CI to
# collect.
python tools/graftlint.py --json /tmp/graftlint_findings.json || {
    echo "FAIL: graftlint found violations (JSON artifact: /tmp/graftlint_findings.json)"
    exit 1
}
# committed flight artifacts must validate against obs/flight.py's schema
python tools/graftlint.py --artifacts
# Self-test: the linter must FAIL on an injected violation of each
# statically-guarded invariant. HG002's fixture is specifically the
# aliased import the old grep gate could not see.
LINT_ST="$(mktemp -d)"
cat > "$LINT_ST/hg001_hot_sync.py" <<'EOF'
def make_train_step(model):
    def step(state, batch):
        return float(state.loss)

    return step
EOF
cat > "$LINT_ST/hg002_aliased_mesh.py" <<'EOF'
from jax.sharding import Mesh as M


def build(devices):
    return M(devices, ("data",))
EOF
cat > "$LINT_ST/hg005_unknown_kind.py" <<'EOF'
def emit(flight):
    flight.record("totally_unknown_kind", x=1)
EOF
cat > "$LINT_ST/hg006_rogue_knob.py" <<'EOF'
import os


def read():
    return os.environ.get("HYDRAGNN_NOT_A_KNOB")
EOF
for rule in HG001 HG002 HG005 HG006; do
    fixture="$(ls "$LINT_ST"/$(echo "$rule" | tr '[:upper:]' '[:lower:]')_*.py)"
    if python tools/graftlint.py --rule "$rule" --strict --no-baseline "$fixture" >/dev/null 2>&1; then
        echo "FAIL: graftlint self-test — $rule did not flag $fixture"
        exit 1
    fi
done
echo "graftlint self-test: HG001/HG002/HG005/HG006 each reject their injected violation"
rm -rf "$LINT_ST"

echo "== graftsync (thread-safety/lock-discipline analyzer, docs/LINT.md HS rules) =="
# Same shape as graftlint: the --changed pre-commit fast path first,
# then the authoritative full-tree scan against the EMPTY committed
# baseline (tools/graftsync_baseline.json — every finding in the
# shipped tree is a regression, not a grandfathered debt).
python tools/graftsync.py --changed || {
    echo "FAIL: graftsync --changed (pre-commit fast path) found violations"
    exit 1
}
python tools/graftsync.py --json /tmp/graftsync_findings.json || {
    echo "FAIL: graftsync found violations (JSON artifact: /tmp/graftsync_findings.json)"
    exit 1
}
# Self-test: each HS rule must individually FAIL on an injected
# violation of the invariant it guards. Fixtures live in a temp dir
# (tests/ and lint/fixtures are exempt from the HS path policy).
SYNC_ST="$(mktemp -d)"
cat > "$SYNC_ST/hs001_unguarded_state.py" <<'EOF'
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        self._items.append(x)
EOF
cat > "$SYNC_ST/hs002_bare_acquire.py" <<'EOF'
import threading

_L = threading.Lock()


def f(work):
    _L.acquire()
    work()
    _L.release()
EOF
cat > "$SYNC_ST/hs003_sleep_under_lock.py" <<'EOF'
import threading
import time

_L = threading.Lock()


def f():
    with _L:
        time.sleep(0.1)
EOF
cat > "$SYNC_ST/hs004_unjoined_spawn.py" <<'EOF'
import threading


def work():
    pass


def main():
    t = threading.Thread(target=work)
    t.start()
EOF
cat > "$SYNC_ST/hs005_undeclared_root.py" <<'EOF'
import threading


def work():
    pass


def main():
    threading.Thread(target=work, daemon=True).start()
EOF
cat > "$SYNC_ST/hs006_lock_order_cycle.py" <<'EOF'
import threading


class A:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def ab(self):
        with self._la:
            with self._lb:
                pass

    def ba(self):
        with self._lb:
            with self._la:
                pass
EOF
for rule in HS001 HS002 HS003 HS004 HS005 HS006; do
    fixture="$(ls "$SYNC_ST"/$(echo "$rule" | tr '[:upper:]' '[:lower:]')_*.py)"
    if python tools/graftsync.py --rule "$rule" --strict --no-baseline "$fixture" >/dev/null 2>&1; then
        echo "FAIL: graftsync self-test — $rule did not flag $fixture"
        exit 1
    fi
done
echo "graftsync self-test: HS001..HS006 each reject their injected violation"
rm -rf "$SYNC_ST"

echo "== graftcheck (compiled-IR contract checker, docs/LINT.md CC rules) =="
# Lowers the registered hot entry points (train step, scan-epoch body,
# eval/stats steps, serve bucket ladder) under BOTH CI layouts — pure-DP
# (data=8) and fsdp=2 (data=4, fsdp=2) — on the forced 8-device host
# mesh and proves the six compiled-IR contracts from the StableHLO /
# post-SPMD HLO text. Empty committed baseline
# (tools/graftcheck_baseline.json); JSON findings artifact published
# next to graftlint's for CI to collect.
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/graftcheck.py --json /tmp/graftcheck_findings.json || {
    echo "FAIL: graftcheck found compiled-IR contract violations (JSON artifact: /tmp/graftcheck_findings.json)"
    exit 1
}
# Self-test: each contract must individually reject a REAL injected
# violation — the injection (HYDRAGNN_INJECT_GRAFTCHECK, docs/LINT.md
# "Self-test injections") perturbs the lowered program itself (a forced
# host callback, an f32 edge dot, a rogue collective, ...), not the
# checker, so a pass here proves the contract detects the defect class,
# not merely that a flag flips an exit code.
for cc in cc001 cc002 cc003 cc004 cc005 cc006; do
    CC="$(echo "$cc" | tr '[:lower:]' '[:upper:]')"
    if HYDRAGNN_INJECT_GRAFTCHECK="$cc" \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/graftcheck.py --layout dp --contract "$CC" --no-baseline \
        >/dev/null 2>&1; then
        echo "FAIL: graftcheck self-test — $CC did not reject its injected violation"
        exit 1
    fi
done
echo "graftcheck self-test: CC001..CC006 each reject their injected violation"

echo "== chip hygiene report =="
python tools/chip_hygiene.py || true

echo "== serial suite (virtual 8-device CPU mesh, incl. 2-process pass) =="
python -m pytest tests/ -q

echo "== partitioner smoke (HG002 mesh gate; fsdp=2 train == fsdp=1, flight parallel block) =="
# Train, serve, and bench obtain meshes/shardings exclusively through the
# Partitioner: no module outside hydragnn_tpu/parallel/ may construct a
# jax.sharding.Mesh directly. tests/ are exempt (they build adversarial
# meshes on purpose). AST-accurate gate (graftlint HG002, docs/LINT.md):
# unlike the old `grep -rn 'Mesh('`, it also catches aliased imports
# (`from jax.sharding import Mesh as M`) and `jax.sharding.Mesh(...)`.
python tools/graftlint.py --rule HG002 --strict \
    hydragnn_tpu bench.py bench_scaling.py bench_serve.py tools examples __graft_entry__.py
PART_DIR="$(mktemp -d)"
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python - "$PART_DIR" <<'EOF'
import glob
import sys

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs.flight import read_flight_record
from hydragnn_tpu.parallel import FSDP_AXIS

out = sys.argv[1]
assert jax.local_device_count() == 8, jax.devices()


def cfg(fsdp):
    c = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=8, num_epoch=2)
    c["NeuralNetwork"]["Parallel"] = {"fsdp": fsdp}
    return c


def data():
    return deterministic_graph_data(
        number_configurations=24,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


_, _, hist_dp, _ = run_training(cfg(1), samples=data(), log_dir=out + "/dp/")
_, state, hist_f, _ = run_training(cfg(2), samples=data(), log_dir=out + "/fsdp/")

# the fsdp layout changes WHERE state bytes live, never what is computed
np.testing.assert_allclose(hist_f["train_loss"], hist_dp["train_loss"], rtol=1e-5)

# committed shardings, not inference: param leaves carry the fsdp axis
sharded = sum(
    any(
        e == FSDP_AXIS or (isinstance(e, tuple) and FSDP_AXIS in e)
        for e in leaf.sharding.spec
        if e is not None
    )
    for leaf in jax.tree_util.tree_leaves(state.params)
)
assert sharded > 0, "no fsdp-sharded parameter leaves"

# flight parallel block: mesh shape, fsdp factor, per-device byte drop
flight = glob.glob(out + "/fsdp/*/flight.jsonl")[0]
start = [e for e in read_flight_record(flight) if e["kind"] == "run_start"][0]
par = start["manifest"]["parallel"]
assert par["available"] and par["fsdp"] == 2, par
assert par["mesh"]["shape"] == {"data": 4, "fsdp": 2}, par["mesh"]
assert par["params"]["sharded"] == sharded, (par["params"], sharded)
assert par["params"]["bytes_per_device"] < par["params"]["bytes_global"]
assert par["opt"]["bytes_per_device"] < par["opt"]["bytes_global"]
print(
    f"partitioner smoke: OK (loss histories equal, {sharded} fsdp-sharded "
    f"param leaves, {par['params']['bytes_per_device']}/"
    f"{par['params']['bytes_global']} param bytes per device)"
)
EOF
PART_FLIGHT="$(ls "$PART_DIR"/fsdp/*/flight.jsonl)"
# --validate must surface the parallel block alongside the verdict
PART_OUT="$(python tools/obs_report.py --validate "$PART_FLIGHT")"
echo "$PART_OUT"
echo "$PART_OUT" | grep -q "parallel: mesh=" || {
    echo "FAIL: --validate did not surface the parallel block"; exit 1; }
rm -rf "$PART_DIR"

echo "== telemetry smoke (tiny 2-head training -> schema-valid v2 flight record with head diagnostics + MFU ledger) =="
SMOKE_DIR="$(mktemp -d)"
JAX_PLATFORMS=cpu python - "$SMOKE_DIR" <<'EOF'
import sys

from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config

# trimmed to TWO heads (graph energy + one node head): the introspection
# smoke must exercise a genuinely multi-head record without the full
# flagship's 4-head cost
cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)
voi = cfg["NeuralNetwork"]["Variables_of_interest"]
voi["output_names"] = ["sum_x_x2_x3", "x"]
voi["output_index"] = [0, 0]
voi["type"] = ["graph", "node"]
cfg["NeuralNetwork"]["Architecture"]["task_weights"] = [1.0, 1.0]
samples = deterministic_graph_data(
    number_configurations=20,
    unit_cell_x_range=(2, 3),
    unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3),
    seed=0,
)
run_training(cfg, samples=samples, log_dir=sys.argv[1] + "/logs/")
EOF
FLIGHT="$(ls "$SMOKE_DIR"/logs/*/flight.jsonl)"
python tools/obs_report.py --validate --require-complete "$FLIGHT"
python tools/obs_report.py "$FLIGHT"
# the --heads view must render the diagnosis non-empty
HEADS_OUT="$(python tools/obs_report.py --heads "$FLIGHT")"
echo "$HEADS_OUT"
echo "$HEADS_OUT" | grep -q "task-conflict matrix" || {
    echo "FAIL: --heads view did not render the conflict matrix"; exit 1; }
python - "$FLIGHT" <<'EOF'
import sys

from hydragnn_tpu.obs.flight import read_flight_record

ev = read_flight_record(sys.argv[1])
eps = [e for e in ev if e.get("kind") == "epoch"]
assert eps and all(e.get("v") == 2 for e in eps), "epoch events must be schema v2"
names = ["sum_x_x2_x3", "x"]
for e in eps:
    heads, hw = e["heads"], e["hw"]
    assert heads["available"] and sorted(heads["grad_norm"]) == sorted(names)
    assert len(heads["cosine"]) == 2 and len(heads["cosine"][0]) == 2
    assert sorted(heads["mae"]) == sorted(names) and sorted(heads["rmse"]) == sorted(names)
    assert sorted(e["train_tasks"]) == sorted(names), "per-task losses must be name-keyed"
    # MFU ledger: achieved TFLOP/s + an MFU slot (None off-TPU) or an
    # explicit available:false; memory watermark always explicit
    assert "available" in hw and "available" in hw["memory"]
    if hw["available"]:
        assert hw["achieved_tflops"] > 0 and "mfu" in hw
assert eps[-1]["compiles"]["unexpected"] is False, "diagnostics caused a recompile"
print("introspection smoke: OK (v2 record, head diagnostics + MFU ledger present)")
EOF
rm -rf "$SMOKE_DIR"

echo "== fault-injection smoke (SIGTERM mid-epoch -> supervisor resume) =="
FAULT_DIR="$(mktemp -d)"
cat > "$FAULT_DIR/child.py" <<'EOF'
import sys

from hydragnn_tpu.resilience import run_guard
from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config

cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)
cfg["NeuralNetwork"]["Training"]["checkpoint_every"] = 1
# pin per-step dispatch in BOTH segments: the injection env forces
# per_step in segment 1 but is stripped on restart, and the executable
# cache key includes the dispatch mode — the resumed segment must ask
# for the SAME program to warm-start from the cache
cfg["NeuralNetwork"]["Training"]["scan_epoch"] = False
samples = deterministic_graph_data(
    number_configurations=20,
    unit_cell_x_range=(2, 3),
    unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3),
    seed=0,
)
with run_guard():
    run_training(cfg, samples=samples, log_dir=sys.argv[1] + "/logs/")
EOF
# PYTHONPATH: the child script lives in the temp dir, so the repo must
# reach its sys.path through the environment
# HYDRAGNN_EXEC_CACHE is NOT an injection var, so it survives the
# supervisor's restart env-strip: the resumed segment finds the
# executable segment 1 stored and must not recompile it
JAX_PLATFORMS=cpu PYTHONPATH="$PWD" HYDRAGNN_INJECT_SIGTERM_STEP=2 \
    HYDRAGNN_EXEC_CACHE="$FAULT_DIR/exec_cache" \
    python tools/supervise.py \
    --flight "$FAULT_DIR/supervisor.jsonl" -- \
    python "$FAULT_DIR/child.py" "$FAULT_DIR"
FAULT_FLIGHT="$(ls "$FAULT_DIR"/logs/*/flight.jsonl)"
python tools/obs_report.py --faults "$FAULT_FLIGHT"
python tools/obs_report.py --validate "$FAULT_FLIGHT" "$FAULT_DIR/supervisor.jsonl"
python - "$FAULT_FLIGHT" <<'EOF'
import sys

from hydragnn_tpu.obs.flight import read_flight_record

ev = read_flight_record(sys.argv[1])
ends = [e for e in ev if e.get("kind") == "run_end"]
assert [e["status"] for e in ends] == ["preempted", "completed"], ends
assert sum(1 for e in ev if e.get("kind") == "resumed") == 1, [
    e.get("kind") for e in ev
]
# warm auto-resume: segment 1 compiled+stored the train step (miss),
# segment 2 must reach first-step-ready as a cache HIT with 0 compiles
ready = [
    e
    for e in ev
    if e.get("kind") == "exec_cache" and e.get("event") == "train_ready"
]
assert len(ready) == 2, ready
assert ready[0]["hit"] is False, ready[0]
assert ready[1]["hit"] is True and ready[1]["compiles"] == 0, ready[1]
print(
    "fault-injection smoke: OK (one preempted + one resumed, run completed; "
    f"resume warm-started from the exec cache in {ready[1]['build_s']}s, 0 compiles)"
)
EOF
rm -rf "$FAULT_DIR"

echo "== serve-chaos smoke (poison request -> quarantine; hot reload from the saved checkpoint; health probe) =="
SERVE_DIR="$(mktemp -d)"
JAX_PLATFORMS=cpu python - "$SERVE_DIR" <<'EOF'
import glob
import os
import sys

import numpy as np

out = sys.argv[1]
# poison injection: the request admitted with sequence number 2 raises
# inside the forward; only ITS future may fail
os.environ["HYDRAGNN_INJECT_SERVE_RAISE"] = "2"

from hydragnn_tpu.api import prepare_loaders_and_config, run_training, serve_model
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs import FlightRecorder
from hydragnn_tpu.serve import RequestFailed, ServeConfig


def cfg():
    return flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=1)


def data():
    return deterministic_graph_data(
        number_configurations=20,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


run_training(cfg(), samples=data(), log_dir=out + "/logs/")
log_name = os.path.basename(os.path.dirname(glob.glob(out + "/logs/*/flight.jsonl")[0]))

flight = FlightRecorder(out + "/serve_flight.jsonl")
server = serve_model(
    cfg(),
    samples=data(),
    log_dir=out + "/logs/",
    serve_config=ServeConfig(max_batch=4, max_delay_ms=5.0),
    flight=flight,
)
_, _, test_loader, _ = prepare_loaders_and_config(cfg(), data())
# the tiny run's test split is small; cycle it so the poison request
# (admission seq 2) exists and is co-batched with innocents
test = (list(test_loader.all_samples) * 6)[:6]

futs = [server.submit(s) for s in test]
results, quarantined = {}, 0
for i, f in enumerate(futs):
    try:
        results[i] = f.result(timeout=120)
    except RequestFailed as exc:
        assert exc.seq == 2, exc
        quarantined += 1
assert quarantined == 1, f"expected exactly the poison request to fail, got {quarantined}"
assert len(results) == 5, "co-batched requests must survive the poison"

# hot reload from the freshly saved checkpoint (validating loader path);
# same weights -> answers must be bit-identical afterwards
os.environ.pop("HYDRAGNN_INJECT_SERVE_RAISE")
before = server.predict(test[0], timeout=120)
info = server.reload(log_name)
after = server.predict(test[0], timeout=120)
for k in before:
    np.testing.assert_allclose(after[k], before[k], rtol=0, atol=0)

health = server.health()
assert health["ready"] and health["live"], health
snap = server.metrics_snapshot()
assert snap["quarantined"] == 1 and snap["reloads"] == 1, snap
assert snap["compile_misses"] == 0, "chaos/reload recompiled on the serving path"
server.export_prometheus(out + "/serve.prom")
server.stop()
print(f"serve-chaos smoke: OK (quarantined=1, reload {info['swap_s']}s, answers identical)")
EOF
python tools/obs_report.py --validate "$SERVE_DIR/serve_flight.jsonl" | tee "$SERVE_DIR/validate.out"
if grep -q "WARNING" "$SERVE_DIR/validate.out"; then
    echo "FAIL: serve flight kinds not schema-known"; exit 1
fi
python tools/obs_report.py --faults "$SERVE_DIR/serve_flight.jsonl"
python tools/serve_probe.py --prom "$SERVE_DIR/serve.prom" --verbose
# lock-order witness smoke: serve the same checkpoint with the runtime
# witness ON (HYDRAGNN_LOCK_DEBUG=1) and a synthetic lock-order
# inversion injected between two real serve-path locks. The witness
# must convert the inversion into a `lock_order` flight event (thread
# stacks attached, record schema-valid) while the server answers
# normally and the health probe still exits 0 — an enabled witness is
# pure observability, never an availability risk.
JAX_PLATFORMS=cpu HYDRAGNN_LOCK_DEBUG=1 \
    HYDRAGNN_INJECT_LOCK_ORDER="batcher.MicroBatchQueue._cv,flight.FlightRecorder._lock" \
    python - "$SERVE_DIR" <<'EOF'
import sys

out = sys.argv[1]

from hydragnn_tpu.api import prepare_loaders_and_config, serve_model
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs import FlightRecorder
from hydragnn_tpu.obs.flight import read_flight_record, validate_flight_record
from hydragnn_tpu.serve import ServeConfig


def cfg():
    return flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=1)


def data():
    return deterministic_graph_data(
        number_configurations=20,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


flight = FlightRecorder(out + "/witness_flight.jsonl")
server = serve_model(
    cfg(),
    samples=data(),
    log_dir=out + "/logs/",  # the chaos smoke's checkpoint
    serve_config=ServeConfig(max_batch=4, max_delay_ms=5.0),
    flight=flight,
)
_, _, test_loader, _ = prepare_loaders_and_config(cfg(), data())
test = (list(test_loader.all_samples) * 4)[:4]
for s in test:
    server.predict(s, timeout=120)
health = server.health()
assert health["ready"] and health["live"], health
server.export_prometheus(out + "/witness.prom")
server.stop()

ev = read_flight_record(out + "/witness_flight.jsonl")
lock_events = [e for e in ev if e.get("kind") == "lock_order"]
assert len(lock_events) == 1, f"expected one injected lock_order event, got {lock_events}"
e = lock_events[0]
assert e["injected"] is True, e
assert set(e["locks"]) == {
    "batcher.MicroBatchQueue._cv",
    "flight.FlightRecorder._lock",
}, e["locks"]
assert e["stacks"], "lock_order event carried no thread stacks"
problems = validate_flight_record(ev)
assert not problems, problems
print(
    "lock-order witness smoke: OK (injected inversion -> one schema-valid "
    "lock_order event with thread stacks; server answered with the witness on)"
)
EOF
python tools/serve_probe.py --prom "$SERVE_DIR/witness.prom" --verbose
rm -rf "$SERVE_DIR"

echo "== fleet smoke (2-replica fleet from one checkpoint; replica-kill under traffic -> capacity restored warm; rolling reload bit-identical; merged flight validates) =="
FLEET_DIR="$(mktemp -d)"
JAX_PLATFORMS=cpu python - "$FLEET_DIR" <<'EOF'
import glob
import os
import sys
import threading

import numpy as np

out = sys.argv[1]

from hydragnn_tpu.api import prepare_loaders_and_config, run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.fleet import ControllerConfig, Fleet, FleetController
from hydragnn_tpu.obs import FlightRecorder
from hydragnn_tpu.obs.flight import read_flight_record, validate_flight_record
from hydragnn_tpu.serve import ModelRegistry, Overloaded, ServeConfig, ServerClosed
from hydragnn_tpu.serve.server import RequestFailed


def cfg():
    return flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=1)


def data():
    return deterministic_graph_data(
        number_configurations=20,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


# ONE trained checkpoint feeds every replica in the fleet
run_training(cfg(), samples=data(), log_dir=out + "/logs/")
log_name = os.path.basename(os.path.dirname(glob.glob(out + "/logs/*/flight.jsonl")[0]))

train_loader, val_loader, test_loader, config = prepare_loaders_and_config(cfg(), data())
reference = (
    list(train_loader.all_samples)
    + list(val_loader.all_samples)
    + list(test_loader.all_samples)
)
served = ModelRegistry(out + "/logs/").load(
    log_name, config["NeuralNetwork"], example_graph=reference[0]
)

flight = FlightRecorder(out + "/fleet_flight.jsonl")
fleet = Fleet(exec_cache_dir=out + "/exec_cache", flight=flight)
reps = fleet.add_model(
    "flagship", served, reference,
    ServeConfig(max_batch=4, num_buckets=2, max_delay_ms=5.0), replicas=2,
)
# the second replica must warm-start ENTIRELY from the first's exec cache
snap = reps[1].server.metrics_snapshot()
assert snap["compile_warmup"] == 0, snap
assert snap["exec_cache_hits"] > 0, snap

# kill one replica while traffic flows through the router: the death
# retry absorbs in-flights — zero futures may fail untyped
test = (list(test_loader.all_samples) * 8)[:16]
victim = fleet.replicas()[0]
killer = threading.Timer(0.02, victim.kill)
killer.start()
futs = [fleet.submit(s) for s in test]
lost = 0
for f in futs:
    try:
        f.result(timeout=120)
    except (RequestFailed, Overloaded, ServerClosed):
        pass  # typed rejection is an answer; silence is the failure
    except BaseException:
        lost += 1
killer.join()
assert lost == 0, f"{lost} futures failed UNtyped after the replica kill"

# the controller reaps the dead replica and restores capacity; the
# replacement warm-starts from the shared cache with 0 compile misses
ctl = FleetController(
    fleet, registry=fleet.registry,
    config=ControllerConfig(min_replicas=1, max_replicas=3),
    flight=flight,
)
decisions = ctl.step()
assert [d["action"] for d in decisions] == ["replace"], decisions
assert fleet.replica_count() == 2 and not fleet.dead_replicas()
replacement = [r for r in fleet.replicas() if r.name not in {x.name for x in reps}]
assert len(replacement) == 1 and replacement[0].ready
assert replacement[0].server.metrics_snapshot()["compile_warmup"] == 0
for s in test[:4]:
    fleet.predict(s, timeout=120)
for r in fleet.replicas():
    m = r.server.metrics_snapshot()
    assert m["compile_misses"] == 0, (r.name, m)

# fleet-wide rolling reload from the SAME saved checkpoint: one replica
# at a time, and the answers must be bit-identical afterwards
before = fleet.predict(test[0], timeout=120)
outcomes = fleet.rolling_reload("flagship", log_name, log_dir=out + "/logs/")
assert len(outcomes) == 2 and all(o["ok"] for o in outcomes), outcomes
after = fleet.predict(test[0], timeout=120)
for k in before:
    np.testing.assert_allclose(after[k], before[k], rtol=0, atol=0)
health = fleet.health()
assert health["ready_count"] == 2 and health["live_count"] == 2, health

fleet.export_probes(out + "/probes")
fleet.stop()
flight.close()

# the MERGED flight (every replica's run_start, the scale decision, the
# reload outcomes) must be schema-valid as one timeline
ev = read_flight_record(out + "/fleet_flight.jsonl")
assert sum(1 for e in ev if e.get("kind") == "run_start") >= 3, "3 replica manifests"
scale = [e for e in ev if e.get("kind") == "fleet_scale"]
assert [e["action"] for e in scale] == ["replace"], scale
reloads = [e for e in ev if e.get("kind") == "fleet_reload"]
assert len(reloads) == 2 and all(e["ok"] for e in reloads), reloads
problems = validate_flight_record(ev)
assert not problems, problems
print(
    "fleet smoke: OK (replica-kill absorbed with 0 lost futures, replacement "
    "warm with 0 compile misses, rolling reload bit-identical, merged flight valid)"
)
EOF
python tools/obs_report.py --validate "$FLEET_DIR/fleet_flight.jsonl" | tee "$FLEET_DIR/validate.out"
if grep -q "WARNING" "$FLEET_DIR/validate.out"; then
    echo "FAIL: fleet flight kinds not schema-known"; exit 1
fi
python tools/serve_probe.py --fleet "$FLEET_DIR/probes" --verbose
rm -rf "$FLEET_DIR"

echo "== incident smoke (SLO triggers: clean control -> zero incidents; injected NaN train + wedged serve -> one validated bundle each) =="
INC_DIR="$(mktemp -d)"
# --- clean control: triggers armed + tracing on, nothing injected ->
#     ZERO incidents and sub-1% measured trigger/capture overhead; the
#     sampled step traces must land in the flight record and export as
#     Chrome/Perfetto JSON
JAX_PLATFORMS=cpu python - "$INC_DIR/clean" <<'EOF'
import glob
import json
import os
import sys

from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs import export_flight_chrome, read_flight_record

out = sys.argv[1]
cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)
cfg["NeuralNetwork"]["Training"]["slo_triggers"] = True
cfg["NeuralNetwork"]["Training"]["scan_epoch"] = False  # the traced per-step path
samples = deterministic_graph_data(
    number_configurations=20,
    unit_cell_x_range=(2, 3),
    unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3),
    seed=0,
)
run_training(cfg, samples=samples, log_dir=out + "/logs/")
flight = glob.glob(out + "/logs/*/flight.jsonl")[0]
inc_root = os.path.join(os.path.dirname(flight), "incidents")
bundles = sorted(os.listdir(inc_root)) if os.path.isdir(inc_root) else []
assert bundles == [], f"clean control produced incidents: {bundles}"
ev = read_flight_record(flight)
trig = [e for e in ev if e.get("kind") == "run_end"][-1].get("triggers")
assert trig is not None and trig["fired"] == 0 and trig["incidents"] == [], trig
assert trig["overhead_frac"] < 0.01, f"trigger overhead over 1%: {trig}"
assert any(e.get("kind") == "trace_capture" for e in ev), "no sampled step traces"
export_flight_chrome(flight, out + "/trace.json")
with open(out + "/trace.json") as f:
    assert json.load(f)["traceEvents"], "empty chrome trace export"
print(
    "incident smoke (clean control): OK (0 incidents, "
    f"overhead_frac={trig['overhead_frac']})"
)
EOF
# --- injected NaN batch: the nonfinite sentry skips it and the
#     train_nonfinite_burst rule turns the skip counter's delta into
#     exactly ONE incident bundle, captured over the next epoch's steps
JAX_PLATFORMS=cpu HYDRAGNN_INJECT_NAN_STEP=2 HYDRAGNN_INCIDENT_PROFILE_STEPS=2 \
    python - "$INC_DIR/nan" <<'EOF'
import glob
import json
import os
import sys

from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs import read_flight_record
from hydragnn_tpu.obs.triggers import list_incidents, validate_incident_bundle

out = sys.argv[1]
cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)
cfg["NeuralNetwork"]["Training"]["slo_triggers"] = True
samples = deterministic_graph_data(
    number_configurations=20,
    unit_cell_x_range=(2, 3),
    unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3),
    seed=0,
)
run_training(cfg, samples=samples, log_dir=out + "/logs/")
flight = glob.glob(out + "/logs/*/flight.jsonl")[0]
bundles = list_incidents(os.path.join(os.path.dirname(flight), "incidents"))
assert len(bundles) == 1, f"expected exactly one train incident, got {bundles}"
problems = validate_incident_bundle(bundles[0])
assert not problems, problems
with open(os.path.join(bundles[0], "incident_manifest.json")) as f:
    man = json.load(f)
assert man["rule"] == "train_nonfinite_burst", man
assert man["trigger"]["kind"] == "nonfinite_burst", man["trigger"]
assert man["profile"]["nonempty"], "train incident captured an empty profiler trace"
ev = read_flight_record(flight)
assert sum(1 for e in ev if e.get("kind") == "incident") == 1
trig = [e for e in ev if e.get("kind") == "run_end"][-1].get("triggers")
assert trig["incidents"] == ["train_nonfinite_burst"], trig
print(f"incident smoke (NaN train): OK (one bundle at {bundles[0]})")
EOF
# --- injected dispatch wedge: serve p99 blows through the SLO, the
#     serve_p99 rule opens ONE incident, post-wedge traffic drives the
#     bounded capture; request traces land in the serve flight record
JAX_PLATFORMS=cpu python - "$INC_DIR" "$INC_DIR/clean" <<'EOF'
import json
import os
import sys

out, ckpt = sys.argv[1], sys.argv[2]
# wedge: dispatch sleeps 1 s inside the forward for request seq 2
os.environ["HYDRAGNN_INJECT_SERVE_WEDGE"] = "2:1"
os.environ["HYDRAGNN_INCIDENT_PROFILE_STEPS"] = "2"

from hydragnn_tpu.api import prepare_loaders_and_config, serve_model
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs import FlightRecorder, read_flight_record
from hydragnn_tpu.obs.triggers import list_incidents, validate_incident_bundle
from hydragnn_tpu.serve import ServeConfig


def cfg():
    # num_epoch=2 matches the clean control's run name (the checkpoint dir)
    return flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)


def data():
    return deterministic_graph_data(
        number_configurations=20,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


flight = FlightRecorder(out + "/serve_flight.jsonl")
server = serve_model(
    cfg(),
    samples=data(),
    log_dir=ckpt + "/logs/",  # the clean control's checkpoint
    serve_config=ServeConfig(
        max_batch=4,
        max_delay_ms=5.0,
        slo_p99_ms=200.0,
        trigger_eval_every_s=0.05,
        incident_dir=out + "/serve_incidents",
    ),
    flight=flight,
)
_, _, test_loader, _ = prepare_loaders_and_config(cfg(), data())
test = (list(test_loader.all_samples) * 8)[:8]
for s in test:  # sequential: the wedged batch, then post-wedge traffic
    server.predict(s, timeout=120)
server.export_trace(out + "/serve_trace.json")
server.stop()
with open(out + "/serve_trace.json") as f:
    assert json.load(f)["traceEvents"], "serve trace export empty"
bundles = list_incidents(out + "/serve_incidents")
assert len(bundles) == 1, f"expected exactly one serve incident, got {bundles}"
problems = validate_incident_bundle(bundles[0])
assert not problems, problems
with open(os.path.join(bundles[0], "incident_manifest.json")) as f:
    man = json.load(f)
assert man["rule"] == "serve_p99" and man["trigger"]["kind"] == "latency_p99", man
assert man["profile"]["nonempty"], "serve incident captured an empty profiler trace"
ev = read_flight_record(out + "/serve_flight.jsonl")
assert sum(1 for e in ev if e.get("kind") == "incident") == 1
assert any(e.get("kind") == "trace_capture" for e in ev), "no request traces sampled"
print(f"incident smoke (serve wedge): OK (one bundle at {bundles[0]})")
EOF
# the bundles pass the lint artifact gate and the reporter renders them
python tools/graftlint.py --artifacts \
    "$INC_DIR"/nan/logs/*/incidents/*/incident_manifest.json \
    "$INC_DIR"/serve_incidents/*/incident_manifest.json
python tools/incident_report.py --validate \
    "$INC_DIR"/nan/logs/*/incidents "$INC_DIR/serve_incidents"
python tools/incident_report.py \
    "$INC_DIR"/nan/logs/*/incidents "$INC_DIR/serve_incidents" \
    | tee "$INC_DIR/report.out"
grep -q "== incident" "$INC_DIR/report.out" || {
    echo "FAIL: incident_report.py rendered nothing"; exit 1; }
# the incident appears in the fault timeline (and the record validates)
python tools/obs_report.py --faults "$(ls "$INC_DIR"/nan/logs/*/flight.jsonl)"
rm -rf "$INC_DIR"

echo "== podview smoke (simulated 2-host pod: per-host shards merge into one timeline with per-host Chrome tracks; injected straggler -> one step_skew bundle naming host 1) =="
POD_DIR="$(mktemp -d)"
cat > "$POD_DIR/host_run.py" <<'EOF'
"""One simulated host's tiny training run into a shared run dir. The
podview smoke runs this once per host — host 1 first, then host 0,
whose rank-0 SkewMonitor reads the completed peer shard; the
host_epoch summaries carry durations, so wall-clock overlap between
the simulated hosts is not required (docs/OBSERVABILITY.md "Pod
visibility")."""
import sys

from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config

out, triggers = sys.argv[1], sys.argv[2] == "1"
cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)
cfg["NeuralNetwork"]["Training"]["slo_triggers"] = triggers
# per-step path: the straggler injection lives in StepSpans.step
cfg["NeuralNetwork"]["Training"]["scan_epoch"] = False
samples = deterministic_graph_data(
    number_configurations=20,
    unit_cell_x_range=(2, 3),
    unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3),
    seed=0,
)
run_training(cfg, samples=samples, log_dir=out + "/logs/")
EOF
# --- clean pass: the same tiny config once per simulated host into ONE
#     run dir; triggers stay off here (two sequential CPU runs carry
#     real compile-time noise — the straggler pass below proves the
#     trigger loop with an unambiguous signal)
JAX_PLATFORMS=cpu HYDRAGNN_PODVIEW_HOSTS=2 HYDRAGNN_PODVIEW_RUN_ID=podsmoke \
    HYDRAGNN_PODVIEW_HOST=1 PYTHONPATH="$PWD" python "$POD_DIR/host_run.py" "$POD_DIR/clean" 0
JAX_PLATFORMS=cpu HYDRAGNN_PODVIEW_HOSTS=2 HYDRAGNN_PODVIEW_RUN_ID=podsmoke \
    HYDRAGNN_PODVIEW_HOST=0 PYTHONPATH="$PWD" python "$POD_DIR/host_run.py" "$POD_DIR/clean" 0
JAX_PLATFORMS=cpu python - "$POD_DIR/clean" <<'EOF'
import glob
import os
import sys

from hydragnn_tpu.obs import (
    export_flight_chrome,
    flight_to_chrome,
    host_epoch_table,
    merge_host_flights,
    read_flight_record,
)

out = sys.argv[1]
flight = glob.glob(out + "/logs/*/flight.jsonl")[0]
run_dir = os.path.dirname(flight)
assert os.path.exists(os.path.join(run_dir, "flight.host1.jsonl")), \
    "host 1 wrote no shard"
merged = merge_host_flights(run_dir)
assert merged.hosts == [0, 1], merged.hosts
assert merged.problems == [], merged.problems
table = host_epoch_table(merged.events, run_id="podsmoke")
assert sorted(table) == [0, 1] and all(
    sorted(v) == [0, 1] for v in table.values()
), table
# rank 0's monitor saw the peer shard: skew verdicts in the record
assert any(e.get("kind") == "podview" for e in merged.events), \
    "no podview skew verdicts in the canonical shard"
# the plane's cost is stamped into run_end and <1% on the clean path
end = [e for e in read_flight_record(flight) if e.get("kind") == "run_end"][-1]
pv = end.get("podview")
assert pv and pv["enabled"] and pv["hosts"] == 2, pv
assert pv["overhead_frac"] < 0.01, f"podview overhead over 1%: {pv}"
# one Chrome track per host
chrome = flight_to_chrome(merged.events)["traceEvents"]
tids = {
    e["tid"] for e in chrome
    if e.get("ph") == "X" and str(e.get("name", "")).startswith("host")
}
assert tids == {0, 1}, tids
export_flight_chrome(run_dir, out + "/pod_trace.json")
print(
    "podview smoke (clean pod): OK (2 shards merged, "
    f"overhead_frac={pv['overhead_frac']})"
)
EOF
# the shard directory passes the reporter's validate gate (torn or
# missing hosts would be warnings, not failures), the --hosts view
# renders, and each shard passes the lint artifact gate
POD_RUN_DIR="$(dirname "$(ls "$POD_DIR"/clean/logs/*/flight.jsonl)")"
python tools/obs_report.py --validate "$POD_RUN_DIR"
python tools/obs_report.py --hosts "$POD_RUN_DIR" | tee "$POD_DIR/hosts.out"
grep -q "slowest" "$POD_DIR/hosts.out" || {
    echo "FAIL: obs_report --hosts rendered no per-host table"; exit 1; }
python tools/graftlint.py --artifacts \
    "$POD_RUN_DIR/flight.jsonl" "$POD_RUN_DIR/flight.host1.jsonl"
# --- straggler pass: host 1 sleeps 200 ms per step; host 0's monitor
#     must turn the cross-host skew into exactly ONE step_skew incident
#     whose podview_report.json names the injected host
JAX_PLATFORMS=cpu HYDRAGNN_PODVIEW_HOSTS=2 HYDRAGNN_PODVIEW_RUN_ID=podstrag \
    HYDRAGNN_PODVIEW_HOST=1 HYDRAGNN_INJECT_STRAGGLER=1:200 \
    PYTHONPATH="$PWD" python "$POD_DIR/host_run.py" "$POD_DIR/strag" 0
JAX_PLATFORMS=cpu HYDRAGNN_PODVIEW_HOSTS=2 HYDRAGNN_PODVIEW_RUN_ID=podstrag \
    HYDRAGNN_PODVIEW_HOST=0 HYDRAGNN_INCIDENT_PROFILE_STEPS=2 \
    PYTHONPATH="$PWD" python "$POD_DIR/host_run.py" "$POD_DIR/strag" 1
JAX_PLATFORMS=cpu python - "$POD_DIR/strag" <<'EOF'
import glob
import json
import os
import sys

from hydragnn_tpu.obs import validate_podview_report
from hydragnn_tpu.obs.triggers import list_incidents, validate_incident_bundle

out = sys.argv[1]
flight = glob.glob(out + "/logs/*/flight.jsonl")[0]
bundles = list_incidents(os.path.join(os.path.dirname(flight), "incidents"))
assert len(bundles) == 1, \
    f"expected exactly one step_skew incident, got {bundles}"
problems = validate_incident_bundle(bundles[0])
assert not problems, problems
with open(os.path.join(bundles[0], "incident_manifest.json")) as f:
    man = json.load(f)
assert man["rule"] == "podview_step_skew" and man["kind"] == "step_skew", man
assert man["trigger"]["detail"]["slowest_host"] == 1, man["trigger"]
with open(os.path.join(bundles[0], "podview_report.json")) as f:
    report = json.load(f)
assert validate_podview_report(report) == [], report
assert report["slowest_host"] == 1, report  # names the injected host
assert report["history"], "podview report carries no skew history"
# per-host evidence: the straggler's own shard tail rides in the bundle
assert os.path.exists(os.path.join(bundles[0], "flight_tail.host1.jsonl")), \
    "bundle missing the peer shard's tail"
print(
    "podview smoke (straggler): OK (one step_skew bundle naming host 1 "
    f"at {bundles[0]})"
)
EOF
# the new sidecar passes the lint artifact gate by name
python tools/graftlint.py --artifacts \
    "$POD_DIR"/strag/logs/*/incidents/*/podview_report.json
rm -rf "$POD_DIR"

echo "== pod-recovery smoke (concurrent 2-host pod under supervise.py --pod: SIGKILL host 1 mid-checkpoint -> host_lost restart from the last COMMIT, losses bit-match the uninterrupted reference; elastic leg re-shards 2->1) =="
PODREC_DIR="$(mktemp -d)"
cat > "$PODREC_DIR/child.py" <<'EOF'
"""One pod host's training run. tools/supervise.py --pod N launches N
of these CONCURRENTLY (HYDRAGNN_PODVIEW_HOST=k/_HOSTS=N per child);
run_guard maps TrainingPreempted/PodHostLost onto the supervisor's
exit-code contract (docs/RESILIENCE.md 'Pod recovery')."""
import sys

from hydragnn_tpu.resilience import run_guard
from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config

cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=3)
cfg["NeuralNetwork"]["Training"]["checkpoint_every"] = 1
# Pin one dispatch mode for every run in this smoke: armed HYDRAGNN_INJECT_*
# vars force the per-step path (scan auto-eligibility), but the supervisor
# strips them for restarted attempts and the uninterrupted reference never
# has them — without the pin, the legs would compare scan-epoch losses
# against per-step losses and the bit-match below would be meaningless.
cfg["NeuralNetwork"]["Training"]["scan_epoch"] = False
samples = deterministic_graph_data(
    number_configurations=20,
    unit_cell_x_range=(2, 3),
    unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3),
    seed=0,
)
with run_guard():
    run_training(cfg, samples=samples, log_dir=sys.argv[1] + "/logs/")
EOF
cat > "$PODREC_DIR/check_leg.py" <<'EOF'
"""One recovery leg's evidence chain: supervisor flight (host_lost ->
prompt restart), host 0's merged training flight (preempted segment +
pod_resume lineage), the on-disk commit protocol, and the bit-match
against the uninterrupted reference."""
import glob
import os
import sys

from hydragnn_tpu.obs.flight import read_flight_record
from hydragnn_tpu.resilience.podckpt import latest_commit_info
from hydragnn_tpu.utils.checkpoint import load_train_meta

base, leg = sys.argv[1], sys.argv[2]
want_width, want_gen = int(sys.argv[3]), int(sys.argv[4])

# supervisor flight: exactly ONE host_lost (host 1, signal-dead) and
# one host_lost-class restart — prompt (no backoff) at the expected
# pod width (2 fixed, 1 elastic)
sup = read_flight_record(os.path.join(base, f"sup{leg}.jsonl"))
lost = [e for e in sup if e.get("kind") == "host_lost"]
assert len(lost) == 1 and lost[0]["host"] == 1, lost
assert int(lost[0]["exit_code"]) < 0, lost[0]
restarts = [e for e in sup if e.get("kind") == "restart"]
assert len(restarts) == 1 and restarts[0]["cause"] == "host_lost", restarts
assert restarts[0]["delay_s"] == 0, restarts[0]
assert int(restarts[0]["hosts"]) == want_width, restarts[0]
assert [e["status"] for e in sup if e.get("kind") == "run_end"] == ["completed"]

# host 0's merged training flight: the survivor cut its boundary and
# exited preempted inside the grace window; the restarted segment rose
# from committed gen 1 (gen 2's manifest never landed) and completed
flight_path = glob.glob(
    os.path.join(base, f"pod{leg}", "logs", "*", "flight.jsonl")
)[0]
run_dir = os.path.dirname(flight_path)
ev = read_flight_record(flight_path)
ends = [e["status"] for e in ev if e.get("kind") == "run_end"]
assert ends == ["preempted", "completed"], ends
assert sum(1 for e in ev if e.get("kind") == "resumed") == 1
pre = [e for e in ev if e.get("kind") == "preempt"]
assert pre and pre[0]["signal"] == 15, pre
fails = [
    e
    for e in ev
    if e.get("kind") == "error" and e.get("error_type") == "PodCommitFailed"
]
assert fails, "the torn generation left no PodCommitFailed evidence"
resumes = [e for e in ev if e.get("kind") == "pod_resume"]
assert len(resumes) == 1, resumes
assert resumes[0]["gen"] == 1 and resumes[0]["prior_hosts"] == 2, resumes[0]
assert not resumes[0].get("fallbacks"), resumes[0]
starts = [e for e in ev if e.get("kind") == "run_start"]
lineage = (starts[-1].get("manifest") or {}).get("pod_resume")
assert lineage and lineage["resumed_from_gen"] == 1, lineage
assert lineage["prior_hosts"] == 2, lineage

# on-disk protocol ground truth: the newest COMMIT marker names the
# expected generation (3 after a full-width recovery; still 1 after
# the elastic leg, whose single-host continuation leaves pod cutting
# off) and the meta sidecar describes the completed run
commit = latest_commit_info(run_dir)
assert commit is not None and int(commit["gen"]) == want_gen, commit
assert int(commit["hosts"]) == 2, commit
meta = load_train_meta(os.path.basename(run_dir), os.path.dirname(run_dir))
assert meta is not None and int(meta["epoch"]) == 3, meta
assert int(meta.get("format_version", 1)) == 2, meta

# recovery correctness: every epoch's final losses equal the
# uninterrupted single-process reference's EXACTLY (the restored
# generation is byte-identical state, the replayed epochs deterministic)
ref_flight = glob.glob(os.path.join(base, "ref", "logs", "*", "flight.jsonl"))[0]
ref = {
    e["epoch"]: e
    for e in read_flight_record(ref_flight)
    if e.get("kind") == "epoch"
}
got = {e["epoch"]: e for e in ev if e.get("kind") == "epoch"}
assert sorted(got) == sorted(ref) == [0, 1, 2], (sorted(got), sorted(ref))
for ep in sorted(ref):
    for k in ("train_loss", "val_loss", "test_loss"):
        assert got[ep][k] == ref[ep][k], (ep, k, got[ep][k], ref[ep][k])
print(
    f"pod-recovery leg {leg}: OK (host_lost -> prompt restart at width "
    f"{want_width}, resumed from committed gen 1, last commit gen "
    f"{want_gen}, losses bit-match the reference)"
)
EOF
# the uninterrupted reference: same config, single process, no pod.
# Also warms the shared exec cache so every pod host below starts
# compile-free — the bounded commit waits then measure the protocol,
# not cross-host compile skew.
JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    HYDRAGNN_EXEC_CACHE="$PODREC_DIR/exec_cache" \
    python "$PODREC_DIR/child.py" "$PODREC_DIR/ref"
# --- fixed-width leg: host 1 is SIGKILLed inside its gen-2 shard write
#     (shard bytes land, the manifest never does -> gen 2 can never
#     commit). The supervisor classifies the signal death host_lost,
#     SIGTERMs the survivor (it cuts its boundary and exits 75 inside
#     the grace window), and restarts the full pod promptly with the
#     injection stripped; both hosts resume from committed gen 1.
JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    HYDRAGNN_EXEC_CACHE="$PODREC_DIR/exec_cache" \
    HYDRAGNN_INJECT_POD_KILL_HOST=1:2 \
    HYDRAGNN_POD_COMMIT_TIMEOUT_S=10 \
    python tools/supervise.py --pod 2 --pod-grace 90 --run-id podrecA \
    --flight "$PODREC_DIR/supA.jsonl" -- \
    python "$PODREC_DIR/child.py" "$PODREC_DIR/podA"
JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    python "$PODREC_DIR/check_leg.py" "$PODREC_DIR" A 2 3
PODREC_RUN_A="$(dirname "$(ls "$PODREC_DIR"/podA/logs/*/flight.jsonl)")"
# the reporter surfaces the protocol state and the resume lineage, the
# fault timeline narrates the loss and the rise, and every flight
# artifact (host shards + the supervisor's) passes the lint gate
python tools/obs_report.py --validate "$PODREC_RUN_A" \
    | tee "$PODREC_DIR/validateA.out"
grep -q "podckpt: last committed gen 3" "$PODREC_DIR/validateA.out" || {
    echo "FAIL: --validate did not surface the committed generation"; exit 1; }
grep -q "pod_resume: from gen 1 (prior_hosts=2" "$PODREC_DIR/validateA.out" || {
    echo "FAIL: --validate did not surface the pod resume lineage"; exit 1; }
python tools/obs_report.py --faults "$PODREC_DIR/supA.jsonl" \
    | tee "$PODREC_DIR/faultsA.out"
grep -q "host 1 declared lost" "$PODREC_DIR/faultsA.out" || {
    echo "FAIL: --faults did not narrate the lost host"; exit 1; }
python tools/obs_report.py --faults "$PODREC_RUN_A/flight.jsonl" \
    | tee "$PODREC_DIR/faultsA_train.out"
grep -q "resumed from committed gen 1" "$PODREC_DIR/faultsA_train.out" || {
    echo "FAIL: --faults did not narrate the pod resume"; exit 1; }
python tools/graftlint.py --artifacts \
    "$PODREC_RUN_A/flight.jsonl" "$PODREC_RUN_A/flight.host1.jsonl" \
    "$PODREC_DIR/supA.jsonl"
# --- elastic leg: same loss, --pod-elastic restarts the pod at width 1;
#     the single-host continuation restores the 2-host generation
#     re-sharded onto itself and completes with the same losses
JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    HYDRAGNN_EXEC_CACHE="$PODREC_DIR/exec_cache" \
    HYDRAGNN_INJECT_POD_KILL_HOST=1:2 \
    HYDRAGNN_POD_COMMIT_TIMEOUT_S=10 \
    python tools/supervise.py --pod 2 --pod-elastic --pod-grace 90 \
    --run-id podrecB --flight "$PODREC_DIR/supB.jsonl" -- \
    python "$PODREC_DIR/child.py" "$PODREC_DIR/podB"
JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    python "$PODREC_DIR/check_leg.py" "$PODREC_DIR" B 1 1
PODREC_RUN_B="$(dirname "$(ls "$PODREC_DIR"/podB/logs/*/flight.jsonl)")"
python tools/obs_report.py --validate "$PODREC_RUN_B" \
    | tee "$PODREC_DIR/validateB.out"
grep -q "podckpt: last committed gen 1" "$PODREC_DIR/validateB.out" || {
    echo "FAIL: --validate did not surface the elastic leg's commit"; exit 1; }
python tools/graftlint.py --artifacts \
    "$PODREC_RUN_B/flight.jsonl" "$PODREC_DIR/supB.jsonl"
rm -rf "$PODREC_DIR"

echo "== exec-cache smoke (train once; two server starts vs one cache dir; corrupt entry -> loud eviction) =="
EXEC_DIR="$(mktemp -d)"
cat > "$EXEC_DIR/serve_once.py" <<'EOF'
import sys

out = sys.argv[1]
expect = sys.argv[2]  # cold | warm | corrupt

from hydragnn_tpu.api import run_training, serve_model
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.serve import ServeConfig


def cfg():
    return flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=1)


def data():
    return deterministic_graph_data(
        number_configurations=20,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


if expect == "cold":
    run_training(cfg(), samples=data(), log_dir=out + "/logs/")

server = serve_model(
    cfg(),
    samples=data(),
    log_dir=out + "/logs/",
    serve_config=ServeConfig(
        max_batch=4, max_delay_ms=5.0, exec_cache_dir=out + "/exec_cache"
    ),
)
snap = server.metrics_snapshot()
n = len(server.buckets)
server.stop()
print(
    f"{expect} start: buckets={n} warmup_compiles={snap['compile_warmup']} "
    f"cache_hits={snap['exec_cache_hits']} "
    f"miss_reasons={snap['exec_cache_miss_reasons']}"
)
if expect == "cold":
    assert snap["compile_warmup"] == n and snap["exec_cache_misses"] == n, snap
elif expect == "warm":
    # the second-replica criterion: 0 AOT compiles, every bucket from disk
    assert snap["compile_warmup"] == 0, f"warm start recompiled: {snap}"
    assert snap["exec_cache_hits"] == n, snap
else:  # corrupt: ONE loud eviction + recompile of that bucket, rest hit
    assert snap["exec_cache_miss_reasons"] == {"corrupt": 1}, snap
    assert snap["compile_warmup"] == 1 and snap["exec_cache_hits"] == n - 1, snap
EOF
JAX_PLATFORMS=cpu PYTHONPATH="$PWD" python "$EXEC_DIR/serve_once.py" "$EXEC_DIR" cold
JAX_PLATFORMS=cpu PYTHONPATH="$PWD" python "$EXEC_DIR/serve_once.py" "$EXEC_DIR" warm
# flip bytes inside one entry: the next start must evict LOUDLY (stderr
# names the entry), recompile just that bucket, and serve normally
python - "$EXEC_DIR/exec_cache" <<'EOF'
import glob
import sys

path = sorted(glob.glob(sys.argv[1] + "/*.bin"))[0]
with open(path, "r+b") as f:
    f.seek(30)
    f.write(b"\xde\xad\xbe\xef")
EOF
if ! JAX_PLATFORMS=cpu PYTHONPATH="$PWD" python "$EXEC_DIR/serve_once.py" "$EXEC_DIR" corrupt \
        2>"$EXEC_DIR/corrupt.err"; then
    echo "FAIL: server start over a corrupt cache entry crashed"
    cat "$EXEC_DIR/corrupt.err"
    exit 1
fi
grep -q "exec_cache: evicted entry" "$EXEC_DIR/corrupt.err" || {
    echo "FAIL: corruption eviction was not loud on stderr"
    cat "$EXEC_DIR/corrupt.err"
    exit 1
}
rm -rf "$EXEC_DIR"

echo "== drift smoke (request spool + drift plane: clean traffic -> zero incidents + bounded spool overhead; injected covariate shift -> one validated feature_drift bundle) =="
DRIFT_DIR="$(mktemp -d)"
# --- train the reference: run_training stamps the per-channel stats
#     block (moments, quantiles, histogram fractions) into its flight
#     manifest — that flight IS the drift_ref a server arms against
JAX_PLATFORMS=cpu python - "$DRIFT_DIR/train" <<'EOF'
import glob
import sys

from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs.drift import load_reference

out = sys.argv[1]
cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)
samples = deterministic_graph_data(
    number_configurations=24,
    unit_cell_x_range=(2, 3),
    unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3),
    seed=0,
)
run_training(cfg, samples=samples, log_dir=out + "/logs/")
flight = glob.glob(out + "/logs/*/flight.jsonl")[0]
ref = load_reference(flight)  # raises if the stats block is absent/invalid
assert ref["num_rows"] > 0 and ref["feature"]["channels"], ref.keys()
print(f"drift smoke (train ref): OK ({ref['num_rows']} reference rows)")
EOF
DRIFT_REF="$(ls "$DRIFT_DIR"/train/logs/*/flight.jsonl)"
# --- clean serve: spool + drift armed against the training reference.
#     In-distribution traffic must produce ZERO incidents, a run_end
#     spool block with its measured overhead fraction, and shards that
#     reload bit-compatibly through the training batcher (the retrain
#     contract). The smoke's wall time is ~1 s, so the overhead gate is
#     a sanity bound, not a production SLO.
JAX_PLATFORMS=cpu python - "$DRIFT_DIR" "$DRIFT_DIR/train" "$DRIFT_REF" <<'EOF'
import os
import sys

import numpy as np

out, ckpt, ref_path = sys.argv[1], sys.argv[2], sys.argv[3]

from hydragnn_tpu.api import prepare_loaders_and_config, serve_model
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.graph.batch import batch_graphs
from hydragnn_tpu.obs import FlightRecorder, read_flight_record
from hydragnn_tpu.obs.spool import list_shards, read_shard_manifest, read_spool
from hydragnn_tpu.obs.triggers import list_incidents
from hydragnn_tpu.serve import ServeConfig
from hydragnn_tpu.serve.server import request_to_dict


def cfg():
    return flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)


def data():
    return deterministic_graph_data(
        number_configurations=24,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


flight = FlightRecorder(out + "/clean_flight.jsonl")
server = serve_model(
    cfg(),
    samples=data(),
    log_dir=ckpt + "/logs/",
    serve_config=ServeConfig(
        max_batch=4,
        max_delay_ms=5.0,
        incident_dir=out + "/clean_incidents",
        spool=True,
        spool_sample=2,
        spool_shard_mb=0.05,
        spool_dir=out + "/spool",
        drift_ref=ref_path,
        drift_min_count=16,
    ),
    flight=flight,
)
train_loader, _, _, _ = prepare_loaders_and_config(cfg(), data())
reqs = list(train_loader.all_samples) * 2  # in-distribution traffic
for s in reqs:
    server.predict(s, timeout=120)
server.stop()
assert list_incidents(out + "/clean_incidents") == [], "clean traffic drifted?"
ev = read_flight_record(out + "/clean_flight.jsonl")
start = next(e for e in ev if e.get("kind") == "run_start")
man = start["manifest"]
assert man["spool"]["enabled"] and man["drift"]["armed"], man
end = [e for e in ev if e.get("kind") == "run_end"][-1]
sp, dr = end["spool"], end["drift"]
assert sp["spooled"] >= len(reqs) // 2, sp
assert 0.0 <= sp["overhead_frac"] < 0.05, f"spool overhead over 5%: {sp}"
assert dr["feature_rows"] > 0 and dr["feature_psi_max"] < 0.25, dr
# the spooled window reloads through the training batcher: same node
# payload (f32) and identical edge_occupancy as the original requests
shards = list_shards(out + "/spool")
assert shards, "clean serve spooled nothing"
mans = [read_shard_manifest(s) for s in shards]
assert sum(m["num_samples"] for m in mans) == sp["spooled"], (mans, sp)
back = sorted(read_spool(out + "/spool"), key=lambda s: s.meta["spool"]["seq"])
seqs = [s.meta["spool"]["seq"] for s in back]
orig = [reqs[i] for i in seqs]
want = batch_graphs([request_to_dict(s) for s in orig])
got = batch_graphs([request_to_dict(s) for s in back])
assert int(want.edge_occupancy) == int(got.edge_occupancy)
np.testing.assert_array_equal(
    np.asarray(want.nodes), np.asarray(got.nodes)
)
print(
    f"drift smoke (clean serve): OK (0 incidents, {sp['spooled']} spooled, "
    f"overhead_frac={sp['overhead_frac']}, feature_psi_max={dr['feature_psi_max']})"
)
EOF
# --- injected covariate shift: every admitted request's node features
#     move by +5.0; the feature_drift rule must open exactly ONE
#     incident whose bundle carries a schema-valid drift_report.json
#     and the spool window holding the offending traffic
JAX_PLATFORMS=cpu HYDRAGNN_INJECT_DRIFT=5.0 \
    python - "$DRIFT_DIR" "$DRIFT_DIR/train" "$DRIFT_REF" <<'EOF'
import json
import os
import sys

out, ckpt, ref_path = sys.argv[1], sys.argv[2], sys.argv[3]

from hydragnn_tpu.api import prepare_loaders_and_config, serve_model
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs import FlightRecorder, read_flight_record
from hydragnn_tpu.obs.drift import validate_drift_report
from hydragnn_tpu.obs.triggers import list_incidents, validate_incident_bundle
from hydragnn_tpu.serve import ServeConfig


def cfg():
    return flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)


def data():
    return deterministic_graph_data(
        number_configurations=24,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


flight = FlightRecorder(out + "/shift_flight.jsonl")
server = serve_model(
    cfg(),
    samples=data(),
    log_dir=ckpt + "/logs/",
    serve_config=ServeConfig(
        max_batch=4,
        max_delay_ms=5.0,
        incident_dir=out + "/shift_incidents",
        spool=True,
        spool_sample=2,
        spool_shard_mb=0.05,
        spool_dir=out + "/shift_spool",
        drift_ref=ref_path,
        drift_min_count=16,
        trigger_eval_every_s=0.05,
    ),
    flight=flight,
)
train_loader, _, _, _ = prepare_loaders_and_config(cfg(), data())
for s in list(train_loader.all_samples) * 2:
    server.predict(s, timeout=120)
server.stop()
bundles = list_incidents(out + "/shift_incidents")
assert len(bundles) == 1, f"expected exactly one drift incident, got {bundles}"
problems = validate_incident_bundle(bundles[0])
assert not problems, problems
with open(os.path.join(bundles[0], "incident_manifest.json")) as f:
    man = json.load(f)
assert man["rule"] == "serve_feature_drift", man
assert man["trigger"]["kind"] == "feature_drift", man["trigger"]
report_path = os.path.join(bundles[0], "drift_report.json")
with open(report_path) as f:
    report = json.load(f)
assert validate_drift_report(report) == [], validate_drift_report(report)
assert report["feature"]["psi_max"] > 0.25, report["feature"]
assert (report.get("spool_window") or {}).get("dir"), report.get("spool_window")
ev = read_flight_record(out + "/shift_flight.jsonl")
drift_ev = [e for e in ev if e.get("kind") == "drift"]
assert len(drift_ev) == 1 and drift_ev[0]["rule_kind"] == "feature_drift", drift_ev
print(
    "drift smoke (injected shift): OK (one bundle, "
    f"observed psi={drift_ev[0]['observed']:.3f} > {drift_ev[0]['threshold']})"
)
EOF
# the artifacts pass the lint gate and every reader renders/validates them
python tools/graftlint.py --artifacts \
    "$DRIFT_DIR"/shift_incidents/*/incident_manifest.json \
    "$DRIFT_DIR"/shift_incidents/*/drift_report.json \
    "$DRIFT_DIR"/spool/*/spool_manifest.json
python tools/incident_report.py --validate "$DRIFT_DIR/shift_incidents"
python tools/drift_report.py --validate \
    "$DRIFT_REF" "$DRIFT_DIR/clean_flight.jsonl" "$DRIFT_DIR/spool" \
    "$DRIFT_DIR"/shift_incidents/*/drift_report.json
python tools/drift_report.py --no-trend \
    "$DRIFT_DIR/shift_flight.jsonl" "$DRIFT_DIR/spool" \
    "$DRIFT_DIR"/shift_incidents/*/drift_report.json \
    | tee "$DRIFT_DIR/report.out"
grep -q "breaches: 1" "$DRIFT_DIR/report.out" || {
    echo "FAIL: drift_report.py did not render the breach"; exit 1; }
# the breach appears in the fault timeline (and the record validates)
python tools/obs_report.py --faults "$DRIFT_DIR/shift_flight.jsonl"
rm -rf "$DRIFT_DIR"

echo "== closed-loop smoke (retrain pilot: drift incident -> fine-tune from pinned spool -> two-slice canary -> hot reload; injected train crash absorbed, injected regression rejected, torn candidate rolled back) =="
PILOT_DIR="$(mktemp -d)"
# --- train once (the same tiny flagship the drift smoke uses); each
#     scenario then gets its own COPY of the checkpoint tree — the
#     pilot journal and the candidate run live NEXT TO the serving run,
#     so sharing one tree would leak pilot state (and candidates) from
#     one scenario into the next
JAX_PLATFORMS=cpu python - "$PILOT_DIR/train" <<'EOF'
import glob
import sys

from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs.drift import load_reference

out = sys.argv[1]
cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)
samples = deterministic_graph_data(
    number_configurations=24,
    unit_cell_x_range=(2, 3),
    unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3),
    seed=0,
)
run_training(cfg, samples=samples, log_dir=out + "/logs/")
flight = glob.glob(out + "/logs/*/flight.jsonl")[0]
ref = load_reference(flight)
assert ref["num_rows"] > 0, ref.keys()
print(f"closed-loop smoke (train ref): OK ({ref['num_rows']} reference rows)")
EOF
# one driver, three scenarios: serve with HYDRAGNN_INJECT_DRIFT shifted
# traffic and a REAL attached RetrainPilot (real supervised child
# fine-tune, real canary, real hot reload), then assert the journal,
# the flight narration, and the serving weights per scenario.
# CANARY_TOL=10.0 keeps CI deterministic: the smoke proves the LOOP's
# mechanics (a 1-epoch fine-tune on 1x-CPU pseudo-label data is not a
# model-quality statement); the regression scenario still rejects
# because its injected inflation dwarfs any tolerance.
cat > "$PILOT_DIR/driver.py" <<'EOF'
"""Closed-loop smoke driver: serve a drifting model with a retrain
pilot attached and assert one full cycle per scenario (ok / canary /
torn)."""

import glob
import json
import os
import sys
import time

import numpy as np

out, ckpt, ref_path, scenario = sys.argv[1:5]

from hydragnn_tpu.api import prepare_loaders_and_config, serve_model
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.obs import FlightRecorder, read_flight_record
from hydragnn_tpu.obs.triggers import list_incidents
from hydragnn_tpu.pilot import RetrainPilot
from hydragnn_tpu.serve import ServeConfig


def cfg():
    return flagship_config(
        hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2
    )


def data():
    return deterministic_graph_data(
        number_configurations=24,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


flight_path = f"{out}/{scenario}_flight.jsonl"
flight = FlightRecorder(flight_path)
server = serve_model(
    cfg(),
    samples=data(),
    log_dir=ckpt + "/logs/",
    serve_config=ServeConfig(
        max_batch=4,
        max_delay_ms=5.0,
        incident_dir=f"{out}/{scenario}_incidents",
        spool=True,
        spool_sample=2,
        spool_shard_mb=0.05,
        spool_dir=f"{out}/{scenario}_spool",
        drift_ref=ref_path,
        # node rows, not requests: fire the rule mid-traffic, once the
        # spool holds a trainable window (~24 requests in)
        drift_min_count=400,
        trigger_eval_every_s=0.05,
    ),
    flight=flight,
)
run_name = os.path.basename(
    os.path.dirname(glob.glob(ckpt + "/logs/*/flight.jsonl")[0])
)
train_loader, _, _, _ = prepare_loaders_and_config(cfg(), data())
refs = list(train_loader.all_samples)
pilot = RetrainPilot(server, run_name, reference_samples=refs, flight=flight)
server.attach_pilot(pilot)

baseline = server.predict(refs[0], timeout=120)
for s in refs * 2:
    server.predict(s, timeout=120)
# the drift verdict fires on the trigger thread; wait for the cycle
deadline = time.time() + 600
while time.time() < deadline and pilot.status()["cycle"] == 0:
    time.sleep(0.2)
assert pilot.status()["cycle"] == 1, f"no retrain cycle flew: {pilot.status()}"
pilot.join(timeout=600)
st = pilot.status()
assert st["state"] == "cooldown", st
assert st["pinned_shards"] == [], st  # the cycle released its pins
after = server.predict(refs[0], timeout=120)  # serving path alive post-cycle
server.export_prometheus(f"{out}/{scenario}.prom")
server.stop()

candidate = f"{run_name}-pilot-c1"
cand_ckpt = os.path.join(ckpt, "logs", candidate, f"{candidate}.mp")
states = [e["state"] for e in pilot.journal.entries()]
tail = pilot.journal.last()["detail"]
ev = read_flight_record(flight_path)
reloads = [e for e in ev if e.get("kind") == "reload"]
reload_fails = [e for e in ev if e.get("kind") == "reload_failed"]
pilot_ev = [e for e in ev if e.get("kind") == "pilot"]
assert pilot_ev, "pilot cycle left no flight narration"

if scenario == "ok":
    # full success: the injected train crash was absorbed by the
    # supervisor's restart (stripped injection), the candidate passed
    # both canary slices, and the reload swapped weights
    assert st["last_cycle_ok"] is True and st["failed_cycles"] == 0, st
    assert states == [
        "idle", "drift_confirmed", "fine_tuning", "canary",
        "reloading", "cooldown",
    ], states
    assert tail["reason"] == "reloaded", tail
    assert tail["reference"]["passed"] and tail["window"]["passed"], tail
    assert os.path.exists(cand_ckpt), cand_ckpt
    assert os.path.exists(
        os.path.join(ckpt, "logs", candidate, "config.json")
    ), "candidate config missing"
    assert len(reloads) == 1 and not reload_fails, (reloads, reload_fails)
    # the fine-tune manifest names its lineage (spool window + parent)
    cand_flight = glob.glob(
        os.path.join(ckpt, "logs", candidate, "flight.jsonl")
    )
    if cand_flight:
        cev = read_flight_record(cand_flight[0])
        man = next(e for e in cev if e.get("kind") == "run_start")["manifest"]
        assert man["fine_tune"]["from_run"] == run_name, man["fine_tune"]
        assert man["fine_tune"]["shards"], man["fine_tune"]
    # the drift incident bundle pinned its evidence: per-shard spool
    # manifests copied INTO the bundle
    (bundle,) = list_incidents(f"{out}/{scenario}_incidents")
    copies = glob.glob(os.path.join(bundle, "spool_manifests", "*.json"))
    assert copies, f"no spool manifest copies in {bundle}"
    with open(os.path.join(bundle, "drift_report.json")) as f:
        report = json.load(f)
    assert report["pinned_shards"], report.get("pinned_shards")
    print(
        f"closed-loop smoke (ok): OK (cycle 1 reloaded the candidate "
        f"despite an injected train crash; canary ref_mae="
        f"{tail['reference']['candidate_mae']}, "
        f"{len(copies)} pinned manifests in bundle)"
    )
elif scenario == "canary":
    # the candidate trained fine but the injected regression must be
    # rejected at the canary gate: no reload, old weights serve on
    # (the hung-tune wall-clock kill path is unit-tested in
    # tests/test_pilot.py — a real fine-tune here would need a wall
    # clock too generous to also prove the kill cheaply)
    assert st["last_cycle_ok"] is False and st["failed_cycles"] == 1, st
    assert states[-1] == "cooldown" and "reloading" not in states, states
    assert tail["reason"] == "canary_regression", tail
    assert not reloads and not reload_fails, (reloads, reload_fails)
    for k in baseline:
        np.testing.assert_array_equal(
            np.asarray(baseline[k]), np.asarray(after[k])
        )
    print(
        "closed-loop smoke (canary): OK (regressed candidate rejected "
        "at the canary gate, old weights bit-identical)"
    )
elif scenario == "torn":
    # the pilot canary passed but the checkpoint was torn before the
    # swap: the RELOAD path's validating loader must reject it and the
    # old weights keep serving
    assert st["last_cycle_ok"] is False and st["failed_cycles"] == 1, st
    assert states[-2:] == ["reloading", "cooldown"], states
    assert tail["reason"] == "reload_failed", tail
    assert reload_fails and not reloads, (reloads, reload_fails)
    for k in baseline:
        np.testing.assert_array_equal(
            np.asarray(baseline[k]), np.asarray(after[k])
        )
    print(
        "closed-loop smoke (torn): OK (torn candidate rejected by the "
        "reload canary, old weights bit-identical)"
    )
else:
    raise SystemExit(f"unknown scenario {scenario!r}")
EOF
for SCEN in ok canary torn; do
    cp -r "$PILOT_DIR/train" "$PILOT_DIR/train_$SCEN"
done
PILOT_ENV=(env PYTHONPATH="$PWD" JAX_PLATFORMS=cpu HYDRAGNN_INJECT_DRIFT=5.0
    HYDRAGNN_PILOT_CANARY_TOL=10.0 HYDRAGNN_PILOT_COOLDOWN_S=120
    HYDRAGNN_PILOT_TUNE_EPOCHS=1 HYDRAGNN_PILOT_TUNE_BACKOFF_S=0.1)
"${PILOT_ENV[@]}" HYDRAGNN_INJECT_PILOT_TRAIN_CRASH=1 \
    python "$PILOT_DIR/driver.py" "$PILOT_DIR" "$PILOT_DIR/train_ok" \
    "$(ls "$PILOT_DIR"/train_ok/logs/*/flight.jsonl)" ok
"${PILOT_ENV[@]}" HYDRAGNN_INJECT_PILOT_CANARY_REGRESS=1 \
    python "$PILOT_DIR/driver.py" "$PILOT_DIR" "$PILOT_DIR/train_canary" \
    "$(ls "$PILOT_DIR"/train_canary/logs/*/flight.jsonl)" canary
"${PILOT_ENV[@]}" HYDRAGNN_INJECT_PILOT_TORN_RELOAD=1 \
    python "$PILOT_DIR/driver.py" "$PILOT_DIR" "$PILOT_DIR/train_torn" \
    "$(ls "$PILOT_DIR"/train_torn/logs/*/flight.jsonl)" torn
# the pilot gauges round-trip through the prom textfile to the probe:
# healthy after the reloaded cycle, degraded (rc 1) after a failed one
for SCEN in ok canary torn; do
    rc=0
    python tools/serve_probe.py --prom "$PILOT_DIR/$SCEN.prom" \
        --pilot --max-age 3600 --verbose || rc=$?
    case "$SCEN" in ok) want=0 ;; *) want=1 ;; esac
    if [ "$rc" -ne "$want" ]; then
        echo "FAIL: serve_probe --pilot rc=$rc want=$want ($SCEN)"; exit 1
    fi
done
# the fault timeline narrates the cycle (pilot events + the reload)
python tools/obs_report.py --faults "$PILOT_DIR/ok_flight.jsonl" \
    | tee "$PILOT_DIR/report.out"
grep -q "pilot_cycles=1" "$PILOT_DIR/report.out" || {
    echo "FAIL: obs_report.py did not count the pilot cycle"; exit 1; }
rm -rf "$PILOT_DIR"

echo "== perf gate (tiny fixed-config bench vs committed baseline) =="
# fails on a >15% graphs/sec regression (and MFU regression on TPU)
# against BENCH_CI_BASELINE.json, keyed per backend:device so every CI
# machine gates against its own recorded number (tools/bench_gate.py)
JAX_PLATFORMS=cpu python tools/bench_gate.py
# the gate must DEMONSTRABLY fail on a slow build: inject a genuine
# per-step slowdown into the timed loop and require a nonzero exit
if JAX_PLATFORMS=cpu python tools/bench_gate.py --inject-slowdown-ms 40 >/tmp/_gate_inject.log 2>&1; then
    echo "FAIL: bench gate did not catch an injected 40 ms/step slowdown"
    cat /tmp/_gate_inject.log
    exit 1
else
    echo "bench gate self-test: injected slowdown correctly rejected"
fi
# same for the traffic arm: price a real ballast executable's
# cost-model bytes into the step and require a nonzero exit
if JAX_PLATFORMS=cpu python tools/bench_gate.py --inject-traffic-mb 64 >/tmp/_gate_traffic.log 2>&1; then
    echo "FAIL: bench gate did not catch 64 MiB of injected step traffic"
    cat /tmp/_gate_traffic.log
    exit 1
else
    echo "bench gate self-test: injected traffic correctly rejected"
fi
# warm-start arm: same executable through a fresh cache — the warm start
# must cost <50% of the cold compile and perform 0 XLA compiles
JAX_PLATFORMS=cpu python tools/bench_gate.py --warm-start-arm

if [ "${CI_FULL:-0}" = "1" ]; then
    echo "== full acceptance matrix (reference thresholds) =="
    HYDRAGNN_FULL_MATRIX=1 python -m pytest tests/test_train_matrix.py -q
else
    echo "== full acceptance matrix: skipped (set CI_FULL=1) =="
fi

if [ "${CI_TPU:-0}" = "1" ]; then
    echo "== real-chip TPU kernel suite =="
    HYDRAGNN_TPU_TESTS=1 python -m pytest tests/test_tpu_chip.py -q
else
    echo "== real-chip TPU kernel suite: skipped (set CI_TPU=1, needs a TPU) =="
fi

echo "CI protocol complete."
