#!/usr/bin/env bash
# CI protocol runner — the committed encoding of the test discipline
# (VERDICT r02 item 7), mirroring the reference's CI pipeline
# (/root/reference/.github/workflows/CI.yml: black format gate, serial
# pytest, the same suite again under mpirun -n 2).
#
# Stages:
#   1. format gate      — `black --check .` when black is installed; the
#                         baked TPU image ships no formatter, so the gate
#                         degrades to a full-tree syntax check (compileall)
#                         and prints which gate ran.
#   2. serial suite     — python -m pytest tests/ -q on the virtual
#                         8-device CPU mesh (conftest pins it). This
#                         INCLUDES the 2-OS-process distributed pass: the
#                         reference re-runs its whole suite under
#                         `mpirun -n 2`; here the multi-process rendezvous
#                         is exercised by tests/test_multiprocess.py, which
#                         spawns 2 python processes with a shared
#                         coordinator itself (TPU-native launch shape —
#                         jax.distributed, not MPI).
#   3. full matrix      — opt-in (CI_FULL=1): all 7 models x head configs
#                         trained to the reference accuracy thresholds
#                         (HYDRAGNN_FULL_MATRIX=1, ~15 min).
#   4. TPU kernel suite — opt-in (CI_TPU=1, needs a real TPU):
#                         HYDRAGNN_TPU_TESTS=1 on-chip kernel-vs-XLA
#                         checks, budgeted under the tunnel's dispatch
#                         throttle (tests/test_tpu_chip.py).
#
# Usage: ./ci.sh            # stages 1-2 (the default CI gate)
#        CI_FULL=1 ./ci.sh  # + acceptance matrix
#        CI_TPU=1  ./ci.sh  # + real-chip kernel suite
set -euo pipefail
cd "$(dirname "$0")"

echo "== [1/4] format gate =="
if python -m black --version >/dev/null 2>&1; then
    python -m black --check .
elif command -v black >/dev/null 2>&1; then
    black --check .
else
    echo "black not installed in this image; running syntax gate (compileall)"
    python -m compileall -q hydragnn_tpu tests examples bench.py bench_scaling.py __graft_entry__.py
fi

echo "== [2/4] serial suite (virtual 8-device CPU mesh, incl. 2-process pass) =="
python -m pytest tests/ -q

if [ "${CI_FULL:-0}" = "1" ]; then
    echo "== [3/4] full acceptance matrix (reference thresholds) =="
    HYDRAGNN_FULL_MATRIX=1 python -m pytest tests/test_train_matrix.py -q
else
    echo "== [3/4] full acceptance matrix: skipped (set CI_FULL=1) =="
fi

if [ "${CI_TPU:-0}" = "1" ]; then
    echo "== [4/4] real-chip TPU kernel suite =="
    HYDRAGNN_TPU_TESTS=1 python -m pytest tests/test_tpu_chip.py -q
else
    echo "== [4/4] real-chip TPU kernel suite: skipped (set CI_TPU=1, needs a TPU) =="
fi

echo "CI protocol complete."
