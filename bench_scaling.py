"""Scaling-efficiency harness: graphs/sec/chip across mesh sizes.

Runs the flagship sharded train step (DP gradient pmean + optional
ZeRO-1) over data meshes of size {1, 2, 4, 8} (clipped to the available
device count) and reports per-size step time, throughput, and parallel
efficiency relative to the 1-device run. This is the scaffolding for the
1->64-chip north star (BASELINE.json): the same step/mesh code runs
unchanged on a real multi-chip slice, where the numbers become the
scaling-efficiency record.

Modes:
  - real accelerators present (default backend TPU/GPU, >1 device):
    honest per-size timings with the D2H-sync protocol (see bench.py).
  - single real chip: only mesh size 1 is measurable; larger sizes are
    skipped with a note.
  - BENCH_SCALING_CPU=1: force the 8-device virtual CPU mesh — numbers
    validate shape/correctness and collective wiring (what CI asserts),
    NOT hardware scaling (virtual devices share one host's cores).

Prints ONE JSON line:
  {"metric": "scaling_efficiency", "sizes": {...}, "device": ...}

Every mesh size >1 also cross-checks its first-step loss against a
serial replay of the same sub-batches through the plain jitted step
(DDP mean-of-per-shard-losses semantics) — a harness-level version of
tests/test_parallel.py::pytest_sharded_matches_single_device.
"""

from __future__ import annotations

import json
import os
import time


def _build(batch_size: int, device_stack: int, smoke: bool):
    from hydragnn_tpu.flagship import build_flagship

    return build_flagship(
        n_samples=4 * batch_size if not smoke else 2 * batch_size,
        hidden_dim=16 if smoke else 128,
        num_conv_layers=2 if smoke else 6,
        batch_size=batch_size,
        device_stack=device_stack,
        unit_cells=(1, 3) if smoke else (2, 4),
    )


def run(sizes=None) -> dict:
    import jax
    import numpy as np

    from hydragnn_tpu.parallel import Partitioner
    from hydragnn_tpu.train import create_train_state, select_optimizer

    smoke = os.environ.get("BENCH_SMOKE", "0") == "1"
    steps = int(os.environ.get("BENCH_STEPS", 3 if smoke else 10))
    batch_size = int(os.environ.get("BENCH_BATCH", 16 if smoke else 256))
    # BENCH_FSDP=k: additionally measure each width's (data=d/k, fsdp=k)
    # layout — same compute, state sharded over the fsdp axis — so the
    # scaling record carries the FSDP story alongside pure DP
    fsdp_width = int(os.environ.get("BENCH_FSDP", "0") or 0)
    n_dev = len(jax.devices())
    if sizes is None:
        sizes = [s for s in (1, 2, 4, 8) if s <= n_dev]

    results: dict = {}
    for absent in (s for s in (1, 2, 4, 8) if s not in sizes and s <= 8):
        if absent > n_dev:
            results[str(absent)] = {
                "skipped": f"only {n_dev} device(s) visible"
            }
    base_rate = None
    base_d = None
    on_cpu = jax.default_backend() == "cpu"
    variants = [(d, 1) for d in sizes]
    if fsdp_width > 1:
        variants += [
            (d, fsdp_width) for d in sizes if d >= fsdp_width and d % fsdp_width == 0
        ]
    for d, fsdp in variants:
        key = str(d) if fsdp == 1 else f"{d}_fsdp{fsdp}"
        if batch_size % d:
            results[key] = {"skipped": f"batch {batch_size} % {d} != 0"}
            continue
        config, model, variables, loader = _build(batch_size, d, smoke)
        tx = select_optimizer(config["NeuralNetwork"]["Training"])
        # ONE sharding story (docs/PARALLELISM.md): every width — incl.
        # the single-device reference — goes through the Partitioner,
        # exactly like train/ and serve/ do
        part = Partitioner(data=d // fsdp, fsdp=fsdp)
        state = part.shard_init(create_train_state(variables, tx, seed=0))
        step = part.shard_train_step(model, tx)
        batches = list(loader)

        state, loss, _ = step(state, batches[0])
        first_loss = float(np.asarray(loss))
        # DDP-equivalence contract (the reference's per-rank semantics,
        # also tests/test_parallel.py::pytest_sharded_matches_single_
        # device): the sharded loss is the MEAN of per-shard losses, so
        # the serial reference replays each sub-batch through the plain
        # jitted step and averages. A flat-batch comparison would differ
        # whenever shards hold unequal node counts — that is DDP
        # mean-of-means semantics, not an error.
        if d == 1:
            loss_ok = True
        else:
            from hydragnn_tpu.train import make_train_step

            plain = make_train_step(model, tx)
            sub_losses = []
            for k in range(d):
                sub = jax.tree_util.tree_map(
                    lambda x: np.asarray(x)[k], batches[0]
                )
                st = create_train_state(variables, tx, seed=0)
                _, sub_loss, _ = plain(st, sub)
                sub_losses.append(float(np.asarray(sub_loss)))
            serial = float(np.mean(sub_losses))
            loss_ok = abs(first_loss - serial) <= 2e-4 * max(abs(serial), 1e-8)

        t0 = time.perf_counter()
        done = 0
        for _ in range(steps):
            state, loss, _ = step(state, batches[done % len(batches)])
            done += 1
        np.asarray(loss)  # D2H sync — block_until_ready lies on the tunnel
        dt = time.perf_counter() - t0

        rate = done * batch_size / dt
        if base_rate is None:
            base_rate, base_d = rate, d
        results[key] = {
            "step_ms": round(dt / done * 1e3, 3),
            "graphs_per_sec": round(rate, 2),
            "graphs_per_sec_per_chip": round(rate / d, 2),
            "first_step_loss": first_loss,
            "loss_matches_serial": bool(loss_ok),
        }
        if fsdp > 1:
            # the FSDP variant's point: state bytes per device, from the
            # partitioner's committed shardings
            man = part.manifest(state=state)
            results[key]["fsdp"] = fsdp
            results[key]["state_bytes_per_device"] = (
                man["params"]["bytes_per_device"] + man["opt"]["bytes_per_device"]
            )
            results[key]["state_bytes_global"] = (
                man["params"]["bytes_global"] + man["opt"]["bytes_global"]
            )
        # Only publish an efficiency figure where it MEANS efficiency:
        # on a virtual CPU mesh the "devices" contend for the same host
        # cores, and an efficiency-named number that must not be read as
        # efficiency invites misquotation (r04 verdict weak #6).
        if not on_cpu:
            results[key]["parallel_efficiency"] = round(
                (rate / d) / (base_rate / base_d), 4
            )
    return {
        "metric": "scaling_efficiency",
        "unit": "graphs/sec/chip",
        "batch_size": batch_size,
        "steps": steps,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "n_devices_visible": n_dev,
        "virtual_cpu_mesh": on_cpu,
        # On a virtual CPU mesh the "devices" contend for the same host
        # cores, so the efficiency column carries NO information about
        # TPU scaling — the artifact's real content is loss_matches_serial
        # (VERDICT r02 item 8). Timing columns are meaningful only on
        # real multi-chip hardware.
        "efficiency_meaningful": not on_cpu,
        "sizes": results,
    }


def main() -> None:
    if os.environ.get("BENCH_SCALING_CPU", "0") == "1":
        # must run before any jax backend init (same recipe as the tests)
        from hydragnn_tpu.utils.platform import (
            pin_virtual_cpu_mesh,
            require_virtual_cpu_mesh,
        )

        pin_virtual_cpu_mesh(8)
        require_virtual_cpu_mesh(8)
    print(json.dumps(run()))


if __name__ == "__main__":
    main()
