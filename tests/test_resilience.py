"""Fault-tolerant training (hydragnn_tpu/resilience): deterministic
fault-injection coverage of every path docs/RESILIENCE.md claims —
preemption, non-finite sentry + rollback, hang watchdog, checkpoint
retention/integrity fallback, and the bounded restart supervisor.
All CPU; process-killing faults run in subprocesses."""

import glob
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.obs.flight import read_flight_record, validate_flight_record
from hydragnn_tpu.resilience import (
    EXIT_CONFIG_ERROR,
    EXIT_HUNG,
    EXIT_PREEMPTED,
    EXIT_ROLLBACK_EXHAUSTED,
    HangWatchdog,
    NonFiniteRollbackExhausted,
    Supervisor,
    SupervisorPolicy,
    TrainingPreempted,
    classify_exit,
    run_guard,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------------
# tiny shared run config

def _tiny_config(num_epoch=2, **training_overrides):
    from hydragnn_tpu.flagship import flagship_config

    cfg = flagship_config(
        hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=num_epoch
    )
    cfg["NeuralNetwork"]["Training"].update(training_overrides)
    return cfg


def _tiny_samples():
    from hydragnn_tpu.data.synthetic import deterministic_graph_data

    return deterministic_graph_data(
        number_configurations=20,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )


_CHILD = r"""
import sys
sys.path.insert(0, {repo!r})
from __graft_entry__ import _load_platform_module
_load_platform_module().pin_virtual_cpu_mesh(1)

from hydragnn_tpu.resilience import run_guard
from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config

cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)
cfg["NeuralNetwork"]["Training"].update({training!r})
samples = deterministic_graph_data(
    number_configurations=20, unit_cell_x_range=(2, 3), unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3), seed=0)
with run_guard():
    run_training(cfg, samples=samples, log_dir=sys.argv[1] + "/logs/")
print("CHILD-COMPLETED")
"""


def _run_child(tmp_path, training, env_extra, timeout=240):
    script = tmp_path / "child.py"
    script.write_text(_CHILD.format(repo=_REPO, training=dict(training)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", **env_extra)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, str(script), str(tmp_path)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=timeout,
    )
    return proc


def _flight_events(tmp_path):
    (fl,) = glob.glob(str(tmp_path / "logs" / "*" / "flight.jsonl"))
    return read_flight_record(fl)


def _final_val_loss(tmp_path):
    (mp,) = glob.glob(str(tmp_path / "logs" / "*" / "metrics.jsonl"))
    with open(mp) as f:
        rows = [json.loads(line) for line in f]
    return rows[-1]["val_loss"]


@pytest.fixture(scope="module")
def reference_run(tmp_path_factory):
    """One clean uninterrupted run of the tiny config — the equivalence
    baseline the interrupted-then-resumed scenarios must match."""
    from hydragnn_tpu.api import run_training

    d = tmp_path_factory.mktemp("reference")
    cfg = _tiny_config(checkpoint_every=1)
    _, _, history, _ = run_training(
        cfg, samples=_tiny_samples(), log_dir=str(d / "logs/")
    )
    return d, history


# ---------------------------------------------------------------------------
# exit-code contract + supervisor policy (no jax, no processes)

def pytest_classify_exit_contract():
    assert classify_exit(0) == "completed"
    assert classify_exit(EXIT_PREEMPTED) == "preempted"
    assert classify_exit(EXIT_ROLLBACK_EXHAUSTED) == "rollback_exhausted"
    assert classify_exit(EXIT_CONFIG_ERROR) == "config_error"
    assert classify_exit(EXIT_HUNG) == "hung"
    assert classify_exit(1) == "crash"
    assert classify_exit(-9) == "crash"  # SIGKILL


def pytest_supervisor_retries_crashes_with_backoff():
    codes = iter([1, EXIT_HUNG, 0])
    calls = []
    delays = []
    sup = Supervisor(
        ["cmd"],
        policy=SupervisorPolicy(max_restarts=5, backoff_base_s=1.0, backoff_max_s=60),
        env={"HYDRAGNN_INJECT_SIGTERM_STEP": "3", "KEEP": "1"},
        runner=lambda argv, env: (calls.append(dict(env)), next(codes))[1],
        sleep=delays.append,
    )
    result = sup.run()
    assert result["status"] == "completed"
    assert result["restarts"] == 2
    assert delays == [1.0, 2.0]  # exponential backoff
    # first attempt keeps injection + no resume; restarts strip/resume
    assert "HYDRAGNN_INJECT_SIGTERM_STEP" in calls[0]
    assert "HYDRAGNN_AUTO_RESUME" not in calls[0]
    for env in calls[1:]:
        assert "HYDRAGNN_INJECT_SIGTERM_STEP" not in env
        assert env["HYDRAGNN_AUTO_RESUME"] == "1"
        assert env["KEEP"] == "1"


def pytest_supervisor_fail_fast_and_give_up():
    # config error: exactly one attempt, no sleeps
    delays = []
    sup = Supervisor(
        ["cmd"],
        runner=lambda argv, env: EXIT_CONFIG_ERROR,
        sleep=delays.append,
    )
    result = sup.run()
    assert result["status"] == "failed_fast"
    assert result["cause"] == "config_error"
    assert result["attempts"] == 1 and delays == []
    # rollback exhausted: also fail-fast
    assert (
        Supervisor(["c"], runner=lambda a, e: EXIT_ROLLBACK_EXHAUSTED).run()["status"]
        == "failed_fast"
    )
    # unbounded crashes: bounded give-up
    sup = Supervisor(
        ["cmd"],
        policy=SupervisorPolicy(max_restarts=2, backoff_base_s=0.0),
        runner=lambda argv, env: 1,
        sleep=lambda s: None,
    )
    result = sup.run()
    assert result["status"] == "gave_up"
    assert result["attempts"] == 3  # initial + 2 restarts


def pytest_supervisor_preemption_restarts_promptly():
    codes = iter([EXIT_PREEMPTED, EXIT_PREEMPTED, 0])
    delays = []
    sup = Supervisor(
        ["cmd"],
        policy=SupervisorPolicy(max_restarts=0),  # preemptions aren't crashes
        runner=lambda argv, env: next(codes),
        sleep=delays.append,
    )
    result = sup.run()
    assert result["status"] == "completed"
    assert result["preemptions"] == 2
    assert delays == []  # no backoff for eviction


def pytest_run_guard_exit_codes():
    with pytest.raises(SystemExit) as e:
        with run_guard():
            raise TrainingPreempted(15, 3)
    assert e.value.code == EXIT_PREEMPTED
    with pytest.raises(SystemExit) as e:
        with run_guard():
            raise NonFiniteRollbackExhausted("gave up")
    assert e.value.code == EXIT_ROLLBACK_EXHAUSTED
    with pytest.raises(SystemExit) as e:
        with run_guard():
            raise ValueError("bad config")
    assert e.value.code == EXIT_CONFIG_ERROR
    with pytest.raises(RuntimeError):
        with run_guard():  # crash class propagates untouched
            raise RuntimeError("boom")


def pytest_watchdog_arms_after_warmup_and_fires(tmp_path):
    from hydragnn_tpu.obs.flight import FlightRecorder

    fired = []
    flight = FlightRecorder(str(tmp_path / "flight.jsonl"))
    wd = HangWatchdog(
        stall_s=0.2,
        flight=flight,
        action=lambda: fired.append(True),
        poll_s=0.02,
        warmup_beats=2,
    )
    wd.start()
    try:
        time.sleep(0.5)  # unarmed: setup/compile time never fires
        assert not wd.fired
        for _ in range(3):
            wd.beat()
        assert wd.armed
        time.sleep(0.5)
        assert wd.fired and fired
    finally:
        wd.stop()
    events = read_flight_record(str(tmp_path / "flight.jsonl"))
    (wd_ev,) = [e for e in events if e["kind"] == "watchdog"]
    assert wd_ev["stall_s"] >= 0.2 and wd_ev["stacks"]
    assert events[-1]["kind"] == "run_end" and events[-1]["status"] == "hung"
    assert not validate_flight_record(events)


# ---------------------------------------------------------------------------
# checkpoint retention + integrity fallback (in-process)

def _fake_state(step, value):
    from hydragnn_tpu.train.state import TrainState

    return TrainState(
        step=jnp.asarray(step, jnp.int32),
        params={"w": jnp.full((4,), float(value))},
        batch_stats={},
        opt_state=(),
        rng=jax.random.PRNGKey(0),
    )


def pytest_checkpoint_retention_prunes_and_falls_back(tmp_path):
    from hydragnn_tpu.utils.checkpoint import (
        checkpoint_exists,
        list_versioned_checkpoints,
        load_existing_model,
        save_model,
        validate_checkpoint_file,
    )

    log_dir = str(tmp_path)
    for step in (1, 2, 3):
        save_model(_fake_state(step, step * 10.0), "run", log_dir, keep_last=2)
    versions = list_versioned_checkpoints("run", log_dir)
    assert [s for s, _ in versions] == [3, 2]  # keep-last-2, newest first
    assert all(validate_checkpoint_file(p) for _, p in versions)
    assert checkpoint_exists("run", log_dir)

    # torn latest-pointer write: truncated file fails validation, the
    # restore falls back to the newest intact version
    pointer = os.path.join(log_dir, "run", "run.mp")
    with open(pointer, "rb") as f:
        data = f.read()
    with open(pointer, "wb") as f:
        f.write(data[: len(data) // 2])
    assert not validate_checkpoint_file(pointer)
    with pytest.warns(RuntimeWarning, match="rejected"):
        restored = load_existing_model(_fake_state(0, 0.0), "run", log_dir)
    assert int(restored.step) == 3
    np.testing.assert_allclose(np.asarray(restored.params["w"]), 30.0)

    # every candidate corrupt -> loud failure, not a silent fresh start
    for _, p in list_versioned_checkpoints("run", log_dir):
        with open(p, "wb") as f:
            f.write(b"junk")
    with pytest.raises(ValueError, match="no valid checkpoint"):
        load_existing_model(_fake_state(0, 0.0), "run", log_dir)


# ---------------------------------------------------------------------------
# guarded train step (device half of the sentry)

def pytest_guarded_step_skips_nonfinite_batch():
    from hydragnn_tpu.graph import batch_graphs
    from hydragnn_tpu.models import ModelConfig, create_model
    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

    rng = np.random.default_rng(0)
    n, e = 24, 64
    g = {
        "x": rng.normal(size=(n, 4)).astype(np.float32),
        "senders": rng.integers(0, n, e).astype(np.int32),
        "receivers": np.sort(rng.integers(0, n, e)).astype(np.int32),
        "graph_targets": {"energy": np.asarray([1.0], np.float32)},
    }
    batch = batch_graphs([g], n_node_pad=n + 8, n_edge_pad=e + 8, n_graph_pad=2)
    cfg = ModelConfig(
        model_type="GIN",
        input_dim=4,
        hidden_dim=8,
        output_dim=(1,),
        output_type=("graph",),
        output_names=("energy",),
        task_weights=(1.0,),
        num_conv_layers=2,
        graph_num_sharedlayers=1,
        graph_dim_sharedlayers=8,
        graph_num_headlayers=1,
        graph_dim_headlayers=(8,),
    )
    model, variables = create_model(cfg, batch)
    tx = select_optimizer({"Optimizer": {"type": "SGD", "learning_rate": 0.05}})
    step = make_train_step(model, tx, guard_nonfinite=True)

    state = create_train_state(variables, tx, seed=0)
    before = jax.device_get(state.params)
    consec = jnp.zeros((), jnp.int32)

    nan_batch = batch.replace(nodes=np.full_like(np.asarray(batch.nodes), np.nan))
    state, loss, tasks, consec, bad = step(state, nan_batch, consec)
    assert float(bad) == 1.0 and int(consec) == 1
    assert float(loss) == 0.0 and int(state.step) == 0  # update skipped
    for a, b in zip(
        jax.tree_util.tree_leaves(before),
        jax.tree_util.tree_leaves(jax.device_get(state.params)),
    ):
        np.testing.assert_array_equal(a, b)

    state, loss, tasks, consec, bad = step(state, batch, consec)
    assert float(bad) == 0.0 and int(consec) == 0  # consec resets
    assert np.isfinite(float(loss)) and int(state.step) == 1
    changed = any(
        not np.array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(before),
            jax.tree_util.tree_leaves(jax.device_get(state.params)),
        )
    )
    assert changed  # the good batch's update landed


# ---------------------------------------------------------------------------
# in-process fault injection through the full loop

def pytest_nan_injection_skipped_and_counted(tmp_path, monkeypatch):
    from hydragnn_tpu.api import run_training

    monkeypatch.setenv("HYDRAGNN_INJECT_NAN_STEP", "3:2")
    cfg = _tiny_config(num_epoch=3)
    _, _, history, _ = run_training(
        cfg, samples=_tiny_samples(), log_dir=str(tmp_path / "logs/")
    )
    assert np.isfinite(np.asarray(history["train_loss"])).all()
    assert history["train_loss"][-1] < history["train_loss"][0]
    skipped = {
        e["epoch"]: e["nonfinite"]["skipped"]
        for e in _flight_events(tmp_path)
        if e.get("kind") == "epoch" and e.get("nonfinite")
    }
    assert skipped == {0: 1, 1: 1}  # steps 3 and 4 (epochs of 4 steps)


def pytest_consecutive_nans_roll_back_to_last_good(tmp_path, monkeypatch):
    from hydragnn_tpu.api import run_training

    # steps 6-7: the tail of epoch 1 — its end-of-epoch consec (2)
    # meets the patience and rollback fires against epoch 0's checkpoint
    monkeypatch.setenv("HYDRAGNN_INJECT_NAN_STEP", "6:2")
    cfg = _tiny_config(num_epoch=4, checkpoint_every=1, nonfinite_patience=2)
    _, _, history, _ = run_training(
        cfg, samples=_tiny_samples(), log_dir=str(tmp_path / "logs/")
    )
    events = _flight_events(tmp_path)
    rollbacks = [e for e in events if e.get("kind") == "rollback"]
    assert len(rollbacks) == 1
    assert rollbacks[0]["epoch"] == 1 and rollbacks[0]["consec"] == 2
    assert events[-1]["kind"] == "run_end" and events[-1]["status"] == "completed"
    # the reduced-LR signal
    assert history["lr"][-1] == pytest.approx(history["lr"][0] * 0.5)
    assert not validate_flight_record(events)


def pytest_rollback_budget_exhausts_to_typed_failure(tmp_path, monkeypatch):
    from hydragnn_tpu.api import run_training

    # NaNs from step 6 onward: every epoch tail is bad; one rollback is
    # allowed, the second trips the budget -> typed fail-fast exception
    monkeypatch.setenv("HYDRAGNN_INJECT_NAN_STEP", "6:100")
    cfg = _tiny_config(
        num_epoch=6,
        checkpoint_every=1,
        nonfinite_patience=2,
        nonfinite_max_rollbacks=1,
    )
    with pytest.raises(NonFiniteRollbackExhausted):
        run_training(cfg, samples=_tiny_samples(), log_dir=str(tmp_path / "logs/"))
    events = _flight_events(tmp_path)
    assert sum(e.get("kind") == "rollback" for e in events) == 1
    assert events[-1]["kind"] == "run_end" and events[-1]["status"] == "failed"


# ---------------------------------------------------------------------------
# process-killing faults (subprocess)

@pytest.mark.slow
def pytest_sigterm_preempts_then_resumes(tmp_path, reference_run):
    # SIGTERM mid-epoch: distinct exit code, checkpoint + meta written,
    # flight ends preempted
    proc = _run_child(
        tmp_path,
        {"checkpoint_every": 1},
        {"HYDRAGNN_INJECT_SIGTERM_STEP": "2"},
    )
    assert proc.returncode == EXIT_PREEMPTED, proc.stdout
    events = _flight_events(tmp_path)
    assert events[-1]["kind"] == "run_end" and events[-1]["status"] == "preempted"
    (preempt,) = [e for e in events if e.get("kind") == "preempt"]
    assert preempt["signal"] == 15
    assert glob.glob(str(tmp_path / "logs" / "*" / "*.mp"))
    assert glob.glob(str(tmp_path / "logs" / "*" / "*.meta.json"))

    # resume (what the supervisor does): completes, one resumed event,
    # and the merged record stays schema-valid
    proc = _run_child(tmp_path, {"checkpoint_every": 1}, {"HYDRAGNN_AUTO_RESUME": "1"})
    assert proc.returncode == 0, proc.stdout
    assert "CHILD-COMPLETED" in proc.stdout
    events = _flight_events(tmp_path)
    assert sum(e.get("kind") == "resumed" for e in events) == 1
    statuses = [e["status"] for e in events if e.get("kind") == "run_end"]
    assert statuses == ["preempted", "completed"]
    assert not validate_flight_record(events)
    # the resumed run converges to the uninterrupted reference
    _, ref_history = reference_run
    assert _final_val_loss(tmp_path) == pytest.approx(
        ref_history["val_loss"][-1], rel=0.2
    )


@pytest.mark.slow
def pytest_sigkill_mid_checkpoint_restores_previous_valid(tmp_path, reference_run):
    # the 2nd checkpoint save tears the latest-pointer write and
    # SIGKILLs; subprocess reports the signal death
    proc = _run_child(
        tmp_path,
        {"checkpoint_every": 1},
        {"HYDRAGNN_INJECT_KILL_CHECKPOINT": "2"},
    )
    assert proc.returncode == -9, proc.stdout
    from hydragnn_tpu.utils.checkpoint import validate_checkpoint_file

    (run_dir,) = glob.glob(str(tmp_path / "logs" / "*/"))
    pointer = [
        p
        for p in glob.glob(os.path.join(run_dir, "*.mp"))
        if ".step" not in os.path.basename(p)
    ]
    assert pointer and not validate_checkpoint_file(pointer[0])

    # restart: integrity check rejects the torn pointer, restores the
    # newest intact version, and the run completes
    proc = _run_child(tmp_path, {"checkpoint_every": 1}, {"HYDRAGNN_AUTO_RESUME": "1"})
    assert proc.returncode == 0, proc.stdout
    assert "rejected" in proc.stdout  # the integrity warning fired
    events = _flight_events(tmp_path)
    assert sum(e.get("kind") == "resumed" for e in events) == 1
    assert events[-1]["status"] == "completed"
    # final eval loss matches an uninterrupted run of the same config
    _, ref_history = reference_run
    assert _final_val_loss(tmp_path) == pytest.approx(
        ref_history["val_loss"][-1], rel=1e-3
    )


@pytest.mark.slow
def pytest_stalled_loader_trips_watchdog_with_stacks(tmp_path):
    proc = _run_child(
        tmp_path,
        {"watchdog_stall_s": 3.0},
        {"HYDRAGNN_INJECT_STALL_LOADER": "2:120"},
        timeout=180,
    )
    assert proc.returncode == EXIT_HUNG, proc.stdout
    events = _flight_events(tmp_path)
    (wd,) = [e for e in events if e.get("kind") == "watchdog"]
    assert wd["stall_s"] >= 3.0
    assert "MainThread" in wd["stacks"]  # the blocked consumer's stack
    assert events[-1]["kind"] == "run_end" and events[-1]["status"] == "hung"
    assert not validate_flight_record(events)


def pytest_strip_injection_env_derives_from_knob_registry():
    """strip_injection_env must drop EVERY registered HYDRAGNN_INJECT_*
    knob — derived from knobs.active_injections(), not a hand-kept list
    that silently rots when a new injection is added — plus any
    unregistered INJECT-prefixed stragglers, while preserving
    everything else (including HYDRAGNN_AUTO_RESUME / exec-cache env)."""
    from hydragnn_tpu.resilience.inject import strip_injection_env
    from hydragnn_tpu.utils import knobs

    registered = [
        k for k in knobs.KNOBS if k.startswith(knobs.INJECT_PREFIX)
    ]
    assert "HYDRAGNN_INJECT_POD_KILL_HOST" in registered  # pod faults too
    assert "HYDRAGNN_INJECT_STRAGGLER" in registered
    env = {k: "1" for k in registered}
    env["HYDRAGNN_INJECT_FUTURE_UNREGISTERED"] = "1"  # prefix backstop
    env["HYDRAGNN_AUTO_RESUME"] = "1"
    env["HYDRAGNN_EXEC_CACHE"] = "/tmp/cache"
    env["KEEP"] = "x"
    out = strip_injection_env(env)
    assert not any(k.startswith(knobs.INJECT_PREFIX) for k in out)
    assert out["HYDRAGNN_AUTO_RESUME"] == "1"
    assert out["HYDRAGNN_EXEC_CACHE"] == "/tmp/cache"
    assert out["KEEP"] == "x"


# ---------------------------------------------------------------------------
# obs_report --faults view

def pytest_obs_report_faults_view(tmp_path, monkeypatch, capsys):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import obs_report

    from hydragnn_tpu.obs.flight import FlightRecorder

    path = str(tmp_path / "flight.jsonl")
    with FlightRecorder(path) as fl:
        fl.start_run({"run": "x"})
        fl.record("preempt", signal=15, epoch=1, step=9)
        fl.end_run(status="preempted")
        fl.start_run({"run": "x"})
        fl.record("resumed", epoch=1)
        fl.record("rollback", epoch=2, consec=4, rollbacks=1, lr=5e-4)
        fl.record("restart", attempt=1, cause="crash", exit_code=1, delay_s=1.0)
        fl.end_run(status="completed")
    assert obs_report.main(["--faults", path]) == 0
    out = capsys.readouterr().out
    assert "preempted=1" in out and "resumed=1" in out and "rollbacks=1" in out
    assert "[watchdog]" not in out and "[rollback]" in out

    # a fault event missing required fields is a schema failure
    with open(path, "a") as f:
        f.write(json.dumps({"v": 1, "kind": "rollback", "t": 0, "rank": 0}) + "\n")
        f.write("{}\n")  # keep a parseable final line so the tail isn't dropped
    assert obs_report.main(["--faults", path]) == 1
