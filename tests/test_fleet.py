"""Fleet subsystem tests (hydragnn_tpu/fleet): fake-clock autoscaler
decision policy, router admission (quotas, priorities, placement,
death-retry), and the real-fleet integration contracts — warm-start
from the shared exec cache, kill-then-replace, rolling reload
bit-identity.

The controller suite drives :meth:`FleetController.step` directly under
an injected clock against a stub fleet, so every decision (sustained
breach scale-up, cooldown suppression, quiet scale-down, min/max
bounds, dead-replica reap) is asserted deterministically — no sleeps,
no wall clock. The router suite uses stub replicas for the same reason.
Integration tests build a real smoke-sized fleet (CPU, conftest's
virtual mesh), warmed once through a shared exec cache.
"""

import os
import time
from concurrent.futures import Future

import numpy as np
import pytest

from hydragnn_tpu.fleet import (
    ControllerConfig,
    Fleet,
    FleetController,
    RouterConfig,
    FleetRouter,
    TenantOverloaded,
    TenantQuota,
)
from hydragnn_tpu.obs.registry import MetricsRegistry
from hydragnn_tpu.serve import ModelRegistry, Overloaded, ServeConfig
from hydragnn_tpu.serve.server import RequestFailed


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeFleet:
    """Duck-typed fleet for controller tests: scaling verbs record
    their calls and mutate a replica counter."""

    def __init__(self, replicas: int = 1, load: int = 0):
        self.n = replicas
        self.load = load
        self.dead: list = []
        self.calls: list = []
        self.fail_scale_up = False
        self.fail_replace = False

    def replica_count(self) -> int:
        return self.n

    def dead_replicas(self) -> list:
        return list(self.dead)

    def total_load(self) -> int:
        return self.load

    def scale_up(self, reason: str = "manual") -> str:
        self.calls.append(("up", reason))
        if self.fail_scale_up:
            raise RuntimeError("spawn exploded")
        self.n += 1
        return f"r{self.n}"

    def scale_down(self, reason: str = "manual", timeout=None) -> str:
        self.calls.append(("down", reason))
        self.n -= 1
        return "r0"

    def replace(self, name: str, reason: str = "dead_replica") -> str:
        self.calls.append(("replace", name))
        if self.fail_replace:
            raise RuntimeError("respawn exploded")
        self.dead.remove(name)
        return f"{name}bis"


def _controller(fleet, clk, **cfg_kw):
    """Controller + its private registry's fleet.queue_depth gauge."""
    reg = MetricsRegistry()
    gauge = reg.gauge("fleet.queue_depth")
    defaults = dict(
        min_replicas=1, max_replicas=3, cooldown_s=60.0, quiet_for_s=120.0,
        eval_every_s=1.0, breach_evals=2, slo_queue_depth=8.0,
    )
    defaults.update(cfg_kw)
    ctl = FleetController(
        fleet, registry=reg, config=ControllerConfig(**defaults), clock=clk
    )
    return ctl, gauge


# ---------------------------------------------------------------------------
# autoscaler decision policy (fake clock, stub fleet)
# ---------------------------------------------------------------------------


def test_sustained_breach_scales_up_once():
    fleet, clk = FakeFleet(replicas=1), FakeClock()
    ctl, gauge = _controller(fleet, clk)
    gauge.set(20)  # over slo_queue_depth=8
    assert ctl.step() == []  # one breach is a blip, not a capacity problem
    clk.advance(1.0)
    out = ctl.step()  # second consecutive breach: sustained
    assert [d["action"] for d in out] == ["up"]
    assert out[0]["reason"] == "fleet_queue_depth"
    assert out[0]["spawned"] == "r2"
    assert fleet.n == 2
    assert [d["action"] for d in ctl.decision_log()] == ["up"]


def test_cooldown_suppresses_then_rearms():
    fleet, clk = FakeFleet(replicas=1), FakeClock()
    ctl, gauge = _controller(fleet, clk)
    gauge.set(20)
    ctl.step()
    clk.advance(1.0)
    assert [d["action"] for d in ctl.step()] == ["up"]
    # still breaching, but the last decision is settling: no decision
    for _ in range(5):
        clk.advance(1.0)
        assert ctl.step() == []
    assert fleet.n == 2
    clk.advance(60.0)  # past cooldown_s
    out = ctl.step()
    assert [d["action"] for d in out] == ["up"] and fleet.n == 3


def test_breach_at_max_replicas_records_hold():
    fleet, clk = FakeFleet(replicas=3), FakeClock()
    ctl, gauge = _controller(fleet, clk, max_replicas=3)
    gauge.set(20)
    ctl.step()
    clk.advance(1.0)
    out = ctl.step()
    assert [d["action"] for d in out] == ["hold"]
    assert out[0]["bound"] == "max_replicas"
    assert fleet.n == 3 and fleet.calls == []  # suppressed, counted, no spawn


def test_quiet_fleet_scales_down_to_min_and_stops():
    fleet, clk = FakeFleet(replicas=3, load=0), FakeClock()
    ctl, gauge = _controller(fleet, clk, min_replicas=2)
    gauge.set(0)
    ctl.step()  # starts the quiet timer
    clk.advance(119.0)
    assert ctl.step() == []  # not quiet for long enough yet
    clk.advance(1.0)
    out = ctl.step()
    assert [d["action"] for d in out] == ["down"] and fleet.n == 2
    # at min_replicas now: quiet forever, never goes below the floor
    clk.advance(500.0)
    assert ctl.step() == []
    assert fleet.n == 2


def test_load_resets_quiet_timer():
    fleet, clk = FakeFleet(replicas=2, load=0), FakeClock()
    ctl, gauge = _controller(fleet, clk)
    ctl.step()
    clk.advance(100.0)
    fleet.load = 5  # traffic returns mid-countdown
    assert ctl.step() == []
    fleet.load = 0
    clk.advance(119.0)
    assert ctl.step() == []  # timer restarts HERE: quiet counted from now
    clk.advance(119.0)
    assert ctl.step() == []  # 119s since restart, needs 120
    clk.advance(2.0)
    assert [d["action"] for d in ctl.step()] == ["down"]


def test_dead_replica_replaced_even_during_cooldown():
    fleet, clk = FakeFleet(replicas=2), FakeClock()
    ctl, gauge = _controller(fleet, clk)
    gauge.set(20)
    ctl.step()
    clk.advance(1.0)
    assert [d["action"] for d in ctl.step()] == ["up"]  # starts cooldown
    fleet.dead = ["r1"]  # replica dies while the scale-up settles
    clk.advance(1.0)
    out = ctl.step()
    assert ("replace", "r1") in fleet.calls
    actions = [d["action"] for d in out]
    assert "replace" in actions  # capacity restoration is never rate-limited
    assert out[actions.index("replace")]["dead"] == "r1"


def test_scale_failures_become_decisions_not_crashes():
    fleet, clk = FakeFleet(replicas=1), FakeClock()
    fleet.fail_scale_up = True
    ctl, gauge = _controller(fleet, clk)
    gauge.set(20)
    ctl.step()
    clk.advance(1.0)
    out = ctl.step()
    assert [d["action"] for d in out] == ["up_failed"]
    assert "spawn exploded" in out[0]["error"]
    fleet2, clk2 = FakeFleet(replicas=2), FakeClock()
    fleet2.fail_replace = True
    fleet2.dead = ["r9"]
    ctl2, _ = _controller(fleet2, clk2)
    out2 = ctl2.step()
    assert [d["action"] for d in out2] == ["replace_failed"]


def test_decisions_are_flight_events(tmp_path):
    from hydragnn_tpu.obs import FlightRecorder
    from hydragnn_tpu.obs.flight import read_flight_record, validate_flight_record

    path = str(tmp_path / "fleet_flight.jsonl")
    flight = FlightRecorder(path)
    fleet, clk = FakeFleet(replicas=1), FakeClock()
    reg = MetricsRegistry()
    reg.gauge("fleet.queue_depth").set(20)
    ctl = FleetController(
        fleet,
        registry=reg,
        config=ControllerConfig(
            min_replicas=1, max_replicas=2, cooldown_s=0.0, quiet_for_s=1e9,
            breach_evals=1, slo_queue_depth=8.0,
        ),
        flight=flight,
        clock=clk,
    )
    ctl.step()
    flight.close()
    events = read_flight_record(path)
    scale = [e for e in events if e.get("kind") == "fleet_scale"]
    assert len(scale) == 1
    assert scale[0]["action"] == "up" and scale[0]["replicas"] == 2
    assert validate_flight_record(events) == []


# ---------------------------------------------------------------------------
# router admission (stub replicas)
# ---------------------------------------------------------------------------


class FakeReplica:
    def __init__(self, name: str, model: str = "m", load: int = 0):
        self.name = name
        self.model = model
        self._load = load
        self.ready = True
        self.live = True
        self.submitted: list = []
        self.fail_with = None

    def load(self) -> int:
        return self._load

    def queue_depth(self) -> int:
        return self._load

    def submit(self, sample, tenant=None) -> Future:
        fut: Future = Future()
        self.submitted.append((sample, fut))
        if self.fail_with is not None:
            fut.set_exception(self.fail_with)
        return fut


def _router(**kw):
    reg = MetricsRegistry()
    return FleetRouter(reg, **kw), reg


def test_quota_rejection_is_typed_with_tenant_in_trace():
    clk = FakeClock()
    router, reg = _router(clock=clk)
    router.attach(FakeReplica("r0"))
    router.set_quota("acme", TenantQuota(rate=1e-9, burst=1.0))
    fut = router.submit("s0", tenant="acme")  # burns the only token
    with pytest.raises(TenantOverloaded) as ei:
        router.submit("s1", tenant="acme")
    assert ei.value.tenant == "acme"
    assert ei.value.trace_id  # attributable end to end
    assert reg.get("fleet.rejected_quota").value == 1
    assert reg.get("fleet.tenant.acme.rejected").value == 1
    # the admission trace carries the tenant and the reject span
    rejected = [
        t for t in router.traces()
        if t.attrs.get("tenant") == "acme"
        and any(s["name"] == "fleet.reject" for s in t.spans)
    ]
    assert rejected and rejected[0].trace_id == ei.value.trace_id
    # an unrelated tenant is not throttled by acme's bucket
    router.submit("s2", tenant="other")
    assert len(router.replicas()[0].submitted) == 2
    fut.cancel()


def test_shed_gate_drops_batch_priority_only():
    router, _ = _router(config=RouterConfig(shed_load=1))
    busy = FakeReplica("r0", load=5)
    router.attach(busy)
    router.set_quota("bulk", TenantQuota(priority="batch"))
    with pytest.raises(TenantOverloaded):
        router.submit("s", tenant="bulk")
    router.submit("s", tenant="interactive")  # standard priority rides through
    assert len(busy.submitted) == 1


def test_least_loaded_ready_replica_wins():
    router, _ = _router()
    heavy = FakeReplica("r0", load=5)
    light = FakeReplica("r1", load=1)
    router.attach(heavy)
    router.attach(light)
    router.submit("s")
    assert len(light.submitted) == 1 and heavy.submitted == []
    # paused replicas leave placement without detaching
    router.pause("r1")
    router.submit("s2")
    assert len(heavy.submitted) == 1
    router.resume("r1")
    # not-ready replicas are skipped too
    heavy.ready = False
    router.submit("s3")
    assert len(light.submitted) == 2


def test_no_ready_replica_is_typed_overloaded():
    router, reg = _router()
    fut = router.submit("s")
    with pytest.raises(Overloaded):
        fut.result(timeout=5)
    assert reg.get("fleet.rejected_no_replica").value == 1


def test_replica_death_retries_on_another_replica():
    router, reg = _router()
    dying = FakeReplica("r0", load=0)
    dying.fail_with = RequestFailed("dispatch died", reason="dispatch")
    healthy = FakeReplica("r1", load=3)  # heavier, so the dying one is picked
    router.attach(dying)
    router.attach(healthy)
    fut = router.submit("s")
    assert len(dying.submitted) == 1 and len(healthy.submitted) == 1
    healthy.submitted[0][1].set_result({"e": 1.0})
    assert fut.result(timeout=5) == {"e": 1.0}
    assert reg.get("fleet.death_retries").value == 1
    assert reg.get("fleet.failed").value == 0
    # a non-death failure (poison request) is NOT retried: same answer
    # everywhere, so the typed error surfaces immediately
    healthy.fail_with = RequestFailed("nan", reason="nonfinite")
    dying.ready = False
    fut2 = router.submit("s2")
    with pytest.raises(RequestFailed):
        fut2.result(timeout=5)
    assert reg.get("fleet.death_retries").value == 1


# ---------------------------------------------------------------------------
# real fleet integration (smoke-sized, shared exec cache)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def flagship():
    from hydragnn_tpu.flagship import build_flagship

    _, model, variables, loader = build_flagship(
        n_samples=24,
        hidden_dim=8,
        num_conv_layers=2,
        batch_size=4,
        unit_cells=(2, 3),
    )
    registry = ModelRegistry()
    served = registry.register("fleet_smoke", model, variables)
    return served, variables, list(loader.all_samples)


@pytest.fixture(scope="module")
def exec_cache(tmp_path_factory):
    return str(tmp_path_factory.mktemp("fleet_exec_cache"))


def _serve_cfg():
    return ServeConfig(max_batch=4, num_buckets=2, max_delay_ms=2.0)


def test_fleet_second_replica_warm_starts_and_serves(flagship, exec_cache, tmp_path):
    served, _, samples = flagship
    with Fleet(exec_cache_dir=exec_cache) as fleet:
        reps = fleet.add_model("m", served, samples, _serve_cfg(), replicas=2)
        snap = reps[1].server.metrics_snapshot()
        assert snap["compile_warmup"] == 0, (
            "second replica paid AOT compiles despite the shared exec cache"
        )
        assert snap["exec_cache_hits"] > 0
        out = fleet.predict(samples[0], timeout=60)
        assert isinstance(out, dict) and out
        h = fleet.health()
        assert h["replica_count"] == 2 and h["ready_count"] == 2
        # probe aggregation over the exported textfiles
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
        try:
            from serve_probe import probe_fleet
        finally:
            sys.path.pop(0)
        probe_dir = str(tmp_path / "probes")
        fleet.export_probes(probe_dir)
        rc, rows = probe_fleet(probe_dir)
        assert rc == 0, rows
        assert {name for name, _, _ in rows} == {"router", "r0", "r1"}


def test_kill_then_controller_restores_capacity(flagship, exec_cache):
    served, _, samples = flagship
    with Fleet(exec_cache_dir=exec_cache) as fleet:
        fleet.add_model("m", served, samples, _serve_cfg(), replicas=2)
        victim = fleet.replicas()[0]
        victim.kill()
        assert fleet.dead_replicas() == [victim.name]
        ctl = FleetController(
            fleet,
            registry=fleet.registry,
            config=ControllerConfig(min_replicas=1, max_replicas=3),
        )
        out = ctl.step()
        assert [d["action"] for d in out] == ["replace"]
        assert fleet.dead_replicas() == []
        assert fleet.replica_count() == 2
        replacement = [r for r in fleet.replicas() if r.name != victim.name]
        assert all(r.ready for r in replacement)
        # the replacement warm-started from the shared cache
        assert all(
            r.server.metrics_snapshot()["compile_warmup"] == 0
            for r in replacement
        )
        assert isinstance(fleet.predict(samples[1], timeout=60), dict)


def test_rolling_reload_aborts_when_replica_dies_mid_roll(
    flagship, exec_cache, tmp_path
):
    """A replica that dies mid-roll aborts the roll: the fleet ends
    READY on the OLD weights with zero lost futures (the corpse's
    queued work failed typed when it died), and the abort is narrated
    as a ``fleet_reload`` flight event with ``aborted_roll``."""
    from hydragnn_tpu.obs.flight import FlightRecorder, read_flight_record
    from hydragnn_tpu.serve.server import ReloadFailed

    served, variables, samples = flagship
    flight_path = str(tmp_path / "flight.jsonl")
    with Fleet(
        exec_cache_dir=exec_cache, flight=FlightRecorder(flight_path)
    ) as fleet:
        fleet.add_model("m", served, samples, _serve_cfg(), replicas=2)
        # the roll visits replicas in name order: kill the first so the
        # abort fires before ANY replica swapped weights
        victim = sorted(fleet.replicas(), key=lambda r: r.name)[0]
        before = fleet.predict(samples[0], timeout=60)
        victim.kill()
        futures = [fleet.submit(s) for s in samples[:6]]
        with pytest.raises(ReloadFailed, match="died mid-roll"):
            fleet.rolling_reload("m", variables=dict(variables), drain_timeout_s=5.0)
        # zero lost futures: everything submitted resolves (result or
        # typed failure), nothing hangs
        resolved = 0
        for f in futures:
            try:
                f.result(timeout=60)
                resolved += 1
            except RequestFailed:
                resolved += 1
        assert resolved == len(futures)
        # the survivor still serves the previous weights
        h = fleet.health()
        assert h["ready_count"] >= 1
        after = fleet.predict(samples[0], timeout=60)
        for key in before:
            np.testing.assert_array_equal(
                np.asarray(before[key]), np.asarray(after[key])
            )
        events = read_flight_record(flight_path)
        aborts = [
            e
            for e in events
            if e.get("kind") == "fleet_reload" and e.get("aborted_roll")
        ]
        assert [e["replica"] for e in aborts] == [victim.name]
        assert not any(
            e.get("kind") == "fleet_reload" and e.get("ok")
            for e in events
        ), "no replica may swap weights on an aborted roll"


def test_rolling_reload_is_bit_identical_for_same_weights(flagship, exec_cache):
    served, variables, samples = flagship
    with Fleet(exec_cache_dir=exec_cache) as fleet:
        fleet.add_model("m", served, samples, _serve_cfg(), replicas=2)
        before = fleet.predict(samples[0], timeout=60)
        outcomes = fleet.rolling_reload("m", variables=variables)
        assert [o["ok"] for o in outcomes] == [True, True]
        assert all(r.ready for r in fleet.replicas())
        after = fleet.predict(samples[0], timeout=60)
        assert sorted(before) == sorted(after)
        for key in before:
            np.testing.assert_array_equal(
                np.asarray(before[key]), np.asarray(after[key])
            )
