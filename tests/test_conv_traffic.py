"""HBM-traffic campaign pins (ops/fused_conv.py + obs/introspect.py):
occupancy-aware chunk skipping must be bit-identical (f32) to the full
pad walk, the VMEM-resident multi-layer stack must be bit-identical to
the per-layer loop it replaces (forward AND gradients), the bf16
activation path must sit within its documented tolerance of f32, the
loader's filler batches must advertise zero device cost, and the
analytic conv-traffic model must show the headline >=30% bytes/step
drop on the large-graph shape — all in Pallas interpret mode on CPU."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.fused_conv import (
    _fused_ref,
    fused_conv,
    fused_conv_stack,
    residency_vmem_budget_bytes,
    residency_vmem_bytes,
)
from hydragnn_tpu.ops.segment_pallas import CE


@pytest.fixture
def occ_case():
    """Tail-occupancy layout: every edge slot at index >= real is pad
    (masked) — the loader contract behind GraphBatch.edge_occupancy."""
    rng = np.random.default_rng(42)
    e, n, h = 1400, 120, 128
    real = 640  # > CE, and leaves a full tail chunk to skip
    recv = np.sort(rng.integers(0, n - 15, e)).astype(np.int32)
    send = rng.integers(0, n, e).astype(np.int32)
    mask = rng.random(e) > 0.2
    mask[real:] = False
    x = rng.normal(size=(n, h)).astype(np.float32)
    return (
        jnp.asarray(x),
        jnp.asarray(send),
        jnp.asarray(recv),
        jnp.asarray(mask),
        n,
        jnp.asarray(real, jnp.int32),
    )


def _mlp_params(h, seed=7):
    rng = np.random.default_rng(seed)
    W = jnp.asarray((rng.normal(size=(h, h)) * 0.1).astype(np.float32))
    b = jnp.asarray((rng.normal(size=(h,)) * 0.1).astype(np.float32))
    return W, b


def pytest_occupancy_skip_bit_exact_fwd_and_vjp(occ_case, monkeypatch):
    """The skip path's contract: with every slot >= real_edges masked,
    bounding the chunk loop is BIT-IDENTICAL in f32 — forward and
    grads — because skipped chunks only ever contributed exact +0."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, mask, n, re_ = occ_case
    W, b = _mlp_params(x.shape[1])

    def run(real_edges):
        return fused_conv(
            x, send, recv, mask, n,
            branches=((W, b, None, None),), acts=("sigmoid",),
            real_edges=real_edges,
        )

    out_skip = run(re_)
    out_full = run(None)
    np.testing.assert_array_equal(np.asarray(out_skip), np.asarray(out_full))
    ref = _fused_ref(
        (1, ("sigmoid",)), n, x, send, recv, mask, ((W, b, None, None),), None
    )
    scale_ref = float(jnp.abs(ref).max()) or 1.0
    assert float(jnp.abs(out_skip - ref).max()) / scale_ref < 1e-4

    def loss(x, W, b, real_edges):
        o = fused_conv(
            x, send, recv, mask, n,
            branches=((W, b, None, None),), acts=("sigmoid",),
            real_edges=real_edges,
        )
        return (o * o).sum()

    g_skip = jax.grad(loss, argnums=(0, 1, 2))(x, W, b, re_)
    g_full = jax.grad(loss, argnums=(0, 1, 2))(x, W, b, None)
    for a, bb, name in zip(g_skip, g_full, ("x", "W", "b")):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(bb), err_msg=f"grad {name}"
        )


def pytest_occupancy_zero_is_exact_zeros(occ_case, monkeypatch):
    """A real_edges=0 batch (the loader's filler shape) must produce
    exact zeros even through a biased+activated edge network."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, _, n, _ = occ_case
    W, b = _mlp_params(x.shape[1])
    out = fused_conv(
        x, send, recv, jnp.zeros(send.shape[0], bool), n,
        branches=((W, jnp.ones_like(b), None, None),), acts=("softplus",),
        real_edges=jnp.asarray(0, jnp.int32),
    )
    assert float(jnp.abs(out).max()) == 0.0


def pytest_occupancy_skip_narrow_lane(monkeypatch):
    """Non-128 widths lane-pad into the kernel; the occupancy bound
    must stay bit-exact through that padding (identity mode + VJP)."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    rng = np.random.default_rng(4)
    e, n, h, real = 1100, 70, 40, 600
    recv = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    send = jnp.asarray(rng.integers(0, n, e).astype(np.int32))
    mask = np.asarray(rng.random(e) > 0.25)
    mask[real:] = False
    mask = jnp.asarray(mask)
    x = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))
    re_ = jnp.asarray(real, jnp.int32)
    out_skip = fused_conv(x, send, recv, mask, n, real_edges=re_)
    out_full = fused_conv(x, send, recv, mask, n)
    np.testing.assert_array_equal(np.asarray(out_skip), np.asarray(out_full))
    g1 = jax.grad(
        lambda x: (fused_conv(x, send, recv, mask, n, real_edges=re_) ** 2).sum()
    )(x)
    g2 = jax.grad(
        lambda x: (fused_conv(x, send, recv, mask, n) ** 2).sum()
    )(x)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def pytest_occupancy_skip_bf16_path(occ_case, monkeypatch):
    """bf16 activations + occupancy skip: skip vs no-skip stays
    bit-identical (same arithmetic, fewer chunks), and the bf16 result
    sits within the documented 5e-2 relative bound of the f32
    reference (one bf16 rounding on the streamed operands; f32 MXU
    accumulation — docs/PERF.md r08)."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, mask, n, re_ = occ_case
    xb = x.astype(jnp.bfloat16)
    out_skip = fused_conv(xb, send, recv, mask, n, real_edges=re_)
    out_full = fused_conv(xb, send, recv, mask, n)
    np.testing.assert_array_equal(np.asarray(out_skip), np.asarray(out_full))
    ref = _fused_ref((0, ()), n, x, send, recv, mask, (), None)
    scale_ref = float(jnp.abs(ref).max()) or 1.0
    assert float(jnp.abs(out_skip - ref).max()) / scale_ref < 5e-2


def _loop_stack(x, send, recv, mask, n, Ws, bs, real_edges=None):
    """The per-layer composition fused_conv_stack's resident kernel
    must reproduce bit-for-bit: sigmoid edge act, relu between layers."""
    h = x
    out = None
    for l in range(Ws.shape[0]):
        out = fused_conv(
            h, send, recv, mask, n,
            branches=((Ws[l], bs[l], None, None),), acts=("sigmoid",),
            real_edges=real_edges,
        )
        if l + 1 < Ws.shape[0]:
            h = jax.nn.relu(out).astype(x.dtype)
    return out


def pytest_resident_stack_bit_exact_vs_loop(occ_case, monkeypatch):
    """The cross-layer VMEM-resident kernel vs the per-layer loop it
    replaces: bit-identical forward and grads (x, W, b) in f32 — the
    residency optimisation moves bytes, never bits."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, mask, n, re_ = occ_case
    h = x.shape[1]
    rng = np.random.default_rng(9)
    L = 2
    Ws = jnp.asarray((rng.normal(size=(L, h, h)) * 0.1).astype(np.float32))
    bs = jnp.asarray((rng.normal(size=(L, h)) * 0.1).astype(np.float32))
    assert residency_vmem_bytes(n, h) <= residency_vmem_budget_bytes()

    out_res = fused_conv_stack(
        x, send, recv, mask, n, Ws, bs,
        edge_act="sigmoid", inter_act="relu", real_edges=re_,
    )
    out_loop = _loop_stack(x, send, recv, mask, n, Ws, bs, real_edges=re_)
    np.testing.assert_array_equal(np.asarray(out_res), np.asarray(out_loop))

    def loss_res(x, Ws, bs):
        o = fused_conv_stack(
            x, send, recv, mask, n, Ws, bs,
            edge_act="sigmoid", inter_act="relu", real_edges=re_,
        )
        return (o * o).sum()

    def loss_loop(x, Ws, bs):
        o = _loop_stack(x, send, recv, mask, n, Ws, bs, real_edges=re_)
        return (o * o).sum()

    g1 = jax.grad(loss_res, argnums=(0, 1, 2))(x, Ws, bs)
    g2 = jax.grad(loss_loop, argnums=(0, 1, 2))(x, Ws, bs)
    for a, bb, name in zip(g1, g2, ("x", "W", "b")):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(bb), err_msg=f"grad {name}"
        )


def pytest_resident_stack_budget_fallback(occ_case, monkeypatch):
    """A VMEM budget too small for the footprint must fall back to the
    per-layer path with identical results — the decision rule is an
    implementation detail, never a numerics change."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, mask, n, re_ = occ_case
    h = x.shape[1]
    rng = np.random.default_rng(10)
    Ws = jnp.asarray((rng.normal(size=(2, h, h)) * 0.1).astype(np.float32))
    bs = jnp.asarray((rng.normal(size=(2, h)) * 0.1).astype(np.float32))
    kw = dict(edge_act="sigmoid", inter_act="relu", real_edges=re_)
    out_res = fused_conv_stack(x, send, recv, mask, n, Ws, bs, **kw)
    monkeypatch.setenv("HYDRAGNN_RESIDENCY_VMEM_MB", "0.01")
    assert residency_vmem_bytes(n, h) > residency_vmem_budget_bytes()
    out_fb = fused_conv_stack(x, send, recv, mask, n, Ws, bs, **kw)
    np.testing.assert_array_equal(np.asarray(out_res), np.asarray(out_fb))


def pytest_stack_validates_inputs():
    x = jnp.zeros((8, 16))
    ids = jnp.zeros((4,), jnp.int32)
    mask = jnp.ones((4,), bool)
    with pytest.raises(ValueError, match="square"):
        fused_conv_stack(x, ids, ids, mask, 8, jnp.zeros((2, 16, 8)))
    with pytest.raises(ValueError, match="width"):
        fused_conv_stack(x, ids, ids, mask, 8, jnp.zeros((2, 8, 8)))
    with pytest.raises(ValueError, match="num_segments"):
        fused_conv_stack(x, ids, ids, mask, 6, jnp.zeros((2, 16, 16)))
    with pytest.raises(ValueError, match="activation"):
        fused_conv_stack(
            x, ids, ids, mask, 8, jnp.zeros((2, 16, 16)), inter_act="nope"
        )


def _tiny_loader(batch_size=4):
    from hydragnn_tpu.data.ingest import prepare_dataset
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.flagship import flagship_config
    from hydragnn_tpu.utils.config import update_config

    cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=batch_size)
    samples = deterministic_graph_data(
        number_configurations=8,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )
    train, val, test, _, _ = prepare_dataset(samples, cfg)
    cfg = update_config(cfg, train, val, test)
    return cfg, GraphLoader(train, batch_size, shuffle=False)


def pytest_filler_batch_advertises_zero_cost(monkeypatch):
    """The loader's all-padding filler batches (partial final device
    rounds) must carry edge_occupancy == 0 / n_real_nodes == 0, so the
    fused kernel's chunk loop runs ZERO iterations on that device slot
    — and the conv output is exact zeros."""
    from hydragnn_tpu.data.loader import _mask_out

    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    _, loader = _tiny_loader()
    batch = next(iter(loader))
    assert batch.edge_occupancy is not None and batch.n_real_nodes is not None
    assert int(batch.edge_occupancy) > 0

    filler = _mask_out(batch)
    assert int(filler.edge_occupancy) == 0
    assert int(filler.n_real_nodes) == 0
    assert not np.asarray(filler.edge_mask).any()
    # the kernel's chunk-loop bound: ceil(occupancy / CE) chunks run
    assert -(-int(filler.edge_occupancy) // CE) == 0
    x = jnp.asarray(
        np.random.default_rng(0)
        .normal(size=(filler.nodes.shape[0], 32))
        .astype(np.float32)
    )
    out = fused_conv(
        x,
        filler.senders,
        filler.receivers,
        filler.edge_mask,
        int(filler.nodes.shape[0]),
        real_edges=filler.edge_occupancy,
    )
    assert float(jnp.abs(out).max()) == 0.0


def pytest_pad_waste_from_batch_consistent():
    """pad_waste_from_batch must agree with the batch's own masks and
    occupancy fields (the bench/manifest accounting input)."""
    from hydragnn_tpu.obs.introspect import pad_waste_from_batch

    _, loader = _tiny_loader()
    batch = next(iter(loader))
    waste = pad_waste_from_batch(batch)
    assert waste["edge_pad"] == int(np.asarray(batch.senders).shape[-1])
    assert waste["node_pad"] == int(np.asarray(batch.node_mask).shape[-1])
    assert waste["real_edges_mean"] == pytest.approx(
        float(np.asarray(batch.edge_occupancy)), abs=0.1
    )
    assert 0.0 <= waste["edge_waste_frac"] < 1.0
    assert 0.0 <= waste["node_waste_frac"] < 1.0
    # the occupancy bound can sit ABOVE the real-edge count (run_align
    # interleaves masked self-loops below it) but never above the pad
    assert waste["real_edges_mean"] <= waste["edge_pad"]
    assert float(np.asarray(batch.edge_mask).sum()) <= waste["real_edges_mean"]


def pytest_traffic_model_large_graph_drop():
    """The acceptance headline: on the large-graph bench shape the
    analytic cost model must show >=30% bytes/step off the padded
    fused path with occupancy skip + bf16 activations."""
    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.obs.introspect import (
        conv_traffic_model,
        pad_waste_from_batch,
    )

    _, _, _, loader = build_flagship(
        n_samples=12, hidden_dim=16, num_conv_layers=2, batch_size=4,
        unit_cells=(4, 5),
    )
    batch = next(iter(loader))
    waste = pad_waste_from_batch(batch)
    for hidden, layers in ((16, 2), (128, 6)):  # smoke + full bench shape
        m = conv_traffic_model(
            waste["node_pad"], waste["edge_pad"], hidden, layers,
            real_edges=waste["real_edges_mean"],
        )
        bps = m["bytes_per_step"]
        assert bps["fused_skip"] <= bps["fused_padded"] <= bps["xla_unfused"]
        assert bps["resident_skip"] < bps["fused_skip_bf16"]
        assert m["drop_vs_fused_padded"]["fused_skip_bf16"] >= 0.30, m


def pytest_model_level_conv_bf16(monkeypatch):
    """Architecture.conv_bf16 through the real chassis: loss and grads
    finite and within bf16 tolerance of the f32 path, same params.
    Runs the XLA conv path (fast on CPU) — the knob casts the same
    streamed operands in both paths, and kernel-vs-fallback bf16
    equivalence is already pinned at the op level above."""
    from hydragnn_tpu.models.base import model_loss
    from hydragnn_tpu.models.create import create_model_config

    monkeypatch.setenv("HYDRAGNN_PALLAS", "0")
    cfg, loader = _tiny_loader()
    batch = next(iter(loader))
    arch = cfg["NeuralNetwork"]["Architecture"]

    results = {}
    for bf16 in (False, True):
        arch["conv_bf16"] = bf16
        model, variables = create_model_config(cfg["NeuralNetwork"], batch)
        assert model.cfg.conv_bf16 is bf16

        def loss(params):
            outs = model.apply(
                {
                    "params": params,
                    "batch_stats": variables.get("batch_stats", {}),
                },
                batch,
                train=False,
            )
            total, _ = model_loss(model.cfg, outs, batch)
            return total

        results[bf16] = jax.value_and_grad(loss)(variables["params"])

    l0, g0 = results[False]
    l1, g1 = results[True]
    assert np.isfinite(float(l1))
    assert abs(float(l1) - float(l0)) <= 5e-2 * max(abs(float(l0)), 1.0)
    leaves0 = jax.tree_util.tree_leaves(g0)
    leaves1 = jax.tree_util.tree_leaves(g1)
    gmax = max(float(jnp.abs(a).max()) for a in leaves0)
    gerr = max(
        float(jnp.abs(a - b).max()) for a, b in zip(leaves0, leaves1)
    )
    assert np.isfinite(gerr)
    assert gerr / max(gmax, 1e-9) < 8e-2
