"""Serving-resilience tests (docs/RESILIENCE.md "Serving resilience"):
poison-request isolation (typed RequestFailed, retry-as-singles,
quarantine), supervised dispatch (thread death -> bounded restart;
wedged forward -> re-armed watchdog), health/readiness probes +
serve_probe exit codes, typed ServerClosed after stop (incl. the
submit-vs-stop race), zero-downtime reload with canary + rollback, and
the registry's torn-checkpoint fallback.

Every fault here is driven deterministically through
``HYDRAGNN_INJECT_SERVE_*`` (hydragnn_tpu/resilience/inject.py); the
chaos composition of all of them lives in ``bench_serve.py --chaos``.
"""

import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from hydragnn_tpu.obs import FlightRecorder
from hydragnn_tpu.obs.flight import (
    flight_record_warnings,
    read_flight_record,
    validate_flight_record,
)
from hydragnn_tpu.serve import (
    MicroBatchQueue,
    ModelRegistry,
    ModelServer,
    Overloaded,
    ReloadFailed,
    RequestFailed,
    ServeConfig,
    ServerClosed,
)

REPO = __file__.rsplit("/", 2)[0]


@pytest.fixture(scope="module")
def served_setup():
    """Smoke-sized PNA multihead (+ completed config for the registry
    tests), registered once for the module."""
    from hydragnn_tpu.flagship import build_flagship

    config, model, variables, loader = build_flagship(
        n_samples=24,
        hidden_dim=8,
        num_conv_layers=2,
        batch_size=4,
        unit_cells=(2, 3),
    )
    registry = ModelRegistry()
    served = registry.register("resilience_smoke", model, variables)
    return config, served, list(loader.all_samples)


def _direct_forward(served, sample):
    from hydragnn_tpu.graph.batch import batch_graphs
    from hydragnn_tpu.serve import request_to_dict

    g = request_to_dict(sample)
    batch = batch_graphs([g])
    outputs = served.forward(served.variables, batch)
    cfg = served.cfg
    n = int(np.asarray(g["x"]).shape[0])
    out = {}
    for ihead in range(cfg.num_heads):
        o = np.asarray(outputs[ihead])
        if cfg.output_type[ihead] == "graph":
            out[cfg.output_names[ihead]] = o[0]
        else:
            out[cfg.output_names[ihead]] = o[:n]
    return out


def _assert_result_close(got, want):
    assert sorted(got) == sorted(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# poison-request isolation
# ---------------------------------------------------------------------------


def test_poison_raise_fails_only_its_future(served_setup, monkeypatch, tmp_path):
    _, served, samples = served_setup
    monkeypatch.setenv("HYDRAGNN_INJECT_SERVE_RAISE", "1")
    flight = FlightRecorder(str(tmp_path / "flight.jsonl"))
    with ModelServer(
        served,
        samples,
        # long deadline: all four requests coalesce into ONE batch, so
        # the poison must be localized by the retry-as-singles hunt
        ServeConfig(max_batch=4, max_delay_ms=200.0),
        flight=flight,
    ) as server:
        futs = [server.submit(s) for s in samples[:4]]
        outcomes = []
        for f in futs:
            try:
                outcomes.append(("ok", f.result(timeout=120)))
            except RequestFailed as exc:
                outcomes.append(("failed", exc))
        snap = server.metrics_snapshot()
        # the server keeps serving after the poison
        monkeypatch.delenv("HYDRAGNN_INJECT_SERVE_RAISE")
        _assert_result_close(
            server.predict(samples[0], timeout=120),
            _direct_forward(served, samples[0]),
        )
        assert server.health()["ready"]
    kinds = [o[0] for o in outcomes]
    assert kinds.count("failed") == 1 and kinds[1] == "failed"
    exc = outcomes[1][1]
    assert exc.seq == 1 and exc.reason == "exception"
    for i in (0, 2, 3):
        _assert_result_close(outcomes[i][1], _direct_forward(served, samples[i]))
    assert snap["quarantined"] == 1
    assert snap["poison_retries"] >= 2  # the co-batched requests re-ran alone
    assert snap["compile_misses"] == 0  # retries used the warm bucket
    events = read_flight_record(str(tmp_path / "flight.jsonl"))
    quar = [e for e in events if e.get("kind") == "quarantine"]
    assert len(quar) == 1 and quar[0]["seq"] == 1 and quar[0]["reason"] == "exception"


def test_poison_nan_output_quarantined(served_setup, monkeypatch):
    _, served, samples = served_setup
    monkeypatch.setenv("HYDRAGNN_INJECT_SERVE_NAN", "2")
    with ModelServer(
        served, samples, ServeConfig(max_batch=4, max_delay_ms=200.0)
    ) as server:
        futs = [server.submit(s) for s in samples[:4]]
        failed = {}
        for i, f in enumerate(futs):
            try:
                _assert_result_close(
                    f.result(timeout=120), _direct_forward(served, samples[i])
                )
            except RequestFailed as exc:
                failed[i] = exc
        snap = server.metrics_snapshot()
    assert list(failed) == [2]
    assert failed[2].reason == "nonfinite"
    assert snap["quarantined"] == 1 and snap["errors"] == 1


def test_single_request_batch_quarantined_directly(served_setup, monkeypatch):
    _, served, samples = served_setup
    monkeypatch.setenv("HYDRAGNN_INJECT_SERVE_RAISE", "0")
    with ModelServer(
        served, samples, ServeConfig(max_batch=4, max_delay_ms=5.0)
    ) as server:
        with pytest.raises(RequestFailed):
            server.predict(samples[0], timeout=120)
        snap = server.metrics_snapshot()
        assert snap["quarantined"] == 1
        # a single-request batch is quarantined without a retry pass
        assert snap["poison_retries"] == 0


# ---------------------------------------------------------------------------
# supervised dispatch: thread death + wedged forward
# ---------------------------------------------------------------------------


def test_dispatch_death_recovery(served_setup, monkeypatch, tmp_path):
    _, served, samples = served_setup
    monkeypatch.setenv("HYDRAGNN_INJECT_SERVE_KILL_DISPATCH", "2")
    flight = FlightRecorder(str(tmp_path / "flight.jsonl"))
    server = ModelServer(
        served,
        samples,
        ServeConfig(
            max_batch=2,
            max_delay_ms=10.0,
            dispatch_backoff_base_s=0.5,  # wide enough to observe not-ready
        ),
        flight=flight,
    )
    server.start()
    try:
        futs = [server.submit(s) for s in samples[:8]]
        # readiness must flip false (thread down, in backoff) -> true
        saw_not_ready = saw_ready_again = False
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            ready = server.health()["ready"]
            if not ready:
                saw_not_ready = True
            elif saw_not_ready:
                saw_ready_again = True
                break
            time.sleep(0.005)
        results, dispatch_failed = 0, 0
        for f in futs:
            try:
                f.result(timeout=120)
                results += 1
            except RequestFailed as exc:
                assert exc.reason == "dispatch"
                dispatch_failed += 1
        assert saw_not_ready and saw_ready_again
        # the killed batch's futures resolved with the typed error; the
        # rest were served by the restarted thread
        assert dispatch_failed >= 1 and results + dispatch_failed == 8
        # post-recovery traffic hits the warm compile cache
        misses_before = server.metrics_snapshot()["compile_misses"]
        _assert_result_close(
            server.predict(samples[0], timeout=120),
            _direct_forward(served, samples[0]),
        )
        snap = server.metrics_snapshot()
        assert snap["compile_misses"] == misses_before == 0
        assert snap["dispatch_restarts"] == 1
        assert server.health()["dispatch_restarts"] == 1
    finally:
        server.stop()
    events = read_flight_record(str(tmp_path / "flight.jsonl"))
    restarts = [e for e in events if e.get("kind") == "dispatch_restart"]
    assert len(restarts) == 1 and restarts[0]["cause"] == "crash"


def test_wedged_dispatch_flips_liveness_then_recovers(
    served_setup, monkeypatch, tmp_path
):
    from hydragnn_tpu.resilience import inject

    _, served, samples = served_setup
    monkeypatch.setattr(inject, "_SERVE_WEDGED", False)
    monkeypatch.setenv("HYDRAGNN_INJECT_SERVE_WEDGE", "1:1")
    flight = FlightRecorder(str(tmp_path / "flight.jsonl"))
    with ModelServer(
        served,
        samples,
        ServeConfig(max_batch=4, max_delay_ms=50.0, dispatch_stall_s=0.2),
        flight=flight,
    ) as server:
        futs = [server.submit(s) for s in samples[:4]]
        saw_stalled = False
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            h = server.health()
            if h["dispatch_stalled"]:
                saw_stalled = True
                assert not h["live"] and not h["ready"]
                break
            time.sleep(0.01)
        # the wedge ends; every future still resolves with a result
        for i, f in enumerate(futs):
            _assert_result_close(
                f.result(timeout=120), _direct_forward(served, samples[i])
            )
        assert saw_stalled, "watchdog never flagged the wedged forward"
        deadline = time.monotonic() + 5.0
        while not server.health()["ready"] and time.monotonic() < deadline:
            time.sleep(0.01)
        h = server.health()
        assert h["ready"] and not h["dispatch_stalled"]  # re-armed, recovered
        assert server.metrics_snapshot()["dispatch_restarts"] == 0  # no restart
    events = read_flight_record(str(tmp_path / "flight.jsonl"))
    wd = [e for e in events if e.get("kind") == "watchdog"]
    assert len(wd) == 1 and "stacks" in wd[0]
    # the serve run survived the stall: run_end is stopped, not hung
    assert events[-1]["kind"] == "run_end" and events[-1]["status"] == "stopped"


# ---------------------------------------------------------------------------
# typed ServerClosed (+ the submit-vs-stop race)
# ---------------------------------------------------------------------------


def test_server_closed_is_typed_and_immediate(served_setup):
    _, served, samples = served_setup
    q = MicroBatchQueue(num_buckets=1, max_batch=2, max_delay_s=0.1, max_pending=4)
    q.close()
    with pytest.raises(ServerClosed):
        q.put(0, "x")
    server = ModelServer(served, samples, ServeConfig(max_batch=2, max_delay_ms=5.0))
    server.start()
    server.stop()
    with pytest.raises(ServerClosed):
        server.submit(samples[0])
    with pytest.raises(ServerClosed):
        server.start()  # a stopped server does not resurrect silently


def test_submit_vs_stop_race_leaves_no_hanging_future(served_setup):
    _, served, samples = served_setup
    server = ModelServer(
        served, samples, ServeConfig(max_batch=4, max_delay_ms=5.0)
    )
    server.start()
    futures, rejected = [], []
    lock = threading.Lock()

    def feeder():
        # submit until the stop lands (time-bounded, not count-bounded:
        # the race only exists while submissions straddle the stop)
        deadline = time.monotonic() + 5.0
        i = 0
        while time.monotonic() < deadline:
            i += 1
            try:
                f = server.submit(samples[i % len(samples)])
                with lock:
                    futures.append(f)
            except Overloaded:
                time.sleep(0.001)
            except ServerClosed as exc:
                with lock:
                    rejected.append(exc)
                return

    threads = [threading.Thread(target=feeder) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    server.stop()
    for t in threads:
        t.join(timeout=30)
    assert any(isinstance(e, ServerClosed) for e in rejected)
    # EVERY future handed out resolves: a result (drained) — never a hang
    for f in futures:
        f.result(timeout=30)


# ---------------------------------------------------------------------------
# zero-downtime reload
# ---------------------------------------------------------------------------


def _scaled_params(variables, factor):
    import jax

    def scale(a):
        arr = np.asarray(a)
        if np.issubdtype(arr.dtype, np.floating):
            return arr * factor
        return a

    return {
        "params": jax.tree_util.tree_map(scale, variables["params"]),
        "batch_stats": variables.get("batch_stats", {}),
    }


def test_reload_swaps_weights_without_recompiling(served_setup, tmp_path):
    _, served, samples = served_setup
    old_vars = served.variables
    flight = FlightRecorder(str(tmp_path / "flight.jsonl"))
    try:
        with ModelServer(
            served, samples, ServeConfig(max_batch=4, max_delay_ms=5.0), flight=flight
        ) as server:
            before = server.predict(samples[0], timeout=120)
            info = server.reload(variables=_scaled_params(old_vars, 1.5))
            after = server.predict(samples[0], timeout=120)
            # new weights actually serve...
            _assert_result_close(after, _direct_forward(served, samples[0]))
            assert any(
                not np.allclose(after[k], before[k]) for k in after
            ), "reload did not change the served weights"
            snap = server.metrics_snapshot()
            assert snap["reloads"] == 1 and snap["reload_failed"] == 0
            # ...with ZERO new compiles (AOT executables are shape-
            # specialized; the warm ladder survives the swap)
            assert snap["compile_misses"] == 0
            assert info["canary_buckets"] == len(server.buckets)
            assert server.health()["ready"]
        events = read_flight_record(str(tmp_path / "flight.jsonl"))
        assert [e["source"] for e in events if e.get("kind") == "reload"] == [
            "<variables>"
        ]
    finally:
        served.variables = old_vars  # module fixture: restore for later tests


def test_reload_rolls_back_on_canary_failure(served_setup, monkeypatch, tmp_path):
    _, served, samples = served_setup
    flight = FlightRecorder(str(tmp_path / "flight.jsonl"))
    with ModelServer(
        served, samples, ServeConfig(max_batch=4, max_delay_ms=5.0), flight=flight
    ) as server:
        before = server.predict(samples[0], timeout=120)
        # torn reload: the candidate is corrupted before the canary
        monkeypatch.setenv("HYDRAGNN_INJECT_SERVE_TORN_RELOAD", "1")
        with pytest.raises(ReloadFailed):
            server.reload(variables=dict(served.variables))
        monkeypatch.delenv("HYDRAGNN_INJECT_SERVE_TORN_RELOAD")
        # structurally wrong candidate: rejected by the canary too
        with pytest.raises(ReloadFailed):
            server.reload(variables={"params": {"nope": np.zeros(3)}})
        after = server.predict(samples[0], timeout=120)
        _assert_result_close(after, before)  # old weights kept serving
        snap = server.metrics_snapshot()
        assert snap["reload_failed"] == 2 and snap["reloads"] == 0
        assert server.health()["ready"]
    events = read_flight_record(str(tmp_path / "flight.jsonl"))
    fails = [e for e in events if e.get("kind") == "reload_failed"]
    assert len(fails) == 2 and all(e.get("rolled_back") for e in fails)


# ---------------------------------------------------------------------------
# registry: the validating checkpoint path
# ---------------------------------------------------------------------------


def test_registry_load_falls_back_on_torn_pointer(served_setup, tmp_path):
    from hydragnn_tpu.train import create_eval_state, select_optimizer
    from hydragnn_tpu.utils.checkpoint import save_model

    config, served, samples = served_setup
    nn = config["NeuralNetwork"]
    log_dir = str(tmp_path) + "/logs/"
    tx = select_optimizer(
        nn["Training"],
        freeze_conv=bool(nn["Architecture"].get("freeze_conv_layers")),
    )
    state = create_eval_state(served.variables, tx)
    save_model(state, "torn_run", path=log_dir, keep_last=2)
    # tear the latest-pointer file (torn write / bit rot); the sha256-
    # sidecar'd step version must be served instead — loudly
    pointer = log_dir + "torn_run/torn_run.mp"
    with open(pointer, "r+b") as f:
        f.truncate(max(f.seek(0, 2) // 2, 1))
    registry = ModelRegistry(log_dir)
    with pytest.warns(RuntimeWarning, match="integrity"):
        loaded = registry.load("torn_run", nn, example_graph=samples[0])
    # the fallback restore carries the true weights, not garbage
    want = jax_leaves(served.variables["params"])
    got = jax_leaves(loaded.variables["params"])
    assert len(want) == len(got)
    for w, g in zip(want, got):
        np.testing.assert_allclose(np.asarray(w), np.asarray(g), rtol=0, atol=0)


def jax_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


# ---------------------------------------------------------------------------
# probes: health(), Prometheus textfile, serve_probe exit codes
# ---------------------------------------------------------------------------


def _probe(args):
    return subprocess.run(
        [sys.executable, f"{REPO}/tools/serve_probe.py", *args],
        capture_output=True,
        text=True,
    ).returncode


def test_health_probe_and_prometheus_textfile(served_setup, tmp_path):
    _, served, samples = served_setup
    prom = str(tmp_path / "serve.prom")
    server = ModelServer(
        served,
        samples,
        ServeConfig(
            max_batch=2,
            max_delay_ms=5.0,
            prometheus_path=prom,
            prometheus_every_s=0.05,
        ),
    )
    assert not server.health()["live"]  # not started yet
    server.start()
    try:
        h = server.health()
        assert h["live"] and h["ready"] and h["warm_buckets"] == h["num_buckets"]
        assert h["reasons"] == []
        # the supervisor's monitor exports the textfile periodically
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:
                with open(prom) as f:
                    if "hydragnn_serve_ready" in f.read():
                        break
            except OSError:
                pass
            time.sleep(0.02)
        assert _probe(["--prom", prom]) == 0
        assert _probe(["--prom", prom, "--live"]) == 0
        # a stale textfile is NO evidence of health: exit 2
        assert _probe(["--prom", prom, "--max-age", "1e-9"]) == 2
        assert _probe(["--prom", str(tmp_path / "missing.prom")]) == 2
    finally:
        server.stop()
    # a stopped server exports not-ready/not-live: exit 1
    server.export_prometheus(prom)
    assert _probe(["--prom", prom]) == 1
    assert _probe(["--prom", prom, "--live"]) == 1


def test_serve_fault_events_validate_and_render(tmp_path):
    path = str(tmp_path / "flight.jsonl")
    flight = FlightRecorder(path)
    flight.start_run({"mode": "serve"})
    flight.record("quarantine", seq=7, reason="exception", bucket=0, error="boom")
    flight.record("dispatch_restart", attempt=1, cause="crash", delay_s=0.05)
    flight.record("reload", source="run42", swap_s=0.2)
    flight.record("reload_failed", source="run43", error="canary", rolled_back=True)
    flight.end_run(status="stopped")
    flight.close()
    assert validate_flight_record(path) == []
    # the serve kinds are schema-KNOWN: no forward-compat warnings
    assert flight_record_warnings(path) == []
    out = subprocess.run(
        [sys.executable, f"{REPO}/tools/obs_report.py", "--faults", path],
        capture_output=True,
        text=True,
    )
    assert out.returncode == 0, out.stderr
    for token in ("quarantine", "dispatch_restart", "reload", "reload_failed"):
        assert token in out.stdout
    assert "quarantined=1" in out.stdout and "reloads=1" in out.stdout
