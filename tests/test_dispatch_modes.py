"""Dispatch-mode satellites (ISSUE 6): scan_epoch as the automatic
default where eligible (with the flight-record field saying which mode
ran), the guarded scan body, and the per-step sync discipline — zero
``block_until_ready`` / ``device_get`` outside the sampled span window
and the epoch boundary."""

import glob
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.train import (
    create_train_state,
    make_train_step,
    select_optimizer,
)
from hydragnn_tpu.train.loop import _scan_auto_eligible, train_epoch
from hydragnn_tpu.utils.config import update_config

from test_data_pipeline import base_config


@pytest.fixture(scope="module")
def tiny_problem():
    cfg = base_config(multihead=False)
    cfg["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
    samples = deterministic_graph_data(number_configurations=24, seed=7)
    train, val, test, _, _ = prepare_dataset(samples, cfg)
    cfg = update_config(cfg, train, val, test)
    loader = GraphLoader(train, 6, shuffle=False)
    example = next(iter(loader))
    model, variables = create_model_config(cfg["NeuralNetwork"], example)
    return cfg, model, variables, loader


# -- eligibility unit tests -------------------------------------------------


def pytest_scan_auto_eligibility(tiny_problem, monkeypatch):
    _, _, _, loader = tiny_problem
    ok, reason = _scan_auto_eligible(loader)
    assert ok, reason

    class NoStack:
        pass

    ok, reason = _scan_auto_eligible(NoStack())
    assert not ok and "stack" in reason

    monkeypatch.setenv("HYDRAGNN_INJECT_SIGTERM_STEP", "5")
    ok, reason = _scan_auto_eligible(loader)
    assert not ok and "fault injection" in reason
    monkeypatch.delenv("HYDRAGNN_INJECT_SIGTERM_STEP")

    # serve-side injection does not force per-step training dispatch
    monkeypatch.setenv("HYDRAGNN_INJECT_SERVE_RAISE", "1")
    ok, _ = _scan_auto_eligible(loader)
    assert ok
    monkeypatch.delenv("HYDRAGNN_INJECT_SERVE_RAISE")

    monkeypatch.setenv("HYDRAGNN_WATCHDOG_S", "30")
    ok, reason = _scan_auto_eligible(loader)
    assert not ok and "watchdog" in reason


def pytest_multi_device_stack_not_eligible(tiny_problem):
    cfg, _, _, _ = tiny_problem
    samples = deterministic_graph_data(number_configurations=24, seed=7)
    train, _, _, _, _ = prepare_dataset(samples, base_config(multihead=False))
    if jax.local_device_count() < 2:
        pytest.skip("needs the virtual multi-device mesh")
    loader = GraphLoader(train, 8, shuffle=False, device_stack=2)
    ok, reason = _scan_auto_eligible(loader)
    assert not ok and "multi-device" in reason


# -- flight-record dispatch_mode field --------------------------------------


def _read_manifest(log_dir):
    from hydragnn_tpu.obs.flight import read_flight_record

    path = glob.glob(log_dir + "/*/flight.jsonl")[0]
    events = read_flight_record(path)
    man = [e for e in events if e.get("kind") == "run_start"][0]["manifest"]
    epochs = [e for e in events if e.get("kind") == "epoch"]
    return man, epochs


def pytest_auto_scan_default_and_flight_field(tmp_path, monkeypatch):
    """A default run_training on the single-device path must pick the
    scan dispatch automatically and say so in the flight record."""
    monkeypatch.setenv("HYDRAGNN_TELEMETRY", "1")
    from hydragnn_tpu.api import run_training
    from test_train_e2e import make_config

    config = make_config("GIN", False, str(tmp_path), num_epoch=2)
    # batch NOT divisible by the virtual 8-device mesh, so run_training
    # takes the single-device (loop-owned) path the auto default targets
    config["NeuralNetwork"]["Training"]["batch_size"] = 5
    samples = deterministic_graph_data(number_configurations=30, seed=0)
    run_training(config, samples=samples, log_dir=str(tmp_path) + "/logs/")
    man, epochs = _read_manifest(str(tmp_path) + "/logs")
    assert man["scan_epoch"] is True
    dm = man["dispatch_mode"]
    assert dm["mode"] == "scan_epoch" and dm["auto"] is True, dm
    assert "stacked loader" in dm["reason"]
    assert all(e["step_time"]["mode"] == "scan_epoch" for e in epochs)


def pytest_explicit_false_keeps_per_step(tmp_path, monkeypatch):
    monkeypatch.setenv("HYDRAGNN_TELEMETRY", "1")
    from hydragnn_tpu.api import run_training
    from test_train_e2e import make_config

    config = make_config("GIN", False, str(tmp_path), num_epoch=1)
    config["NeuralNetwork"]["Training"]["batch_size"] = 5
    config["NeuralNetwork"]["Training"]["scan_epoch"] = False
    samples = deterministic_graph_data(number_configurations=30, seed=0)
    run_training(config, samples=samples, log_dir=str(tmp_path) + "/logs/")
    man, epochs = _read_manifest(str(tmp_path) + "/logs")
    dm = man["dispatch_mode"]
    assert dm["mode"] == "per_step" and dm["auto"] is False
    assert dm["reason"] == "Training.scan_epoch=false"
    for e in epochs:
        st = e["step_time"]
        # the per-step span decomposition (data-wait / dispatch /
        # sampled device) — moved here from the obs e2e now that the
        # default dispatch is scan
        assert st["mode"] == "per_step"
        assert st["data_wait_s"] >= 0 and st["dispatch_s"] > 0
        assert st["sampled_steps"] >= 1 and st["device_wait_ms_mean"] is not None


def pytest_injection_forces_per_step(tmp_path, monkeypatch):
    """Step-indexed fault injection needs batch granularity: the auto
    default must fall back to per-step dispatch (NAN_STEP far beyond the
    epoch so nothing actually fires)."""
    monkeypatch.setenv("HYDRAGNN_TELEMETRY", "1")
    monkeypatch.setenv("HYDRAGNN_INJECT_NAN_STEP", "99999")
    from hydragnn_tpu.api import run_training
    from test_train_e2e import make_config

    config = make_config("GIN", False, str(tmp_path), num_epoch=1)
    config["NeuralNetwork"]["Training"]["batch_size"] = 5
    samples = deterministic_graph_data(number_configurations=30, seed=0)
    run_training(config, samples=samples, log_dir=str(tmp_path) + "/logs/")
    man, _ = _read_manifest(str(tmp_path) + "/logs")
    dm = man["dispatch_mode"]
    assert dm["mode"] == "per_step" and "fault injection" in dm["reason"]


# -- guarded scan body ------------------------------------------------------


def pytest_guarded_scan_matches_unguarded_on_finite_data(tiny_problem):
    from hydragnn_tpu.train import make_scan_epoch

    cfg, model, variables, loader = tiny_problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}})
    stacked = loader.stacked_device_batches()
    order = jnp.arange(len(loader), dtype=jnp.int32)

    s0 = create_train_state(variables, tx, seed=0)
    plain = make_scan_epoch(model, tx)
    s0, losses0, _, counts0 = plain(s0, stacked, order)

    s1 = create_train_state(variables, tx, seed=0)
    guarded = make_scan_epoch(model, tx, guard_nonfinite=True)
    s1, losses1, _, counts1, bads, consec = guarded(
        s1, loader.stacked_device_batches(), order, jnp.zeros((), jnp.int32)
    )
    assert float(jnp.asarray(bads).sum()) == 0.0
    assert int(consec) == 0
    np.testing.assert_allclose(np.asarray(losses1), np.asarray(losses0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(counts1), np.asarray(counts0))
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s0.params)),
        jax.tree_util.tree_leaves(jax.device_get(s1.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def pytest_guarded_scan_skips_nan_batch(tiny_problem):
    """A poisoned batch inside the stack must be skipped (zero loss and
    count, bad flag set) without corrupting the carried params."""
    from hydragnn_tpu.train import make_scan_epoch

    cfg, model, variables, loader = tiny_problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}})
    stacked = loader.stacked_device_batches()
    nb = len(loader)
    poisoned = stacked.replace(
        nodes=stacked.nodes.at[1].set(jnp.nan)
    )
    order = jnp.arange(nb, dtype=jnp.int32)
    state = create_train_state(variables, tx, seed=0)
    guarded = make_scan_epoch(model, tx, guard_nonfinite=True)
    state, losses, _, counts, bads, consec = guarded(
        state, poisoned, order, jnp.zeros((), jnp.int32)
    )
    bads = np.asarray(bads)
    assert bads[1] == 1.0 and bads.sum() == 1.0, bads
    assert float(np.asarray(losses)[1]) == 0.0
    assert float(np.asarray(counts)[1]) == 0.0
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state.params)):
        assert np.isfinite(leaf).all()


# -- per-step sync discipline ----------------------------------------------


def pytest_zero_syncs_outside_sampled_window(tiny_problem):
    """The per-step loop must not block on the device outside the span
    tracer's sampled window, and must not call device_get at all until
    the epoch-boundary finalize — the dispatch-overhead contract the
    deferred _MetricAccum provides."""
    from hydragnn_tpu.obs import StepSpans

    cfg, model, variables, loader = tiny_problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}})
    state = create_train_state(variables, tx)
    step = make_train_step(model, tx)

    real_block = jax.block_until_ready
    real_get = jax.device_get
    calls = {"block": 0, "get": 0}

    def counting_block(tree):
        calls["block"] += 1
        return real_block(tree)

    def counting_get(tree):
        calls["get"] += 1
        return real_get(tree)

    spans = StepSpans(sample_steps=2, skip_first=1)
    spans.epoch_start(0)
    jax.block_until_ready = counting_block
    jax.device_get = counting_get
    try:
        state, loss, tasks = train_epoch(loader, state, step, spans=spans)
        in_loop = dict(calls)
    finally:
        jax.block_until_ready = real_block
        jax.device_get = real_get
    assert len(loader) > spans.sample_steps + 1
    # exactly the sampled window blocks; nothing else syncs per step
    assert in_loop["block"] == spans.sample_steps, in_loop
    assert in_loop["get"] == 0, in_loop
    assert np.isfinite(loss)


def pytest_metric_accum_defers_and_weights():
    """_MetricAccum with raw masks + bad flags reproduces the weighted
    mean the old per-step-multiply accumulator computed."""
    from hydragnn_tpu.train.loop import _MetricAccum

    acc = _MetricAccum()
    masks = [
        jnp.asarray([True, True, False]),
        jnp.asarray([True, False, False]),
        jnp.asarray([True, True, True]),
    ]
    losses = [jnp.asarray(2.0), jnp.asarray(4.0), jnp.asarray(1.0)]
    tasks = [jnp.asarray([2.0, 0.0]), jnp.asarray([4.0, 1.0]), jnp.asarray([1.0, 2.0])]
    bads = [None, jnp.asarray(1.0), None]  # batch 1 skipped by the sentry
    for l, t, m, b in zip(losses, tasks, masks, bads):
        acc.add(l, t, m, bad=b)
    avg_loss, avg_tasks = acc.finalize()
    # weights: 2, 0 (bad), 3 -> loss = (2*2 + 1*3) / 5
    assert avg_loss == pytest.approx((2.0 * 2 + 1.0 * 3) / 5)
    np.testing.assert_allclose(
        avg_tasks, [(2.0 * 2 + 1.0 * 3) / 5, (0.0 * 2 + 2.0 * 3) / 5]
    )


def pytest_metric_accum_scalar_counts_still_work():
    from hydragnn_tpu.train.loop import _MetricAccum

    acc = _MetricAccum()
    acc.add(jnp.asarray(3.0), jnp.asarray([3.0]), jnp.asarray(2.0))
    acc.add(jnp.asarray(5.0), jnp.asarray([5.0]), jnp.asarray(6.0))
    avg_loss, avg_tasks = acc.finalize()
    assert avg_loss == pytest.approx((3.0 * 2 + 5.0 * 6) / 8)
